"""CI perf-regression gate: (a) the headline bench at CI-sized shapes on
the CPU backend, gated on decisions/sec; (b) the serving-path HOST-PREP
gate, portable across machines.

Usage:
    python benchmarks/ci_gate.py            # gate (exit 1 on regression)
    python benchmarks/ci_gate.py --update   # re-baseline after intentional
                                            # perf-relevant changes

Gate (a): the committed baseline is machine-relative, so it is only
*enforced* on a machine with the same fingerprint (cpu count + node name)
that produced it — there the gate uses a 2× margin over the best of three
runs. On any other machine (e.g. a shared CI runner of a different hardware
class) the gate falls back to an absolute sanity floor instead: the failure
mode that matters — an accidental per-event host loop, lost fusion, or an
accidental device sync per event — costs 3-5 orders of magnitude, which the
sanity floor catches on any hardware.

Gate (b) — the portable one: serving-path host prep (entry_batch /
request_tokens dispatch cost per step) is tunnel-independent (BASELINE.md:
stalls are tunnel weather, host cost is code), but raw ms/step still scales
with machine class — so the gate measures a fixed pure-Python+numpy
CALIBRATION workload on the same machine and enforces the RATIO
host_prep/calibration. Machine speed cancels to first order; what's left is
the code: re-introducing a per-event Python loop moves the ratio by the
same factor on a laptop, this VM, or a shared CI runner, and fails the gate
everywhere. Margin 2.5× over the committed ratio. Run ``--update`` after
intentional host-prep changes.

Gate (c) — the prio-cliff gate (also portable): before r6, one prioritized
event demoted a whole batch to the sorted general path (a 16× cliff on the
TPU headline). Two checks pin it shut: (i) a BANDED ratio of the
general_bench ``prio_mixed`` metric (the occupy-aware split: scalar bulk +
fast-occupy prio slice) over the ``general`` metric (the sorted whole-batch
path a demotion collapses into) — machine speed cancels, and a reintroduced
demotion drags the ratio to ~1.0; (ii) a binary routing probe through the
runtime itself: a mixed 1%-prio batch must still take
``_decide_split_nowait`` (general_bench pre-stages its sub-batches, so only
this probe sees the runtime's routing decision).

Gate (d) — the observability-overhead gate (portable): the obs/ telemetry
layer rides the batch hot path behind ``if obs.enabled`` checks; this gate
times the SAME split-firing workload through two runtimes — obs enabled vs
``SENTINEL_OBS_DISABLE=1`` — interleaved best-of-N, and bands the
instrumented/uninstrumented step-time ratio at ``OBS_OVERHEAD_MAX`` (1.02,
the ISSUE's ≤2% budget). Machine speed cancels in the ratio.

Gate (e) — the dispatch-pipeline gate (r6, portable): the fused
decide+exit program must actually save its dispatch (fused/two-call
step-time ratio ≤ ``FUSED_MAX``), the depth-2 ``DispatchPipeline``
overlay must cost nothing material over the bare sync loop
(≤ ``PIPELINE_OVERHEAD_MAX``), and the ``pipeline.depth`` counter must
prove batches genuinely overlapped in flight. The comment block above
``measure_dispatch_pipeline`` explains why the overlay's latency WIN is
carried by the BENCH artifacts rather than gated on the CPU backend.

Gate (f) — the serving SLO gate (r7): request→verdict latency through
the real ingest front end (frontend/batcher.py, replayed open-loop by
benchmarks/serving_bench.py). The steady workload's p99 must sit in
``STEADY_P99_BAND_MS`` at a pinned offered rate, with exact request
accounting; the flash-crowd run must shed/queue gracefully (no lost
futures, no deadline-miss collapse) while actually cutting full
batches. See the comment block above ``measure_serving``.

Gate (g) — the trace-capture mechanism probe (r8): an induced
flash-crowd deadline miss must leave a persisted ``<app>-trace`` chain
behind (obs/flight.py) that spans the request AND batch tiers and
survives the Chrome-trace export round trip. See the comment block
above ``TRACE_REQUIRED_REQUEST_SPAN``.

Gate (h) — the meshed-serving gate (r9): on an 8-virtual-device CPU
mesh (a ``--meshed`` subprocess, so XLA_FLAGS lands before jax
initializes), the row-sharded engine's verdicts through the FULL
serving path — DispatchPipeline, fused decide+exit, split/prio/occupy
routing, a rule reload with live occupy bookings, and the
AdaptiveBatcher fan-out — must be bit-identical to the single-device
engine, and the weak-scaling curve's normalized per-partition cost must
stay flat (≤ ``WEAK_SCALING_FLAT_MAX``). ``CI_GATE_MESHED=0`` skips.
See the comment block above ``MESHED_ENV_FLAG``.

Gate (i) — the sort-free general-path gate (r10): the hash-bucketed
claim-cascade aggregation (ops/sortfree.py) is the DEFAULT general
aggregation; two engines built under SENTINEL_SORTFREE=1 vs =0 must
produce BIT-IDENTICAL verdicts through the real dispatch (pair-key
general route, split route with a prioritized occupy slice, booking
carry across a mid-stream rule reload), the ``split_route.sortfree``
attribution must tick on the sortfree engine only, the DEFAULT-sized
claim table must not overflow, and the sortfree/sorted general
throughput ratio must stay ≥ ``SORTFREE_MIN_RATIO`` on the CPU backend
— a band that pins the cascade's KNOWN below-parity CPU cost from
degenerating (XLA:CPU's sort is the fast case; the win this round
claims is the accelerator's, carried informationally by the bench
artifacts ``general`` vs ``general_sortfree`` and their
``aggregation_ms`` keys). ``CI_GATE_SORTFREE=0`` skips. See the
comment block above ``SORTFREE_ENV_FLAG``.

Gate (j) — the autotune gate (r11): a tiny CPU sweep (2 knobs × small
grids, short rungs) through ``sentinel_tpu.tune.run_sweep`` must
CONVERGE with every trial passing the verdict bit-parity spot-check
and pin a ``TUNED.json``; the pinned config, loaded back through the
real ``SENTINEL_TUNED_CONFIG`` startup path, must then produce
bit-identical verdicts below the batcher and ≥ ``TUNE_MIN_RATIO`` of
the default config's throughput through the full serving replay.
``CI_GATE_TUNE=0`` skips. See the comment block above
``TUNE_ENV_FLAG``.

Gate (k) — the hot-resource telemetry gate (r12): a planted-hot-key
Zipf mix through the FULL serving path (engine + ``start_transport`` +
dashboard server) must surface the planted keys in ``/obs/topk.json``
(hottest planted key ranked FIRST — the sharded top-K is exact, not
approximate) AND in the ``<app>-metric`` log read back through
``MetricSearcher``, with a non-empty per-second timeline; and the obs
overhead probe re-run with the telemetry ticker ON (5 Hz, harsher than
the production 1 Hz) must stay inside the same fixed
``OBS_OVERHEAD_MAX`` band — telemetry must not cost what obs/ saved.
``CI_GATE_TELEMETRY=0`` skips. See the comment block above
``TELEMETRY_ENV_FLAG``.

Gate (l) — the tiered-state gate (r15): a 16M-key Zipf(s=1.1) stream
through the FULL serving path (AdaptiveBatcher replay, the tiering
ticker running at a small ``SENTINEL_HOT_ROWS`` target) must sustain a
hot-tier hit rate ≥ ``TIER_HIT_RATE_MIN`` while actually migrating
rows (nonzero ``tier.promoted`` AND ``tier.demoted``) and recording
the migration-latency histogram; and a resident-key parity probe —
identical seeded traffic with live flow rules and a mid-run rule
reload, through a hot tier an order of magnitude smaller than the key
set vs an all-resident engine — must produce BIT-IDENTICAL verdicts
(the cold tier's demote→promote round trip may never change an
answer). The obs-overhead band (gate d, ≤ ``OBS_OVERHEAD_MAX``) now
runs with tiering ON on both engines, so the sketch-update dispatch
cost is already inside that band. ``CI_GATE_TIER=0`` skips. See the
comment block above ``TIER_ENV_FLAG``.

Gate (m) — the single-dispatch gate (r16): with both cadence carries
armed, a steady fused serving batch must cost exactly ONE device
dispatch (``pipeline.dispatches`` rises by one per batch; the sketch
observe, the telemetry tick and the sketch decay all ride the jitted
program's ``lax.cond`` epilogue) with each service ticking once per
due cadence slot; verdicts AND the count-min table must be
bit-identical between ``SENTINEL_SINGLE_DISPATCH=1`` and ``=0``
through tiered churn with a mid-run rule reload; and the armed-vs-
disarmed step-time ratio must stay ≤ ``OBS_OVERHEAD_MAX`` — the
epilogue may not leak cost into batches where no tick is due.
``CI_GATE_SINGLE_DISPATCH=0`` skips. See the comment block above
``SINGLE_DISPATCH_ENV_FLAG``.
"""

from __future__ import annotations

import json
import os
import platform
import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).resolve().parent
BASELINE_FILE = HERE / "ci_baseline.json"

# any machine that can run the suite at all clears this unless the fused
# step degenerates into per-event Python/host work (that failure mode
# costs ~1000x; honest CPU throughput at gate shapes is ~0.3-1M/s)
SANITY_FLOOR_DECISIONS_PER_SEC = 2e5

ENV = {
    **os.environ,
    # BENCH_PLATFORM applies the override via jax.config, which outranks
    # the dev image's sitecustomize (the JAX_PLATFORMS env var alone is
    # silently ignored there and the "cpu" gate would bench the tunneled
    # TPU); plain env var kept for runners without a sitecustomize
    "JAX_PLATFORMS": "cpu",
    "BENCH_PLATFORM": "cpu",
    "BENCH_RESOURCES": str(1 << 14),
    "BENCH_BATCH": str(1 << 13),
    "BENCH_STEPS": "20",
    "BENCH_RULES": "256",
    # the gate times the scalar headline; the general/mixed add-ons
    # (bench.py BENCH_GENERAL) would triple gate wall time for a number
    # gated separately by the parity tests
    "BENCH_GENERAL": "0",
}


def fingerprint() -> str:
    return f"{platform.node()}/{os.cpu_count()}cpu"


def measure_once() -> float:
    out = subprocess.run(
        [sys.executable, str(HERE.parent / "bench.py")], env=ENV,
        capture_output=True, text=True, timeout=600, check=True)
    line = out.stdout.strip().splitlines()[-1]
    return float(json.loads(line)["value"])


HOST_PREP_MARGIN = 2.5


def calibrate() -> float:
    """Fixed CPU reference workload (numpy vector ops + dict/string churn,
    the same primitive mix the host-prep paths use) → seconds. Used to
    normalize host-prep timings into a machine-independent ratio."""
    import time as _time

    import numpy as np
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 5000, 200_000)
    t0 = _time.perf_counter()
    for _ in range(10):
        u, inv = np.unique(keys, return_inverse=True)
        _ = u[inv][:1000].tolist()
        d = {}
        for i in range(20_000):
            d[f"k{i & 1023}"] = i
        _ = np.argsort(keys[:50_000], kind="stable")
    return _time.perf_counter() - t0


def measure_host_prep() -> dict:
    """Serving-path host-prep seconds/step on the CPU backend: the dispatch
    side of entry_batch_nowait (param keys) and request_tokens_nowait
    (cluster grouping) — the two vectorized prep paths BASELINE.md gates."""
    import time as _time

    import numpy as np

    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sentinel_tpu as stpu
    from sentinel_tpu.parallel.cluster import (
        THRESHOLD_GLOBAL, ClusterEngine, ClusterFlowRule, ClusterSpec,
    )

    B, STEPS = 4096, 12
    # donation off for THIS runtime: the CPU PJRT client acquires donated
    # buffers synchronously, which folds device step time into the
    # dispatch call — this gate pins the HOST marshalling code, so it
    # must time an undonated dispatch (the donated fast path is covered
    # by gate (e) and the parity tests)
    prev_donate = os.environ.get("SENTINEL_DONATE")
    os.environ["SENTINEL_DONATE"] = "0"
    try:
        sph = stpu.Sentinel(stpu.load_config(
            max_resources=256, max_flow_rules=16, max_degrade_rules=16,
            max_authority_rules=16, max_param_rules=16,
            param_table_slots=1 << 12))
    finally:
        if prev_donate is None:
            os.environ.pop("SENTINEL_DONATE", None)
        else:
            os.environ["SENTINEL_DONATE"] = prev_donate
    sph.load_param_flow_rules([stpu.ParamFlowRule(
        resource="hot", param_idx=0, count=1e9)])
    rng = np.random.default_rng(0)
    keys = (rng.zipf(1.2, size=B * STEPS) % 2048).reshape(STEPS, B, 1)
    resources = ["hot"] * B
    handles = [sph.entry_batch_nowait(resources, args_list=keys[0])
               for _ in range(2)]          # warm compile + caches
    for h in handles:
        h.result()
    entry_times = []
    for s in range(STEPS):
        t0 = _time.perf_counter()
        h = sph.entry_batch_nowait(resources, args_list=keys[s])
        entry_times.append(_time.perf_counter() - t0)
        h.result()

    eng = ClusterEngine(ClusterSpec(n_shards=1, flows_per_shard=64,
                                    namespaces=4))
    eng.load_rules("ns", [ClusterFlowRule(flow_id=i, count=1e9,
                                          threshold_type=THRESHOLD_GLOBAL)
                          for i in range(64)])
    ids = rng.integers(0, 64, B)
    ones = np.ones(B, np.int64)
    eng.request_tokens(ids, ones, now_ms=10_000_000)
    cluster_times = []
    for s in range(STEPS):
        t0 = _time.perf_counter()
        h = eng.request_tokens_nowait(ids, ones, now_ms=10_000_100 + s)
        cluster_times.append(_time.perf_counter() - t0)
        h.result()
    return {"entry_prep_s_per_step": min(entry_times),
            "cluster_prep_s_per_step": min(cluster_times)}


# prio_mixed / general throughput band at gate shapes. Honest CPU value is
# ~1.5 (both prio_mixed dispatches skip alt recording; general pays the
# composite-key sort + alt scatter). A reintroduced whole-batch demotion
# makes the prio-mixed workload RUN the general path, so the ratio falls to
# ~1.0 — well below the low edge. The high edge catches a degenerated
# denominator (the general measurement itself collapsing) rather than a
# legitimate speedup: both sides share the same fixture and backend, so a
# >8x gap means the gate is no longer measuring what it claims.
PRIO_RATIO_BAND = (1.15, 8.0)


def measure_prio_cliff() -> dict:
    """Kernel-level prio gate: general_bench's ``prio_mixed`` (the exact
    two-dispatch split shape the runtime issues for a 1%-prioritized batch
    with live bookings) vs ``general`` (the sorted whole-batch path the
    pre-r6 demotion forced everything onto), both in-process at small CPU
    shapes. The RATIO is the gated number — machine speed cancels."""
    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from benchmarks import general_bench

    R, B, STEPS, NRULES, REPEATS = 1 << 12, 1 << 12, 8, 128, 3
    pm = general_bench.measure(jax, "prio_mixed", R, B, STEPS, NRULES,
                               REPEATS)["value"]
    gen = general_bench.measure(jax, "general", R, B, STEPS, NRULES,
                                REPEATS)["value"]
    return {"prio_mixed_per_sec": pm, "general_per_sec": gen,
            "prio_vs_general_ratio": pm / gen}


def check_prio_split_routing():
    """Runtime-level prio gate → error string or None. general_bench
    pre-stages the split's sub-batches, so a demotion reintroduced in
    ``runtime._decide_split_nowait`` would not move the metric above —
    this probe feeds a mixed 1%-prio batch through the runtime and
    asserts the split dispatch actually fires."""
    import numpy as np

    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sentinel_tpu as stpu

    sph = stpu.Sentinel(stpu.load_config(
        max_resources=64, max_origins=32, max_flow_rules=32,
        max_degrade_rules=16, max_authority_rules=16,
        host_fast_path=False))
    sph.load_flow_rules([
        stpu.FlowRule(resource="api", count=500.0),
        stpu.FlowRule(resource="api", count=3.0, limit_app="app-a"),
    ])
    oid = sph.origins.pin("app-a")
    row = sph.resources.get_or_create("api")
    rng = np.random.default_rng(7)
    n = 8192                      # scalar side > the 4096 split threshold
    pad_a = sph.spec.alt_rows
    has_o = rng.random(n) < 0.1
    oids = np.where(has_o, oid, 0).astype(np.int32)
    orow = np.where(has_o, sph._alt_row(row, 0, int(oid)),
                    pad_a).astype(np.int32)
    calls = []
    orig = sph._decide_split_nowait
    sph._decide_split_nowait = lambda *a, **k: (calls.append(1),
                                                orig(*a, **k))[1]
    sph.decide_raw(np.full(n, row, np.int32), oids, orow,
                   np.zeros(n, np.int32), np.full(n, pad_a, np.int32),
                   np.ones(n, np.int32), np.ones(n, bool),
                   rng.random(n) < 0.01)          # 1% prioritized
    if not calls:
        return ("mixed 1%-prio batch did not take the split dispatch — "
                "whole-batch prioritized demotion is back (pre-r6 cliff)")
    return None


# instrumented/uninstrumented wall-time band for the observability layer
# (obs/): the spans + counters + histograms riding the batch hot path must
# stay within 2% of SENTINEL_OBS_DISABLE=1. Measured best-of-N interleaved
# THROUGH the runtime (entry_batch_nowait with a split-firing mixed batch)
# — general_bench.measure() pre-stages sub-batches and drives the jitted
# step directly, so it never executes a single instrumented line.
OBS_OVERHEAD_MAX = 1.02


def measure_obs_overhead() -> dict:
    """Ratio of best entry-batch step time with obs enabled over obs
    disabled (two otherwise-identical runtimes, the disabled one built
    under SENTINEL_OBS_DISABLE=1). Mixed 10%-origin batches above the
    4096-row threshold so the split path — the most-instrumented route —
    is the one being timed. Both runtimes build under the default env,
    so from round 20 BOTH carry the per-resource RT histogram scatter
    in record_exits — the band therefore re-verifies with histograms
    enabled, and the scatter itself is exercised on the timed path."""
    import time as _time

    import numpy as np

    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sentinel_tpu as stpu
    from sentinel_tpu.obs import OBS_DISABLE_ENV

    def build(disable: bool):
        prev = os.environ.get(OBS_DISABLE_ENV)
        if disable:
            os.environ[OBS_DISABLE_ENV] = "1"
        else:
            os.environ.pop(OBS_DISABLE_ENV, None)
        try:
            sph = stpu.Sentinel(stpu.load_config(
                max_resources=64, max_origins=32, max_flow_rules=32,
                max_degrade_rules=16, max_authority_rules=16,
                host_fast_path=False))
        finally:
            if prev is None:
                os.environ.pop(OBS_DISABLE_ENV, None)
            else:
                os.environ[OBS_DISABLE_ENV] = prev
        sph.load_flow_rules([
            stpu.FlowRule(resource="api", count=1e9),
            stpu.FlowRule(resource="api", count=1e9, limit_app="app-a"),
        ])
        return sph

    B, STEPS, REPEATS = 8192, 6, 8
    rng = np.random.default_rng(11)
    resources = ["api"] * B
    origins = ["app-a" if x else "" for x in (rng.random(B) < 0.1)]
    pair = [("on", build(False)), ("off", build(True))]
    assert pair[0][1].obs.enabled and not pair[1][1].obs.enabled
    best = {}
    for _key, sph in pair:                  # warm compiles + caches
        for _ in range(2):
            sph.entry_batch_nowait(resources, origins=origins).result()
    for rep in range(REPEATS):
        # interleaved AND order-alternated: slow drift and the
        # first-measured-runs-warmer bias both cancel in the ratio
        for key, sph in (pair if rep % 2 == 0 else pair[::-1]):
            t0 = _time.perf_counter()
            for _ in range(STEPS):
                sph.entry_batch_nowait(resources,
                                       origins=origins).result()
            dt = (_time.perf_counter() - t0) / STEPS
            best[key] = min(best.get(key, dt), dt)
    for _key, sph in pair:
        sph.close()
    return {"obs_on_s_per_step": best["on"],
            "obs_off_s_per_step": best["off"],
            "obs_overhead_ratio": best["on"] / best["off"]}


# Gate (e) — the dispatch-pipeline gate (r6, portable). Ratios, so machine
# speed cancels:
#   fused:    the allow-then-exit serving loop through
#             decide_and_exit_raw_nowait (ONE dispatch/step) vs the
#             decide+exit two-call form — pure dispatch-count reduction,
#             backend-independent (measured ~0.91-0.97 on CPU; the whole
#             win at the tunneled TPU's 2.37 ms/dispatch floor). Must be
#             ≤ FUSED_MAX of two-call: this is the gated "pipelined
#             dispatch beats the synchronous loop" number.
#   overlay:  DispatchPipeline(depth=2) vs the sync loop through
#             entry_batch_nowait. On THIS backend the window is ~
#             breakeven — the CPU PJRT client acquires donated buffers
#             synchronously at dispatch and chained steps serialize on
#             device anyway — so the CPU pin is "adds no material
#             overhead" (≤ PIPELINE_OVERHEAD_MAX), while the depth/stall
#             counters prove batches genuinely overlapped in flight. The
#             latency WIN of the window is an accelerator-backend effect,
#             carried by the BENCH artifacts (bench.py "serving" +
#             dispatch_floor_*_ms keys), not gateable on CPU.
#   floor:    tiny-op per-dispatch readback vs a depth-2 deferred-readback
#             window — recorded for the artifact trail but NOT gated: the
#             CPU round trip is ~35 µs, so the window's deque overhead is
#             the same order as the savings and the ratio is noise there.
FUSED_MAX = 0.985
PIPELINE_OVERHEAD_MAX = 1.10


def measure_dispatch_pipeline() -> dict:
    import time as _time

    import numpy as np

    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import sentinel_tpu as stpu
    from sentinel_tpu.obs import counters as obs_keys

    # --- floor pin: per-dispatch readback vs depth-2 deferred window ---
    import collections
    tiny = jax.jit(lambda x: x + 1)
    x0 = jnp.zeros((8,), jnp.int32)
    _ = np.asarray(tiny(x0)[:1])
    N = 200

    def floor_sync() -> float:
        t0 = _time.perf_counter()
        for _ in range(N):
            _ = np.asarray(tiny(x0)[:1])
        return (_time.perf_counter() - t0) / N

    def floor_pipe() -> float:
        window: "collections.deque" = collections.deque()
        t0 = _time.perf_counter()
        for _ in range(N):
            window.append(tiny(x0))
            if len(window) > 2:
                _ = np.asarray(window.popleft()[:1])
        while window:
            _ = np.asarray(window.popleft()[:1])
        return (_time.perf_counter() - t0) / N

    fbest = {}
    for rep in range(8):
        for key, fn in ([("s", floor_sync), ("p", floor_pipe)]
                        if rep % 2 == 0 else
                        [("p", floor_pipe), ("s", floor_sync)]):
            dt = fn()
            fbest[key] = min(fbest.get(key, dt), dt)

    # --- runtime fixture shared by the fused and overlay pins ---
    B, STEPS, REPEATS = 8192, 6, 8
    sph = stpu.Sentinel(stpu.load_config(
        max_resources=1024, max_flow_rules=64, max_degrade_rules=16,
        max_authority_rules=16))
    sph.load_flow_rules([stpu.FlowRule(resource=f"s{i}", count=1e9)
                         for i in range(64)])
    rng = np.random.default_rng(13)
    rows = sph.intern_resources(
        [f"s{int(i)}" for i in rng.integers(0, 512, B)])
    pad_a = sph.spec.alt_rows
    orow = np.full(B, pad_a, np.int32)
    ctx0 = np.zeros(B, np.int32)
    ones = np.ones(B, np.int32)
    is_in = np.ones(B, np.bool_)
    noprio = np.zeros(B, np.bool_)
    rt = np.full(B, 5, np.int32)
    err = np.zeros(B, np.bool_)

    def run_two_call() -> float:
        t0 = _time.perf_counter()
        for _ in range(STEPS):
            h = sph.decide_raw_nowait(rows, ctx0, orow, ctx0, orow, ones,
                                      is_in, noprio)
            sph.exit_batch(rows=rows, origin_rows=orow, chain_rows=orow,
                           acquire=ones, rt_ms=rt, error=err, is_in=is_in)
            h.result()
        return (_time.perf_counter() - t0) / STEPS

    def run_fused() -> float:
        t0 = _time.perf_counter()
        for _ in range(STEPS):
            sph.decide_and_exit_raw_nowait(
                rows, ctx0, orow, ctx0, orow, ones, is_in, noprio,
                exit_rows=rows, exit_origin_rows=orow,
                exit_chain_rows=orow, exit_acquire=ones, exit_rt_ms=rt,
                exit_error=err, exit_is_in=is_in).result()
        return (_time.perf_counter() - t0) / STEPS

    def run_sync() -> float:
        t0 = _time.perf_counter()
        for _ in range(STEPS):
            sph.entry_batch_nowait(rows).result()
        return (_time.perf_counter() - t0) / STEPS

    def run_pipelined() -> float:
        pipe = stpu.DispatchPipeline(sph, depth=2)
        tickets: "collections.deque" = collections.deque()
        t0 = _time.perf_counter()
        for _ in range(STEPS):
            tickets.append(pipe.submit(rows))
            if len(tickets) > pipe.depth:
                tickets.popleft().result()
        while tickets:
            tickets.popleft().result()
        return (_time.perf_counter() - t0) / STEPS

    best = {}
    pairs = [("two_call", run_two_call), ("fused", run_fused),
             ("sync", run_sync), ("pipelined", run_pipelined)]
    for _key, fn in pairs:                       # warm compiles + caches
        fn()
    for rep in range(REPEATS):
        for key, fn in (pairs if rep % 2 == 0 else pairs[::-1]):
            dt = fn()
            best[key] = min(best.get(key, dt), dt)

    # mechanism probe: the overlay numbers only mean something if batches
    # actually were in flight together
    depth_sum = sph.obs.counters.get(obs_keys.PIPE_DEPTH)
    stalls = sph.obs.counters.get(obs_keys.PIPE_STALL)
    # run_pipelined executed once to warm + once per repeat; average
    # in-flight depth > 1 ⟺ depth_sum > enqueues
    enqueues = (REPEATS + 1) * STEPS
    fused_routes = sph.obs.counters.get(obs_keys.ROUTE_FUSED)
    sph.close()
    return {
        "floor_sync_s": fbest["s"], "floor_pipelined_s": fbest["p"],
        "floor_ratio": fbest["p"] / fbest["s"],
        "two_call_s_per_step": best["two_call"],
        "fused_s_per_step": best["fused"],
        "fused_ratio": best["fused"] / best["two_call"],
        "sync_s_per_step": best["sync"],
        "pipelined_s_per_step": best["pipelined"],
        "pipeline_overhead_ratio": best["pipelined"] / best["sync"],
        "pipelined_depth_reached": depth_sum > enqueues,
        "pipeline_stalls": stalls,
        "fused_dispatches": fused_routes,
    }


# Gate (f) — the serving SLO gate (r7): end-to-end request→verdict
# latency through the real front end (frontend/batcher.py open-loop
# replay, benchmarks/serving_bench.py). Two probes:
#   steady:  at a pinned offered rate on the CPU backend, the p99 must
#            sit inside a BAND — the high edge is the SLO (generous vs
#            the ~16 ms measured here: CPU CI machine classes vary, but
#            an event-loop stall, a lost wakeup, or a blocking call on
#            the loop thread costs 10-100×, which any hardware catches);
#            the low edge catches a degenerated measurement (a p99 of
#            ~0 means requests never crossed the device). Zero shed and
#            exact accounting (completed == offered) are part of the pin.
#   flash:   an 8× arrival spike against a small batch bound must DEGRADE
#            GRACEFULLY: every request accounted (completed + shed ==
#            offered — no lost futures), no deadline-miss collapse
#            (< FLASH_MISS_COLLAPSE of completed missing their budget),
#            and the mechanism probe — the spike must actually cut
#            batch_max-full batches (flush_full > 0), or the run never
#            stressed the coalescing path it claims to.
STEADY_P99_BAND_MS = (0.2, 150.0)
FLASH_MISS_COLLAPSE = 0.9

# Gate (g) — the trace-capture mechanism probe (r8): an induced
# flash-crowd deadline miss must leave a PERSISTED causal chain behind.
# A fresh flash replay with a 2 ms request deadline (every settled
# request misses) runs with the flight recorder's <app>-trace log
# attached to a temp dir; the probe then reads the rotation back with
# ``load_pinned`` and requires (i) ≥1 pinned record including a
# ``deadline_miss`` kind, (ii) the chain to span the TIERS — the
# request-side terminal span (frontend.settle) AND a batch-side span
# (frontend.flush / pipeline.enqueue) reached through a fan-in link —
# and (iii) the record to survive the Chrome-trace export + json.loads
# round trip. Each leg pins a different failure: trace-id threading
# severed (chain collapses to one tier), trigger plumbing dead (no
# record at all), writer/searcher codec drift (parse failure).
TRACE_REQUIRED_REQUEST_SPAN = "frontend.settle"
TRACE_REQUIRED_BATCH_SPANS = ("frontend.flush", "pipeline.enqueue")


def measure_serving() -> dict:
    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from benchmarks import serving_bench

    steady = serving_bench.run_workload(
        "steady", seed=42, duration_ms=600.0, rate_rps=1000.0)
    flash = serving_bench.run_workload(
        "flash_crowd", seed=43, duration_ms=600.0, rate_rps=1000.0,
        batch_max=64, wl_kwargs={"spike_mult": 8.0})
    return {
        "steady_p99_ms": steady["p99_ms"],
        "steady_worst_traced": bool(
            steady.get("worst_request", {}).get("trace")),
        "steady_p50_ms": steady["p50_ms"],
        "steady_offered": steady["offered"],
        "steady_completed": steady["completed"],
        "steady_shed": steady["shed"],
        "flash_offered": flash["offered"],
        "flash_completed": flash["completed"],
        "flash_shed": flash["shed"],
        "flash_miss_frac": flash["deadline_miss_frac"],
        "flash_flush_full": flash["flush_full"],
        "flash_p50_ms": flash["p50_ms"],
    }


def measure_trace_capture() -> dict:
    """Gate (g): induced deadline misses must pin a persisted, parseable,
    tier-spanning causal chain (see the comment block above
    TRACE_REQUIRED_REQUEST_SPAN)."""
    import shutil
    import tempfile

    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from benchmarks import serving_bench
    from sentinel_tpu.obs import flight as flight_mod
    from sentinel_tpu.obs import traceexport

    tmp = tempfile.mkdtemp(prefix="sentinel-trace-gate-")
    try:
        res = serving_bench.run_workload(
            "flash_crowd", seed=44, duration_ms=300.0, rate_rps=1000.0,
            batch_max=64, deadline_ms=2, wl_kwargs={"spike_mult": 8.0},
            trace_dir=tmp)
        pinned = flight_mod.load_pinned(tmp, "flash_crowd")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    kinds, names = set(), set()
    for rec in pinned:
        kinds.add(rec.get("kind"))
        for s in rec.get("spans", ()):
            names.add(s.get("name"))
    chain_ok = False
    export_ok = False
    for rec in pinned:
        rec_names = {s.get("name") for s in rec.get("spans", ())}
        if (TRACE_REQUIRED_REQUEST_SPAN in rec_names
                and rec_names.intersection(TRACE_REQUIRED_BATCH_SPANS)
                and rec.get("links")):
            chain_ok = True
            doc = json.loads(traceexport.dumps(traceexport.chrome_trace(rec)))
            export_ok = bool(doc.get("traceEvents"))
            break
    return {
        "induced_misses": res["deadline_miss"],
        "pinned_records": len(pinned),
        "kinds": sorted(k for k in kinds if k),
        "chain_spans_tiers_ok": chain_ok,
        "chrome_trace_ok": export_ok,
    }


# Gate (h) — the meshed-serving gate (r9): the row-sharded engine IS the
# serving hot path, so its promotion is pinned by two probes run in a
# dedicated subprocess on an 8-virtual-device CPU mesh (XLA_FLAGS must be
# set before the jax backend initializes — hence the ``--meshed``
# re-exec, the same isolation trick measure_once uses for bench.py):
#   parity:   a single-device engine and an 8-device meshed engine are
#             driven through the FULL serving stack with identical
#             traffic — DispatchPipeline over decide_raw_nowait (a mixed
#             batch above the split threshold with 10% origins and 1%
#             prioritized, so the split + fast-occupy routes fire), a
#             mid-stream rule reload with live occupy bookings (the
#             carry path), the fused decide+exit tier, and the
#             AdaptiveBatcher fan-out (meshed verdicts replayed
#             flush-by-flush on the single-device twin) — and every
#             verdict must be BIT-IDENTICAL. Placement is layout, not
#             math; any divergence means the mesh path computes
#             something different from what the tests promise.
#             Mechanism probes ride along: the split dispatch must
#             actually fire, ROUTE_MESHED/PIPE_MESHED must tick, and
#             both engines must CARRY the same number of live occupy
#             bookings across the reload (a zero means the probe never
#             exercised the carry path it claims to pin).
#   flatness: the weak-scaling curve (benchmarks/weak_scaling.py) at
#             small shapes — fixed rows per device, 1/2/4/8 devices,
#             depth-swept through the pipeline. On this host the
#             virtual devices SERIALIZE, so the gated number is the
#             normalized per-partition cost step_ms(n)/(n·step_ms(1)):
#             ~1.0 benign (measured 0.71-1.02 here), and climbing past
#             WEAK_SCALING_FLAT_MAX only on super-linear pathology
#             (all-to-all blowup, per-shard recompiles, a host loop
#             over shards) — the portable signal that survives the move
#             to real parallel silicon.
# CI_GATE_MESHED=0 skips the whole gate (e.g. a tier that already ran
# it, or a debug loop on the other gates).
MESHED_ENV_FLAG = "CI_GATE_MESHED"
WEAK_SCALING_FLAT_MAX = 1.6
MESHED_N_DEV = 8


def _meshed_parity(jax) -> dict:
    import numpy as np

    import sentinel_tpu as stpu
    from sentinel_tpu.core.clock import ManualClock
    from sentinel_tpu.obs import counters as obs_keys
    from sentinel_tpu.parallel.local_shard import local_mesh
    from sentinel_tpu.serving import DispatchPipeline

    T0 = 1_785_000_000_000

    def cfg():
        return stpu.load_config(
            max_resources=64, max_origins=32, max_flow_rules=32,
            max_degrade_rules=16, max_authority_rules=16,
            host_fast_path=False)

    def build(mesh):
        s = stpu.Sentinel(cfg(), clock=ManualClock(start_ms=T0), mesh=mesh)
        s.load_flow_rules([
            stpu.FlowRule(resource="api", count=3.0),
            stpu.FlowRule(resource="api", count=2.0, limit_app="app-a"),
            stpu.FlowRule(resource="bulk", count=1e6),
        ])
        return s

    ref, meshed = build(None), build(local_mesh(MESHED_N_DEV))

    def vequal(a, b) -> bool:
        return (np.array_equal(np.asarray(a.allow), np.asarray(b.allow))
                and np.array_equal(np.asarray(a.reason),
                                   np.asarray(b.reason))
                and np.array_equal(np.asarray(a.wait_ms),
                                   np.asarray(b.wait_ms)))

    # mixed raw traffic above the 4096 split threshold: 90% scalar bulk,
    # 10% origin-carrying (the general side), 1% prioritized (the
    # fast-occupy side, denied often enough under count=3.0 to book)
    rng = np.random.default_rng(29)
    n = 8192
    row_api = ref.resources.get_or_create("api")
    row_bulk = ref.resources.get_or_create("bulk")
    assert meshed.resources.get_or_create("api") == row_api
    assert meshed.resources.get_or_create("bulk") == row_bulk
    oid = ref.origins.pin("app-a")
    meshed.origins.pin("app-a")
    pad_a = ref.spec.alt_rows
    rows = np.where(rng.random(n) < 0.5, row_api, row_bulk).astype(np.int32)
    has_o = rng.random(n) < 0.1
    oids = np.where(has_o, oid, 0).astype(np.int32)
    # alt rows are scalar-hashed per (resource row, origin); record the
    # edge on BOTH engines so eviction hygiene stays in lockstep
    alt = {r: ref._alt_row(r, 0, int(oid)) for r in (row_api, row_bulk)}
    for r in (row_api, row_bulk):
        assert meshed._alt_row(r, 0, int(oid)) == alt[r]
    orow = np.where(has_o,
                    np.where(rows == row_api, alt[row_api], alt[row_bulk]),
                    pad_a).astype(np.int32)
    ctx0 = np.zeros(n, np.int32)
    chain = np.full(n, pad_a, np.int32)
    ones = np.ones(n, np.int32)
    is_in = np.ones(n, np.bool_)
    prio = rng.random(n) < 0.01
    rt = np.full(n, 5, np.int32)
    err = np.zeros(n, np.bool_)

    split_calls = []
    orig_split = meshed._decide_split_nowait
    meshed._decide_split_nowait = lambda *a, **k: (
        split_calls.append(1), orig_split(*a, **k))[1]

    out = {"parity": {}}
    pipes = {"ref": DispatchPipeline(ref, depth=2),
             "meshed": DispatchPipeline(meshed, depth=2)}

    def drive_raw(steps: int, tick0: int) -> bool:
        got = {}
        for key, pipe in pipes.items():
            tickets = [pipe.submit_raw(
                rows, oids, orow, ctx0, chain, ones, is_in, prio,
                at_ms=T0 + (tick0 + i) * 250) for i in range(steps)]
            got[key] = [t.result() for t in tickets]
        return all(vequal(a, b) for a, b in zip(got["ref"], got["meshed"]))

    # depth-2 pipelined dispatch, windows rotating, split + occupy live
    out["parity"]["pipeline_raw"] = drive_raw(4, 0)
    granted = {k: s.obs.counters.get(obs_keys.OCCUPY_GRANTED)
               for k, s in (("ref", ref), ("meshed", meshed))}
    # rule reload with those bookings still PENDING: the engine clock
    # must first catch up to the traffic timeline — settle_occupied
    # carries only bookings whose target window is the clock's next one
    for s in (ref, meshed):
        s.clock.advance_ms(750)
        s.load_flow_rules([
            stpu.FlowRule(resource="api", count=4.0),
            stpu.FlowRule(resource="api", count=2.0, limit_app="app-a"),
            stpu.FlowRule(resource="bulk", count=1e6),
        ])
    out["parity"]["post_reload"] = drive_raw(4, 4)
    # fused decide+exit through the pipeline
    fused = {}
    for key, pipe in pipes.items():
        tickets = [pipe.submit_fused(
            rows, oids, orow, ctx0, chain, ones, is_in, prio,
            exit_rows=rows, exit_origin_rows=orow, exit_chain_rows=chain,
            exit_acquire=ones, exit_rt_ms=rt, exit_error=err,
            exit_is_in=is_in, at_ms=T0 + (8 + i) * 50)
            for i in range(3)]
        fused[key] = [t.result() for t in tickets]
    out["parity"]["fused"] = all(
        vequal(a, b) for a, b in zip(fused["ref"], fused["meshed"]))

    out["split_fired"] = len(split_calls)
    out["occupy_granted_ref"] = granted["ref"]
    out["occupy_granted_meshed"] = granted["meshed"]
    out["occupy_carried_ref"] = ref.obs.counters.get(
        obs_keys.OCCUPY_CARRIED)
    out["occupy_carried_meshed"] = meshed.obs.counters.get(
        obs_keys.OCCUPY_CARRIED)
    out["route_meshed"] = meshed.obs.counters.get(obs_keys.ROUTE_MESHED)
    out["pipe_meshed"] = meshed.obs.counters.get(obs_keys.PIPE_MESHED)
    ref.close()
    meshed.close()

    # front-end fan-out: the batcher on the MESHED engine, its recorded
    # flush cuts replayed sequentially on a fresh single-device twin
    import asyncio

    from sentinel_tpu.frontend.batcher import AdaptiveBatcher

    fe_m, seq_r = build(local_mesh(MESHED_N_DEV)), build(None)
    frng = np.random.default_rng(31)
    stream = [("api" if frng.random() < 0.7 else "bulk",
               bool(frng.random() < 0.3),
               "app-a" if frng.random() < 0.4 else "")
              for _ in range(42)]

    async def run():
        b = AdaptiveBatcher(fe_m, batch_max=8, deadline_ms=60_000,
                            idle_ms=10_000.0, depth=2, record_flushes=True)
        verdicts = await asyncio.gather(
            *(b.submit(r, prioritized=p, origin=o) for r, p, o in stream))
        await b.drain()
        return verdicts, b.flush_log

    verdicts, flush_log = asyncio.run(run())
    seq = []
    for f in flush_log:
        v = seq_r.entry_batch_nowait(
            f["resources"],
            acquire=np.asarray(f["counts"], np.int32),
            prioritized=np.asarray(f["prioritized"], np.bool_),
            origins=(f["origins"] if any(f["origins"]) else None),
        ).result()
        seq.extend(zip(np.asarray(v.allow), np.asarray(v.reason),
                       np.asarray(v.wait_ms)))
    out["parity"]["frontend"] = (
        len(seq) == len(verdicts)
        and all((g.allow, g.reason, g.wait_ms)
                == (bool(w[0]), int(w[1]), int(w[2]))
                for g, w in zip(verdicts, seq)))
    fe_m.close()
    seq_r.close()
    return out


def meshed_main() -> int:
    """The ``--meshed`` re-exec body: 8 virtual CPU devices (flag set
    before jax initializes), parity + flatness, ONE JSON line out."""
    os.environ.setdefault(
        "XLA_FLAGS", f"--xla_force_host_platform_device_count={MESHED_N_DEV}")
    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from benchmarks import weak_scaling

    out = _meshed_parity(jax)
    points = weak_scaling.measure(
        jax, rows_per_dev=2048, batch=4096, steps=4,
        device_counts=(1, 2, 4, MESHED_N_DEV), depths=(1, 2), rules=64)
    out["curve_devices"] = [p["devices"] for p in points if "step_ms" in p]
    out["flatness_norm"] = weak_scaling.flatness(points)
    print(json.dumps(out))
    return 0


def measure_meshed() -> dict:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "BENCH_PLATFORM": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={MESHED_N_DEV}",
    }
    out = subprocess.run(
        [sys.executable, str(Path(__file__).resolve()), "--meshed"],
        env=env, capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        return {"error": (out.stderr or out.stdout)[-2000:]}
    return json.loads(out.stdout.strip().splitlines()[-1])


# Gate (i) — the sort-free general-path gate (r10): ops/sortfree.py's
# hash-bucketed claim cascade replaced the n·log n composite-key sort as
# the DEFAULT general/mixed aggregation, with the sorted path kept as a
# bit-parity reference behind SENTINEL_SORTFREE=0. Two probes pin the
# promotion:
#   parity:   two engines built under SENTINEL_SORTFREE=1 vs =0 are
#             driven through the REAL dispatch with identical traffic
#             in two phases — first a rate-limiter ruleset (the
#             per-rule segment collapse) under a non-uniform-acquire
#             mixed batch (defeats the fast-path uniform-acquire
#             precondition, so the whole batch takes the pair-key
#             GENERAL route the cascade owns) plus a split-firing
#             8192-row mixed batch; then a reload to an occupy-capable
#             ruleset whose 1% prioritized slice is denied often
#             enough under count=3.0 to book PriorityWait, with a
#             second reload while those bookings are live (the carry
#             fold) — and every verdict must be BIT-IDENTICAL.
#             Mechanism probes ride along:
#             split_route.sortfree must tick on the sortfree engine and
#             stay dead on the sorted one, ROUTE_GENERAL and the split
#             dispatch must prove the cascade routes actually ran, the
#             carried booking counts must match, and the DEFAULT-sized
#             claim table must not overflow (an overflow here means
#             table sizing regressed — the lax.cond sorted fallback
#             would hide the perf loss while parity stays green).
#   ratio:    general_bench mode="general" sortfree/sorted decisions
#             per sec at small CPU shapes — machine speed cancels. The
#             honest CPU story (BASELINE.md round 10): XLA:CPU's sort
#             is excellent and the claim cascade's chunked scatter scan
#             is serial there, so sortfree runs BELOW parity on this
#             backend (~0.78× at the gate's B=4096, degrading with B —
#             the win this round claims is the accelerator's, where the
#             composite-key sort is the bottleneck the paper names).
#             The band therefore pins the CPU cost from DEGENERATING,
#             not from existing: a per-element host loop, lost fusion,
#             or an accidental sync costs 10-1000×, which ≥
#             SORTFREE_MIN_RATIO catches on any hardware, while the
#             accelerator-side win is carried informationally by the
#             bench artifacts.
# CI_GATE_SORTFREE=0 skips the whole gate.
SORTFREE_ENV_FLAG = "CI_GATE_SORTFREE"
SORTFREE_MIN_RATIO = 0.5


def _sortfree_parity() -> dict:
    import numpy as np

    import sentinel_tpu as stpu
    from sentinel_tpu.core.clock import ManualClock
    from sentinel_tpu.obs import counters as obs_keys

    T0 = 1_785_000_000_000
    # phase 1 carries the RATE-LIMITER rule (the per-rule segment
    # collapse the cascade must reproduce); phase 2 swaps it for the
    # always-pass bulk rule because an RL rule in the ruleset suppresses
    # PriorityWait grants — the occupy booking/carry probe needs them
    RULES_RL = [
        stpu.FlowRule(resource="api", count=3.0),
        stpu.FlowRule(resource="api", count=2.0, limit_app="app-a"),
        stpu.FlowRule(resource="paced", count=10.0,
                      control_behavior=stpu.BEHAVIOR_RATE_LIMITER,
                      max_queueing_time_ms=400),
    ]
    RULES_OCC = [
        stpu.FlowRule(resource="api", count=3.0),
        stpu.FlowRule(resource="api", count=2.0, limit_app="app-a"),
        stpu.FlowRule(resource="bulk", count=1e6),
    ]

    def build(env):
        # the flag is read at ruleset build, so it must be set before
        # construction (and again before every reload)
        os.environ["SENTINEL_SORTFREE"] = env
        s = stpu.Sentinel(stpu.load_config(
            max_resources=64, max_origins=32, max_flow_rules=32,
            max_degrade_rules=16, max_authority_rules=16,
            host_fast_path=False), clock=ManualClock(start_ms=T0))
        s.load_flow_rules(RULES_RL)
        return s

    saved = os.environ.get("SENTINEL_SORTFREE")
    engines = []
    try:
        srt, sf = build("0"), build("1")
        engines = [srt, sf]
        assert not srt._sortfree and sf._sortfree

        def reload(rules):
            # the env flag is re-read at every reload: restore each
            # engine's setting or both would flip to the last value set
            for s, env in ((srt, "0"), (sf, "1")):
                os.environ["SENTINEL_SORTFREE"] = env
                s.load_flow_rules(rules)
            assert not srt._sortfree and sf._sortfree

        rng = np.random.default_rng(29)
        rows_by_name = {}
        for name in ("api", "paced", "bulk"):
            rows_by_name[name] = srt.resources.get_or_create(name)
            assert sf.resources.get_or_create(name) == rows_by_name[name]
        oid = srt.origins.pin("app-a")
        sf.origins.pin("app-a")
        pad_a = srt.spec.alt_rows
        alt = {r: srt._alt_row(r, 0, int(oid))
               for r in rows_by_name.values()}
        for r in rows_by_name.values():
            assert sf._alt_row(r, 0, int(oid)) == alt[r]

        def mixed(n, other, origin_frac, prio_frac, acquire_hi):
            row_api, row_o = rows_by_name["api"], rows_by_name[other]
            rows = np.where(rng.random(n) < 0.5, row_api,
                            row_o).astype(np.int32)
            has_o = rng.random(n) < origin_frac
            oids = np.where(has_o, oid, 0).astype(np.int32)
            orow = np.where(has_o,
                            np.where(rows == row_api, alt[row_api],
                                     alt[row_o]),
                            pad_a).astype(np.int32)
            acq = rng.integers(1, acquire_hi + 1, n).astype(np.int32)
            return (rows, oids, orow, np.zeros(n, np.int32),
                    np.full(n, pad_a, np.int32), acq,
                    np.ones(n, np.bool_),
                    np.asarray(rng.random(n) < prio_frac))

        split_calls = []
        orig_split = sf._decide_split_nowait
        sf._decide_split_nowait = lambda *a, **k: (
            split_calls.append(1), orig_split(*a, **k))[1]

        def vequal(a, b):
            return (np.array_equal(np.asarray(a.allow), np.asarray(b.allow))
                    and np.array_equal(np.asarray(a.reason),
                                       np.asarray(b.reason))
                    and np.array_equal(np.asarray(a.wait_ms),
                                       np.asarray(b.wait_ms)))

        parity = True

        def both(batch):
            nonlocal parity
            parity = parity and vequal(srt.decide_raw(*batch),
                                       sf.decide_raw(*batch))

        def tick(ms=250):
            for s in engines:
                s.clock.advance_ms(ms)

        # batches are built ONCE so both engines see byte-identical
        # traffic: non-uniform acquire → whole-batch pair-key general
        # route; 8192 rows + origins → split dispatch
        gen = mixed(1024, "paced", 0.25, 0.0, 2)
        spl = mixed(8192, "paced", 0.25, 0.01, 1)
        occ = mixed(8192, "bulk", 0.1, 0.01, 1)

        # phase 1 — RL ruleset: general-route + split parity with the
        # per-rule segment collapse live
        for _ in range(2):
            both(gen)
            tick()
            both(spl)
            tick()
        reload(RULES_OCC)
        # phase 2 — occupy: windows rotate under the 250ms ticks until
        # the api quota fills from a PRIOR bucket, then the denied prio
        # slice books into the next window (PriorityWait); reloading
        # BEFORE the next tick finds those bookings pending → carried
        for i in range(4):
            both(occ)
            if i < 3:
                tick()
        reload(RULES_OCC)
        tick()
        both(gen)          # general route with the carried ring live
        both(occ)
        return {
            "parity": bool(parity),
            "split_fired": len(split_calls),
            "route_general": sf.obs.counters.get(obs_keys.ROUTE_GENERAL),
            "route_sortfree": sf.obs.counters.get(obs_keys.ROUTE_SORTFREE),
            "route_sortfree_sorted_engine":
                srt.obs.counters.get(obs_keys.ROUTE_SORTFREE),
            "overflow_default_table":
                sf.obs.counters.get(obs_keys.SORTFREE_OVERFLOW),
            "occupy_granted_sorted":
                srt.obs.counters.get(obs_keys.OCCUPY_GRANTED),
            "occupy_granted_sortfree":
                sf.obs.counters.get(obs_keys.OCCUPY_GRANTED),
            "occupy_carried_sorted":
                srt.obs.counters.get(obs_keys.OCCUPY_CARRIED),
            "occupy_carried_sortfree":
                sf.obs.counters.get(obs_keys.OCCUPY_CARRIED),
        }
    finally:
        if saved is None:
            os.environ.pop("SENTINEL_SORTFREE", None)
        else:
            os.environ["SENTINEL_SORTFREE"] = saved
        for s in engines:
            s.close()


def measure_sortfree() -> dict:
    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from benchmarks import general_bench

    out = _sortfree_parity()
    R, B, STEPS, NRULES, REPEATS = 1 << 12, 1 << 12, 8, 128, 3
    srt = general_bench.measure(jax, "general", R, B, STEPS, NRULES,
                                REPEATS)["value"]
    sf = general_bench.measure(jax, "general", R, B, STEPS, NRULES,
                               REPEATS, sortfree=True)["value"]
    out["sorted_per_sec"] = srt
    out["sortfree_per_sec"] = sf
    out["sortfree_vs_sorted_ratio"] = sf / srt
    return out


# Gate (j) — the autotune gate (r11): sentinel_tpu/tune/ promoted the
# scattered env knobs into a typed registry plus a measurement-driven
# sweep (coordinate descent + successive halving over REAL serving
# episodes), so the gate pins the whole loop end to end:
#   sweep:    run_sweep over 2 knobs × tiny grids at short rungs on the
#             CPU backend. It must CONVERGE (every trial ran; no parity
#             failure) and write the TUNED.json artifact. Every trial's
#             verdict bit-parity spot-check vs the default config must
#             pass (tune.parity_fail == 0) — the tuner is a PERF tool
#             and must never pin a config that changes a verdict.
#   pin:      the artifact is then loaded back the way production
#             would: SENTINEL_TUNED_CONFIG set for a fresh serving
#             replay, with the provenance probe asserting the startup
#             path genuinely resolved it (fingerprint matched, knobs
#             applied) rather than silently falling back to defaults.
#   parity:   the pinned config's trace-knob slice must produce a
#             byte-identical verdict stream below the batcher
#             (_verdict_signature, the same comparable every trial
#             used).
#   ratio:    tuned/default settled-request throughput through the full
#             serving replay, best-of-N interleaved so machine drift
#             cancels, must stay ≥ TUNE_MIN_RATIO — the tuner's whole
#             contract is "never worse than defaults"; a winner that
#             loses to the baseline it beat during search means the
#             scoring plumbing (obs-sourced decisions_per_s / p99) or
#             the artifact application path regressed.
# CI_GATE_TUNE=0 skips the whole gate.
TUNE_ENV_FLAG = "CI_GATE_TUNE"
TUNE_MIN_RATIO = 0.95


def measure_tune() -> dict:
    import shutil
    import tempfile

    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from benchmarks import serving_bench
    from sentinel_tpu.obs import counters as obs_keys
    from sentinel_tpu.tune import artifact as tune_artifact
    from sentinel_tpu.tune import knobs as tune_knobs
    from sentinel_tpu.tune import run_sweep
    from sentinel_tpu.tune.runner import _verdict_signature

    tmp = tempfile.mkdtemp(prefix="sentinel-tune-gate-")
    out_path = os.path.join(tmp, "TUNED.json")
    try:
        sweep = run_sweep(
            envs=("SENTINEL_PIPELINE_DEPTH", "SENTINEL_FRONTEND_BUDGET_MS"),
            grids={"SENTINEL_PIPELINE_DEPTH": (1, 2),
                   "SENTINEL_FRONTEND_BUDGET_MS": (1, 3)},
            workload="steady", seed=11, rate_rps=800.0, slo_p99_ms=150.0,
            rung_ms=(150, 300), out_path=out_path)
        res = sweep["result"]
        out = {
            "converged": bool(res.converged),
            "trials": sweep["trials"],
            "parity_checks": sweep["parity_checks"],
            "parity_fail": sweep["counters"].get(
                obs_keys.TUNE_PARITY_FAIL, 0),
            "best_config": dict(res.best_config),
            "artifact_written": sweep["artifact"] is not None,
        }
        if sweep["artifact"] is None:
            return out

        # pinned-config bit-parity below the batcher: same comparable
        # every trial used, over the winner's trace-knob slice
        trace_cfg = tune_knobs.trace_knobs(sweep["artifact"]["knobs"])
        out["pinned_bit_parity"] = (
            _verdict_signature(trace_cfg, seed=5, steps=3, events=64)
            == _verdict_signature({}, seed=5, steps=3, events=64))

        # pinned vs default through the full serving replay — the pinned
        # run loads the artifact via the REAL startup path (env pin), so
        # this also covers resolve_startup + the frontend kwarg fill
        prev = os.environ.get(tune_artifact.TUNED_CONFIG_ENV)

        def episode(pin: bool) -> float:
            if pin:
                os.environ[tune_artifact.TUNED_CONFIG_ENV] = out_path
            else:
                os.environ.pop(tune_artifact.TUNED_CONFIG_ENV, None)
            try:
                if pin and "artifact_loaded" not in out:
                    prov = tune_artifact.provenance()
                    out["artifact_loaded"] = bool(prov.get("tuned"))
                m = serving_bench.run_workload(
                    "steady", seed=11, duration_ms=300.0, rate_rps=800.0)
            finally:
                if prev is None:
                    os.environ.pop(tune_artifact.TUNED_CONFIG_ENV, None)
                else:
                    os.environ[tune_artifact.TUNED_CONFIG_ENV] = prev
            return float(m.get("decisions_per_s") or 0.0)

        best = {}
        for rep in range(3):
            order = [("tuned", True), ("default", False)]
            for key, pin in (order if rep % 2 == 0 else order[::-1]):
                best[key] = max(best.get(key, 0.0), episode(pin))
        out["tuned_decisions_per_s"] = best["tuned"]
        out["default_decisions_per_s"] = best["default"]
        out["tuned_vs_default_ratio"] = (
            best["tuned"] / best["default"] if best["default"] else 0.0)
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# Gate (k) — the hot-resource telemetry gate (r12). Two halves:
#   surface:  a planted-hot-key Zipf mix through the FULL serving path
#             (real Sentinel + start_transport + the dashboard server)
#             must surface the planted keys in the dashboard's
#             /obs/topk.json proxy of the agent's ``topk`` command AND
#             in the <app>-metric log the telemetry writer rides
#             (metrics/searcher.py read-back). Binary: the whole
#             device-tick → async-readback → transport → dashboard
#             chain either works or the gate fails.
#   overhead: the obs-overhead probe re-run with the telemetry TICKER
#             running on the instrumented engine (device tick + async
#             readback overlapped with the dispatch loop) — the
#             instrumented/uninstrumented step-time ratio must stay
#             inside the SAME fixed band (OBS_OVERHEAD_MAX, 1.02):
#             telemetry must not cost what obs/ saved. Machine speed
#             cancels in the ratio.
# CI_GATE_TELEMETRY=0 skips the whole gate.
TELEMETRY_ENV_FLAG = "CI_GATE_TELEMETRY"


def measure_telemetry() -> dict:
    import tempfile
    import time as _time
    import urllib.request

    import numpy as np

    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sentinel_tpu as stpu
    from sentinel_tpu.core.clock import ManualClock
    from sentinel_tpu.dashboard import Dashboard
    from sentinel_tpu.dashboard.server import DashboardServer
    from sentinel_tpu.metrics.searcher import MetricSearcher
    from sentinel_tpu.obs import OBS_DISABLE_ENV
    from sentinel_tpu.transport import start_transport

    T0 = 1_785_000_000_000
    out: dict = {}

    # ---- surface half: planted hot keys end to end -------------------
    tmp = tempfile.mkdtemp(prefix="sentinel-telemetry-gate-")
    clk = ManualClock(start_ms=T0)
    sph = stpu.Sentinel(stpu.load_config(
        max_resources=64, max_flow_rules=16, max_degrade_rules=16,
        max_authority_rules=16, host_fast_path=False,
        metric_log_dir=tmp), clock=clk)
    rt = start_transport(sph, host="127.0.0.1", port=0)
    dash = DashboardServer(Dashboard(password="", clock=clk,
                                     agent_timeout_s=30.0),
                           host="127.0.0.1", port=0)
    dport = dash.start(fetch=False)
    try:
        # drive LATE in the wall second so the traffic is still inside
        # the rolling window when the completed second lands
        clk.advance_ms(600)
        rng = np.random.default_rng(12)
        for z in rng.zipf(1.4, size=200):       # Zipf background
            try:
                sph.entry(f"bg-{min(int(z) - 1, 24)}").exit()
            except stpu.BlockException:
                pass
        for name, n in (("planted-hot-a", 120), ("planted-hot-b", 60)):
            for _ in range(n):
                sph.entry(name).exit()
        clk.advance_ms(500)                     # completes second T0/1000
        with urllib.request.urlopen(
                f"http://127.0.0.1:{dport}/obs/topk.json"
                f"?ip=127.0.0.1&port={rt.port}&tick=1",
                timeout=30) as r:
            body = json.loads(r.read().decode("utf-8"))
        data = body.get("data") or {}
        hot_names = [h["resource"] for h in data.get("hot", [])]
        out["topk_top3"] = hot_names[:3]
        out["planted_in_topk"] = (
            body.get("success", False)
            and "planted-hot-a" in hot_names
            and "planted-hot-b" in hot_names)
        out["planted_rank1"] = bool(hot_names
                                    and hot_names[0] == "planted-hot-a")
        out["timeline_len"] = len(data.get("timeline", []))
        out["drops"] = data.get("drops", -1)
        out["knobs"] = {"k": data.get("k"),
                        "n_shards": data.get("n_shards")}
        seen = {n.resource for n in MetricSearcher(
            tmp, sph.telemetry.base_name).find(T0 - 1000, T0 + 10_000)}
        out["metric_log_resources"] = len(seen)
        out["planted_in_metric_log"] = "planted-hot-a" in seen
    finally:
        dash.stop()
        rt.stop()
        sph.close()
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)

    # ---- overhead half: obs-overhead probe, telemetry ticker ON ------
    def build(disable_obs: bool):
        prev = os.environ.get(OBS_DISABLE_ENV)
        if disable_obs:
            os.environ[OBS_DISABLE_ENV] = "1"
        else:
            os.environ.pop(OBS_DISABLE_ENV, None)
        try:
            s = stpu.Sentinel(stpu.load_config(
                max_resources=64, max_origins=32, max_flow_rules=32,
                max_degrade_rules=16, max_authority_rules=16,
                host_fast_path=False))
        finally:
            if prev is None:
                os.environ.pop(OBS_DISABLE_ENV, None)
            else:
                os.environ[OBS_DISABLE_ENV] = prev
        s.load_flow_rules([
            stpu.FlowRule(resource="api", count=1e9),
            stpu.FlowRule(resource="api", count=1e9, limit_app="app-a"),
        ])
        return s

    B, STEPS, REPEATS = 8192, 6, 8
    rng = np.random.default_rng(11)
    resources = ["api"] * B
    origins = ["app-a" if x else "" for x in (rng.random(B) < 0.1)]
    pair = [("on", build(False)), ("off", build(True))]
    assert pair[0][1].telemetry.enabled
    assert not pair[1][1].obs.enabled
    # 5 Hz — HARSHER than the production 1 Hz cadence, so the band holds
    # margin: the tick's brief engine-lock hold and the async readback
    # both overlap the timed dispatch loop several times per region
    pair[0][1].telemetry.start(interval_sec=0.2)
    best: dict = {}
    for _key, s in pair:                    # warm compiles + caches
        for _ in range(2):
            s.entry_batch_nowait(resources, origins=origins).result()
    for rep in range(REPEATS):
        for key, s in (pair if rep % 2 == 0 else pair[::-1]):
            t0 = _time.perf_counter()
            for _ in range(STEPS):
                s.entry_batch_nowait(resources, origins=origins).result()
            dt = (_time.perf_counter() - t0) / STEPS
            best[key] = min(best.get(key, dt), dt)
    out["telemetry_ticks"] = pair[0][1].telemetry.snapshot()["ticks"]
    for _key, s in pair:
        s.close()
    out["telemetry_on_s_per_step"] = best["on"]
    out["telemetry_off_s_per_step"] = best["off"]
    out["telemetry_overhead_ratio"] = best["on"] / best["off"]
    return out


# Gate (l) — the tiered-state gate (r15). Two halves:
#   serving:  zipf_hot over a 16M-rank universe (no materialized key
#             list — workloads._zipf_ranks) through the real
#             AdaptiveBatcher replay with the tiering ticker running
#             against a deliberately small SENTINEL_HOT_ROWS target.
#             Gated: hit rate ≥ TIER_HIT_RATE_MIN (hot_hit/(hot_hit+
#             cold_miss); FIRST-SIGHT keys tick neither — a brand-new
#             key never had state to miss, so the rate measures
#             hot-tier sizing, not keyspace size), nonzero promoted
#             AND demoted (the migration machinery actually ran), and
#             a recorded migration-latency histogram.
#   parity:   seeded churn traffic with live flow rules and a mid-run
#             rule reload through a 24-row hot tier vs a 4096-row
#             all-resident engine — verdict triples (allow, reason,
#             wait_ms) must be bit-identical, and the probe must
#             actually block somewhere (a parity of all-PASS proves
#             nothing about restored window state).
# The serving half pins SENTINEL_TPU_NATIVE=0: proactive (sketch-
# driven) demotion needs Registry.evict_name, which the native C++
# table does not expose this round — under the native registry only
# LRU-overflow demotion applies (documented in OPERATIONS.md).
# CI_GATE_TIER=0 skips the whole gate.
TIER_ENV_FLAG = "CI_GATE_TIER"
TIER_HIT_RATE_MIN = 0.95


def measure_tiering() -> dict:
    import numpy as np

    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sentinel_tpu as stpu
    from sentinel_tpu.core.clock import ManualClock

    from benchmarks import serving_bench

    out: dict = {}

    # ---- serving half: 16M-key Zipf through the full front end -------
    overrides = {"SENTINEL_TPU_NATIVE": "0", "SENTINEL_HOT_ROWS": "512",
                 "SENTINEL_TIER_TICK_MS": "100"}
    prev = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        m = serving_bench.run_workload(
            "zipf_hot", seed=15, duration_ms=800.0, rate_rps=2500.0,
            wl_kwargs={"universe": 16_000_000})
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    t = m.get("tiering") or {}
    hits, misses = t.get("hot_hit", 0), t.get("cold_miss", 0)
    out["hit_rate"] = (hits / (hits + misses)
                       if (hits + misses) else None)
    out["hot_hit"] = hits
    out["cold_miss"] = misses
    out["promoted"] = t.get("promoted", 0)
    out["demoted"] = t.get("demoted", 0)
    out["sketch_overflow"] = t.get("sketch_overflow", 0)
    out["resident"] = t.get("resident", 0)
    out["cold"] = t.get("cold", 0)
    out["ticks"] = t.get("ticks", 0)
    out["migrate_p50_ms"] = t.get("migrate_p50_ms")
    out["migrate_p99_ms"] = t.get("migrate_p99_ms")
    out["serving_completed"] = m.get("completed", 0)
    out["serving_p99_ms"] = m.get("p99_ms")

    # ---- parity half: tiered vs all-resident, bit-identical ----------
    T0 = 1_785_000_000_000
    RULED = [f"zk{i}" for i in range(8)]
    KEYS = [f"zk{i}" for i in range(48)]

    def drive(capacity: int):
        clk = ManualClock(start_ms=T0)
        sph = stpu.Sentinel(stpu.load_config(
            max_resources=capacity, max_flow_rules=16,
            max_degrade_rules=16, max_authority_rules=16,
            host_fast_path=False), clock=clk)
        sph.load_flow_rules([stpu.FlowRule(resource=r, count=3.0)
                             for r in RULED])
        rng = np.random.default_rng(1501)
        verdicts = []
        for step in range(40):
            if step == 20:      # mid-run reload: pins move, state carries
                sph.load_flow_rules(
                    [stpu.FlowRule(resource=r, count=3.0)
                     for r in RULED[:4]]
                    + [stpu.FlowRule(resource=f"zk{i}", count=2.0)
                       for i in range(8, 12)])
            names = list(rng.choice(KEYS, size=12, replace=False))
            prio = rng.random(12) < 0.25
            v = sph.entry_batch(names, acquire=[1] * 12,
                                prioritized=list(prio))
            verdicts.append((np.asarray(v.allow).copy(),
                             np.asarray(v.reason).copy(),
                             np.asarray(v.wait_ms).copy()))
            clk.advance_ms(25)
        snap = sph.tiering.snapshot()
        sph.close()
        return verdicts, snap

    small_v, small_snap = drive(24)
    big_v, big_snap = drive(4096)
    out["parity"] = all(
        np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
        and np.array_equal(a[2], b[2])
        for a, b in zip(small_v, big_v))
    out["parity_blocked"] = int(sum(
        int((~a).sum()) for a, _r, _w in small_v))
    out["parity_promoted"] = small_snap.get("promoted", 0)
    out["parity_demoted"] = small_snap.get("demoted", 0)
    out["parity_big_demoted"] = big_snap.get("demoted", 0)
    return out


# Gate (m) — the single-dispatch gate (r16). Three halves:
#   mechanism: a ManualClock engine with BOTH cadence carries armed and
#             steady fused (decide+exit) traffic — pipeline.dispatches
#             must rise by exactly ONE per batch (the sketch observe,
#             the telemetry tick and the sketch decay all ride the one
#             jitted program, no standalone observe/tick dispatches),
#             split_route.single_dispatch must attribute every batch,
#             and each service's tick count must equal a host-side
#             replay of its cadence (once per due slot, never per
#             batch, no skipped slots).
#   parity:   seeded churn traffic (tiered 24-row engine, mid-run rule
#             reload, ~25% prioritized) with SENTINEL_SINGLE_DISPATCH=1
#             vs =0 — verdict triples AND the final count-min table
#             must be bit-identical, the probe must block somewhere
#             (an all-PASS parity is vacuous), and the route counter
#             must prove the two runs really took different routes.
#   overhead: steady fused step time with the carries ARMED at 5 Hz vs
#             disarmed, interleaved min-of-N, ratio ≤ OBS_OVERHEAD_MAX
#             — the lax.cond epilogue may not leak cost into batches
#             where no tick is due.
# CI_GATE_SINGLE_DISPATCH=0 skips the whole gate.
SINGLE_DISPATCH_ENV_FLAG = "CI_GATE_SINGLE_DISPATCH"


def measure_single_dispatch() -> dict:
    import time as _time

    import numpy as np

    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sentinel_tpu as stpu
    from sentinel_tpu.core.clock import ManualClock
    from sentinel_tpu.obs import counters as obs_keys

    T0 = 1_785_000_000_000
    out: dict = {}

    def build(clock=None, **env):
        prev = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            return stpu.Sentinel(stpu.load_config(
                max_resources=64, max_flow_rules=16, max_degrade_rules=16,
                max_authority_rules=16, minute_enabled=True,
                host_fast_path=False), clock=clock)
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def fused_cols(s, rows):
        n = rows.shape[0]
        pad_a = s.spec.alt_rows
        return (rows, np.zeros(n, np.int32), np.full(n, pad_a, np.int32),
                np.zeros(n, np.int32), np.full(n, pad_a, np.int32),
                np.ones(n, np.int32), np.ones(n, np.bool_),
                np.zeros(n, np.bool_))

    # ---- mechanism half: armed carries, dispatches/batch == 1 --------
    clk = ManualClock(start_ms=T0)
    sph = build(clk, SENTINEL_SINGLE_DISPATCH="1")
    try:
        rows_all = sph.intern_resources(["sd-a", "sd-b", "sd-c"])
        t_arm = int(clk.now_ms())
        sph.telemetry.arm_carry(400)
        sph.tiering.arm_carry(150)
        base = sph.obs.counters.get(obs_keys.PIPE_DISPATCH)
        route0 = sph.obs.counters.get(obs_keys.ROUTE_SINGLE_DISPATCH)
        tel0 = sph.telemetry.snapshot()["ticks"]
        tier0 = sph.tiering.snapshot()["ticks"]
        rng = np.random.default_rng(1603)
        times, prev_rows = [], None
        for _ in range(30):
            rows = np.asarray(rng.choice(rows_all, size=4), np.int32)
            times.append(int(clk.now_ms()))
            sph.decide_and_exit_raw_nowait(
                *fused_cols(sph, rows),
                exit_rows=prev_rows if prev_rows is not None else rows,
                exit_valid=(np.ones(4, np.bool_)
                            if prev_rows is not None
                            else np.zeros(4, np.bool_))).result()
            prev_rows = rows
            sph.telemetry.drain()       # the CadenceScheduler's job
            sph.tiering.drain()
            clk.advance_ms(50)

        def claims(interval):
            last, n = t_arm, 0
            for t in times:
                if t - last >= interval:
                    last, n = t, n + 1
            return n

        disp = sph.obs.counters.get(obs_keys.PIPE_DISPATCH) - base
        out["mech_batches"] = len(times)
        out["dispatches_per_batch"] = disp / len(times)
        out["route_single_dispatch"] = (
            sph.obs.counters.get(obs_keys.ROUTE_SINGLE_DISPATCH) - route0)
        out["tel_ticks"] = sph.telemetry.snapshot()["ticks"] - tel0
        out["tel_ticks_expected"] = claims(400)
        out["tier_ticks"] = sph.tiering.snapshot()["ticks"] - tier0
        out["tier_ticks_expected"] = claims(150)
        out["tel_drops"] = sph.telemetry.snapshot()["drops"]
    finally:
        sph.close()

    # ---- parity half: fused observe+epilogue vs legacy, bitwise ------
    RULED = [f"sd{i}" for i in range(8)]
    SKEYS = [f"sd{i}" for i in range(48)]

    def churn(sd_env: str):
        # staging stays ON: slot reuse is settlement-tied since round 17
        # (ROADMAP issue 5 fixed), so the bit-parity probe now also
        # exercises the ring under tiering churn
        overrides = {"SENTINEL_TPU_NATIVE": "0",
                     "SENTINEL_SINGLE_DISPATCH": sd_env}
        prev = {k: os.environ.get(k) for k in overrides}
        os.environ.update(overrides)
        try:
            cclk = ManualClock(start_ms=T0)
            s = stpu.Sentinel(stpu.load_config(
                max_resources=24, max_flow_rules=16, max_degrade_rules=16,
                max_authority_rules=16, host_fast_path=False), clock=cclk)
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        try:
            s.load_flow_rules([stpu.FlowRule(resource=r, count=3.0)
                               for r in RULED])
            rng = np.random.default_rng(1604)
            verdicts = []
            for step in range(32):
                if step == 16:  # mid-run reload: pins move, state carries
                    s.load_flow_rules(
                        [stpu.FlowRule(resource=r, count=3.0)
                         for r in RULED[:4]]
                        + [stpu.FlowRule(resource=f"sd{i}", count=2.0)
                           for i in range(8, 12)])
                names = list(rng.choice(SKEYS, size=12, replace=False))
                prio = list(rng.random(12) < 0.25)
                v = s.entry_batch(names, acquire=[1] * 12,
                                  prioritized=prio)
                verdicts.append((np.asarray(v.allow).copy(),
                                 np.asarray(v.reason).copy(),
                                 np.asarray(v.wait_ms).copy()))
                cclk.advance_ms(25)
            sketch = np.asarray(s.tiering._sketch).copy()
            route = s.obs.counters.get(obs_keys.ROUTE_SINGLE_DISPATCH)
            return verdicts, sketch, route
        finally:
            s.close()

    on_v, on_sk, on_route = churn("1")
    off_v, off_sk, off_route = churn("0")
    out["parity"] = all(
        np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
        and np.array_equal(a[2], b[2])
        for a, b in zip(on_v, off_v))
    out["sketch_parity"] = bool(np.array_equal(on_sk, off_sk))
    out["parity_blocked"] = int(sum(
        int((~a).sum()) for a, _r, _w in on_v))
    out["parity_route_on"] = on_route
    out["parity_route_off"] = off_route

    # ---- overhead half: armed epilogue vs disarmed, no-tick-due ------
    # Both engines run on ManualClocks that NEVER advance inside a
    # timed region, so no timed batch has a tick due — the gated
    # property is precisely that the lax.cond epilogue costs nothing
    # on those batches. Between regions the armed clock jumps past the
    # cadence and one UNTIMED dispatch fires the real epilogue program,
    # so the armed engine keeps the production steady state (carry
    # bookkeeping warm, epilogue executable resident) rather than an
    # idealized never-armed one.
    B, STEPS, REPEATS = 4096, 6, 8
    pair = []
    for key, armed in (("on", True), ("off", False)):
        sclk = ManualClock(start_ms=T0)
        s = build(sclk, SENTINEL_SINGLE_DISPATCH="1")
        s.load_flow_rules([stpu.FlowRule(resource="sd-api", count=1e9)])
        rows_all = s.intern_resources([f"sd-r{i}" for i in range(8)])
        rng = np.random.default_rng(1605)
        cols = fused_cols(
            s, np.asarray(rng.choice(rows_all, size=B), np.int32))
        kw = dict(exit_rows=cols[0], exit_valid=np.zeros(B, np.bool_))
        if armed:
            s.telemetry.arm_carry(200)
            s.tiering.arm_carry(200)
        for _ in range(2):                  # warm the plain fused program
            s.decide_and_exit_raw_nowait(*cols, **kw).result()
        if armed:                           # warm the epilogue variant too
            sclk.advance_ms(250)
            s.decide_and_exit_raw_nowait(*cols, **kw).result()
            s.telemetry.drain()
            s.tiering.drain()
        pair.append((key, s, sclk, cols, kw))
    best: dict = {}
    for rep in range(REPEATS):
        for key, s, sclk, cols, kw in (pair if rep % 2 == 0
                                       else pair[::-1]):
            t0 = _time.perf_counter()
            for _ in range(STEPS):
                s.decide_and_exit_raw_nowait(*cols, **kw).result()
            dt = (_time.perf_counter() - t0) / STEPS
            best[key] = min(best.get(key, dt), dt)
            sclk.advance_ms(250)            # untimed: epilogue fires here
            s.decide_and_exit_raw_nowait(*cols, **kw).result()
            s.telemetry.drain()
            s.tiering.drain()
    for _key, s, _clk, _cols, _kw in pair:
        s.close()
    out["sd_epilogue_on_s_per_step"] = best["on"]
    out["sd_epilogue_off_s_per_step"] = best["off"]
    out["sd_overhead_ratio"] = best["on"] / best["off"]
    return out


# Gate (n) — the overload-controller gate (r17): the closed loop from
# device telemetry to the frontend admission valve must actually hold
# service through a composite overload episode. The probe replays the
# ``overload_episode`` workload (steady tenant + flash crowd + bursty
# slow consumer, benchmarks can't fake this: the arrival schedule is
# 2-3× the CPU backend's service rate at batch_max=8) four ways:
#   controlled: ControlLoop attached (100 ms cadence, 300 ms cooldown)
#             with a bounded queue — the steady TENANT's p95 (the
#             by_prefix breakdown, not the blended number the abusive
#             streams pollute; p95 because the extreme tail belongs to
#             the backend's own 1 Hz cadence programs, measured
#             identical in an unloaded run — see measure_control) must
#             sit inside the same STEADY_P99_BAND_MS gate (f) pins for
#             healthy serving, and goodput (completed within deadline)
#             must reach CONTROL_MIN_RATIO of the best STATIC config
#             below — self-driving protection may not cost more than
#             that vs the best hand-tuned fixed setting.
#   static grid: the same episode through three fixed configs (deep
#             queue, shallow queue, bigger batches) with NO controller
#             — the honest competitors a careful operator could have
#             picked in advance.
#   off-probe: the deep-queue static run doubles as the control: with
#             nobody shedding, queueing delay must push the steady
#             tenant's p95 OUTSIDE the band — if it doesn't, the
#             episode never overloaded the backend and the controlled
#             numbers above are vacuous.
# Mechanism probes ride along: the controller must APPLY at least one
# action (an idle controller holding the band proves nothing), the
# admission valve must actually drop requests (control.admission_dropped
# > 0), and EVERY applied action must land a pinned ``controller_action``
# flight record in the <app>-trace log — interventions are evidence,
# not just counters (the force=True trigger path bypasses the per-kind
# rate limiter precisely so no action goes unpinned).
# Round 20 adds the deterministic tail probe (measure_control_tail):
# a ManualClock slow-consumer episode whose per-tick mean sits under
# SENTINEL_CONTROL_DEGRADE_RT_MS while its interval p99 sits over it —
# the tail-aware degrade path must open the victim's breaker, the
# mean fallback (SENTINEL_RESOURCE_HIST_DISABLE=1) must NOT, and a
# histograms-on/off parity leg pins verdicts + dispatch count equal.
# CI_GATE_CONTROL=0 skips the whole gate.
CONTROL_ENV_FLAG = "CI_GATE_CONTROL"
CONTROL_MIN_RATIO = 0.5


def measure_control() -> dict:
    import shutil
    import tempfile

    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from benchmarks import serving_bench
    from sentinel_tpu.control import PolicyConfig
    from sentinel_tpu.obs import flight as flight_mod

    # The backend is made slow on purpose (batch_max=16 at an 8 ms
    # coalescing budget ≈ 1-2k req/s service) so a modest arrival rate
    # overloads IT rather than the replay interpreter — past ~4k req
    # total the asyncio loop itself becomes the bottleneck and every
    # config collapses identically, proving nothing about the
    # controller. burst_mult is tamed from the workload default so the
    # slow-consumer share doesn't push the NON-spike average over
    # service: outside the spike window ([0.3, 0.6] of the episode)
    # the offered rate sits comfortably under service; inside it the
    # 8× flash share pushes well over.
    EP = dict(seed=17, duration_ms=2000.0, rate_rps=1000.0,
              batch_max=16, budget_ms=8, deadline_ms=25,
              wl_kwargs={"burst_mult": 4.0})

    def goodput(m: dict) -> int:
        return m["completed"] - m["deadline_miss"]

    def steady_of(m: dict) -> dict:
        return (m.get("by_prefix") or {}).get("steady") or {}

    # Warmup: a long, LIGHT episode at both batch geometries so every
    # padded dispatch width AND the 1 Hz cadence-carry program variants
    # compile before anything is timed — a first-occurrence XLA compile
    # mid-replay stalls serving for hundreds of ms and would be charged
    # to whichever config drew it. The 1.6 s duration is what lets the
    # telemetry/tiering carries actually fire during warmup.
    for bm in (16, 32):
        serving_bench.run_workload(
            "overload_episode", seed=3, duration_ms=1600.0,
            rate_rps=400.0, batch_max=bm, budget_ms=8,
            wl_kwargs={"burst_mult": 4.0})

    # The scored statistic is the steady tenant's p95, not p99: the
    # residual extreme tail (~1% at ~0.3-0.5 s) is the backend's own
    # 1 Hz cadence programs executing on the CPU "device", which
    # serialize with serving dispatches — it shows up identically in
    # an UNLOADED steady run and no admission policy can shed around
    # it. p95 isolates the queueing delay the controller actually
    # owns; the off-probe violation below clears the band by >10× so
    # nothing rides on the choice.
    out: dict = {}

    # ---- static grid: the hand-tuned competitors, no controller ------
    grid = {
        "deep_queue": dict(queue_max=1024),
        "shallow_queue": dict(queue_max=64),
        "big_batch": dict(queue_max=1024, batch_max=32),
    }
    best_static, static_out = None, {}
    for gname, cfg in grid.items():
        m = serving_bench.run_workload(
            "overload_episode", **{**EP, **cfg})
        g = goodput(m)
        st = steady_of(m)
        static_out[gname] = {
            "goodput": g, "steady_p95_ms": st.get("p95_ms"),
            "steady_p99_ms": st.get("p99_ms"),
            "shed": m["shed"], "deadline_miss": m["deadline_miss"]}
        if best_static is None or g > best_static:
            best_static = g
        if gname == "deep_queue":   # doubles as the controller-off probe
            out["off_steady_p95_ms"] = st.get("p95_ms")
    out["static"] = static_out
    out["best_static_goodput"] = best_static

    # ---- controlled episode, flight recorder attached ----------------
    # Policy tuned to the probe's timescale: 100 ms cadence, 300 ms
    # cooldown; the p99 trip wire sits above the request deadline so
    # the QUEUE signal (0.75 × queue_max) does the fast work and the
    # shed floor is 0.3 — the valve may never throttle below 30%, which
    # bounds the goodput a misestimated p99 can throw away. The
    # overload retune HALVES the coalescing budget (shorter batches →
    # lower admitted-request latency) instead of the big-batch default,
    # and recovery is snappier so the post-spike tail contributes
    # goodput. Best-of-2: an open-loop real-time replay on a shared CI
    # box draws scheduler noise the controller cannot shed around, so
    # the run with the better steady p95 is scored (same min-of-N
    # discipline as every timing probe in this file).
    ctl, pinned = None, []
    for _attempt in range(2):
        tmp = tempfile.mkdtemp(prefix="sentinel-control-gate-")
        try:
            m = serving_bench.run_workload(
                "overload_episode", control=True, queue_max=48,
                control_kwargs={
                    "interval_ms": 100,
                    "config": PolicyConfig(
                        p99_hi_ms=35.0, p99_lo_ms=15.0, min_admit=0.3,
                        cooldown_ms=300, retune_budget_ms=4,
                        retune_cap_frac=1.0, shed_recover=0.25)},
                trace_dir=tmp, **EP)
            pins = flight_mod.load_pinned(tmp, "overload_episode")
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        if (ctl is None or (steady_of(m).get("p95_ms") or 1e9)
                < (steady_of(ctl).get("p95_ms") or 1e9)):
            ctl, pinned = m, pins
    snap = ctl.get("control") or {}
    steady = steady_of(ctl)
    out["steady_p95_ms"] = steady.get("p95_ms")
    out["steady_p99_ms"] = steady.get("p99_ms")
    out["steady_completed"] = steady.get("completed", 0)
    out["goodput"] = goodput(ctl)
    out["actions_applied"] = snap.get("total_actions", 0)
    out["action_kinds"] = sorted(
        {a.get("kind") for a in snap.get("actions", ())})
    out["admission_dropped"] = ctl.get("control_dropped", 0)
    out["actions_pinned"] = sum(
        1 for rec in pinned if rec.get("kind") == "controller_action")
    out["min_admit_frac"] = min(
        [a["action"].get("frac", 1.0)
         for a in snap.get("actions", ())
         if a.get("kind") == "shed_rate"] or [1.0])
    out["goodput_ratio"] = (out["goodput"] / best_static
                            if best_static else None)
    return out


def measure_control_tail() -> dict:
    """Gate (n) round-20 extension: the slow-consumer episode the MEAN
    degrade signal provably cannot catch. Deterministic ManualClock
    probe (no replay, no wall clock): a victim resource serves a
    bimodal mix — 40 × 1 ms + 2 × 200 ms per controller tick, mean
    ≈ 10 ms, interval p99 ≈ 230 ms — against a 100 ms degrade bound,
    next to an all-fast steady resource. Four legs:

      tail:   histograms ON — the tail-aware controller must force-open
              the VICTIM's breaker (and only the victim's) while every
              per-tick mean stays under the bound;
      mean:   ``SENTINEL_RESOURCE_HIST_DISABLE=1`` — the same episode
              through the pre-r20 mean fallback must decide NOTHING
              (if it trips, the scenario doesn't discriminate and the
              tail leg proves nothing);
      parity: a controller-free mixed pass/block stream, histograms on
              vs off — verdict-for-verdict identical AND the SAME
              ``pipeline.dispatches`` count (the table may not cost a
              dispatch: ``dispatches_per_batch`` is pinned unchanged
              from round 16 by gate (m); this is the same invariant
              from the feature side).
    """
    sys.path.insert(0, str(HERE.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import sentinel_tpu as stpu
    from sentinel_tpu.control import ControlLoop
    from sentinel_tpu.core.clock import ManualClock
    from sentinel_tpu.core.errors import BlockException
    from sentinel_tpu.obs import counters as obs_keys
    from sentinel_tpu.tune.knobs import env_overrides

    BOUND_MS = 100.0

    def _cfg():
        return stpu.load_config(
            max_resources=64, max_flow_rules=16, max_degrade_rules=16,
            max_authority_rules=16, host_fast_path=False)

    def _timed(s, name, rt_ms):
        e = s.entry(name)
        if rt_ms:
            s.clock.advance_ms(rt_ms)
        e.exit()

    def _episode() -> dict:
        """One slow-consumer episode under the current env; returns the
        per-leg evidence."""
        s = stpu.Sentinel(_cfg(),
                          clock=ManualClock(start_ms=1_785_000_000_000))
        try:
            s.load_degrade_rules([
                stpu.DegradeRule(resource=r,
                                 grade=stpu.GRADE_EXCEPTION_COUNT,
                                 count=10_000, time_window=5)
                for r in ("victim", "steady")])
            ctl = ControlLoop(s, interval_ms=50)
            mean_max, p99_min = 0.0, float("inf")
            for _ in range(ctl.policy.cfg.degrade_bad_ticks):
                for _i in range(40):
                    _timed(s, "victim", 1)
                    _timed(s, "steady", 1)
                for _i in range(2):
                    _timed(s, "victim", 200)
                s.telemetry.poll()
                hot = {h["resource"]: h
                       for h in s.telemetry.hot_entries()}
                v = hot.get("victim", {})
                mean_max = max(mean_max, float(v.get("rt_ms", 0.0)))
                p99_min = min(p99_min,
                              float(v.get("rt_p99_ms", float("inf"))))
                ctl.tick()
                ctl.drain()
            deg = ctl.policy.snapshot().get("degrade", {})
            victim_open = deg.get("victim") == "open"
            steady_open = "steady" in deg
            victim_blocked = False
            try:
                s.entry("victim")
            except stpu.DegradeException:
                victim_blocked = True
            steady_serves = True
            try:
                with s.entry("steady"):
                    pass
            except Exception:
                steady_serves = False
            return {
                "victim_open": victim_open and victim_blocked,
                "steady_open": steady_open or not steady_serves,
                "victim_mean_ms_max": mean_max,
                "victim_p99_ms_min": (None if p99_min == float("inf")
                                      else p99_min),
                "tail_signal_ticks":
                    s.obs.counters.get(obs_keys.CONTROL_TAIL_SIGNAL),
            }
        finally:
            s.close()

    def _verdicts() -> tuple:
        """Controller-free mixed stream against a tight flow rule;
        returns (verdict bits, dispatch count) for the parity leg."""
        s = stpu.Sentinel(_cfg(),
                          clock=ManualClock(start_ms=1_785_000_000_000))
        try:
            s.load_flow_rules([stpu.FlowRule(resource="lim", count=3)])
            bits = []
            for i in range(150):
                name = "lim" if i % 3 else "free"
                try:
                    e = s.entry(name)
                    s.clock.advance_ms(1 + (i % 7))
                    e.exit()
                    bits.append(True)
                except BlockException:
                    bits.append(False)
            return bits, int(s.obs.counters.get(obs_keys.PIPE_DISPATCH))
        finally:
            s.close()

    out: dict = {}
    with env_overrides({"SENTINEL_CONTROL_DEGRADE_RT_MS": BOUND_MS}):
        tail = _episode()
        with env_overrides({"SENTINEL_RESOURCE_HIST_DISABLE": True}):
            mean = _episode()
    out["tail_degrade_opened"] = tail["victim_open"]
    out["tail_steady_open"] = tail["steady_open"]
    out["victim_mean_ms_max"] = tail["victim_mean_ms_max"]
    out["victim_p99_ms_min"] = tail["victim_p99_ms_min"]
    out["tail_signal_ticks"] = tail["tail_signal_ticks"]
    out["mean_under_bound"] = tail["victim_mean_ms_max"] < BOUND_MS
    out["mean_fallback_opened"] = mean["victim_open"]
    v_on, d_on = _verdicts()
    with env_overrides({"SENTINEL_RESOURCE_HIST_DISABLE": True}):
        v_off, d_off = _verdicts()
    out["verdict_parity"] = bool(np.array_equal(v_on, v_off))
    out["dispatches_on"] = d_on
    out["dispatches_off"] = d_off
    return out


def main() -> int:
    best = max(measure_once() for _ in range(3))
    cal = calibrate()
    prep = measure_host_prep()
    prio = measure_prio_cliff()
    routing_err = check_prio_split_routing()
    obs = measure_obs_overhead()
    disp = measure_dispatch_pipeline()
    serving = measure_serving()
    trace = measure_trace_capture()
    meshed = (measure_meshed()
              if os.environ.get(MESHED_ENV_FLAG, "1") != "0" else None)
    sortfree = (measure_sortfree()
                if os.environ.get(SORTFREE_ENV_FLAG, "1") != "0" else None)
    tune = (measure_tune()
            if os.environ.get(TUNE_ENV_FLAG, "1") != "0" else None)
    telemetry = (measure_telemetry()
                 if os.environ.get(TELEMETRY_ENV_FLAG, "1") != "0"
                 else None)
    tiering = (measure_tiering()
               if os.environ.get(TIER_ENV_FLAG, "1") != "0" else None)
    single = (measure_single_dispatch()
              if os.environ.get(SINGLE_DISPATCH_ENV_FLAG, "1") != "0"
              else None)
    control = (measure_control()
               if os.environ.get(CONTROL_ENV_FLAG, "1") != "0" else None)
    if control is not None:
        # round 20: the deterministic slow-consumer tail probe rides the
        # same gate flag — binary mechanism legs, nothing re-baselined
        control["tail"] = {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in measure_control_tail().items()}
    ratios = {k.replace("_s_per_step", "_ratio"): v / cal
              for k, v in prep.items()}
    if "--update" in sys.argv:
        BASELINE_FILE.write_text(json.dumps(
            {"cpu_decisions_per_sec_floor": best / 2,
             "measured_at_update": best,
             "machine": fingerprint(),
             "host_prep_ratios": ratios,
             # informational: the prio band and the obs-overhead band are
             # fixed (PRIO_RATIO_BAND / OBS_OVERHEAD_MAX), not
             # re-baselined per machine
             "prio_cliff": {k: round(v, 4) for k, v in prio.items()},
             "obs_overhead": {k: round(v, 4) for k, v in obs.items()},
             "dispatch_pipeline": {
                 k: (round(v, 6) if isinstance(v, float) else v)
                 for k, v in disp.items()},
             # informational: the serving SLO band is fixed
             # (STEADY_P99_BAND_MS / FLASH_MISS_COLLAPSE), not
             # re-baselined per machine
             "serving": {k: (round(v, 4) if isinstance(v, float) else v)
                         for k, v in serving.items()},
             # informational: gate (g) is binary (mechanism), nothing
             # machine-relative to pin
             "trace_capture": trace,
             # informational: gate (h) is parity (binary) plus the fixed
             # WEAK_SCALING_FLAT_MAX band, not re-baselined per machine
             "meshed_serving": meshed,
             # informational: gate (i) is parity (binary) plus the fixed
             # SORTFREE_MIN_RATIO band, not re-baselined per machine
             "sortfree": ({k: (round(v, 4) if isinstance(v, float) else v)
                           for k, v in sortfree.items()}
                          if sortfree is not None else None),
             # informational: gate (j) is convergence + parity (binary)
             # plus the fixed TUNE_MIN_RATIO band, not re-baselined
             "tune": ({k: (round(v, 4) if isinstance(v, float) else v)
                       for k, v in tune.items()}
                      if tune is not None else None),
             # informational: gate (k) is binary (surface) plus the
             # fixed OBS_OVERHEAD_MAX band, not re-baselined per machine
             "telemetry": ({k: (round(v, 6) if isinstance(v, float)
                                else v)
                            for k, v in telemetry.items()}
                           if telemetry is not None else None),
             # informational: gate (l) is parity (binary) plus the fixed
             # TIER_HIT_RATE_MIN band, not re-baselined per machine
             "tiering": ({k: (round(v, 4) if isinstance(v, float)
                              else v)
                          for k, v in tiering.items()}
                         if tiering is not None else None),
             # informational: gate (m) is parity + mechanism (binary)
             # plus the fixed OBS_OVERHEAD_MAX band, not re-baselined
             # per machine
             "single_dispatch": ({k: (round(v, 6) if isinstance(v, float)
                                      else v)
                                  for k, v in single.items()}
                                 if single is not None else None),
             # informational: gate (n) is band + mechanism (binary) plus
             # the fixed STEADY_P99_BAND_MS / CONTROL_MIN_RATIO bands,
             # not re-baselined per machine
             "control": ({k: (round(v, 4) if isinstance(v, float)
                              else v)
                          for k, v in control.items()}
                         if control is not None else None),
             "calibration_s": cal}, indent=1))
        print(f"baseline updated: floor={best / 2:.0f} (measured {best:.0f}) "
              f"on {fingerprint()}; host-prep ratios "
              f"{ {k: round(v, 4) for k, v in ratios.items()} }")
        return 0
    baseline = json.loads(BASELINE_FILE.read_text())
    same_machine = baseline.get("machine") == fingerprint()
    floor = (baseline["cpu_decisions_per_sec_floor"] if same_machine
             else SANITY_FLOOR_DECISIONS_PER_SEC)
    out = {
        "measured": best, "floor": floor,
        "mode": "baseline-machine" if same_machine else "sanity-floor",
        "ratio_vs_floor": round(best / floor, 2),
        "calibration_s": round(cal, 4),
        "host_prep": {k: round(v, 4) for k, v in prep.items()},
        "host_prep_ratios": {k: round(v, 4) for k, v in ratios.items()},
        "prio_cliff": {k: round(v, 4) for k, v in prio.items()},
        "prio_split_routing": "ok" if routing_err is None else "DEMOTED",
        "obs_overhead": {k: round(v, 4) for k, v in obs.items()},
        "dispatch_pipeline": {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in disp.items()},
        "serving": {k: (round(v, 4) if isinstance(v, float) else v)
                    for k, v in serving.items()},
        "trace_capture": trace,
        "meshed_serving": meshed if meshed is not None else "skipped",
        "sortfree": ({k: (round(v, 4) if isinstance(v, float) else v)
                      for k, v in sortfree.items()}
                     if sortfree is not None else "skipped"),
        "tune": ({k: (round(v, 4) if isinstance(v, float) else v)
                  for k, v in tune.items()}
                 if tune is not None else "skipped"),
        "telemetry": ({k: (round(v, 6) if isinstance(v, float) else v)
                       for k, v in telemetry.items()}
                      if telemetry is not None else "skipped"),
        "tiering": ({k: (round(v, 4) if isinstance(v, float) else v)
                     for k, v in tiering.items()}
                    if tiering is not None else "skipped"),
        "single_dispatch": ({k: (round(v, 6) if isinstance(v, float)
                                 else v)
                             for k, v in single.items()}
                            if single is not None else "skipped"),
        "control": ({k: (round(v, 4) if isinstance(v, float) else v)
                     for k, v in control.items()}
                    if control is not None else "skipped"),
    }
    print(json.dumps(out))
    rc = 0
    if meshed is not None:
        if "error" in meshed:
            print(f"MESHED-GATE REGRESSION: the --meshed probe subprocess "
                  f"failed to run: {meshed['error']}", file=sys.stderr)
            rc = 1
        else:
            for probe, ok in meshed["parity"].items():
                if not ok:
                    print(f"MESHED-PARITY REGRESSION ({probe}): verdicts "
                          f"through the meshed serving path diverged from "
                          f"the single-device engine — placement must be "
                          f"layout, not math; the row-sharded hot path is "
                          f"computing something different", file=sys.stderr)
                    rc = 1
            if meshed["split_fired"] == 0:
                print("MESHED-MECHANISM REGRESSION: the mixed probe batch "
                      "never took the split dispatch on the meshed engine "
                      "— the parity above did not cover the prio/occupy "
                      "routing it claims to", file=sys.stderr)
                rc = 1
            if meshed["route_meshed"] == 0 or meshed["pipe_meshed"] == 0:
                print(f"MESHED-MECHANISM REGRESSION: mesh attribution "
                      f"counters dead (split_route.meshed="
                      f"{meshed['route_meshed']}, pipeline.meshed_dispatch="
                      f"{meshed['pipe_meshed']}) — the scrape can no "
                      f"longer tell meshed traffic from single-device",
                      file=sys.stderr)
                rc = 1
            carried = (meshed["occupy_carried_ref"],
                       meshed["occupy_carried_meshed"])
            if carried[0] != carried[1] or carried[0] == 0:
                print(f"MESHED-OCCUPY REGRESSION: occupy bookings carried "
                      f"across the rule reload diverged or never happened "
                      f"(ref={carried[0]}, meshed={carried[1]}) — the "
                      f"booking carry path is broken or unexercised on "
                      f"the mesh", file=sys.stderr)
                rc = 1
            flat = meshed.get("flatness_norm") or {}
            worst = max((v for k, v in flat.items() if k != "1"),
                        default=None)
            if (worst is None or worst > WEAK_SCALING_FLAT_MAX
                    or MESHED_N_DEV not in meshed.get("curve_devices", [])):
                print(f"WEAK-SCALING REGRESSION: normalized per-partition "
                      f"cost {flat} (curve over "
                      f"{meshed.get('curve_devices')}) — worst ratio "
                      f"{worst} vs max {WEAK_SCALING_FLAT_MAX}; per-step "
                      f"cost is growing super-linearly with device count "
                      f"(all-to-all blowup, per-shard recompiles, or a "
                      f"host loop over shards)", file=sys.stderr)
                rc = 1
    if sortfree is not None:
        if not sortfree["parity"]:
            print("SORTFREE-PARITY REGRESSION: verdicts through the "
                  "hash-bucketed general path diverged from the sorted "
                  "reference through the real dispatch — the claim "
                  "cascade (or its lax.cond sorted fallback) is "
                  "computing something different; SENTINEL_SORTFREE=0 "
                  "is the operator escape hatch while this is debugged",
                  file=sys.stderr)
            rc = 1
        if (sortfree["route_sortfree"] == 0
                or sortfree["route_sortfree_sorted_engine"] != 0):
            print(f"SORTFREE-MECHANISM REGRESSION: split_route.sortfree "
                  f"attribution is wrong (sortfree engine="
                  f"{sortfree['route_sortfree']}, sorted engine="
                  f"{sortfree['route_sortfree_sorted_engine']}) — either "
                  f"the default flipped or the scrape can no longer tell "
                  f"the aggregation variants apart", file=sys.stderr)
            rc = 1
        if sortfree["route_general"] == 0 or sortfree["split_fired"] == 0:
            print(f"SORTFREE-MECHANISM REGRESSION: the probe batches no "
                  f"longer exercise the routes the parity claims to pin "
                  f"(general route={sortfree['route_general']}, "
                  f"split_fired={sortfree['split_fired']})",
                  file=sys.stderr)
            rc = 1
        if sortfree["overflow_default_table"] != 0:
            print(f"SORTFREE-TABLE REGRESSION: the DEFAULT-sized claim "
                  f"table overflowed "
                  f"{sortfree['overflow_default_table']} times on the "
                  f"probe traffic — table sizing regressed; the sorted "
                  f"fallback hides the perf loss while parity stays "
                  f"green", file=sys.stderr)
            rc = 1
        carried = (sortfree["occupy_carried_sorted"],
                   sortfree["occupy_carried_sortfree"])
        if carried[0] != carried[1] or carried[0] == 0:
            print(f"SORTFREE-OCCUPY REGRESSION: occupy bookings carried "
                  f"across the rule reload diverged or never happened "
                  f"(sorted={carried[0]}, sortfree={carried[1]}) — the "
                  f"booking-fold parity is broken or unexercised",
                  file=sys.stderr)
            rc = 1
        sr = sortfree["sortfree_vs_sorted_ratio"]
        if sr < SORTFREE_MIN_RATIO:
            print(f"SORTFREE-PERF REGRESSION: sortfree/sorted general "
                  f"throughput ratio {sr:.3f} < {SORTFREE_MIN_RATIO} on "
                  f"the CPU backend — the cascade's known below-parity "
                  f"CPU cost (~0.78× at gate shapes; the accelerator "
                  f"owns the win) has DEGENERATED: look for a "
                  f"per-element host loop, lost fusion, or an "
                  f"accidental device sync in ops/sortfree.py",
                  file=sys.stderr)
            rc = 1
    if tune is not None:
        if not tune["converged"] or not tune["artifact_written"]:
            print(f"TUNE-GATE REGRESSION: the tiny CPU sweep failed to "
                  f"converge or pin its TUNED.json (converged="
                  f"{tune['converged']}, artifact_written="
                  f"{tune['artifact_written']}, trials={tune['trials']}) "
                  f"— the search/runner/artifact loop is broken",
                  file=sys.stderr)
            rc = 1
        if tune["parity_fail"] != 0 or not tune.get("pinned_bit_parity",
                                                    True):
            print(f"TUNE-PARITY REGRESSION: verdict bit-parity broke "
                  f"(tune.parity_fail={tune['parity_fail']}, pinned "
                  f"config parity={tune.get('pinned_bit_parity')}) — a "
                  f"tuned config changed a VERDICT; the tuner must only "
                  f"ever move perf knobs", file=sys.stderr)
            rc = 1
        if tune["artifact_written"] and not tune.get("artifact_loaded"):
            print("TUNE-MECHANISM REGRESSION: SENTINEL_TUNED_CONFIG "
                  "pointed at the freshly pinned artifact but the "
                  "startup path did not resolve it (provenance says "
                  "tuned=false) — the load/fingerprint plumbing is dead "
                  "and every 'tuned' run silently uses defaults",
                  file=sys.stderr)
            rc = 1
        tr = tune.get("tuned_vs_default_ratio")
        if tune["artifact_written"] and (tr is None
                                         or tr < TUNE_MIN_RATIO):
            print(f"TUNE-PERF REGRESSION: tuned/default throughput ratio "
                  f"{tr if tr is None else round(tr, 3)} < "
                  f"{TUNE_MIN_RATIO} through the serving replay — the "
                  f"pinned winner loses to the defaults it beat during "
                  f"search; the obs-sourced scoring or the artifact "
                  f"application path regressed", file=sys.stderr)
            rc = 1
    if telemetry is not None:
        if not telemetry["planted_in_topk"]:
            print(f"TELEMETRY-GATE REGRESSION: planted hot keys missing "
                  f"from /obs/topk.json (top3={telemetry['topk_top3']}) "
                  f"— the device top-K → async readback → topk command "
                  f"→ dashboard chain is broken somewhere",
                  file=sys.stderr)
            rc = 1
        elif not telemetry["planted_rank1"]:
            print(f"TELEMETRY-GATE REGRESSION: the hottest planted key "
                  f"is not ranked first (top3={telemetry['topk_top3']}) "
                  f"— the sharded top-K merge ordering regressed",
                  file=sys.stderr)
            rc = 1
        if not telemetry["planted_in_metric_log"]:
            print(f"TELEMETRY-GATE REGRESSION: planted hot keys never "
                  f"reached the <app>-metric log "
                  f"({telemetry['metric_log_resources']} resources read "
                  f"back) — the per-second persistence ride on the "
                  f"metric writer/searcher is dead", file=sys.stderr)
            rc = 1
        if telemetry["timeline_len"] == 0:
            print("TELEMETRY-GATE REGRESSION: the per-second timeline "
                  "ring surfaced zero completed seconds through the "
                  "dashboard probe — the device ring append or its "
                  "readback is dead", file=sys.stderr)
            rc = 1
        tratio = telemetry["telemetry_overhead_ratio"]
        if tratio > OBS_OVERHEAD_MAX:
            print(f"TELEMETRY-OVERHEAD REGRESSION: instrumented/"
                  f"uninstrumented step-time ratio {tratio:.4f} > "
                  f"{OBS_OVERHEAD_MAX} with the telemetry ticker ON "
                  f"(5 Hz probe cadence) — the telemetry tick is "
                  f"leaking cost into the dispatch path (lock hold too "
                  f"long, a sync readback, or per-tick recompiles)",
                  file=sys.stderr)
            rc = 1
    if tiering is not None:
        if not tiering["parity"]:
            print("TIER-PARITY REGRESSION: verdicts through the small "
                  "hot tier diverged from the all-resident engine — the "
                  "demote→promote round trip (window slices, occupy "
                  "bookings, or the settle replay for missed reloads) "
                  "changed an answer; SENTINEL_TIERING_DISABLE=1 is the "
                  "operator escape hatch while this is debugged",
                  file=sys.stderr)
            rc = 1
        if tiering["parity_blocked"] == 0:
            print("TIER-PARITY REGRESSION: the parity probe never "
                  "produced a BLOCK verdict — an all-PASS parity proves "
                  "nothing about restored window state; the probe's rule "
                  "pressure degenerated", file=sys.stderr)
            rc = 1
        if tiering["parity_promoted"] == 0 or tiering["parity_demoted"] == 0:
            print(f"TIER-MECHANISM REGRESSION: the parity probe's small "
                  f"engine migrated nothing (promoted="
                  f"{tiering['parity_promoted']}, demoted="
                  f"{tiering['parity_demoted']}) — the parity above "
                  f"never exercised the cold tier", file=sys.stderr)
            rc = 1
        hr = tiering["hit_rate"]
        if hr is None or hr < TIER_HIT_RATE_MIN:
            print(f"TIER-HIT-RATE REGRESSION: hot-tier hit rate "
                  f"{hr if hr is None else round(hr, 4)} < "
                  f"{TIER_HIT_RATE_MIN} on the 16M-key Zipf serving run "
                  f"(hot_hit={tiering['hot_hit']}, cold_miss="
                  f"{tiering['cold_miss']}) — the sketch-driven demotion "
                  f"is evicting keys the workload still needs (hash "
                  f"quality, decay cadence, or victim selection "
                  f"regressed)", file=sys.stderr)
            rc = 1
        if tiering["promoted"] == 0 or tiering["demoted"] == 0:
            print(f"TIER-MECHANISM REGRESSION: the 16M-key serving run "
                  f"migrated nothing (promoted={tiering['promoted']}, "
                  f"demoted={tiering['demoted']}, ticks="
                  f"{tiering['ticks']}) — the ticker, the hot-rows "
                  f"target, or the evict_name path is dead and the hit "
                  f"rate above is vacuous", file=sys.stderr)
            rc = 1
        if tiering["promoted"] and tiering["migrate_p50_ms"] is None:
            print("TIER-MECHANISM REGRESSION: promotions happened but "
                  "the migration-latency histogram recorded nothing — "
                  "the cold-miss slow path lost its instrumentation",
                  file=sys.stderr)
            rc = 1
    if single is not None:
        if single["dispatches_per_batch"] != 1.0:
            print(f"SINGLE-DISPATCH REGRESSION: steady-state fused "
                  f"serving cost {single['dispatches_per_batch']} device "
                  f"dispatches per batch with both tickers armed "
                  f"(batches={single['mech_batches']}) — the sketch "
                  f"observe or the tick epilogue fell back to a "
                  f"standalone program", file=sys.stderr)
            rc = 1
        if single["route_single_dispatch"] < single["mech_batches"]:
            print(f"SINGLE-DISPATCH MECHANISM REGRESSION: only "
                  f"{single['route_single_dispatch']} of "
                  f"{single['mech_batches']} fused batches earned "
                  f"split_route.single_dispatch — the scrape can no "
                  f"longer tell the fused route from the legacy "
                  f"composition", file=sys.stderr)
            rc = 1
        if (single["tel_ticks"] != single["tel_ticks_expected"]
                or single["tier_ticks"] != single["tier_ticks_expected"]
                or single["tel_ticks_expected"] == 0
                or single["tier_ticks_expected"] == 0
                or single["tel_drops"] != 0):
            print(f"SINGLE-DISPATCH CADENCE REGRESSION: carried ticks "
                  f"drifted from the host cadence replay — telemetry "
                  f"{single['tel_ticks']}/{single['tel_ticks_expected']} "
                  f"(drops {single['tel_drops']}), tiering "
                  f"{single['tier_ticks']}/{single['tier_ticks_expected']}"
                  f" — the epilogue is firing per batch, skipping due "
                  f"slots, or the probe degenerated", file=sys.stderr)
            rc = 1
        if not single["parity"] or not single["sketch_parity"]:
            print(f"SINGLE-DISPATCH PARITY REGRESSION: verdict parity="
                  f"{single['parity']}, sketch parity="
                  f"{single['sketch_parity']} between "
                  f"SENTINEL_SINGLE_DISPATCH=1 and =0 — the fused "
                  f"observe or the lax.cond epilogue changed an answer; "
                  f"SENTINEL_SINGLE_DISPATCH=0 is the operator escape "
                  f"hatch while this is debugged", file=sys.stderr)
            rc = 1
        if single["parity_blocked"] == 0:
            print("SINGLE-DISPATCH PARITY REGRESSION: the parity probe "
                  "never produced a BLOCK verdict — an all-PASS parity "
                  "proves nothing; the probe's rule pressure degenerated",
                  file=sys.stderr)
            rc = 1
        if (single["parity_route_on"] == 0
                or single["parity_route_off"] != 0):
            print(f"SINGLE-DISPATCH MECHANISM REGRESSION: route "
                  f"attribution (split_route.single_dispatch on="
                  f"{single['parity_route_on']}, off="
                  f"{single['parity_route_off']}) says the two parity "
                  f"runs did not actually take different routes",
                  file=sys.stderr)
            rc = 1
        if single["sd_overhead_ratio"] > OBS_OVERHEAD_MAX:
            print(f"SINGLE-DISPATCH OVERHEAD REGRESSION: armed-epilogue "
                  f"step time ratio "
                  f"{round(single['sd_overhead_ratio'], 4)} > "
                  f"{OBS_OVERHEAD_MAX} vs carries disarmed (5 Hz probe "
                  f"cadence) — the lax.cond epilogue is leaking cost "
                  f"into batches where no tick is due", file=sys.stderr)
            rc = 1
    if control is not None:
        c_lo, c_hi = STEADY_P99_BAND_MS
        sp95 = control["steady_p95_ms"]
        if sp95 is None or not c_lo <= sp95 <= c_hi:
            print(f"CONTROL-GATE REGRESSION: steady-tenant p95 "
                  f"{sp95 if sp95 is None else round(sp95, 2)} ms "
                  f"outside band [{c_lo}, {c_hi}] WITH the controller "
                  f"attached — the closed loop is not protecting the "
                  f"well-behaved tenant through the overload episode "
                  f"(SENTINEL_CONTROL_DISABLE=1 is the operator escape "
                  f"hatch while this is debugged)", file=sys.stderr)
            rc = 1
        off95 = control["off_steady_p95_ms"]
        if off95 is not None and off95 <= c_hi:
            print(f"CONTROL-GATE REGRESSION: the controller-OFF "
                  f"deep-queue run kept the steady tenant's p95 at "
                  f"{round(off95, 2)} ms (≤ {c_hi}) — the episode never "
                  f"overloaded the backend, so the controlled band "
                  f"above is vacuous; the probe's rate/batch pressure "
                  f"degenerated", file=sys.stderr)
            rc = 1
        gr = control["goodput_ratio"]
        if gr is None or gr < CONTROL_MIN_RATIO:
            print(f"CONTROL-GOODPUT REGRESSION: controlled goodput "
                  f"{control['goodput']} is "
                  f"{gr if gr is None else round(gr, 3)} of the best "
                  f"static config "
                  f"({control['best_static_goodput']}) < "
                  f"{CONTROL_MIN_RATIO} — self-driving protection is "
                  f"throwing away more work than the best hand-tuned "
                  f"fixed setting would", file=sys.stderr)
            rc = 1
        if (control["actions_applied"] == 0
                or control["admission_dropped"] == 0):
            print(f"CONTROL-MECHANISM REGRESSION: the controller applied "
                  f"{control['actions_applied']} actions and the "
                  f"admission valve dropped "
                  f"{control['admission_dropped']} requests over the "
                  f"overload episode — an idle controller holding the "
                  f"band proves nothing; the observe/decide/actuate "
                  f"chain is dead", file=sys.stderr)
            rc = 1
        if control["actions_pinned"] < control["actions_applied"]:
            print(f"CONTROL-EVIDENCE REGRESSION: "
                  f"{control['actions_applied']} applied actions pinned "
                  f"only {control['actions_pinned']} controller_action "
                  f"flight records — interventions must leave evidence; "
                  f"the force-pin path (flight.trigger force=True) or "
                  f"the <app>-trace persistence is dropping them",
                  file=sys.stderr)
            rc = 1
        tail = control.get("tail") or {}
        if not tail.get("mean_under_bound", False):
            print(f"CONTROL-TAIL REGRESSION: the slow-consumer probe's "
                  f"per-tick victim MEAN peaked at "
                  f"{tail.get('victim_mean_ms_max')} ms (>= the 100 ms "
                  f"bound) — the bimodal mix degenerated and the tail "
                  f"leg below discriminates nothing", file=sys.stderr)
            rc = 1
        if not tail.get("tail_degrade_opened", False) \
                or tail.get("tail_steady_open", True):
            print(f"CONTROL-TAIL REGRESSION: tail-aware degrade did not "
                  f"isolate the slow consumer (victim opened: "
                  f"{tail.get('tail_degrade_opened')}, steady touched: "
                  f"{tail.get('tail_steady_open')}; victim interval p99 "
                  f"{tail.get('victim_p99_ms_min')} ms, mean "
                  f"{tail.get('victim_mean_ms_max')} ms, tail-signal "
                  f"ticks {tail.get('tail_signal_ticks')}) — the device "
                  f"histogram → ResourceTailTracker → degrade tracker → "
                  f"force_breaker chain is broken", file=sys.stderr)
            rc = 1
        if tail.get("mean_fallback_opened", True):
            print("CONTROL-TAIL REGRESSION: the mean-RT fallback "
                  "(SENTINEL_RESOURCE_HIST_DISABLE=1) ALSO opened the "
                  "victim on the bimodal episode — the scenario no "
                  "longer separates tail from mean, so the tail leg "
                  "proves nothing; re-tune the probe's mix",
                  file=sys.stderr)
            rc = 1
        if not tail.get("verdict_parity", False) \
                or tail.get("dispatches_on") != tail.get("dispatches_off"):
            print(f"CONTROL-TAIL PARITY REGRESSION: histograms on vs "
                  f"off diverged (verdict parity "
                  f"{tail.get('verdict_parity')}, dispatches "
                  f"{tail.get('dispatches_on')} vs "
                  f"{tail.get('dispatches_off')}) — the table must be "
                  f"verdict-free and dispatch-free", file=sys.stderr)
            rc = 1
    if trace["pinned_records"] == 0 or "deadline_miss" not in trace["kinds"]:
        print(f"TRACE-CAPTURE REGRESSION: {trace['induced_misses']} induced "
              f"deadline misses pinned {trace['pinned_records']} chains "
              f"(kinds {trace['kinds']}) — the flight recorder's "
              f"deadline_miss trigger or its <app>-trace persistence is "
              f"dead", file=sys.stderr)
        rc = 1
    elif not trace["chain_spans_tiers_ok"]:
        print("TRACE-CAPTURE REGRESSION: no pinned chain spans both the "
              f"request tier ({TRACE_REQUIRED_REQUEST_SPAN}) and a batch "
              f"tier span {TRACE_REQUIRED_BATCH_SPANS} with a causal "
              "link — the trace-id threading between the front end and "
              "the dispatch path is severed", file=sys.stderr)
        rc = 1
    elif not trace["chrome_trace_ok"]:
        print("TRACE-CAPTURE REGRESSION: the pinned chain did not survive "
              "the Chrome-trace export + json.loads round trip",
              file=sys.stderr)
        rc = 1
    p99 = serving["steady_p99_ms"]
    slo_lo, slo_hi = STEADY_P99_BAND_MS
    if p99 is None or not slo_lo <= p99 <= slo_hi:
        print(f"SERVING-SLO REGRESSION: steady p99 request→verdict "
              f"{p99 if p99 is None else round(p99, 2)} ms outside band "
              f"[{slo_lo}, {slo_hi}] — "
              f"{'the measurement degenerated (requests never crossed the device)' if p99 is not None and p99 < slo_lo else 'the ingest tier is stalling (blocking call on the loop thread, lost wakeup, or deadline logic broken)'}",
              file=sys.stderr)
        rc = 1
    if (serving["steady_shed"] != 0
            or serving["steady_completed"] != serving["steady_offered"]):
        print(f"SERVING-SLO REGRESSION: steady workload shed "
              f"{serving['steady_shed']} / completed "
              f"{serving['steady_completed']} of "
              f"{serving['steady_offered']} offered — a sustainable rate "
              f"must neither shed nor lose requests", file=sys.stderr)
        rc = 1
    if (serving["flash_completed"] + serving["flash_shed"]
            != serving["flash_offered"]):
        print(f"SERVING-FLASH REGRESSION: "
              f"{serving['flash_completed']} completed + "
              f"{serving['flash_shed']} shed != "
              f"{serving['flash_offered']} offered — requests were LOST "
              f"(leaked futures) under the spike", file=sys.stderr)
        rc = 1
    if serving["flash_miss_frac"] >= FLASH_MISS_COLLAPSE:
        print(f"SERVING-FLASH REGRESSION: deadline-miss fraction "
              f"{serving['flash_miss_frac']:.3f} ≥ {FLASH_MISS_COLLAPSE} "
              f"under the flash crowd — the front end collapsed instead "
              f"of shedding/queueing through the spike", file=sys.stderr)
        rc = 1
    if serving["flash_flush_full"] == 0:
        print("SERVING-FLASH REGRESSION: the spike never cut a "
              "batch_max-full batch (flush_reason.full == 0) — the flash "
              "probe is not stressing the coalescing path",
              file=sys.stderr)
        rc = 1
    fu = disp["fused_ratio"]
    if fu > FUSED_MAX:
        print(f"FUSED-DISPATCH REGRESSION: fused/two-call step-time ratio "
              f"{fu:.4f} > {FUSED_MAX} — decide_and_exit_raw_nowait no "
              f"longer saves its dispatch (it must cost ONE dispatch, "
              f"not two)", file=sys.stderr)
        rc = 1
    po = disp["pipeline_overhead_ratio"]
    if po > PIPELINE_OVERHEAD_MAX:
        print(f"PIPELINE-OVERHEAD REGRESSION: pipelined/sync step-time "
              f"ratio {po:.4f} > {PIPELINE_OVERHEAD_MAX} — the "
              f"DispatchPipeline layer costs material time over the bare "
              f"nowait loop (lock contention, per-submit device syncs, or "
              f"settle-order bookkeeping growth)", file=sys.stderr)
        rc = 1
    if not disp["pipelined_depth_reached"]:
        print("PIPELINE-MECHANISM REGRESSION: pipeline.depth counter shows "
              "batches never overlapped in flight (depth window collapsed "
              "to 1) — the overlay timing above proved nothing",
              file=sys.stderr)
        rc = 1
    oratio = obs["obs_overhead_ratio"]
    if oratio > OBS_OVERHEAD_MAX:
        print(f"OBS-OVERHEAD REGRESSION: instrumented/uninstrumented "
              f"step-time ratio {oratio:.4f} > {OBS_OVERHEAD_MAX} — the "
              f"observability layer (obs/) is no longer ~free on the hot "
              f"path; look for per-event work, device syncs, or lock "
              f"contention added under `if obs.enabled`", file=sys.stderr)
        rc = 1
    lo, hi = PRIO_RATIO_BAND
    pr = prio["prio_vs_general_ratio"]
    if not lo <= pr <= hi:
        print(f"PRIO-CLIFF REGRESSION: prio_mixed/general ratio {pr:.3f} "
              f"outside band [{lo}, {hi}] — "
              f"{'the occupy-aware split has collapsed to sorted-general speed (demotion cliff)' if pr < lo else 'the general denominator degenerated; the gate is not measuring what it claims'}",
              file=sys.stderr)
        rc = 1
    if routing_err is not None:
        print(f"PRIO-ROUTING REGRESSION: {routing_err}", file=sys.stderr)
        rc = 1
    if best < floor:
        print(f"PERF REGRESSION: {best:.0f} decisions/s < floor {floor:.0f} "
              f"({'>2x below the rate at baseline time' if same_machine else 'below the absolute sanity floor — the fused step has degenerated'})",
              file=sys.stderr)
        rc = 1
    committed = baseline.get("host_prep_ratios")
    if committed:
        for k, limit in committed.items():
            got = ratios.get(k)
            if got is not None and got > limit * HOST_PREP_MARGIN:
                print(f"HOST-PREP REGRESSION ({k}): measured ratio "
                      f"{got:.4f} > committed {limit:.4f} × "
                      f"{HOST_PREP_MARGIN} — serving-path host prep grew "
                      f"relative to this machine's CPU calibration "
                      f"(machine-independent signal)", file=sys.stderr)
                rc = 1
    return rc


if __name__ == "__main__":
    if "--meshed" in sys.argv:
        raise SystemExit(meshed_main())
    raise SystemExit(main())
