"""CI perf-regression gate: run the headline bench at CI-sized shapes on
the CPU backend and fail on a >2× regression of decisions/sec against the
committed baseline.

Usage:
    python benchmarks/ci_gate.py            # gate (exit 1 on regression)
    python benchmarks/ci_gate.py --update   # re-baseline after intentional
                                            # perf-relevant changes

The baseline is machine-relative noise-prone, so the gate (a) uses a 2×
margin, (b) takes the best of three runs, and (c) stores a deliberately
conservative floor (half the measured rate at update time). It catches the
failure mode that matters — an accidental 10× step cost (lost fusion,
accidental sync, per-event host loop) — not 20% drift.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).resolve().parent
BASELINE_FILE = HERE / "ci_baseline.json"

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "BENCH_RESOURCES": str(1 << 14),
    "BENCH_BATCH": str(1 << 13),
    "BENCH_STEPS": "20",
    "BENCH_RULES": "256",
}


def measure_once() -> float:
    out = subprocess.run(
        [sys.executable, str(HERE.parent / "bench.py")], env=ENV,
        capture_output=True, text=True, timeout=600, check=True)
    line = out.stdout.strip().splitlines()[-1]
    return float(json.loads(line)["value"])


def main() -> int:
    best = max(measure_once() for _ in range(3))
    if "--update" in sys.argv:
        BASELINE_FILE.write_text(json.dumps(
            {"cpu_decisions_per_sec_floor": best / 2,
             "measured_at_update": best}, indent=1))
        print(f"baseline updated: floor={best / 2:.0f} (measured {best:.0f})")
        return 0
    baseline = json.loads(BASELINE_FILE.read_text())
    floor = baseline["cpu_decisions_per_sec_floor"]
    print(json.dumps({"measured": best, "floor": floor,
                      "ratio_vs_floor": round(best / floor, 2)}))
    if best < floor:
        print(f"PERF REGRESSION: {best:.0f} decisions/s < floor {floor:.0f} "
              f"(>2x below the rate at baseline time)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
