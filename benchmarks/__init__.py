"""Benchmark harnesses (importable so bench.py can embed the general-path
numbers in the driver artifact)."""
