"""Headline benchmark: pass/block decisions/sec @ 1M resources, one chip.

BASELINE.json primary metric. Measures the fused decision pipeline (the full
slot chain: authority → system → flow → degrade → statistics recording) as a
jitted device step over a 1M-row counter tensor, with pre-staged event batches
so the number is device throughput, not host marshalling.

North star (BASELINE.json): ≥50M decisions/sec across 1M resources on a
v5e-8 ⇒ 6.25M/sec/chip. ``vs_baseline`` = measured / 6.25e6.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Knobs via env: BENCH_RESOURCES, BENCH_BATCH, BENCH_STEPS, BENCH_RULES,
BENCH_SHARDS (>1 row-shards the counter tensors over that many devices via
parallel/local_shard.py — the product multi-chip mode; requires that many
visible devices, e.g. the 8-virtual-device CPU harness or a real pod).

The artifact always carries a ``mesh`` block (device count, rows per
device, sharded-vs-replicated state leaf counts, donation/staging knob
state) so the 1-chip run is a self-describing comparison row, and — on
sharded runs or under BENCH_WEAK_SCALING=1 — a ``weak_scaling`` block:
the 1/2/4/8-device fixed-rows-per-device curve through the runtime with
its normalized flatness ratios (benchmarks/weak_scaling.py).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np


def scatter_ab() -> None:
    """BENCH_SCATTER={xla,pallas}: the counter-table scatter-add microbench
    (SURVEY §7 phase 1 'Pallas streaming scatter kernel' — A/B'd against
    XLA's native scatter). Knobs: BENCH_SCATTER_K (table rows),
    BENCH_SCATTER_N (event-stream length), BENCH_SCATTER_E (event lanes).
    Prints the standard one-JSON-line; see benchmarks/scatter_ab.py for the
    full shape sweep + committed results table in BASELINE.md."""
    import time

    import jax
    import jax.numpy as jnp

    from sentinel_tpu.ops.pallas_kernels import (
        scatter_add_pallas, scatter_add_xla,
    )

    backend = os.environ["BENCH_SCATTER"]
    K = int(os.environ.get("BENCH_SCATTER_K", str(1 << 12)))
    N = int(os.environ.get("BENCH_SCATTER_N", str(1 << 16)))
    E = int(os.environ.get("BENCH_SCATTER_E", "8"))
    STEPS = int(os.environ.get("BENCH_STEPS", "50"))

    rng = np.random.default_rng(0)
    counters = jnp.zeros((K, E), jnp.int32)
    keys = jnp.asarray(rng.integers(0, K, N).astype(np.int32))
    events = jnp.asarray(rng.integers(0, E, N).astype(np.int32))
    amounts = jnp.asarray(rng.integers(1, 3, N).astype(np.int32))

    if backend == "pallas":
        interp = jax.devices()[0].platform != "tpu"
        fn = jax.jit(functools.partial(scatter_add_pallas, interpret=interp))
    elif backend == "xla":
        fn = jax.jit(scatter_add_xla)
    else:
        raise SystemExit(f"BENCH_SCATTER must be xla|pallas, got {backend}")

    for _ in range(3):
        counters = fn(counters, keys, events, amounts)
    # honest-mode gate (see main bench): force real execution before timing
    _ = np.asarray(counters[:1, :1])
    jax.block_until_ready(counters)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        counters = fn(counters, keys, events, amounts)
    jax.block_until_ready(counters)
    dt = time.perf_counter() - t0
    rate = N * STEPS / dt
    print(json.dumps({
        "metric": f"scatter_add_events_per_sec_{backend}_K{K}_N{N}",
        "value": round(rate, 1),
        "unit": "events/s",
        "vs_baseline": 0.0,      # microbench: no north-star share
    }))


def measure_serving(jax) -> dict:
    """Through-the-runtime serving-loop decomposition for the artifact:
    the same traffic dispatched synchronously
    (``entry_batch_nowait(...).result()`` per step) vs through a
    :class:`~sentinel_tpu.serving.DispatchPipeline` at depths 1/2/4,
    plus per-stage span attribution of the pipelined run (mean µs per
    span name, sample=1.0). The sync-vs-depth-2 delta is the per-step
    host readback/idle cost the pipeline hides; the CI gate
    (benchmarks/ci_gate.py ``dispatch_pipeline``) holds the ratio."""
    import collections
    import statistics

    import sentinel_tpu as stpu

    B = int(os.environ.get("BENCH_SERVING_BATCH", "4096"))
    STEPS = int(os.environ.get("BENCH_SERVING_STEPS", "30"))
    REPEATS = int(os.environ.get("BENCH_SERVING_REPEATS", "3"))
    DEPTHS = (1, 2, 4)

    sph = stpu.Sentinel(config=stpu.load_config(
        max_resources=4096, max_flow_rules=256, max_degrade_rules=16,
        max_authority_rules=16, minute_enabled=False))
    sph.load_flow_rules([stpu.FlowRule(resource=f"s{i}", count=1e9)
                         for i in range(256)])
    rng = np.random.default_rng(6)
    rows = sph.intern_resources(
        [f"s{int(i)}" for i in rng.integers(0, 1024, B)])

    def run_sync() -> float:
        t0 = time.perf_counter()
        for _ in range(STEPS):
            sph.entry_batch_nowait(rows).result()
        return (time.perf_counter() - t0) / STEPS * 1000

    def run_pipelined(depth: int) -> float:
        pipe = stpu.DispatchPipeline(sph, depth=depth)
        tickets = collections.deque()
        t0 = time.perf_counter()
        for _ in range(STEPS):
            tickets.append(pipe.submit(rows))
            if len(tickets) > depth:
                tickets.popleft().result()
        while tickets:
            tickets.popleft().result()
        return (time.perf_counter() - t0) / STEPS * 1000

    run_sync()                                   # warm every variant once
    run_pipelined(2)
    out = {"batch": B, "steps": STEPS,
           "sync_step_ms": round(min(run_sync() for _ in range(REPEATS)), 3)}
    out["pipelined_step_ms"] = {
        str(d): round(min(run_pipelined(d) for _ in range(REPEATS)), 3)
        for d in DEPTHS}

    # per-stage attribution of one pipelined pass, every dispatch sampled
    sph.obs.spans.clear()
    sph.obs.spans._stride = 1
    run_pipelined(2)
    stages: dict = {}
    for s in sph.obs.spans.snapshot():
        agg = stages.setdefault(s["name"], [])
        agg.append(s["dur_ns"])
    out["stage_us"] = {
        name: {"n": len(v),
               "mean": round(statistics.fmean(v) / 1000, 1)}
        for name, v in sorted(stages.items())}

    # round 12 — telemetry overhead for the artifact trail: the cost of
    # ONE hot-resource telemetry tick + readback (obs/telemetry.py)
    # against the serving step it rides beside at 1 Hz; the enforced
    # on/off step-time ratio lives in ci_gate gate (k)
    telem = getattr(sph, "telemetry", None)
    if telem is not None and telem.enabled:
        telem.poll()                             # compile the tick once
        t0 = time.perf_counter()
        for _ in range(10):
            telem.poll()
        tick_ms = (time.perf_counter() - t0) / 10 * 1000
        out["telemetry"] = {
            "k": telem.k,
            "tick_ms": round(tick_ms, 3),
            "tick_vs_sync_step": round(
                tick_ms / out["sync_step_ms"], 4) if out["sync_step_ms"]
                else None,
        }

    # round 16 — single-dispatch ablation for the artifact trail: the
    # SAME traffic through this engine (count-min observe fused into
    # the decide program, SENTINEL_SINGLE_DISPATCH default-on) vs an
    # engine built with the knob off (decide + a standalone observe
    # dispatch per step). ``dispatches_per_batch`` is counted from
    # ``pipeline.dispatches`` over the measured region; bit-parity and
    # the steady ==1 invariant are gated by ci_gate gate (m).
    from sentinel_tpu.obs import counters as obs_keys
    c0 = sph.obs.counters.get(obs_keys.PIPE_DISPATCH)
    fused_ms = min(run_sync() for _ in range(REPEATS))
    n_disp = sph.obs.counters.get(obs_keys.PIPE_DISPATCH) - c0
    out["dispatches_per_batch"] = round(n_disp / (STEPS * REPEATS), 4)
    prev_sd = os.environ.get("SENTINEL_SINGLE_DISPATCH")
    os.environ["SENTINEL_SINGLE_DISPATCH"] = "0"
    try:
        two = stpu.Sentinel(config=stpu.load_config(
            max_resources=4096, max_flow_rules=256, max_degrade_rules=16,
            max_authority_rules=16, minute_enabled=False))
    finally:
        if prev_sd is None:
            os.environ.pop("SENTINEL_SINGLE_DISPATCH", None)
        else:
            os.environ["SENTINEL_SINGLE_DISPATCH"] = prev_sd
    two.load_flow_rules([stpu.FlowRule(resource=f"s{i}", count=1e9)
                         for i in range(256)])
    rows_two = two.intern_resources(
        [f"s{int(i)}" for i in rng.integers(0, 1024, B)])

    def run_sync_two() -> float:
        t0 = time.perf_counter()
        for _ in range(STEPS):
            two.entry_batch_nowait(rows_two).result()
        return (time.perf_counter() - t0) / STEPS * 1000

    run_sync_two()                               # warm
    d0 = two.obs.counters.get(obs_keys.PIPE_DISPATCH)
    two_ms = min(run_sync_two() for _ in range(REPEATS))
    d1 = two.obs.counters.get(obs_keys.PIPE_DISPATCH)
    out["single_dispatch"] = {
        "enabled": bool(sph._single_dispatch),
        "fused_step_ms": round(fused_ms, 3),
        "two_dispatch_step_ms": round(two_ms, 3),
        "two_dispatch_per_batch": round(
            (d1 - d0) / (STEPS * REPEATS), 4),
        "step_ratio": (round(fused_ms / two_ms, 4) if two_ms else None),
    }
    two.close()
    sph.close()
    return out


def _tuned_provenance(spec, mesh):
    """Round-11 tuned-config provenance for the artifact (sentinel_tpu/
    tune): fingerprint-checked against this run's spec/mesh. A broken
    artifact must never take the headline down — degrade to an error
    field instead."""
    try:
        from sentinel_tpu.tune import provenance
        return provenance(spec, mesh)
    except Exception as exc:      # noqa: BLE001
        return {"tuned": False, "error": repr(exc)}


def main() -> None:
    import jax

    # sitecustomize pins the axon TPU platform at interpreter boot; a
    # BENCH_PLATFORM override (e.g. cpu, with
    # XLA_FLAGS=--xla_force_host_platform_device_count=8) lets the sharded
    # mode run on the virtual-device harness
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    if os.environ.get("BENCH_SCATTER"):
        scatter_ab()
        return
    import jax.numpy as jnp

    from sentinel_tpu.core.registry import OriginRegistry, Registry, ResourceRegistry
    from sentinel_tpu.runtime import (
        donation_enabled as _donation_enabled,
        host_staging_enabled as _staging_enabled,
        pipeline_depth as _pipeline_depth,
    )
    from sentinel_tpu.engine.pipeline import (
        EngineSpec, EntryBatch, RuleSet, decide_entries, init_state,
    )
    from sentinel_tpu.rules import authority as auth_mod
    from sentinel_tpu.rules import degrade as deg_mod
    from sentinel_tpu.rules import flow as flow_mod
    from sentinel_tpu.rules import param_flow as pf_mod
    from sentinel_tpu.rules import system as sys_mod
    from sentinel_tpu.stats.window import WindowSpec

    R = int(os.environ.get("BENCH_RESOURCES", str(1 << 20)))        # 1M rows
    # Default batch: 512k, the knee of the committed scaling study
    # (benchmarks/scaling_study.py; curve table in BASELINE.md) — on the
    # v5 lite chip throughput is flat within ~5% from 512k up while step
    # latency grows linearly; B=2M buys +5% if latency is irrelevant.
    # BENCH_BATCH overrides; re-run the study on new hardware.
    B = int(os.environ.get("BENCH_BATCH", str(1 << 19)))
    STEPS = int(os.environ.get("BENCH_STEPS", "60"))
    NRULES = int(os.environ.get("BENCH_RULES", "4096"))
    WARMUP = 3

    spec = EngineSpec(
        rows=R, alt_rows=1024,
        second=WindowSpec(buckets=2, win_ms=500),
        minute=None,                      # minute ring off: 1M×60 won't fit
        statistic_max_rt=5000)

    resources = ResourceRegistry(R)
    origins = OriginRegistry(64)
    contexts = Registry(64, reserved=("sentinel_default_context",))

    # QPS rules on the first NRULES resources; the rest decide rule-free
    # (still full statistics recording) — a realistic mixed population.
    rules = [flow_mod.FlowRule(resource=f"r{i}", count=50.0)
             for i in range(NRULES)]
    compiled = flow_mod.compile_flow_rules(
        rules, resource_registry=resources, context_registry=contexts,
        capacity=NRULES, k_per_resource=2, num_rows=R, origin_registry=origins)
    deg_rules = [deg_mod.DegradeRule(resource=f"r{i}",
                                     grade=deg_mod.GRADE_EXCEPTION_RATIO,
                                     count=0.5, time_window=10)
                 for i in range(min(NRULES, 1024))]
    deg = deg_mod.compile_degrade_rules(
        deg_rules, resource_registry=resources, capacity=max(len(deg_rules), 1),
        k_per_resource=2, num_rows=R)
    auth = auth_mod.compile_authority_rules(
        [], resource_registry=resources, origin_registry=origins,
        capacity=16, k_per_resource=2, num_rows=R)
    param = pf_mod.compile_param_rules(
        [], resource_registry=resources, capacity=1, k_per_resource=2)
    ruleset = RuleSet(
        flow_table=compiled.table, flow_idx=compiled.rule_idx,
        deg_table=deg.table, deg_idx=deg.rule_idx,
        auth_table=auth.table, auth_idx=auth.rule_idx,
        sys_thresholds=sys_mod.compile_system_rules([]),
        param_table=param.table)

    state = init_state(spec, NRULES, max(len(deg_rules), 1))

    # One layout authority (parallel/local_shard.py) for mesh construction,
    # shardings, and placement — the runtime, this bench, and the gates all
    # build the serving layout through the same helpers.
    from sentinel_tpu.parallel.local_shard import (
        local_mesh, mesh_topology, pin_state, place_batch, shardings_for,
    )

    SHARDS = int(os.environ.get("BENCH_SHARDS", "1"))
    mesh = mesh_sh = None
    if SHARDS > 1:
        try:
            mesh = local_mesh(SHARDS)
        except ValueError as exc:
            raise SystemExit(str(exc))
        mesh_sh = shardings_for(spec, mesh, state)
        state = pin_state(state, mesh_sh[0])

    rng = np.random.default_rng(42)
    n_batches = 4
    batches = []
    for _ in range(n_batches):
        # 1/4 of traffic on ruled rows (hot), rest uniform over all 1M
        hot = rng.integers(1, NRULES, B // 4)
        cold = rng.integers(1, R, B - B // 4)
        rows = np.concatenate([hot, cold]).astype(np.int32)
        rng.shuffle(rows)
        batches.append(EntryBatch(
            rows=jax.device_put(jnp.asarray(rows)),
            origin_ids=jnp.zeros(B, jnp.int32),
            origin_rows=jnp.full(B, spec.alt_rows, jnp.int32),
            context_ids=jnp.zeros(B, jnp.int32),
            chain_rows=jnp.full(B, spec.alt_rows, jnp.int32),
            acquire=jnp.ones(B, jnp.int32),
            is_in=jnp.ones(B, jnp.bool_),
            prioritized=jnp.zeros(B, jnp.bool_),
            valid=jnp.ones(B, jnp.bool_)))
    if mesh is not None:
        # batch columns partitioned on the event axis, exactly as the
        # runtime's dispatch tier places them (layout only — values and
        # verdicts are unchanged; the parity tests pin that)
        batches = [place_batch(b, mesh) for b in batches]

    # record_alt=False + scalar_flow: the bench batch carries no origin/
    # chain rows, uniform acquire=1, no priorities — the runtime selects
    # these same static variants for such batches (scalar admission path,
    # empty-slot skips, used-rule-slot slicing; see runtime.decide_raw)
    ruleset = ruleset._replace(
        flow_idx=compiled.rule_idx[:, :compiled.k_used],
        deg_idx=deg.rule_idx[:, :deg.k_used]).with_joint()
    # skip_threads: the bench ruleset has no THREAD-grade/system rules, so
    # the runtime would elide the gauge scatters for it too (VERDICT r4 #2)
    step = jax.jit(functools.partial(decide_entries, spec,
                                     enable_occupy=False, record_alt=False,
                                     scalar_flow=True, scalar_has_rl=False,
                                     skip_auth=True, skip_sys=True,
                                     skip_threads=True),
                   donate_argnums=(1,),
                   **({"out_shardings": mesh_sh} if mesh_sh else {}))

    t0_ms = 1_000_000_000
    sys_scalars = jnp.asarray(np.array([0.5, 0.1], np.float32))

    def scalars(i):
        now = t0_ms + i * 2  # 2 ms per step → windows rotate during the run
        # packed: ONE transfer per step (tunneled-TPU dispatch latency)
        return jnp.asarray(np.array(
            [spec.second.index_of(now), 0, now - t0_ms,
             now % spec.second.win_ms], np.int32))

    print(f"bench: R={R} B={B} steps={STEPS} on {jax.devices()[0]}",
          file=sys.stderr)
    for i in range(WARMUP):
        state, verdicts = step(ruleset, state, batches[i % n_batches],
                               scalars(i), sys_scalars)
    # HONEST-MODE GATE: the tunneled TPU runtime defers execution until the
    # process's first device→host copy — before it, dispatches complete
    # instantly and block_until_ready is a no-op lie (measured: a 2048³
    # matmul loop "runs" at 0.03 ms/step before the first readback, 3.6 ms
    # after, and the first readback pays for the entire deferred graph).
    # One tiny copy after warmup flips the process to real execution so the
    # timed region below measures actual device throughput.
    _ = np.asarray(verdicts.allow[:1])
    jax.block_until_ready(state)

    # N repeated timed regions: the tunnel varies >2x run to run
    # (BASELINE.md), so the driver artifact carries the min/max band — a
    # regression is a shifted BAND, not a shifted point.
    REPEATS = int(os.environ.get("BENCH_REPEATS", "3"))
    rates = []
    tick = WARMUP
    for _ in range(REPEATS):
        start = time.perf_counter()
        for i in range(STEPS):
            state, verdicts = step(ruleset, state, batches[i % n_batches],
                                   scalars(tick), sys_scalars)
            tick += 1
        jax.block_until_ready((state, verdicts))
        elapsed = time.perf_counter() - start
        rates.append(B * STEPS / elapsed)
        print(f"bench: {B * STEPS} decisions in {elapsed:.3f}s "
              f"({rates[-1]:.0f}/s)", file=sys.stderr)
    rate = sorted(rates)[len(rates) // 2]      # median of the regions

    # decomposition: dispatch floor (chained trivial op) vs full step —
    # together with the band this lets BENCH_r0N.json alone distinguish
    # code regressions from tunnel weather
    tiny = jax.jit(lambda x: x + 1)
    c = tiny(jnp.zeros((8,), jnp.int32))
    _ = np.asarray(c[:1])
    t0 = time.perf_counter()
    for _ in range(50):
        c = tiny(c)
    jax.block_until_ready(c)
    floor_ms = (time.perf_counter() - t0) / 50 * 1000
    # the same floor with a per-dispatch READBACK (the sync serving
    # loop's real cost on a remote-attached device) vs a depth-2 window
    # that defers each readback one step — the pair the runtime's
    # DispatchPipeline trades between (serving section below measures it
    # through the full runtime)
    import collections as _coll
    x0 = jnp.zeros((8,), jnp.int32)
    t0 = time.perf_counter()
    for _ in range(50):
        _ = np.asarray(tiny(x0)[:1])
    floor_sync_ms = (time.perf_counter() - t0) / 50 * 1000
    window: "_coll.deque" = _coll.deque()
    t0 = time.perf_counter()
    for _ in range(50):
        window.append(tiny(x0))
        if len(window) > 2:
            _ = np.asarray(window.popleft()[:1])
    while window:
        _ = np.asarray(window.popleft()[:1])
    floor_pipe_ms = (time.perf_counter() - t0) / 50 * 1000

    metric = ("decisions_per_sec_1chip_1M_resources" if SHARDS <= 1 else
              f"decisions_per_sec_{SHARDS}shard_1M_resources")
    # north star is per-chip: a sharded run is held to SHARDS× the target
    out = {
        "metric": metric,
        "value": round(rate, 1),
        "unit": "decisions/s",
        "vs_baseline": round(rate / (6.25e6 * max(SHARDS, 1)), 4),
        "band_min": round(min(rates), 1),
        "band_max": round(max(rates), 1),
        "runs": len(rates),
        "step_ms": round(B * STEPS / rate / STEPS * 1000, 2),
        "dispatch_floor_ms": round(floor_ms, 2),
        "dispatch_floor_sync_ms": round(floor_sync_ms, 2),
        "dispatch_floor_pipelined_ms": round(floor_pipe_ms, 2),
        "pipeline_depth": _pipeline_depth(),
        "batch": B,
        "resources": R,
        # serving-mode knob state at measurement time, so BENCH_r0N
        # artifacts are self-describing (absent key = knob at default)
        "env_knobs": {k: os.environ[k] for k in (
            "SENTINEL_PIPELINE_DEPTH", "SENTINEL_DONATE",
            "SENTINEL_HOST_STAGING", "SENTINEL_FRONTEND_BATCH",
            "SENTINEL_FRONTEND_DEADLINE_MS", "SENTINEL_FRONTEND_BUDGET_MS",
            "SENTINEL_FRONTEND_IDLE_MS", "SENTINEL_FRONTEND_QUEUE",
            "SENTINEL_SORTFREE", "SENTINEL_SORTFREE_BITS",
            "SENTINEL_SORTFREE_CHUNK", "SENTINEL_TUNED_CONFIG",
            "SENTINEL_TELEMETRY_K", "SENTINEL_TELEMETRY_DISABLE",
        ) if k in os.environ},
        # round 11 — tuned-config provenance: whether a
        # SENTINEL_TUNED_CONFIG artifact applied to this run (fingerprint
        # checked against THIS spec/mesh), and its per-knob values, so a
        # BASELINE.md chip row is reproducible without the machine
        "tuned_config": _tuned_provenance(spec, mesh),
        # serving layout that produced the headline (n_devices=1 on the
        # single-chip run — the comparison row the weak-scaling curve and
        # sharded artifacts are read against), plus the transfer knobs
        # whose defaults depend on the mesh (donation on, host staging
        # bypassed when batch placement is active)
        "mesh": {**mesh_topology(spec, mesh,
                                 mesh_sh[0] if mesh_sh else None),
                 "donation": _donation_enabled(),
                 "host_staging": mesh is None and _staging_enabled(),
                 "batch_placement": mesh is not None},
    }
    # General-path + mixed-batch numbers ride the same artifact (VERDICT
    # r4 #10: the non-happy path must not regress silently). Skippable via
    # BENCH_GENERAL=0; a failure never takes the headline down with it.
    if os.environ.get("BENCH_GENERAL", "1") != "0" and SHARDS <= 1:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        try:
            from benchmarks.general_bench import measure
            del state, batches        # free HBM before the second fixture
            g_steps = int(os.environ.get("BENCH_GENERAL_STEPS", "20"))
            # the sorted/sortfree pair carries the r10 claim: same mode,
            # same fixture, aggregation stage swapped — with the
            # per-stage aggregation_ms marginal in both rows
            out["general"] = measure(jax, "fast", R, B, g_steps, NRULES, 3,
                                     aggregation=True)
            out["general_sortfree"] = measure(
                jax, "fast", R, B, g_steps, NRULES, 3, sortfree=True,
                aggregation=True)
            out["mixed"] = measure(jax, "mixed", R, B, g_steps, NRULES, 3)
            # prioritized-traffic numbers (r6: the 16x priority/occupy
            # cliff — BENCH artifacts from r06 on must carry them so a
            # reintroduced whole-batch demotion can never hide)
            out["prio"] = measure(jax, "prio", R, B, g_steps, NRULES, 3)
            out["prio_mixed"] = measure(jax, "prio_mixed", R, B, g_steps,
                                        NRULES, 3)
        except Exception as exc:      # noqa: BLE001 — headline must print
            out["general_error"] = repr(exc)
    # Through-the-runtime serving decomposition (r6: pipelined dispatch).
    # Skippable via BENCH_SERVING=0; never takes the headline down.
    if os.environ.get("BENCH_SERVING", "1") != "0" and SHARDS <= 1:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        try:
            out["serving"] = measure_serving(jax)
        except Exception as exc:      # noqa: BLE001
            out["serving_error"] = repr(exc)
    # 1/2/4/8-device weak-scaling curve through the runtime (r9: fixed
    # rows per device, DispatchPipeline depth swept). Runs by default only
    # on a sharded invocation (the single-chip TPU artifact would see one
    # device and produce a degenerate curve); BENCH_WEAK_SCALING=1 forces
    # it (the CPU virtual-device harness), =0 skips. Never takes the
    # headline down.
    ws_knob = os.environ.get("BENCH_WEAK_SCALING", "")
    if ws_knob != "0" and (ws_knob == "1" or SHARDS > 1):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        try:
            from benchmarks.weak_scaling import flatness, measure as ws_measure
            counts = tuple(n for n in (1, 2, 4, 8)
                           if n <= max(SHARDS, len(jax.devices())))
            points = ws_measure(
                jax,
                rows_per_dev=int(os.environ.get("WEAK_ROWS_PER_DEV",
                                                str(1 << 14))),
                batch=int(os.environ.get("WEAK_BATCH", str(1 << 13))),
                steps=int(os.environ.get("WEAK_STEPS", "6")),
                device_counts=counts,
                depths=tuple(int(d) for d in os.environ.get(
                    "WEAK_DEPTHS", "1,2,4").split(",")))
            out["weak_scaling"] = {"curve": points,
                                   "flatness_norm": flatness(points)}
        except Exception as exc:      # noqa: BLE001
            out["weak_scaling_error"] = repr(exc)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
