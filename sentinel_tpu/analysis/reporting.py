"""graftlint reporters: human-readable text and machine JSON."""

from __future__ import annotations

import json
from typing import List, Sequence, TextIO

from sentinel_tpu.analysis.core import Finding


def split_findings(findings: Sequence[Finding]):
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    return active, suppressed


def render_human(findings: Sequence[Finding], stream: TextIO,
                 show_suppressed: bool = False) -> None:
    active, suppressed = split_findings(findings)
    for f in active:
        stream.write(f.format() + "\n")
    if show_suppressed:
        for f in suppressed:
            stream.write(f.format() + "\n")
    by_rule = {}
    for f in active:
        by_rule[f.rule_id] = by_rule.get(f.rule_id, 0) + 1
    summary = ", ".join("%s=%d" % kv for kv in sorted(by_rule.items()))
    stream.write(
        "graftlint: %d finding(s)%s, %d suppressed\n"
        % (len(active), " (%s)" % summary if summary else "",
           len(suppressed)))


def render_json(findings: Sequence[Finding], files_scanned: int) -> str:
    active, suppressed = split_findings(findings)
    return json.dumps({
        "tool": "graftlint",
        "version": 1,
        "files_scanned": files_scanned,
        "unsuppressed_count": len(active),
        "suppressed_count": len(suppressed),
        "findings": [f.to_dict() for f in findings],
    }, indent=2, sort_keys=False)
