"""graftlint reporters: human text, machine JSON, SARIF 2.1.0, and the
baseline ratchet.

SARIF is the GitHub code-scanning ingestion format — the CI lint job
uploads ``graftlint.sarif`` so findings annotate PR diffs inline.
Suppressed findings ship with a SARIF ``suppressions`` entry (kind
``inSource``) and baselined findings with ``baselineState:
"unchanged"`` so code scanning shows both without failing the run.

The baseline (``--baseline graftlint-baseline.json``) exists for scope
widening: pre-existing findings in test/bench files are recorded once
(``--write-baseline``) and matched by ``(path, rule, message)``
multiset — line numbers are deliberately NOT part of the fingerprint so
unrelated edits don't churn it. New findings never match and still fail
the gate; fixed findings leave stale entries that the report counts so
the baseline only ratchets down.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, List, Sequence, TextIO, Tuple

from sentinel_tpu.analysis.core import Finding

BASELINE_VERSION = 1
SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def split_findings(findings: Sequence[Finding]):
    """(active, muted): muted = suppressed in source OR baselined."""
    active = [f for f in findings if f.active]
    muted = [f for f in findings if not f.active]
    return active, muted


def render_human(findings: Sequence[Finding], stream: TextIO,
                 show_suppressed: bool = False) -> None:
    active, muted = split_findings(findings)
    for f in active:
        stream.write(f.format() + "\n")
    if show_suppressed:
        for f in muted:
            stream.write(f.format() + "\n")
    by_rule: Dict[str, int] = {}
    for f in active:
        by_rule[f.rule_id] = by_rule.get(f.rule_id, 0) + 1
    summary = ", ".join("%s=%d" % kv for kv in sorted(by_rule.items()))
    n_sup = sum(1 for f in muted if f.suppressed)
    n_base = sum(1 for f in muted if f.baselined)
    base_tag = ", %d baselined" % n_base if n_base else ""
    stream.write(
        "graftlint: %d finding(s)%s, %d suppressed%s\n"
        % (len(active), " (%s)" % summary if summary else "",
           n_sup, base_tag))


def render_json(findings: Sequence[Finding], files_scanned: int) -> str:
    active, muted = split_findings(findings)
    return json.dumps({
        "tool": "graftlint",
        "version": 1,
        "files_scanned": files_scanned,
        "unsuppressed_count": len(active),
        "suppressed_count": sum(1 for f in muted if f.suppressed),
        "baselined_count": sum(1 for f in muted if f.baselined),
        "findings": [f.to_dict() for f in findings],
    }, indent=2, sort_keys=False)


# ----------------------------------------------------------------------
# SARIF 2.1.0
# ----------------------------------------------------------------------

def _sarif_uri(path: str) -> str:
    p = path.replace("\\", "/")
    while p.startswith("./"):
        p = p[2:]
    return p


def render_sarif(findings: Sequence[Finding], rules) -> str:
    """One-run SARIF document. ``rules`` is the rule instances that ran
    (their id/name/rationale become the driver's rule metadata, which
    GitHub renders in the finding details pane)."""
    rule_meta = [{
        "id": r.id,
        "name": r.name or r.id,
        "shortDescription": {"text": r.name or r.id},
        "fullDescription": {"text": r.rationale or r.name or r.id},
        "helpUri": "https://github.com/sentinel-tpu/sentinel-tpu/blob/"
                   "main/docs/LINT.md",
        "defaultConfiguration": {"level": "error"},
    } for r in rules]
    rule_index = {m["id"]: i for i, m in enumerate(rule_meta)}
    results = []
    for f in findings:
        res = {
            "ruleId": f.rule_id,
            "level": "error" if f.active else "note",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": _sarif_uri(f.path),
                        "uriBaseId": "%SRCROOT%",
                    },
                    "region": {
                        "startLine": max(f.line, 1),
                        "startColumn": max(f.col, 0) + 1,
                    },
                },
            }],
        }
        if f.rule_id in rule_index:
            res["ruleIndex"] = rule_index[f.rule_id]
        if f.suppressed:
            res["suppressions"] = [{
                "kind": "inSource",
                "justification": f.suppress_reason,
            }]
        if f.baselined:
            res["baselineState"] = "unchanged"
        results.append(res)
    return json.dumps({
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri": "https://github.com/sentinel-tpu/"
                                  "sentinel-tpu/blob/main/docs/LINT.md",
                "semanticVersion": "2.0.0",
                "rules": rule_meta,
            }},
            "columnKind": "utf16CodeUnits",
            "results": results,
        }],
    }, indent=2)


# ----------------------------------------------------------------------
# Baseline ratchet
# ----------------------------------------------------------------------

def _fingerprint(f: Finding) -> Tuple[str, str, str]:
    return (_sarif_uri(f.path), f.rule_id, f.message)


def write_baseline(findings: Sequence[Finding], path: str) -> int:
    """Record every currently-unsuppressed finding. Returns the entry
    count. Suppressed findings are NOT baselined — their suppression
    comment already carries the reviewed reason."""
    entries = [{"path": _sarif_uri(f.path), "rule": f.rule_id,
                "message": f.message}
               for f in sorted((f for f in findings if f.active),
                               key=lambda f: f.sort_key)]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"tool": "graftlint", "baseline_version":
                   BASELINE_VERSION, "entries": entries}, fh, indent=2)
        fh.write("\n")
    return len(entries)


def apply_baseline(findings: Sequence[Finding],
                   path: str) -> Tuple[int, int]:
    """Mark findings matching baseline entries as ``baselined``
    in place. Returns ``(matched, stale)`` — stale entries match
    nothing anymore and should be deleted from the baseline file."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    budget: Counter = Counter(
        (e["path"], e["rule"], e["message"]) for e in doc.get("entries", ()))
    matched = 0
    for f in findings:
        if not f.active:
            continue
        fp = _fingerprint(f)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            f.baselined = True
            matched += 1
    stale = sum(budget.values())
    return matched, stale
