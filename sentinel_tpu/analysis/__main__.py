"""graftlint CLI.

``python -m sentinel_tpu.analysis sentinel_tpu/`` — exit 0 iff zero
unsuppressed findings (the CI gate). See ``docs/LINT.md``.

Exit codes: 0 clean, 1 unsuppressed findings, 2 usage error,
3 ``--budget-s`` wall-time budget exceeded (findings still reported).

``--jobs N`` fans pass-2 (per-file checks) over a process pool. Pass 1
(the cross-module project index) needs every module, so each worker
parses the full file set once in its initializer and runs every rule's
``prepare`` — the index is then shared across all files that worker
checks. Findings are order-merged so ``--jobs N`` output is
byte-identical to ``--jobs 1``.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, List, Optional

from sentinel_tpu.analysis import core, reporting
from sentinel_tpu.analysis.rules import ALL_RULES, RULES_BY_ID


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m sentinel_tpu.analysis",
        description="graftlint: AST static analysis for SPMD, trace, "
                    "concurrency, and device-contract safety")
    p.add_argument("paths", nargs="*",
                   help="files or directories to analyze")
    p.add_argument("--select", metavar="IDS",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--rule", metavar="ID", action="append", default=[],
                   help="run only this rule id (repeatable; combines "
                        "with --select)")
    p.add_argument("--ignore", metavar="IDS",
                   help="comma-separated rule ids to skip")
    p.add_argument("--exclude", metavar="PATHFRAG", action="append",
                   default=[],
                   help="skip files whose path contains this fragment "
                        "(repeatable; e.g. tests/fixtures/graftlint)")
    p.add_argument("--jobs", type=int, default=1, metavar="N",
                   help="parallel per-file analysis processes sharing "
                        "the pass-1 project index (default: 1)")
    p.add_argument("--format", choices=("human", "json"), default="human")
    p.add_argument("--json-out", metavar="FILE",
                   help="also write the JSON report to FILE")
    p.add_argument("--sarif-out", metavar="FILE",
                   help="also write a SARIF 2.1.0 report to FILE "
                        "(GitHub code scanning)")
    p.add_argument("--baseline", metavar="FILE",
                   help="demote findings matching this baseline file "
                        "(path+rule+message multiset) to non-gating")
    p.add_argument("--write-baseline", metavar="FILE",
                   help="record current unsuppressed findings as the "
                        "baseline and exit 0")
    p.add_argument("--budget-s", type=float, metavar="SECONDS",
                   help="fail (exit 3) when analysis wall time exceeds "
                        "this budget — keeps the CI quick tier honest")
    p.add_argument("--show-suppressed", action="store_true",
                   help="print suppressed/baselined findings too "
                        "(human format)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


# ----------------------------------------------------------------------
# --jobs worker pool (module-level for picklability under spawn)
# ----------------------------------------------------------------------

_WORKER: dict = {}


def _worker_init(files: List[str], rule_ids: List[str]) -> None:
    contexts, errors = core.parse_contexts(files)
    rules = [RULES_BY_ID[i] for i in rule_ids]
    for rule in rules:
        rule.prepare(contexts)
    _WORKER["contexts"] = {ctx.path: ctx for ctx in contexts}
    _WORKER["errors"] = errors
    _WORKER["rules"] = rules


def _worker_check(path: str) -> List[core.Finding]:
    ctx = _WORKER["contexts"].get(path)
    if ctx is None:
        return [e for e in _WORKER["errors"] if e.path == path]
    return core.check_context(ctx, _WORKER["rules"])


def _analyze(files: List[str], rules, jobs: int) -> List[core.Finding]:
    if jobs <= 1 or len(files) < 2:
        return core.analyze_paths(files, rules)
    import concurrent.futures as cf
    import multiprocessing as mp
    rule_ids = [r.id for r in rules]
    try:
        mp_ctx = mp.get_context("fork")
    except ValueError:
        mp_ctx = None
    pool_kw = {"max_workers": min(jobs, len(files))}
    if mp_ctx is not None:
        # build the pass-1 index ONCE in the parent; forked workers
        # inherit it copy-on-write, so only pass 2 is distributed
        _worker_init(files, rule_ids)
        pool_kw["mp_context"] = mp_ctx
    else:
        pool_kw["initializer"] = _worker_init
        pool_kw["initargs"] = (files, rule_ids)
    findings: List[core.Finding] = []
    with cf.ProcessPoolExecutor(**pool_kw) as pool:
        for per_file in pool.map(_worker_check, files, chunksize=4):
            findings.extend(per_file)
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print("%s  %s\n    %s" % (r.id, r.name, r.rationale))
        return 0

    select = {s.strip() for s in (args.select or "").split(",") if s.strip()}
    for rid in args.rule:
        select |= {s.strip() for s in rid.split(",") if s.strip()}
    ignore = {s.strip() for s in (args.ignore or "").split(",") if s.strip()}
    unknown = (select | ignore) - set(RULES_BY_ID)
    if unknown:
        print("unknown rule id(s): %s" % ", ".join(sorted(unknown)),
              file=sys.stderr)
        return 2
    rules = [r for r in ALL_RULES
             if (not select or r.id in select) and r.id not in ignore]

    if not args.paths:
        print("error: no paths given (try: python -m sentinel_tpu.analysis "
              "sentinel_tpu/)", file=sys.stderr)
        return 2

    files = list(dict.fromkeys(core.iter_python_files(args.paths)))
    if args.exclude:
        norm = [frag.replace("\\", "/") for frag in args.exclude]
        files = [f for f in files
                 if not any(frag in f.replace("\\", "/") for frag in norm)]
    if not files:
        print("error: no Python files under %s" % ", ".join(args.paths),
              file=sys.stderr)
        return 2

    t0 = time.monotonic()
    findings = _analyze(files, rules, args.jobs)
    findings.sort(key=lambda f: f.sort_key)
    elapsed = time.monotonic() - t0

    if args.write_baseline:
        n = reporting.write_baseline(findings, args.write_baseline)
        print("graftlint: wrote %d baseline entries to %s"
              % (n, args.write_baseline))
        return 0
    stale = 0
    if args.baseline:
        try:
            _, stale = reporting.apply_baseline(findings, args.baseline)
        except (OSError, ValueError, KeyError) as exc:
            print("error: cannot read baseline %s: %s"
                  % (args.baseline, exc), file=sys.stderr)
            return 2

    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            fh.write(reporting.render_json(findings, len(files)) + "\n")
    if args.sarif_out:
        with open(args.sarif_out, "w", encoding="utf-8") as fh:
            fh.write(reporting.render_sarif(findings, rules) + "\n")
    if args.format == "json":
        print(reporting.render_json(findings, len(files)))
    else:
        reporting.render_human(findings, sys.stdout,
                               show_suppressed=args.show_suppressed)
        if stale:
            print("graftlint: %d stale baseline entr%s (fixed findings "
                  "— delete them so the baseline ratchets down)"
                  % (stale, "y" if stale == 1 else "ies"))

    if args.budget_s is not None and elapsed > args.budget_s:
        print("graftlint: wall time %.1fs exceeded --budget-s %.1fs"
              % (elapsed, args.budget_s), file=sys.stderr)
        return 3
    active, _ = reporting.split_findings(findings)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
