"""graftlint CLI.

``python -m sentinel_tpu.analysis sentinel_tpu/`` — exit 0 iff zero
unsuppressed findings (the CI gate). See ``docs/LINT.md``.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from sentinel_tpu.analysis import core, reporting
from sentinel_tpu.analysis.rules import ALL_RULES, RULES_BY_ID


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m sentinel_tpu.analysis",
        description="graftlint: AST static analysis for SPMD, trace, and "
                    "concurrency safety")
    p.add_argument("paths", nargs="*",
                   help="files or directories to analyze")
    p.add_argument("--select", metavar="IDS",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--ignore", metavar="IDS",
                   help="comma-separated rule ids to skip")
    p.add_argument("--format", choices=("human", "json"), default="human")
    p.add_argument("--json-out", metavar="FILE",
                   help="also write the JSON report to FILE")
    p.add_argument("--show-suppressed", action="store_true",
                   help="print suppressed findings too (human format)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print("%s  %s\n    %s" % (r.id, r.name, r.rationale))
        return 0

    rules = list(ALL_RULES)
    for flag, keep in (("select", True), ("ignore", False)):
        raw = getattr(args, flag)
        if not raw:
            continue
        ids = {s.strip() for s in raw.split(",") if s.strip()}
        unknown = ids - set(RULES_BY_ID)
        if unknown:
            print("unknown rule id(s): %s" % ", ".join(sorted(unknown)),
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if (r.id in ids) == keep]

    if not args.paths:
        print("error: no paths given (try: python -m sentinel_tpu.analysis "
              "sentinel_tpu/)", file=sys.stderr)
        return 2

    files = list(core.iter_python_files(args.paths))
    if not files:
        print("error: no Python files under %s" % ", ".join(args.paths),
              file=sys.stderr)
        return 2
    findings = core.analyze_paths(args.paths, rules)

    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            fh.write(reporting.render_json(findings, len(files)) + "\n")
    if args.format == "json":
        print(reporting.render_json(findings, len(files)))
    else:
        reporting.render_human(findings, sys.stdout,
                               show_suppressed=args.show_suppressed)
    active, _ = reporting.split_findings(findings)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
