"""graftlint pass 1 — the whole-program project model.

The round-3 engine ran each rule as a pure function of one
:class:`~sentinel_tpu.analysis.core.ModuleContext`; the only
cross-module fact anywhere was TRACE001's private jit-wrap-site map.
The round-18 concurrency/device-contract rules (LOCK002, DONATE001,
ORDER001, CAT001) all need *project* facts — which attributes a class
guards with which lock, which functions a thread can reach, which
callables donate their operands, what the counter catalog and knob
registry actually declare — so pass 1 is now a first-class shared
index built ONCE per analysis run:

* :class:`ClassIndex` — per-class attribute access sites, each tagged
  with the set of ``self.*`` / module-level locks held at that point,
  plus base-class names and method table.
* thread entry points (``threading.Thread(target=...)``, ``Timer``,
  ``executor.submit``, ``asyncio.to_thread``, ``run_in_executor``,
  ``run`` methods of Thread subclasses) and a name-based call graph,
  closed transitively into :attr:`ProjectIndex.thread_reachable`.
* donation provenance — every ``jax.jit(f, donate_argnums=...)`` wrap
  site (including the repo's ``**kw_d1`` dict-splat idiom inside
  ``_build_sd_steps`` / ``_jitted_steps_cached``) maps the wrapped
  function name AND the assignment target to its donated positions;
  staging-slot provenance comes from ``<ring>.acquire()`` call sites.
* declaration registries parsed from source, never imported: the
  counter catalog (a module named ``counters.py`` with a top-level
  ``CATALOG`` tuple), the knob registry (``knobs.py`` with a top-level
  ``KNOBS`` tuple of ``KnobSpec(...)`` calls + ``OPERATIONAL_ENVS``),
  and the ``SentinelConfig`` dataclass fields (``config.py``) that the
  ``SENTINEL_TPU_<FIELD>`` env mapping derives from.

Sharing: :func:`core.analyze_paths` wraps its context list in
:class:`ContextSet`; :func:`shared_index` memoizes the built index on
that object so the four rules' ``prepare`` passes pay for pass 1 once.
A plain list (the ``analyze_source`` single-module path) just builds a
fresh index — one module is cheap.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from sentinel_tpu.analysis.core import ModuleContext
from sentinel_tpu.analysis.rules import _shared


class ContextSet(list):
    """List of ModuleContexts that can carry the memoized pass-1 index
    (plain lists cannot take attributes)."""


def shared_index(contexts: Sequence[ModuleContext]) -> "ProjectIndex":
    cached = getattr(contexts, "_graftlint_index", None)
    if cached is not None:
        return cached
    index = ProjectIndex(contexts)
    try:
        contexts._graftlint_index = index  # type: ignore[attr-defined]
    except AttributeError:
        pass
    return index


# ----------------------------------------------------------------------
# Constant-expression evaluation (clamp bounds, donate_argnums, keys)
# ----------------------------------------------------------------------

def const_eval(node: ast.AST, names: Optional[Dict[str, object]] = None):
    """Evaluate the tiny constant-expression language the registries are
    written in: literals, ``-x``, ``a + b``, ``a * b``, ``a << b``,
    ``a // b``, tuples, and names resolvable through ``names``.
    Returns None when the expression is not statically known (callers
    must treat that as "unknown", never as a value)."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name) and names and node.id in names:
        return names[node.id]
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = const_eval(node.operand, names)
        return -v if isinstance(v, (int, float)) else None
    if isinstance(node, ast.Tuple):
        items = [const_eval(e, names) for e in node.elts]
        return None if any(i is None for i in items) else tuple(items)
    if isinstance(node, ast.BinOp):
        left = const_eval(node.left, names)
        right = const_eval(node.right, names)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.LShift):
                return left << right
            if isinstance(node.op, ast.FloorDiv):
                return left // right
            if isinstance(node.op, ast.Pow):
                return left ** right
        except TypeError:
            return None
    return None


def module_string_constants(ctx: ModuleContext) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` (and const-concat) bindings."""
    out: Dict[str, str] = {}
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            v = const_eval(stmt.value, out)
            if isinstance(v, str):
                out[stmt.targets[0].id] = v
    return out


# ----------------------------------------------------------------------
# Per-class access index (LOCK002 / ORDER001 substrate)
# ----------------------------------------------------------------------

#: Methods where unlocked access to guarded state is definitionally
#: fine: the object is not yet (or no longer) shared.
CONSTRUCTION_METHODS = frozenset({
    "__init__", "__new__", "__post_init__", "__del__", "__repr__",
})

#: Docstring shapes that declare a lock contract ("callers hold
#: ``_lock``"), the repo's documented-precondition idiom; a method whose
#: name ends in ``_locked`` declares the same contract by naming.
_LOCK_CONTRACT_RE = re.compile(
    r"caller[s]?\s+(?:must\s+)?hold|hold[s]?\s+(?:the\s+)?[`_\w.]*lock"
    r"|with\s+[`_\w.]*lock\s+held|under\s+[`_\w.]*lock",
    re.IGNORECASE)


@dataclasses.dataclass
class AttrAccess:
    """One ``self.<attr>`` load/store inside a method body."""

    attr: str
    node: ast.AST
    method: str
    is_store: bool
    locks_held: FrozenSet[str]     # lock names held at this point


@dataclasses.dataclass
class ClassIndex:
    name: str
    module: str                    # dotted module name
    path: str
    node: ast.ClassDef
    bases: Tuple[str, ...]
    methods: Dict[str, ast.AST]
    accesses: List[AttrAccess]

    def lock_contract_methods(self) -> Set[str]:
        out = set()
        for name, fn in self.methods.items():
            if name.endswith("_locked"):
                out.add(name)
                continue
            doc = ast.get_docstring(fn) or ""
            if doc and _LOCK_CONTRACT_RE.search(doc):
                out.add(name)
        return out


def _lock_name(expr: ast.AST, ctx: ModuleContext) -> Optional[str]:
    """``with self._lock:`` → ``_lock``; ``with REGISTRY_LOCK:`` →
    ``REGISTRY_LOCK``; calls (``lock.acquire_timeout()``) unwrap."""
    if isinstance(expr, ast.Call):
        expr = expr.func
        if isinstance(expr, ast.Attribute):       # lock.acquire_timeout
            expr = expr.value
    if not _shared.is_lockish(expr, ctx):
        return None
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


class _ClassWalker(_shared.AncestorVisitor):
    """Collect every self.<attr> access in a class body, tagged with the
    set of locks held (enclosing lockish ``with`` items) and the method
    it sits in. Nested defs inside a method attribute to that method
    (closures run on the same thread discipline as their home method for
    our purposes — thread-target closures are seeded separately)."""

    def __init__(self, ctx: ModuleContext, cls: ClassIndex):
        self.ctx = ctx
        self.cls = cls

    def visit(self, node, ancestors):
        if isinstance(node, ast.ClassDef):
            return False                      # nested classes: own index
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            method = None
            locks: Set[str] = set()
            for anc in ancestors:
                if isinstance(anc, _shared.FUNC_NODES) and method is None:
                    method = anc.name
                if isinstance(anc, (ast.With, ast.AsyncWith)):
                    for item in anc.items:
                        ln = _lock_name(item.context_expr, self.ctx)
                        if ln is not None:
                            locks.add(ln)
            if method is not None:
                self.cls.accesses.append(AttrAccess(
                    attr=node.attr, node=node, method=method,
                    is_store=isinstance(node.ctx, (ast.Store, ast.Del)),
                    locks_held=frozenset(locks)))
        return True


# ----------------------------------------------------------------------
# Thread entry points + name-based call graph
# ----------------------------------------------------------------------

_THREAD_FACTORIES = frozenset({
    "threading.Thread", "Thread", "threading.Timer", "Timer",
})
_SUBMIT_METHODS = frozenset({
    "submit", "run_in_executor", "call_soon_threadsafe", "to_thread",
    "start_new_thread", "defer",
})
_THREAD_BASES = frozenset({"threading.Thread", "Thread"})


def _callable_bare_name(arg: ast.AST) -> Optional[str]:
    """``self._serve`` → ``_serve``; ``serve`` → ``serve``; lambdas and
    calls → None (their bodies are walked where they appear)."""
    if isinstance(arg, ast.Attribute):
        return arg.attr
    if isinstance(arg, ast.Name):
        return arg.id
    return None


def _thread_target_names(ctx: ModuleContext) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.call_name(node)
        if name in _THREAD_FACTORIES:
            for kw in node.keywords:
                if kw.arg == "target":
                    t = _callable_bare_name(kw.value)
                    if t:
                        out.add(t)
            # Timer(interval, fn) / Thread(None, fn) positional form
            for arg in node.args[1:2]:
                t = _callable_bare_name(arg)
                if t:
                    out.add(t)
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SUBMIT_METHODS:
            pos = 1 if node.func.attr == "run_in_executor" else 0
            if len(node.args) > pos:
                t = _callable_bare_name(node.args[pos])
                if t:
                    out.add(t)
        elif name in ("asyncio.to_thread",) and node.args:
            t = _callable_bare_name(node.args[0])
            if t:
                out.add(t)
    return out


def _call_graph(ctx: ModuleContext,
                graph: Dict[str, Set[str]]) -> None:
    """name-based call edges: for each function/method def, the bare
    names it calls (``self.m()`` / ``obj.m()`` / ``m()``) and the bare
    names of callables it passes as thread/executor targets."""
    for fn in _shared.iter_functions(ctx.tree):
        edges = graph.setdefault(fn.name, set())
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute):
                    edges.add(node.func.attr)
                elif isinstance(node.func, ast.Name):
                    edges.add(node.func.id)


def _transitive_closure(seeds: Set[str],
                        graph: Dict[str, Set[str]]) -> Set[str]:
    seen = set(seeds)
    frontier = list(seeds)
    while frontier:
        cur = frontier.pop()
        for nxt in graph.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    return seen


# ----------------------------------------------------------------------
# Donation / staging provenance
# ----------------------------------------------------------------------

_JIT_NAMES = frozenset({"jax.jit", "jit", "jax.pmap"})


def _donate_positions(call: ast.Call, ctx: ModuleContext,
                      local_dicts: Dict[str, Tuple[int, ...]]):
    """donate positions of a ``jit(...)`` call: literal
    ``donate_argnums=(1, 2)`` or the repo's ``**kw_d1`` splat of a local
    ``{"donate_argnums": (1,)}`` dict (possibly conditional — treated as
    donating, the default-on configuration)."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = const_eval(kw.value)
            if isinstance(v, int):
                return (v,)
            if isinstance(v, tuple):
                return tuple(int(i) for i in v)
        elif kw.arg is None and isinstance(kw.value, ast.Name):
            if kw.value.id in local_dicts:
                return local_dicts[kw.value.id]
    return None


def _splat_dicts(fn: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """``kw_d1 = {"donate_argnums": (1,)} if donate else {}`` → kw_d1."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        value = node.value
        if isinstance(value, ast.IfExp):
            value = value.body
        if not isinstance(value, ast.Dict):
            continue
        for k, v in zip(value.keys, value.values):
            if isinstance(k, ast.Constant) and k.value == "donate_argnums":
                pos = const_eval(v)
                if isinstance(pos, int):
                    pos = (pos,)
                if isinstance(pos, tuple):
                    out[node.targets[0].id] = tuple(int(i) for i in pos)
    return out


def _donating_callables(ctx: ModuleContext) -> Dict[str, Tuple[int, ...]]:
    """bare name → donated positions, from every jit-with-donation wrap
    site in the module. Both the *wrapped function's* name and the
    *assignment target's* bare name are recorded: later calls through
    either spelling are donating dispatches."""
    out: Dict[str, Tuple[int, ...]] = {}
    scopes: List[Tuple[ast.AST, Dict[str, Tuple[int, ...]]]] = [
        (ctx.tree, _splat_dicts(ctx.tree))]
    for fn in _shared.iter_functions(ctx.tree):
        scopes.append((fn, _splat_dicts(fn)))
    for scope, local_dicts in scopes:
        for node in _shared.walk_without_nested_functions(scope) \
                if scope is not ctx.tree else ast.walk(scope):
            call = None
            targets: List[str] = []
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                call = node.value
                for t in node.targets:
                    bare = _callable_bare_name(t)
                    if bare:
                        targets.append(bare)
            elif isinstance(node, ast.Call):
                call = node
            if call is None or ctx.call_name(call) not in _JIT_NAMES:
                continue
            pos = _donate_positions(call, ctx, local_dicts)
            if pos is None:
                continue
            if call.args and (bare := _callable_bare_name(call.args[0])):
                out[bare] = pos
            for t in targets:
                out[t] = pos
    return out


# ----------------------------------------------------------------------
# Registry declarations (CAT001 substrate)
# ----------------------------------------------------------------------

@dataclasses.dataclass
class CounterDecl:
    path: str
    node: ast.AST                  # the CATALOG assignment
    constants: Dict[str, str]      # NAME -> key string
    catalog: List[str]             # evaluated CATALOG order
    prefixes: Set[str]             # declared dynamic-key prefixes


@dataclasses.dataclass
class KnobDecl:
    path: str
    specs: Dict[str, Tuple[object, object]]    # env -> (lo, hi)
    kinds: Dict[str, str]                      # env -> kind
    operational: Set[str]


def _parse_counters_module(ctx: ModuleContext) -> Optional[CounterDecl]:
    consts = module_string_constants(ctx)
    cat_node = None
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == "CATALOG":
            cat_node = stmt
    if cat_node is None:
        return None
    cat = const_eval(cat_node.value, consts)
    if not isinstance(cat, tuple) or \
            not all(isinstance(k, str) for k in cat):
        return None
    prefixes = {v for v in consts.values() if v.endswith(".")}
    return CounterDecl(ctx.path, cat_node, consts, list(cat), prefixes)


def _parse_knobs_module(ctx: ModuleContext) -> Optional[KnobDecl]:
    consts = module_string_constants(ctx)
    specs: Dict[str, Tuple[object, object]] = {}
    kinds: Dict[str, str] = {}
    operational: Set[str] = set()
    found = False
    for stmt in ctx.tree.body:
        if not (isinstance(stmt, ast.Assign) or
                isinstance(stmt, ast.AnnAssign)):
            continue
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        value = stmt.value
        if value is None or len(targets) != 1 or \
                not isinstance(targets[0], ast.Name):
            continue
        tname = targets[0].id
        if tname == "KNOBS" and isinstance(value, ast.Tuple):
            for el in value.elts:
                if not (isinstance(el, ast.Call) and
                        isinstance(el.func, ast.Name) and
                        el.func.id == "KnobSpec" and len(el.args) >= 5):
                    continue
                env = const_eval(el.args[0], consts)
                kind = const_eval(el.args[1], consts)
                lo = const_eval(el.args[3], consts)
                hi = const_eval(el.args[4], consts)
                if isinstance(env, str):
                    specs[env] = (lo, hi)
                    kinds[env] = kind if isinstance(kind, str) else ""
                    found = True
        elif tname == "OPERATIONAL_ENVS" and isinstance(value, ast.Dict):
            for k in value.keys:
                kv = const_eval(k, consts)
                if isinstance(kv, str):
                    operational.add(kv)
            found = True
    if not found:
        return None
    return KnobDecl(ctx.path, specs, kinds, operational)


def _parse_config_fields(ctx: ModuleContext) -> Set[str]:
    """``SENTINEL_TPU_<FIELD>`` env keys derivable from the
    ``SentinelConfig`` dataclass fields (core/config.py)."""
    out: Set[str] = set()
    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "SentinelConfig":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    out.add("SENTINEL_TPU_" + stmt.target.id.upper())
    return out


# ----------------------------------------------------------------------
# The index
# ----------------------------------------------------------------------

class ProjectIndex:
    """Everything pass 2 needs, built once over all parsed modules."""

    def __init__(self, contexts: Sequence[ModuleContext]):
        self.classes: List[ClassIndex] = []
        self.module_constants: Dict[str, Dict[str, str]] = {}
        self.donating: Dict[str, Tuple[int, ...]] = {}
        self.counters: Optional[CounterDecl] = None
        self.knobs: Optional[KnobDecl] = None
        self.config_field_envs: Set[str] = set()
        graph: Dict[str, Set[str]] = {}
        thread_seeds: Set[str] = set()

        for ctx in contexts:
            self.module_constants[ctx.module_name] = \
                module_string_constants(ctx)
            self.donating.update(_donating_callables(ctx))
            thread_seeds |= _thread_target_names(ctx)
            _call_graph(ctx, graph)
            for node in ctx.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._index_class(ctx, node, thread_seeds)
            base = ctx.path.replace("\\", "/").rsplit("/", 1)[-1]
            if base == "counters.py" and self.counters is None:
                self.counters = _parse_counters_module(ctx)
            elif base == "knobs.py" and self.knobs is None:
                self.knobs = _parse_knobs_module(ctx)
            elif base == "config.py":
                self.config_field_envs |= _parse_config_fields(ctx)

        self.call_graph = graph
        self.thread_entry_names = thread_seeds
        self.thread_reachable = _transitive_closure(thread_seeds, graph)

    def _index_class(self, ctx: ModuleContext, node: ast.ClassDef,
                     thread_seeds: Set[str]) -> None:
        bases = tuple(b for b in (ctx.dotted(x) for x in node.bases) if b)
        cls = ClassIndex(
            name=node.name, module=ctx.module_name, path=ctx.path,
            node=node, bases=bases,
            methods={s.name: s for s in node.body
                     if isinstance(s, _shared.FUNC_NODES)},
            accesses=[])
        _ClassWalker(ctx, cls).run(node)
        self.classes.append(cls)
        if any(b in _THREAD_BASES for b in bases) and "run" in cls.methods:
            thread_seeds.add("run")

    # ------------------------------------------------------------------
    def classes_in(self, path: str) -> List[ClassIndex]:
        return [c for c in self.classes if c.path == path]

    def resolve_string(self, ctx: ModuleContext,
                       node: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute/Constant to a string constant using
        this module's bindings, import aliases, and every indexed
        module's constants (suffix-matched on the dotted prefix)."""
        if isinstance(node, ast.Constant):
            return node.value if isinstance(node.value, str) else None
        dotted = ctx.dotted(node)
        if dotted is None:
            return None
        local = self.module_constants.get(ctx.module_name, {})
        if dotted in local:
            return local[dotted]
        if "." in dotted:
            mod, leaf = dotted.rsplit(".", 1)
            mod = mod.lstrip(".")
            for mod_name, consts in self.module_constants.items():
                if (mod_name == mod or mod_name.endswith("." + mod)
                        or mod.endswith("." + mod_name)) and leaf in consts:
                    return consts[leaf]
        return None
