"""graftlint — self-hosted AST static analysis for SPMD, trace, and
concurrency safety.

The bug classes PR 1/2 fixed by hand, caught by tooling instead of
reviewers (see ``docs/LINT.md`` for the catalog and rationale):

* **SPMD001** — collectives reachable under process-divergent branches
  (multihost deadlock).
* **DEV001** — import-time device access (backend init before
  ``jax.distributed.initialize``; the PR 1 ``stats/window.py`` class).
* **TRACE001** — host syncs inside jit/shard_map-traced functions.
* **ASYNC001** — blocking calls in coroutines; thread locks held across
  ``await``.
* **LOCK001** — module-level mutable state mutated from both async and
  threaded contexts without a lock.

v2 adds a two-pass project model (pass 1 builds a cross-module
``ProjectIndex``: donation wrap sites, thread-reachability closure,
counter/knob registries; pass 2 runs flow-sensitive per-function
checks, parallelizable with ``--jobs``) and four whole-program rules
for the races that actually shipped:

* **LOCK002** — unlocked read of an inferred lock-guarded attribute
  from a thread-reachable method (the PR 11 ``_seen_idx`` race).
* **DONATE001** — use of a donated operand / staging slot after
  dispatch to a ``donate_argnums`` callable (the PR 16/17 bug shape).
* **ORDER001** — resource freed before the intent record inside a
  locked region (the PR 15 demote TOCTOU).
* **CAT001** — registry drift: counter keys vs ``CATALOG`` and its
  wire-order manifest; ``SENTINEL_*`` env reads and read-site clamps
  vs the knob/config registries.

Usage::

    python -m sentinel_tpu.analysis sentinel_tpu/

Programmatic::

    from sentinel_tpu.analysis import analyze_paths, ALL_RULES
    findings = analyze_paths(["sentinel_tpu/"], ALL_RULES)

This package is intentionally dependency-free (stdlib ``ast`` only): it
parses source, it never imports the modules it analyzes, and no JAX
backend is touched beyond what ``import sentinel_tpu`` itself does.
"""

from sentinel_tpu.analysis.core import (      # noqa: F401
    Finding, ModuleContext, Rule, analyze_paths, analyze_source,
    iter_python_files, parse_suppressions,
)
from sentinel_tpu.analysis.rules import ALL_RULES, RULES_BY_ID  # noqa: F401

__all__ = [
    "Finding", "ModuleContext", "Rule", "analyze_paths", "analyze_source",
    "iter_python_files", "parse_suppressions", "ALL_RULES", "RULES_BY_ID",
]
