"""ORDER001 — free/evict must not precede its pending-intent record.

The PR 15 demote TOCTOU: the tiering ticker freed a registry row
(``evict_name``) *before* recording the demote intent
(``_pending_demote[row] = name`` + shadow-map deletion). A racing
re-intern of the same name in that window classified hot against the
stale shadow entry, and the next drain invalidated the row without
queuing its promotion — silently zeroing a resident key. The shipped
fix is an ordering contract: **inside one locked region, intent lands
before the row is freed.**

This rule checks that contract mechanically over a configurable pair
table: for every call to a free/evict/invalidate primitive inside a
locked region (a lockish ``with`` block, or the whole body of a
``*_locked`` / documented-lock-contract method), any *later* mutation
of the paired pending-intent structure in the same region flags the
free call — the intent should have been recorded first. Intent
mutations are subscript stores, ``setdefault``, and (for shadow-map
style intents) ``del`` / ``pop``.

Aliases are tracked per function: ``evict = getattr(reg, "evict_name",
None)`` (the registry's optional-method idiom) makes later ``evict(...)``
calls count as ``evict_name`` calls.

Known limitations: ordering is by source line within the region —
branch-aware paths (intent in the ``if``, free in the ``else``) are
treated as sequential, which can over-flag mutually exclusive arms;
suppress with the branch argument when that happens. Frees and intent
records split across *different* locked regions of the same method are
not paired (each region is checked independently).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from sentinel_tpu.analysis.core import Finding, ModuleContext, Rule
from sentinel_tpu.analysis.rules import _shared

#: free/evict primitive → pending-intent structures whose mutation must
#: precede it in the same locked region. Extend here when a new
#: free-with-intent protocol ships.
DEFAULT_PAIRS: Dict[str, Tuple[str, ...]] = {
    "evict_name": ("_pending_demote", "_shadow"),
    "invalidate_resource_rows": ("_pending_demote", "_shadow"),
    "release": ("_pending_demote", "_shadow"),
    "free_row": ("_pending_demote", "_shadow"),
}

_INTENT_METHODS = frozenset({"setdefault", "pop", "update"})


class IntentBeforeFreeRule(Rule):
    id = "ORDER001"
    name = "free-before-pending-intent"
    rationale = (
        "freeing/evicting a row before recording its pending-intent "
        "opens the PR 15 TOCTOU: a racing re-intern classifies against "
        "stale state; record intent first, then free")

    def __init__(self, pairs: Optional[Dict[str, Tuple[str, ...]]] = None):
        self.pairs = dict(DEFAULT_PAIRS if pairs is None else pairs)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _shared.iter_functions(ctx.tree):
            aliases = _free_aliases(fn, self.pairs)
            for region in _locked_regions(ctx, fn):
                yield from self._check_region(ctx, region, aliases)

    # ------------------------------------------------------------------
    def _check_region(self, ctx: ModuleContext, region: List[ast.stmt],
                      aliases: Dict[str, str]) -> Iterator[Finding]:
        frees: List[Tuple[int, ast.Call, str]] = []
        intents: List[Tuple[int, str]] = []
        for stmt in region:
            for node in _shared.walk_without_nested_functions(stmt):
                free = _free_call_name(node, aliases, self.pairs)
                if free is not None:
                    frees.append((node.lineno, node, free))
                intent = _intent_mutation(node)
                if intent is not None:
                    intents.append((node.lineno, intent))
            # the region statements themselves can BE the mutation
            intent = _intent_mutation(stmt)
            if intent is not None:
                intents.append((stmt.lineno, intent))
        for line, call, free in frees:
            paired = self.pairs[free]
            late = sorted({i for l, i in intents
                           if l > line and i in paired})
            if late:
                yield self.finding(
                    ctx, call,
                    "'%s' frees state before the paired pending-intent "
                    "(%s mutated at a later line in the same locked "
                    "region) — record intent BEFORE freeing, or a "
                    "racing re-intern classifies against stale state" % (
                        free, ", ".join("'%s'" % i for i in late)))


# ----------------------------------------------------------------------

def _free_aliases(fn: ast.AST, pairs: Dict[str, Tuple[str, ...]]
                  ) -> Dict[str, str]:
    """local alias → free primitive: ``evict = getattr(reg,
    "evict_name", None)`` or ``evict = reg.evict_name``."""
    out: Dict[str, str] = {}
    for node in _shared.walk_without_nested_functions(fn):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        value = node.value
        target = node.targets[0].id
        if isinstance(value, ast.Attribute) and value.attr in pairs:
            out[target] = value.attr
        elif isinstance(value, ast.Call) and \
                isinstance(value.func, ast.Name) and \
                value.func.id == "getattr" and len(value.args) >= 2 and \
                isinstance(value.args[1], ast.Constant) and \
                value.args[1].value in pairs:
            out[target] = value.args[1].value
    return out


def _free_call_name(node: ast.AST, aliases: Dict[str, str],
                    pairs: Dict[str, Tuple[str, ...]]) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    if isinstance(node.func, ast.Attribute) and node.func.attr in pairs:
        return node.func.attr
    if isinstance(node.func, ast.Name):
        if node.func.id in pairs:
            return node.func.id
        if node.func.id in aliases:
            return aliases[node.func.id]
    return None


def _intent_base_attr(expr: ast.AST) -> Optional[str]:
    """``self._pending_demote[row]`` / ``shadow[row]`` → base attr/name."""
    if isinstance(expr, ast.Subscript):
        base = expr.value
        if isinstance(base, ast.Attribute):
            return base.attr
        if isinstance(base, ast.Name):
            return base.id
    return None


def _intent_mutation(node: ast.AST) -> Optional[str]:
    """Name of the intent structure this node mutates, else None."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            base = _intent_base_attr(t)
            if base is not None:
                return base
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            base = _intent_base_attr(t)
            if base is not None:
                return base
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in _INTENT_METHODS:
        recv = node.func.value
        if isinstance(recv, ast.Attribute):
            return recv.attr
        if isinstance(recv, ast.Name):
            return recv.id
    return None


def _locked_regions(ctx: ModuleContext, fn: ast.AST) -> List[List[ast.stmt]]:
    """Statement lists that run under a lock: lockish ``with`` bodies,
    plus the whole body of a method that declares a lock contract by
    name (``*_locked``)."""
    regions: List[List[ast.stmt]] = []
    if getattr(fn, "name", "").endswith("_locked"):
        regions.append(list(fn.body))
    for node in _shared.walk_without_nested_functions(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                _shared.is_lockish(item.context_expr, ctx)
                for item in node.items):
            regions.append(list(node.body))
    return regions
