"""DONATE001 — use of a donated operand / staged slot after dispatch.

Two historical shapes, one contract — *an array handed to the device
is not yours until the dispatch settles*:

* **Donated operands.** The engine's jitted steps donate their state
  operand (``donate_argnums``): after ``out = step(state, x)`` the
  buffers behind ``state`` are the device's scratch. Reading ``state``
  again observes freed/aliased memory (JAX raises on CPU, silently
  corrupts under some async backends). Every legitimate call site
  rebinds (``state = step(state, x)``).
* **Staging slots.** ``_StagingRing.acquire()`` hands out preallocated
  host buffers that a dispatch reads *asynchronously* (deferred
  host→device copy). Rewriting a slot (``pad_into(slot[...], ...)`` or
  a subscript store) after it was passed into a dispatch but before
  ``release(slot)`` / a settle is the PR 16/17 staging-ring bug: the
  in-flight program reads the new batch's bytes.

Detection is flow-sensitive per function over a straight-line
approximation (statements ordered by source line, branches treated as
sequential):

1. Donation provenance comes from pass 1 (:mod:`..project`): every
   ``jax.jit(f, donate_argnums=...)`` wrap site — including the
   ``**kw_d1`` splat-dict idiom inside ``_build_sd_steps`` /
   ``_jitted_steps_cached`` — maps both the wrapped function and the
   assignment target (``self._jit_decide``) to its donated positions,
   propagated through simple re-binds.
2. A call to a donating callable consumes the Name / ``self.attr``
   passed at each donated position — unless the same statement rebinds
   it (the ``state = step(state, ...)`` idiom). Any later read of a
   consumed name before a rebind flags.
3. A slot from ``<ring/staging>.acquire()`` becomes in-flight when it
   (or a view of it: ``v = pad_into(slot[...], ...)`` / ``v =
   slot[...]``) is passed to a donating or dispatch-named callable;
   any later write into the slot before ``release(slot)`` flags.

Any settle-like call (``.settle()`` / ``.result()`` /
``.block_until_ready()`` / ``sync_global_devices``) conservatively
clears all tracked state — after a settle the device has consumed the
operands, so the rule never flags past one. Cross-function settles
(caller settles the returned handle) therefore never false-positive:
the rule only flags *uses*, never a missing settle.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from sentinel_tpu.analysis import project
from sentinel_tpu.analysis.core import Finding, ModuleContext, Rule
from sentinel_tpu.analysis.rules import _shared

_SETTLE_METHODS = frozenset({
    "settle", "result", "block_until_ready", "join", "wait",
})
_SETTLE_CALLS = frozenset({
    "jax.block_until_ready",
    "jax.experimental.multihost_utils.sync_global_devices",
})
#: Callee-name fragments that mark a call as a device dispatch for
#: staged-slot purposes even without known donation provenance.
_DISPATCH_FRAGMENTS = ("step", "decide", "dispatch", "_jit")
#: Writers that fill a buffer in place.
_FILL_CALLS = frozenset({"pad_into", "copyto", "numpy.copyto"})
_RINGISH = ("ring", "staging", "slab")


class UseAfterDispatchRule(Rule):
    id = "DONATE001"
    name = "use-after-dispatch-of-donated-buffer"
    rationale = (
        "a donated operand or acquired staging slot belongs to the "
        "in-flight dispatch until settle/release; touching it early is "
        "the staging-ring rewrite bug (freed/aliased device memory)")

    def prepare(self, contexts) -> None:
        self._index = project.shared_index(contexts)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = getattr(self, "_index", None)
        if index is None:
            index = project.shared_index([ctx])
        donating = dict(index.donating)
        donating.update(project._donating_callables(ctx))
        for fn in _shared.iter_functions(ctx.tree):
            yield from _FunctionScan(self, ctx, donating).run(fn)


class _FunctionScan:
    """One function's straight-line scan. Tracks consumed (donated)
    names, staged slots, slot views, and in-flight slots."""

    def __init__(self, rule: UseAfterDispatchRule, ctx: ModuleContext,
                 donating: Dict[str, Tuple[int, ...]]):
        self.rule = rule
        self.ctx = ctx
        self.donating = donating
        self.consumed: Dict[str, Tuple[str, int]] = {}  # name -> (callee, line)
        self.staged: Set[str] = set()
        self.views: Dict[str, str] = {}                 # view -> slot
        self.inflight: Dict[str, Tuple[str, int]] = {}  # slot -> (callee, line)

    def run(self, fn: ast.AST) -> Iterator[Finding]:
        stmts = sorted(
            (n for n in _shared.walk_without_nested_functions(fn)
             if isinstance(n, ast.stmt)),
            key=lambda n: (n.lineno, n.col_offset))
        for stmt in stmts:
            yield from self._scan_stmt(stmt)

    @staticmethod
    def _own_nodes(stmt: ast.stmt) -> Iterator[ast.AST]:
        """The nodes this statement itself evaluates. Compound statements
        contribute only their header expressions — their body statements
        are scanned individually (each with its own rebind exemptions),
        so walking the whole subtree here would double-process them."""
        if isinstance(stmt, (ast.If, ast.While)):
            roots: List[ast.AST] = [stmt.test]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            roots = [stmt.target, stmt.iter]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            roots = [i.context_expr for i in stmt.items]
            roots += [i.optional_vars for i in stmt.items
                      if i.optional_vars is not None]
        elif isinstance(stmt, (ast.Try, ast.ClassDef) + _shared.FUNC_NODES):
            roots = []
        else:
            roots = [stmt]
        for r in roots:
            yield from ast.walk(r)

    # ------------------------------------------------------------------
    def _scan_stmt(self, stmt: ast.stmt) -> Iterator[Finding]:
        rebinds = self._rebound_names(stmt)
        # 1. flag reads of consumed names (before processing new events,
        #    but a same-statement rebind of that name is the safe idiom)
        yield from self._flag_uses(stmt, rebinds)
        # 2. rebinds kill stale tracking BEFORE this statement's calls
        #    are processed — ``slot = ring.acquire()`` must end with the
        #    fresh staging, not have it killed by its own rebind
        for name in rebinds:
            self.consumed.pop(name, None)
            if name in self.views:
                del self.views[name]
            if name in self.staged:
                self.staged.discard(name)
                self.inflight.pop(name, None)
        # 3. process calls in this statement: settles, releases,
        #    dispatches, acquires, view bindings
        for node in self._own_nodes(stmt):
            if isinstance(node, ast.Call):
                self._process_call(node, stmt)

    def _flag_uses(self, stmt: ast.stmt,
                   rebinds: Set[str]) -> Iterator[Finding]:
        for node in self._own_nodes(stmt):
            key = _ref_key(node)
            if key is None:
                continue
            if key in self.consumed and key not in rebinds:
                callee, line = self.consumed[key]
                # the consuming call itself re-walks here; skip nodes on
                # the consuming line
                if node.lineno == line:
                    continue
                yield self.rule.finding(
                    self.ctx, node,
                    "'%s' was donated to '%s' (line %d) and is %s here "
                    "before any settle — the buffer belongs to the "
                    "in-flight dispatch; use the returned value or "
                    "settle first" % (
                        key, callee, line,
                        "written" if isinstance(
                            getattr(node, "ctx", None), ast.Store)
                        else "read"))
                del self.consumed[key]        # one finding per donation
        # slot rewrites: subscript store into an in-flight slot, or an
        # in-place fill call targeting it
        for node in self._own_nodes(stmt):
            slot = self._written_slot(node)
            if slot is not None and slot in self.inflight:
                callee, line = self.inflight[slot]
                yield self.rule.finding(
                    self.ctx, node,
                    "staging slot '%s' is rewritten here while the "
                    "dispatch through '%s' (line %d) may still read it "
                    "— release the slot on settlement first (the "
                    "PR 16/17 staging-ring bug)" % (slot, callee, line))
                del self.inflight[slot]

    # ------------------------------------------------------------------
    def _process_call(self, call: ast.Call, stmt: ast.stmt) -> None:
        name = self.ctx.call_name(call)
        attr = call.func.attr if isinstance(call.func, ast.Attribute) \
            else None
        # settle: conservatively clears everything
        if attr in _SETTLE_METHODS or name in _SETTLE_CALLS:
            self.consumed.clear()
            self.inflight.clear()
            return
        # release(slot)
        if attr == "release" or (isinstance(call.func, ast.Name)
                                 and call.func.id == "release"):
            for arg in call.args:
                key = _ref_key(arg)
                if key is not None:
                    self.inflight.pop(key, None)
                    self.staged.discard(key)
            return
        # slot = ring.acquire()
        if attr == "acquire" and self._ringish_receiver(call.func.value):
            target = _assign_target(stmt, call)
            if target is not None:
                self.staged.add(target)
                self.inflight.pop(target, None)
            return
        # view = pad_into(slot[...], ...) / plain slot subscript binding
        if name is not None and name.rsplit(".", 1)[-1] in _FILL_CALLS:
            slot = self._slot_of_args(call.args[:1])
            if slot is not None:
                target = _assign_target(stmt, call)
                if target is not None:
                    self.views[target] = slot
            return
        # donation / dispatch
        bare = (attr or (call.func.id if isinstance(call.func, ast.Name)
                         else None))
        positions = None
        if bare is not None and bare in self.donating:
            positions = self.donating[bare]
        if positions is not None:
            rebinds = self._rebound_names(stmt)
            for pos in positions:
                if pos < len(call.args):
                    key = _ref_key(call.args[pos])
                    if key is not None and key not in rebinds:
                        self.consumed[key] = (bare, call.lineno)
        if positions is not None or self._dispatchish(bare):
            slot = self._slot_of_args(call.args) or \
                self._slot_of_args(kw.value for kw in call.keywords)
            if slot is not None:
                self.inflight.setdefault(slot, (bare or "<call>",
                                                call.lineno))

    # ------------------------------------------------------------------
    def _ringish_receiver(self, recv: ast.AST) -> bool:
        dotted = self.ctx.dotted(recv)
        if dotted is None:
            return False
        low = dotted.lower()
        return any(tok in low for tok in _RINGISH)

    def _dispatchish(self, bare: Optional[str]) -> bool:
        if bare is None:
            return False
        low = bare.lower()
        return any(tok in low for tok in _DISPATCH_FRAGMENTS)

    def _slot_of_args(self, args) -> Optional[str]:
        """First staged slot referenced by these argument expressions
        (directly, via subscript, or via a recorded view name)."""
        for arg in args:
            for node in ast.walk(arg if isinstance(arg, ast.AST) else arg):
                if isinstance(node, ast.Name):
                    if node.id in self.staged:
                        return node.id
                    if node.id in self.views:
                        return self.views[node.id]
        return None

    def _written_slot(self, node: ast.AST) -> Optional[str]:
        """slot for ``slot[...] = x`` / ``slot["c"][...] = x`` stores and
        in-place fill calls (``pad_into(slot[...], ...)``)."""
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Name) and base.id in self.staged:
                        return base.id
        elif isinstance(node, ast.Call):
            name = self.ctx.call_name(node)
            if name is not None and \
                    name.rsplit(".", 1)[-1] in _FILL_CALLS and node.args:
                return self._slot_of_args(node.args[:1])
        return None

    def _rebound_names(self, stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            targets = [stmt.target]
        for t in targets:
            key = _ref_key(t)
            if key is not None:
                out.add(key)
            for n in ast.walk(t):
                k = _ref_key(n)
                if k is not None:
                    out.add(k)
        return out


def _ref_key(node: ast.AST) -> Optional[str]:
    """Canonical tracking key: bare Name → ``x``; ``self.attr`` →
    ``self.attr``. Other expressions don't track."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return "self." + node.attr
    return None


def _assign_target(stmt: ast.stmt, call: ast.Call) -> Optional[str]:
    """Name the statement binds the call's result to, if any."""
    if isinstance(stmt, ast.Assign) and stmt.value is call and \
            len(stmt.targets) == 1:
        return _ref_key(stmt.targets[0])
    return None
