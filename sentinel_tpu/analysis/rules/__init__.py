"""graftlint rule registry.

Order here is presentation order in ``--list-rules``; rule ids are
stable API (suppression comments reference them).
"""

from __future__ import annotations

from typing import Dict, List

from sentinel_tpu.analysis.core import Rule
from sentinel_tpu.analysis.rules.spmd import SpmdRule
from sentinel_tpu.analysis.rules.device import DeviceImportRule
from sentinel_tpu.analysis.rules.trace import TraceHygieneRule
from sentinel_tpu.analysis.rules.async_block import AsyncBlockingRule
from sentinel_tpu.analysis.rules.locks import SharedStateRule
from sentinel_tpu.analysis.rules.lockdiscipline import LockDisciplineRule
from sentinel_tpu.analysis.rules.donate import UseAfterDispatchRule
from sentinel_tpu.analysis.rules.order import IntentBeforeFreeRule
from sentinel_tpu.analysis.rules.registry import RegistryDriftRule

ALL_RULES: List[Rule] = [
    SpmdRule(),
    DeviceImportRule(),
    TraceHygieneRule(),
    AsyncBlockingRule(),
    SharedStateRule(),
    LockDisciplineRule(),
    UseAfterDispatchRule(),
    IntentBeforeFreeRule(),
    RegistryDriftRule(),
]

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in ALL_RULES}
