"""ASYNC001 — blocking calls in coroutines; locks held across ``await``.

The transport/cluster/dashboard servers run single-threaded event loops
fronting a device engine: one blocking call in a coroutine stalls every
connection on that loop (the cluster batcher already routes engine steps
through ``asyncio.to_thread`` for exactly this reason). Two shapes:

1. a known-blocking call (``time.sleep``, sync sockets/HTTP/subprocess)
   lexically inside an ``async def`` — nested sync ``def``s are excluded
   (they may legitimately run via ``to_thread``);
2. a synchronous ``with <lock>`` whose body contains ``await``: the
   coroutine parks holding a *thread* lock, and the next thread that
   wants it blocks the whole loop (classic async-deadlock shape).
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from sentinel_tpu.analysis.core import Finding, ModuleContext, Rule
from sentinel_tpu.analysis.rules import _shared

BLOCKING_EXACT = frozenset({
    "time.sleep",
    "socket.create_connection", "socket.getaddrinfo", "socket.socket",
    "os.system", "os.waitpid", "os.wait",
    "select.select",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
    "urllib.request.urlopen",
})

BLOCKING_PREFIXES = (
    "requests.",
    "http.client.",
)

#: Codebase-tuned: the engine/token-client decision surfaces are blocking
#: host→device round-trips (or socket RPCs) — and on a multi-process mesh
#: a *collective*. Coroutines must route them through asyncio.to_thread
#: the way cluster/server.py's batcher does (await to_thread(engine.f, ...)
#: passes the method as a value, which this rule correctly ignores).
BLOCKING_SUFFIXES = (
    ".request_tokens", ".request_param_tokens",
    ".request_tokens_batch", ".request_param_tokens_batch",
    ".request_token", ".request_param_token",
)

_ASYNC_ALTERNATIVE = {
    "time.sleep": "await asyncio.sleep(...)",
}


class AsyncBlockingRule(Rule):
    id = "ASYNC001"
    name = "blocking-call-in-coroutine"
    rationale = (
        "one blocking call in a coroutine stalls every connection on "
        "the event loop; route through asyncio primitives or "
        "asyncio.to_thread")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _shared.iter_functions(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            yield from self._scan_coroutine(ctx, fn)

    def _scan_coroutine(self, ctx: ModuleContext, fn) -> Iterator[Finding]:
        for node in _shared.walk_without_nested_functions(fn):
            if isinstance(node, ast.Call):
                name = ctx.call_name(node)
                if _shared.name_matches(name, exact=BLOCKING_EXACT,
                                        prefixes=BLOCKING_PREFIXES,
                                        suffixes=BLOCKING_SUFFIXES):
                    alt = _ASYNC_ALTERNATIVE.get(name, "asyncio.to_thread")
                    yield self.finding(
                        ctx, node,
                        "blocking '%s' inside coroutine '%s' stalls the "
                        "event loop; use %s" % (name, fn.name, alt))
            elif isinstance(node, ast.With):
                if any(_shared.is_lockish(i.context_expr, ctx)
                       for i in node.items) and _holds_await(node):
                    yield self.finding(
                        ctx, node,
                        "thread lock held across 'await' in coroutine "
                        "'%s': the parked coroutine keeps the lock and "
                        "any thread contending for it blocks the loop; "
                        "narrow the critical section or use "
                        "asyncio.Lock" % fn.name)


def _holds_await(with_node: ast.With) -> bool:
    for stmt in with_node.body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                return True
            if isinstance(node, _shared.FUNC_NODES + (ast.Lambda,)):
                break
    return False
