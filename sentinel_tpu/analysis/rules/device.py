"""DEV001 — import-time device access.

The PR 1 regression class: ``stats/window.py`` once held a module-scope
``jnp.int32(...)`` constant. Materializing any device value at import
initializes the JAX backend — and backend initialization MUST NOT happen
before ``jax.distributed.initialize`` (multihost/bootstrap.py), which a
mere ``import sentinel_tpu.stats.window`` would otherwise race. The fix
pattern is a ``np.int32``/plain-Python constant at module scope and
device placement at first use.

Import-time contexts scanned: module body, class bodies, function
decorators, and function default arguments. ``jax.jit``/``jax.vmap`` at
module scope are fine (tracing is lazy); ``jnp.iinfo``/``jnp.finfo`` and
dtype *references* are metadata and fine. Every other ``jax.numpy.*``
call — and the explicit backend probes below — flags.
"""

from __future__ import annotations

import ast
from typing import Iterator

from sentinel_tpu.analysis.core import Finding, ModuleContext, Rule
from sentinel_tpu.analysis.rules import _shared

#: jax.numpy entry points that only inspect dtypes/metadata (no backend).
SAFE_JNP = frozenset({
    "iinfo", "finfo", "dtype", "result_type", "promote_types",
    "issubdtype", "shape", "ndim", "size",
})

#: Explicit backend-initializing / device-touching calls.
BACKEND_EXACT = frozenset({
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.device_put", "jax.device_get",
    "jax.process_index", "jax.process_count", "jax.default_backend",
    "jax.make_mesh", "jax.live_arrays", "jax.block_until_ready",
})

BACKEND_PREFIXES = (
    "jax.random.",                 # PRNGKey materializes a device array
    "jax.experimental.multihost_utils.",
)


class DeviceImportRule(Rule):
    id = "DEV001"
    name = "import-time-device-access"
    rationale = (
        "a device value materialized at import initializes the JAX "
        "backend before jax.distributed.initialize can run, breaking "
        "every multi-process entry point that imports the module")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in _shared.iter_import_time_nodes(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.call_name(node)
            if name is None:
                continue
            if name.startswith("jax.numpy."):
                tail = name.split(".", 2)[2]
                if tail.split(".")[0] in SAFE_JNP:
                    continue
                yield self.finding(
                    ctx, node,
                    "module-scope '%s' materializes a device constant at "
                    "import (initializes the backend before "
                    "jax.distributed.initialize; keep host constants in "
                    "numpy and device_put at first use)" % name)
            elif name in BACKEND_EXACT or name.startswith(BACKEND_PREFIXES):
                yield self.finding(
                    ctx, node,
                    "'%s' at import time touches the device backend; "
                    "defer it into a function that runs after "
                    "multihost bootstrap" % name)
