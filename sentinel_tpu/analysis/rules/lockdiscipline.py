"""LOCK002 — inferred lock discipline for instance attributes.

The PR 11 over-admission race shipped exactly this shape: ``_seen_idx``
was *written* under ``self._lock`` everywhere (the discipline is
obvious from the code), but one fast-path flush *read* it outside the
lock, and a decide landing between that read and the locked restamp
resurrected spent admission budget mid-window. The discipline was
real — it just wasn't checkable. This rule makes it checkable by
inference instead of annotation:

1. For every class, collect each ``self.<attr>`` store and the set of
   locks held at that point (pass 1, :mod:`..project`). An attribute
   written under the same ``self.*`` lock in **≥ 2 distinct sites**
   (outside ``__init__``) is treated as lock-guarded — two locked
   writes are the author declaring a discipline, not a coincidence.
2. Every read or write of a guarded attribute that holds *none* of the
   attribute's guard locks is flagged — but only in methods reachable
   from a thread entry point (``threading.Thread(target=...)``,
   ``Timer``, ``executor.submit``, ``asyncio.to_thread``,
   ``run_in_executor``, ``run`` of a Thread subclass), closed over the
   project's name-based call graph. A class no thread can reach is
   single-threaded by construction and stays silent.

Escape hatches (both are *documented contracts*, not suppressions):
a method named ``*_locked`` or whose docstring declares "callers hold
``_lock``" is treated as running under the lock — the repo's existing
idiom for helpers with a locking precondition. Anything else needs a
``# graftlint: disable=LOCK002 -- <why>`` with the actual argument for
why the unlocked access is safe (seqlock read, monotonic flag, ...).

Known limitations: the guard inference is name-based per class (two
locks with the same attribute name in different classes are distinct,
but re-entrant acquisition through a helper is invisible); reachability
is call-graph-by-name, so a method name shared with an unrelated
threaded function is conservatively treated as reachable.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Set

from sentinel_tpu.analysis import project
from sentinel_tpu.analysis.core import Finding, ModuleContext, Rule

#: Locked-write sites required before an attribute counts as guarded.
MIN_GUARDED_WRITES = 2


class LockDisciplineRule(Rule):
    id = "LOCK002"
    name = "guarded-attribute-accessed-outside-lock"
    rationale = (
        "an attribute written under self._lock in 2+ sites has an "
        "inferred lock discipline; reading or writing it without the "
        "lock from thread-reachable code is the PR 11 over-admission "
        "race shape")

    def prepare(self, contexts) -> None:
        self._index = project.shared_index(contexts)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = getattr(self, "_index", None)
        if index is None:
            index = project.shared_index([ctx])
        for cls in index.classes_in(ctx.path):
            yield from self._check_class(ctx, index, cls)

    # ------------------------------------------------------------------
    def _check_class(self, ctx: ModuleContext, index: project.ProjectIndex,
                     cls: project.ClassIndex) -> Iterator[Finding]:
        guards = self._guarded_attrs(cls)
        if not guards:
            return
        contract = cls.lock_contract_methods()
        reachable = index.thread_reachable
        for acc in cls.accesses:
            locks = guards.get(acc.attr)
            if locks is None:
                continue
            if acc.method in project.CONSTRUCTION_METHODS or \
                    acc.method in contract:
                continue
            if acc.locks_held & locks:
                continue
            if acc.method not in reachable:
                continue
            yield self.finding(
                ctx, acc.node,
                "'self.%s' %s outside %s in thread-reachable method "
                "'%s.%s' — %d locked write site(s) establish the lock "
                "discipline; hold the lock here or document the "
                "contract (method docstring / *_locked name)" % (
                    acc.attr,
                    "written" if acc.is_store else "read",
                    " / ".join("self.%s" % l for l in sorted(locks)),
                    cls.name, acc.method,
                    self._site_counts[acc.attr]))

    def _guarded_attrs(self, cls: project.ClassIndex) -> Dict[str, Set[str]]:
        """attr → guard-lock names, for attrs with ≥2 locked writes."""
        locked_sites: Dict[str, List] = {}
        locks_of: Dict[str, Set[str]] = {}
        for acc in cls.accesses:
            low = acc.attr.lower()
            if "lock" in low or "mutex" in low or "semaphore" in low:
                continue                      # locks themselves never flag
            if acc.is_store and acc.locks_held and \
                    acc.method not in project.CONSTRUCTION_METHODS:
                locked_sites.setdefault(acc.attr, []).append(acc.node)
                locks_of.setdefault(acc.attr, set()).update(acc.locks_held)
        self._site_counts = {a: len(s) for a, s in locked_sites.items()}
        return {a: locks_of[a] for a, sites in locked_sites.items()
                if len(sites) >= MIN_GUARDED_WRITES}
