"""AST helpers shared by the graftlint rule families."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from sentinel_tpu.analysis.core import ModuleContext

FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def iter_import_time_nodes(tree: ast.Module) -> Iterator[ast.AST]:
    """Every AST node whose expression evaluates at *import time*: module
    body, class bodies, function decorators, and function default
    arguments — but NOT function/lambda bodies (those run at call time)."""

    def walk(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FUNC_NODES):
                for d in child.decorator_list:
                    yield from ast.walk(d)
                args = child.args
                for dflt in list(args.defaults) + [
                        d for d in args.kw_defaults if d is not None]:
                    yield from ast.walk(dflt)
            elif isinstance(child, ast.Lambda):
                continue
            elif isinstance(child, ast.ClassDef):
                yield child
                yield from walk(child)
            else:
                yield child
                yield from walk(child)

    yield from walk(tree)


def iter_functions(tree: ast.Module):
    """All function definitions (sync and async), at any nesting level."""
    for node in ast.walk(tree):
        if isinstance(node, FUNC_NODES):
            yield node


def walk_without_nested_functions(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node``'s body but stop at nested function/class boundaries
    (their bodies run in a different execution context)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, FUNC_NODES + (ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(n))


def name_matches(dotted: Optional[str], exact=(), prefixes=(),
                 suffixes=()) -> bool:
    if dotted is None:
        return False
    if dotted in exact:
        return True
    if any(dotted.startswith(p) for p in prefixes):
        return True
    if any(dotted.endswith(s) for s in suffixes):
        return True
    return False


def enclosing_with_lock(ancestors: List[ast.AST],
                        ctx: ModuleContext) -> bool:
    """True when any enclosing ``with``/``async with`` in ``ancestors``
    acquires something lock-like (dotted name mentioning lock/mutex)."""
    for anc in ancestors:
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                if is_lockish(item.context_expr, ctx):
                    return True
    return False


def is_lockish(expr: ast.AST, ctx: ModuleContext) -> bool:
    """Heuristic: does this with-item expression acquire a lock?

    Catches ``self._lock``, ``self._state_lock``, ``REGISTRY_LOCK``,
    ``lock.acquire_timeout(...)``, ``threading.Lock()`` — any dotted
    chain (or call on one) whose text mentions lock/mutex/semaphore.
    """
    if isinstance(expr, ast.Call):
        expr = expr.func
    dotted = ctx.dotted(expr)
    if dotted is None:
        return False
    low = dotted.lower()
    return any(tok in low for tok in ("lock", "mutex", "semaphore"))


class AncestorVisitor:
    """Generic walk that maintains the ancestor stack. Subclass and
    override ``visit(node, ancestors)``; return False to skip children."""

    def run(self, root: ast.AST) -> None:
        self._walk(root, [])

    def _walk(self, node: ast.AST, ancestors: List[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            if self.visit(child, ancestors) is not False:
                ancestors.append(child)
                self._walk(child, ancestors)
                ancestors.pop()

    def visit(self, node: ast.AST, ancestors: List[ast.AST]):
        raise NotImplementedError


def terminates_block(stmts: List[ast.stmt]) -> bool:
    """Does this statement list end by leaving the enclosing function or
    loop iteration (return/raise/continue/break)?"""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def assigned_names(target: ast.AST) -> Iterator[str]:
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            yield n.id
