"""TRACE001 — host synchronization inside traced (jit/shard_map) code.

Inside a function that JAX traces, ``.item()`` / ``float()`` / ``bool()``
on a traced array, ``np.asarray``, and Python ``if`` on an array-valued
expression either fail at trace time or — worse — silently bake a
trace-time constant into the compiled program and sync the device
pipeline. The hot paths (engine step functions) must stay pure.

Traced-function discovery is two-pass:

1. decorator-based — ``@jax.jit``, ``@jit``, ``@partial(jax.jit, ...)``,
   ``@shard_map(...)`` / ``@partial(shard_map, ...)``;
2. wrap-site-based — a local ``def f`` whose *name* is later passed to
   ``jax.jit(f)`` / ``shard_map(f, ...)`` anywhere in the module.

Nested defs inside a traced function are traced too.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from sentinel_tpu.analysis.core import Finding, ModuleContext, Rule
from sentinel_tpu.analysis.rules import _shared

_TRACER_WRAPPERS = frozenset({
    "jax.jit", "jit", "jax.pmap",
    "jax.experimental.shard_map.shard_map", "jax.shard_map", "shard_map",
    # repo idiom: parallel/cluster.py's version-compat shard_map wrapper
    "_shard_map",
})

#: numpy metadata calls that never touch array *values*.
_SAFE_NP = frozenset({"iinfo", "finfo", "dtype"})

_HOST_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})


class TraceHygieneRule(Rule):
    id = "TRACE001"
    name = "host-sync-in-traced-code"
    rationale = (
        "host syncs inside jit/shard_map either raise TracerError or "
        "freeze a trace-time value into the compiled program; branches "
        "on array values must become lax.cond/jnp.where")

    def prepare(self, contexts) -> None:
        # Cross-module wrap sites: runtime.py does jax.jit(record_exits)
        # on a function *defined* in stats/pipeline.py — record
        # (defining module → function name) so the defining module scans
        # it as traced code.
        self._cross: dict = {}
        for ctx in contexts:
            for target in _wrap_site_targets(ctx):
                if "." in target:
                    mod, fn = target.rsplit(".", 1)
                    self._cross.setdefault(mod, set()).add(fn)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        wrapped = {t for t in _wrap_site_targets(ctx) if "." not in t}
        mod_name = ctx.module_name
        for mod, fns in getattr(self, "_cross", {}).items():
            if (mod_name == mod or mod_name.endswith("." + mod)
                    or mod.endswith("." + mod_name)):
                wrapped |= fns
        for fn in _traced_functions(ctx, wrapped):
            yield from self._scan(ctx, fn)

    # ------------------------------------------------------------------
    def _scan(self, ctx: ModuleContext, fn) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = ctx.call_name(node)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _HOST_SYNC_METHODS):
                    yield self.finding(
                        ctx, node,
                        "'.%s()' inside traced function '%s' forces a "
                        "host sync (TracerError under jit)" % (
                            node.func.attr, fn.name))
                elif name in ("float", "int", "bool") and node.args and \
                        not isinstance(node.args[0], ast.Constant) and \
                        not _static_valued(node.args[0]):
                    yield self.finding(
                        ctx, node,
                        "'%s(...)' on a non-literal inside traced "
                        "function '%s' concretizes a traced value" % (
                            name, fn.name))
                elif (name is not None and name.startswith("numpy.")
                      and name.split(".")[1] not in _SAFE_NP):
                    yield self.finding(
                        ctx, node,
                        "'%s' inside traced function '%s' pulls the "
                        "value to host; use jax.numpy" % (name, fn.name))
            elif isinstance(node, (ast.If, ast.While)) and \
                    _array_valued(node.test, ctx):
                yield self.finding(
                    ctx, node,
                    "Python branch on an array-valued expression inside "
                    "traced function '%s'; use lax.cond/lax.select or "
                    "jnp.where" % fn.name)


def _static_valued(arg: ast.AST) -> bool:
    """``int(x.shape[0])`` / ``float(len(xs))`` concretize *static* trace
    metadata, which is legal under jit — don't flag those."""
    for node in ast.walk(arg):
        if isinstance(node, ast.Attribute) and node.attr in (
                "shape", "ndim", "size", "dtype"):
            return True
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and node.func.id == "len":
            return True
    return False


def _array_valued(test: ast.AST, ctx: ModuleContext) -> bool:
    """Conservative: the test computes an array (jnp/lax call or
    .any()/.all()/.item() method) — static config attributes and plain
    names do NOT flag."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            name = ctx.call_name(node)
            if name is not None and name.startswith(
                    ("jax.numpy.", "jax.lax.")):
                return True
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("any", "all", "item"):
                return True
    return False


def _wrap_site_targets(ctx: ModuleContext) -> Set[str]:
    """Dotted names of functions passed to jax.jit(...) / shard_map(...),
    including through one level of ``functools.partial`` — directly
    (``jax.jit(partial(f, spec))``) or via an intermediate variable
    (``body = partial(f, spec); shard_map(body, ...)``). A local ``def``
    yields its bare name; an imported function yields its fully-qualified
    dotted path (consumed by the cross-module ``prepare`` pass)."""
    partial_of: dict = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            inner = _partial_target(node.value, ctx)
            if inner is not None:
                partial_of[node.targets[0].id] = inner
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and \
                ctx.call_name(node) in _TRACER_WRAPPERS:
            for arg in node.args[:1]:
                target = None
                if isinstance(arg, ast.Name) and arg.id in partial_of:
                    target = partial_of[arg.id]
                elif isinstance(arg, (ast.Name, ast.Attribute)):
                    target = ctx.dotted(arg)
                elif isinstance(arg, ast.Call):
                    target = _partial_target(arg, ctx)
                if target is not None:
                    out.add(target)
    return out


def _partial_target(value: ast.AST, ctx: ModuleContext):
    """``functools.partial(f, ...)`` → dotted name of ``f``."""
    if isinstance(value, ast.Call) and \
            ctx.call_name(value) in ("functools.partial", "partial") and \
            value.args and isinstance(value.args[0], (ast.Name, ast.Attribute)):
        return ctx.dotted(value.args[0])
    return None


def _traced_functions(ctx: ModuleContext, wrapped: Set[str]):
    traced: List[ast.AST] = []
    for fn in _shared.iter_functions(ctx.tree):
        if fn.name in wrapped or any(
                _is_tracer_decorator(d, ctx) for d in fn.decorator_list):
            traced.append(fn)
    # nested defs inside a traced function trace with it
    seen = set(id(f) for f in traced)
    for fn in list(traced):
        for sub in ast.walk(fn):
            if isinstance(sub, _shared.FUNC_NODES) and id(sub) not in seen:
                seen.add(id(sub))
                traced.append(sub)
    return traced


def _is_tracer_decorator(dec: ast.AST, ctx: ModuleContext) -> bool:
    if isinstance(dec, ast.Call):
        name = ctx.call_name(dec)
        if name in _TRACER_WRAPPERS:
            return True
        if name in ("functools.partial", "partial") and dec.args:
            return ctx.dotted(dec.args[0]) in _TRACER_WRAPPERS
        return False
    return ctx.dotted(dec) in _TRACER_WRAPPERS
