"""SPMD001 — collectives reachable under process-divergent branches.

The multihost deadlock class PR 2 guarded against by hand
(``cluster/server.py`` refuses multi-process engines): a collective —
``psum``, ``process_allgather``, ``shard_map``-launched computation,
``jax.distributed.*`` — is a *rendezvous*: every process in the mesh must
execute it, in the same order, or the mesh hangs. Any collective that is
only reachable when a branch on ``process_index()`` / coordinator-ness /
environment variables goes one way is therefore a deadlock wired in and
waiting for traffic.

Two shapes are detected:

1. **Lexical**: a collective call inside the body (or else-branch) of an
   ``if``/``while``/ternary/short-circuit whose test is process-divergent.
2. **Guard-return**: a process-divergent ``if`` whose body leaves the
   function (``return``/``raise``/``continue``/``break``) followed — later
   in the same suite — by a collective call. Only the surviving processes
   reach the rendezvous.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from sentinel_tpu.analysis.core import Finding, ModuleContext, Rule
from sentinel_tpu.analysis.rules import _shared

#: Fully-qualified collective entry points (exact names).
COLLECTIVE_EXACT = frozenset({
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.psum_scatter", "jax.lax.all_gather", "jax.lax.all_to_all",
    "jax.lax.ppermute", "jax.lax.pshuffle",
    "jax.experimental.shard_map.shard_map", "jax.shard_map",
})

#: Any call under these prefixes is a cross-process rendezvous.
COLLECTIVE_PREFIXES = (
    "jax.experimental.multihost_utils.",
    "jax.distributed.",
)

#: Process-divergent signals inside a branch test.
_DIVERGENT_SUFFIXES = (".process_index", ".is_coordinator")
_DIVERGENT_EXACT = frozenset({
    "jax.process_index", "process_index", "is_coordinator",
    "socket.gethostname", "platform.node", "os.getpid",
})
_DIVERGENT_PREFIXES = ("os.environ", "os.getenv")


class SpmdRule(Rule):
    id = "SPMD001"
    name = "collective-under-divergent-branch"
    rationale = (
        "collectives are rendezvous points: every process must execute "
        "them in lockstep, so one reachable only under a per-process "
        "branch deadlocks the mesh")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        yield from self._lexical(ctx)
        yield from self._guard_return(ctx)

    # ------------------------------------------------------------------
    def _lexical(self, ctx: ModuleContext) -> Iterator[Finding]:
        seen = set()
        for node in ast.walk(ctx.tree):
            branches: List[ast.AST] = []
            if isinstance(node, (ast.If, ast.While)):
                if _divergent(node.test, ctx):
                    branches = list(node.body) + list(getattr(node, "orelse", []))
            elif isinstance(node, ast.IfExp):
                if _divergent(node.test, ctx):
                    branches = [node.body, node.orelse]
            elif isinstance(node, ast.BoolOp):
                if any(_divergent(v, ctx) for v in node.values[:-1]):
                    branches = list(node.values[1:])
            for b in branches:
                for call in ast.walk(b):
                    if isinstance(call, ast.Call) and id(call) not in seen:
                        name = ctx.call_name(call)
                        if _collective(name):
                            seen.add(id(call))
                            yield self.finding(
                                ctx, call,
                                "collective '%s' reachable only under a "
                                "process-divergent branch (test involves "
                                "process_index/coordinator/env); every "
                                "process must reach this rendezvous or the "
                                "mesh deadlocks" % name)

    def _guard_return(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _shared.iter_functions(ctx.tree):
            yield from self._scan_suite(ctx, fn.body, gated=False)

    def _scan_suite(self, ctx: ModuleContext, stmts, gated: bool
                    ) -> Iterator[Finding]:
        for stmt in stmts:
            if gated:
                for call in ast.walk(stmt):
                    if isinstance(call, ast.Call):
                        name = ctx.call_name(call)
                        if _collective(name):
                            yield self.finding(
                                ctx, call,
                                "collective '%s' follows a process-"
                                "divergent early exit above it: processes "
                                "that took the exit never reach this "
                                "rendezvous and the rest hang" % name)
            if (isinstance(stmt, ast.If) and _divergent(stmt.test, ctx)
                    and _shared.terminates_block(stmt.body)
                    and not stmt.orelse):
                gated = True
                continue
            # recurse into nested suites with the current gating state
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub and not isinstance(stmt, _shared.FUNC_NODES):
                    # findings inside nested suites of a gated region were
                    # already reported by the blanket walk above
                    if not gated:
                        yield from self._scan_suite(ctx, sub, gated=False)


def _collective(name) -> bool:
    return _shared.name_matches(
        name, exact=COLLECTIVE_EXACT, prefixes=COLLECTIVE_PREFIXES) or (
        name is not None and name.split(".")[-1] in (
            "psum", "pmean", "pmax", "pmin", "process_allgather",
            "sync_global_devices", "broadcast_one_to_all")
        and not name.startswith(("self.", "cls.")))


def _divergent(test: ast.AST, ctx: ModuleContext) -> bool:
    for node in ast.walk(test):
        name = None
        if isinstance(node, (ast.Attribute, ast.Name)):
            name = ctx.dotted(node)
        elif isinstance(node, ast.Call):
            name = ctx.call_name(node)
        if name is None:
            continue
        if (name in _DIVERGENT_EXACT
                or name.startswith(_DIVERGENT_PREFIXES)
                or any(name.endswith(s) for s in _DIVERGENT_SUFFIXES)):
            return True
    return False
