"""CAT001 — cross-module registry drift, caught at lint time.

Two registries hold the runtime's contract with its operators and its
multihost peers, and both previously relied on *test-pinned* audits to
stay honest:

* **Counter catalog** (``obs/counters.py`` ``CATALOG``): the ordered
  key set IS the wire format of the multihost counter vector — a key
  incremented on the hot path but missing from ``CATALOG`` silently
  drops from pod-wide aggregation; a reordered ``CATALOG`` corrupts
  every mixed-version allgather. The rule resolves each key passed to
  the counter API (``<anything>.counters.add(KEY)`` or a local
  ``counters`` alias) through import aliases and cross-module string
  constants, and flags resolved keys absent from ``CATALOG``. Keys
  built from a declared dynamic prefix (a constant ending in ``.`` —
  ``block_reason.``, ``flight.trigger.``) aggregate through the
  transport surface by design and are skipped. ``CATALOG`` itself is
  checked against the checked-in manifest
  (``obs/counters_catalog.txt``): the manifest must be an exact
  *prefix* of ``CATALOG`` (appended-last ordering), and every new key
  must land in the manifest in the same change.
* **Knob registry** (``tune/knobs.py``): every ``os.environ`` read of
  a ``SENTINEL_*`` key must be declared — a ``KnobSpec``, an
  ``OPERATIONAL_ENVS`` entry, or a ``SENTINEL_TPU_<FIELD>`` config
  mapping — or typos ship silently (the round-11
  ``SENTINEL_PIPLINE_DEPTH`` lesson). Where the read site is one of
  the clamped helpers (``_env_int(env, default, lo, hi)`` /
  ``_env_num(...)``), the literal clamp bounds must equal the
  ``KnobSpec``'s — the drift ``test_tune.py`` pins at runtime, now a
  file:line lint failure.

Both registries are parsed from *source* in pass 1 (never imported);
when the counters/knobs module is outside the analyzed path set, the
corresponding checks stay silent rather than guessing. The manifest
file is the one filesystem input a rule reads (it is declared config,
like the ORDER001 pair table — fixtures carry their own).
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Optional, Sequence, Set

from sentinel_tpu.analysis import project
from sentinel_tpu.analysis.core import Finding, ModuleContext, Rule

MANIFEST_NAME = "counters_catalog.txt"

_ENV_HELPER_PREFIXES = ("_env_",)
_ENV_READ_CALLS = frozenset({"os.environ.get", "os.getenv"})


class RegistryDriftRule(Rule):
    id = "CAT001"
    name = "registry-drift"
    rationale = (
        "counter keys outside CATALOG drop from multihost aggregation "
        "and CATALOG order is the wire format; SENTINEL_* env reads "
        "without a KnobSpec ship typos silently and read-site clamps "
        "must match the registry")

    def prepare(self, contexts: Sequence[ModuleContext]) -> None:
        self._index = project.shared_index(contexts)
        self._manifest: Optional[List[str]] = None
        decl = self._index.counters
        if decl is not None:
            path = os.path.join(os.path.dirname(decl.path) or ".",
                                MANIFEST_NAME)
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    self._manifest = [ln.strip() for ln in fh
                                      if ln.strip()
                                      and not ln.startswith("#")]
            except OSError:
                self._manifest = None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = getattr(self, "_index", None)
        if index is None:
            self.prepare([ctx])
            index = self._index
        decl = index.counters
        if decl is not None and ctx.path == decl.path:
            yield from self._check_manifest(ctx, decl)
        if decl is not None:
            yield from self._check_counter_keys(ctx, index, decl)
        if index.knobs is not None:
            yield from self._check_env_reads(ctx, index)

    # ------------------------------------------------------------------
    # CATALOG vs manifest
    # ------------------------------------------------------------------
    def _check_manifest(self, ctx: ModuleContext,
                        decl: project.CounterDecl) -> Iterator[Finding]:
        if self._manifest is None:
            yield self.finding(
                ctx, decl.node,
                "CATALOG has no checked-in manifest (%s next to this "
                "module) — the append-only wire order is unenforceable "
                "without it; write one line per key, in order"
                % MANIFEST_NAME)
            return
        for i, key in enumerate(self._manifest):
            if i >= len(decl.catalog):
                yield self.finding(
                    ctx, decl.node,
                    "CATALOG lost manifest key '%s' (entry %d) — the "
                    "catalog is append-only; removing or reordering "
                    "keys corrupts mixed-version counter vectors"
                    % (key, i))
                return
            if decl.catalog[i] != key:
                yield self.finding(
                    ctx, decl.node,
                    "CATALOG order diverges from the manifest at entry "
                    "%d: manifest has '%s', CATALOG has '%s' — the "
                    "catalog is append-only (new keys go LAST, and "
                    "into the manifest)" % (i, key, decl.catalog[i]))
                return
        for key in decl.catalog[len(self._manifest):]:
            yield self.finding(
                ctx, decl.node,
                "CATALOG key '%s' is not in the manifest — append it "
                "to %s in the same change (the manifest is the "
                "reviewed wire order)" % (key, MANIFEST_NAME))

    # ------------------------------------------------------------------
    # counter API call sites
    # ------------------------------------------------------------------
    def _check_counter_keys(self, ctx: ModuleContext,
                            index: project.ProjectIndex,
                            decl: project.CounterDecl) -> Iterator[Finding]:
        catalog = set(decl.catalog)
        aliases = _counter_aliases(ctx)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr == "add" and node.args):
                continue
            if not _is_counter_receiver(ctx, node.func.value, aliases):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.BinOp):
                # PREFIX + dynamic: fine when the prefix is declared
                left = index.resolve_string(ctx, arg.left)
                if left is not None and left not in decl.prefixes \
                        and not left.endswith("."):
                    yield self.finding(
                        ctx, node,
                        "counter key built from '%s' which is not a "
                        "declared dynamic prefix (constants ending "
                        "'.') — dynamic keys drop from multihost "
                        "aggregation" % left)
                continue
            key = index.resolve_string(ctx, arg)
            if key is None:
                continue
            if key not in catalog and \
                    not any(key.startswith(p) for p in decl.prefixes):
                yield self.finding(
                    ctx, node,
                    "counter key '%s' is not in counters.CATALOG — "
                    "it will silently drop from the multihost "
                    "aggregation vector; append it to CATALOG (and "
                    "the manifest)" % key)

    # ------------------------------------------------------------------
    # SENTINEL_* env reads
    # ------------------------------------------------------------------
    def _check_env_reads(self, ctx: ModuleContext,
                         index: project.ProjectIndex) -> Iterator[Finding]:
        knobs = index.knobs
        if ctx.path == knobs.path:
            return                      # the registry defines, not reads
        known: Set[str] = (set(knobs.specs) | knobs.operational
                           | index.config_field_envs)
        for node in ast.walk(ctx.tree):
            key = None
            clamp = None
            if isinstance(node, ast.Call):
                name = ctx.call_name(node)
                bare = node.func.id if isinstance(node.func, ast.Name) \
                    else None
                if name in _ENV_READ_CALLS and node.args:
                    key = index.resolve_string(ctx, node.args[0])
                elif bare is not None and \
                        bare.startswith(_ENV_HELPER_PREFIXES) and node.args:
                    key = index.resolve_string(ctx, node.args[0])
                    if key is not None and len(node.args) >= 4:
                        lo = project.const_eval(node.args[2])
                        hi = project.const_eval(node.args[3])
                        if lo is not None and hi is not None:
                            clamp = (lo, hi)
            elif isinstance(node, ast.Subscript) and \
                    ctx.dotted(node.value) == "os.environ":
                key = index.resolve_string(ctx, node.slice)
            if key is None or not key.startswith("SENTINEL_"):
                continue
            if key not in known:
                yield self.finding(
                    ctx, node,
                    "env knob '%s' is read here but declared nowhere — "
                    "add a KnobSpec (tunable) or OPERATIONAL_ENVS entry "
                    "(operational) in tune/knobs.py, or typos of it "
                    "ship silently" % key)
                continue
            spec = knobs.specs.get(key)
            if clamp is not None and spec is not None and \
                    None not in spec and clamp != spec:
                yield self.finding(
                    ctx, node,
                    "read-site clamp [%s, %s] for '%s' disagrees with "
                    "its KnobSpec [%s, %s] in tune/knobs.py — one of "
                    "them is lying to the autotuner" % (
                        clamp[0], clamp[1], key, spec[0], spec[1]))


# ----------------------------------------------------------------------

def _counter_aliases(ctx: ModuleContext) -> Set[str]:
    """Local names bound from a ``.counters`` attribute chain:
    ``counters = self._obs.counters`` → ``counters``."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Attribute) and \
                node.value.attr == "counters":
            out.add(node.targets[0].id)
    return out


def _is_counter_receiver(ctx: ModuleContext, recv: ast.AST,
                         aliases: Set[str]) -> bool:
    if isinstance(recv, ast.Name):
        return recv.id == "counters" or recv.id in aliases
    dotted = ctx.dotted(recv)
    return dotted is not None and dotted.rsplit(".", 1)[-1] == "counters"
