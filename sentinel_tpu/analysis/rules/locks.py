"""LOCK001 — module-level mutable state shared across async + threaded
contexts without a lock.

The runtime spans three execution domains — daemon threads (metric
writers, reconnect loops, the device-step thread), asyncio loops
(transport/cluster/dashboard servers), and plain sync callers. A
module-level dict/list/set mutated from BOTH an ``async def`` (loop
thread) and a plain ``def`` (any thread) is a data race unless every
mutation site holds a lock: CPython dict/list ops are atomic only
individually, and check-then-act sequences interleave.

Only *container mutations* count (subscript/attr assignment, augmented
assignment, mutating method calls, ``global``-rebind); reads don't flag.
A mutation site under any enclosing ``with <lock>`` is protected. The
rule fires only when the same name is mutated in both domains and at
least one site is unprotected — each unprotected site gets a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from sentinel_tpu.analysis.core import Finding, ModuleContext, Rule
from sentinel_tpu.analysis.rules import _shared

_MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "appendleft", "popleft",
})

_MUTABLE_FACTORIES = frozenset({
    "dict", "list", "set", "collections.defaultdict", "defaultdict",
    "collections.OrderedDict", "OrderedDict", "collections.deque", "deque",
})


class SharedStateRule(Rule):
    id = "LOCK001"
    name = "unlocked-cross-context-module-state"
    rationale = (
        "module-level containers mutated from both coroutines and "
        "threads interleave check-then-act sequences; every mutation "
        "site needs the same lock (or the state needs to move into one "
        "owner)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        shared = _module_level_mutables(ctx)
        if not shared:
            return
        # (name) -> list of (site_node, is_async_ctx, protected)
        sites: Dict[str, List[Tuple[ast.AST, bool, bool]]] = {}
        collector = _SiteCollector(ctx, shared, sites)
        collector.run(ctx.tree)
        for name, lst in sites.items():
            domains = {is_async for (_, is_async, _) in lst}
            if len(domains) < 2:
                continue
            for node, is_async, protected in lst:
                if not protected:
                    yield self.finding(
                        ctx, node,
                        "module-level '%s' mutated here (%s context) and "
                        "also from %s context; this site holds no lock"
                        % (name,
                           "async" if is_async else "threaded",
                           "threaded" if is_async else "async"))


def _module_level_mutables(ctx: ModuleContext) -> Set[str]:
    out: Set[str] = set()
    for stmt in ctx.tree.body:
        targets: List[ast.expr] = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                              ast.ListComp, ast.SetComp)):
            mutable = True
        elif isinstance(value, ast.Call):
            mutable = ctx.call_name(value) in _MUTABLE_FACTORIES
        else:
            mutable = False
        if mutable:
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


class _SiteCollector(_shared.AncestorVisitor):
    """Collect mutation sites of the shared names, tagged with execution
    domain (inside async def vs sync def) and lock protection."""

    def __init__(self, ctx, shared, sites):
        self.ctx = ctx
        self.shared = shared
        self.sites = sites

    def visit(self, node, ancestors):
        name = self._mutated_name(node)
        if name is not None and name in self.shared and \
                not self._is_local(name, ancestors):
            is_async = any(isinstance(a, ast.AsyncFunctionDef)
                           for a in ancestors) or False
            in_fn = any(isinstance(a, _shared.FUNC_NODES) for a in ancestors)
            if in_fn:
                protected = _shared.enclosing_with_lock(ancestors, self.ctx)
                self.sites.setdefault(name, []).append(
                    (node, is_async, protected))
        return True

    def _mutated_name(self, node: ast.AST):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name):
                    return t.value.id
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATING_METHODS and \
                isinstance(node.func.value, ast.Name):
            return node.func.value.id
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name):
                    return t.value.id
        return None

    def _is_local(self, name: str, ancestors) -> bool:
        """Shadowed by a function parameter or a plain local assignment
        in any enclosing function → not the module global."""
        for anc in ancestors:
            if isinstance(anc, _shared.FUNC_NODES):
                args = anc.args
                all_args = (args.posonlyargs + args.args + args.kwonlyargs
                            + ([args.vararg] if args.vararg else [])
                            + ([args.kwarg] if args.kwarg else []))
                if any(a.arg == name for a in all_args):
                    return True
                declared_global = False
                for n in ast.walk(anc):
                    if isinstance(n, (ast.Global, ast.Nonlocal)) and \
                            name in n.names:
                        declared_global = True
                if declared_global:
                    return False
                for n in _shared.walk_without_nested_functions(anc):
                    if isinstance(n, ast.Assign):
                        for t in n.targets:
                            if isinstance(t, ast.Name) and t.id == name:
                                return True
        return False
