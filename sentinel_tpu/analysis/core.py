"""graftlint core: findings, suppressions, and the per-module analysis context.

The engine is deliberately self-hosted on stdlib ``ast`` + ``tokenize`` —
no third-party linter framework. Rules receive a :class:`ModuleContext`
(parsed tree + import alias map + raw source) and yield :class:`Finding`
objects; the engine then applies per-line suppression comments and emits
meta-findings for malformed or stale suppressions so the baseline can only
ratchet down.

Suppression syntax (one physical line, reason REQUIRED)::

    risky_call()  # graftlint: disable=ASYNC001 -- bounded 1ms sleep, see #42

A suppression comment on a line of its own applies to the next code line::

    # graftlint: disable=LOCK001 -- single-writer by construction (boot thread)
    _REGISTRY["x"] = 1
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

#: Meta rule ids emitted by the engine itself (not suppressible).
MALFORMED_SUPPRESSION = "GL000"
UNUSED_SUPPRESSION = "GL002"
PARSE_ERROR = "GL999"

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+?)"
    r"(?:\s+--\s+(.+?))?\s*$")


@dataclasses.dataclass
class Finding:
    """One diagnostic at a source location."""

    rule_id: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    suppress_reason: str = ""
    baselined: bool = False        # matched a --baseline entry

    @property
    def sort_key(self) -> Tuple[str, int, int, str]:
        return (self.path, self.line, self.col, self.rule_id)

    @property
    def active(self) -> bool:
        """Counts against the zero-unsuppressed CI gate."""
        return not self.suppressed and not self.baselined

    def to_dict(self) -> dict:
        return {
            "rule": self.rule_id,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
            "baselined": self.baselined,
        }

    def format(self) -> str:
        tag = ""
        if self.suppressed:
            tag = " [suppressed: %s]" % self.suppress_reason
        elif self.baselined:
            tag = " [baselined]"
        return "%s:%d:%d: %s %s%s" % (
            self.path, self.line, self.col, self.rule_id, self.message, tag)


@dataclasses.dataclass
class Suppression:
    """One parsed ``# graftlint: disable=...`` comment."""

    comment_line: int          # line the comment sits on
    target_line: int           # code line the suppression governs
    rule_ids: Tuple[str, ...]
    reason: str
    used: bool = False


class Rule:
    """Base class for graftlint rules.

    Subclasses set ``id``/``name``/``rationale`` and implement
    :meth:`check`, yielding findings. Keep rules pure functions of the
    :class:`ModuleContext` — no filesystem or interpreter-state access —
    so fixtures and real modules analyze identically.
    """

    id: str = "GL???"
    name: str = ""
    rationale: str = ""

    def prepare(self, contexts: Sequence["ModuleContext"]) -> None:
        """Optional whole-run pre-pass over every module being analyzed.

        Lets a rule gather *cross-module* facts before per-module checks
        run — e.g. TRACE001 records which imported functions a module
        passes to ``jax.jit`` so the defining module scans them as traced
        code. Called exactly once per analysis run, before any
        :meth:`check`; instance state set here is overwritten on the next
        run.
        """

    def check(self, ctx: "ModuleContext") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "ModuleContext", node: ast.AST,
                message: str) -> Finding:
        return Finding(self.id, ctx.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


class ModuleContext:
    """Parsed module plus the name-resolution helpers every rule needs."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.aliases = _collect_aliases(tree)

    @property
    def module_name(self) -> str:
        """Dotted module name derived from the file path (best effort:
        correct when analysis runs from the repo root, and cross-module
        consumers suffix-match so absolute paths still resolve)."""
        p = self.path
        if p.endswith(".py"):
            p = p[:-3]
        if p.endswith("/__init__") or p.endswith("\\__init__"):
            p = p[:-9]
        return p.replace("\\", "/").strip("/").replace("/", ".")

    # ------------------------------------------------------------------
    def dotted(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain, aliases expanded.

        ``jnp.zeros`` → ``jax.numpy.zeros`` (given ``import jax.numpy as
        jnp``); ``self._lock`` → ``self._lock``; non-name expressions
        (calls, subscripts) terminate the chain → None.
        """
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])

    def call_name(self, call: ast.Call) -> Optional[str]:
        return self.dotted(call.func)


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    """alias → fully-qualified dotted prefix, from every import statement
    in the module (function-local imports included: rules care about what
    a name *means*, not where it was bound)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            mod = ("." * node.level) + (node.module or "")
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{mod}.{a.name}" if mod else a.name
    return out


# ----------------------------------------------------------------------
# Suppressions
# ----------------------------------------------------------------------

def parse_suppressions(path: str, source: str,
                       known_rule_ids: Sequence[str],
                       ) -> Tuple[List[Suppression], List[Finding]]:
    """Scan ``source`` for graftlint suppression comments.

    Returns (suppressions, meta_findings). A comment with no ``-- reason``
    tail, an empty rule list, or an unknown rule id yields a GL000
    meta-finding and the suppression is NOT honored.
    """
    sups: List[Suppression] = []
    meta: List[Finding] = []
    lines = source.splitlines()
    known = set(known_rule_ids)
    for i, col, text in _iter_comments(source):
        m = _SUPPRESS_RE.search(text)
        if not m:
            if "graftlint:" in text:
                meta.append(Finding(
                    MALFORMED_SUPPRESSION, path, i, 0,
                    "unparseable graftlint comment (expected "
                    "'# graftlint: disable=<RULE,...> -- <reason>')"))
            continue
        rule_ids = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(2) or "").strip()
        if not reason:
            meta.append(Finding(
                MALFORMED_SUPPRESSION, path, i, 0,
                "suppression missing required reason "
                "('# graftlint: disable=%s -- <why>')" % ",".join(rule_ids)))
            continue
        unknown = [r for r in rule_ids if r not in known]
        if unknown or not rule_ids:
            meta.append(Finding(
                MALFORMED_SUPPRESSION, path, i, 0,
                "suppression names unknown rule id(s): %s"
                % (", ".join(unknown) or "<none>")))
            continue
        target = i
        if not lines[i - 1][:col].strip():
            # comment on a line of its own: governs the next code line
            target = _next_code_line(lines, i)
        sups.append(Suppression(i, target, rule_ids, reason))
    return sups, meta


def _iter_comments(source: str):
    """(line, col, comment_text) for every real COMMENT token — string
    literals that merely *mention* graftlint syntax don't count."""
    import io
    import tokenize
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.start[1], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


def _next_code_line(lines: List[str], after: int) -> int:
    for j in range(after, len(lines)):
        s = lines[j].strip()            # lines[j] is line j+1
        if s and not s.startswith("#"):
            return j + 1
    return after  # trailing comment: governs nothing real


# ----------------------------------------------------------------------
# Per-module analysis
# ----------------------------------------------------------------------

def analyze_source(path: str, source: str,
                   rules: Sequence[Rule]) -> List[Finding]:
    """Run ``rules`` over one module's source (single-module convenience:
    cross-module ``prepare`` sees just this file)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding(PARSE_ERROR, path, exc.lineno or 1,
                        exc.offset or 0, "syntax error: %s" % exc.msg)]
    ctx = ModuleContext(path, source, tree)
    for rule in rules:
        rule.prepare([ctx])
    return _check_module(ctx, rules)


def _registry_rule_ids() -> List[str]:
    """Every registered rule id + the meta ids — suppression comments
    are validated against the FULL registry, not the (possibly
    ``--rule``-filtered) active set, so a subset run never misreads a
    valid suppression as naming an unknown rule."""
    from sentinel_tpu.analysis.rules import RULES_BY_ID
    return list(RULES_BY_ID)


def _check_module(ctx: ModuleContext,
                  rules: Sequence[Rule]) -> List[Finding]:
    """Per-module rule run + suppression application + meta-findings
    (malformed/unused suppressions). ``prepare`` must already have run."""
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(ctx))

    sups, meta = parse_suppressions(ctx.path, ctx.source,
                                    _registry_rule_ids())
    by_line: Dict[int, List[Suppression]] = {}
    for s in sups:
        by_line.setdefault(s.target_line, []).append(s)
    for f in findings:
        for s in by_line.get(f.line, ()):
            if f.rule_id in s.rule_ids:
                f.suppressed = True
                f.suppress_reason = s.reason
                s.used = True
    active_ids = {r.id for r in rules}
    for s in sups:
        # a suppression whose rules were all filtered out this run
        # (``--rule`` subset) cannot have been consumed — not "unused"
        if not s.used and set(s.rule_ids) & active_ids:
            meta.append(Finding(
                UNUSED_SUPPRESSION, ctx.path, s.comment_line, 0,
                "unused suppression for %s (finding fixed? delete the "
                "comment so the baseline ratchets down)"
                % ",".join(s.rule_ids)))
    findings.extend(meta)
    findings.sort(key=lambda f: f.sort_key)
    return findings


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    import os
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", "node_modules"))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        elif p.endswith(".py"):
            yield p


def parse_contexts(files: Iterable[str]):
    """Parse every file into a ModuleContext. Returns ``(contexts,
    errors)`` where errors are GL999 findings for unreadable/unparsable
    files. The context list is a :class:`~.project.ContextSet` so the
    pass-1 project index built by the first rule's ``prepare`` is
    shared by the rest (and by parallel workers, per process)."""
    from sentinel_tpu.analysis.project import ContextSet
    errors: List[Finding] = []
    contexts = ContextSet()
    for fp in files:
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError) as exc:
            errors.append(Finding(PARSE_ERROR, fp, 1, 0,
                                  "unreadable: %s" % exc))
            continue
        try:
            tree = ast.parse(source, filename=fp)
        except SyntaxError as exc:
            errors.append(Finding(PARSE_ERROR, fp, exc.lineno or 1,
                                  exc.offset or 0,
                                  "syntax error: %s" % exc.msg))
            continue
        contexts.append(ModuleContext(fp, source, tree))
    return contexts, errors


def check_context(ctx: ModuleContext,
                  rules: Sequence[Rule]) -> List[Finding]:
    """Pass-2 for one already-prepared module (the per-file unit the
    ``--jobs`` worker pool distributes)."""
    return _check_module(ctx, rules)


def analyze_paths(paths: Iterable[str],
                  rules: Sequence[Rule]) -> List[Finding]:
    """Whole-run analysis: parse every module first, give each rule its
    cross-module ``prepare`` pass over all of them, then check each."""
    contexts, out = parse_contexts(iter_python_files(paths))
    for rule in rules:
        rule.prepare(contexts)
    for ctx in contexts:
        out.extend(_check_module(ctx, rules))
    return out
