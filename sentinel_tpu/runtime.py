"""Host runtime: the public facade (SphU/SphO/Tracer analog) around the
jitted decision pipeline.

The reference's hot path is an in-process method call
(``SphU.entry → CtSph.entryWithPriority``, SURVEY §3.1); here a guarded call
becomes one device step. Two API tiers:

* :meth:`Sentinel.entry` — per-call context-manager parity with
  ``try (Entry e = SphU.entry(name)) { ... }``: pads the event into a small
  fixed batch, runs the decide step, raises a
  :class:`~sentinel_tpu.core.errors.BlockException` subclass on deny, sleeps
  on pass-with-wait (RateLimiter verdicts). Convenient, correct, ~one device
  round-trip of latency.
* :meth:`Sentinel.entry_batch` / :meth:`Sentinel.exit_batch` — the throughput
  tier: numpy arrays in, verdict arrays out; this is what adapters, the
  cluster token server, and the benchmark drive.

State lives on device; the runtime owns the registries, rule compilation
(property-cell driven, ``XxxRuleManager.loadRules`` analog), the process
epoch for wraparound-safe relative time, and the 1 s system-status sampler
(``SystemStatusListener`` analog).
"""

from __future__ import annotations

import collections
import functools
import logging
import os
import threading
import weakref
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from sentinel_tpu.core.batching import (
    pad_into as _pad_into, pad_pow2, pad_to as _pad_to,
)
from sentinel_tpu.core.clock import Clock, global_clock
from sentinel_tpu.core.pending import PendingResult, start_host_copy
from sentinel_tpu.core.config import SentinelConfig, load_config
from sentinel_tpu.core.context import current_context
from sentinel_tpu.core.errors import (
    BlockException, BlockReason, ErrorEntryFreeError, block_exception_for,
    is_block_exception,
)
from sentinel_tpu.core import errors as err_mod
from sentinel_tpu.core.property import SentinelProperty
from sentinel_tpu.core.registry import (
    ENTRY_NODE_ROW, OriginRegistry, Registry, ResourceRegistry,
    make_origin_registry, make_registry, make_resource_registry,
)
from sentinel_tpu.engine.pipeline import (
    EngineSpec, EntryBatch, ExitBatch, RuleSet, SentinelState, Verdicts,
    decide_and_record_exits, decide_entries, init_state,
    invalidate_resource_rows, record_blocks, record_exits,
)
from sentinel_tpu.engine import fastpath as fp_mod
from sentinel_tpu.rules import authority as auth_mod
from sentinel_tpu.rules import degrade as deg_mod
from sentinel_tpu.rules import flow as flow_mod
from sentinel_tpu.rules import param_flow as pf_mod
from sentinel_tpu.rules import system as sys_mod
from sentinel_tpu.core.callbacks import StatisticCallbackRegistry
from sentinel_tpu.core.logs import BlockStatLogger, record_log
from sentinel_tpu.obs import RuntimeObs
from sentinel_tpu.obs import counters as obs_keys
from sentinel_tpu.stats import events as ev
from sentinel_tpu.stats.window import (
    MINUTE_SPEC, SECOND_SPEC, WindowSpec, bucket_snapshot, init_window,
    rolling_totals, rt_totals,
)

ENTRY_TYPE_OUT = 0
ENTRY_TYPE_IN = 1

_log = logging.getLogger("sentinel_tpu.runtime")

#: Depth of the serving dispatch pipeline (sentinel_tpu/serving.py) — how
#: many batches may be in flight before a submit settles the oldest.
PIPELINE_DEPTH_ENV = "SENTINEL_PIPELINE_DEPTH"


def _env_on(name: str, default: bool = True) -> bool:
    v = os.environ.get(name, "")
    if not v:
        return default
    return v.lower() not in ("0", "off", "false", "disable", "disabled")


def donation_enabled() -> bool:
    """Buffer donation on the jitted steps: the engine-state argument's
    device buffers are reused for the output state, halving the step's
    peak state footprint and letting XLA update the window tensors in
    place. Every runtime call site threads ``state_in → state_out``
    under the dispatch lock, so the consumed input is never read again;
    ``SENTINEL_DONATE=0`` is the escape hatch (e.g. for external code
    that calls the ``_jit_*`` steps directly and re-reads its input)."""
    return _env_on("SENTINEL_DONATE")


def host_staging_enabled() -> bool:
    """Reuse preallocated host staging buffers for the per-step batch
    columns instead of fresh numpy allocations (``_StagingRing``);
    ``SENTINEL_HOST_STAGING=0`` disables."""
    return _env_on("SENTINEL_HOST_STAGING")


def sortfree_enabled() -> bool:
    """Sort-free general path: the flow slots group admission segments
    via the hash-bucketed claim cascade + scatter ranks (ops/sortfree.py)
    instead of n·log n stable sorts — the default. Bit-exact with the
    sorted reference by construction (claim overflow falls back to the
    sorted branch under ``lax.cond``; the ``sortfree.bucket_overflow``
    counter tracks how often). ``SENTINEL_SORTFREE=0`` is the escape
    hatch — it reverts every path to the sorted reference machinery and
    restores the pre-round-10 program cache keys (see
    docs/OPERATIONS.md "Sort-free general path")."""
    return _env_on("SENTINEL_SORTFREE")


def single_dispatch_enabled() -> bool:
    """Single-dispatch serving tick (round 16): fold the tiering
    sketch's conservative-update scatter into the jitted decide programs
    (the sketch table becomes another donated operand) and, on the fused
    decide+exit path, a ``lax.cond``-gated epilogue that runs the
    telemetry tick + sketch decay when the host says one is due — so a
    steady-state serving batch costs exactly ONE device dispatch.
    Bit-exact with the two-dispatch composition by construction (the
    fused programs trace the same ``sketch.update_sketch`` /
    ``sketch.tick_read`` / ``telemetry_tick`` math in the same order).
    ``SENTINEL_SINGLE_DISPATCH=0`` is the operator escape hatch — it
    restores the pre-round-16 dispatch sequence AND its program cache
    keys byte-for-byte (see docs/OPERATIONS.md "Single-dispatch
    serving")."""
    return _env_on("SENTINEL_SINGLE_DISPATCH")


def pipeline_depth(default: int = 2) -> int:
    """The ``SENTINEL_PIPELINE_DEPTH`` knob, clamped to [1, 64]."""
    raw = os.environ.get(PIPELINE_DEPTH_ENV, "")
    try:
        d = int(raw) if raw else default
    except ValueError:
        return default
    return max(1, min(d, 64))


def _build_steps(spec: EngineSpec, custom_slots: tuple, shardings=None,
                 donate: bool = True):
    """``shardings`` = (state_shardings, verdict_shardings) pins every
    step's state output to the mesh layout (parallel/local_shard.py) so
    sharded state can never silently decay to replicated across steps.

    ``donate`` donates each step's engine-state argument (the output
    state reuses its buffers — see :func:`donation_enabled`)."""
    if shardings is None:
        st_out = vd_out = None
        kw_sv = kw_s = {}
    else:
        st_out, vd_out = shardings
        kw_sv = {"out_shardings": (st_out, vd_out)}
        kw_s = {"out_shardings": st_out}
    # state is positional arg 1 of the partials below (rules, state, ...)
    # except invalidate/record_blocks where it leads
    kw_d1 = {"donate_argnums": (1,)} if donate else {}
    kw_d0 = {"donate_argnums": (0,)} if donate else {}
    def dec(occ, alt):
        return jax.jit(functools.partial(
            decide_entries, spec, enable_occupy=occ,
            custom_slots=custom_slots, record_alt=alt),
            static_argnames=("scalar_flow", "fast_flow", "skip_auth",
                             "skip_sys", "scalar_has_rl",
                             "skip_threads", "sortfree"), **kw_sv, **kw_d1)

    def fused(occ, alt):
        # decide+exit in ONE program (engine/pipeline.py
        # decide_and_record_exits): the allow-then-exit serving pattern
        # pays one dispatch where the two-call form pays two
        return jax.jit(functools.partial(
            decide_and_record_exits, spec, enable_occupy=occ,
            custom_slots=custom_slots, record_alt=alt),
            static_argnames=("scalar_flow", "fast_flow", "skip_auth",
                             "skip_sys", "scalar_has_rl",
                             "skip_threads", "sortfree"), **kw_sv, **kw_d1)

    # jit objects are lazy (tracing happens on first call), so building all
    # variants is free; the *_noalt ones compile away the origin/chain
    # scatters for batches the host verified carry no alt rows (the common
    # origin-less case — two fewer million-index scatters per step)
    return (dec(False, True), dec(True, True),
            dec(False, False), dec(True, False),
            jax.jit(functools.partial(record_exits, spec),
                    static_argnames=("skip_threads",), **kw_s, **kw_d1),
            jax.jit(functools.partial(record_exits, spec,
                                      record_alt=False),
                    static_argnames=("skip_threads",), **kw_s, **kw_d1),
            jax.jit(functools.partial(invalidate_resource_rows, spec),
                    **kw_s, **kw_d0),
            jax.jit(functools.partial(record_blocks, spec),
                    **kw_s, **kw_d0),
            (fused(False, True), fused(True, True),
             fused(False, False), fused(True, False)))


@functools.lru_cache(maxsize=None)
def _jitted_steps_cached(spec: EngineSpec, donate: bool = True):
    return _build_steps(spec, (), donate=donate)


def _jitted_steps(spec: EngineSpec, custom_slots: tuple = (), shardings=None,
                  donate: Optional[bool] = None):
    """Compiled steps shared across Sentinel instances with the same geometry
    (EngineSpec is a frozen, hashable dataclass). Variants WITH custom
    DeviceSlots or mesh shardings are deliberately NOT cached globally: the
    owning Sentinel holds the only reference, so stale compilations (and the
    slot objects / mesh) are garbage-collected on every register/unregister
    instead of pinned forever by an unbounded cache key."""
    if donate is None:
        donate = donation_enabled()
    if custom_slots or shardings is not None:
        return _build_steps(spec, custom_slots, shardings, donate)
    return _jitted_steps_cached(spec, donate)

#: Static flag names shared by every decide-shaped program (must match
#: the ``decide_entries`` keyword surface — _build_steps uses the same
#: tuple inline).
_STEP_STATICS = ("scalar_flow", "fast_flow", "skip_auth", "skip_sys",
                 "scalar_has_rl", "skip_threads", "sortfree")

#: Epilogue due-flag bits (host-computed, packed into the int32[4]
#: ``epi`` operand as [flags, now_idx_s, sec_idx_m, append]).
_EPI_TELEMETRY = 1       # run the telemetry tick branch
_EPI_TIER = 2            # run the sketch decay + estimate branch


def _build_sd_steps(spec: EngineSpec, custom_slots: tuple, shardings=None,
                    donate: bool = True, mesh=None, tel_k: int = 1,
                    tel_rows_per_shard: int = 0):
    """Round-16 sketch-fused serving programs (``SENTINEL_SINGLE_DISPATCH``).

    Three families, mirroring :func:`_build_steps`'s variant layout
    (index ``(2 if no_alt else 0) + (1 if use_occ else 0)``):

    * ``decide`` — ``decide_entries`` + :func:`sketch.update_sketch`
      over the batch's rows, one program: ``(rules, state, sketch,
      batch, times, sys_scalars) → (state, verdicts, sketch)``.
    * ``fused`` — same fusion over ``decide_and_record_exits``.
    * ``fused_epi`` — the fused program plus a ``lax.cond``-gated
      epilogue: bit ``_EPI_TELEMETRY`` of ``epi[0]`` runs
      :func:`~sentinel_tpu.obs.telemetry.telemetry_tick` over the
      post-decide window state + timeline ring, bit ``_EPI_TIER`` runs
      :func:`sketch.tick_read` (decay then full-table estimate).
      Signature ``(rules, state, sketch, ring, epi, batch, xbatch,
      times, sys_scalars) → (state, verdicts, sketch, ring, tel_outs,
      est)``; the skipped branches return zero-shaped outputs and the
      operands unchanged.

    Bit-parity with the legacy two-dispatch composition is by
    construction: the sketch update reads only ``batch.rows``/``valid``
    (never the decide outputs), the decide never reads the sketch, and
    the epilogue branches trace the exact helpers the standalone ticks
    jit — same math, same order (observe, then decay+estimate over the
    updated table), different program boundaries.

    Sketch/ring/epilogue outputs are replicated on meshed engines
    (``NamedSharding(mesh, P())`` — the tables are a few KB; only the
    row-sharded state carries a layout)."""
    from sentinel_tpu.obs.telemetry import TelemetryRing, telemetry_tick
    from sentinel_tpu.tiering import sketch as sk_mod

    if shardings is None or mesh is None:
        kw3: dict = {}
        kw6: dict = {}
    else:
        from jax.sharding import NamedSharding, PartitionSpec
        st_out, vd_out = shardings
        rep = NamedSharding(mesh, PartitionSpec())
        ring_rep = TelemetryRing(seconds=rep, lanes=rep, rt=rep,
                                 cursor=rep)
        kw3 = {"out_shardings": (st_out, vd_out, rep)}
        kw6 = {"out_shardings": (st_out, vd_out, rep, ring_rep, rep, rep)}
    kw_d12 = {"donate_argnums": (1, 2)} if donate else {}
    kw_d123 = {"donate_argnums": (1, 2, 3)} if donate else {}
    n_ev = ev.NUM_EVENTS

    def dec_sd(occ, alt):
        base = functools.partial(decide_entries, spec, enable_occupy=occ,
                                 custom_slots=custom_slots, record_alt=alt)

        def step(rules, state, sketch, batch, times, sys_scalars,
                 scalar_flow=False, fast_flow=False, skip_auth=False,
                 skip_sys=False, scalar_has_rl=True, skip_threads=False,
                 sortfree=False):
            state, verdicts = base(
                rules, state, batch, times, sys_scalars,
                scalar_flow=scalar_flow, fast_flow=fast_flow,
                skip_auth=skip_auth, skip_sys=skip_sys,
                scalar_has_rl=scalar_has_rl, skip_threads=skip_threads,
                sortfree=sortfree)
            # the overflow flag is dropped exactly like observe_locked's
            # (self-clamping halve happens inside update_sketch; the
            # COUNTER is ticked from the ticker's estimate readback)
            sketch, _overflow = sk_mod.update_sketch(
                sketch, batch.rows, batch.valid)
            return state, verdicts, sketch

        return jax.jit(step, static_argnames=_STEP_STATICS,
                       **kw3, **kw_d12)

    def fused_sd(occ, alt, epilogue):
        base = functools.partial(decide_and_record_exits, spec,
                                 enable_occupy=occ,
                                 custom_slots=custom_slots, record_alt=alt)

        def step(rules, state, sketch, batch, xbatch, times, sys_scalars,
                 scalar_flow=False, fast_flow=False, skip_auth=False,
                 skip_sys=False, scalar_has_rl=True, skip_threads=False,
                 sortfree=False):
            state, verdicts = base(
                rules, state, batch, xbatch, times, sys_scalars,
                scalar_flow=scalar_flow, fast_flow=fast_flow,
                skip_auth=skip_auth, skip_sys=skip_sys,
                scalar_has_rl=scalar_has_rl, skip_threads=skip_threads,
                sortfree=sortfree)
            sketch, _overflow = sk_mod.update_sketch(
                sketch, batch.rows, batch.valid)
            return state, verdicts, sketch

        if not epilogue:
            return jax.jit(step, static_argnames=_STEP_STATICS,
                           **kw3, **kw_d12)

        def step_epi(rules, state, sketch, ring, epi, batch, xbatch,
                     times, sys_scalars, scalar_flow=False,
                     fast_flow=False, skip_auth=False, skip_sys=False,
                     scalar_has_rl=True, skip_threads=False,
                     sortfree=False):
            state, verdicts, sketch = step(
                rules, state, sketch, batch, xbatch, times, sys_scalars,
                scalar_flow=scalar_flow, fast_flow=fast_flow,
                skip_auth=skip_auth, skip_sys=skip_sys,
                scalar_has_rl=scalar_has_rl, skip_threads=skip_threads,
                sortfree=sortfree)

            def tel_run(op):
                second, minute, rt_hist, rg = op
                return telemetry_tick(
                    spec.second, spec.minute, tel_k, mesh,
                    tel_rows_per_shard, second, minute, rt_hist, rg,
                    epi[1], epi[2], epi[3])

            def tel_skip(op):
                _second, _minute, _rt_hist, rg = op
                hb = spec.hist_buckets       # 0 → zero-width hist outputs
                zk = jnp.zeros((tel_k,), jnp.int32)
                zl = jnp.zeros((tel_k, n_ev), jnp.int32)
                return (zk, zk, zl, zl, jnp.zeros((tel_k,), jnp.float32),
                        jnp.zeros((n_ev,), jnp.int32),
                        jnp.zeros((), jnp.float32),
                        jnp.zeros((tel_k, hb), jnp.int32),
                        jnp.zeros((tel_k, 3 if hb else 0),
                                  jnp.float32)), rg

            tel_outs, ring2 = jax.lax.cond(
                (epi[0] & _EPI_TELEMETRY) > 0, tel_run, tel_skip,
                (state.second, state.minute, state.rt_hist, ring))

            def tier_run(sc):
                return sk_mod.tick_read(sc, spec.rows)

            def tier_skip(sc):
                return sc, jnp.zeros((spec.rows,), jnp.int32)

            sketch, est = jax.lax.cond(
                (epi[0] & _EPI_TIER) > 0, tier_run, tier_skip, sketch)
            return state, verdicts, sketch, ring2, tel_outs, est

        return jax.jit(step_epi, static_argnames=_STEP_STATICS,
                       **kw6, **kw_d123)

    return {
        "decide": (dec_sd(False, True), dec_sd(True, True),
                   dec_sd(False, False), dec_sd(True, False)),
        "fused": (fused_sd(False, True, False), fused_sd(True, True, False),
                  fused_sd(False, False, False),
                  fused_sd(True, False, False)),
        "fused_epi": (fused_sd(False, True, True),
                      fused_sd(True, True, True),
                      fused_sd(False, False, True),
                      fused_sd(True, False, True)),
    }


@functools.lru_cache(maxsize=None)
def _sd_steps_cached(spec: EngineSpec, donate: bool, tel_k: int,
                     tel_rows_per_shard: int):
    """Sketch-fused programs shared across Sentinel instances with the same
    geometry + telemetry layout — same caching policy as
    :func:`_jitted_steps_cached` (variants with custom DeviceSlots or mesh
    shardings stay per-instance so their compilations are collectable)."""
    return _build_sd_steps(spec, (), donate=donate, tel_k=tel_k,
                           tel_rows_per_shard=tel_rows_per_shard)


# jitted once at import; shapes are padded to powers of two so the trace
# cache stays small (calling jax.jit(...) per drain would re-trace every time)
_jit_invalidate_param_keys = jax.jit(pf_mod.invalidate_param_keys)
_jit_apply_overrides = jax.jit(pf_mod.apply_overrides)
# small device-side copy used to hand breaker observers a column that
# survives the next step's donation of the state it was read from
_jit_copy_column = jax.jit(jnp.copy)


@functools.lru_cache(maxsize=None)
def _jit_uncount_reserved(spec: EngineSpec):
    from sentinel_tpu.engine.pipeline import uncount_reserved
    return jax.jit(functools.partial(uncount_reserved, spec))


@functools.lru_cache(maxsize=None)
def _jit_bucket_snapshot(spec: WindowSpec):
    return jax.jit(functools.partial(bucket_snapshot, spec))


@functools.lru_cache(maxsize=None)
def _jit_settle_occupied(spec: WindowSpec):
    from sentinel_tpu.stats.window import settle_occupied
    return jax.jit(functools.partial(settle_occupied, spec,
                                     event=ev.PASS))

_H1 = 0x9E3779B1
_H2 = 0x85EBCA6B
_MASK = 0xFFFFFFFF

# (A background first-execution warmup thread was built and measured in
# round 5: overlapping the tunnel's fixed first-execution cost with
# construction's own RPCs changed the warm start by <0.3 s — the tunnel
# serializes the RPCs server-side — so it was removed. The measured
# decomposition lives in docs/OPERATIONS.md.)


def _alt_hash(row: int, kind: int, key_id: int, ra: int) -> int:
    """Stable (resource, origin/context) → alt-table row."""
    h = ((row * _H1) ^ ((key_id * 2 + kind) * _H2)) & _MASK
    return h % ra


class _CpuSampler:
    """CPU usage from /proc/stat deltas, sampled at most once per second."""

    def __init__(self, clock: Clock):
        self._clock = clock
        self._last_ms = -10_000
        self._last_total = 0
        self._last_idle = 0
        self._value = -1.0

    def sample(self) -> Tuple[float, float]:
        now = self._clock.now_ms()
        if now - self._last_ms >= 1000:
            self._last_ms = now
            try:
                import os
                load1 = os.getloadavg()[0]
            except OSError:  # pragma: no cover
                load1 = -1.0
            self._load1 = load1
            try:
                with open("/proc/stat") as fh:
                    parts = fh.readline().split()[1:]
                vals = [int(x) for x in parts[:8]]
                total = sum(vals)
                idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
                dt = total - self._last_total
                di = idle - self._last_idle
                if self._last_total and dt > 0:
                    self._value = max(0.0, min(1.0, 1.0 - di / dt))
                self._last_total, self._last_idle = total, idle
            except (OSError, ValueError, IndexError):  # pragma: no cover
                self._value = -1.0
        return getattr(self, "_load1", -1.0), self._value


class Entry:
    """A granted (or in-flight) guarded call. Context-manager; reference
    ``Entry``/``CtEntry`` with try-with-resources semantics."""

    __slots__ = ("_rt", "resource", "row", "origin_row", "chain_row",
                 "acquire", "is_in", "create_ms", "error", "_exited",
                 "param_pairs", "wait_ms", "_terminate_handlers", "fast")

    def __init__(self, rt: "Sentinel", resource: str, row: int, origin_row: int,
                 chain_row: int, acquire: int, is_in: bool, create_ms: int,
                 param_pairs=None):
        self._rt = rt
        self.resource = resource
        self.row = row
        self.origin_row = origin_row
        self.chain_row = chain_row
        self.acquire = acquire
        self.is_in = is_in
        self.create_ms = create_ms
        self.param_pairs = param_pairs   # (rules [PV], keys [PV]) or None
        self.error: Optional[BaseException] = None
        self._exited = False
        self.wait_ms = 0   # pacing verdict; >0 only with entry(sleep=False)
        self._terminate_handlers = None   # CtEntry.whenTerminate callbacks
        self.fast = None   # "free"/"leased" when host-fast-path admitted

    def trace(self, exc: BaseException) -> None:
        """Reference ``Tracer.trace`` — mark a business exception so it feeds
        exception-ratio/count circuit breakers and exception QPS."""
        if exc is not None and not is_block_exception(exc):
            self.error = exc

    def when_terminate(self, fn) -> None:
        """Register ``fn(entry)`` to run after exit (reference
        ``CtEntry.whenTerminate`` — the hook HALF_OPEN probes and the api
        facade's entry stack use)."""
        if self._terminate_handlers is None:
            self._terminate_handlers = []
        self._terminate_handlers.append(fn)

    def exit(self) -> None:
        if self._exited:
            raise ErrorEntryFreeError(f"entry for {self.resource!r} exited twice")
        self._exited = True
        self._rt._exit_one(self)
        if self._terminate_handlers:
            for fn in self._terminate_handlers:
                fn(self)

    def __enter__(self) -> "Entry":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.trace(exc)
        self.exit()
        return False


def _settle_leaked(cell, on_leak) -> None:
    """GC finalizer for a :class:`PendingVerdicts` dropped without
    ``.result()``: the deferred host bookkeeping (blocked-pin release,
    block log, breaker diffs) must not be lost with the handle. Runs the
    leak callback (counter + warning) first so a settle failure — e.g.
    the owning Sentinel was closed — still leaves the leak visible."""
    if cell.done:
        return
    try:
        on_leak()
    except Exception:   # telemetry must never mask the settle
        pass
    try:
        cell.settle()
    except Exception:
        _log.debug("leaked PendingVerdicts settle failed", exc_info=True)


class PendingVerdicts(PendingResult):
    """Handle for an in-flight batch decide: ``result()`` materializes the
    :class:`Verdicts` and performs the deferred host-side bookkeeping
    (blocked-pin release, block log) — it MUST be called for every handle.

    A handle the caller drops anyway is settled by a GC finalizer (see
    :func:`_settle_leaked`) and counted in ``pipeline.leaked_handles`` —
    correctness is preserved, but the settle then runs at an arbitrary
    point on the GC's thread, so a leak is still a caller bug."""

    __slots__ = ("_leak_finalizer",)

    def attach_leak_guard(self, on_leak) -> None:
        f = weakref.finalize(self, _settle_leaked, self._cell, on_leak)
        # never settle during interpreter shutdown: the backend may
        # already be torn down, and the process exiting is not a leak
        f.atexit = False
        self._leak_finalizer = f

    def result(self):
        fin = getattr(self, "_leak_finalizer", None)
        if fin is not None:
            fin.detach()
        return self._cell.settle()


class _StagingRing:
    """Preallocated host staging for the always-present entry-batch columns
    of one padded size: ``_build_entry_batch`` fills a free slot in place
    (``pad_into``) instead of allocating ~9 fresh numpy arrays per step —
    the ``entry.prep`` cost a serving loop re-pays every dispatch.

    A slot must not be rewritten while a dispatch built from it could
    still read it. The round-7 ring assumed a jit call copies host
    operands synchronously; on this backend that does not always hold
    under tiering churn (ROADMAP known-issue 5), so slot reuse is now
    tied to dispatch SETTLEMENT: ``acquire()`` hands out a slot from the
    free list, and the dispatch path releases it from its deferred-read
    closure only after the verdict readback has materialized — by which
    point the device has consumed the staged operands. Under churn
    (pipeline deeper than the free list, or a slot held across a stall)
    ``acquire()`` grows the pool with a fresh slot instead of ever
    rewriting an in-flight one; ``grown`` counts those allocations. A
    slot leaked on an exception path simply shrinks the pool — the next
    acquire re-grows it — so correctness never depends on release."""

    __slots__ = ("b", "_free", "_lock", "grown")

    _INT_COLS = ("rows", "origin_ids", "origin_rows", "context_ids",
                 "chain_rows", "acquire")
    _BOOL_COLS = ("is_in", "prioritized", "valid")

    def __init__(self, b: int, depth: int):
        self.b = b
        self.grown = 0
        self._lock = threading.Lock()
        self._free = [self._new_slot() for _ in range(depth)]

    def _new_slot(self) -> dict:
        return {**{c: np.empty(self.b, np.int32) for c in self._INT_COLS},
                **{c: np.empty(self.b, np.bool_) for c in self._BOOL_COLS}}

    def acquire(self) -> dict:
        with self._lock:
            if self._free:
                return self._free.pop()
            self.grown += 1
        return self._new_slot()

    def release(self, slot: dict) -> None:
        with self._lock:
            self._free.append(slot)


class Sentinel:
    """The framework instance (Env/CtSph + rule managers, in one object)."""

    def __init__(self, config: Optional[SentinelConfig] = None,
                 clock: Optional[Clock] = None, mesh=None):
        """``mesh`` (a ``jax.sharding.Mesh`` with a ``"rows"`` axis) turns
        on the row-sharded multi-chip mode: the ``[R, B, E]`` window tensors
        and thread gauges shard on the resource axis across the mesh
        (parallel/local_shard.py), the product form of the north-star
        "single sharded counter tensor". Semantics are identical to the
        single-device engine (parity is pinned by tests); max_resources
        must be a multiple of the mesh size.

        The mesh may be externally built and span PROCESSES (a
        ``sentinel_tpu.multihost.mesh.global_mesh(axis="rows")`` over a
        bootstrapped multi-process runtime): state then shards across
        hosts and ``is_multihost`` is True. That mode is SPMD — every
        process must construct the engine identically and replay the
        same rule loads and entry batches in the same order (see
        docs/OPERATIONS.md "Multi-host pod deployment")."""
        self.cfg = config or load_config()
        self.clock = clock or global_clock()
        self.mesh = mesh
        self._mesh_shardings = None      # (state_sh, verdict_sh) when meshed
        cfg = self.cfg

        # Cold-start: persistent XLA compilation cache — the first process
        # on a machine pays the step compiles, every later process starts
        # warm (core/compile_cache.py; measured numbers in OPERATIONS.md)
        from sentinel_tpu.core.compile_cache import enable_persistent_cache
        enable_persistent_cache(getattr(cfg, "compile_cache_dir", None))

        # factories pick the native C++ interning table when buildable
        self.resources = make_resource_registry(cfg.max_resources)
        self.origins = make_origin_registry(cfg.max_origins)
        self.contexts = make_registry(2048,
                                      reserved=("sentinel_default_context",))

        from sentinel_tpu.obs.resource_hist import engine_hist_buckets
        self.spec = EngineSpec(
            rows=cfg.max_resources,
            alt_rows=max(2 * cfg.max_resources, 1024),
            second=WindowSpec(cfg.second_sample_count,
                              cfg.second_interval_ms // max(cfg.second_sample_count, 1)),
            minute=MINUTE_SPEC if cfg.minute_enabled else None,
            statistic_max_rt=cfg.statistic_max_rt,
            param_keys=cfg.param_table_slots,
            param_pairs=cfg.param_pairs_per_event,
            occupy_timeout_ms=cfg.occupy_timeout_ms,
            # round 20 — per-resource RT histograms (0 = disabled; a
            # trace-time knob: the value is baked into the state pytree
            # and every jitted step program's cache key)
            hist_buckets=engine_hist_buckets(),
        )
        self.param_key_registry = pf_mod.make_param_key_registry(cfg.param_table_slots)
        self._user_param_rules: List[pf_mod.ParamFlowRule] = []
        self._gateway_param_rules: List[pf_mod.ParamFlowRule] = []
        # bumped on every param-rule reload: pairs resolved against a stale
        # (table, registry) pair carry their generation and are dropped by
        # decide_raw/exit if a reload happened in between — a stale rule slot
        # must never be applied against the new table
        self._param_gen = 0
        # process epoch: wraparound-safe int32 relative time base
        self.epoch_ms = self.clock.now_ms()

        # Round 11 — tuned-config startup resolution + knob-registry
        # validation (sentinel_tpu/tune). ``SENTINEL_TUNED_CONFIG``
        # names a sweep-produced TUNED.json; a fingerprint-matching
        # artifact fills in every knob whose env var the operator left
        # UNSET (explicit env wins per knob — the override path), a
        # mismatch resolves to {} and serving proceeds on defaults.
        # Events (artifact load/fallback + unknown/out-of-clamp
        # SENTINEL_* env keys) are routed to RecordLog and the tune.*
        # counters once self.obs exists below.
        from sentinel_tpu import tune as tune_mod
        self._tuned, self._tune_events = tune_mod.resolve_startup(
            spec=self.spec, mesh=mesh)
        # SORTFREE_BITS/CHUNK are read from env inside the traced flow
        # programs (ops/sortfree.py) — the one knob pair with no
        # injection path — so a tuned value pins the (still-unset) env
        # var for this process; first engine wins, and the pin is logged
        for _env in ("SENTINEL_SORTFREE_BITS", "SENTINEL_SORTFREE_CHUNK"):
            if _env in self._tuned and _env not in os.environ:
                os.environ[_env] = str(self._tuned[_env])
                self._tune_events.append((
                    None,   # log-only: the artifact load already ticked
                    f"pinned {_env}={self._tuned[_env]} from tuned "
                    f"config (trace-time knob, applied via env)"))

        self._lock = threading.RLock()
        # main row → alt rows it ever hashed to; consulted on row eviction so
        # the recycled row's origin/context stats are cleared too
        self._alt_rows_by_row: dict = {}
        # init_state picks transfer-based init (one device_put, no XLA
        # program) for serving-sized geometries and one fused fill
        # program at bench scale — see OPERATIONS.md "Cold start" for
        # the measured round-5 decomposition.
        self._state = init_state(self.spec, cfg.max_flow_rules,
                                 cfg.max_degrade_rules)
        # Multi-process "rows" mesh (multihost/): replicated leaves
        # (rules, verdicts) stay host-readable everywhere; row-sharded
        # leaves are only partially addressable per host.
        self.is_multihost = mesh is not None and len(
            {d.process_index for d in np.ravel(np.asarray(mesh.devices))}) > 1
        # meshed serving places batch columns on batch-axis NamedShardings
        # before dispatch (parallel/local_shard.place_batch) — single-
        # process meshes only: a multihost batch column is per-process
        # host data and stays with the SPMD replication contract
        self._place_batches = mesh is not None and not self.is_multihost
        if mesh is not None:
            from sentinel_tpu.parallel.local_shard import validate_mesh
            validate_mesh(self.spec, mesh)
            self._refresh_shardings_locked()
        self._compile_empty_rules()

        self.flow_property: SentinelProperty = SentinelProperty()
        self.degrade_property: SentinelProperty = SentinelProperty()
        self.system_property: SentinelProperty = SentinelProperty()
        self.authority_property: SentinelProperty = SentinelProperty()
        self.flow_property.add_listener(lambda rs: self.load_flow_rules(rs))
        self.degrade_property.add_listener(lambda rs: self.load_degrade_rules(rs))
        self.system_property.add_listener(lambda rs: self.load_system_rules(rs))
        self.authority_property.add_listener(lambda rs: self.load_authority_rules(rs))
        self.param_flow_property: SentinelProperty = SentinelProperty()
        self.param_flow_property.add_listener(lambda rs: self.load_param_flow_rules(rs))
        # SampleCountProperty / IntervalProperty analogs: live second-window
        # geometry (update_window_geometry rebuilds state + re-jits)
        self.sample_count_property: SentinelProperty = SentinelProperty()
        self.sample_count_property.add_listener(
            lambda sc: self.update_window_geometry(sample_count=int(sc)))
        self.interval_property: SentinelProperty = SentinelProperty()
        self.interval_property.add_listener(
            lambda ms: self.update_window_geometry(interval_ms=int(ms)))

        self._sys_rules: List[sys_mod.SystemRule] = []
        self._cpu = _CpuSampler(self.clock)
        self._global_on = True  # reference Constants.ON / setSwitch command
        # resource → ResourceTypeConstants classification (first writer wins)
        self.resource_types: dict = {}
        # per-second rolled-up block log (LogSlot → EagleEyeLogUtil analog)
        self.block_log = BlockStatLogger(self.clock)
        # self-telemetry bundle (obs/): spans + decision counters +
        # latency histograms + sampled block-event log. Every hot-path
        # instrumentation site below guards on the single `obs.enabled`
        # flag (SENTINEL_OBS_DISABLE); sampling via SENTINEL_TRACE_SAMPLE.
        self.obs = RuntimeObs(clock=self.clock)
        # surface the startup tune events (artifact load / fingerprint
        # fallback / rejected env knobs) now that telemetry exists:
        # RecordLog line + one counter tick each (key None = log-only)
        if self._tune_events:
            rl = record_log()
            for _key, _msg in self._tune_events:
                (rl.info if _key == obs_keys.TUNE_LOADED
                 else rl.warning)("tune: %s", _msg)
                if _key is not None:
                    self.obs.counters.add(_key)
        # services registered for Sentinel.close() (metric timer,
        # exporter, ...): stopped once, LIFO, idempotently
        self._shutdown_hooks: List = []
        self._closed = False
        # Round 12 — device-resident hot-resource telemetry (obs/
        # telemetry.py): a jitted tick over the live sharded window state
        # (per-shard top-K merged device-side + the ENTRY-row per-second
        # timeline ring) with asynchronous host readback on its own
        # thread. Constructed here (after the shutdown registry — it
        # self-registers) but the ticker only starts when the transport
        # bootstrap (or an operator) calls telemetry.start().
        from sentinel_tpu.obs.telemetry import HotTelemetry
        self.telemetry = HotTelemetry(self)
        # Round 15 — tiered resource state (tiering/): the device table
        # becomes the HOT tier; recycled rows' window counters, thread
        # gauges and occupy bookings spill to a host cold tier and are
        # restored bit-identically when the key is interned again.
        # Constructed after the shutdown registry (it self-registers);
        # the sketch ticker starts with the transport bootstrap or an
        # operator tiering.start(). SENTINEL_TIERING_DISABLE reverts to
        # the pre-round-15 lossy eviction.
        from sentinel_tpu.tiering import TierManager
        self.tiering = TierManager(self)
        # per-rule-family pinned-name ledger (flow/degrade/param/auth):
        # reloads release pins no other family still needs, so formerly
        # ruled keys become demotable (see _update_rule_pins_locked)
        self._rule_pins: dict = {}
        self.callbacks = StatisticCallbackRegistry()
        # circuit-breaker transition observers (EventObserverRegistry).
        # Event-driven: every decide/exit step that can move breaker state
        # carries the [ND] state vector out with its existing readback and
        # diffs it against ONE shared baseline on the thread that lands the
        # batch; the metric-timer poll shares the same baseline, so it is a
        # pure fallback (unread pending verdicts) and never double-fires.
        self._breaker_observers: list = []
        # (seq, rules-tuple identity, states list) of the last landed diff
        self._breaker_live: Optional[Tuple[int, tuple, List[int]]] = None
        self._breaker_seq = 0            # dispatch order, under self._lock
        # serializes diffs: concurrent diffs against one baseline would
        # double-fire observers and lose interleaved transitions
        self._breaker_event_lock = threading.Lock()
        # delivery stays seq-ordered WITHOUT holding the event lock in
        # user code: transitions are enqueued under the event lock (queue
        # order == seq order) and drained by a single active drainer;
        # re-entrant or concurrent callers enqueue and return
        self._breaker_fire_q: "collections.deque" = collections.deque()
        self._breaker_firing = False

        # dispatch-cost knobs (read once at construction): buffer donation
        # on the jitted steps and host staging reuse for batch columns.
        # self._tuned only carries knobs whose env var is UNSET, so the
        # get() fallback to the env helper preserves env precedence
        self._donate = bool(self._tuned.get("SENTINEL_DONATE",
                                            donation_enabled()))
        self._staging_on = bool(self._tuned.get("SENTINEL_HOST_STAGING",
                                                host_staging_enabled()))
        # padded batch size → _StagingRing; ring depth covers the deepest
        # supported dispatch pipeline plus the split path's two builds
        self._staging: dict = {}
        self._staging_depth = max(4, 2 * int(self._tuned.get(
            PIPELINE_DEPTH_ENV, pipeline_depth())) + 2)

        (self._jit_decide, self._jit_decide_prio,
         self._jit_decide_noalt, self._jit_decide_prio_noalt,
         self._jit_exit, self._jit_exit_noalt,
         self._jit_invalidate, self._jit_record_blocks,
         self._jit_fused_steps) = \
            _jitted_steps(self.spec, shardings=self._mesh_shardings,
                          donate=self._donate)
        # round 16 — single-dispatch serving tick: sketch-fused decide
        # programs built lazily (_sd_steps_locked; reset wherever the
        # legacy 9-tuple above is reassigned). The knob off leaves every
        # legacy path — and its program cache keys — byte-identical to
        # pre-r16.
        self._single_dispatch = bool(self._tuned.get(
            "SENTINEL_SINGLE_DISPATCH", single_dispatch_enabled()))
        self._sd_steps = None
        # (variant, geometry, statics) combos whose program fetch was
        # already guarded — see _warm_first_fetch_locked
        self._fetched_programs: set = set()
        self._token_service = None          # cluster TokenService (client or
        # embedded server facade); set via set_token_service
        self._cluster_rules_by_row: dict = {}
        self._cluster_param_rules_by_row: dict = {}
        self._occupy_live_until_ms = -1     # last ms a booking can be live
        # highest second-window index any dispatch has stamped; late fast-
        # path flush groups older than a full ring vs this are re-stamped
        # to now (safe-late) instead of resurrecting a recycled bucket
        self._seen_idx = -(2 ** 62)

        # pluggable processor slots (SlotChainBuilder SPI analog,
        # engine/slots.py): host gates veto before dispatch, device slots
        # compile into the fused decide at registration
        self._host_gates: tuple = ()
        self._device_slots: tuple = ()

        # host-side fast path (SURVEY §7 hard-part 1): rule-free rows admit
        # on host with batched stat recording; single-simple-QPS rows serve
        # from a device-pre-charged token lease
        self._fast = fp_mod.HostFastPath(
            flush_events=cfg.fast_path_flush_events,
            flush_ms=cfg.fast_path_flush_ms,
            lease_fraction=cfg.fast_path_lease_fraction,
            win_ms=self.spec.second.win_ms)
        self._fast_enabled = bool(cfg.host_fast_path)
        # serializes drain→dispatch in _flush_fast: without it a concurrent
        # flush could land a buffered EXIT before the flush carrying its
        # matching pass, leaving the thread gauge permanently skewed (the
        # exit decrement clamps at 0, the late pass increment doesn't)
        self._flush_lock = threading.Lock()

        # SPI-discovered slots (SlotChainProvider.newSlotChain analog:
        # every new "chain" is built from the registered ProcessorSlot
        # providers). Fresh instances per Sentinel — slot state must not
        # leak across engines.
        from sentinel_tpu.core.spi import SERVICE_PROCESSOR_SLOT, SpiLoader
        for slot in SpiLoader.of(
                SERVICE_PROCESSOR_SLOT).load_new_instance_list_sorted():
            self.register_slot(slot)

    # ------------------------------------------------------------------
    # Rule management (XxxRuleManager.loadRules analog)
    # ------------------------------------------------------------------

    def _compile_empty_rules(self) -> None:
        cfg = self.cfg
        self._flow = flow_mod.compile_flow_rules(
            [], resource_registry=self.resources, context_registry=self.contexts,
            capacity=cfg.max_flow_rules, k_per_resource=cfg.max_rules_per_resource,
            num_rows=cfg.max_resources, cold_factor=float(cfg.cold_factor),
            origin_registry=self.origins)
        self._deg = deg_mod.compile_degrade_rules(
            [], resource_registry=self.resources, capacity=cfg.max_degrade_rules,
            k_per_resource=cfg.max_rules_per_resource, num_rows=cfg.max_resources)
        self._auth = auth_mod.compile_authority_rules(
            [], resource_registry=self.resources, origin_registry=self.origins,
            capacity=cfg.max_authority_rules, k_per_resource=2,
            num_rows=cfg.max_resources)
        self._sys = sys_mod.compile_system_rules([])
        self._param = pf_mod.compile_param_rules(
            [], resource_registry=self.resources,
            capacity=cfg.max_param_rules,
            k_per_resource=cfg.max_rules_per_resource)
        self._ruleset = self._build_ruleset()

    def _build_ruleset(self) -> RuleSet:
        """Assemble the dispatch RuleSet from the compiled tables.

        Callers hold ``self._lock`` (every rule-swap API rebuilds under
        it); the ``__init__`` call runs before any thread exists.
        """
        # Used-slot slicing: the device steps iterate a [B, K] pair axis
        # where K is the rule-gather width — slicing it to the MAX RULES ON
        # ANY ONE RESOURCE (not the configured capacity) halves the hot
        # path's per-pair work for the dominant one-rule-per-resource
        # population. A reload that widens K retraces the step (rare, and
        # amortized by the persistent compilation cache).
        kf = self._flow.k_used
        kd = self._deg.k_used
        # Static step flags (jit static args — variants recompile when they
        # flip, steady-state rulesets keep one trace):
        self._scalar_has_rl = any(
            r.control_behavior in (flow_mod.BEHAVIOR_RATE_LIMITER,
                                   flow_mod.BEHAVIOR_WARM_UP_RATE_LIMITER)
            and r.grade == flow_mod.GRADE_QPS for r in self._flow.rules)
        self._skip_auth = self._auth.num_active == 0
        self._skip_sys = not getattr(self, "_sys_rules", [])
        # sort-free segment grouping (env-pinned per process, read at
        # every reload so a test flipping the env var between Sentinels
        # gets the expected variant; the tuned-config override applies
        # only while the env var is unset — see resolve_startup)
        self._sortfree = bool(getattr(self, "_tuned", {}).get(
            "SENTINEL_SORTFREE", sortfree_enabled()))
        # Thread-gauge elision: nothing loaded READS live concurrency →
        # the gauge-maintenance scatters compile away (the only readers:
        # THREAD-grade flow rules — DefaultController.java:50-76, system
        # rules — SystemRuleManager.checkSystem, THREAD-grade param rules
        # — ParamFlowChecker). Gauges read 0 while elided; loading a
        # reader flips the flag (retrace) and the gauge warms as pre-flip
        # entries exit (decrements clamp at 0). See docs/OPERATIONS.md.
        prev_skip = getattr(self, "_skip_threads", None)
        self._skip_threads = (
            not self.cfg.thread_gauge_always
            and self._skip_sys
            and not any(r.grade == flow_mod.GRADE_THREAD
                        for r in self._flow.rules)
            and not any(r.grade == pf_mod.GRADE_THREAD
                        for r in self._param.rules))
        if prev_skip is not None and prev_skip != self._skip_threads \
                and hasattr(self, "_state"):
            # Flag flip invalidates the gauges: entries counted while
            # maintenance was ON would otherwise leak a permanent
            # OVER-count when their elided exits never decrement (e.g.
            # unload the THREAD rule, exits happen elided, reload one).
            # Zeroing restores the documented contract — transient
            # under-count only, gauges warm as live entries exit
            # (decrements clamp at 0). `x * 0` keeps mesh sharding.
            st = self._state
            self._state = st._replace(
                threads=st.threads * 0,
                alt_threads=st.alt_threads * 0,
                param_dyn=st.param_dyn._replace(
                    threads=st.param_dyn.threads * 0))
        # Used-slot slice + joint concat in NUMPY, one device transfer:
        # the jnp forms dispatch dynamic_slice/concatenate programs whose
        # per-process loads cost ~0.6 s each on a tunneled TPU (the cold-
        # start story, docs/OPERATIONS.md).
        if self._flow.rule_idx_np is not None \
                and self._deg.rule_idx_np is not None:
            fi_np = self._flow.rule_idx_np[:, :kf]
            di_np = self._deg.rule_idx_np[:, :kd]
            joint_np = RuleSet.build_joint_np(fi_np, di_np)
            flow_idx, deg_idx, joint = jax.device_put(
                (fi_np, di_np, joint_np))
            return RuleSet(
                flow_table=self._flow.table,
                flow_idx=flow_idx,
                deg_table=self._deg.table,
                deg_idx=deg_idx,
                auth_table=self._auth.table, auth_idx=self._auth.rule_idx,
                sys_thresholds=self._sys,
                param_table=self._param.table,
                joint_idx=joint)
        flow_idx = self._flow.rule_idx[:, :kf]
        deg_idx = self._deg.rule_idx[:, :kd]
        return RuleSet(
            flow_table=self._flow.table,
            flow_idx=flow_idx,
            deg_table=self._deg.table,
            deg_idx=deg_idx,
            auth_table=self._auth.table, auth_idx=self._auth.rule_idx,
            sys_thresholds=self._sys,
            param_table=self._param.table).with_joint()

    def _rebuild_fastpath(self) -> None:
        """Recompute the host-fast-path classification after any rule load
        (see :mod:`sentinel_tpu.engine.fastpath`). Rows named by any rule
        are pinned in the registry, so classifications can't be stolen by
        LRU row recycling. Callers hold ``self._lock`` (all rule-swap
        paths); the ``__init__`` call runs before any thread exists."""
        if not self._fast_enabled:
            return
        inel: set = set()
        lease: dict = {}
        for r in self._deg.rules:
            inel.add(self.resources.get_or_create(r.resource))
        for r in self._auth.rules:
            inel.add(self.resources.get_or_create(r.resource))
        inel.update(self._param.by_row.keys())
        inel.update(self._cluster_rules_by_row.keys())
        inel.update(self._cluster_param_rules_by_row.keys())
        flow_by_row: dict = {}
        for r in self._flow.rules:
            row = self.resources.get_or_create(r.resource)
            flow_by_row.setdefault(row, []).append(r)
            if r.strategy == flow_mod.STRATEGY_RELATE and r.ref_resource:
                # RELATE reads the ref row's live counts — fast-path lag
                # there would skew this rule's decisions
                inel.add(self.resources.get_or_create(r.ref_resource))
        for row, rs in flow_by_row.items():
            r = rs[0]
            if (len(rs) == 1 and r.grade == flow_mod.GRADE_QPS
                    and r.control_behavior == flow_mod.BEHAVIOR_DEFAULT
                    and r.strategy == flow_mod.STRATEGY_DIRECT
                    and (r.limit_app or "default") == "default"
                    and not r.cluster_mode):
                lease[row] = float(r.count)
            else:
                inel.add(row)
        lease = {row: c for row, c in lease.items() if row not in inel}
        self._fast.set_tables(inel, lease, sys_active=bool(self._sys_rules))

    def load_flow_rules(self, rules: Sequence[flow_mod.FlowRule]) -> None:
        # buffered fast-path passes were admitted under the OLD tables —
        # land them before the swap or the flush would re-decide them
        self._flush_fast()
        cfg = self.cfg
        compiled = flow_mod.compile_flow_rules(
            rules, resource_registry=self.resources, context_registry=self.contexts,
            capacity=cfg.max_flow_rules, k_per_resource=cfg.max_rules_per_resource,
            num_rows=cfg.max_resources, cold_factor=float(cfg.cold_factor),
            origin_registry=self.origins)
        # cluster rules carry their rule-table SLOT position (k within the
        # per-resource rule gather) so a failed token request can re-enable
        # exactly that rule locally via a per-event bitmask — per-rule
        # fallbackToLocalOrPass (FlowRuleChecker.java:184-193), not one
        # all-or-nothing flag. Slot assignment mirrors compile_flow_rules.
        cluster_map: dict = {}
        slots_used: dict = {}
        for r in compiled.rules:
            row = self.resources.get_or_create(r.resource)
            k = slots_used.get(row, 0)
            slots_used[row] = k + 1
            if r.cluster_mode:
                cluster_map.setdefault(row, []).append((k, r))
        with self._lock:
            self._flow = compiled
            self._cluster_rules_by_row = cluster_map
            self._ruleset = self._build_ruleset()
            # fresh shaping state for the new tables (reference rebuilds
            # raters) — but occupy bookings are ROW-keyed promises already
            # granted to callers (the PriorityWait admission happened), so
            # they must survive the reload: LANDED bookings settle into
            # the second window as PASS (every rolling sum then reads the
            # same total it read from the booking ring) and PENDING ones
            # carry into the fresh ring (tests/test_occupy.py pins both)
            old_dyn = self._state.flow_dyn
            now_idx = self.spec.second.index_of(self.clock.now_ms())
            second, pend_cnt, pend_win = _jit_settle_occupied(
                self.spec.second)(
                self._state.second, old_dyn.occupied_count,
                old_dyn.occupied_window, jnp.int32(now_idx))
            if self.obs.enabled:
                # booking lifecycle at reload: pending bookings carry into
                # the fresh ring, landed ones settled as PASS — a cold
                # path, so the two device reads are acceptable here
                prev = int(np.asarray(
                    jax.device_get(old_dyn.occupied_count)).sum())
                carried = int(np.asarray(jax.device_get(pend_cnt)).sum())
                self.obs.counters.add(obs_keys.OCCUPY_CARRIED, carried)
                self.obs.counters.add(obs_keys.OCCUPY_SETTLED,
                                      max(0, prev - carried))
            fresh = flow_mod.init_flow_dyn(cfg.max_flow_rules,
                                           self.spec.second.buckets,
                                           self.spec.rows)
            fresh = fresh._replace(occupied_count=pend_cnt,
                                   occupied_window=pend_win)
            self._state = self._state._replace(second=second,
                                               flow_dyn=fresh)
            self._pin_state_locked()
            self._rebuild_fastpath()
            # release pins the new table no longer needs (mirrors the
            # compile's pin sites: resource, relate-ref resource,
            # chain-ref context, origin-specific limit_app)
            res: set = set()
            org: set = set()
            ctxs: set = set()
            for r in compiled.rules:
                res.add(r.resource)
                la = r.limit_app or "default"
                if la not in ("default", "other"):
                    org.add(la)
                if r.strategy == flow_mod.STRATEGY_RELATE:
                    res.add(r.ref_resource)
                elif r.strategy == flow_mod.STRATEGY_CHAIN:
                    ctxs.add(r.ref_resource)
            self._update_rule_pins_locked("flow", res, org, ctxs)
            # cold entries replay this settle at promote time with this
            # exact now_idx (tiering/coldtier.settle_entry_np)
            self.tiering.on_rules_reloaded_locked(now_idx)

    def set_token_service(self, svc) -> None:
        """Install the cluster token service used for cluster-mode flow rules
        (reference ``TokenClientProvider`` / embedded-server provider): any
        object with ``request_token(flow_id, count, prioritized=False) →
        TokenResult-like`` (``status``, ``wait_ms``). ``None`` uninstalls —
        cluster rules then take the fallback path."""
        self._token_service = svc

    # ------------------------------------------------------------------
    # Pluggable processor slots (SlotChainProvider / SlotChainBuilder SPI
    # analog — engine/slots.py; demo: demos/slot_spi.py)
    # ------------------------------------------------------------------

    def register_slot(self, slot) -> None:
        """Register a user processor slot WITHOUT editing the engine:
        a :class:`~sentinel_tpu.engine.slots.HostGate` runs on host before
        every dispatch (single + batch tiers); a
        :class:`~sentinel_tpu.engine.slots.DeviceSlot` is compiled into
        the fused decide step (re-jit at registration), with its own state
        slice carried in the engine state. Denials surface as
        :class:`CustomSlotException` carrying the slot's name and are
        recorded like every other block."""
        from sentinel_tpu.engine import slots as slots_mod

        # reason codes live in int8 verdict arrays: DeviceSlot i maps to
        # CUSTOM_BASE+i (must stay below CUSTOM_GATE_BASE), HostGate i to
        # CUSTOM_GATE_BASE+i (must stay below 128) — enforce the caps
        # loudly instead of silently wrapping into another slot's code
        max_dev = int(BlockReason.CUSTOM_GATE_BASE) - int(
            BlockReason.CUSTOM_BASE)
        max_gate = 128 - int(BlockReason.CUSTOM_GATE_BASE)
        if isinstance(slot, slots_mod.DeviceSlot):
            if len(self._device_slots) >= max_dev:
                raise ValueError(f"at most {max_dev} device slots")
            self._flush_fast()      # land buffered stats via the old step
            with self._lock:
                self._device_slots = self._device_slots + (slot,)
                # device slots must see EVERY event: the host fast path
                # (which bypasses the device) turns off while any are live
                self._fast_enabled = False
                self._reload_custom_jits_locked()
        elif isinstance(slot, slots_mod.HostGate):
            if len(self._host_gates) >= max_gate:
                raise ValueError(f"at most {max_gate} host gates")
            with self._lock:
                self._host_gates = self._host_gates + (slot,)
        else:
            raise TypeError(
                "slot must subclass HostGate or DeviceSlot (engine/slots.py)")

    def unregister_slot(self, slot) -> None:
        from sentinel_tpu.engine import slots as slots_mod

        if isinstance(slot, slots_mod.DeviceSlot):
            with self._lock:
                self._device_slots = tuple(
                    s for s in self._device_slots if s is not slot)
                self._fast_enabled = (bool(self.cfg.host_fast_path)
                                      and not self._device_slots)
                self._reload_custom_jits_locked()
        else:
            with self._lock:
                self._host_gates = tuple(
                    g for g in self._host_gates if g is not slot)

    def _refresh_shardings_locked(self) -> None:
        """Meshed mode: re-derive the sharding pytree from the CURRENT state
        structure (custom-slot registration / geometry changes alter it) and
        re-place every leaf on its canonical device layout."""
        if self.mesh is None:
            return
        from sentinel_tpu.parallel.local_shard import (
            pin_state, shardings_for,
        )
        self._mesh_shardings = shardings_for(self.spec, self.mesh,
                                             self._state)
        self._state = pin_state(self._state, self._mesh_shardings[0])

    def _uncount_step(self):
        """Lease-uncount step; the meshed variant pins the state output to
        the canonical shardings (the global cache can't — it's keyed on spec
        alone and shardings are per-instance). Cached per (spec, shardings)
        on the instance so flushes don't retrace."""
        if self.mesh is None:
            return _jit_uncount_reserved(self.spec)
        cached = getattr(self, "_uncount_cache", None)
        # identity compare on the live shardings object (a freed tuple's id
        # could be reused; holding the reference makes 'is' sound)
        if (cached is None or cached[0] is not self._mesh_shardings
                or cached[1] != self.spec):
            from sentinel_tpu.engine.pipeline import uncount_reserved
            fn = jax.jit(functools.partial(uncount_reserved, self.spec),
                         out_shardings=self._mesh_shardings[0])
            self._uncount_cache = cached = (self._mesh_shardings, self.spec,
                                            fn)
        return cached[2]

    def _update_rule_pins_locked(self, family: str, res: set, org: set,
                                 ctx: set) -> None:
        """Refcounted rule-pin release (round 15): each rule family
        registers the (resource, origin, context) names its CURRENT
        compiled table pins; names the previous table pinned that no
        family references anymore are unpinned, so formerly ruled keys
        become evictable — and hence demotable to the cold tier.
        Pre-round-15 compile-time pins leaked forever, which would have
        made every rule-bound row a permanent hot-tier resident. Must
        run AFTER the table swap: until then the old table still
        addresses the old rows. Reserved rows (ENTRY node, origin "")
        are pinned at construction outside this ledger and never appear
        in rule sets."""
        old = self._rule_pins.get(family, (set(), set(), set()))
        new = (set(res), set(org), set(ctx))
        self._rule_pins[family] = new
        regs = (self.resources, self.origins, self.contexts)
        for kind in range(3):
            still: set = set()
            for fam, sets in self._rule_pins.items():
                if fam != family:
                    still |= sets[kind]
            for name in old[kind] - new[kind] - still:
                regs[kind].unpin(name)
        # pin-path interns bypass intern_resources: a newly ruled key
        # that sits in the COLD tier just got a fresh (zeroed) row from
        # the pin's alloc — classify it so the next eviction drain
        # promotes its window/booking state before any rule evaluates
        # against the zeroed row. tick=False: rule loads are control
        # plane, not serving traffic — the hit-rate counters stay pure.
        if self.tiering.enabled and res:
            pairs = [(n, r) for n, r in
                     ((n, self.resources.lookup(n)) for n in res)
                     if r is not None]
            if pairs:
                self.tiering.note_interned(
                    [p[0] for p in pairs], [p[1] for p in pairs],
                    tick=False)

    def _pin_state_locked(self) -> None:
        """Re-place state leaves after host code rebuilt some of them
        (rule reloads swap in fresh unsharded arrays); no-op without a
        mesh, and a cheap no-op for leaves already placed correctly."""
        if self.mesh is not None:
            from sentinel_tpu.parallel.local_shard import pin_state
            self._state = pin_state(self._state, self._mesh_shardings[0])

    def _reload_custom_jits_locked(self) -> None:
        self._state = self._state._replace(custom=tuple(
            s.init_state(self.spec) for s in self._device_slots))
        self._refresh_shardings_locked()    # custom states change structure
        (self._jit_decide, self._jit_decide_prio,
         self._jit_decide_noalt, self._jit_decide_prio_noalt,
         self._jit_exit, self._jit_exit_noalt,
         self._jit_invalidate, self._jit_record_blocks,
         self._jit_fused_steps) = \
            _jitted_steps(self.spec, self._device_slots,
                          self._mesh_shardings, donate=self._donate)
        self._sd_steps = None       # sketch-fused variants track the 9-tuple

    def _sd_steps_locked(self):
        """Round-16 sketch-fused serving programs, built lazily (engine
        lock held — the builder reads live geometry / shardings /
        telemetry layout; plain-geometry engines share the process-wide
        :func:`_sd_steps_cached` compilations). Never consulted with
        ``SENTINEL_SINGLE_DISPATCH`` off."""
        if self._sd_steps is None:
            if self._device_slots or self._mesh_shardings is not None \
                    or self.mesh is not None:
                self._sd_steps = _build_sd_steps(
                    self.spec, self._device_slots, self._mesh_shardings,
                    donate=self._donate, mesh=self.mesh,
                    tel_k=self.telemetry.k,
                    tel_rows_per_shard=self.telemetry._rows_per_shard)
            else:
                self._sd_steps = _sd_steps_cached(
                    self.spec, self._donate, self.telemetry.k,
                    self.telemetry._rows_per_shard)
        return self._sd_steps

    def _slot_code(self, kind: str, index: int) -> int:
        """Reason code for a custom slot denial (disjoint sub-spaces: the
        pipeline emits CUSTOM_BASE+i for DeviceSlot i; host gates use
        CUSTOM_GATE_BASE+i)."""
        return (int(BlockReason.CUSTOM_GATE_BASE) + index if kind == "gate"
                else int(BlockReason.CUSTOM_BASE) + index)

    def slot_name_for_code(self, code: int) -> str:
        """Registered slot name for a CUSTOM_BASE+ reason code."""
        code = int(code)
        if code >= BlockReason.CUSTOM_GATE_BASE:
            i = code - int(BlockReason.CUSTOM_GATE_BASE)
            return (self._host_gates[i].name  # graftlint: disable=LOCK002 -- diagnostic lookup over append-only slot lists; a stale read names the previous slot
                    if i < len(self._host_gates) else "unknown-slot")  # graftlint: disable=LOCK002 -- diagnostic lookup over append-only slot lists; a stale read names the previous slot
        i = code - int(BlockReason.CUSTOM_BASE)
        return (self._device_slots[i].name  # graftlint: disable=LOCK002 -- diagnostic lookup over append-only slot lists; a stale read names the previous slot
                if i < len(self._device_slots) else "unknown-slot")  # graftlint: disable=LOCK002 -- diagnostic lookup over append-only slot lists; a stale read names the previous slot

    def _run_host_gates_one(self, resource: str, origin: str, acquire: int,
                            args: Sequence, row: int, o_row: int, c_row: int,
                            is_in: bool) -> None:
        """Run the registered gates for one entry; raises on denial after
        recording the block (StatisticSlot parity)."""
        for gi, gate in enumerate(self._host_gates):  # graftlint: disable=LOCK002 -- gate list is append-only and published whole; iterating a stale snapshot is the SPI contract
            exc = None
            try:
                ok = gate.check(resource, origin, acquire, args)
            except BlockException as e:
                ok, exc = False, e
            if not ok:
                raise self._record_cluster_block(
                    self._slot_code("gate", gi), resource, origin, row,
                    o_row, c_row, acquire, is_in, exc=exc,
                    slot_name=gate.name)

    def _run_host_gates_batch(self, resources, origins, acq, args_list,
                              is_in, n: int):
        """→ (blocked bool[n], reasons int32[n]); denials are block-logged
        here (the device record happens batched upstream)."""
        blocked = np.zeros(n, np.bool_)
        reasons = np.zeros(n, np.int32)
        for gi, gate in enumerate(self._host_gates):  # graftlint: disable=LOCK002 -- gate list is append-only and published whole; iterating a stale snapshot is the SPI contract
            oks = np.asarray(gate.check_batch(resources, origins, acq,
                                              args_list), np.bool_)
            newly = ~oks & ~blocked
            if newly.any():
                code = self._slot_code("gate", gi)
                reasons[newly] = code
                blocked |= newly
                for i in np.nonzero(newly)[0].tolist():
                    org = (origins[i] if origins is not None
                           and origins[i] else "")
                    self._log_cluster_block(code, resources[i], org,
                                            int(acq[i]))
        return blocked, reasons

    def load_degrade_rules(self, rules: Sequence[deg_mod.DegradeRule]) -> None:
        # buffered fast-path passes were admitted under the OLD tables —
        # land them before the swap or the flush would re-decide them
        self._flush_fast()
        cfg = self.cfg
        compiled = deg_mod.compile_degrade_rules(
            rules, resource_registry=self.resources, capacity=cfg.max_degrade_rules,
            k_per_resource=cfg.max_rules_per_resource, num_rows=cfg.max_resources)
        with self._lock:
            self._deg = compiled
            self._ruleset = self._build_ruleset()
            self._state = self._state._replace(
                breakers=deg_mod.init_breaker_state(cfg.max_degrade_rules))
            self._pin_state_locked()
            self._rebuild_fastpath()
            self._update_rule_pins_locked(
                "degrade", {r.resource for r in compiled.rules}, set(),
                set())

    def load_param_flow_rules(self, rules: Sequence[pf_mod.ParamFlowRule]) -> None:
        self._user_param_rules = list(rules)
        self._reload_param_rules()

    def set_gateway_param_rules(self, rules: Sequence[pf_mod.ParamFlowRule]) -> None:
        """Install gateway-converted param rules (GatewayRuleManager path);
        merged with user param rules into the single param slot."""
        self._gateway_param_rules = list(rules)
        self._reload_param_rules()

    def _reload_param_rules(self) -> None:
        self._flush_fast()      # see load_flow_rules
        cfg = self.cfg
        all_rules = self._user_param_rules + self._gateway_param_rules
        # cluster-mode param rules delegate to the token server
        # (ParamFlowChecker.passClusterCheck → requestParamToken); only the
        # local ones compile into the device table
        rules = [r for r in all_rules if not r.cluster_mode]
        cluster_map: dict = {}
        for r in all_rules:
            if r.cluster_mode:
                row = self.resources.get_or_create(r.resource)
                cluster_map.setdefault(row, []).append(r)
        compiled = pf_mod.compile_param_rules(
            rules, resource_registry=self.resources,
            capacity=cfg.max_param_rules,
            k_per_resource=cfg.max_rules_per_resource)
        with self._lock:
            self._cluster_param_rules_by_row = cluster_map
            self._param = compiled
            self._ruleset = self._build_ruleset()
            # rule slots changed meaning: fresh key interning + cold key state
            # (ParameterMetricStorage re-initializes metrics per rule)
            self.param_key_registry = pf_mod.make_param_key_registry(cfg.param_table_slots)
            self._param_gen += 1
            self._state = self._state._replace(
                param_dyn=pf_mod.init_param_dyn(self.spec.param_keys))
            self._pin_state_locked()
            self._rebuild_fastpath()
            # cluster-mode param rules don't compile into the device
            # table but their rows must stay resident for delegation
            self._update_rule_pins_locked(
                "param", {r.resource for r in compiled.rules}
                | {r.resource for r in all_rules if r.cluster_mode},
                set(), set())

    def load_system_rules(self, rules: Sequence[sys_mod.SystemRule]) -> None:
        # buffered fast-path passes were admitted under the OLD tables —
        # land them before the swap or the flush would re-decide them
        self._flush_fast()
        with self._lock:
            self._sys_rules = list(rules)
            self._sys = sys_mod.compile_system_rules(rules)
            self._ruleset = self._build_ruleset()
            self._rebuild_fastpath()

    def load_authority_rules(self, rules: Sequence[auth_mod.AuthorityRule]) -> None:
        # buffered fast-path passes were admitted under the OLD tables —
        # land them before the swap or the flush would re-decide them
        self._flush_fast()
        cfg = self.cfg
        compiled = auth_mod.compile_authority_rules(
            rules, resource_registry=self.resources, origin_registry=self.origins,
            capacity=cfg.max_authority_rules, k_per_resource=2,
            num_rows=cfg.max_resources)
        with self._lock:
            self._auth = compiled
            self._ruleset = self._build_ruleset()
            self._rebuild_fastpath()
            org: set = set()
            for r in compiled.rules:
                org.update(o.strip() for o in r.limit_app.split(",")
                           if o.strip())
            self._update_rule_pins_locked(
                "authority", {r.resource for r in compiled.rules}, org,
                set())

    def update_window_geometry(self, sample_count: Optional[int] = None,
                               interval_ms: Optional[int] = None) -> None:
        """Live second-window geometry change — the
        ``SampleCountProperty``/``IntervalProperty`` analog
        (``node/SampleCountProperty.java``: the reference swaps fresh
        LeapArrays into every node). Second windows and flow shaping state
        cold-reset (history discard is the reference semantic); the minute
        ring, thread gauges, breakers and hot-param state carry over. The
        engine re-jits for the new geometry and host leases are dropped."""
        import dataclasses as _dc

        sc = int(sample_count if sample_count is not None
                 else self.cfg.second_sample_count)
        iv = int(interval_ms if interval_ms is not None
                 else self.cfg.second_interval_ms)
        if sc <= 0 or iv <= 0 or iv % sc:
            raise ValueError(
                "interval_ms must be a positive multiple of sample_count")
        self._flush_fast()      # land buffered stats on the OLD geometry
        with self._lock:
            if (sc == self.cfg.second_sample_count
                    and iv == self.cfg.second_interval_ms):
                return
            self.cfg = _dc.replace(self.cfg, second_sample_count=sc,
                                   second_interval_ms=iv)
            new_second = WindowSpec(sc, iv // sc)
            self.spec = _dc.replace(self.spec, second=new_second)
            self._state = self._state._replace(
                second=init_window(new_second, self.spec.rows),
                alt_second=init_window(new_second, self.spec.alt_rows),
                flow_dyn=flow_mod.init_flow_dyn(
                    self.cfg.max_flow_rules, new_second.buckets,
                    self.spec.rows))
            self._refresh_shardings_locked()
            (self._jit_decide, self._jit_decide_prio,
             self._jit_decide_noalt, self._jit_decide_prio_noalt,
             self._jit_exit, self._jit_exit_noalt,
             self._jit_invalidate, self._jit_record_blocks,
             self._jit_fused_steps) = \
                _jitted_steps(self.spec, self._device_slots,
                              self._mesh_shardings, donate=self._donate)
            self._sd_steps = None   # sketch-fused variants track the 9-tuple
            self._occupy_live_until_ms = -1
            self._seen_idx = -(2 ** 62)
            self._fast.win_ms = max(1, new_second.win_ms)
            self._rebuild_fastpath()     # drops leases against old buckets
            # tiering: cold entries + in-flight demote payloads carry
            # OLD-geometry second windows and booking rings; land the
            # in-flight ones, then cold-reset every cold entry to the
            # new bucket count (the same reset resident rows just got)
            # so a later promote can't scatter mismatched shapes
            self.tiering.on_geometry_changed_locked()

    def set_global_switch(self, on: bool) -> None:
        """Reference setSwitch command — off = everything passes unchecked."""
        self._global_on = bool(on)

    @property
    def threads_elided(self) -> bool:
        """True while thread-gauge maintenance is compiled away (no loaded
        rule reads live concurrency): ``curThreadNum``-style gauges read 0
        regardless of traffic. Observability payloads carry this as
        ``threadsElided`` so an operator can't mistake an elided 0 for an
        idle system (docs/OPERATIONS.md "Live-concurrency gauges")."""
        return bool(getattr(self, "_skip_threads", False))

    # ------------------------------------------------------------------
    # Lifecycle (shutdown registry + close)
    # ------------------------------------------------------------------

    def register_shutdown(self, service) -> None:
        """Register a service for :meth:`close` — anything with a
        ``stop()`` or ``close()`` method (``MetricTimerListener`` and
        ``PrometheusExporter`` self-register at construction). Stopped
        LIFO, each at most once; double registration is deduplicated so
        re-wiring a service across restarts can't double-stop it."""
        if not any(service is s for s in self._shutdown_hooks):
            self._shutdown_hooks.append(service)

    def close(self) -> None:
        """Idempotent runtime teardown: flush buffered fast-path stats,
        stop every registered service (daemon threads joined — no thread
        leak across repeated open/close), close self-telemetry and the
        block log. The engine object stays readable (snapshots work) but
        should not dispatch after close."""
        if self._closed:
            return
        self._closed = True
        try:
            self._flush_fast()
        except Exception:       # closing must not depend on device health
            pass
        hooks, self._shutdown_hooks = self._shutdown_hooks, []
        for svc in reversed(hooks):
            fn = getattr(svc, "stop", None) or getattr(svc, "close", None)
            if fn is None:
                continue
            try:
                fn()
            except Exception:   # one bad service must not leak the rest
                pass
        self.obs.close()
        try:
            self.block_log.close()
        except Exception:       # pragma: no cover - appender already gone
            pass

    def __enter__(self) -> "Sentinel":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    def frontend(self, **kwargs):
        """A new :class:`~sentinel_tpu.frontend.AdaptiveBatcher` ingest
        tier over this runtime (kwargs pass through: batch_max,
        deadline_ms, budget_ms, idle_ms, queue_max, depth, ...). The
        batcher self-registers with :meth:`register_shutdown`, so
        :meth:`close` tears it down. One batcher per event loop.

        Tuned-config application (round 11): any of those kwargs the
        caller leaves unset is filled from the ``SENTINEL_TUNED_CONFIG``
        artifact resolved at construction — but only for knobs whose env
        var is also unset (explicit kwarg > explicit env > artifact >
        the batcher's built-in defaults)."""
        from sentinel_tpu.frontend import AdaptiveBatcher
        from sentinel_tpu.tune import FRONTEND_KWARG_ENVS
        for kw, env in FRONTEND_KWARG_ENVS:
            if kw not in kwargs and env in self._tuned:
                kwargs[kw] = self._tuned[env]
        return AdaptiveBatcher(self, **kwargs)

    # ------------------------------------------------------------------
    # Time helpers
    # ------------------------------------------------------------------

    def _rel_ms(self, now_ms: int) -> int:
        return int((now_ms - self.epoch_ms + 2 ** 31) % 2 ** 32 - 2 ** 31)

    def _time_scalars(self, now_ms: int):
        """Packed int32[4] time vector: ONE host→device transfer per step
        (per-scalar transfers are hot-path latency on a tunneled TPU)."""
        s = self.spec
        idx_s = s.second.index_of(now_ms)
        idx_m = s.minute.index_of(now_ms) if s.minute else 0
        return jnp.asarray(np.array(
            [idx_s, idx_m, self._rel_ms(now_ms),
             now_ms % s.second.win_ms], np.int32))

    def _restamp_if_stale_locked(self, at_ms: Optional[int], now: int,
                                 times):
        """Safe-late re-stamp for event-time (``at_ms``) dispatches,
        atomic with ``_seen_idx`` — callers hold ``_lock``. A stamp a
        full window ring older than anything already dispatched would
        re-own a physical bucket a newer write holds: the device-side
        refresh zeroes that bucket's LIVE counts, resurrecting spent
        admission budget mid-window (real over-admission, caught by
        test_fastpath's deterministic overadmit harness). The fast-path
        flush pre-checks the same condition, but reads ``_seen_idx``
        outside this lock — a decide landing between its check and this
        dispatch makes the stale stamp dangerous, so the authoritative
        check lives here."""
        if (at_ms is not None
                and self._seen_idx - self.spec.second.index_of(now)
                >= self.spec.second.buckets):
            now = self.clock.now_ms()
            times = self._time_scalars(now)
        return now, times

    # ------------------------------------------------------------------
    # Per-call API
    # ------------------------------------------------------------------

    def entry(self, resource: str, *, origin: Optional[str] = None,
              acquire: int = 1, entry_type: int = ENTRY_TYPE_IN,
              prioritized: bool = False, args: Sequence = (),
              resource_type: int = 0, sleep: bool = True) -> Entry:
        """Guard a call. Raises a BlockException subclass when denied;
        sleeps (via the clock) on pass-with-wait verdicts. ``args`` are the
        call's parameters for hot-param rules (``SphU.entry(name, args)``).
        ``sleep=False`` skips the pacing sleep and instead reports it on
        ``Entry.wait_ms`` so async callers can await it (the cluster
        protocol's ``TokenResult.waitInMs`` pattern generalized locally)."""
        if not self._global_on:
            now = self.clock.now_ms()
            return Entry(self, resource, -1, -1, -1, acquire,
                         entry_type == ENTRY_TYPE_IN, now)
        ctx = current_context()
        use_origin = ctx.origin if origin is None else origin
        # resolve rows ONCE; the same rows feed the verdict and the Entry so
        # an LRU eviction between lookups can't skew exit accounting
        row = self.resources.get_or_create(resource)
        if self.tiering.enabled:
            # classify + queue promotion if this key's state is cold
            self.tiering.note_interned((resource,), (row,))
        if resource_type:   # ResourceTypeConstants classification for metrics
            self.resource_types[resource] = resource_type
        origin_id = self.origins.get_or_create(use_origin) if use_origin else 0
        o_row, c_row = self._alt_rows_for(row, use_origin, ctx.name)
        context_id = (self.contexts.get_or_create(ctx.name)
                      if c_row < self.spec.alt_rows else 0)
        is_in = entry_type == ENTRY_TYPE_IN

        # user host gates veto before anything else (slot-chain SPI tier 1)
        if self._host_gates:  # graftlint: disable=LOCK002 -- hot-path feature gate: a stale read routes one call through the exact device path, never unsafely
            self._run_host_gates_one(resource, use_origin or "", acquire,
                                     args, row, o_row, c_row, is_in)

        # host fast path: rule-free rows admit on host with batched stat
        # recording; single-simple-QPS rows serve from a device
        # pre-charged lease (engine/fastpath.py). Falls through to the
        # exact device path for everything else.
        if self._fast_enabled and not prioritized:  # graftlint: disable=LOCK002 -- hot-path feature gate: a stale read routes one call through the exact device path, never unsafely
            fe = self._fast_entry(resource, row, o_row, c_row, origin_id,
                                  use_origin or "", acquire, is_in, args)
            if fe is not None:
                return fe
        if self._fast_enabled and self._fast.due(self.clock.now_ms()):  # graftlint: disable=LOCK002 -- hot-path feature gate: a stale read routes one call through the exact device path, never unsafely
            self._flush_fast()     # keep buffered stats fresh under mixed
            # fast/slow traffic (the device sees them before this decide)

        # cluster-mode rules: token-server delegation BEFORE the local
        # pipeline (FlowRuleChecker.passClusterCheck); failed requests with
        # fallbackToLocalWhenFail re-enable exactly those rules locally
        # (per-rule slot bitmask)
        cluster_fb = 0
        cluster_wait = 0
        crules = self._cluster_rules_by_row.get(row)
        if crules:
            cluster_fb, cluster_wait = self._cluster_check(
                resource, use_origin or "", row, o_row, c_row, acquire,
                is_in, prioritized, crules, sleep)
        cprules = self._cluster_param_rules_by_row.get(row)
        if cprules and args:
            cluster_wait += self._cluster_param_check(
                resource, use_origin or "", row, o_row, c_row, acquire,
                is_in, args, cprules, sleep)

        pairs = self._resolve_param_pairs_one(row, args)
        pr = pk = None
        if pairs is not None:
            pr = pairs[0][None, :]
            pk = pairs[1][None, :]
        try:
            verdict = self.decide_raw(
                np.array([row], np.int32), np.array([origin_id], np.int32),
                np.array([o_row], np.int32), np.array([context_id], np.int32),
                np.array([c_row], np.int32), np.array([acquire], np.int32),
                np.array([is_in], np.bool_), np.array([prioritized], np.bool_),
                param_rules=pr, param_keys=pk,
                param_gen=pairs[2] if pairs is not None else -1,
                cluster_fallback=(np.array([cluster_fb], np.int32)
                                  if cluster_fb else None))
            if not bool(verdict.allow[0]):
                rcode = int(verdict.reason[0])
                exc = block_exception_for(
                    rcode, resource, origin=use_origin,
                    slot_name=(self.slot_name_for_code(rcode)
                               if rcode >= BlockReason.CUSTOM_BASE else ""))
                # LogSlot: block events roll into sentinel-block.log
                self.block_log.log(resource, type(exc).__name__,
                                   origin=use_origin or "")
                if self.obs.enabled:
                    self._obs_block(resource, rcode, use_origin or "", 1)
                if not self.callbacks.empty:   # StatisticSlot onBlocked
                    self.callbacks.fire_blocked(resource, use_origin or "",
                                                acquire, exc)
                raise exc
        except BaseException:
            if pairs is not None:   # blocked entries never exit → unpin now
                pairs[3].unpin_rows(pairs[4])
            raise
        if not self.callbacks.empty:           # StatisticSlot onPass
            self.callbacks.fire_pass(resource, use_origin or "", acquire,
                                     args)
        wait = int(verdict.wait_ms[0])
        if wait > 0 and sleep:
            self.clock.sleep_ms(wait)
        if not sleep:
            wait += cluster_wait     # cluster SHOULD_WAIT surfaces here too
        now = self.clock.now_ms()
        # sleep=False: project create_ms past the wait the caller will await,
        # so rt excludes pacing delay exactly like the sleep=True path
        e = Entry(self, resource, row, o_row, c_row, acquire, is_in,
                  now if sleep else now + wait, param_pairs=pairs)
        if not sleep:
            e.wait_ms = wait
        return e

    def _record_cluster_block(self, reason: int, resource: str, origin: str,
                              row: int, o_row: int, c_row: int,
                              acquire: int, is_in: bool, exc=None,
                              slot_name: str = "") -> BlockException:
        """Record + log + fire callbacks for a denial decided off-device
        (token server or host gate); returns the exception for the caller
        to raise (StatisticSlot accounting for blocks decided off-device).
        ``exc`` overrides the constructed exception (a gate raising its own
        BlockException subclass propagates it)."""
        times = self._time_scalars(self.clock.now_ms())
        with self._lock:
            self._state = self._jit_record_blocks(
                self._state,
                jnp.asarray(np.array([row], np.int32)),
                jnp.asarray(np.array([o_row], np.int32)),
                jnp.asarray(np.array([c_row], np.int32)),
                jnp.asarray(np.array([acquire], np.int32)),
                jnp.asarray(np.array([is_in], np.bool_)),
                jnp.asarray(np.array([True], np.bool_)),
                times)
        return self._log_cluster_block(reason, resource, origin, acquire,
                                       exc=exc, slot_name=slot_name)

    def _cluster_check(self, resource: str, origin: str, row: int,
                       o_row: int, c_row: int, acquire: int, is_in: bool,
                       prioritized: bool, crules,
                       sleep: bool = True,
                       record: bool = True) -> Tuple[int, int]:
        """``passClusterCheck`` for this resource's cluster-mode rules.
        ``crules`` is a list of ``(slot_k, rule)`` pairs (slot = the rule's
        position in the per-resource rule gather). Returns
        ``(fallback_bits, pending_wait_ms)`` where bit k of ``fallback_bits``
        re-enables exactly slot k's rule in the local pipeline — per-rule
        ``fallbackToLocalOrPass`` (FlowRuleChecker.java:184-193), so mixed
        grant/failure locally enforces only the failed rules. Raises
        FlowException on BLOCKED and records the block like StatisticSlot
        would. TOO_MANY_REQUEST (server overload, status -2) degrades to the
        fallback path like FAIL — it never denies outright
        (FlowRuleChecker.applyTokenResult). With ``sleep=False`` SHOULD_WAIT
        waits are returned instead of slept (async callers await them via
        ``Entry.wait_ms``)."""
        svc = self._token_service
        fallback_bits = 0
        pending_wait = 0
        for slot_k, r in crules:
            status, wait = -1, 0           # FAIL when no service installed
            if svc is not None:
                try:
                    res = svc.request_token(r.cluster_flow_id, acquire,
                                            prioritized)
                    status = int(res.status)
                    wait = int(getattr(res, "wait_ms", 0))
                except Exception as exc:
                    from sentinel_tpu.core.logs import record_log
                    record_log().warning(
                        "cluster token request failed: %r", exc)
            if status == 0:                # OK
                continue
            if status == 2:                # SHOULD_WAIT → sleep, then pass
                if wait > 0:
                    if sleep:
                        self.clock.sleep_ms(wait)
                    else:
                        pending_wait += wait
                continue
            if status == 1:                # BLOCKED
                if record:
                    raise self._record_cluster_block(
                        int(BlockReason.FLOW), resource, origin, row,
                        o_row, c_row, acquire, is_in)
                raise self._log_cluster_block(int(BlockReason.FLOW),
                                              resource, origin, acquire)
            # FAIL / NO_RULE_EXISTS / BAD_REQUEST / TOO_MANY_REQUEST
            # → local check (iff fallbackToLocalWhenFail) or pass
            if r.cluster_fallback_to_local:
                fallback_bits |= 1 << slot_k
        return fallback_bits, pending_wait

    def _cluster_param_check(self, resource: str, origin: str, row: int,
                             o_row: int, c_row: int, acquire: int,
                             is_in: bool, args: Sequence, cprules,
                             sleep: bool = True, record: bool = True) -> int:
        """``ParamFlowChecker.passClusterCheck`` → ``requestParamToken`` for
        cluster-mode hot-param rules. BLOCKED raises ParamFlowException and
        (when ``record``) records the block; ``record=False`` lets the batch
        tier record all cluster blocks in ONE device call instead.
        TOO_MANY_REQUEST (server overload) passes through like FAIL — it
        never denies (ParamFlowChecker.passClusterCheck fallback). The local
        fallback for param rules is a documented pass-through here — the
        flow path carries the exact local fallback."""
        svc = self._token_service
        pending_wait = 0
        for r in cprules:
            idx = r.param_idx if r.param_idx >= 0 else len(args) + r.param_idx
            if idx < 0 or idx >= len(args):
                continue                      # no such arg → rule passes
            value = args[idx]
            status, wait = -1, 0
            if svc is not None:
                try:
                    res = svc.request_param_token(r.cluster_flow_id, acquire,
                                                  [value])
                    status = int(res.status)
                    wait = int(getattr(res, "wait_ms", 0))
                except Exception as exc:
                    from sentinel_tpu.core.logs import record_log
                    record_log().warning(
                        "cluster param token request failed: %r", exc)
            if status == 0:
                continue
            if status == 2:
                if wait > 0:
                    if sleep:
                        self.clock.sleep_ms(wait)
                    else:
                        pending_wait += wait
                continue
            if status == 1:                   # BLOCKED
                if record:
                    raise self._record_cluster_block(
                        int(BlockReason.PARAM_FLOW), resource, origin, row,
                        o_row, c_row, acquire, is_in)
                raise self._log_cluster_block(int(BlockReason.PARAM_FLOW),
                                              resource, origin, acquire)
            # FAIL / NO_RULE / TOO_MANY: pass through (logged when RPC failed)
        return pending_wait

    def _resolve_param_pairs_one(self, row: int, args: Sequence):
        """→ (rules [PV], keys [PV], generation, registry), or None when the
        resource has no param rules / no args (rule-free events skip the
        param slot). Table, registry and generation are snapshotted together
        under the lock so they are mutually consistent. The key rows come
        back PINNED against LRU recycling (so a concurrent intern flood can't
        recycle them between decide and exit); the caller owns the unpin —
        on block, or after the exit-side decrement."""
        with self._lock:
            compiled = self._param
            registry = self.param_key_registry
            gen = self._param_gen
        if not compiled.num_active or not args:
            return None
        if row not in compiled.by_row:
            return None
        pr, pk = pf_mod.resolve_pairs(compiled, registry, row, args,
                                      self.spec.param_pairs)
        pins = pf_mod.thread_key_rows(compiled, pr, pk)
        registry.pin_rows(pins)
        return (pr, pk, gen, registry, pins)

    def _alt_row(self, row: int, kind: int, key_id: int) -> int:
        """Hash + record the (main row → alt row) edge for eviction
        hygiene. The slot's host identity ``(kind, key_id)`` travels
        with the edge so the tiering demote can snapshot the slice under
        a portable key and the promote can re-hash it onto the new row
        (tiering/manager.py)."""
        r = _alt_hash(row, kind, key_id, self.spec.alt_rows)
        self._alt_rows_by_row.setdefault(row, {})[r] = (kind, key_id)
        return r

    def _alt_rows_for(self, row: int, origin: str, context_name: str):
        ra = self.spec.alt_rows
        o_row = ra
        c_row = ra
        if origin:
            o_row = self._alt_row(row, 0, self.origins.get_or_create(origin))
        if context_name and context_name != "sentinel_default_context":
            c_row = self._alt_row(row, 1, self.contexts.get_or_create(context_name))
        return o_row, c_row

    def _fast_entry(self, resource: str, row: int, o_row: int, c_row: int,
                    origin_id: int, origin: str, acquire: int,
                    is_in: bool, args: Sequence = ()) -> Optional[Entry]:
        """Try the host fast path → an admitted :class:`Entry`, or None to
        take the exact device path (never decides a DENIAL on host)."""
        fast = self._fast
        if fast.sys_active and is_in:
            return None          # SystemSlot gates inbound traffic globally
        kind = fast.classify(row)
        if kind == fp_mod.INELIGIBLE:
            return None
        now = self.clock.now_ms()
        if kind == fp_mod.FREE:
            fast.buffer_pass(row, o_row, c_row, acquire, is_in, now)
            mode = "free"
        else:
            # leases pre-charge stats without alt rows, so they only serve
            # origin-less, default-context events; others need per-event
            # recording → device path
            if origin_id != 0 or c_row < self.spec.alt_rows:
                return None
            verdict = fast.lease_state(row, acquire, is_in, now)
            if verdict == fp_mod.DEVICE:
                return None
            if verdict == fp_mod.RENEW:
                if fast.is_hot(row, now):
                    return None    # chunk denied this bucket: exact path
                # single renewal in flight per row: a concurrent pre-charge
                # would double-spend the window budget (under-admission)
                if not fast.begin_renewal(row):
                    return None
                try:
                    # re-check under the claim (another thread may have
                    # installed a lease between lease_state and here)
                    recheck = fast.lease_state(row, acquire, is_in, now)
                    if recheck == fp_mod.DEVICE:
                        # a mismatched-entry-type lease went live meanwhile:
                        # pre-charging a second chunk would double-spend
                        # the window — exactly what DEVICE exists to avoid
                        return None
                    if recheck != fp_mod.ADMIT:
                        chunk = fast.lease_chunk(row, acquire)
                        gen0 = fast.table_gen
                        ra = self.spec.alt_rows
                        # at_ms=now: the chunk's PASS must land in the SAME
                        # bucket the lease is stamped with — a rotation
                        # mid-pre-charge would otherwise make the expiry
                        # uncount target a bucket that never held the chunk
                        v = self.decide_raw(
                            np.array([row], np.int32), np.zeros(1, np.int32),
                            np.array([ra], np.int32), np.zeros(1, np.int32),
                            np.array([ra], np.int32),
                            np.array([chunk], np.int32),
                            np.array([is_in], np.bool_),
                            np.zeros(1, np.bool_),
                            count_thread=np.zeros(1, np.bool_),
                            record_block=np.zeros(1, np.bool_),
                            at_ms=now)
                        if not bool(v.allow[0]):
                            fast.mark_hot(row, now)
                            return None
                        fast.install_lease(row, chunk, acquire, is_in, now,
                                           gen=gen0)
                finally:
                    fast.end_renewal(row)
            mode = "leased"
        if not self.callbacks.empty:   # StatisticSlot onPass
            self.callbacks.fire_pass(resource, origin, acquire, args)
        e = Entry(self, resource, row, o_row, c_row, acquire, is_in, now)
        e.fast = mode
        if fast.due(now):
            self._flush_fast(now)
        return e

    def _flush_fast(self, now_ms: Optional[int] = None) -> None:
        """Land buffered fast-path stats on device with their EVENT-TIME
        window stamps: groups are keyed by second-window index and each
        group dispatches with its own times, so late flushes (idle gaps,
        introspection pulls) still attribute pass/success to the second
        they happened in — reference exit-time recording semantics. Groups
        older than a full window ring relative to anything already
        dispatched are re-stamped to now (safe-late): stamping them old
        could resurrect a physical bucket a newer write already owns.
        Passes go through the normal jitted decide (rule-free events can't
        block → pure StatisticSlot recording), exits through the batched
        exit step."""
        now = self.clock.now_ms() if now_ms is None else now_ms
        with self._flush_lock:
            self._flush_fast_locked(now)

    def _flush_fast_locked(self, now: int) -> None:
        passes, exits, expired = self._fast.drain(now)
        if not passes and not exits and not expired:
            return
        B = self.spec.second.buckets
        idx_of = self.spec.second.index_of

        def grouped(events, ms_pos):
            by: dict = {}
            for e in events:
                by.setdefault(idx_of(e[ms_pos]), []).append(e)
            return sorted(by.items())

        for g_idx, grp in grouped(passes, 5):
            at = grp[0][5] if self._seen_idx - g_idx < B else None
            n = len(grp)
            self.decide_raw_nowait(
                np.fromiter((p[0] for p in grp), np.int32, n),
                np.zeros(n, np.int32),
                np.fromiter((p[1] for p in grp), np.int32, n),
                np.zeros(n, np.int32),
                np.fromiter((p[2] for p in grp), np.int32, n),
                np.fromiter((p[3] for p in grp), np.int32, n),
                np.fromiter((p[4] for p in grp), np.bool_, n),
                np.zeros(n, np.bool_),     # verdicts unused: all rule-free
                at_ms=at)
        if expired:
            # return unused lease tokens to their window buckets (pass
            # metrics then reflect actual admissions, not reservations);
            # is_in pre-charges also counted the ENTRY node
            rows: list = []
            secs: list = []
            mins: list = []
            amts: list = []
            min_spec = self.spec.minute
            for row, created, remaining, was_in in expired:
                targets = [row, ENTRY_NODE_ROW] if was_in else [row]
                for r in targets:
                    rows.append(r)
                    secs.append(self.spec.second.index_of(created))
                    mins.append(min_spec.index_of(created) if min_spec else 0)
                    amts.append(remaining)
            m = len(rows)
            bm = self._pad(m)
            with self._lock:
                self._state = self._uncount_step()(
                    self._state,
                    jnp.asarray(_pad_to(np.asarray(rows, np.int32), bm,
                                        self.spec.rows, np.int32)),
                    jnp.asarray(_pad_to(np.asarray(secs, np.int32), bm, 0,
                                        np.int32)),
                    jnp.asarray(_pad_to(np.asarray(mins, np.int32), bm, 0,
                                        np.int32)),
                    jnp.asarray(_pad_to(np.asarray(amts, np.int32), bm, 0,
                                        np.int32)))
        for g_idx, grp in grouped(exits, 8):
            at = grp[0][8] if self._seen_idx - g_idx < B else None
            n = len(grp)
            self.exit_batch(
                rows=np.fromiter((x[0] for x in grp), np.int32, n),
                origin_rows=np.fromiter((x[1] for x in grp), np.int32, n),
                chain_rows=np.fromiter((x[2] for x in grp), np.int32, n),
                acquire=np.fromiter((x[3] for x in grp), np.int32, n),
                rt_ms=np.fromiter((x[4] for x in grp), np.int32, n),
                error=np.fromiter((x[5] for x in grp), np.bool_, n),
                is_in=np.fromiter((x[6] for x in grp), np.bool_, n),
                count_thread=np.fromiter((x[7] for x in grp), np.bool_, n),
                at_ms=at)

    def _exit_one(self, e: Entry) -> None:
        if e.row < 0:  # global switch was off at entry
            return
        now = self.clock.now_ms()
        rt = max(0, now - e.create_ms)
        if e.fast is not None:
            # fast-path entries exit through the host buffer (leased ones
            # opted out of the thread gauge on entry — symmetric here)
            self._fast.buffer_exit(
                e.row, e.origin_row, e.chain_row, e.acquire,
                min(rt, self.cfg.statistic_max_rt), e.error is not None,
                e.is_in, e.fast == "free", now)
            if not self.callbacks.empty:
                self.callbacks.fire_exit(e.resource, rt, e.error is not None,
                                         e.acquire)
            if self._fast.due(now):
                self._flush_fast(now)
            return
        pr = pk = None
        gen = -1
        if e.param_pairs is not None:
            pr = e.param_pairs[0][None, :]
            pk = e.param_pairs[1][None, :]
            gen = e.param_pairs[2]
        self.exit_batch(
            rows=np.array([e.row], np.int32),
            origin_rows=np.array([e.origin_row], np.int32),
            chain_rows=np.array([e.chain_row], np.int32),
            acquire=np.array([e.acquire], np.int32),
            rt_ms=np.array([min(rt, self.cfg.statistic_max_rt)], np.int32),
            error=np.array([e.error is not None], np.bool_),
            is_in=np.array([e.is_in], np.bool_),
            param_rules=pr, param_keys=pk, param_gen=gen)
        if not self.callbacks.empty:           # MetricExitCallback analog
            self.callbacks.fire_exit(e.resource, rt, e.error is not None,
                                     e.acquire)

    # ------------------------------------------------------------------
    # Batch API (throughput tier)
    # ------------------------------------------------------------------

    def _pad(self, n: int) -> int:
        return pad_pow2(n)

    def intern_resources(self, resources: Sequence[str]) -> np.ndarray:
        """Pre-stage a batch's resource rows: intern every DISTINCT name
        once and return the int32 row array. Serving loops that dispatch
        the same resource set step after step pass the returned array
        straight to :meth:`entry_batch` / :meth:`entry_batch_nowait` as
        ``resources``, moving the string-encode + intern cost out of the
        per-step path (one FFI call here instead of one per step).

        Duplicates resolve through a host map rather than repeated
        registry allocations — a Zipf batch over a huge keyspace (round
        15's 16M–64M-key workloads) interns its few hundred distinct
        names once instead of pre-building a row per occurrence, so a
        single skewed batch can no longer churn the LRU with cold keys."""
        distinct = dict.fromkeys(resources)
        names = list(distinct)
        batch_intern = getattr(self.resources, "get_or_create_batch", None)
        if batch_intern is not None:
            drows = np.asarray(batch_intern(names), np.int32)
        else:
            drows = np.fromiter(
                (self.resources.get_or_create(r) for r in names),
                np.int32, count=len(names))
        self.tiering.note_interned(names, drows)
        if len(names) == len(resources):
            return drows
        by_name = dict(zip(names, drows))
        return np.fromiter((by_name[r] for r in resources), np.int32,
                           count=len(resources))

    def entry_batch(self, resources: Sequence[str], *,
                    origins: Optional[Sequence[str]] = None,
                    contexts: Optional[Sequence[str]] = None,
                    acquire: Optional[Sequence[int]] = None,
                    entry_types: Optional[Sequence[int]] = None,
                    prioritized: Optional[Sequence[bool]] = None,
                    args_list: Optional[Sequence[Sequence]] = None) -> Verdicts:
        return self.entry_batch_nowait(
            resources, origins=origins, contexts=contexts, acquire=acquire,
            entry_types=entry_types, prioritized=prioritized,
            args_list=args_list).result()

    def entry_batch_nowait(
            self, resources: Sequence[str], *,
            origins: Optional[Sequence[str]] = None,
            contexts: Optional[Sequence[str]] = None,
            acquire: Optional[Sequence[int]] = None,
            entry_types: Optional[Sequence[int]] = None,
            prioritized: Optional[Sequence[bool]] = None,
            args_list: Optional[Sequence[Sequence]] = None,
            trace_id: int = 0
    ) -> "PendingVerdicts":
        """Dispatch-only batch tier: host prep + cluster delegation + the
        jitted decide are all issued, but the verdict readback (the ~RTT
        that dominates a remote-attached device) is deferred to
        ``.result()``. Callers double-buffer — dispatch batch N+1 while N's
        verdicts are in flight — to hide the device→host latency entirely.
        ``.result()`` MUST be called for every handle: it also releases
        blocked events' key pins and writes the block log.

        ``args_list`` may be a 2D numpy integer array (one row per event) —
        the fastest form: single-rule integer-key workloads then resolve
        fully vectorized with one intern per distinct key.

        ``resources`` may be a numpy INTEGER array of pre-interned rows
        (from :meth:`intern_resources`) — serving loops that re-dispatch
        the same resource set every step then skip the per-step string
        intern entirely (the config-4 host-prep hotspot: encoding B
        strings per step dwarfed the device time at large batches).
        Names are recovered lazily (registry reverse lookup) only where a
        denial log or cluster/gate tier actually needs them. Rows evicted
        by registry pressure after interning resolve to row-recycled
        verdicts — same class of skew as any stale name→row cache."""
        n = len(resources)
        # self-telemetry: one flag check when off; when on, the
        # entry→verdict histogram records per batch and a sampled batch
        # (obs.spans stride) carries a trace id through its whole
        # lifecycle — entry prep → host gates → cluster precheck →
        # split decision → compile-cache lookup → device dispatch →
        # settle (docs/OBSERVABILITY.md span schema). A caller-minted
        # trace_id (DispatchPipeline / the serving front end) overrides
        # the stride so the batch stays on its causal chain.
        obs = self.obs
        obs_on = obs.enabled
        tr = (trace_id or obs.spans.maybe_trace()) if obs_on else 0
        t0 = obs.spans.now_ns() if obs_on else 0
        if isinstance(resources, np.ndarray) and resources.dtype.kind in "iu":
            rows = np.ascontiguousarray(resources, np.int32)
            resources = None
        else:
            batch_intern = getattr(self.resources, "get_or_create_batch",
                                   None)
            if batch_intern is not None:  # native table: one FFI call, no GIL
                rows = batch_intern(resources)
            else:
                rows = np.fromiter(
                    (self.resources.get_or_create(r) for r in resources),
                    np.int32, count=n)
            # tiering: classify hot hit / cold miss and queue promotions
            # for any re-interned cold keys (restored in this dispatch's
            # eviction drain, before its decide)
            self.tiering.note_interned(resources, rows)
        if resources is None and (self._host_gates  # graftlint: disable=LOCK002 -- hot-path feature gate: a stale read routes one batch through the exact device path, never unsafely
                                  or self._cluster_rules_by_row
                                  or self._cluster_param_rules_by_row):
            # gates and cluster delegation are name-keyed SPI surfaces;
            # materialize names once for the whole batch (rare combination)
            resources = [self.resources.name_of(int(r)) or "" for r in rows]
        param_rules = param_keys = None
        param_gen = -1
        with self._lock:
            compiled = self._param
            registry = self.param_key_registry
            gen = self._param_gen
        origin_ids = np.zeros(n, np.int32)
        origin_rows = np.full(n, self.spec.alt_rows, np.int32)
        context_ids = np.zeros(n, np.int32)
        chain_rows = np.full(n, self.spec.alt_rows, np.int32)
        if origins is not None:
            for i, o in enumerate(origins):
                if o:
                    oid = self.origins.get_or_create(o)
                    origin_ids[i] = oid
                    origin_rows[i] = self._alt_row(int(rows[i]), 0, oid)
        if contexts is not None:
            for i, c in enumerate(contexts):
                if c and c != "sentinel_default_context":
                    cid = self.contexts.get_or_create(c)
                    context_ids[i] = cid
                    chain_rows[i] = self._alt_row(int(rows[i]), 1, cid)
        acq = np.asarray(acquire, np.int32) if acquire is not None else np.ones(n, np.int32)
        is_in = (np.asarray(entry_types, np.int32) == ENTRY_TYPE_IN) \
            if entry_types is not None else np.ones(n, np.bool_)
        prio = np.asarray(prioritized, np.bool_) if prioritized is not None \
            else np.zeros(n, np.bool_)
        if tr:
            obs.spans.record(tr, "entry.prep", t0, obs.spans.now_ns(), n=n)

        # user host gates veto first (slot-chain SPI tier 1); denials are
        # logged in the gate runner and device-recorded batched below.
        # Gates run BEFORE param-key pinning: a gate that raises must not
        # leak pins (a custom check_batch raising propagates to the caller)
        gate_blocked = gate_reasons = None
        if self._host_gates:  # graftlint: disable=LOCK002 -- hot-path feature gate: a stale read routes one batch through the exact device path, never unsafely
            t_g = obs.spans.now_ns() if tr else 0
            gate_blocked, gate_reasons = self._run_host_gates_batch(
                resources, origins, acq, args_list, is_in, n)
            if tr:
                obs.spans.record(tr, "entry.host_gates", t_g,
                                 obs.spans.now_ns(), n=n)
            if not gate_blocked.any():
                gate_blocked = gate_reasons = None

        pin_arr = None
        if args_list is not None and compiled.num_active:
            param_gen = gen
            param_rules, param_keys = pf_mod.resolve_pairs_many(
                compiled, registry, rows, args_list, self.spec.param_pairs)
            # pin THREAD-grade pairs while in flight (released for blocked
            # events below; allowed events stay pinned until exit_batch);
            # computed once and reused for the blocked-event release
            pin_arr = pf_mod.thread_key_rows(
                compiled, param_rules, param_keys).reshape(
                    param_keys.shape)
            registry.pin_rows(pin_arr)

        # cluster-mode rules: token delegation BEFORE the local decide, ONE
        # batched RPC for the whole batch when the service supports it.
        # Cluster-blocked events are excluded from the local decide and
        # surfaced as FLOW/PARAM_FLOW denials in the returned verdicts.
        cl = None
        if self._cluster_rules_by_row or self._cluster_param_rules_by_row:
            t_c = obs.spans.now_ns() if tr else 0
            cl = self._cluster_precheck_batch(
                resources, origins, rows, origin_rows, chain_rows,
                acq, is_in, prio, args_list, n, skip=gate_blocked)
            if tr:
                obs.spans.record(tr, "entry.cluster_precheck", t_c,
                                 obs.spans.now_ns(), n=n)
        cl_blocked = cl_waits = cl_reasons = None
        cluster_fb_arr = valid_mask = None
        if cl is not None:
            cluster_fb_arr, cl_blocked, cl_waits, cl_reasons, valid_mask = cl
        if gate_blocked is not None:
            # merge gate denials into the pre-blocked set (gates ran first,
            # so they take precedence and never overlap a cluster denial)
            if cl_blocked is None:
                cl_blocked = gate_blocked
                cl_reasons = gate_reasons
                cl_waits = np.zeros(n, np.int32)
                valid_mask = ~gate_blocked
            else:
                cl_blocked = cl_blocked | gate_blocked
                cl_reasons = np.where(gate_blocked, gate_reasons, cl_reasons)
                valid_mask = valid_mask & ~gate_blocked
        if cl_blocked is not None:
            # one batched device record for every pre-blocked event
            if cl_blocked.any():
                idxs = np.nonzero(cl_blocked)[0]
                m = len(idxs)
                bm = self._pad(m)
                times = self._time_scalars(self.clock.now_ms())
                with self._lock:
                    self._state = self._jit_record_blocks(
                        self._state,
                        jnp.asarray(_pad_to(rows[idxs], bm, self.spec.rows,
                                            np.int32)),
                        jnp.asarray(_pad_to(origin_rows[idxs], bm,
                                            self.spec.alt_rows, np.int32)),
                        jnp.asarray(_pad_to(chain_rows[idxs], bm,
                                            self.spec.alt_rows, np.int32)),
                        jnp.asarray(_pad_to(acq[idxs], bm, 0, np.int32)),
                        jnp.asarray(_pad_to(is_in[idxs], bm, False,
                                            np.bool_)),
                        jnp.asarray(_pad_to(np.ones(m, np.bool_), bm, False,
                                            np.bool_)),
                        times)

        pending = self.decide_raw_nowait(
            rows, origin_ids, origin_rows, context_ids, chain_rows, acq,
            is_in, prio, param_rules=param_rules, param_keys=param_keys,
            param_gen=param_gen, cluster_fallback=cluster_fb_arr,
            valid=valid_mask, trace_id=tr)

        def _finalize() -> Verdicts:
            t_s = obs.spans.now_ns() if tr else 0
            verdicts = pending.result()
            if cl_blocked is not None and cl_blocked.any():
                allow = np.array(verdicts.allow, copy=True)
                reason = np.array(verdicts.reason, copy=True)
                allow[cl_blocked] = False
                # per-event reason: param-token denials raise
                # ParamFlowException downstream, flow-token denials
                # FlowException (entry() parity)
                reason[cl_blocked] = cl_reasons[cl_blocked]
                verdicts = Verdicts(allow=allow, reason=reason,
                                    wait_ms=np.maximum(verdicts.wait_ms,
                                                       cl_waits))
            elif cl_waits is not None:
                verdicts = verdicts._replace(
                    wait_ms=np.maximum(verdicts.wait_ms, cl_waits))

            if param_keys is not None:
                # blocked events never exit → release their pins immediately
                blocked = ~np.asarray(verdicts.allow)
                if blocked.any():
                    registry.unpin_rows(pin_arr[blocked])
            # LogSlot parity for the batch tier: blocked events roll into
            # sentinel-block.log (same per-second dedup as the single path,
            # grouped here so a mostly-blocked batch is a handful of log
            # calls); cluster blocks were already logged in the pre-check
            denied = np.nonzero(~np.asarray(verdicts.allow))[0]
            if denied.size:
                reasons = np.asarray(verdicts.reason)
                grouped: dict = {}
                for i in denied.tolist():
                    if cl_blocked is not None and cl_blocked[i]:
                        continue
                    res_i = (resources[i] if resources is not None
                             else self.resources.name_of(int(rows[i])) or "")
                    key = (res_i, int(reasons[i]),
                           (origins[i] if origins is not None
                            and origins[i] else ""))
                    grouped[key] = grouped.get(key, 0) + 1
                for (res, rcode, origin), cnt in grouped.items():
                    self.block_log.log(
                        res, err_mod.exception_name_for(rcode),
                        origin=origin, count=cnt)
                    if obs_on:
                        self._obs_block(res, rcode, origin, cnt)
            if obs_on:
                t_end = obs.spans.now_ns()
                obs.hist_entry.record(t_end - t0)
                if tr:
                    obs.spans.record(tr, "entry.settle", t_s, t_end, n=n)
                    obs.spans.record(tr, "entry.total", t0, t_end, n=n)
            return verdicts

        return self._pending_verdicts(_finalize)

    def _log_cluster_block(self, reason: int, resource: str, origin: str,
                           acquire: int, exc=None,
                           slot_name: Optional[str] = None) -> BlockException:
        """Block log + StatisticSlot callbacks for a denial decided
        off-device (token server or host gate; device record happens
        batched upstream); returns the exception for callers that raise
        it. ``exc`` overrides the constructed exception (a gate raising
        its own BlockException subclass propagates it)."""
        if exc is None:
            if slot_name is None:
                slot_name = (self.slot_name_for_code(reason)
                             if reason >= BlockReason.CUSTOM_BASE else "")
            exc = block_exception_for(reason, resource, origin=origin,
                                      slot_name=slot_name)
        self.block_log.log(resource, type(exc).__name__, origin=origin)
        if self.obs.enabled:
            self._obs_block(resource, reason, origin, 1)
        if not self.callbacks.empty:
            self.callbacks.fire_blocked(resource, origin, acquire, exc)
        return exc

    def _obs_block(self, resource: str, rcode: int, origin: str,
                   count: int, now_ms: Optional[int] = None) -> None:
        """Per-reason denial counter + sampled structured block-event
        record (obs/eventlog.py), keyed by the int8 verdict code —
        custom-slot codes resolve through :meth:`slot_name_for_code`."""
        label = (self.slot_name_for_code(rcode)
                 if rcode >= BlockReason.CUSTOM_BASE
                 else err_mod.exception_name_for(rcode))
        obs = self.obs
        obs.counters.add(obs_keys.BLOCK_PREFIX + label, count)
        ms = self.clock.now_ms() if now_ms is None else now_ms
        obs.block_events.log(
            ms, resource, rcode, reason_name=label, origin=origin,
            count=count)
        # block-reason burst SLO trigger (obs/flight.py): one cheap
        # counter roll per grouped denial record, window math inside
        obs.flight.note_blocks(count, ms)

    def _cluster_precheck_batch(self, resources, origins, rows, origin_rows,
                                chain_rows, acq, is_in, prio, args_list,
                                n: int, skip=None):
        """Cluster token delegation for a whole batch → ``(fallback_bits or
        None, cl_blocked, cl_waits, cl_reasons, valid_mask)``.

        When the installed token service exposes the pipelined batch surface
        (``request_tokens_batch`` — the embedded engine and the socket
        client both do), ALL of the batch's token requests go out as ONE
        call instead of a blocking RPC per event
        (``ClusterFlowChecker.java:55-112`` semantics per request, applied
        in rule order per event; a BLOCKED verdict short-circuits the
        event's remaining results exactly like the exception would have).
        Tokens for an event's later rules may be consumed even when an
        earlier rule blocks — bounded over-consumption of the same class as
        the reference's tolerated check-then-act races. Falls back to the
        per-event blocking path for plain per-call services."""
        svc = self._token_service
        fallback = np.zeros(n, np.int32)      # per-rule slot bitmask
        cl_blocked = np.zeros(n, np.bool_)
        cl_waits = np.zeros(n, np.int32)
        cl_reasons = np.full(n, int(BlockReason.FLOW), np.int32)
        valid_mask = np.ones(n, np.bool_)

        use_batch = svc is not None and hasattr(svc, "request_tokens_batch")
        if not use_batch:
            for i in range(n):
                if skip is not None and skip[i]:
                    continue       # already denied by a host gate
                crules = self._cluster_rules_by_row.get(int(rows[i]))
                cprules = self._cluster_param_rules_by_row.get(int(rows[i]))
                if not crules and not cprules:
                    continue
                org = (origins[i] if origins is not None
                       and origins[i] else "")
                try:
                    if crules:
                        fb, w = self._cluster_check(
                            resources[i], org, int(rows[i]),
                            int(origin_rows[i]), int(chain_rows[i]),
                            int(acq[i]), bool(is_in[i]), bool(prio[i]),
                            crules, sleep=False, record=False)
                        fallback[i] = fb
                        cl_waits[i] = w
                    if (cprules and args_list is not None
                            and args_list[i] is not None
                            and len(args_list[i]) > 0):
                        cl_waits[i] += self._cluster_param_check(
                            resources[i], org, int(rows[i]),
                            int(origin_rows[i]), int(chain_rows[i]),
                            int(acq[i]), bool(is_in[i]), args_list[i],
                            cprules, sleep=False, record=False)
                except BlockException as exc:
                    cl_blocked[i] = True
                    if isinstance(exc, err_mod.ParamFlowException):
                        cl_reasons[i] = int(BlockReason.PARAM_FLOW)
                    valid_mask[i] = False   # out of the local decide
            return ((fallback if fallback.any() else None), cl_blocked,
                    cl_waits, cl_reasons, valid_mask)

        # ---- batched path: collect → one RPC per kind → apply in order ----
        flow_req: list = []    # (event_i, slot_k, rule)
        param_req: list = []   # (event_i, rule, value)
        for i in range(n):
            if skip is not None and skip[i]:
                continue           # already denied by a host gate
            crules = self._cluster_rules_by_row.get(int(rows[i]))
            cprules = self._cluster_param_rules_by_row.get(int(rows[i]))
            if crules:
                for slot_k, r in crules:
                    flow_req.append((i, slot_k, r))
            if (cprules and args_list is not None
                    and args_list[i] is not None
                    and len(args_list[i]) > 0):
                a = args_list[i]
                for r in cprules:
                    idx = (r.param_idx if r.param_idx >= 0
                           else len(a) + r.param_idx)
                    if 0 <= idx < len(a):
                        param_req.append((i, r, a[idx]))
        from sentinel_tpu.core.logs import record_log
        flow_res: list = [None] * len(flow_req)
        param_res: list = [None] * len(param_req)
        try:
            if flow_req:
                flow_res = svc.request_tokens_batch(
                    [(r.cluster_flow_id, int(acq[i]), bool(prio[i]))
                     for i, _k, r in flow_req])
        except Exception as exc:
            record_log().warning("batched cluster token request failed: %r",
                                 exc)
        # the param batch surface is gated on ITS OWN method — a service
        # exposing only the flow batch must not silently fail-open for
        # param rules (per-call requestParamToken is the fallback)
        try:
            if param_req and hasattr(svc, "request_param_tokens_batch"):
                param_res = svc.request_param_tokens_batch(
                    [(r.cluster_flow_id, int(acq[i]), [v])
                     for i, r, v in param_req])
            elif param_req:
                param_res = [svc.request_param_token(
                    r.cluster_flow_id, int(acq[i]), [v])
                    for i, r, v in param_req]
        except Exception as exc:
            record_log().warning("batched cluster param request failed: %r",
                                 exc)
        for (i, slot_k, r), res in zip(flow_req, flow_res):
            if cl_blocked[i]:
                continue        # first BLOCK wins (exception short-circuit)
            status = int(res.status) if res is not None else -1
            if status == 0:
                continue
            if status == 2:
                cl_waits[i] += int(getattr(res, "wait_ms", 0))
                continue
            if status == 1:
                cl_blocked[i] = True
                valid_mask[i] = False
                cl_reasons[i] = int(BlockReason.FLOW)
                self._log_cluster_block(
                    int(BlockReason.FLOW), resources[i],
                    (origins[i] if origins is not None and origins[i]
                     else ""), int(acq[i]))
                continue
            # FAIL / NO_RULE / BAD_REQUEST / TOO_MANY → per-rule fallback
            if r.cluster_fallback_to_local:
                fallback[i] |= 1 << slot_k
        for (i, r, _v), res in zip(param_req, param_res):
            if cl_blocked[i]:
                continue
            status = int(res.status) if res is not None else -1
            if status == 0:
                continue
            if status == 2:
                cl_waits[i] += int(getattr(res, "wait_ms", 0))
                continue
            if status == 1:
                cl_blocked[i] = True
                valid_mask[i] = False
                cl_reasons[i] = int(BlockReason.PARAM_FLOW)
                self._log_cluster_block(
                    int(BlockReason.PARAM_FLOW), resources[i],
                    (origins[i] if origins is not None and origins[i]
                     else ""), int(acq[i]))
            # other statuses: pass through (param fallback is pass-through)
        return ((fallback if fallback.any() else None), cl_blocked,
                cl_waits, cl_reasons, valid_mask)

    def _pad_pairs(self, arr: Optional[np.ndarray], b: int, fill: int):
        """Pad an [n, PV] pair array to [b, PV] (or None passthrough)."""
        if arr is None:
            return None
        out = np.full((b, self.spec.param_pairs), fill, np.int32)
        out[:arr.shape[0]] = arr
        return out

    def decide_raw(self, rows, origin_ids, origin_rows, context_ids, chain_rows,
                   acquire, is_in, prioritized, *, param_rules=None,
                   param_keys=None, param_gen: int = -1,
                   cluster_fallback=None, valid=None,
                   count_thread=None, record_block=None,
                   at_ms: Optional[int] = None) -> Verdicts:
        """Lowest-level host entry point: pre-resolved numpy arrays.
        ``param_gen`` is the generation the pair arrays were resolved against;
        stale pairs (a reload raced the resolve) are dropped, not misapplied."""
        return self.decide_raw_nowait(
            rows, origin_ids, origin_rows, context_ids, chain_rows, acquire,
            is_in, prioritized, param_rules=param_rules,
            param_keys=param_keys, param_gen=param_gen,
            cluster_fallback=cluster_fallback, valid=valid,
            count_thread=count_thread, record_block=record_block,
            at_ms=at_ms).result()

    def _batch_has_no_alt(self, origin_rows, chain_rows) -> bool:
        """True when every origin/chain row is padding (>= alt_rows) — the
        single criterion both the entry and exit paths use to pick the
        *_noalt step variants (the alt-table scatters compile away)."""
        pad_a = self.spec.alt_rows
        return bool(np.min(origin_rows, initial=pad_a) >= pad_a
                    and np.min(chain_rows, initial=pad_a) >= pad_a)

    def _on_leaked_handle(self) -> None:
        if self.obs.enabled:
            self.obs.counters.add(obs_keys.PIPE_LEAKED)
        _log.warning("PendingVerdicts dropped without .result(); "
                     "settled by the GC finalizer")

    def _pending_verdicts(self, fn) -> "PendingVerdicts":
        """Wrap a deferred settle in a leak-guarded handle (every nowait
        path returns through here so no handle can silently drop its
        bookkeeping)."""
        h = PendingVerdicts(fn)
        h.attach_leak_guard(self._on_leaked_handle)
        return h

    def _breaker_snapshot_locked(self):
        """Donation-safe handle on the current breaker-state column for a
        DEFERRED read: with donation on, the state pytree owning this
        leaf is consumed by the next dispatched step, so observers get a
        small async device-side copy instead of the live leaf."""
        col = self._state.breakers.state
        return _jit_copy_column(col) if self._donate else col

    def decide_raw_nowait(self, rows, origin_ids, origin_rows, context_ids,
                          chain_rows, acquire, is_in, prioritized, *,
                          param_rules=None, param_keys=None,
                          param_gen: int = -1, cluster_fallback=None,
                          valid=None, count_thread=None,
                          record_block=None,
                          at_ms: Optional[int] = None,
                          trace_id: int = 0) -> "PendingVerdicts":
        """:meth:`decide_raw` with the verdict readback deferred: the step
        is dispatched (state already advanced in order under the lock) and
        the device→host verdict copy started async; ``.result()``
        materializes. The double-buffering primitive for serving paths.

        Path selection (host-verified; see rules/flow.py for the variants):

        * all events scalar-eligible → scalar admission path (with live
          occupy bookings: the occupy-base scalar variant — bookings are
          read into the QPS base, never written);
        * origin-bearing or PRIORITIZED events present, uniform acquire →
          the fast general path (whole batch; prioritized traffic takes
          the occupy-capable variant), or a PER-EVENT SPLIT when the
          batch mixes kinds — one origin or prioritized event no longer
          demotes the entire batch to the sorted path;
        * otherwise (non-uniform acquire, oversized key) → general path.

        ``trace_id`` threads a sampled batch's span chain through from
        ``entry_batch_nowait``; direct callers get their own sampling
        decision. Every dispatch lands one ``split_route.*`` counter.
        """
        n = rows.shape[0]
        obs = self.obs
        obs_on = obs.enabled
        tr = trace_id if trace_id else (obs.spans.maybe_trace()
                                        if obs_on else 0)
        t_d0 = obs.spans.now_ns() if obs_on else 0
        pad_a = self.spec.alt_rows
        # ---- host-side eligibility (numpy, before any padding) ----
        # Only lanes the caller marked valid count: arbitrary values on
        # invalid lanes are masked device-side and must not disqualify a
        # fast path. A shorter `valid` is legal (pad_to fills False).
        vfull = np.ones(n, np.bool_)
        if valid is not None:
            vsrc = np.asarray(valid, bool)
            m = min(n, vsrc.shape[0])
            vfull[:] = False
            vfull[:m] = vsrc[:m]
        acq_np = np.asarray(acquire)
        oid_np = np.asarray(origin_ids)
        acq_v = acq_np if valid is None else acq_np[vfull]
        acq_uniform = (acq_v.size > 0
                       and int(acq_v.min()) == int(acq_v.max()) >= 1)
        oid_v = oid_np if valid is None else oid_np[vfull]
        no_origin_ids = int(np.max(oid_v, initial=0)) == 0
        no_alt_rows = self._batch_has_no_alt(origin_rows, chain_rows)
        # the fast general path's composite rank key must fit int32
        key_fits = (self._ruleset.flow_table.active.shape[0]  # graftlint: disable=LOCK002 -- single atomic reference read; rule swaps publish a complete RuleSet under the lock
                    * (pad_a + 1)) < 2 ** 31
        # one host copy of the prioritized column, reused by the any-prio
        # check, the split mask, and the occupy-granted counting below
        prio_np = np.asarray(prioritized)
        any_prio = bool(prio_np.any())
        now = self.clock.now_ms() if at_ms is None else at_ms

        # ---- per-event split (occupy state re-verified under the lock
        # by _decide_split_nowait). The dominant pure-scalar batch
        # short-circuits on the aggregate checks above and never
        # materializes the per-event mask (hot dispatch path). Neither
        # prioritized events nor live bookings disable the split any
        # more: prioritized events ride the general side's occupy-capable
        # fast variant, and the scalar side folds live bookings into its
        # admission base (occupy_base) — the pre-r6 whole-batch demotion
        # to the sorted path was a 16x cliff (BASELINE.md).
        pure_scalar = (no_origin_ids and no_alt_rows
                       and cluster_fallback is None)
        if (not pure_scalar or any_prio) and acq_uniform and key_fits:
            # per-event scalar eligibility: no origin id (origin-limited
            # RELATE rules match on the ID, not the row), no real alt
            # rows, no cluster-fallback bits, not prioritized (only the
            # general side may book); invalid lanes scalar-safe
            ev_scalar = ((oid_np == 0)
                         & (np.asarray(origin_rows) >= pad_a)
                         & (np.asarray(chain_rows) >= pad_a)
                         & ~prio_np)
            if cluster_fallback is not None:
                ev_scalar = ev_scalar & (np.asarray(cluster_fallback) == 0)
            ev_scalar = ev_scalar | ~vfull
            n_general_v = int(np.count_nonzero(~ev_scalar & vfull))
            n_scalar_v = int(np.count_nonzero(ev_scalar & vfull))
            if n_general_v > 0 and n_scalar_v >= 4096:
                if obs_on:
                    obs.counters.add(obs_keys.ROUTE_SPLIT)
                    if self.mesh is not None:
                        obs.counters.add(obs_keys.ROUTE_MESHED)
                    if tr:
                        obs.spans.record(
                            tr, "decide.split_decision", t_d0,
                            obs.spans.now_ns(), n=n,
                            note=f"scalar={n_scalar_v} "
                                 f"general={n_general_v}")
                return self._decide_split_nowait(
                    rows, origin_ids, origin_rows, context_ids, chain_rows,
                    acquire, is_in, ev_scalar, vfull,
                    prioritized=prio_np, any_prio=any_prio,
                    param_rules=param_rules, param_keys=param_keys,
                    param_gen=param_gen, cluster_fallback=cluster_fallback,
                    count_thread=count_thread, record_block=record_block,
                    now=now, trace_id=tr)

        staged: list = []
        batch = self._build_entry_batch(
            rows, origin_ids, origin_rows, context_ids, chain_rows,
            acquire, is_in, prioritized, vfull, param_rules, param_keys,
            cluster_fallback, count_thread, record_block, staged=staged)
        # no_alt_rows (computed above) is about ROWS only: batches with no
        # real origin/chain rows take the *_noalt step variants (the
        # alt-table scatters compile away; origin ids without rows are
        # fine for the elision — the fast path matches them by ID)
        times = self._time_scalars(now)
        load1, cpu = self._cpu.sample()
        sys_scalars = jnp.asarray(np.array([load1, cpu], np.float32))
        with self._lock:
            # gen check must happen under the same lock that guards reloads,
            # or a reload racing here could land stale pairs on the new table
            if batch.param_rules is not None and param_gen != self._param_gen:
                batch = batch._replace(param_rules=None, param_keys=None)
            now, times = self._restamp_if_stale_locked(at_ms, now, times)
            self._drain_evictions_locked()
            # hot-set sketch observe (tiering): single-dispatch engines
            # fuse the scatter-max INTO the decide program below (round
            # 16 — the sketch rides as a donated operand); the legacy
            # standalone dispatch stays as the disabled/fallback path.
            # Padding lanes are valid=False no-ops either way.
            sd_sketch = (self.tiering.sketch_for_fuse_locked()
                         if self._single_dispatch else None)
            observed = False
            if sd_sketch is None:
                observed = self.tiering.observe_locked(batch.rows,
                                                       batch.valid)
            self._seen_idx = max(self._seen_idx,
                                 self.spec.second.index_of(now))
            # static occupy variant: the occupy-aware pipeline runs only
            # when this batch is prioritized OR a previous booking can
            # still be live (bookings last ≤ B+1 windows); everything else
            # compiles to a pipeline with zero occupy code
            if any_prio:
                self._occupy_live_until_ms = now + (
                    (self.spec.second.buckets + 1)
                    * self.spec.second.win_ms)
            use_occ = any_prio or now < self._occupy_live_until_ms
            if no_alt_rows:
                decide = (self._jit_decide_prio_noalt if use_occ
                          else self._jit_decide_noalt)
            else:
                decide = (self._jit_decide_prio if use_occ
                          else self._jit_decide)
            flags = {"skip_auth": self._skip_auth,
                     "skip_sys": self._skip_sys,
                     "skip_threads": self._skip_threads}
            if self._sortfree:
                # conditional key presence: with sortfree disabled the
                # flags dict — hence every cached program key — is
                # byte-identical to pre-round-10 builds
                flags["sortfree"] = True
            if (no_alt_rows and no_origin_ids and not any_prio
                    and cluster_fallback is None and acq_uniform):
                # scalar admission path (rules/flow.flow_check_scalar);
                # requires the row-based no_alt (the step variant must be
                # record_alt=False for the scalar assertion). Live occupy
                # bookings are fine: the occupy step variant folds them
                # into the QPS base (occupy_base) — this path never books
                flags["scalar_flow"] = True
                flags["scalar_has_rl"] = self._scalar_has_rl
            elif acq_uniform and key_fits:
                # fast general path: origins/alt rows/fallback bits live,
                # rank closed-form admission (rules/flow.flow_check_fast);
                # with prioritized events or live bookings the occupy-
                # capable variant runs (flow_check_fast_occupy) — no more
                # whole-batch demotion to the sorted path
                flags["fast_flow"] = True
                flags["scalar_has_rl"] = self._scalar_has_rl
            if sd_sketch is not None:
                dec_sd = self._sd_steps_locked()["decide"][
                    (2 if no_alt_rows else 0) + (1 if use_occ else 0)]
                self._warm_sd_first_fetch_locked(
                    dec_sd, batch, sd_sketch, times, sys_scalars, flags,
                    trace_id=tr)
                with obs.annotate("sentinel_tpu.decide"):
                    state, verdicts, new_sketch = dec_sd(
                        self._ruleset, self._state, sd_sketch, batch,
                        times, sys_scalars, **flags)
                self.tiering.set_sketch_locked(new_sketch)
            else:
                self._warm_first_fetch_locked(decide, batch, times,
                                              sys_scalars, flags,
                                              trace_id=tr)
                with obs.annotate("sentinel_tpu.decide"):
                    state, verdicts = decide(
                        self._ruleset, self._state, batch, times,
                        sys_scalars, **flags)
            self._state = state
            # breaker observers: ride the existing readback (seq taken
            # under the dispatch lock so diffs land in dispatch order)
            brk = None
            if self._breaker_observers:
                self._breaker_seq += 1
                brk = (self._breaker_seq, self._deg.rules,
                       self._breaker_snapshot_locked())
        start_host_copy((verdicts.allow, verdicts.reason, verdicts.wait_ms)
                        + ((brk[2],) if brk else ()))
        t_disp = 0
        if obs_on:
            # which path this whole batch took (flags/use_occ were fixed
            # under the dispatch lock)
            if "scalar_flow" in flags:
                route = obs_keys.ROUTE_SCALAR
            elif "fast_flow" in flags:
                route = (obs_keys.ROUTE_FAST_OCCUPY if use_occ
                         else obs_keys.ROUTE_FAST)
            else:
                route = obs_keys.ROUTE_GENERAL
            obs.counters.add(route)
            if "sortfree" in flags:
                obs.counters.add(obs_keys.ROUTE_SORTFREE)
            if self.mesh is not None:
                obs.counters.add(obs_keys.ROUTE_MESHED)
            obs.counters.add(obs_keys.PIPE_DISPATCH,
                             2 if observed else 1)
            if sd_sketch is not None:
                obs.counters.add(obs_keys.ROUTE_SINGLE_DISPATCH)
            t_disp = obs.spans.now_ns()
            if tr:
                obs.spans.record(tr, "decide.dispatch", t_d0, t_disp, n=n,
                                 note=route.split(".", 1)[1])
        prio_np_full = prio_np if any_prio else None

        def _read() -> Verdicts:
            out = Verdicts(allow=np.asarray(verdicts.allow)[:n],
                           reason=np.asarray(verdicts.reason)[:n],
                           wait_ms=np.asarray(verdicts.wait_ms)[:n])
            # verdict materialization proves the device consumed the
            # staged host operands: only now may the slots be reused (a
            # read that raised instead just leaks its slots — safe)
            while staged:
                ring, slot = staged.pop()
                ring.release(slot)
            if obs_on:
                t_end = obs.spans.now_ns()
                obs.hist_dispatch.record(t_end - t_disp)
                if tr:
                    obs.spans.record(tr, "decide.device", t_disp, t_end,
                                     n=n)
                if verdicts.sf_overflow is not None:
                    ovf = int(np.asarray(verdicts.sf_overflow))
                    if ovf:
                        obs.counters.add(obs_keys.SORTFREE_OVERFLOW, ovf)
                if prio_np_full is not None:
                    granted = int(np.count_nonzero(
                        out.allow & (out.wait_ms > 0)
                        & prio_np_full[:n]))
                    if granted:
                        obs.counters.add(obs_keys.OCCUPY_GRANTED, granted)
            if brk is not None:
                self._diff_and_fire_breakers(
                    brk[0], brk[1], np.asarray(brk[2][:-1]).tolist())
            return out

        return self._pending_verdicts(_read)

    def _warm_first_fetch_locked(self, dec, batch, times, sys_scalars,
                                 flags, trace_id: int = 0) -> None:
        """Cap the cold-start tail on remote-attached backends: the FIRST
        dispatch of each (step variant, batch geometry, statics) combo
        pays the program fetch (persistent-cache load + transfer), and
        one measured warm start in three rode a ~50 s transport stall on
        a single load (docs/OPERATIONS.md "Cold start"). Before the real
        dispatch, force the exact same program through an idempotent
        throwaway execution — fresh state (the step donates its state
        argument) and an all-invalid copy of the real batch, so shapes
        and statics match and admission state is untouched — under
        ``core.compile_cache.guarded_first_fetch``'s timeout + bounded
        retry (a warning logs every retry). Disabled on the CPU backend
        by default: program loads there are local file reads. Knobs:
        ``SENTINEL_FIRST_LOAD_TIMEOUT_S`` / ``SENTINEL_FIRST_LOAD_RETRIES``.

        Self-telemetry rides the same membership check on every backend:
        ``compile_cache.hit`` / ``compile_cache.miss`` count first-vs-
        repeat dispatches of each combo, ``compile_cache.
        first_fetch_retry`` each guarded-fetch stall retry, and a traced
        batch records the fetch as a ``decide.first_fetch`` span."""
        from sentinel_tpu.core.compile_cache import program_key
        b = int(batch.rows.shape[0])

        def _attempt():
            throwaway = init_state(self.spec, self.cfg.max_flow_rules,
                                   self.cfg.max_degrade_rules)
            # re-place the all-invalid copy so the warm execution's input
            # shardings (hence its compiled program) match the real one's
            warm = self._place_batch(
                batch._replace(valid=np.zeros(b, np.bool_)))
            if self.mesh is not None:
                throwaway = jax.tree.map(jax.device_put, throwaway,
                                         self._mesh_shardings[0])
            return jax.block_until_ready(
                dec(self._ruleset, throwaway, warm, times, sys_scalars,
                    **flags))

        self._warm_first_fetch_key_locked(
            program_key("decide", id(dec), (b,), flags), _attempt,
            f"decide step (B={b})", trace_id, b)

    def _warm_sd_first_fetch_locked(self, dec_sd, batch, sketch, times,
                                    sys_scalars, flags,
                                    trace_id: int = 0) -> None:
        """:meth:`_warm_first_fetch_locked` for the sketch-fused decide
        step (round 16). Distinct cache kind (``decide_sd``): the fused
        program has an extra donated sketch operand and a third output,
        so it is a different executable from the plain decide step. The
        throwaway execution feeds ``jnp.zeros_like(sketch)`` — the real
        table is live engine state and the step donates its sketch
        argument."""
        from sentinel_tpu.core.compile_cache import program_key
        b = int(batch.rows.shape[0])

        def _attempt():
            throwaway = init_state(self.spec, self.cfg.max_flow_rules,
                                   self.cfg.max_degrade_rules)
            warm = self._place_batch(
                batch._replace(valid=np.zeros(b, np.bool_)))
            warm_sketch = jnp.zeros_like(sketch)
            if self.mesh is not None:
                throwaway = jax.tree.map(jax.device_put, throwaway,
                                         self._mesh_shardings[0])
            return jax.block_until_ready(
                dec_sd(self._ruleset, throwaway, warm_sketch, warm, times,
                       sys_scalars, **flags))

        self._warm_first_fetch_key_locked(
            program_key("decide_sd", id(dec_sd), (b,), flags), _attempt,
            f"sketch-fused decide step (B={b})", trace_id, b)

    def _warm_first_fetch_key_locked(self, key, attempt, what: str,
                                     trace_id: int, n: int) -> None:
        """Shared guard body for :meth:`_warm_first_fetch_locked` and the
        fused decide+exit path: first-dispatch membership + hit/miss
        counters, then ``attempt`` (an IDEMPOTENT throwaway execution of
        the exact program) under the guarded fetch policy."""
        obs = self.obs
        hit = key in self._fetched_programs
        if obs.enabled:
            obs.counters.add(obs_keys.CACHE_HIT if hit
                             else obs_keys.CACHE_MISS)
        if hit:
            return
        from sentinel_tpu.core.compile_cache import (
            first_fetch_policy, guarded_first_fetch)
        timeout_s, retries = first_fetch_policy()
        if timeout_s <= 0:
            # guard off (CPU default): no throwaway execution, but the
            # combo still counts as fetched for hit/miss accounting
            self._fetched_programs.add(key)
            return
        t0 = obs.spans.now_ns() if trace_id else 0
        guarded_first_fetch(
            attempt, what, timeout_s, retries,
            on_retry=((lambda: obs.counters.add(obs_keys.CACHE_RETRY))
                      if obs.enabled else None))
        if trace_id:
            obs.spans.record(trace_id, "decide.first_fetch", t0,
                             obs.spans.now_ns(), n=n)
        self._fetched_programs.add(key)

    # below this padded size, staging buys nothing: the per-call entry
    # tier pads to b=8..256 and its allocation cost is noise, while the
    # ring would become shared mutable state for every concurrent
    # entry() thread
    _STAGING_MIN_B = 512

    def _build_entry_batch(self, rows, origin_ids, origin_rows, context_ids,
                           chain_rows, acquire, is_in, prioritized, vfull,
                           param_rules, param_keys, cluster_fallback,
                           count_thread, record_block,
                           staged=None) -> EntryBatch:
        """Pad raw numpy event arrays into a device EntryBatch (shared by
        the whole-batch, split, and fused dispatch paths).

        Serving-sized batches fill a preallocated staging slot
        (``_StagingRing``) in place of ~9 fresh allocations per step;
        the rare optional columns (param pairs, cluster bits, thread
        counting, block recording) stay freshly allocated. ``staged``
        (a list) is the slot-ownership out-param: a staging slot used
        here is appended as ``(ring, slot)`` and the CALLER must release
        it after its dispatch settles (the deferred-read closures do).
        Callers that pass no list get fresh allocations — a slot nobody
        will release must never be acquired.

        Meshed serving additionally places every column on its batch-axis
        :class:`NamedSharding` (parallel/local_shard.place_batch) so the
        host→device transfer lands partitioned like the step that
        consumes it. Placement BYPASSES the staging ring: ``device_put``
        gives no bound on when it finishes reading the source buffer, so
        a reused slot could be rewritten mid-transfer by a later step in
        the dispatch window — fresh columns make the handoff safe."""
        n = rows.shape[0]
        b = self._pad(n)
        pad_r = self.spec.rows
        pad_a = self.spec.alt_rows
        if (self._staging_on and b >= self._STAGING_MIN_B
                and staged is not None and not self._place_batches):
            ring = self._staging.get(b)
            if ring is None:
                ring = self._staging.setdefault(
                    b, _StagingRing(b, self._staging_depth))
            s = ring.acquire()
            staged.append((ring, s))
            rows_c = _pad_into(s["rows"], rows, pad_r)
            origin_ids_c = _pad_into(s["origin_ids"], origin_ids, 0)
            origin_rows_c = _pad_into(s["origin_rows"], origin_rows, pad_a)
            context_ids_c = _pad_into(s["context_ids"], context_ids, 0)
            chain_rows_c = _pad_into(s["chain_rows"], chain_rows, pad_a)
            acquire_c = _pad_into(s["acquire"], acquire, 0)
            is_in_c = _pad_into(s["is_in"], is_in, False)
            prio_c = _pad_into(s["prioritized"], prioritized, False)
            valid_c = _pad_into(s["valid"], vfull, False)
        else:
            rows_c = _pad_to(rows, b, pad_r, np.int32)
            origin_ids_c = _pad_to(origin_ids, b, 0, np.int32)
            origin_rows_c = _pad_to(origin_rows, b, pad_a, np.int32)
            context_ids_c = _pad_to(context_ids, b, 0, np.int32)
            chain_rows_c = _pad_to(chain_rows, b, pad_a, np.int32)
            acquire_c = _pad_to(acquire, b, 0, np.int32)
            is_in_c = _pad_to(is_in, b, False, np.bool_)
            prio_c = _pad_to(prioritized, b, False, np.bool_)
            valid_c = _pad_to(vfull, b, False, np.bool_)
        batch = EntryBatch(
            rows=rows_c,
            origin_ids=origin_ids_c,
            origin_rows=origin_rows_c,
            context_ids=context_ids_c,
            chain_rows=chain_rows_c,
            acquire=acquire_c,
            is_in=is_in_c,
            prioritized=prio_c,
            valid=valid_c,
            param_rules=self._pad_pairs(param_rules, b,
                                        self.cfg.max_param_rules),
            param_keys=self._pad_pairs(param_keys, b, self.spec.param_keys),
            cluster_fallback=(_pad_to(cluster_fallback, b, 0, np.int32)
                              if cluster_fallback is not None else None),
            count_thread=(_pad_to(count_thread, b, False, np.bool_)
                          if count_thread is not None else None),
            record_block=(_pad_to(record_block, b, False, np.bool_)
                          if record_block is not None else None),
        )
        return self._place_batch(batch)

    def _place_batch(self, batch):
        """Meshed-mode batch-axis placement (no-op otherwise); shared by
        the entry, split, fused, and exit dispatch tiers."""
        if not self._place_batches:
            return batch
        from sentinel_tpu.parallel.local_shard import place_batch
        return place_batch(batch, self.mesh)

    def _decide_split_nowait(self, rows, origin_ids, origin_rows,
                             context_ids, chain_rows, acquire, is_in,
                             ev_scalar, vfull, *, prioritized, any_prio,
                             param_rules, param_keys,
                             param_gen, cluster_fallback, count_thread,
                             record_block, now,
                             trace_id: int = 0) -> "PendingVerdicts":
        """Mixed-batch dispatch: scalar-eligible events take the scalar
        step, origin-bearing AND prioritized ones the fast general step —
        one origin or prioritized event no longer demotes the whole batch
        off the fast paths.

        The two sub-steps run scalar-first under one dispatch-lock hold.
        That is a legitimate serialization of the batch: intra-batch
        ordering is already a batching artifact (the reference's
        concurrent callers race the same way), and each sub-step is
        bit-exact with the general path over its own events
        (tests/test_split_dispatch.py pins split == sequential).
        Prioritized events are routed to the GENERAL side by the caller's
        ``ev_scalar`` mask: only the general sub-step may commit occupy
        bookings (flow_check_fast_occupy); the scalar sub-step runs first
        and — when bookings may be live — folds them into its admission
        base (occupy_base) without ever writing them."""
        n = rows.shape[0]
        obs = self.obs
        obs_on = obs.enabled
        tr = trace_id
        t_d0 = obs.spans.now_ns() if obs_on else 0
        idx_s = np.nonzero(ev_scalar)[0]
        idx_g = np.nonzero(~ev_scalar)[0]

        def take(arr, idx):
            return None if arr is None else np.asarray(arr)[idx]

        zeros_s = np.zeros(idx_s.shape[0], np.bool_)
        zeros_g = np.zeros(idx_g.shape[0], np.bool_)
        staged: list = []
        bs = self._build_entry_batch(
            take(rows, idx_s), take(origin_ids, idx_s),
            take(origin_rows, idx_s), take(context_ids, idx_s),
            take(chain_rows, idx_s), take(acquire, idx_s),
            take(is_in, idx_s), zeros_s, vfull[idx_s],
            take(param_rules, idx_s), take(param_keys, idx_s),
            None, take(count_thread, idx_s), take(record_block, idx_s),
            staged=staged)
        orow_g = take(origin_rows, idx_g)
        crow_g = take(chain_rows, idx_g)
        prio_g = (take(prioritized, idx_g) if any_prio else zeros_g)
        bg = self._build_entry_batch(
            take(rows, idx_g), take(origin_ids, idx_g), orow_g,
            take(context_ids, idx_g), crow_g, take(acquire, idx_g),
            take(is_in, idx_g), prio_g, vfull[idx_g],
            take(param_rules, idx_g), take(param_keys, idx_g),
            take(cluster_fallback, idx_g), take(count_thread, idx_g),
            take(record_block, idx_g), staged=staged)
        no_alt_g = self._batch_has_no_alt(orow_g, crow_g)
        times = self._time_scalars(now)
        load1, cpu = self._cpu.sample()
        sys_scalars = jnp.asarray(np.array([load1, cpu], np.float32))
        with self._lock:
            if bs.param_rules is not None and param_gen != self._param_gen:
                bs = bs._replace(param_rules=None, param_keys=None)
                bg = bg._replace(param_rules=None, param_keys=None)
            self._drain_evictions_locked()
            # hot-set sketch observe (tiering): both split halves carry
            # real traffic rows; padding lanes are valid=False no-ops.
            # Single-dispatch mode (round 16) folds the observe into each
            # sub-step instead — the sketch threads through both halves.
            sd_sketch = (self.tiering.sketch_for_fuse_locked()
                         if self._single_dispatch else None)
            observed = 0
            if sd_sketch is None:
                observed += int(self.tiering.observe_locked(bs.rows,
                                                            bs.valid))
                observed += int(self.tiering.observe_locked(bg.rows,
                                                            bg.valid))
            self._seen_idx = max(self._seen_idx,
                                 self.spec.second.index_of(now))
            flags = {"skip_auth": self._skip_auth,
                     "skip_sys": self._skip_sys,
                     "skip_threads": self._skip_threads}
            if self._sortfree:
                flags["sortfree"] = True   # see decide_raw_nowait
            # occupy re-verify under the lock: this batch's prioritized
            # events, or a concurrent prioritized batch since the
            # optimistic host check, keep occupy live — both sides then
            # take their occupy-AWARE fast variants (scalar reads live
            # bookings via occupy_base, general may book via
            # flow_check_fast_occupy); neither demotes to the sorted path
            if any_prio:
                self._occupy_live_until_ms = now + (
                    (self.spec.second.buckets + 1)
                    * self.spec.second.win_ms)
            use_occ = any_prio or now < self._occupy_live_until_ms
            fl_s = dict(flags, scalar_flow=True,
                        scalar_has_rl=self._scalar_has_rl)
            fl_g = dict(flags, fast_flow=True,
                        scalar_has_rl=self._scalar_has_rl)
            if use_occ:
                dec_s = self._jit_decide_prio_noalt
                dec_g = (self._jit_decide_prio_noalt if no_alt_g
                         else self._jit_decide_prio)
            else:
                dec_s = self._jit_decide_noalt
                dec_g = (self._jit_decide_noalt if no_alt_g
                         else self._jit_decide)
            if sd_sketch is not None:
                # sketch-fused sub-steps: the scalar half is always the
                # noalt variant (origin-free by construction), the
                # general half keys off its own no_alt_g
                sd_steps = self._sd_steps_locked()["decide"]
                dec_s_sd = sd_steps[2 + (1 if use_occ else 0)]
                dec_g_sd = sd_steps[(2 if no_alt_g else 0)
                                    + (1 if use_occ else 0)]
                self._warm_sd_first_fetch_locked(
                    dec_s_sd, bs, sd_sketch, times, sys_scalars, fl_s,
                    trace_id=tr)
                self._warm_sd_first_fetch_locked(
                    dec_g_sd, bg, sd_sketch, times, sys_scalars, fl_g,
                    trace_id=tr)
                with obs.annotate("sentinel_tpu.decide_split"):
                    state, v1, sd_sk1 = dec_s_sd(
                        self._ruleset, self._state, sd_sketch, bs, times,
                        sys_scalars, **fl_s)
                    state, v2, sd_sk2 = dec_g_sd(
                        self._ruleset, state, sd_sk1, bg, times,
                        sys_scalars, **fl_g)
                self.tiering.set_sketch_locked(sd_sk2)
            else:
                self._warm_first_fetch_locked(dec_s, bs, times,
                                              sys_scalars, fl_s,
                                              trace_id=tr)
                self._warm_first_fetch_locked(dec_g, bg, times,
                                              sys_scalars, fl_g,
                                              trace_id=tr)
                with obs.annotate("sentinel_tpu.decide_split"):
                    state, v1 = dec_s(self._ruleset, self._state, bs,
                                      times, sys_scalars, **fl_s)
                    state, v2 = dec_g(self._ruleset, state, bg, times,
                                      sys_scalars, **fl_g)
            self._state = state
            brk = None
            if self._breaker_observers:
                self._breaker_seq += 1
                brk = (self._breaker_seq, self._deg.rules,
                       self._breaker_snapshot_locked())
        start_host_copy((v1.allow, v1.reason, v1.wait_ms,
                         v2.allow, v2.reason, v2.wait_ms)
                        + ((brk[2],) if brk else ()))
        n_s = idx_s.shape[0]
        n_g = idx_g.shape[0]
        t_disp = 0
        if obs_on:
            if "sortfree" in flags:
                obs.counters.add(obs_keys.ROUTE_SORTFREE)
            # two sub-dispatches plus any legacy standalone observes;
            # split never earns split_route.single_dispatch (it is a
            # two-program route by definition)
            obs.counters.add(obs_keys.PIPE_DISPATCH, 2 + observed)
            t_disp = obs.spans.now_ns()
            if tr:
                obs.spans.record(tr, "split.dispatch", t_d0, t_disp, n=n,
                                 note=f"scalar={n_s} general={n_g} "
                                      f"occ={int(use_occ)}")

        def _read() -> Verdicts:
            allow = np.empty(n, np.bool_)
            reason = np.empty(n, np.int8)
            wait = np.empty(n, np.int32)
            allow[idx_s] = np.asarray(v1.allow)[:n_s]
            reason[idx_s] = np.asarray(v1.reason)[:n_s]
            wait[idx_s] = np.asarray(v1.wait_ms)[:n_s]
            allow[idx_g] = np.asarray(v2.allow)[:n_g]
            reason[idx_g] = np.asarray(v2.reason)[:n_g]
            wait[idx_g] = np.asarray(v2.wait_ms)[:n_g]
            # both halves materialized → staged slots consumed; reuse ok
            while staged:
                ring, slot = staged.pop()
                ring.release(slot)
            if obs_on:
                t_end = obs.spans.now_ns()
                obs.hist_dispatch.record(t_end - t_disp)
                if tr:
                    obs.spans.record(tr, "split.device", t_disp, t_end,
                                     n=n)
                if any_prio:
                    granted = int(np.count_nonzero(
                        allow[idx_g] & (wait[idx_g] > 0) & prio_g))
                    if granted:
                        obs.counters.add(obs_keys.OCCUPY_GRANTED, granted)
                ovf = 0
                if v1.sf_overflow is not None:
                    ovf += int(np.asarray(v1.sf_overflow))
                if v2.sf_overflow is not None:
                    ovf += int(np.asarray(v2.sf_overflow))
                if ovf:
                    obs.counters.add(obs_keys.SORTFREE_OVERFLOW, ovf)
            if brk is not None:
                self._diff_and_fire_breakers(
                    brk[0], brk[1], np.asarray(brk[2][:-1]).tolist())
            return Verdicts(allow=allow, reason=reason, wait_ms=wait)

        return self._pending_verdicts(_read)

    def decide_and_exit_raw_nowait(
            self, rows, origin_ids, origin_rows, context_ids, chain_rows,
            acquire, is_in, prioritized, *, exit_rows,
            exit_origin_rows=None, exit_chain_rows=None, exit_acquire=None,
            exit_rt_ms=None, exit_error=None, exit_is_in=None,
            exit_valid=None, valid=None, at_ms: Optional[int] = None,
            trace_id: int = 0) -> "PendingVerdicts":
        """Fused decide+exit dispatch: ONE device program runs this step's
        entry decisions and records the previous step's completions
        (engine/pipeline.py ``decide_and_record_exits`` — exits land
        after decides, bit-identical to the decide-then-exit call pair).
        The allow-then-exit serving loop collapses its two dispatches per
        step into one; at the measured ~2.4 ms per-dispatch floor that is
        the whole point.

        Scope: the fused program covers the raw decide/exit columns only.
        Call sites needing param-flow pairs, cluster token delegation,
        host gates, per-event split routing, or exit-side thread-pair
        accounting keep the two-call form (``entry_batch_nowait`` +
        ``exit_batch``) — those tiers do host work between the halves
        that a single program cannot express. Exit columns default to the
        trivial padding (no origins, acquire=1, rt=0, no errors) so the
        common "report last step's completions" call stays short."""
        n = rows.shape[0]
        n_x = exit_rows.shape[0]
        obs = self.obs
        obs_on = obs.enabled
        tr = trace_id if trace_id else (obs.spans.maybe_trace()
                                        if obs_on else 0)
        t_d0 = obs.spans.now_ns() if obs_on else 0
        pad_a = self.spec.alt_rows
        vfull = np.ones(n, np.bool_)
        if valid is not None:
            vsrc = np.asarray(valid, bool)
            m = min(n, vsrc.shape[0])
            vfull[:] = False
            vfull[:m] = vsrc[:m]
        acq_np = np.asarray(acquire)
        oid_np = np.asarray(origin_ids)
        acq_v = acq_np if valid is None else acq_np[vfull]
        acq_uniform = (acq_v.size > 0
                       and int(acq_v.min()) == int(acq_v.max()) >= 1)
        oid_v = oid_np if valid is None else oid_np[vfull]
        no_origin_ids = int(np.max(oid_v, initial=0)) == 0
        key_fits = (self._ruleset.flow_table.active.shape[0]
                    * (pad_a + 1)) < 2 ** 31
        prio_np = np.asarray(prioritized)
        any_prio = bool(prio_np.any())
        now = self.clock.now_ms() if at_ms is None else at_ms

        # record_alt is shared by both fused halves: the no-alt scatter
        # elision is legal only when NEITHER side carries real alt rows
        # (defaulted exit columns are all padding)
        empty = np.empty(0, np.int32)
        no_alt = (self._batch_has_no_alt(origin_rows, chain_rows)
                  and self._batch_has_no_alt(
                      exit_origin_rows if exit_origin_rows is not None
                      else empty,
                      exit_chain_rows if exit_chain_rows is not None
                      else empty))

        staged: list = []
        batch = self._build_entry_batch(
            rows, origin_ids, origin_rows, context_ids, chain_rows,
            acquire, is_in, prioritized, vfull, None, None, None, None,
            None, staged=staged)
        b_x = self._pad(n_x)
        xbatch = ExitBatch(
            rows=_pad_to(exit_rows, b_x, self.spec.rows, np.int32),
            origin_rows=(_pad_to(exit_origin_rows, b_x, pad_a, np.int32)
                         if exit_origin_rows is not None
                         else np.full(b_x, pad_a, np.int32)),
            chain_rows=(_pad_to(exit_chain_rows, b_x, pad_a, np.int32)
                        if exit_chain_rows is not None
                        else np.full(b_x, pad_a, np.int32)),
            acquire=(_pad_to(exit_acquire, b_x, 0, np.int32)
                     if exit_acquire is not None
                     else _pad_to(np.ones(n_x, np.int32), b_x, 0, np.int32)),
            rt_ms=(_pad_to(exit_rt_ms, b_x, 0, np.int32)
                   if exit_rt_ms is not None else np.zeros(b_x, np.int32)),
            error=(_pad_to(exit_error, b_x, False, np.bool_)
                   if exit_error is not None else np.zeros(b_x, np.bool_)),
            is_in=(_pad_to(exit_is_in, b_x, False, np.bool_)
                   if exit_is_in is not None
                   else _pad_to(np.ones(n_x, np.bool_), b_x, False,
                                np.bool_)),
            valid=(_pad_to(exit_valid, b_x, False, np.bool_)
                   if exit_valid is not None
                   else _pad_to(np.ones(n_x, np.bool_), b_x, False,
                                np.bool_)),
        )
        xbatch = self._place_batch(xbatch)
        times = self._time_scalars(now)
        load1, cpu = self._cpu.sample()
        sys_scalars = jnp.asarray(np.array([load1, cpu], np.float32))
        with self._lock:
            now, times = self._restamp_if_stale_locked(at_ms, now, times)
            self._drain_evictions_locked()
            # hot-set sketch observe (tiering): see decide_raw_nowait.
            # Single-dispatch mode (round 16) folds the observe — and any
            # due telemetry/tiering tick epilogue — into the one fused
            # serving program dispatched below.
            sd_sketch = (self.tiering.sketch_for_fuse_locked()
                         if self._single_dispatch else None)
            observed = False
            if sd_sketch is None:
                observed = self.tiering.observe_locked(batch.rows,
                                                       batch.valid)
            self._seen_idx = max(self._seen_idx,
                                 self.spec.second.index_of(now))
            if any_prio:
                self._occupy_live_until_ms = now + (
                    (self.spec.second.buckets + 1)
                    * self.spec.second.win_ms)
            use_occ = any_prio or now < self._occupy_live_until_ms
            # variant order mirrors the decide set: (occ,alt) =
            # (F,T),(T,T),(F,F),(T,F)
            vidx = (2 if no_alt else 0) + (1 if use_occ else 0)
            flags = {"skip_auth": self._skip_auth,
                     "skip_sys": self._skip_sys,
                     "skip_threads": self._skip_threads}
            if self._sortfree:
                flags["sortfree"] = True   # see decide_raw_nowait
            if no_alt and no_origin_ids and not any_prio and acq_uniform:
                flags["scalar_flow"] = True
                flags["scalar_has_rl"] = self._scalar_has_rl
            elif acq_uniform and key_fits:
                flags["fast_flow"] = True
                flags["scalar_has_rl"] = self._scalar_has_rl
            tel_prep = None
            tier_due = False
            tel_outs = est = None
            if sd_sketch is not None:
                # consult both carry cadences under the SAME lock hold
                # that dispatches — a claim is only made when the
                # epilogue program below will actually run it
                tel_prep = self.telemetry.carry_due_locked(now)
                tier_due = self.tiering.carry_due_locked(now)
                sd = self._sd_steps_locked()
                if tel_prep is not None or tier_due:
                    fused_sd = sd["fused_epi"][vidx]
                    ring = self.telemetry.ring_for_fuse_locked()
                    eflags = ((_EPI_TELEMETRY if tel_prep is not None
                               else 0) | (_EPI_TIER if tier_due else 0))
                    if tel_prep is not None:
                        _, _, append, idx_s, sec_idx_m = tel_prep
                    else:
                        append = idx_s = sec_idx_m = 0
                    epi = jnp.asarray(np.array(
                        [eflags, idx_s, sec_idx_m, append], np.int32))
                    self._warm_fused_sd_first_fetch_locked(
                        fused_sd, batch, xbatch, sd_sketch, times,
                        sys_scalars, flags, epilogue=True, trace_id=tr)
                    with obs.annotate("sentinel_tpu.fused"):
                        (state, verdicts, new_sketch, new_ring, tel_outs,
                         est) = fused_sd(
                            self._ruleset, self._state, sd_sketch, ring,
                            epi, batch, xbatch, times, sys_scalars,
                            **flags)
                    self.tiering.set_sketch_locked(new_sketch)
                    if tel_prep is not None:
                        self.telemetry.queue_carry(tel_prep, tel_outs,
                                                   new_ring)
                    else:
                        self.telemetry.set_ring_locked(new_ring)
                        tel_outs = None
                    if tier_due:
                        self.tiering.queue_estimates(est)
                    else:
                        est = None
                else:
                    fused_sd = sd["fused"][vidx]
                    self._warm_fused_sd_first_fetch_locked(
                        fused_sd, batch, xbatch, sd_sketch, times,
                        sys_scalars, flags, epilogue=False, trace_id=tr)
                    with obs.annotate("sentinel_tpu.fused"):
                        state, verdicts, new_sketch = fused_sd(
                            self._ruleset, self._state, sd_sketch, batch,
                            xbatch, times, sys_scalars, **flags)
                    self.tiering.set_sketch_locked(new_sketch)
            else:
                fused = self._jit_fused_steps[vidx]
                self._warm_fused_first_fetch_locked(fused, batch, xbatch,
                                                    times, sys_scalars,
                                                    flags, trace_id=tr)
                with obs.annotate("sentinel_tpu.fused"):
                    state, verdicts = fused(
                        self._ruleset, self._state, batch, xbatch, times,
                        sys_scalars, **flags)
            self._state = state
            brk = None
            if self._breaker_observers:
                self._breaker_seq += 1
                brk = (self._breaker_seq, self._deg.rules,
                       self._breaker_snapshot_locked())
        start_host_copy((verdicts.allow, verdicts.reason, verdicts.wait_ms)
                        + (tuple(tel_outs) if tel_outs is not None else ())
                        + ((est,) if est is not None else ())
                        + ((brk[2],) if brk else ()))
        t_disp = 0
        if obs_on:
            if "scalar_flow" in flags:
                route = obs_keys.ROUTE_SCALAR
            elif "fast_flow" in flags:
                route = (obs_keys.ROUTE_FAST_OCCUPY if use_occ
                         else obs_keys.ROUTE_FAST)
            else:
                route = obs_keys.ROUTE_GENERAL
            obs.counters.add(obs_keys.ROUTE_FUSED)
            obs.counters.add(obs_keys.PIPE_DISPATCH,
                             2 if observed else 1)
            if sd_sketch is not None:
                obs.counters.add(obs_keys.ROUTE_SINGLE_DISPATCH)
            if "sortfree" in flags:
                obs.counters.add(obs_keys.ROUTE_SORTFREE)
            if self.mesh is not None:
                obs.counters.add(obs_keys.ROUTE_MESHED)
            t_disp = obs.spans.now_ns()
            if tr:
                obs.spans.record(tr, "fused.dispatch", t_d0, t_disp, n=n,
                                 note=f"{route.split('.', 1)[1]} "
                                      f"exits={n_x}")
        prio_np_full = prio_np if any_prio else None

        def _read() -> Verdicts:
            out = Verdicts(allow=np.asarray(verdicts.allow)[:n],
                           reason=np.asarray(verdicts.reason)[:n],
                           wait_ms=np.asarray(verdicts.wait_ms)[:n])
            # settlement proves the staged operands were consumed
            while staged:
                ring, slot = staged.pop()
                ring.release(slot)
            if obs_on:
                t_end = obs.spans.now_ns()
                obs.hist_dispatch.record(t_end - t_disp)
                if tr:
                    obs.spans.record(tr, "fused.device", t_disp, t_end,
                                     n=n)
                if verdicts.sf_overflow is not None:
                    ovf = int(np.asarray(verdicts.sf_overflow))
                    if ovf:
                        obs.counters.add(obs_keys.SORTFREE_OVERFLOW, ovf)
                if prio_np_full is not None:
                    granted = int(np.count_nonzero(
                        out.allow & (out.wait_ms > 0)
                        & prio_np_full[:n]))
                    if granted:
                        obs.counters.add(obs_keys.OCCUPY_GRANTED, granted)
            if brk is not None:
                self._diff_and_fire_breakers(
                    brk[0], brk[1], np.asarray(brk[2][:-1]).tolist())
            return out

        return self._pending_verdicts(_read)

    def _warm_fused_first_fetch_locked(self, fused, batch, xbatch, times,
                                       sys_scalars, flags,
                                       trace_id: int = 0) -> None:
        """First-fetch guard for the fused decide+exit program (same
        policy as :meth:`_warm_first_fetch_locked`; the fused program is
        keyed on BOTH padded geometries)."""
        from sentinel_tpu.core.compile_cache import program_key
        b_e = int(batch.rows.shape[0])
        b_x = int(xbatch.rows.shape[0])

        def _attempt():
            throwaway = init_state(self.spec, self.cfg.max_flow_rules,
                                   self.cfg.max_degrade_rules)
            warm_e = self._place_batch(
                batch._replace(valid=np.zeros(b_e, np.bool_)))
            warm_x = self._place_batch(
                xbatch._replace(valid=np.zeros(b_x, np.bool_)))
            if self.mesh is not None:
                throwaway = jax.tree.map(jax.device_put, throwaway,
                                         self._mesh_shardings[0])
            return jax.block_until_ready(
                fused(self._ruleset, throwaway, warm_e, warm_x, times,
                      sys_scalars, **flags))

        self._warm_first_fetch_key_locked(
            program_key("fused", id(fused), (b_e, b_x), flags), _attempt,
            f"fused decide+exit step (B={b_e}/{b_x})", trace_id, b_e)

    def _warm_fused_sd_first_fetch_locked(self, fused_sd, batch, xbatch,
                                          sketch, times, sys_scalars,
                                          flags, *, epilogue: bool,
                                          trace_id: int = 0) -> None:
        """First-fetch guard for the sketch-fused decide+exit programs
        (round 16). Two cache kinds — ``fused_sd`` and ``fused_sd_epi``
        — since the epilogue variant is a different executable (extra
        ring/epi operands, six outputs). All donated operands are fed
        throwaways: fresh state, a zero sketch, and (epilogue) a fresh
        ring; the zero ``epi`` flags make both cond branches take their
        skip side, so the warm run is a no-op on service state."""
        from sentinel_tpu.core.compile_cache import program_key
        b_e = int(batch.rows.shape[0])
        b_x = int(xbatch.rows.shape[0])

        def _attempt():
            throwaway = init_state(self.spec, self.cfg.max_flow_rules,
                                   self.cfg.max_degrade_rules)
            warm_e = self._place_batch(
                batch._replace(valid=np.zeros(b_e, np.bool_)))
            warm_x = self._place_batch(
                xbatch._replace(valid=np.zeros(b_x, np.bool_)))
            warm_sketch = jnp.zeros_like(sketch)
            if self.mesh is not None:
                throwaway = jax.tree.map(jax.device_put, throwaway,
                                         self._mesh_shardings[0])
            if epilogue:
                from sentinel_tpu.obs.telemetry import init_ring
                warm_ring = init_ring(self.telemetry.ring_slots)
                warm_epi = jnp.zeros((4,), jnp.int32)
                return jax.block_until_ready(
                    fused_sd(self._ruleset, throwaway, warm_sketch,
                             warm_ring, warm_epi, warm_e, warm_x, times,
                             sys_scalars, **flags))
            return jax.block_until_ready(
                fused_sd(self._ruleset, throwaway, warm_sketch, warm_e,
                         warm_x, times, sys_scalars, **flags))

        kind = "fused_sd_epi" if epilogue else "fused_sd"
        self._warm_first_fetch_key_locked(
            program_key(kind, id(fused_sd), (b_e, b_x), flags), _attempt,
            f"sketch-fused decide+exit step (B={b_e}/{b_x})", trace_id,
            b_e)

    def exit_batch(self, *, rows, origin_rows, chain_rows, acquire, rt_ms,
                   error, is_in, param_rules=None, param_keys=None,
                   param_gen: int = -1, count_thread=None,
                   at_ms: Optional[int] = None) -> None:
        n = rows.shape[0]
        obs = self.obs
        tr = obs.spans.maybe_trace() if obs.enabled else 0
        t0 = obs.spans.now_ns() if tr else 0
        b = self._pad(n)
        batch = ExitBatch(
            rows=_pad_to(rows, b, self.spec.rows, np.int32),
            origin_rows=_pad_to(origin_rows, b, self.spec.alt_rows, np.int32),
            chain_rows=_pad_to(chain_rows, b, self.spec.alt_rows, np.int32),
            acquire=_pad_to(acquire, b, 0, np.int32),
            rt_ms=_pad_to(rt_ms, b, 0, np.int32),
            error=_pad_to(error, b, False, np.bool_),
            is_in=_pad_to(is_in, b, False, np.bool_),
            valid=_pad_to(np.ones(n, np.bool_), b, False, np.bool_),
            param_rules=self._pad_pairs(param_rules, b, self.cfg.max_param_rules),
            param_keys=self._pad_pairs(param_keys, b, self.spec.param_keys),
            count_thread=(_pad_to(count_thread, b, False, np.bool_)
                          if count_thread is not None else None),
        )
        batch = self._place_batch(batch)
        now = self.clock.now_ms() if at_ms is None else at_ms
        times = self._time_scalars(now)
        with self._lock:
            now, times = self._restamp_if_stale_locked(at_ms, now, times)
            if self.tiering.enabled:
                # tiering only: a key demoted between entry and exit must
                # promote back before this decrement, or the exit would
                # land on a recycled (or invalidated) row. Tiering-off
                # keeps the historical no-drain exit path.
                self._drain_evictions_locked()
            self._seen_idx = max(self._seen_idx,
                                 self.spec.second.index_of(now))
            unpin = None
            if batch.param_rules is not None:
                if param_gen != self._param_gen:
                    # state was reset by a reload: neither decrement nor unpin
                    # (the pins live on the discarded registry)
                    batch = batch._replace(param_rules=None, param_keys=None)
                else:
                    unpin = (self.param_key_registry,
                             pf_mod.thread_key_rows(self._param, param_rules,
                                                    param_keys))
            exit_step = (self._jit_exit_noalt
                         if self._batch_has_no_alt(origin_rows, chain_rows)
                         else self._jit_exit)
            with self.obs.annotate("sentinel_tpu.exit"):
                self._state = exit_step(self._ruleset, self._state, batch,
                                        times,
                                        skip_threads=self._skip_threads)
            # exit feeds resolve probes / trip breakers: with observers
            # registered, this call pays one small state read so the
            # observer fires within the exit call that caused the arc
            brk = None
            if self._breaker_observers:
                self._breaker_seq += 1
                brk = (self._breaker_seq, self._deg.rules,
                       self._breaker_snapshot_locked())
        # unpin only AFTER the device-side decrement is enqueued (entry-side
        # pin discipline: resolve→pin, decide, exit-decrement→unpin)
        if unpin is not None:
            unpin[0].unpin_rows(unpin[1])
        if obs.enabled:
            obs.counters.add(obs_keys.PIPE_DISPATCH)
        if tr:
            obs.spans.record(tr, "exit.dispatch", t0, obs.spans.now_ns(),
                             n=n)
        if brk is not None:
            self._diff_and_fire_breakers(
                brk[0], brk[1], np.asarray(brk[2][:-1]).tolist())

    def _drain_evictions_locked(self) -> None:
        ev_keys, overrides = self.param_key_registry.drain_updates()
        if ev_keys:
            rows = jnp.asarray(_pad_to(np.asarray(ev_keys, np.int32),
                                       self._pad(len(ev_keys)),
                                       self.spec.param_keys, np.int32))
            self._state = self._state._replace(
                param_dyn=_jit_invalidate_param_keys(
                    self._state.param_dyn, rows))
        if overrides:
            rows = jnp.asarray(_pad_to(
                np.asarray([r for r, _ in overrides], np.int32),
                self._pad(len(overrides)), self.spec.param_keys, np.int32))
            vals = jnp.asarray(_pad_to(
                np.asarray([v for _, v in overrides], np.float32),
                self._pad(len(overrides)), -1.0, np.float32))
            self._state = self._state._replace(
                param_dyn=_jit_apply_overrides(
                    self._state.param_dyn, rows, vals))
        evicted = self.resources.drain_evicted()
        if evicted:
            if self.obs.enabled:
                # rows recycled by registry pressure: their stats AND any
                # live occupy bookings are invalidated below
                self.obs.counters.add(obs_keys.OCCUPY_EVICTED,
                                      len(evicted))
            # tiering demote: snapshot the recycled rows' state into the
            # cold tier BEFORE the invalidate destroys it (dispatch-only;
            # stream order keeps the gather reading pre-invalidate
            # values). Must run before the alt-edge pop below — the
            # snapshot needs the slots' host identities.
            self.tiering.pre_invalidate_locked(evicted, self.clock.now_ms())
            alt: List[int] = []
            for row in evicted:
                alt.extend(self._alt_rows_by_row.pop(row, ()))
            rows_arr = _pad_to(np.asarray(evicted, np.int32),
                               self._pad(len(evicted)), self.spec.rows, np.int32)
            alt_arr = _pad_to(np.asarray(alt, np.int32), self._pad(len(alt)),
                              self.spec.alt_rows, np.int32)
            self._state = self._jit_invalidate(
                self._state, jnp.asarray(rows_arr), jnp.asarray(alt_arr))
        # tiering promote (the documented slow path): restore re-interned
        # cold keys into their freshly allocated rows — after the
        # invalidate, before the decide that triggered the intern, so
        # that decide reads the row exactly as if it had never left.
        # Unconditional: the promoted row may come from the free list
        # with no eviction in this drain.
        self.tiering.post_invalidate_locked(self.clock.now_ms())

    # ------------------------------------------------------------------
    # Introspection (command-surface backing)
    # ------------------------------------------------------------------

    def metrics_snapshot(self, time_ms: int):
        """Per-resource :class:`MetricNode` list for the completed second
        containing ``time_ms`` (the ``MetricTimerListener`` pull: reference
        aggregates every ClusterNode + ENTRY_NODE per whole second —
        ``node/metric/MetricTimerListener.java:34-40``). Requires the minute
        ring (per-second buckets); returns [] when it is disabled."""
        from sentinel_tpu.metrics.node import MetricNode, TOTAL_IN_RESOURCE_NAME

        if self.spec.minute is None:
            return []
        self._flush_fast()      # buffered fast-path stats land first
        idx = jnp.int32(self.spec.minute.index_of(time_ms))
        with self._lock:
            counters, rt = _jit_bucket_snapshot(self.spec.minute)(
                self._state.minute, idx)
            counters = np.asarray(counters)
            rt = np.asarray(rt)
            threads = np.asarray(self._state.threads)
            items = self.resources.items()
            rtypes = dict(self.resource_types)
        sec_ms = (time_ms // 1000) * 1000
        nodes = []
        for name, row in items:
            c = counters[row]
            if not (c[ev.PASS] or c[ev.BLOCK] or c[ev.SUCCESS]
                    or c[ev.EXCEPTION] or c[ev.OCCUPIED_PASS]):
                continue
            succ = int(c[ev.SUCCESS])
            nodes.append(MetricNode(
                timestamp=sec_ms,
                resource=(TOTAL_IN_RESOURCE_NAME if row == ENTRY_NODE_ROW
                          else name),
                pass_qps=int(c[ev.PASS]), block_qps=int(c[ev.BLOCK]),
                success_qps=succ, exception_qps=int(c[ev.EXCEPTION]),
                rt=int(rt[row] / succ) if succ else 0,
                occupied_pass_qps=int(c[ev.OCCUPIED_PASS]),
                concurrency=int(threads[row]),
                classification=rtypes.get(name, 0)))
        nodes.sort(key=lambda n: n.resource)
        return nodes

    def node_totals(self, resource: str) -> dict:
        """Current rolling-second totals for a resource (ClusterNode view)."""
        row = self.resources.lookup(resource)
        if row is None:
            return {}
        t = self.node_totals_by_row(row)
        t.pop("avg_rt", None)
        return t

    def get_flow_rules(self) -> List[flow_mod.FlowRule]:
        return list(self._flow.rules)

    def get_degrade_rules(self) -> List[deg_mod.DegradeRule]:
        return list(self._deg.rules)

    def get_authority_rules(self) -> List[auth_mod.AuthorityRule]:
        return list(self._auth.rules)

    def get_system_rules(self) -> List[sys_mod.SystemRule]:
        return list(self._sys_rules)

    def get_param_flow_rules(self) -> List[pf_mod.ParamFlowRule]:
        return list(self._user_param_rules)

    def system_status(self) -> dict:
        """Live ``systemStatus`` command payload (SystemStatusListener view)."""
        load, cpu = self._cpu.sample()
        entry = self.node_totals_by_row(ENTRY_NODE_ROW)
        return {
            "rqps": entry.get("pass", 0), "qps": entry.get("pass", 0),
            "thread": entry.get("threads", 0), "rt": entry.get("avg_rt", 0),
            "load": load, "cpuUsage": cpu,
        }

    def _totals_snapshot(self):
        """One full-table device read → (counters[R,E], rt[R], threads[R])."""
        self._flush_fast()      # buffered fast-path stats land first
        now = self.clock.now_ms()
        idx_s = jnp.int32(self.spec.second.index_of(now))
        with self._lock:
            tot = np.asarray(rolling_totals(self.spec.second,
                                            self._state.second, idx_s))
            rt = (np.asarray(rt_totals(self.spec.second, self._state.second,
                                       idx_s))
                  if self.spec.second.track_rt
                  else np.zeros(self.spec.rows, np.float32))
            threads = np.asarray(self._state.threads)
        return tot, rt, threads

    @staticmethod
    def _totals_dict(tot_row, rt_row: float, threads_row: int) -> dict:
        succ = int(tot_row[ev.SUCCESS])
        return {
            "pass": int(tot_row[ev.PASS]), "block": int(tot_row[ev.BLOCK]),
            "success": succ, "exception": int(tot_row[ev.EXCEPTION]),
            "threads": int(threads_row),
            "avg_rt": (float(rt_row) / succ) if succ else 0.0,
        }

    def node_totals_by_row(self, row: int) -> dict:
        tot, rt, threads = self._totals_snapshot()
        return self._totals_dict(tot[row], rt[row], threads[row])

    def all_node_totals(self) -> List[Tuple[str, int, dict]]:
        """(name, row, totals) for every registered resource — ONE device
        snapshot regardless of resource count (clusterNode/tree commands)."""
        items = self.resources.items()
        tot, rt, threads = self._totals_snapshot()
        return [(name, row,
                 self._totals_dict(tot[row], rt[row], threads[row]))
                for name, row in items]

    def origin_totals(self, resource: str) -> List[dict]:
        """Per-origin rolling-second stats of one resource (the ``origin``
        command — reference ClusterNode.getOriginCountMap view). Origins are
        hashed rows in the alt table, so attribution is per (resource×origin)
        hash cell; collisions merge rows (bounded inaccuracy by design)."""
        row = self.resources.lookup(resource)
        if row is None:
            return []
        self._flush_fast()      # buffered fast-path stats land first
        now = self.clock.now_ms()
        idx_s = jnp.int32(self.spec.second.index_of(now))
        with self._lock:
            touched = set(self._alt_rows_by_row.get(row, ()))
            origins = self.origins.items()
            tot = np.asarray(rolling_totals(self.spec.second,
                                            self._state.alt_second, idx_s))
            threads = np.asarray(self._state.alt_threads)
        out = []
        for name, oid in origins:
            if not name:
                continue
            r = _alt_hash(row, 0, oid, self.spec.alt_rows)
            if r not in touched:
                continue
            t = tot[r]
            out.append({
                "origin": name, "passQps": int(t[ev.PASS]),
                "blockQps": int(t[ev.BLOCK]),
                "successQps": int(t[ev.SUCCESS]),
                "exceptionQps": int(t[ev.EXCEPTION]),
                "threadNum": int(threads[r]),
            })
        return out

    def breaker_states(self) -> List[int]:
        with self._lock:
            return np.asarray(self._state.breakers.state[:-1]).tolist()

    def add_breaker_observer(self, fn) -> None:
        """Register ``fn(resource, prev_state, new_state)`` for circuit-
        breaker transitions (reference ``EventObserverRegistry``).

        Event-driven: the observer fires on the thread that lands the
        entry/exit batch that caused the arc (the state vector rides the
        batch's existing device→host readback, so registering observers
        adds no extra round-trips to the decide path; exit batches — which
        otherwise need no readback — pay one small read while observers
        are registered). The metric timer's
        :meth:`check_breaker_transitions` poll remains as a fallback for
        verdicts nobody materializes, sharing the same baseline so no
        transition fires twice."""
        with self._lock:
            self._breaker_observers = self._breaker_observers + [fn]

    def _diff_and_fire_breakers(self, seq: int, rules_snap: tuple,
                                states: List[int]) -> int:
        """Diff ``states`` (host ints, rule-slot order) against the shared
        baseline and notify observers → transitions fired. ``seq`` orders
        snapshots (dispatch order under the engine lock): a stale snapshot
        landing after a newer one is skipped — its transitions were already
        visible to the newer diff."""
        observers = self._breaker_observers
        to_fire = []
        with self._breaker_event_lock:
            prev = self._breaker_live
            if prev is not None and seq <= prev[0]:
                return 0
            self._breaker_live = (seq, rules_snap, states)
            # a rules reload re-pairs slots with new rules: new baseline
            if prev is None or prev[1] is not rules_snap:
                return 0
            if observers:
                for j, r in enumerate(rules_snap):
                    if j < len(prev[2]) and j < len(states) \
                            and prev[2][j] != states[j]:
                        to_fire.append((r.resource, prev[2][j], states[j],
                                        observers))
            fired = len(to_fire)
            # enqueue under the event lock: the seq check above admits
            # snapshots in order, so queue order == transition order
            self._breaker_fire_q.extend(to_fire)
        # every enqueuer drains its own items, so the empty case can skip
        # the drain's lock round-trips entirely (hot-path materialization)
        if to_fire:
            self._drain_breaker_fires()
        return fired

    def _drain_breaker_fires(self) -> None:
        """Deliver queued breaker transitions in seq order. Exactly one
        thread drains at a time (the rest — including an observer that
        re-enters the engine and lands new transitions — enqueue and
        return; the active drainer picks their items up). Observers thus
        run with NO engine lock held: re-entry (entry(),
        decide_raw().result(), check_breaker_transitions()) cannot
        self-deadlock, and a slow observer cannot stall concurrent
        verdict materializations — only delay later deliveries, which
        must wait anyway to preserve per-observer ordering."""
        with self._breaker_event_lock:
            if self._breaker_firing:
                return
            self._breaker_firing = True
        try:
            while True:
                with self._breaker_event_lock:
                    if not self._breaker_fire_q:
                        # reset ATOMICALLY with the empty check: a
                        # non-atomic reset would let a concurrent
                        # enqueuer see firing=True after our check and
                        # strand its items until the next transition
                        self._breaker_firing = False
                        return
                    res, old, new, observers = \
                        self._breaker_fire_q.popleft()
                for fn in observers:
                    try:
                        fn(res, old, new)
                    except Exception as exc:
                        from sentinel_tpu.core.logs import record_log
                        record_log().warning(
                            "breaker observer failed: %r", exc)
        except BaseException:
            # Ctrl-C/SystemExit in an observer: a stuck True flag would
            # silently end all future delivery (queued items, if any,
            # deliver on the next transition)
            with self._breaker_event_lock:
                self._breaker_firing = False
            raise

    def check_breaker_transitions(self) -> int:
        """Poll fallback: snapshot current breaker states and run them
        through the shared diff → number of transitions seen. With the
        event path active this only catches arcs whose batch verdicts
        were never materialized; rule reloads reset the baseline."""
        with self._lock:
            observers = self._breaker_observers
            if not observers:
                return 0
            self._breaker_seq += 1
            seq = self._breaker_seq
            rules_snap = self._deg.rules
            # materialize under the lock: with donation on, the state
            # could be consumed by a concurrent dispatch the moment the
            # lock is released
            states = np.asarray(self._state.breakers.state[:-1]).tolist()
        return self._diff_and_fire_breakers(seq, rules_snap, states)

    def breaker_resources(self) -> List[Tuple[str, int]]:
        """(resource, state) per loaded degrade rule, rule-slot order
        (EventObserverRegistry/observability view). States and rules are
        snapshotted under one lock so a concurrent rule reload can't pair
        new rules with another generation's states."""
        with self._lock:
            states = np.asarray(self._state.breakers.state[:-1]).tolist()
            rules = list(self._deg.rules)
        return [(r.resource, states[j]) for j, r in enumerate(rules)
                if j < len(states)]

    def force_breaker(self, resource: str, state: int) -> bool:
        """Force every degrade-rule slot on ``resource`` into ``state``
        (``STATE_CLOSED``/``STATE_OPEN``/``STATE_HALF_OPEN``) — the
        overload controller's Degrade actuator (round 17). The device
        kernels then evolve the slot normally: a forced-OPEN slot
        half-opens after the rule's own ``time_window`` (its
        ``next_retry_ms`` is stamped exactly as a device trip would),
        a forced-CLOSED/HALF_OPEN slot starts a fresh stat window.
        Observers see the arc through the shared transition diff. → True
        when the resource has at least one loaded degrade rule."""
        state = int(state)
        if state not in (deg_mod.STATE_CLOSED, deg_mod.STATE_OPEN,
                         deg_mod.STATE_HALF_OPEN):
            raise ValueError(f"invalid breaker state {state}")
        # buffered fast-path passes were admitted under the old breaker
        # state — land them first (same discipline as a rules reload)
        self._flush_fast()
        never = -(2 ** 30)
        with self._lock:
            slots = [j for j, r in enumerate(self._deg.rules)
                     if r.resource == resource]
            if not slots:
                return False
            idx = jnp.asarray(slots, jnp.int32)
            st = self._state.breakers
            if state == deg_mod.STATE_OPEN:
                now_rel = self._rel_ms(self.clock.now_ms())
                retry = st.next_retry_ms.at[idx].set(
                    (self._deg.table.retry_timeout_ms[idx]
                     + now_rel).astype(jnp.int32))
            else:
                retry = st.next_retry_ms.at[idx].set(never)
            self._state = self._state._replace(breakers=st._replace(
                state=st.state.at[idx].set(state),
                next_retry_ms=retry,
                win_stamp=st.win_stamp.at[idx].set(never),
                bad=st.bad.at[idx].set(0),
                total=st.total.at[idx].set(0)))
            self._pin_state_locked()
        self.check_breaker_transitions()
        return True
