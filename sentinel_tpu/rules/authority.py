"""Origin authority rules (AuthoritySlot).

Reference (``sentinel-core/.../slots/block/authority/AuthorityRuleChecker``):
``limitApp`` is a comma-separated origin list; WHITE passes only origins in
the list, BLACK blocks origins in the list; an empty event origin always
passes. Exact string matching (no prefixes), so origins intern cleanly into
registry ids and membership becomes an integer set probe.

TPU-native shape: per-rule padded id lists ``origin_ids[NA, M]`` (-1 pad);
membership = ``any(origin == ids)`` over the gathered rule rows. One rule per
(resource) is typical; Ka=2 slots supported like the other rule kinds.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

STRATEGY_WHITE = 0
STRATEGY_BLACK = 1

MAX_ORIGINS_PER_RULE = 16


@dataclasses.dataclass
class AuthorityRule:
    resource: str
    limit_app: str               # comma-separated origins
    strategy: int = STRATEGY_WHITE

    def is_valid(self) -> bool:
        return bool(self.resource) and bool(self.limit_app.strip()) and \
            self.strategy in (STRATEGY_WHITE, STRATEGY_BLACK)


class AuthorityRuleTable(NamedTuple):
    active: jnp.ndarray        # bool[NA+1]
    strategy: jnp.ndarray      # int32[NA+1]
    origin_ids: jnp.ndarray    # int32[NA+1, M], -1 padded


class CompiledAuthorityRules(NamedTuple):
    table: AuthorityRuleTable
    rule_idx: jnp.ndarray      # int32[R, Ka]
    rules: Tuple[AuthorityRule, ...]
    num_active: int


def compile_authority_rules(rules: Sequence[AuthorityRule], *, resource_registry,
                            origin_registry, capacity: int, k_per_resource: int,
                            num_rows: int) -> CompiledAuthorityRules:
    valid = [r for r in rules if r.is_valid()]
    if len(valid) > capacity:
        raise ValueError(f"too many authority rules: {len(valid)} > {capacity}")
    na = capacity
    active = np.zeros(na + 1, np.bool_)
    strategy = np.zeros(na + 1, np.int32)
    origin_ids = np.full((na + 1, MAX_ORIGINS_PER_RULE), -1, np.int32)
    rule_idx = np.full((num_rows, k_per_resource), na, np.int32)
    slots_used = {}
    for j, r in enumerate(valid):
        row = resource_registry.pin(r.resource)
        k = slots_used.get(row, 0)
        if k >= k_per_resource:
            raise ValueError(
                f"more than {k_per_resource} authority rules for {r.resource!r}")
        slots_used[row] = k + 1
        rule_idx[row, k] = j
        active[j] = True
        strategy[j] = r.strategy
        origins = [o.strip() for o in r.limit_app.split(",") if o.strip()]
        if len(origins) > MAX_ORIGINS_PER_RULE:
            raise ValueError(
                f"authority rule for {r.resource!r} lists {len(origins)} origins "
                f"(max {MAX_ORIGINS_PER_RULE})")
        for m, o in enumerate(origins):
            origin_ids[j, m] = origin_registry.pin(o)
    table = AuthorityRuleTable(
        active=jnp.asarray(active), strategy=jnp.asarray(strategy),
        origin_ids=jnp.asarray(origin_ids))
    return CompiledAuthorityRules(table=table, rule_idx=jnp.asarray(rule_idx),
                                  rules=tuple(valid), num_active=len(valid))


def authority_check(
    table: AuthorityRuleTable, rule_idx: jnp.ndarray,
    rows: jnp.ndarray, origin_ids: jnp.ndarray, valid: jnp.ndarray,
) -> jnp.ndarray:
    """→ allow bool[B] (False = AuthorityException)."""
    B = rows.shape[0]
    Ka = rule_idx.shape[1]
    NA = table.active.shape[0] - 1
    R = rule_idx.shape[0]

    safe_rows = jnp.minimum(rows, R - 1)
    rules_bk = jnp.where((rows < R)[:, None], rule_idx[safe_rows], NA)  # [B,Ka]
    act = table.active[rules_bk]
    member = jnp.any(
        table.origin_ids[rules_bk] == origin_ids[:, None, None], axis=2)  # [B,Ka]
    white_ok = member
    black_ok = ~member
    rule_ok = jnp.where(table.strategy[rules_bk] == STRATEGY_WHITE,
                        white_ok, black_ok)
    # empty origin (id 0) always passes (AuthorityRuleChecker early return)
    rule_ok = rule_ok | (origin_ids == 0)[:, None] | ~act
    return jnp.all(rule_ok, axis=1) | ~valid
