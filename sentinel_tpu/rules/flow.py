"""Flow rules: vectorized FlowSlot / FlowRuleChecker / traffic-shaping controllers.

Reference semantics being reproduced (all paths under
``sentinel-core/.../slots/block/flow/``):

* ``FlowRuleChecker.checkFlow:44-80`` — every rule configured for the resource
  must pass; rules not applicable to the event's origin pass trivially (null
  node selection).
* ``FlowRuleChecker.selectNodeByRequesterAndStrategy:129-161`` — the *stat row*
  a rule reads is a function of (limitApp, strategy): global resource row,
  per-origin row, related resource's row, or per-context (CHAIN) row.
* ``DefaultController.canPass:50-76`` — reject when
  ``current + prefix + acquire > count`` (QPS grade reads rolling-second pass;
  THREAD grade reads live concurrency).
* ``RateLimiterController:30-90`` — leaky-bucket pacing on a per-rule
  ``latestPassedTime``; wait ≤ maxQueueingTimeMs else block.
* ``WarmUpController:66-190`` — Guava-style token ramp: warningToken /
  maxToken / slope; above the warning line the admitted QPS shrinks to
  ``1/(aboveToken·slope + 1/count)``; tokens refill once per second using the
  previous second's pass count.

TPU-native shape: rules compile (host-side numpy, at rule-load time — the
analog of ``FlowRuleUtil.buildFlowRuleMap``) into a struct-of-arrays
``FlowRuleTable`` plus a per-resource gather table ``rule_idx[R, K]``; the
check is one jitted function over (batch × K) rule applications using the
segment machinery in ``ops/segments.py`` for exact greedy FIFO admission
within the batch. Divergence from the reference is *bounded batching skew*
only, licensed by the reference's own tolerated check-then-act races
(``FlowRuleChecker.java:89``, ``DefaultController.java:87``).

Blocking behaviors return ``wait_ms`` verdicts instead of sleeping the caller
(the reference's cluster protocol already works this way — ``TokenResult
.waitInMs`` — generalized here to local mode; the host SDK sleeps).
"""

from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from sentinel_tpu.ops import segments as seg
from sentinel_tpu.ops import sortfree as sfo
from sentinel_tpu.stats import events as ev
from sentinel_tpu.stats.window import (
    WindowSpec, WindowState, prev_window_sum_rows, window_sum_all,
    window_sum_rows,
)

# Grades (reference RuleConstant.FLOW_GRADE_*)
GRADE_THREAD = 0
GRADE_QPS = 1
# Strategies (RuleConstant.STRATEGY_*)
STRATEGY_DIRECT = 0
STRATEGY_RELATE = 1
STRATEGY_CHAIN = 2
# Control behaviors (RuleConstant.CONTROL_BEHAVIOR_*)
BEHAVIOR_DEFAULT = 0
BEHAVIOR_WARM_UP = 1
BEHAVIOR_RATE_LIMITER = 2
BEHAVIOR_WARM_UP_RATE_LIMITER = 3

# limit_origin sentinel codes (limitApp strings "default"/"other")
LIMIT_DEFAULT = -1
LIMIT_OTHER = -2

# Stat-row selection kinds (compiled from limitApp × strategy)
SEL_MAIN = 0    # resource's global row            (default + DIRECT)
SEL_ORIGIN = 1  # event's per-origin row           (specific origin / other)
SEL_REF = 2     # related resource's global row    (RELATE)
SEL_CHAIN = 3   # event's per-context row          (CHAIN, context == refResource)


@dataclasses.dataclass
class FlowRule:
    """Host-facing rule object (reference ``FlowRule.java`` field parity)."""

    resource: str
    count: float
    grade: int = GRADE_QPS
    limit_app: str = "default"
    strategy: int = STRATEGY_DIRECT
    ref_resource: str = ""
    control_behavior: int = BEHAVIOR_DEFAULT
    warm_up_period_sec: int = 10
    max_queueing_time_ms: int = 500
    cluster_mode: bool = False
    cluster_flow_id: int = 0
    cluster_threshold_type: int = 0      # 0 AVG_LOCAL, 1 GLOBAL
    cluster_fallback_to_local: bool = True

    def is_valid(self) -> bool:
        if not self.resource or self.count < 0:
            return False
        if self.grade not in (GRADE_THREAD, GRADE_QPS):
            return False
        if self.strategy in (STRATEGY_RELATE, STRATEGY_CHAIN) and not self.ref_resource:
            return False
        if self.control_behavior == BEHAVIOR_WARM_UP and self.warm_up_period_sec <= 0:
            return False
        return True


class FlowRuleTable(NamedTuple):
    """Static (per rule-load) device arrays, NF+1 rows; last row = inactive
    sentinel so padded gathers are harmless."""

    active: jnp.ndarray          # bool[NF+1]
    grade: jnp.ndarray           # int32
    count: jnp.ndarray           # float32
    behavior: jnp.ndarray        # int32
    sel_kind: jnp.ndarray        # int32 (SEL_*)
    ref_row: jnp.ndarray         # int32 — main-table row for SEL_REF
    ref_context: jnp.ndarray     # int32 — required context id for SEL_CHAIN
    limit_origin: jnp.ndarray    # int32 — LIMIT_DEFAULT/LIMIT_OTHER/origin id
    max_queue_ms: jnp.ndarray    # int32
    # warm-up precomputed constants (WarmUpController ctor math)
    warning_token: jnp.ndarray   # float32
    max_token: jnp.ndarray       # float32
    slope: jnp.ndarray           # float32
    cold_factor: jnp.ndarray     # float32
    sync_row: jnp.ndarray        # int32 — main-table row used for token sync
    cluster_mode: jnp.ndarray    # bool


class FlowDynState(NamedTuple):
    """Per-rule mutable shaping state (device)."""

    latest_passed_ms: jnp.ndarray   # int32[NF+1] — rel-ms pacing clock
    stored_tokens: jnp.ndarray      # float32[NF+1]
    last_filled_sec: jnp.ndarray    # int32[NF+1] — rel seconds
    # occupy ("borrow-from-future", OccupiableBucketLeapArray rebuilt as
    # virtual bookings keyed by RESOURCE ROW — shared by every rule on the
    # node like the reference's future buckets): slot s holds tokens booked
    # for window occupied_window[r, s]; a booking keeps counting toward the
    # rolling admission sum for B windows after it lands. A booking made at
    # W targets W+1 and stays live through W+B, so B+1 consecutive windows
    # can hold live bookings — the slot ring has B+1 slots (window mod B+1)
    # so a new booking never clobbers a live one.
    occupied_count: jnp.ndarray     # float32[R, B+1]
    occupied_window: jnp.ndarray    # int32[R, B+1]


class CompiledFlowRules(NamedTuple):
    """Host-side compile output."""

    table: FlowRuleTable
    rule_idx: jnp.ndarray           # int32[R, K] → table row, NF = none
    rules: Tuple[FlowRule, ...]     # original objects, index-aligned with table
    num_active: int
    k_used: int = 1                 # max rules on any ONE resource (the
    # rule-gather width the device steps actually need — rule_idx slots
    # are front-packed, so slicing [:, :k_used] loses nothing)
    # numpy original of rule_idx: the runtime's ruleset assembly (slice +
    # joint concat) runs host-side — fewer program loads per process on a
    # tunneled TPU (cold-start story)
    rule_idx_np: Optional[np.ndarray] = None


def init_flow_dyn(nf: int, buckets: int = 2, rows: int = 1) -> FlowDynState:
    return FlowDynState(
        latest_passed_ms=jnp.full((nf + 1,), -(2 ** 30), jnp.int32),
        stored_tokens=jnp.zeros((nf + 1,), jnp.float32),
        last_filled_sec=jnp.full((nf + 1,), -(2 ** 30), jnp.int32),
        occupied_count=jnp.zeros((rows, buckets + 1), jnp.float32),
        occupied_window=jnp.full((rows, buckets + 1), -(2 ** 30),
                                 jnp.int32),
    )


def compile_flow_rules(rules: Sequence[FlowRule], *, resource_registry,
                       context_registry, capacity: int, k_per_resource: int,
                       num_rows: int, cold_factor: float = 3.0,
                       origin_registry=None) -> CompiledFlowRules:
    """Validate + vectorize rules (the ``FlowRuleUtil`` analog).

    Origin-specific ``limit_app`` strings are interned through
    ``origin_registry`` (pinned so ids stay stable while referenced).
    Resources named by rules are pinned in the resource registry.
    Invalid rules are skipped (reference logs and skips); rules beyond
    ``capacity`` or more than ``k_per_resource`` per resource raise — unlike
    the reference's silent 6000-chain cap, overflow here is loud.
    """
    valid = [r for r in rules if r.is_valid()]
    if len(valid) > capacity:
        raise ValueError(f"too many flow rules: {len(valid)} > capacity {capacity}")

    nf = capacity
    active = np.zeros(nf + 1, np.bool_)
    grade = np.zeros(nf + 1, np.int32)
    count = np.zeros(nf + 1, np.float32)
    behavior = np.zeros(nf + 1, np.int32)
    sel_kind = np.zeros(nf + 1, np.int32)
    ref_row = np.zeros(nf + 1, np.int32)
    ref_context = np.full(nf + 1, -1, np.int32)
    limit_origin = np.full(nf + 1, LIMIT_DEFAULT, np.int32)
    max_queue_ms = np.zeros(nf + 1, np.int32)
    warning_token = np.zeros(nf + 1, np.float32)
    max_token = np.zeros(nf + 1, np.float32)
    slope = np.zeros(nf + 1, np.float32)
    cold_f = np.full(nf + 1, cold_factor, np.float32)
    sync_row = np.full(nf + 1, num_rows, np.int32)
    cluster_mode = np.zeros(nf + 1, np.bool_)

    rule_idx = np.full((num_rows, k_per_resource), nf, np.int32)
    slots_used = {}

    for j, r in enumerate(valid):
        row = resource_registry.pin(r.resource)
        k = slots_used.get(row, 0)
        if k >= k_per_resource:
            raise ValueError(
                f"more than {k_per_resource} flow rules for resource {r.resource!r}; "
                f"raise max_rules_per_resource")
        slots_used[row] = k + 1
        rule_idx[row, k] = j

        active[j] = True
        grade[j] = r.grade
        count[j] = r.count
        behavior[j] = r.control_behavior
        max_queue_ms[j] = r.max_queueing_time_ms
        cluster_mode[j] = r.cluster_mode
        sync_row[j] = row

        la = r.limit_app or "default"
        if la == "default":
            limit_origin[j] = LIMIT_DEFAULT
        elif la == "other":
            limit_origin[j] = LIMIT_OTHER
        else:
            if origin_registry is None:
                raise ValueError("origin-specific rule needs an origin registry")
            limit_origin[j] = origin_registry.pin(la)

        if r.strategy == STRATEGY_RELATE:
            sel_kind[j] = SEL_REF
            ref_row[j] = resource_registry.pin(r.ref_resource)
            sync_row[j] = ref_row[j]
        elif r.strategy == STRATEGY_CHAIN:
            sel_kind[j] = SEL_CHAIN
            ref_context[j] = context_registry.pin(r.ref_resource)
        elif la in ("default",):
            sel_kind[j] = SEL_MAIN
        else:
            # specific origin or "other" + DIRECT → the event's origin row
            # (FlowRuleChecker.java:137-141,154-158)
            sel_kind[j] = SEL_ORIGIN

        if r.control_behavior in (BEHAVIOR_WARM_UP, BEHAVIOR_WARM_UP_RATE_LIMITER):
            # WarmUpController.java:66-90 constructor math
            wt = (r.warm_up_period_sec * r.count) / (cold_factor - 1.0)
            mt = wt + 2.0 * r.warm_up_period_sec * r.count / (1.0 + cold_factor)
            warning_token[j] = wt
            max_token[j] = mt
            slope[j] = (cold_factor - 1.0) / r.count / max(mt - wt, 1e-9)

    table = FlowRuleTable(
        active=jnp.asarray(active), grade=jnp.asarray(grade),
        count=jnp.asarray(count), behavior=jnp.asarray(behavior),
        sel_kind=jnp.asarray(sel_kind), ref_row=jnp.asarray(ref_row),
        ref_context=jnp.asarray(ref_context),
        limit_origin=jnp.asarray(limit_origin),
        max_queue_ms=jnp.asarray(max_queue_ms),
        warning_token=jnp.asarray(warning_token),
        max_token=jnp.asarray(max_token), slope=jnp.asarray(slope),
        cold_factor=jnp.asarray(cold_f), sync_row=jnp.asarray(sync_row),
        cluster_mode=jnp.asarray(cluster_mode),
    )
    return CompiledFlowRules(table=table, rule_idx=jnp.asarray(rule_idx),
                             rules=tuple(valid), num_active=len(valid),
                             k_used=max(1, max(slots_used.values(),
                                               default=0)),
                             rule_idx_np=rule_idx)


# ---------------------------------------------------------------------------
# Device-side check
# ---------------------------------------------------------------------------

class FlowBatchView(NamedTuple):
    """Pre-gathered per-event inputs the flow check needs (built by the
    engine so gathers are shared across slots)."""

    rows: jnp.ndarray          # int32[B] main row, >= R padding
    origin_ids: jnp.ndarray    # int32[B]
    origin_rows: jnp.ndarray   # int32[B] alt-table row, >= RA when absent
    context_ids: jnp.ndarray   # int32[B]
    chain_rows: jnp.ndarray    # int32[B] alt-table row, >= RA when absent
    acquire: jnp.ndarray       # int32[B]
    valid: jnp.ndarray         # bool[B]
    prioritized: jnp.ndarray   # bool[B] — entryWithPriority (occupy eligible)
    cluster_fallback: jnp.ndarray  # int32[B] — bit k: check slot-k cluster rule locally


def flow_check(
    table: FlowRuleTable,
    dyn: FlowDynState,
    rule_idx: jnp.ndarray,
    spec: WindowSpec,
    main_second: WindowState,
    alt_second: WindowState,
    main_threads: jnp.ndarray,
    alt_threads: jnp.ndarray,
    batch: FlowBatchView,
    now_idx_s: jnp.ndarray,      # int32 scalar, second-window index
    rel_now_ms: jnp.ndarray,     # int32 scalar, ms since process epoch
    minute_spec: Optional[WindowSpec] = None,
    main_minute: Optional[WindowState] = None,
    now_idx_m: Optional[jnp.ndarray] = None,
    in_win_ms: Optional[jnp.ndarray] = None,   # int32 scalar, now % win_ms
    occupy_timeout_ms: int = 500,
    enable_occupy: bool = True,                # STATIC: trade a second jit
    # variant for zero occupy cost on batches with no prioritized events
    has_thread_rules: bool = True,             # STATIC: False = no loaded
    # rule reads live concurrency → the [BK] thread-gauge gathers compile
    # away (the gauges themselves may be unmaintained then; see
    # pipeline.decide_entries skip_threads)
    sortfree: bool = False,                    # STATIC: group segments via
    # the hash-bucketed claim cascade + counting-sort permutation
    # (ops/sortfree.py) instead of the n·log n composite-key sort; on
    # claim overflow a lax.cond takes the sorted reference branch, so
    # results are bit-identical either way (the runtime's
    # SENTINEL_SORTFREE routing flips this; flow_check_sortfree also
    # surfaces the overflow count)
) -> Tuple[FlowDynState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """→ (dyn', allow bool[B], wait_ms int32[B], occupied bool[B]).

    ``allow[i]`` False means blocked by some flow rule. ``wait_ms`` > 0 with
    ``allow`` True = rate-limiter pass-after-wait (host SDK sleeps).
    ``occupied[i]`` True = prioritized event admitted by borrowing from the
    NEXT window (``tryOccupyNext`` → ``PriorityWaitException``): the caller
    sleeps ``wait_ms`` and the pass is accounted to the future window — the
    recorder must log OCCUPIED_PASS, not PASS, for these events.
    """
    dyn, allow, wait_ms, occupied, _ = _flow_check_impl(
        table, dyn, rule_idx, spec, main_second, alt_second, main_threads,
        alt_threads, batch, now_idx_s, rel_now_ms, minute_spec, main_minute,
        now_idx_m, in_win_ms, occupy_timeout_ms, enable_occupy,
        has_thread_rules, sortfree)
    return dyn, allow, wait_ms, occupied


def flow_check_sortfree(
    table: FlowRuleTable,
    dyn: FlowDynState,
    rule_idx: jnp.ndarray,
    spec: WindowSpec,
    main_second: WindowState,
    alt_second: WindowState,
    main_threads: jnp.ndarray,
    alt_threads: jnp.ndarray,
    batch: FlowBatchView,
    now_idx_s: jnp.ndarray,
    rel_now_ms: jnp.ndarray,
    minute_spec: Optional[WindowSpec] = None,
    main_minute: Optional[WindowState] = None,
    now_idx_m: Optional[jnp.ndarray] = None,
    in_win_ms: Optional[jnp.ndarray] = None,
    occupy_timeout_ms: int = 500,
    enable_occupy: bool = True,
    has_thread_rules: bool = True,
) -> Tuple[FlowDynState, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """:func:`flow_check` with ``sortfree=True``, additionally returning
    the claim-cascade overflow count (int32 scalar — elements that fell
    back to the sorted branch this step; feeds the
    ``sortfree.bucket_overflow`` counter)."""
    return _flow_check_impl(
        table, dyn, rule_idx, spec, main_second, alt_second, main_threads,
        alt_threads, batch, now_idx_s, rel_now_ms, minute_spec, main_minute,
        now_idx_m, in_win_ms, occupy_timeout_ms, enable_occupy,
        has_thread_rules, True)


def _flow_check_impl(
    table, dyn, rule_idx, spec, main_second, alt_second, main_threads,
    alt_threads, batch, now_idx_s, rel_now_ms, minute_spec, main_minute,
    now_idx_m, in_win_ms, occupy_timeout_ms, enable_occupy,
    has_thread_rules, sortfree,
) -> Tuple[FlowDynState, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    B = batch.rows.shape[0]
    K = rule_idx.shape[1]
    NF = table.active.shape[0] - 1
    R = rule_idx.shape[0]
    RA = alt_threads.shape[0]

    safe_rows = jnp.minimum(batch.rows, R - 1)
    rules_bk = jnp.where((batch.rows < R)[:, None], rule_idx[safe_rows], NF)  # [B,K]
    rj = rules_bk.reshape(-1)                                                # [BK]

    # ONE packed [NF+1, 9] gather per index set instead of a 1M-element
    # gather per column — on TPU eight separate gathers cost ~8x one
    # packed gather (BASELINE.md round 3); the stack itself is a trivial
    # [NF, 9] op re-done per step
    pk = jnp.stack([
        table.active.astype(jnp.int32),        # 0
        table.limit_origin,                    # 1
        table.cluster_mode.astype(jnp.int32),  # 2
        table.sel_kind,                        # 3
        table.ref_context,                     # 4
        table.ref_row,                         # 5
        table.behavior,                        # 6
        table.grade,                           # 7
        table.max_queue_ms,                    # 8
    ], axis=1)
    g = pk[rj]                                 # [BK, 9]
    act = g[:, 0] != 0

    # --- applicability: limitApp × origin (FlowRuleChecker.checkFlow null-node) ---
    lim = g[:, 1]
    origin_bk = jnp.repeat(batch.origin_ids, K)
    ctx_bk = jnp.repeat(batch.context_ids, K)
    # "other": origin matches no specific-origin rule of this resource
    specific_hit = jnp.any(
        (lim.reshape(B, K) == batch.origin_ids[:, None])
        & act.reshape(B, K), axis=1)                                         # [B]
    specific_hit_bk = jnp.repeat(specific_hit, K)
    app_default = lim == LIMIT_DEFAULT
    app_specific = lim == origin_bk
    app_other = (lim == LIMIT_OTHER) & (~specific_hit_bk) & (origin_bk != 0)
    applicable = act & (app_default | app_specific | app_other)
    # cluster-mode rules are enforced by the token server, not locally —
    # EXCEPT the specific rules whose token request failed with
    # fallbackToLocal: bit k of the per-event mask re-enables slot k
    # (per-rule FlowRuleChecker.passClusterCheck / fallbackToLocalOrPass)
    slot_bk = jnp.tile(jnp.arange(K, dtype=jnp.int32), B)
    fb_bk = (jnp.repeat(batch.cluster_fallback, K) >> slot_bk) & 1
    applicable = applicable & ((g[:, 2] == 0) | (fb_bk == 1))
    # CHAIN additionally requires the event's context to match refResource
    kind = g[:, 3]
    applicable = applicable & jnp.where(
        kind == SEL_CHAIN, ctx_bk == g[:, 4], True)

    # --- stat-row selection ---
    rows_bk = jnp.repeat(batch.rows, K)
    orow_bk = jnp.repeat(batch.origin_rows, K)
    crow_bk = jnp.repeat(batch.chain_rows, K)
    use_alt = (kind == SEL_ORIGIN) | (kind == SEL_CHAIN)
    sel_main_row = jnp.where(kind == SEL_REF, g[:, 5], rows_bk)
    sel_alt_row = jnp.where(kind == SEL_CHAIN, crow_bk, orow_bk)
    # events whose alt row is absent (no origin / no chain stats): rule passes
    applicable = applicable & jnp.where(use_alt, sel_alt_row < RA, True)

    # --- current counts for the selected rows ---
    main_pass = window_sum_rows(spec, main_second, jnp.minimum(sel_main_row, R - 1),
                                ev.PASS, now_idx_s).astype(jnp.float32)
    alt_pass = window_sum_rows(spec, alt_second, jnp.minimum(sel_alt_row, RA - 1),
                               ev.PASS, now_idx_s).astype(jnp.float32)
    cur_pass = jnp.where(use_alt, alt_pass, main_pass)
    if has_thread_rules:
        main_thr = main_threads[jnp.minimum(sel_main_row, R - 1)].astype(
            jnp.float32)
        alt_thr = alt_threads[jnp.minimum(sel_alt_row, RA - 1)].astype(
            jnp.float32)
        cur_thr = jnp.where(use_alt, alt_thr, main_thr)
    else:
        cur_thr = jnp.zeros_like(cur_pass)   # no THREAD-grade rule reads it

    # --- warm-up token sync (vector over rules, once per step) ---
    dyn, eff_limit_per_rule = _warmup_sync_and_limits(
        table, dyn, spec, main_second, now_idx_s, rel_now_ms,
        minute_spec, main_minute, now_idx_m)
    eff_limit = eff_limit_per_rule[rj]                                       # [BK]

    # --- greedy segment admission ---
    acq_bk = jnp.repeat(batch.acquire, K).astype(jnp.float32)
    valid_bk = jnp.repeat(batch.valid, K) & applicable
    # inapplicable pairs get the sentinel rule NF so they share one segment
    # that never blocks; their acquire contributes nothing.
    rj_seg = jnp.where(valid_bk, rj, NF)
    # Pacing state is PER RULE (one latestPassedTime per RateLimiterController
    # instance), so rate-limiter pairs collapse to one segment per rule; other
    # behaviors segment by (rule, selected stat row). behavior/grade come
    # from the rj packed gather: invalid pairs (rj_seg == NF) may read a
    # real rule's values here, but their row_seg is overridden to 0 below
    # either way, so segmentation is unaffected.
    behavior_bk = g[:, 6]
    is_rl_bk = ((behavior_bk == BEHAVIOR_RATE_LIMITER)
                | (behavior_bk == BEHAVIOR_WARM_UP_RATE_LIMITER)) & (
        g[:, 7] == GRADE_QPS)
    row_seg = jnp.where(use_alt, sel_alt_row + R, sel_main_row)  # disjoint key space
    row_seg = jnp.where(is_rl_bk, 0, row_seg)
    row_seg = jnp.where(valid_bk, row_seg, 0)
    if sortfree:
        # Sort-free grouping: the claim cascade + counting sort yields a
        # STABLE key-grouping permutation; everything downstream (starts,
        # prefix sums, greedy admission, RL fixed point, occupy fold,
        # unsorts) is permutation-invariant across segments and
        # stability-preserving within them, so the admitted bits match
        # the sorted branch exactly (parity argument: ops/sortfree.py).
        # Claim overflow takes the sorted branch via lax.cond — graceful
        # fallback, never a wrong answer.
        plan = sfo.build_pair_plan(rj_seg, row_seg, rj_seg == NF,
                                   sfo.table_bits(B * K))
        order = lax.cond(
            plan.overflow,
            lambda _: seg.sort_by_keys(rj_seg, row_seg),
            lambda _: sfo.counting_order(plan.bucket, plan.num_buckets),
            None)
        sf_overflow = plan.overflow_count
    else:
        order = seg.sort_by_keys(rj_seg, row_seg)
        sf_overflow = jnp.int32(0)
    rj_s = rj_seg[order]
    row_s = row_seg[order]
    acq_s = jnp.where(valid_bk, acq_bk, 0.0)[order]
    starts = seg.segment_starts(rj_s, row_s)
    leader = seg.segment_leader_index(starts)

    # --- occupy bookings (virtual OccupiableBucketLeapArray) ---
    # bookings are keyed by resource ROW (shared by all rules on the node,
    # like the reference's future buckets). Landed bookings (window already
    # reached) count toward the rolling admission sum for B windows,
    # exactly as seeded borrowed PASS would. STATIC skip: the host tracks
    # whether any booking can still be live and compiles this away
    # otherwise (the gathers + extra scatter cost ~40% of the hot step).
    occ_cnt = dyn.occupied_count             # [R, S]
    occ_win = dyn.occupied_window            # [R, S]
    g_s = pk[rj_s]                           # [BK, 9] one sorted-side gather
    grade_s = g_s[:, 7]
    if enable_occupy:
        safe_main_occ = jnp.minimum(sel_main_row, R - 1)
        occ_age_bk = now_idx_s - occ_win[safe_main_occ]      # [BK, S]
        occ_cnt_bk = occ_cnt[safe_main_occ]                  # [BK, S]
        landed_bk = jnp.sum(
            jnp.where((occ_age_bk >= 0) & (occ_age_bk < spec.buckets),
                      occ_cnt_bk, 0.0), axis=1)
        # bookings still live in the NEXT window (pending or recently
        # landed) — budget already spoken for when occupying more
        nextw_bk = jnp.sum(
            jnp.where((occ_age_bk >= -1) & (occ_age_bk < spec.buckets - 1),
                      occ_cnt_bk, 0.0), axis=1)
        # only main-row selections see bookings (occupy is main-row-only)
        no_book = use_alt | (sel_main_row >= R)
        landed_bk = jnp.where(no_book, 0.0, landed_bk)
        nextw_bk = jnp.where(no_book, 0.0, nextw_bk)
        base_s = jnp.where(grade_s == GRADE_QPS,
                           cur_pass[order] + landed_bk[order],
                           cur_thr[order])
    else:
        base_s = jnp.where(grade_s == GRADE_QPS, cur_pass[order],
                           cur_thr[order])
    limit_s = eff_limit[order]
    behavior_s = g_s[:, 6]

    pass_default_s = seg.greedy_admit(base_s, acq_s, limit_s, starts, leader)

    # --- rate limiter (paced queue) ---
    # Shaped behaviors apply only to QPS-grade rules (FlowRuleUtil
    # .generateRater falls back to DefaultController for THREAD grade).
    # cost per element in ms: round(acquire / count * 1000)
    raw_count_s = table.count[rj_s]
    count_s = jnp.maximum(raw_count_s, 1e-9)
    cost_s = jnp.round(acq_s / count_s * 1000.0).astype(jnp.int32)
    c_first = seg.segment_broadcast_first(cost_s, leader)
    L0 = dyn.latest_passed_ms[rj_s]
    due = (L0 + c_first - rel_now_ms) <= 0
    base_time = jnp.where(due, rel_now_ms - c_first, L0)
    is_rl = ((behavior_s == BEHAVIOR_RATE_LIMITER)
             | (behavior_s == BEHAVIOR_WARM_UP_RATE_LIMITER)) & (grade_s == GRADE_QPS)
    # a rejected request never advances the pacing clock (its CAS fails in
    # the reference), so its cost must not delay later in-batch requests:
    # fixed-point — exclusive prefix over admitted costs + own cost always
    pass_rl_s = jnp.ones_like(starts)
    maxq_s = g_s[:, 8]
    for _ in range(3):
        excl_cost, _ = seg.segment_prefix_sum(
            jnp.where(pass_rl_s, cost_s, 0), starts, leader)
        latest_s = base_time + excl_cost + cost_s
        wait_s = jnp.maximum(latest_s - rel_now_ms, 0)
        pass_rl_s = wait_s <= maxq_s
        # zero-count rate limiter blocks everything (count<=0 → block)
        pass_rl_s = pass_rl_s & (raw_count_s > 0)

    # --- occupy attempt (tryOccupyNext, DefaultController prioritized path) ---
    # A denied prioritized request may pre-book the NEXT window when the pass
    # count surviving into it (current bucket + live bookings) leaves room
    # under the threshold, and the wait fits OccupyTimeout (default 500 ms).
    inapplicable_s = rj_s == NF
    if enable_occupy and in_win_ms is not None and occupy_timeout_ms > 0:
        wait_next = (jnp.int32(spec.win_ms) - in_win_ms).astype(jnp.int32)

        def _occupy_attempt(_):
            can_time = wait_next <= occupy_timeout_ms
            # passes that SURVIVE into window now+1: every bucket whose
            # stamp is within the last B-1 windows (0 <= now-stamp <= B-2)
            # — the oldest live bucket expires at the edge, the rest carry
            safe_main = jnp.minimum(sel_main_row, R - 1)
            srow_stamps = main_second.stamps[safe_main]        # [BK, B]
            sdelta = now_idx_s - srow_stamps
            survive_mask = (sdelta >= 0) & (sdelta <= spec.buckets - 2)
            surviving_bk = jnp.sum(
                jnp.where(survive_mask,
                          main_second.counters[safe_main, :, ev.PASS], 0),
                axis=1).astype(jnp.float32)
            prio_s = jnp.repeat(batch.prioritized, K)[order]
            eligible_s = (prio_s & (grade_s == GRADE_QPS)
                          & (behavior_s == BEHAVIOR_DEFAULT)
                          & ~pass_default_s & ~inapplicable_s
                          & ~use_alt[order] & can_time)
            occ_base_s = surviving_bk[order] + nextw_bk[order]
            occ_amt_s = jnp.where(eligible_s, acq_s, 0.0)
            occ_adm = seg.greedy_admit(occ_base_s, occ_amt_s, limit_s,
                                       starts, leader) & eligible_s

            # event-level gate BEFORE committing bookings: a booking is
            # only real if the whole event is admitted by the flow slot —
            # every failing pair of the event must itself be
            # occupy-admitted (PriorityWaitException is the admission)
            pair_ok_tmp = jnp.where(is_rl, pass_rl_s,
                                    pass_default_s | occ_adm) | inapplicable_s
            occ_adm_pairs = seg.unsort(
                order, occ_adm.astype(jnp.int32)).astype(jnp.bool_)
            pair_ok_pairs = seg.unsort(
                order, pair_ok_tmp.astype(jnp.int32)).astype(jnp.bool_)
            event_ok = jnp.all(pair_ok_pairs.reshape(B, K), axis=1)  # [B]
            event_occ = (jnp.any(occ_adm_pairs.reshape(B, K), axis=1)
                         & event_ok & batch.valid)                   # [B]

            # book ONE grant per admitted event on its resource row (the
            # reference's first denying rule throws PriorityWait and books
            # on the node once), slot ring keyed by window now+1
            slots_n = occ_cnt.shape[1]
            slot = (now_idx_s + 1) % slots_n
            grants = jnp.zeros(occ_cnt.shape[0], jnp.float32).at[
                jnp.where(event_occ, batch.rows, occ_cnt.shape[0])].add(
                jnp.where(event_occ, batch.acquire, 0).astype(jnp.float32),
                mode="drop")
            granted_row = grants > 0
            slot_keep = occ_win[:, slot] == now_idx_s + 1
            new_cnt = jnp.where(granted_row,
                                jnp.where(slot_keep, occ_cnt[:, slot], 0.0)
                                + grants,
                                occ_cnt[:, slot])
            new_win = jnp.where(granted_row, now_idx_s + 1,
                                occ_win[:, slot])
            return (occ_cnt.at[:, slot].set(new_cnt),
                    occ_win.at[:, slot].set(new_win),
                    occ_adm & jnp.repeat(event_occ, K)[order])

        def _no_occupy(_):
            return (occ_cnt, occ_win,
                    jnp.zeros_like(pass_default_s).astype(jnp.bool_))

        # real control flow: batches with no prioritized events (the common
        # case, and the whole benchmark) skip the occupy math entirely
        new_occ_cnt, new_occ_win, occ_admit_s = jax.lax.cond(
            jnp.any(batch.prioritized), _occupy_attempt, _no_occupy, None)
        dyn = dyn._replace(occupied_count=new_occ_cnt,
                           occupied_window=new_occ_win)
    else:
        occ_admit_s = jnp.zeros_like(pass_default_s).astype(jnp.bool_)
        wait_next = jnp.int32(0)

    pair_pass_s = jnp.where(is_rl, pass_rl_s, pass_default_s | occ_admit_s)
    pair_pass_s = pair_pass_s | inapplicable_s
    pair_wait_s = jnp.where(is_rl & pair_pass_s & ~inapplicable_s, wait_s, 0)
    pair_wait_s = jnp.maximum(pair_wait_s,
                              jnp.where(occ_admit_s, wait_next, 0))

    # update pacing clocks: last passing element's latest per rule segment
    new_latest = jnp.where(is_rl & pair_pass_s & ~inapplicable_s,
                           latest_s, -(2 ** 30))
    dyn = dyn._replace(latest_passed_ms=dyn.latest_passed_ms.at[
        jnp.where(is_rl & ~inapplicable_s, rj_s, NF)].max(new_latest, mode="drop"))

    # --- combine back to events ---
    pair_pass = seg.unsort(order, pair_pass_s.astype(jnp.int32)).astype(jnp.bool_)
    pair_wait = seg.unsort(order, pair_wait_s.astype(jnp.int32))
    pair_occ = seg.unsort(order, occ_admit_s.astype(jnp.int32)).astype(jnp.bool_)
    allow = jnp.all(pair_pass.reshape(B, K), axis=1)
    wait_ms = jnp.max(pair_wait.reshape(B, K), axis=1)
    occupied = jnp.any(pair_occ.reshape(B, K), axis=1) & allow & batch.valid
    allow = allow | ~batch.valid
    return dyn, allow, wait_ms.astype(jnp.int32), occupied, sf_overflow


def flow_check_scalar(
    table: FlowRuleTable,
    dyn: FlowDynState,
    rule_idx: jnp.ndarray,
    spec: WindowSpec,
    main_second: WindowState,
    main_threads: jnp.ndarray,
    rows: jnp.ndarray,           # int32[B] (>= R padding)
    acquire: jnp.ndarray,        # int32[B] — HOST-VERIFIED uniform (>= 1)
    valid: jnp.ndarray,          # bool[B]
    now_idx_s: jnp.ndarray,
    rel_now_ms: jnp.ndarray,
    minute_spec: Optional[WindowSpec] = None,
    main_minute: Optional[WindowState] = None,
    now_idx_m: Optional[jnp.ndarray] = None,
    has_rate_limiter: bool = True,    # STATIC: ruleset has RL/WU-RL rules
    # — False elides the RL columns, closed forms, and pair math entirely
    # (NOT just the pacing update): only pass False when the loaded
    # ruleset truly has no RL/WU-RL rules, or they admit as DEFAULT.
    # Safe default True matches flow_check_fast: forgetting the flag
    # costs performance, never correctness.
    rules_bk: Optional[jnp.ndarray] = None,   # pre-gathered [B, K] rule
    # ids (the pipeline's joint flow+degrade gather); None = gather here
    occupy_base: bool = False,        # STATIC: live occupy bookings may
    # exist → fold LANDED bookings into the per-rule QPS admission base
    # (one [NF+1, S] gather — negligible). The batch itself must still
    # carry no prioritized events (this path never books); it only has
    # to SEE bookings committed by prioritized traffic dispatched around
    # it (runtime._decide_split_nowait's scalar side).
    sortfree: bool = False,           # STATIC: compute per-slot arrival
    # ranks by identity-bucketed scatter (ops/sortfree.ranks2d_ident —
    # keys are already dense rule ids, so no hashing and no overflow)
    # instead of the batched stable sort; exact, not probabilistic
) -> Tuple[FlowDynState, jnp.ndarray, jnp.ndarray]:
    """Scalar-path flow check → (dyn', allow bool[B], wait_ms int32[B]).

    Bit-exact with :func:`flow_check` under the preconditions the HOST
    must verify before selecting this variant (``runtime.decide_raw``):

    * the batch carries no origin/chain rows and no origins (every
      ``use_alt`` selection in the general path resolves to padding →
      SEL_ORIGIN/SEL_CHAIN rules pass trivially);
    * no prioritized events (live bookings are fine with
      ``occupy_base=True`` — this path reads them, never writes them);
    * no per-event ``cluster_fallback`` bits (cluster rules are simply
      inapplicable locally);
    * ``acquire`` is uniform across valid events with value >= 1.

    Under those conditions every quantity the general path gathers PER
    PAIR — window base, live threads, effective limit, pacing clock, cost,
    behavior, grade — is a function of the RULE alone, so this path
    computes [NF+1]-sized per-rule admission budgets and touches the
    B*K pair axis only for: the rule gather, the arrival-rank computation
    (one stable argsort — :func:`ops.segments.ranks_by_key`), one budget
    gather, and elementwise compares. The general path's greedy fixed
    point collapses to ``rank`` compares (exact for uniform acquire: the
    admitted prefix of a segment is its first ``budget`` elements), and
    the rate limiter collapses to its closed form
    (``latest_k = base_time + k*cost`` is monotone in k, so the passing
    set is a rank prefix — RateLimiterController.java:30-90 semantics).

    Reference parity: DefaultController.canPass:50-76 (QPS + THREAD),
    WarmUpController.java:66-190 (via ``_warmup_sync_and_limits``),
    RateLimiterController.java:30-90, FlowRuleChecker rule-set semantics.
    """
    B = rows.shape[0]
    K = rule_idx.shape[1]
    NF = table.active.shape[0] - 1
    R = rule_idx.shape[0]

    # ---- per-rule admission state ([NF+1]-sized, negligible) ----
    dyn, eff_limit = _warmup_sync_and_limits(
        table, dyn, spec, main_second, now_idx_s, rel_now_ms,
        minute_spec, main_minute, now_idx_m)
    sel_row = jnp.minimum(table.sync_row, R - 1)
    base_pass = window_sum_rows(spec, main_second, sel_row, ev.PASS,
                                now_idx_s).astype(jnp.float32)
    if occupy_base:
        # landed bookings count toward the rolling QPS sum exactly as in
        # flow_check; a valid pair's selected row IS its rule's sync_row,
        # so the per-pair landed sum is a per-rule column here (same
        # float operands + association → bit-exact)
        base_pass = base_pass + _landed_per_rule(
            dyn, sel_row, spec, now_idx_s)
    base_thr = main_threads[sel_row].astype(jnp.float32)
    base = jnp.where(table.grade == GRADE_QPS, base_pass, base_thr)

    # rules that can apply to an origin-less, fallback-free batch:
    # default-limitApp, local-mode, MAIN/REF row selection
    applies = (table.active
               & (table.limit_origin == LIMIT_DEFAULT)
               & (~table.cluster_mode)
               & ((table.sel_kind == SEL_MAIN)
                  | (table.sel_kind == SEL_REF)))
    # DEFAULT/WARM_UP: pair with rank r passes iff
    #   (base + r*a) + a <= eff_limit   — same operand association as the
    # general path's `base + excl + amounts <= limit` so the float32
    # rounding is identical (bit-exact while r*a < 2^24, where the general
    # path's cumsum is itself exact)
    acq_of_rule = jnp.float32(0) + jnp.max(
        jnp.where(valid, acquire, 0)).astype(jnp.float32)    # the uniform a
    if has_rate_limiter:
        is_rl = (((table.behavior == BEHAVIOR_RATE_LIMITER)
                  | (table.behavior == BEHAVIOR_WARM_UP_RATE_LIMITER))
                 & (table.grade == GRADE_QPS))
        base_time, cost, max_k = _rl_closed_form(
            table, dyn, acq_of_rule, rel_now_ms)

    # ---- per-pair work ----
    if rules_bk is None:
        rules_bk = seg.padded_table_gather(rule_idx, rows, NF)
    rj = rules_bk.reshape(-1)                                # [BK]
    valid_bk = jnp.repeat(valid, K)
    # INVALID pairs share the sentinel segment (they must not consume
    # ranks in real groups). INAPPLICABLE RULES need no key remap at all:
    # applicability is per-rule in this path, so an inapplicable rule's
    # group holds only inapplicable pairs — encoding "always passes" in
    # its table row (limit=+inf, is_rl off) is equivalent and saves the
    # applies[rj] gather.
    key = jnp.where(valid_bk, rj, NF)
    # per-slot ranks: slot columns carry disjoint rule sets (see
    # seg.ranks_per_slot; the NF sentinel group's per-slot ranks only
    # feed the npairs lane of the inactive rule)
    if sortfree:
        rank = sfo.ranks2d_ident(key.reshape(B, K), NF + 2).reshape(-1)
    else:
        rank = seg.ranks_per_slot(key.reshape(B, K)).reshape(-1)  # int32[BK]

    a_bk = jnp.repeat(acquire, K).astype(jnp.float32)
    limit_eff = jnp.where(applies, eff_limit, jnp.float32(3e38))
    # ONE packed per-rule verdict gather: int columns plus the float
    # columns bitcast to int32 (exact round-trip). RL math stays int32 —
    # float32 ms arithmetic drifts after ~4.6 h of uptime. The 4 RL
    # columns + their pair math only exist when a rate-limiter rule is
    # loaded (static elision, mirrors flow_check_fast).
    cols = [
        lax.bitcast_convert_type(base, jnp.int32),           # 0
        lax.bitcast_convert_type(limit_eff, jnp.int32),      # 1
    ]
    if has_rate_limiter:
        cols += [(is_rl & applies).astype(jnp.int32),        # 2
                 base_time, cost, max_k]                     # 3, 4, 5
    vt = jnp.stack(cols, axis=1)
    g = vt[key]                                              # [BK, C]
    base_pair = lax.bitcast_convert_type(g[:, 0], jnp.float32)
    limit_pair = lax.bitcast_convert_type(g[:, 1], jnp.float32)
    rankf = rank.astype(jnp.float32)

    pass_default = (base_pair + rankf * a_bk) + a_bk <= limit_pair
    if has_rate_limiter:
        # RL: pass iff rank < max_k (the rank-prefix form of
        # `base_time + (rank+1)*cost - now <= maxQueueing`, exactly the
        # general path's fixed point for uniform cost — overflow-free).
        # wait for PASSING pairs only: (rank+1)*cost is bounded there.
        pass_rl = rank < g[:, 5]
        safe_rank = jnp.minimum(rank, g[:, 5])   # blocked lanes: clamp
        # the product so dead-lane arithmetic can't overflow int32
        wait_pair = jnp.maximum(
            g[:, 3] + (safe_rank + 1) * g[:, 4] - rel_now_ms, 0)
        pair_is_rl = g[:, 2] != 0
        pair_pass = jnp.where(pair_is_rl, pass_rl, pass_default)
        pair_pass = pair_pass | (key == NF)
        pair_wait = jnp.where(pair_is_rl & pair_pass & (key != NF),
                              wait_pair, 0)
        wait_ms = jnp.max(pair_wait.reshape(B, K), axis=1)
    else:
        pair_pass = pass_default | (key == NF)
        wait_ms = jnp.zeros((B,), jnp.int32)

    allow = jnp.all(pair_pass.reshape(B, K), axis=1)

    # ---- pacing-clock update (only when the ruleset has RL rules) ----
    if has_rate_limiter:
        # per-rule pass count = min(#valid pairs, rank budget); the rank
        # array already encodes group sizes (max rank + 1)
        npairs = jnp.zeros((NF + 2,), jnp.int32).at[key].max(
            rank + 1, mode="drop")[:NF + 1]
        passed = jnp.minimum(npairs, max_k)
        passed = jnp.where(is_rl & applies & (table.count > 0), passed, 0)
        new_latest = jnp.where(
            passed > 0,
            (base_time + passed * cost).astype(jnp.int32),
            dyn.latest_passed_ms)
        dyn = dyn._replace(
            latest_passed_ms=jnp.maximum(dyn.latest_passed_ms, new_latest))

    allow = allow | ~valid
    return dyn, allow, wait_ms


def _landed_per_rule(dyn: FlowDynState, sel_row: jnp.ndarray,
                     spec: WindowSpec, now_idx_s: jnp.ndarray) -> jnp.ndarray:
    """LANDED occupy bookings per rule → float32[NF+1]: sum of bookings on
    the rule's selected main row whose target window has been reached and
    is still inside the rolling interval (age in [0, B)). The per-rule
    form of ``flow_check``'s ``landed_bk`` — identical numeric values for
    every valid main-row pair, since such a pair's ``sel_main_row`` equals
    its rule's ``sync_row``."""
    occ_age = now_idx_s - dyn.occupied_window[sel_row]      # [NF+1, S]
    return jnp.sum(
        jnp.where((occ_age >= 0) & (occ_age < spec.buckets),
                  dyn.occupied_count[sel_row], 0.0), axis=1)


def flow_check_fast(
    table: FlowRuleTable,
    dyn: FlowDynState,
    rule_idx: jnp.ndarray,
    spec: WindowSpec,
    main_second: WindowState,
    alt_second: WindowState,
    main_threads: jnp.ndarray,
    alt_threads: jnp.ndarray,
    batch: FlowBatchView,
    now_idx_s: jnp.ndarray,
    rel_now_ms: jnp.ndarray,
    minute_spec: Optional[WindowSpec] = None,
    main_minute: Optional[WindowState] = None,
    now_idx_m: Optional[jnp.ndarray] = None,
    has_rate_limiter: bool = True,    # STATIC: ruleset has RL/WU-RL rules
    has_thread_rules: bool = True,    # STATIC: see flow_check
    rules_bk: Optional[jnp.ndarray] = None,   # [B, K] pre-gathered rule ids
    sortfree: bool = False,           # STATIC: per-slot ranks via the
    # hashed claim cascade (ops/sortfree.ranks2d_hashed) with a lax.cond
    # sorted fallback on claim overflow — bit-exact either way
) -> Tuple[FlowDynState, jnp.ndarray, jnp.ndarray]:
    """Fast GENERAL-path flow check → (dyn', allow bool[B], wait_ms int32[B]).

    The scalar path's rank-prefix admission (:func:`flow_check_scalar`)
    generalized to origin-bearing traffic: per-pair applicability and
    stat-row selection (``FlowRuleChecker.selectNodeByRequesterAndStrategy``,
    FlowRuleChecker.java:129-161) stay fully live, but the sorted
    greedy/fixed-point machinery of :func:`flow_check` collapses to ONE
    composite-key rank sort plus closed forms. Host-verified preconditions
    (``runtime.decide_raw``):

    * ``acquire`` uniform across valid events, value >= 1;
    * no prioritized events and no live occupy bookings (occupy off).

    Origins, alt rows, CHAIN contexts, and per-event cluster-fallback bits
    are all allowed — that is the point.

    Why it is bit-exact with :func:`flow_check` under those preconditions:

    * every admission segment of the general path is keyed by
      (rule, selected stat row); a rule's selected MAIN/REF row is a
      function of the rule alone (a flow rule names one resource), so the
      row sub-key matters only for SEL_ORIGIN/SEL_CHAIN pairs, whose alt
      row is < RA — the composite int32 key
      ``rule * (RA + 1) + (use_alt ? alt_row + 1 : 0)`` reproduces the
      exact segmentation (RL pairs pace per RULE — sub-key 0 — matching
      the general path's ``row_seg = 0`` for rate limiters);
    * within a segment, base and limit are constant and amounts are the
      uniform ``a``, so the greedy fixed point's admitted set is the rank
      prefix ``base + rank*a + a <= limit`` (same operand association as
      the general path's cumsum form — bit-identical while counts stay
      under 2^24, where the cumsum itself is exact);
    * the rate limiter collapses to the same bounded per-rule rank budget
      ``max_k`` as the scalar path (RateLimiterController.java:30-90).
    """
    dyn, allow, wait_ms, _, _ = _flow_check_fast_impl(
        table, dyn, rule_idx, spec, main_second, alt_second, main_threads,
        alt_threads, batch, now_idx_s, rel_now_ms, minute_spec, main_minute,
        now_idx_m, has_rate_limiter, has_thread_rules, rules_bk,
        enable_occupy=False, in_win_ms=None, occupy_timeout_ms=0,
        sortfree=sortfree)
    return dyn, allow, wait_ms


def flow_check_fast_sortfree(
    table, dyn, rule_idx, spec, main_second, alt_second, main_threads,
    alt_threads, batch, now_idx_s, rel_now_ms, minute_spec=None,
    main_minute=None, now_idx_m=None, has_rate_limiter=True,
    has_thread_rules=True, rules_bk=None,
) -> Tuple[FlowDynState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """:func:`flow_check_fast` with ``sortfree=True``, additionally
    returning the claim-cascade overflow count (int32 scalar) →
    (dyn', allow, wait_ms, sf_overflow)."""
    dyn, allow, wait_ms, _, sf_overflow = _flow_check_fast_impl(
        table, dyn, rule_idx, spec, main_second, alt_second, main_threads,
        alt_threads, batch, now_idx_s, rel_now_ms, minute_spec, main_minute,
        now_idx_m, has_rate_limiter, has_thread_rules, rules_bk,
        enable_occupy=False, in_win_ms=None, occupy_timeout_ms=0,
        sortfree=True)
    return dyn, allow, wait_ms, sf_overflow


def flow_check_fast_occupy(
    table: FlowRuleTable,
    dyn: FlowDynState,
    rule_idx: jnp.ndarray,
    spec: WindowSpec,
    main_second: WindowState,
    alt_second: WindowState,
    main_threads: jnp.ndarray,
    alt_threads: jnp.ndarray,
    batch: FlowBatchView,
    now_idx_s: jnp.ndarray,
    rel_now_ms: jnp.ndarray,
    minute_spec: Optional[WindowSpec] = None,
    main_minute: Optional[WindowState] = None,
    now_idx_m: Optional[jnp.ndarray] = None,
    in_win_ms: Optional[jnp.ndarray] = None,
    occupy_timeout_ms: int = 500,
    has_rate_limiter: bool = True,    # STATIC: see flow_check_fast
    has_thread_rules: bool = True,    # STATIC: see flow_check
    rules_bk: Optional[jnp.ndarray] = None,   # [B, K] pre-gathered rule ids
    sortfree: bool = False,           # STATIC: see flow_check_fast
) -> Tuple[FlowDynState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Occupy-capable fast general path → (dyn', allow, wait_ms, occupied).

    :func:`flow_check_fast` plus the PRIORITIZED admission path
    (``DefaultController.canPass`` prioritized=true → ``tryOccupyNext``,
    DefaultController.java:77-97) — no composite-key sort, no greedy fixed
    point. Same host-verified preconditions as the plain fast path
    (uniform acquire >= 1, key fits int32); prioritized events and live
    bookings are allowed — that is the point.

    Why it stays bit-exact with :func:`flow_check` (enable_occupy=True):

    * LANDED bookings fold into the admission base per RULE: occupy is
      main-row-only and a valid main-row pair's ``sel_main_row`` is its
      rule's ``sync_row``, so ``landed_bk`` is a [NF+1] column riding the
      packed verdict gather (alt-row pairs never see bookings in either
      path);
    * the occupy attempt's ``greedy_admit`` runs over the same segments
      with amounts only on ELIGIBLE pairs — with uniform acquire its
      fixed point is the rank prefix AMONG ELIGIBLE PAIRS, so one extra
      per-slot rank pass over an eligibility-masked key reproduces it:
      admitted iff ``(surviving + next_window + rank_elig*a) + a <=
      limit`` (same operand association as the cumsum form);
    * the event-level gate (every failing pair must itself be
      occupy-admitted) and the one-booking-per-event scatter commit are
      the general path's own event-indexed code, verbatim — they never
      needed the sort.

    The attempt (ranks + booking scatter) runs under
    ``lax.cond(any(prioritized))``: a batch routed here only because
    bookings were still live pays one [NF+1, S] fold and nothing else.
    """
    assert in_win_ms is not None, \
        "flow_check_fast_occupy needs in_win_ms (occupy wait math)"
    dyn, allow, wait_ms, occupied, _ = _flow_check_fast_impl(
        table, dyn, rule_idx, spec, main_second, alt_second, main_threads,
        alt_threads, batch, now_idx_s, rel_now_ms, minute_spec, main_minute,
        now_idx_m, has_rate_limiter, has_thread_rules, rules_bk,
        enable_occupy=True, in_win_ms=in_win_ms,
        occupy_timeout_ms=occupy_timeout_ms, sortfree=sortfree)
    return dyn, allow, wait_ms, occupied


def flow_check_fast_occupy_sortfree(
    table, dyn, rule_idx, spec, main_second, alt_second, main_threads,
    alt_threads, batch, now_idx_s, rel_now_ms, minute_spec=None,
    main_minute=None, now_idx_m=None, in_win_ms=None, occupy_timeout_ms=500,
    has_rate_limiter=True, has_thread_rules=True, rules_bk=None,
) -> Tuple[FlowDynState, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """:func:`flow_check_fast_occupy` with ``sortfree=True``, additionally
    returning the claim-cascade overflow count (int32 scalar) →
    (dyn', allow, wait_ms, occupied, sf_overflow)."""
    assert in_win_ms is not None, \
        "flow_check_fast_occupy_sortfree needs in_win_ms (occupy wait math)"
    return _flow_check_fast_impl(
        table, dyn, rule_idx, spec, main_second, alt_second, main_threads,
        alt_threads, batch, now_idx_s, rel_now_ms, minute_spec, main_minute,
        now_idx_m, has_rate_limiter, has_thread_rules, rules_bk,
        enable_occupy=True, in_win_ms=in_win_ms,
        occupy_timeout_ms=occupy_timeout_ms, sortfree=True)


def _flow_check_fast_impl(
    table, dyn, rule_idx, spec, main_second, alt_second, main_threads,
    alt_threads, batch, now_idx_s, rel_now_ms, minute_spec, main_minute,
    now_idx_m, has_rate_limiter, has_thread_rules, rules_bk,
    enable_occupy, in_win_ms, occupy_timeout_ms, sortfree=False,
) -> Tuple[FlowDynState, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    B = batch.rows.shape[0]
    K = rule_idx.shape[1]
    NF = table.active.shape[0] - 1
    R = rule_idx.shape[0]
    RA = alt_threads.shape[0]
    # composite key must fit int32 (static shapes → checked at trace time;
    # the runtime host gate checks the same product before selecting this
    # variant and falls back to flow_check otherwise)
    assert (NF + 1) * (RA + 1) < 2 ** 31, \
        "rule-capacity x alt-rows too large for the fast general path"

    if rules_bk is None:
        rules_bk = seg.padded_table_gather(rule_idx, batch.rows, NF)  # [B,K]

    # ---- per-rule step state ----
    dyn, eff_limit = _warmup_sync_and_limits(
        table, dyn, spec, main_second, now_idx_s, rel_now_ms,
        minute_spec, main_minute, now_idx_m)
    acq_of_rule = jnp.float32(0) + jnp.max(
        jnp.where(batch.valid, batch.acquire, 0)).astype(jnp.float32)
    if has_rate_limiter:
        is_rl_rule = (((table.behavior == BEHAVIOR_RATE_LIMITER)
                       | (table.behavior == BEHAVIOR_WARM_UP_RATE_LIMITER))
                      & (table.grade == GRADE_QPS))
        base_time, cost, max_k = _rl_closed_form(
            table, dyn, acq_of_rule, rel_now_ms)

    # ---- stat reads. MAIN/REF rows are PER-RULE quantities: a valid
    # (event, rule) pair always has rule.sync_row == the event's row (the
    # rule was gathered FROM that row; sync_row = own row, or ref_row for
    # RELATE), so the main-table window/thread reads are [NF+1]-sized and
    # ride the packed gather below — no [B]-sized gather over the 1M-row
    # window table at all. Only the ORIGIN/CHAIN reads are per-event, and
    # those hit the small [RA]-row alt table. ----
    # the alt table is tiny ([RA] rows): sum it DENSELY once (cheap) and
    # gather [B] values from the result — one gather per read instead of
    # per-bucket counter+stamp gathers; padding rows index the appended 0
    alt_pass_dense = jnp.concatenate([
        window_sum_all(spec, alt_second, ev.PASS,
                       now_idx_s).astype(jnp.float32),
        jnp.zeros((1,), jnp.float32)])
    safe_orow = jnp.minimum(batch.origin_rows, RA)
    safe_crow = jnp.minimum(batch.chain_rows, RA)
    or_pass = alt_pass_dense[safe_orow]
    cr_pass = alt_pass_dense[safe_crow]
    if has_thread_rules:
        alt_thr_dense = jnp.concatenate([
            alt_threads.astype(jnp.float32), jnp.zeros((1,), jnp.float32)])
        or_thr = alt_thr_dense[safe_orow]
        cr_thr = alt_thr_dense[safe_crow]

    # per-rule selected-row reads ([NF+1]-sized; sync_row covers both the
    # MAIN row — the rule's own resource — and the REF row for RELATE)
    srow_sel = jnp.minimum(table.sync_row, R - 1)
    row_pass = window_sum_rows(spec, main_second, srow_sel, ev.PASS,
                               now_idx_s).astype(jnp.float32)
    if enable_occupy:
        # fold LANDED bookings into the per-rule QPS base (flow_check's
        # `cur_pass + landed_bk`, same operands + association); alt-row
        # pairs read the alt columns and stay booking-free, matching the
        # general path's `no_book` mask
        row_pass = row_pass + _landed_per_rule(dyn, srow_sel, spec,
                                               now_idx_s)

    # ---- ONE packed per-rule gather [NF+1, C] → [B, K, C]. Column count
    # is STATIC per ruleset: the RL block (4 columns + closed forms) only
    # exists when a rate-limiter rule is loaded, the thread block (2
    # columns) only when something reads the gauges — the same static
    # elision as skip_auth/skip_sys/skip_threads ----
    cols = [
        table.active.astype(jnp.int32),                      # 0
        table.limit_origin,                                  # 1
        table.cluster_mode.astype(jnp.int32),                # 2
        table.sel_kind,                                      # 3
        table.ref_context,                                   # 4
        lax.bitcast_convert_type(eff_limit, jnp.int32),      # 5
        lax.bitcast_convert_type(row_pass, jnp.int32),       # 6
    ]
    ncol = 7
    if has_rate_limiter:
        i_rl, i_bt, i_cost, i_mk = ncol, ncol + 1, ncol + 2, ncol + 3
        cols += [is_rl_rule.astype(jnp.int32), base_time, cost, max_k]
        ncol += 4
    if has_thread_rules:
        i_thr, i_grade = ncol, ncol + 1
        row_thr = main_threads[srow_sel].astype(jnp.float32)
        cols += [lax.bitcast_convert_type(row_thr, jnp.int32), table.grade]
        ncol += 2
    if enable_occupy:
        # per-rule occupy eligibility: only DefaultController-grade rules
        # (QPS + DEFAULT behavior) have a prioritized path
        i_occ = ncol
        cols += [((table.grade == GRADE_QPS)
                  & (table.behavior == BEHAVIOR_DEFAULT)).astype(jnp.int32)]
        ncol += 1
    vt = jnp.stack(cols, axis=1)
    g = vt[rules_bk]                                         # [B, K, C]

    # ---- applicability (FlowRuleChecker.checkFlow null-node selection) ----
    act = g[..., 0] != 0
    lim = g[..., 1]
    oid = batch.origin_ids[:, None]
    specific_hit = jnp.any((lim == oid) & act, axis=1)[:, None]
    app = act & ((lim == LIMIT_DEFAULT) | (lim == oid)
                 | ((lim == LIMIT_OTHER) & ~specific_hit & (oid != 0)))
    slot_k = jnp.arange(K, dtype=jnp.int32)[None, :]
    fb = (batch.cluster_fallback[:, None] >> slot_k) & 1
    app = app & ((g[..., 2] == 0) | (fb == 1))
    kind = g[..., 3]
    app = app & jnp.where(kind == SEL_CHAIN,
                          batch.context_ids[:, None] == g[..., 4], True)
    use_alt = (kind == SEL_ORIGIN) | (kind == SEL_CHAIN)
    alt_row = jnp.where(kind == SEL_CHAIN, batch.chain_rows[:, None],
                        batch.origin_rows[:, None])
    app = app & jnp.where(use_alt, alt_row < RA, True)
    valid_pair = batch.valid[:, None] & app

    # ---- per-pair base (selected stat row's count; MAIN/REF both come
    # from the per-rule sync_row column) ----
    main_pass_p = lax.bitcast_convert_type(g[..., 6], jnp.float32)
    alt_pass_p = jnp.where(kind == SEL_CHAIN, cr_pass[:, None],
                           or_pass[:, None])
    cur_pass = jnp.where(use_alt, alt_pass_p, main_pass_p)
    if has_thread_rules:
        main_thr_p = lax.bitcast_convert_type(g[..., i_thr], jnp.float32)
        alt_thr_p = jnp.where(kind == SEL_CHAIN, cr_thr[:, None],
                              or_thr[:, None])
        cur_thr = jnp.where(use_alt, alt_thr_p, main_thr_p)
        base = jnp.where(g[..., i_grade] == GRADE_QPS, cur_pass, cur_thr)
    else:
        base = cur_pass              # no THREAD-grade rule reads the gauge

    # ---- composite-key arrival ranks (the only cross-event pass) ----
    if has_rate_limiter:
        rl_p = g[..., i_rl] != 0
        subrow = jnp.where(use_alt & ~rl_p, alt_row + 1, 0)
    else:
        subrow = jnp.where(use_alt, alt_row + 1, 0)
    key = rules_bk * (RA + 1) + subrow
    key = jnp.where(valid_pair, key, NF * (RA + 1))
    # per-slot ranks: slot columns carry disjoint rule sets (see
    # seg.ranks_per_slot; sentinel ranks are never consumed)
    if sortfree:
        # hashed claim cascade per slot column; any column's claim
        # overflow flips the whole rank table to the sorted reference
        # via lax.cond — graceful fallback, never a wrong answer
        rank_h, sf_ovf = sfo.ranks2d_hashed(key, NF * (RA + 1),
                                            sfo.table_bits(B))
        rank = lax.cond(sf_ovf > 0,
                        lambda _: seg.ranks_per_slot(key),
                        lambda _: rank_h, None)
    else:
        rank = seg.ranks_per_slot(key)
        sf_ovf = jnp.int32(0)

    # ---- admission (closed forms) ----
    a_f = acq_of_rule                       # the uniform acquire, float32
    rankf = rank.astype(jnp.float32)
    limit_pair = lax.bitcast_convert_type(g[..., 5], jnp.float32)
    pass_default = (base + rankf * a_f) + a_f <= limit_pair
    if has_rate_limiter:
        pass_rl = rank < g[..., i_mk]
        safe_rank = jnp.minimum(rank, g[..., i_mk])
        wait_pair = jnp.maximum(
            g[..., i_bt] + (safe_rank + 1) * g[..., i_cost] - rel_now_ms,
            0)

    # ---- occupy attempt (tryOccupyNext; see flow_check_fast_occupy) ----
    if enable_occupy and in_win_ms is not None and occupy_timeout_ms > 0:
        wait_next = (jnp.int32(spec.win_ms) - in_win_ms).astype(jnp.int32)
        occ_cnt = dyn.occupied_count             # [R, S]
        occ_win = dyn.occupied_window            # [R, S]

        def _occupy_attempt(_):
            can_time = wait_next <= occupy_timeout_ms
            # per-rule: passes SURVIVING into window now+1 (flow_check's
            # survive_mask, over the rule's selected row) + bookings
            # still live in the next window — eligible pairs are always
            # main-row, where sel_main_row == sync_row
            srow_stamps = main_second.stamps[srow_sel]       # [NF+1, B]
            sdelta = now_idx_s - srow_stamps
            survive_mask = (sdelta >= 0) & (sdelta <= spec.buckets - 2)
            surviving = jnp.sum(
                jnp.where(survive_mask,
                          main_second.counters[srow_sel, :, ev.PASS], 0),
                axis=1).astype(jnp.float32)
            occ_age = now_idx_s - occ_win[srow_sel]          # [NF+1, S]
            nextw = jnp.sum(
                jnp.where((occ_age >= -1) & (occ_age < spec.buckets - 1),
                          occ_cnt[srow_sel], 0.0), axis=1)
            occ_base_p = (surviving + nextw)[rules_bk]       # [B, K]
            eligible = (batch.prioritized[:, None] & (g[..., i_occ] != 0)
                        & ~pass_default & valid_pair & ~use_alt & can_time)
            # ranks among ELIGIBLE pairs only: the general path's greedy
            # fixed point gives ineligible pairs zero amounts, so its
            # admitted set is exactly the eligible-rank prefix under the
            # uniform acquire — one extra per-slot rank pass, no sort
            key_occ = jnp.where(eligible, key, NF * (RA + 1))
            if sortfree:
                r_occ_h, ovf_occ = sfo.ranks2d_hashed(
                    key_occ, NF * (RA + 1), sfo.table_bits(B))
                rank_occ = lax.cond(
                    ovf_occ > 0,
                    lambda _: seg.ranks_per_slot(key_occ),
                    lambda _: r_occ_h, None).astype(jnp.float32)
            else:
                rank_occ = seg.ranks_per_slot(key_occ).astype(jnp.float32)
                ovf_occ = jnp.int32(0)
            occ_adm = (((occ_base_p + rank_occ * a_f) + a_f <= limit_pair)
                       & eligible)

            # event-level gate BEFORE committing bookings: every failing
            # pair of the event must itself be occupy-admitted
            if has_rate_limiter:
                pair_ok = (jnp.where(rl_p, pass_rl, pass_default | occ_adm)
                           | ~valid_pair)
            else:
                pair_ok = (pass_default | occ_adm) | ~valid_pair
            event_ok = jnp.all(pair_ok, axis=1)
            event_occ = (jnp.any(occ_adm, axis=1) & event_ok
                         & batch.valid)                      # [B]

            # one booking per admitted event on its resource row, slot
            # ring keyed by window now+1 (flow_check's commit, verbatim)
            slots_n = occ_cnt.shape[1]
            slot = (now_idx_s + 1) % slots_n
            grants = jnp.zeros(occ_cnt.shape[0], jnp.float32).at[
                jnp.where(event_occ, batch.rows, occ_cnt.shape[0])].add(
                jnp.where(event_occ, batch.acquire, 0).astype(jnp.float32),
                mode="drop")
            granted_row = grants > 0
            slot_keep = occ_win[:, slot] == now_idx_s + 1
            new_cnt = jnp.where(granted_row,
                                jnp.where(slot_keep, occ_cnt[:, slot], 0.0)
                                + grants,
                                occ_cnt[:, slot])
            new_win = jnp.where(granted_row, now_idx_s + 1,
                                occ_win[:, slot])
            return (occ_cnt.at[:, slot].set(new_cnt),
                    occ_win.at[:, slot].set(new_win),
                    occ_adm & event_occ[:, None],
                    ovf_occ)

        def _no_occupy(_):
            return (occ_cnt, occ_win, jnp.zeros_like(pass_default),
                    jnp.int32(0))

        # real control flow, like flow_check: a batch routed here only
        # because bookings were live (no prioritized events) skips the
        # whole attempt — it pays the landed fold and nothing else
        new_occ_cnt, new_occ_win, occ_adm_p, sf_ovf_occ = jax.lax.cond(
            jnp.any(batch.prioritized), _occupy_attempt, _no_occupy, None)
        dyn = dyn._replace(occupied_count=new_occ_cnt,
                           occupied_window=new_occ_win)
        sf_ovf = sf_ovf + sf_ovf_occ
    else:
        occ_adm_p = jnp.zeros_like(pass_default)
        wait_next = jnp.int32(0)

    if has_rate_limiter:
        pair_pass = (jnp.where(rl_p, pass_rl, pass_default | occ_adm_p)
                     | ~valid_pair)
        pair_wait = jnp.where(rl_p & pair_pass & valid_pair, wait_pair, 0)
        if enable_occupy:
            pair_wait = jnp.maximum(pair_wait,
                                    jnp.where(occ_adm_p, wait_next, 0))
        wait_ms = jnp.max(pair_wait, axis=1)
    else:
        pair_pass = (pass_default | occ_adm_p) | ~valid_pair
        if enable_occupy:
            wait_ms = jnp.max(jnp.where(occ_adm_p, wait_next, 0), axis=1)
        else:
            wait_ms = jnp.zeros((B,), jnp.int32)

    allow = jnp.all(pair_pass, axis=1)
    occupied = jnp.any(occ_adm_p, axis=1) & allow & batch.valid

    # ---- pacing-clock update (per rule; RL segments are per-rule) ----
    if has_rate_limiter:
        rl_valid = rl_p & valid_pair
        npairs = jnp.zeros((NF + 2,), jnp.int32).at[
            jnp.where(rl_valid, rules_bk, NF + 1)].max(
            rank + 1, mode="drop")[:NF + 1]
        passed = jnp.minimum(npairs, max_k)
        passed = jnp.where(is_rl_rule & (table.count > 0), passed, 0)
        new_latest = jnp.where(
            passed > 0,
            (base_time + passed * cost).astype(jnp.int32),
            dyn.latest_passed_ms)
        dyn = dyn._replace(
            latest_passed_ms=jnp.maximum(dyn.latest_passed_ms, new_latest))

    allow = allow | ~batch.valid
    return dyn, allow, wait_ms.astype(jnp.int32), occupied, sf_ovf


def _rl_closed_form(table: FlowRuleTable, dyn: FlowDynState,
                    acq_of_rule: jnp.ndarray, rel_now_ms: jnp.ndarray):
    """Per-rule RATE_LIMITER closed form → (base_time, cost, max_k),
    shared bit-exactly by the scalar and fast paths (cost is per-rule
    for uniform acquire — RateLimiterController.java:30-90).

    All arithmetic stays per-RULE and BOUNDED: the admitted-rank budget
    ``max_k = (now + maxq - base_time) // cost`` has numerator in
    ``[0, cost + maxq]`` (due ⇒ base_time = now - cost; else
    now - L0 < cost), so no rank*cost product over the unbounded arrival
    rank can overflow int32 — a pair passes iff ``rank < max_k``.
    ``cost == 0`` (huge count): every rank shares one wait =
    ``max(base - now, 0)``, matching the general path's uniform-latest
    case. ``count <= 0`` RL blocks everything."""
    count_safe = jnp.maximum(table.count, 1e-9)
    cost = jnp.round(acq_of_rule / count_safe * 1000.0).astype(jnp.int32)
    L0 = dyn.latest_passed_ms
    due = (L0 + cost - rel_now_ms) <= 0
    base_time = jnp.where(due, rel_now_ms - cost, L0)
    maxq_eff = jnp.where(table.count > 0, table.max_queue_ms,
                         jnp.int32(-1))
    rl_numer = rel_now_ms + maxq_eff - base_time
    max_k = jnp.maximum(rl_numer // jnp.maximum(cost, 1), 0)
    wait0_ok = jnp.maximum(base_time - rel_now_ms, 0) <= maxq_eff
    max_k = jnp.where(cost > 0, max_k,
                      jnp.where(wait0_ok, jnp.int32(2 ** 30), 0))
    max_k = jnp.where(table.count > 0, max_k, 0)
    return base_time, cost, max_k


def _warmup_sync_and_limits(
    table: FlowRuleTable, dyn: FlowDynState, spec: WindowSpec,
    main_second: WindowState, now_idx_s: jnp.ndarray, rel_now_ms: jnp.ndarray,
    minute_spec: Optional[WindowSpec], main_minute: Optional[WindowState],
    now_idx_m: Optional[jnp.ndarray],
) -> Tuple[FlowDynState, jnp.ndarray]:
    """Once-per-step warm-up token refill (WarmUpController.syncToken) and the
    per-rule effective QPS limit for this step.

    Non-warm-up rules get their plain ``count``. Token state syncs against the
    rule's ``sync_row``, using the previous *second's* pass count — the
    reference reads ``previousPassQps`` from the MINUTE array's previous 1 s
    bucket (``StatisticNode.previousPassQps`` → ``rollingCounterInMinute``),
    so the minute window is the canonical source; without it we fall back to
    the second window's previous (sub-second) bucket, which under-counts and
    makes the ramp slower (conservative).
    """
    is_wu = ((table.behavior == BEHAVIOR_WARM_UP)
             | (table.behavior == BEHAVIOR_WARM_UP_RATE_LIMITER)) & (
        table.grade == GRADE_QPS)
    R = main_second.stamps.shape[0]
    srow = jnp.minimum(table.sync_row, R - 1)
    if minute_spec is not None and main_minute is not None:
        pass_prev = prev_window_sum_rows(minute_spec, main_minute, srow, ev.PASS,
                                         now_idx_m).astype(jnp.float32)
    else:
        pass_prev = prev_window_sum_rows(spec, main_second, srow, ev.PASS,
                                         now_idx_s).astype(jnp.float32)

    now_sec = rel_now_ms // 1000
    should_sync = is_wu & (now_sec > dyn.last_filled_sec)
    old = dyn.stored_tokens
    elapsed_s = (now_sec - dyn.last_filled_sec).astype(jnp.float32)
    refill_ok = (old < table.warning_token) | (
        (old > table.warning_token)
        & (pass_prev < table.count / jnp.maximum(table.cold_factor, 1.001)))
    refilled = jnp.minimum(old + elapsed_s * table.count, table.max_token)
    new_tokens = jnp.where(refill_ok, refilled, old)
    new_tokens = jnp.maximum(new_tokens - pass_prev, 0.0)
    stored = jnp.where(should_sync, new_tokens, old)
    last_filled = jnp.where(should_sync, now_sec, dyn.last_filled_sec)
    dyn = dyn._replace(stored_tokens=stored, last_filled_sec=last_filled)

    above = jnp.maximum(stored - table.warning_token, 0.0)
    warning_qps = 1.0 / (above * table.slope + 1.0 / jnp.maximum(table.count, 1e-9))
    eff = jnp.where(is_wu & (stored >= table.warning_token),
                    warning_qps, table.count)
    return dyn, eff
