"""Circuit breakers: vectorized DegradeSlot.

Reference (``sentinel-core/.../slots/block/degrade/``):

* ``DegradeSlot`` — entry: every breaker for the resource must ``tryPass``;
  exit: if the entry wasn't blocked, ``onRequestComplete`` feeds each breaker.
* ``AbstractCircuitBreaker`` — CLOSED/OPEN/HALF_OPEN CAS state machine; OPEN
  → HALF_OPEN probe after ``timeWindow`` s (one winner passes); probe failure
  re-opens, success closes.
* ``ResponseTimeCircuitBreaker`` — slow-ratio over a single-bucket LeapArray
  of ``statIntervalMs`` (``new LeapArray<SlowRequestCounter>(1, intervalMs)``);
  trips when ``slow/total > slowRatioThreshold`` and ``total >=
  minRequestAmount``. ``count`` is the max allowed RT.
* ``ExceptionCircuitBreaker`` — ERROR_RATIO / ERROR_COUNT over the same
  single-bucket window shape.

TPU-native shape: one struct-of-arrays breaker state; the per-rule
"single-bucket LeapArray" is a (stamp, slow, total) triple with per-rule
window length — lazy reset by window-index comparison, wraparound-safe int32
rel-ms. Probe admission in a batch picks the segment-first event (the CAS
winner analog).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from sentinel_tpu.ops import segments as seg

# Grades (reference RuleConstant.DEGRADE_GRADE_*)
GRADE_RT = 0
GRADE_EXCEPTION_RATIO = 1
GRADE_EXCEPTION_COUNT = 2

STATE_CLOSED = 0
STATE_OPEN = 1
STATE_HALF_OPEN = 2


@dataclasses.dataclass
class DegradeRule:
    """Host-facing rule (reference ``DegradeRule.java`` field parity)."""

    resource: str
    grade: int
    count: float                 # RT: max allowed rt ms; RATIO: [0,1]; COUNT: n
    time_window: int             # seconds to stay OPEN
    min_request_amount: int = 5
    stat_interval_ms: int = 1000
    slow_ratio_threshold: float = 1.0

    def is_valid(self) -> bool:
        if not self.resource or self.count < 0 or self.time_window <= 0:
            return False
        if self.grade not in (GRADE_RT, GRADE_EXCEPTION_RATIO, GRADE_EXCEPTION_COUNT):
            return False
        if self.grade == GRADE_EXCEPTION_RATIO and self.count > 1.0:
            return False
        if self.min_request_amount <= 0 or self.stat_interval_ms <= 0:
            return False
        if self.grade == GRADE_RT and not (0.0 <= self.slow_ratio_threshold <= 1.0):
            return False
        return True


class DegradeRuleTable(NamedTuple):
    """Static device arrays, ND+1 rows (sentinel last)."""

    active: jnp.ndarray              # bool
    grade: jnp.ndarray               # int32
    count: jnp.ndarray               # float32
    retry_timeout_ms: jnp.ndarray    # int32 (time_window * 1000)
    min_request: jnp.ndarray         # int32
    interval_ms: jnp.ndarray         # int32
    ratio_threshold: jnp.ndarray     # float32 (slow ratio or error ratio or count)


class BreakerState(NamedTuple):
    """Mutable device state."""

    state: jnp.ndarray               # int32[ND+1] STATE_*
    next_retry_ms: jnp.ndarray       # int32[ND+1] rel-ms
    win_stamp: jnp.ndarray           # int32[ND+1] window index of the bucket
    bad: jnp.ndarray                 # int32[ND+1] slow or error count
    total: jnp.ndarray               # int32[ND+1] completed count


class CompiledDegradeRules(NamedTuple):
    table: DegradeRuleTable
    rule_idx: jnp.ndarray            # int32[R, Kd]
    rules: Tuple[DegradeRule, ...]
    num_active: int
    k_used: int = 1                  # max rules on any one resource
    # the numpy original of rule_idx, kept so the runtime's ruleset
    # assembly (used-slot slicing + joint-gather concat) runs host-side
    # — two fewer program loads per process on a tunneled TPU
    rule_idx_np: Optional["np.ndarray"] = None


def init_breaker_state(nd: int) -> BreakerState:
    return BreakerState(
        state=jnp.zeros((nd + 1,), jnp.int32),
        next_retry_ms=jnp.full((nd + 1,), -(2 ** 30), jnp.int32),
        win_stamp=jnp.full((nd + 1,), -(2 ** 30), jnp.int32),
        bad=jnp.zeros((nd + 1,), jnp.int32),
        total=jnp.zeros((nd + 1,), jnp.int32),
    )


def compile_degrade_rules(rules: Sequence[DegradeRule], *, resource_registry,
                          capacity: int, k_per_resource: int,
                          num_rows: int) -> CompiledDegradeRules:
    valid = [r for r in rules if r.is_valid()]
    if len(valid) > capacity:
        raise ValueError(f"too many degrade rules: {len(valid)} > {capacity}")
    nd = capacity
    active = np.zeros(nd + 1, np.bool_)
    grade = np.zeros(nd + 1, np.int32)
    count = np.zeros(nd + 1, np.float32)
    retry = np.full(nd + 1, 1, np.int32)
    minreq = np.full(nd + 1, 1, np.int32)
    interval = np.full(nd + 1, 1000, np.int32)
    ratio = np.zeros(nd + 1, np.float32)
    rule_idx = np.full((num_rows, k_per_resource), nd, np.int32)
    slots_used = {}
    for j, r in enumerate(valid):
        row = resource_registry.pin(r.resource)
        k = slots_used.get(row, 0)
        if k >= k_per_resource:
            raise ValueError(
                f"more than {k_per_resource} degrade rules for {r.resource!r}")
        slots_used[row] = k + 1
        rule_idx[row, k] = j
        active[j] = True
        grade[j] = r.grade
        count[j] = r.count
        retry[j] = r.time_window * 1000
        minreq[j] = r.min_request_amount
        interval[j] = r.stat_interval_ms
        if r.grade == GRADE_RT:
            ratio[j] = r.slow_ratio_threshold
        elif r.grade == GRADE_EXCEPTION_RATIO:
            ratio[j] = r.count
        else:
            ratio[j] = r.count  # absolute error count
    table = DegradeRuleTable(
        active=jnp.asarray(active), grade=jnp.asarray(grade),
        count=jnp.asarray(count), retry_timeout_ms=jnp.asarray(retry),
        min_request=jnp.asarray(minreq), interval_ms=jnp.asarray(interval),
        ratio_threshold=jnp.asarray(ratio),
    )
    return CompiledDegradeRules(table=table, rule_idx=jnp.asarray(rule_idx),
                                rules=tuple(valid), num_active=len(valid),
                                k_used=max(1, max(slots_used.values(),
                                                  default=0)),
                                rule_idx_np=rule_idx)


def degrade_entry_check(
    table: DegradeRuleTable, st: BreakerState, rule_idx: jnp.ndarray,
    rows: jnp.ndarray, valid: jnp.ndarray, rel_now_ms: jnp.ndarray,
) -> Tuple[BreakerState, jnp.ndarray]:
    """→ (state', allow bool[B]).

    CLOSED passes; OPEN passes one probe per rule once the retry window
    elapsed (transitioning to HALF_OPEN); HALF_OPEN blocks (the in-flight
    probe owns it). Mirrors ``AbstractCircuitBreaker.tryPass`` +
    ``fromOpenToHalfOpen`` with segment-first as the CAS winner.
    """
    B = rows.shape[0]
    Kd = rule_idx.shape[1]
    ND = table.active.shape[0] - 1
    R = rule_idx.shape[0]

    safe_rows = jnp.minimum(rows, R - 1)
    rules_bk = jnp.where((rows < R)[:, None], rule_idx[safe_rows], ND)
    rj = rules_bk.reshape(-1)
    valid_bk = jnp.repeat(valid, Kd) & table.active[rj]
    rj_seg = jnp.where(valid_bk, rj, ND)

    order = seg.sort_by_keys(rj_seg)
    rj_s = rj_seg[order]
    starts = seg.segment_starts(rj_s, jnp.zeros_like(rj_s))

    # one packed gather for both breaker-state columns (separate 1M-element
    # gathers cost ~8x a packed one on TPU — BASELINE.md round 3)
    gs = jnp.stack([st.state, st.next_retry_ms], axis=1)[rj_s]
    state_s = gs[:, 0]
    retry_due = (rel_now_ms - gs[:, 1]) >= 0
    open_probe = (state_s == STATE_OPEN) & retry_due & starts
    pass_s = (state_s == STATE_CLOSED) | open_probe | (rj_s == ND)

    pair_pass = seg.unsort(order, pass_s.astype(jnp.int32)).astype(jnp.bool_)
    allow = jnp.all(pair_pass.reshape(B, Kd), axis=1)

    # OPEN→HALF_OPEN only for rules whose probe event is actually admitted by
    # ALL breakers of its resource. Transitioning unconditionally would strand
    # a rule in HALF_OPEN with no in-flight probe to resolve it when a sibling
    # breaker blocks the event (reference parity: fromOpenToHalfOpen reverts
    # via entry.whenTerminate when the entry is blocked downstream).
    event_of_s = order // Kd  # sorted position → originating event index
    probe_event_ok = allow[event_of_s]
    probe_rules = jnp.where(open_probe & probe_event_ok, rj_s, ND)
    new_state = st.state.at[probe_rules].set(STATE_HALF_OPEN, mode="drop")
    new_state = new_state.at[ND].set(STATE_CLOSED)  # keep sentinel inert
    st = st._replace(state=new_state)

    return st, allow | ~valid


def degrade_entry_check_scalar(
    table: DegradeRuleTable, st: BreakerState, rule_idx: jnp.ndarray,
    rows: jnp.ndarray, valid: jnp.ndarray, rel_now_ms: jnp.ndarray,
    rules_bk: Optional[jnp.ndarray] = None,   # pre-gathered [B, Kd] rule
    # ids (the pipeline's joint flow+degrade gather); None = gather here
) -> Tuple[BreakerState, jnp.ndarray]:
    """Sort-free :func:`degrade_entry_check` → (state', allow bool[B]).

    Breaker state is per-RULE, so the only cross-event computation is the
    probe election (one winner per OPEN rule whose retry window elapsed —
    the CAS-winner analog). The common all-CLOSED case is one packed
    per-rule lookup gathered per pair; probe election runs under a
    ``lax.cond`` (a batch only pays the scatter-min when some rule is
    actually OPEN with its retry due). Bit-exact with the sorted path:
    the scatter-min winner is the first valid pair in batch order, which
    is what sort stability picked. Reference:
    ``AbstractCircuitBreaker.tryPass`` + ``fromOpenToHalfOpen``.
    """
    B = rows.shape[0]
    Kd = rule_idx.shape[1]
    ND = table.active.shape[0] - 1
    R = rule_idx.shape[0]
    BK = B * Kd

    if rules_bk is None:
        rules_bk = seg.padded_table_gather(rule_idx, rows, ND)
    rj = rules_bk.reshape(-1)
    # no active[rj] gather: an INACTIVE rule is structurally CLOSED (its
    # state never leaves CLOSED — trip and probe both require active), so
    # its pairs pass via pass_rule and can never win a probe; only event
    # VALIDITY must exclude pairs from probe election
    valid_bk = jnp.repeat(valid, Kd)
    key = jnp.where(valid_bk, rj, ND)

    open_due = ((st.state == STATE_OPEN)
                & ((rel_now_ms - st.next_retry_ms) >= 0)
                & table.active)
    pass_rule = (st.state == STATE_CLOSED) | ~table.active
    pass_rule = pass_rule.at[ND].set(True)       # sentinel never blocks
    # the base verdict is needed by BOTH cond branches: hoisting it keeps
    # the common no-probe branch a pure pass-through. (Measured: running
    # the election UNCONDITIONALLY costs ~6 ms/step more than this cond —
    # the [B]→[ND] scatter-min is the expensive part, not the branch.)
    pair_base = pass_rule[key]

    def _no_probe(_):
        return st.state, jnp.all(pair_base.reshape(B, Kd), axis=1)

    def _probe(_):
        idx = jnp.arange(BK, dtype=jnp.int32)
        win = seg.first_index_by_key(key, ND + 1)
        winner_pair = (idx == win[key]) & open_due[key]
        pair_pass = pair_base | winner_pair
        allow_ev = jnp.all(pair_pass.reshape(B, Kd), axis=1)
        # OPEN→HALF_OPEN only when the probe's event is admitted by ALL
        # breakers of its resource (general-path comment at
        # degrade_entry_check for why)
        winner_ev = jnp.minimum(win // Kd, B - 1)
        ok = open_due & (win < BK) & allow_ev[winner_ev]
        new_state = jnp.where(ok, STATE_HALF_OPEN, st.state)
        return new_state, allow_ev

    new_state, allow_ev = jax.lax.cond(
        jnp.any(open_due), _probe, _no_probe, None)
    st = st._replace(state=new_state.at[ND].set(STATE_CLOSED))
    return st, allow_ev | ~valid


def degrade_exit_feed(
    table: DegradeRuleTable, st: BreakerState, rule_idx: jnp.ndarray,
    rows: jnp.ndarray, rt_ms: jnp.ndarray, error: jnp.ndarray,
    valid: jnp.ndarray, rel_now_ms: jnp.ndarray,
) -> BreakerState:
    """Completion feed (``DegradeSlot.exit`` → ``onRequestComplete``).

    Records (total, slow-or-error) into each rule's single bucket with lazy
    per-rule window reset, resolves HALF_OPEN probes, and trips CLOSED
    breakers whose window crossed the threshold.
    """
    Kd = rule_idx.shape[1]
    ND = table.active.shape[0] - 1
    R = rule_idx.shape[0]

    safe_rows = jnp.minimum(rows, R - 1)
    rules_bk = jnp.where((rows < R)[:, None], rule_idx[safe_rows], ND)
    rj = rules_bk.reshape(-1)
    valid_bk = jnp.repeat(valid, Kd) & table.active[rj] & (rj != ND)
    rj_safe = jnp.where(valid_bk, rj, ND)

    rt_bk = jnp.repeat(rt_ms, Kd)
    err_bk = jnp.repeat(error, Kd)
    is_rt = table.grade[rj_safe] == GRADE_RT
    bad_bk = jnp.where(is_rt, rt_bk.astype(jnp.float32) > table.count[rj_safe],
                       err_bk).astype(jnp.int32)

    # --- HALF_OPEN probe resolution (before window bookkeeping) ---
    # Sort-free: the probe outcome is per-RULE (the first valid completion
    # in batch order resolves it), so a scatter-min elects the winner pair
    # and everything else is [ND]-sized — and the whole election runs under
    # a lax.cond so batches with no in-flight probe (the common case) pay
    # nothing. Winner order parity: flattened [B, Kd] index order is batch
    # order, exactly what the old stable sort's segment-first picked.
    BK = rj_safe.shape[0]

    def _no_resolve(_):
        return st.state, st.next_retry_ms, st.win_stamp

    def _resolve(_):
        win = seg.first_index_by_key(rj_safe, ND + 1)
        half = (st.state == STATE_HALF_OPEN) & (win < BK)
        winner_bad = bad_bk[jnp.minimum(win, BK - 1)]
        ok_r = half & (winner_bad == 0)
        fail_r = half & (winner_bad != 0)
        state = jnp.where(ok_r, STATE_CLOSED,
                          jnp.where(fail_r, STATE_OPEN, st.state))
        next_retry = jnp.where(fail_r, rel_now_ms + table.retry_timeout_ms,
                               st.next_retry_ms)
        # closing resets the stat window (reference resetStat on close)
        win_stamp = jnp.where(ok_r, -(2 ** 30), st.win_stamp)
        return state, next_retry, win_stamp

    state, next_retry, win_stamp = jax.lax.cond(
        jnp.any(st.state == STATE_HALF_OPEN), _resolve, _no_resolve, None)
    state = state.at[ND].set(STATE_CLOSED)
    st = st._replace(state=state, next_retry_ms=next_retry.astype(jnp.int32),
                     win_stamp=win_stamp)

    # --- single-bucket lazy reset + scatter-add ---
    widx = rel_now_ms // jnp.maximum(table.interval_ms[rj_safe], 1)   # [BK]
    keep = (st.win_stamp[rj_safe] == widx).astype(jnp.int32)
    bad0 = st.bad.at[rj_safe].multiply(keep, mode="drop")
    total0 = st.total.at[rj_safe].multiply(keep, mode="drop")
    stamp = st.win_stamp.at[rj_safe].set(widx, mode="drop")
    ones = valid_bk.astype(jnp.int32)
    bad1 = bad0.at[rj_safe].add(bad_bk * ones, mode="drop")
    total1 = total0.at[rj_safe].add(ones, mode="drop")
    st = st._replace(bad=bad1, total=total1, win_stamp=stamp)

    # --- trip CLOSED breakers (vector over rules) ---
    grade = table.grade
    totals = st.total.astype(jnp.float32)
    bads = st.bad.astype(jnp.float32)
    enough = st.total >= table.min_request
    ratio = bads / jnp.maximum(totals, 1.0)
    trip_ratio = enough & (ratio > table.ratio_threshold)
    # RT grade: reference also trips when ratio threshold >= 1 means never
    trip_count = bads >= table.ratio_threshold
    trip = jnp.where(grade == GRADE_EXCEPTION_COUNT, enough & trip_count, trip_ratio)
    trip = trip & (st.state == STATE_CLOSED) & table.active
    state = jnp.where(trip, STATE_OPEN, st.state)
    next_retry = jnp.where(trip, rel_now_ms + table.retry_timeout_ms, st.next_retry_ms)
    return st._replace(state=state, next_retry_ms=next_retry.astype(jnp.int32))
