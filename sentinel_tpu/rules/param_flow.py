"""Hot-parameter flow control: vectorized ParamFlowSlot / ParamFlowChecker.

Reference semantics being reproduced (``sentinel-extension/
sentinel-parameter-flow-control``):

* ``ParamFlowChecker.passDefaultLocalCheck:139-220`` — a simplified token
  bucket per (rule, param value): tokens replenish only once the statistic
  window (``durationInSec``) has passed, refill = ``passTime × tokenCount /
  durationMs`` capped at ``count + burstCount``; an acquire larger than the
  cap, or a zero threshold, blocks outright.
* ``ParamFlowChecker.passThrottleLocalCheck:224-270`` — RATE_LIMITER
  behavior = per-key paced queue with ``costTime = round(1000 · acquire ·
  durationInSec / tokenCount)``; wait must be strictly under
  ``maxQueueingTimeMs`` (default 0 ⇒ only zero-wait passes).
* ``ParamFlowChecker.passSingleValueCheck:115-137`` — THREAD grade = per-key
  live concurrency, ``count + 1 <= threshold`` (acquire ignored).
* ``ParamFlowRule.java:45-83`` — field parity (paramIdx, durationInSec=1,
  burstCount=0, maxQueueingTimeMs=0, paramFlowItemList per-value overrides).
* ``ParamFlowSlot.applyRealParamIdx:56-67`` — negative paramIdx counts from
  the tail; out-of-range indices silently pass.
* ``ParameterMetric.java:37-39`` — key storage is an exact LRU-bounded map
  (NOT a sketch); reproduced host-side by :class:`ParamKeyRegistry`.

TPU-native shape: param values are interned host-side into *key rows* of a
fixed device table (LRU like the reference's ``ConcurrentLinkedHashMap``
caches, but with loud capacity + device-side invalidation of recycled rows);
token/pacing/concurrency state is four dense vectors indexed by key row, and
the check is a segmented scan over (event × pair) applications — the same
machinery as ``flow_check``. Per-item overrides live in a per-key-row
``override`` vector written at intern time, so the device never sees strings.

Divergences (bounded, documented): the token-refill timestamp advances even
when every request in the refilling batch is denied (the reference only
advances it on a passing request — affects only the sub-window fractional
accrual, worst case one ``durationInSec`` of refill); in-batch admission is
greedy-FIFO rather than thread-racy (same class of skew the reference's CAS
loops tolerate).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from sentinel_tpu.ops import segments as seg

GRADE_THREAD = 0
GRADE_QPS = 1
BEHAVIOR_DEFAULT = 0
BEHAVIOR_RATE_LIMITER = 2

_NEVER = -(2 ** 30)


@dataclasses.dataclass
class ParamFlowItem:
    """Per-value threshold override (reference ``ParamFlowItem``)."""

    object: Any
    count: int
    class_type: str = ""   # informational; values are compared by key form


@dataclasses.dataclass
class ParamFlowRule:
    """Host-facing rule (reference ``ParamFlowRule.java`` field parity)."""

    resource: str
    param_idx: int = 0
    count: float = 0.0
    grade: int = GRADE_QPS
    duration_in_sec: int = 1
    burst_count: int = 0
    control_behavior: int = BEHAVIOR_DEFAULT
    max_queueing_time_ms: int = 0
    param_flow_item_list: List[ParamFlowItem] = dataclasses.field(default_factory=list)
    cluster_mode: bool = False
    cluster_flow_id: int = 0

    def is_valid(self) -> bool:
        # ParamFlowRuleUtil.isValidRule: non-empty resource, count >= 0,
        # grade valid, duration > 0, paramIdx set
        if not self.resource or self.count < 0 or self.duration_in_sec <= 0:
            return False
        if self.grade not in (GRADE_THREAD, GRADE_QPS):
            return False
        if self.param_idx is None:
            return False
        return True

    def hot_items(self) -> Dict[Any, int]:
        """Parsed per-value overrides (``ParamFlowRuleUtil.parseHotItems``)."""
        out: Dict[Any, int] = {}
        for it in self.param_flow_item_list:
            if it.object is not None and it.count >= 0:
                out[_key_form(it.object)] = int(it.count)
        return out


def _key_form(value: Any) -> Any:
    """Canonical hashable form of a param value (reference compares via
    Object.equals; here unhashables fall back to repr)."""
    pk = getattr(value, "param_flow_key", None)
    if callable(pk):  # ParamFlowArgument analog
        value = pk()
    try:
        hash(value)
        return value
    except TypeError:
        return repr(value)


class ParamRuleTable(NamedTuple):
    """Static per-rule device arrays, NP+1 rows (last = inactive sentinel)."""

    active: jnp.ndarray        # bool[NP+1]
    grade: jnp.ndarray         # int32
    count: jnp.ndarray         # float32
    duration_ms: jnp.ndarray   # int32
    burst: jnp.ndarray         # float32
    behavior: jnp.ndarray      # int32
    max_queue_ms: jnp.ndarray  # int32


class ParamDynState(NamedTuple):
    """Per-key-row mutable device state, PK+1 rows (last = scatter sink)."""

    tokens: jnp.ndarray          # float32[PK+1]
    last_fill_ms: jnp.ndarray    # int32[PK+1] rel-ms; _NEVER = never filled
    latest_passed_ms: jnp.ndarray  # int32[PK+1] rate-limiter pacing clock
    threads: jnp.ndarray         # int32[PK+1] per-key live concurrency
    override: jnp.ndarray        # float32[PK+1]; <0 = use rule count


class CompiledParamRules(NamedTuple):
    table: ParamRuleTable
    rules: Tuple[ParamFlowRule, ...]       # index-aligned with table
    # host map: main row → ((table_slot, param_idx, hot_items), ...) — pairs
    # are resolved host-side at entry time, so no device gather table exists
    by_row: Dict[int, Tuple[Tuple[int, int, Dict[Any, int]], ...]]
    num_active: int
    # bool[len(rules)] — THREAD-grade per slot, precomputed so the batch
    # tier's pin-row masking is one numpy gather instead of a per-pair loop
    thread_slot_mask: Any = None
    # (row_slot int32[max_row+1], row_idx int32[max_row+1]) when EVERY ruled
    # resource has exactly one rule with a non-negative param index and no
    # per-item overrides — the shape that lets the batch tier resolve pairs
    # fully vectorized (see resolve_pairs_many); None otherwise
    vector_meta: Any = None


def init_param_dyn(pk: int) -> ParamDynState:
    return ParamDynState(
        tokens=jnp.zeros((pk + 1,), jnp.float32),
        last_fill_ms=jnp.full((pk + 1,), _NEVER, jnp.int32),
        latest_passed_ms=jnp.full((pk + 1,), _NEVER, jnp.int32),
        threads=jnp.zeros((pk + 1,), jnp.int32),
        override=jnp.full((pk + 1,), -1.0, jnp.float32),
    )


def compile_param_rules(rules: Sequence[ParamFlowRule], *, resource_registry,
                        capacity: int, k_per_resource: int) -> CompiledParamRules:
    """Validate + vectorize (the ``ParamFlowRuleUtil`` analog). Loud on
    capacity overflow, like the other compilers."""
    valid = [r for r in rules if r.is_valid()]
    if len(valid) > capacity:
        raise ValueError(f"too many param flow rules: {len(valid)} > {capacity}")

    np_ = capacity
    active = np.zeros(np_ + 1, np.bool_)
    grade = np.zeros(np_ + 1, np.int32)
    count = np.zeros(np_ + 1, np.float32)
    duration_ms = np.full(np_ + 1, 1000, np.int32)
    burst = np.zeros(np_ + 1, np.float32)
    behavior = np.zeros(np_ + 1, np.int32)
    max_queue_ms = np.zeros(np_ + 1, np.int32)
    by_row: Dict[int, List[Tuple[int, int, Dict[Any, int]]]] = {}
    slots_used: Dict[int, int] = {}

    for j, r in enumerate(valid):
        row = resource_registry.pin(r.resource)
        k = slots_used.get(row, 0)
        if k >= k_per_resource:
            raise ValueError(
                f"more than {k_per_resource} param rules for {r.resource!r}")
        slots_used[row] = k + 1
        by_row.setdefault(row, []).append((j, int(r.param_idx), r.hot_items()))

        active[j] = True
        grade[j] = r.grade
        count[j] = r.count
        duration_ms[j] = int(r.duration_in_sec) * 1000
        burst[j] = r.burst_count
        behavior[j] = r.control_behavior
        max_queue_ms[j] = r.max_queueing_time_ms

    table = ParamRuleTable(
        active=jnp.asarray(active), grade=jnp.asarray(grade),
        count=jnp.asarray(count), duration_ms=jnp.asarray(duration_ms),
        burst=jnp.asarray(burst), behavior=jnp.asarray(behavior),
        max_queue_ms=jnp.asarray(max_queue_ms))
    by_row_t = {k: tuple(v) for k, v in by_row.items()}
    vector_meta = None
    if by_row_t and all(
            len(entries) == 1 and entries[0][1] >= 0 and not entries[0][2]
            for entries in by_row_t.values()):
        max_row = max(by_row_t)
        row_slot = np.full(max_row + 1, -1, np.int32)
        row_idx = np.zeros(max_row + 1, np.int32)
        for row, entries in by_row_t.items():
            row_slot[row] = entries[0][0]
            row_idx[row] = entries[0][1]
        vector_meta = (row_slot, row_idx)
    return CompiledParamRules(
        table=table, rules=tuple(valid),
        by_row=by_row_t, num_active=len(valid),
        thread_slot_mask=np.array([r.grade == GRADE_THREAD for r in valid],
                                  np.bool_),
        vector_meta=vector_meta)


# ---------------------------------------------------------------------------
# Host-side key interning (ParameterMetric / CacheMap analog)
# ---------------------------------------------------------------------------

class ParamKeyRegistry:
    """LRU intern table: (rule_slot, value) → device key row.

    Mirrors ``ParameterMetric``'s ``ConcurrentLinkedHashMapWrapper`` caches
    (exact, LRU-bounded — SURVEY §2.2), sized globally like
    ``TOTAL_MAX_CAPACITY``. Evicted rows are drained by the runtime and
    invalidated on device so a recycled row starts cold. Rows for values with
    per-item overrides record a pending (row, threshold) update the runtime
    flushes into ``ParamDynState.override`` before the next decide step.
    """

    def __init__(self, capacity: int):
        self._cap = capacity
        self._map: "OrderedDict[Tuple[int, Any], int]" = OrderedDict()
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._evicted: List[int] = []
        self._pending_override: List[Tuple[int, float]] = []
        self._pins: Dict[int, int] = {}   # row → live-entry refcount
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        return self._cap

    def get_or_create(self, rule_slot: int, value: Any,
                      override: Optional[int] = None) -> int:
        key = (rule_slot, _key_form(value))
        with self._lock:
            row = self._map.get(key)
            if row is not None:
                self._map.move_to_end(key)
                return row
            if self._free:
                row = self._free.pop()
            else:
                row = self._evict_lru_locked()
            self._map[key] = row
            if override is not None:
                self._pending_override.append((row, float(override)))
            return row

    def _evict_lru_locked(self) -> int:
        # skip rows pinned by in-flight entries: recycling one would let the
        # old entry's exit decrement the row's NEW occupant's thread count
        for key, row in self._map.items():
            if not self._pins.get(row):
                del self._map[key]
                self._evicted.append(row)
                # a queued override for the evicted occupant must not land on
                # the row's next occupant at the coming drain
                self._pending_override = [
                    (r, v) for r, v in self._pending_override if r != row]
                return row
        raise RuntimeError(
            "all hot-param key rows are pinned by live entries; "
            "raise param_table_slots")

    def _real_pin_counts(self, rows):
        """Unique (row, multiplicity) among rows below capacity — sentinel
        pin-noop rows drop out vectorized, so a 4k-event batch with no
        THREAD-grade pairs costs one numpy filter, not 4k dict ops."""
        arr = np.asarray(rows)
        if arr.size == 0:
            return (), ()
        arr = arr[arr < self._cap]
        if arr.size == 0:
            return (), ()
        uniq, cnt = np.unique(arr, return_counts=True)
        return uniq.tolist(), cnt.tolist()

    def pin_rows(self, rows) -> None:
        """Hold rows against LRU recycling while an entry is in flight."""
        uniq, cnt = self._real_pin_counts(rows)
        if not uniq:
            return
        with self._lock:
            for r, c in zip(uniq, cnt):
                self._pins[r] = self._pins.get(r, 0) + c

    def unpin_rows(self, rows) -> None:
        uniq, cnt = self._real_pin_counts(rows)
        if not uniq:
            return
        with self._lock:
            for r, c in zip(uniq, cnt):
                n = self._pins.get(r, 0) - c
                if n <= 0:
                    self._pins.pop(r, None)
                else:
                    self._pins[r] = n

    def get_or_create_batch(self, items) -> List[int]:
        """Intern many ``(rule_slot, key_form, override_or_None)`` triples
        under ONE lock hold → aligned row list. The batch tier's analog of
        the native resource batch-intern: per-key lock traffic is what
        dominates host-side prep at 4k+ events/step."""
        out: List[int] = []
        with self._lock:
            for rule_slot, kf, override in items:
                key = (rule_slot, kf)
                row = self._map.get(key)
                if row is not None:
                    self._map.move_to_end(key)
                else:
                    row = (self._free.pop() if self._free
                           else self._evict_lru_locked())
                    self._map[key] = row
                    if override is not None:
                        self._pending_override.append((row, float(override)))
                out.append(row)
        return out

    def drain_updates(self) -> Tuple[List[int], List[Tuple[int, float]]]:
        """→ (evicted rows to invalidate, pending override writes)."""
        with self._lock:
            ev_, ov = self._evicted, self._pending_override
            self._evicted, self._pending_override = [], []
            return ev_, ov

    def live_pin_count(self) -> int:
        """Total counted pins held by in-flight entries (observability)."""
        with self._lock:
            return sum(self._pins.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


class NativeParamKeyRegistry:
    """:class:`ParamKeyRegistry` backed by the C++ table (VERDICT r3 #3:
    param-key intern was the config-4 host-prep hotspot — a Python
    dict/LRU loop per distinct key). Same observable behavior: row
    assignment order, LRU eviction skipping counted-pinned rows,
    evicted-row drain, override-on-create with cancel-on-evict (parity is
    pinned row-for-row in ``tests/test_param_key_native.py``).

    Key canonicalization mirrors the Python dict's equality semantics for
    the dominant types: ``bool``/integral ``float`` collapse onto ``int``
    (``True == 1``, ``1.0 == 1`` in a dict), int64-range ints take the
    13-byte binary form the C++ ``i64_get_or_create_batch`` fast path
    writes, strings are utf-8; anything else canonicalizes via ``repr``
    (exotic equal-but-different-repr keys may stay distinct — bounded
    divergence, same class as the reference's Object.equals vs our repr).
    """

    def __init__(self, capacity: int):
        import ctypes

        from sentinel_tpu.native import load_native
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._ct = ctypes
        self._lib = lib
        self._cap = capacity
        self._h = ctypes.c_void_p(lib.str_new(capacity))
        if not self._h:
            raise MemoryError("str_new failed")
        self._lock = threading.Lock()    # guards _evicted/_pending lists
        self._evicted: List[int] = []
        self._pending_override: List[Tuple[int, float]] = []
        self._drain_buf = np.empty(512, np.int32)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.str_free(h)

    @property
    def capacity(self) -> int:
        return self._cap

    # -- encoding ----------------------------------------------------------
    @staticmethod
    def _canon(kf):
        # numpy scalars hash/compare equal to their Python counterparts in
        # the dict-backed registry (np.int64(5) == 5), so they must
        # collapse to the same canonical form here too
        if isinstance(kf, (bool, np.bool_)):
            return int(kf)
        if isinstance(kf, np.integer):
            kf = int(kf)
        elif isinstance(kf, np.floating):
            kf = float(kf)
        if isinstance(kf, float) and kf.is_integer() \
                and -(2 ** 63) <= kf < 2 ** 63:
            return int(kf)
        if isinstance(kf, int) and not (-(2 ** 63) <= kf < 2 ** 63):
            return repr(kf)              # bigint → repr form
        return kf

    def _encode(self, slot: int, kf) -> bytes:
        import struct
        kf = self._canon(kf)
        if isinstance(kf, int):
            return struct.pack("<i", slot) + b"i" + struct.pack("<q", kf)
        if isinstance(kf, str):
            return struct.pack("<i", slot) + b"s" + kf.encode("utf-8")
        if isinstance(kf, float):
            return struct.pack("<i", slot) + b"f" + struct.pack("<d", kf)
        return struct.pack("<i", slot) + b"r" + repr(kf).encode("utf-8")

    # -- native plumbing ---------------------------------------------------
    def _ptr(self, arr, typ):
        return arr.ctypes.data_as(self._ct.POINTER(typ))

    def _drain_native_locked(self) -> None:
        """Pull freshly evicted rows out of the C++ queue and cancel any
        queued override targeting them — the Python registry cancels AT
        eviction; draining immediately after every intern call restores
        that ordering exactly (batches are chunked at override
        boundaries)."""
        buf = self._drain_buf
        while True:
            n = self._lib.str_drain(self._h, self._ptr(buf, self._ct.c_int32),
                                    buf.shape[0])
            if n <= 0:
                break
            rows = buf[:n].tolist()
            self._evicted.extend(rows)
            if self._pending_override:
                rs = set(rows)
                self._pending_override = [
                    (r, v) for r, v in self._pending_override
                    if r not in rs]
            if n < buf.shape[0]:
                break

    def _raise_if_full(self, rows: np.ndarray) -> None:
        if (rows == -2).any():
            raise RuntimeError(
                "all hot-param key rows are pinned by live entries; "
                "raise param_table_slots")

    def _intern_encoded_locked(self, encoded: List[bytes],
                               overrides) -> np.ndarray:
        n = len(encoded)
        offsets = np.zeros(n + 1, np.int32)
        np.cumsum([len(b) for b in encoded], out=offsets[1:])
        data = b"".join(encoded)
        out = np.empty(n, np.int32)
        created = np.empty(n, np.uint8)
        self._lib.str_get_or_create_batch2(
            self._h, data, self._ptr(offsets, self._ct.c_int32), n,
            self._ptr(out, self._ct.c_int32),
            self._ptr(created, self._ct.c_uint8))
        self._drain_native_locked()
        self._raise_if_full(out)
        if overrides is not None:
            for i, ov in overrides:
                if ov is not None and created[i]:
                    self._pending_override.append((int(out[i]), float(ov)))
        return out

    # -- ParamKeyRegistry interface ---------------------------------------
    def get_or_create(self, rule_slot: int, value, override=None) -> int:
        with self._lock:
            out = self._intern_encoded_locked(
                [self._encode(rule_slot, value)],
                [(0, override)] if override is not None else None)
            return int(out[0])

    def get_or_create_batch(self, items) -> List[int]:
        out: List[int] = []
        chunk: List[bytes] = []
        with self._lock:
            for rule_slot, kf, override in items:
                if override is None:
                    chunk.append(self._encode(rule_slot, kf))
                    continue
                # override items chunk-flush so cancel-on-evict ordering
                # matches the Python registry call-for-call
                if chunk:
                    out.extend(self._intern_encoded_locked(chunk, None)
                               .tolist())
                    chunk = []
                one = self._intern_encoded_locked(
                    [self._encode(rule_slot, kf)], [(0, override)])
                out.append(int(one[0]))
            if chunk:
                out.extend(self._intern_encoded_locked(chunk, None)
                           .tolist())
        return out

    def get_or_create_int_batch(self, packed: np.ndarray) -> np.ndarray:
        """Fast path for the vector resolution tier: ``packed`` is the
        int64 combine-key ``slot * 2**32 + (value + 2**31)`` — key bytes
        are produced in C++, one FFI call for the whole distinct set."""
        packed = np.ascontiguousarray(packed, np.int64)
        n = packed.shape[0]
        out = np.empty(n, np.int32)
        created = np.empty(n, np.uint8)
        with self._lock:
            self._lib.i64_get_or_create_batch(
                self._h, self._ptr(packed, self._ct.c_int64), n,
                self._ptr(out, self._ct.c_int32),
                self._ptr(created, self._ct.c_uint8))
            self._drain_native_locked()
            self._raise_if_full(out)
        return out

    def pin_rows(self, rows) -> None:
        arr = np.ascontiguousarray(np.asarray(rows, np.int32).ravel())
        arr = arr[(arr >= 0) & (arr < self._cap)]
        if arr.size:
            arr = np.ascontiguousarray(arr)
            self._lib.str_pin_rows(self._h,
                                   self._ptr(arr, self._ct.c_int32),
                                   arr.shape[0])

    def unpin_rows(self, rows) -> None:
        arr = np.ascontiguousarray(np.asarray(rows, np.int32).ravel())
        arr = arr[(arr >= 0) & (arr < self._cap)]
        if arr.size:
            arr = np.ascontiguousarray(arr)
            self._lib.str_unpin_rows(self._h,
                                     self._ptr(arr, self._ct.c_int32),
                                     arr.shape[0])

    def drain_updates(self) -> Tuple[List[int], List[Tuple[int, float]]]:
        with self._lock:
            self._drain_native_locked()
            ev_, ov = self._evicted, self._pending_override
            self._evicted, self._pending_override = [], []
            return ev_, ov

    def live_pin_count(self) -> int:
        """Total counted pins held by in-flight entries (observability)."""
        return int(self._lib.str_pin_total(self._h))

    def __len__(self) -> int:
        return int(self._lib.str_len(self._h))


def make_param_key_registry(capacity: int):
    """The native table when buildable, else the Python registry —
    identical semantics either way (``SENTINEL_TPU_NATIVE=0`` forces
    Python, same switch as the resource registry)."""
    try:
        from sentinel_tpu.native import native_available
        if native_available():
            return NativeParamKeyRegistry(capacity)
    except Exception:
        pass
    return ParamKeyRegistry(capacity)


_PIN_NOOP = 2 ** 31 - 1       # >= any registry capacity → pin/unpin no-op


def thread_key_rows(compiled: CompiledParamRules, pair_rules: np.ndarray,
                    pair_keys: np.ndarray) -> np.ndarray:
    """Key rows of THREAD-grade pairs only; others → sentinel (skipped by
    pin/unpin). Only THREAD-grade pairs need pinning: their exit-side
    decrement must hit the same occupant, while QPS state is entry-only and
    survives recycling as a bounded reset."""
    keys_flat = np.asarray(pair_keys).reshape(-1)
    mask = compiled.thread_slot_mask
    nrules = len(compiled.rules)
    if nrules == 0 or mask is None or not mask.any():
        return np.full(keys_flat.shape, _PIN_NOOP, keys_flat.dtype)
    rj = np.asarray(pair_rules).reshape(-1)
    valid = (rj >= 0) & (rj < nrules)
    is_thread = valid & mask[np.where(valid, rj, 0)]
    return np.where(is_thread, keys_flat,
                    keys_flat.dtype.type(_PIN_NOOP))


def resolve_pairs(compiled: CompiledParamRules, keys: ParamKeyRegistry,
                  row: int, args: Sequence[Any],
                  pairs_per_event: int) -> Tuple[np.ndarray, np.ndarray]:
    """Map one event's positional args to (rule_slot, key_row) pairs.

    Implements ``ParamFlowSlot.applyRealParamIdx`` (negative index from tail,
    out-of-range passes), ``ParamFlowArgument.paramFlowKey`` resolution, null
    pass-through, and collection/array expansion (every element checked).
    Overflow beyond ``pairs_per_event`` raises — a silent drop would silently
    stop checking, the reference failure mode this build rejects.
    """
    np_sentinel = compiled.table.active.shape[0] - 1
    pk_sentinel = keys.capacity
    pr = np.full(pairs_per_event, np_sentinel, np.int32)
    pk = np.full(pairs_per_event, pk_sentinel, np.int32)
    fills = 0
    entries = compiled.by_row.get(row)
    if not entries:
        return pr, pk
    n = len(args)
    for slot_j, idx, hot in entries:
        if idx < 0:
            idx = n + idx if -idx <= n else -idx
        if idx >= n:
            continue
        value = args[idx]
        if value is None:
            continue
        values = (list(value) if isinstance(value, (list, tuple, set, frozenset))
                  else [value])
        for v in values:
            if v is None:
                continue
            if fills >= pairs_per_event:
                raise ValueError(
                    f"event needs more than {pairs_per_event} param checks; "
                    f"raise param_pairs_per_event")
            kf = _key_form(v)
            ov = hot.get(kf)
            pr[fills] = slot_j
            pk[fills] = keys.get_or_create(slot_j, kf, override=ov)
            fills += 1
    return pr, pk


def _resolve_pairs_vector(compiled: CompiledParamRules,
                          keys: ParamKeyRegistry, rows, args_list,
                          pr: np.ndarray, pk: np.ndarray):
    """Fully vectorized pair resolution for the dominant serving shape:
    one rule per resource (non-negative index, no per-item overrides —
    guaranteed by ``vector_meta``) and integer args of uniform arity.
    Deduplicates (slot, value) via ``np.unique`` so the host dict work is
    one intern per DISTINCT key, not per event. → (pr, pk) filled, or None
    to fall back to the general loop (never a wrong answer — any shape
    this path can't prove safe falls through)."""
    try:
        arr = np.asarray(args_list)
    except (ValueError, TypeError):
        return None
    if arr.ndim != 2 or arr.dtype.kind not in "iu" or arr.shape[1] == 0:
        return None
    if arr.dtype.kind == "u" and arr.dtype.itemsize == 8:
        return None                      # uint64 may wrap in the int64 cast
    n = len(pr)
    row_slot, row_idx = compiled.vector_meta
    rows_arr = np.asarray(rows, np.int64)
    clipped = np.minimum(rows_arr, row_slot.shape[0] - 1)
    in_range = rows_arr < row_slot.shape[0]
    slots = np.where(in_range, row_slot[clipped], -1)
    idxs = np.where(in_range, row_idx[clipped], 0)
    valid = (slots >= 0) & (idxs < arr.shape[1])
    if not valid.any():
        return pr, pk
    vals = arr[np.arange(n), np.where(valid, idxs, 0)].astype(np.int64)
    vv = vals[valid]
    # direct comparisons, NOT np.abs: abs(int64.min) overflows negative
    if (vv >= 2 ** 31).any() or (vv <= -(2 ** 31)).any():
        return None                      # combine-key would overflow
    # pack (slot, value) into one int64 so np.unique runs on a flat array
    comb = slots.astype(np.int64) * (2 ** 32) + (vals + 2 ** 31)
    uniq, inv = np.unique(comb[valid], return_inverse=True)
    goc_int = getattr(keys, "get_or_create_int_batch", None)
    if goc_int is not None:
        # native table: the packed keys go straight through one FFI call
        # (no per-key Python tuples/dict ops)
        rows_out = goc_int(uniq)
    else:
        u_slot = (uniq // (2 ** 32)).tolist()
        u_val = (uniq % (2 ** 32) - 2 ** 31).tolist()
        rows_out = np.asarray(keys.get_or_create_batch(
            [(s, v, None) for s, v in zip(u_slot, u_val)]), np.int32)
    vi = np.nonzero(valid)[0]
    pr[vi, 0] = slots[valid].astype(np.int32)
    pk[vi, 0] = rows_out[inv]
    return pr, pk


def resolve_pairs_many(compiled: CompiledParamRules, keys: ParamKeyRegistry,
                       rows: Sequence[int], args_list: Sequence[Sequence[Any]],
                       pairs_per_event: int) -> Tuple[np.ndarray, np.ndarray]:
    """Batch form of :func:`resolve_pairs`: resolve every event's pairs with
    ONE registry lock hold (``get_or_create_batch``) instead of a lock per
    key. → ``(param_rules [n, PV], param_keys [n, PV])``."""
    n_events = len(rows)
    np_sentinel = compiled.table.active.shape[0] - 1
    pk_sentinel = keys.capacity
    pr = np.full((n_events, pairs_per_event), np_sentinel, np.int32)
    pk = np.full((n_events, pairs_per_event), pk_sentinel, np.int32)
    if compiled.vector_meta is not None:
        out = _resolve_pairs_vector(compiled, keys, rows, args_list, pr, pk)
        if out is not None:
            return out
    # first pass: collect (event, fill, slot) with a key-form DEDUPED intern
    # list — a Zipf-skewed 4k-event batch touches far fewer distinct keys
    # than events, so interning once per distinct (slot, key) pays for the
    # small host-side dict. Locals bound for the hot loop.
    by_row = compiled.by_row
    by_row_get = by_row.get
    uniq_pos: Dict[Tuple[int, Any], int] = {}
    uniq_items: List[Tuple[int, Any, Optional[int]]] = []
    want_i: List[int] = []
    want_f: List[int] = []
    want_slot: List[int] = []
    want_u: List[int] = []
    rows_list = (rows.tolist() if isinstance(rows, np.ndarray)
                 else [int(r) for r in rows])
    for i, (row, args) in enumerate(zip(rows_list, args_list)):
        if args is None or len(args) == 0:   # len(): ndarray rows are valid
            continue
        entries = by_row_get(row)
        if not entries:
            continue
        n = len(args)
        fills = 0
        for slot_j, idx, hot in entries:
            if idx < 0:
                idx = n + idx if -idx <= n else -idx
            if idx >= n:
                continue
            value = args[idx]
            if value is None:
                continue
            tv = type(value)
            if tv is int or tv is str:        # dominant scalar fast path
                values = (value,)
            elif isinstance(value, (list, tuple, set, frozenset)):
                values = value
            else:
                values = (value,)
            for v in values:
                if v is None:
                    continue
                if fills >= pairs_per_event:
                    raise ValueError(
                        f"event needs more than {pairs_per_event} param "
                        f"checks; raise param_pairs_per_event")
                tv2 = type(v)
                kf = v if (tv2 is int or tv2 is str) else _key_form(v)
                ukey = (slot_j, kf)
                u = uniq_pos.get(ukey)
                if u is None:
                    u = uniq_pos[ukey] = len(uniq_items)
                    uniq_items.append(
                        (slot_j, kf, hot.get(kf) if hot else None))
                want_i.append(i)
                want_f.append(fills)
                want_slot.append(slot_j)
                want_u.append(u)
                fills += 1
    if not uniq_items:
        return pr, pk
    rows_out = np.asarray(keys.get_or_create_batch(uniq_items), np.int32)
    ii = np.asarray(want_i, np.int64)
    ff = np.asarray(want_f, np.int64)
    pr[ii, ff] = np.asarray(want_slot, np.int32)
    pk[ii, ff] = rows_out[np.asarray(want_u, np.int64)]
    return pr, pk


# ---------------------------------------------------------------------------
# Device-side check
# ---------------------------------------------------------------------------

def param_check(
    table: ParamRuleTable,
    dyn: ParamDynState,
    pair_rules: jnp.ndarray,     # int32[B, PV] table slot, NP = none
    pair_keys: jnp.ndarray,      # int32[B, PV] key row, PK = none
    acquire: jnp.ndarray,        # int32[B]
    valid: jnp.ndarray,          # bool[B] — events still live in the chain
    rel_now_ms: jnp.ndarray,     # int32 scalar
) -> Tuple[ParamDynState, jnp.ndarray, jnp.ndarray]:
    """→ (dyn', allow bool[B], wait_ms int32[B]).

    One segmented scan over all (event, pair) applications; each key row is a
    segment so in-batch requests on the same hot key consume sequentially.
    """
    B, PV = pair_rules.shape
    NP = table.active.shape[0] - 1
    PK = dyn.tokens.shape[0] - 1

    rj = pair_rules.reshape(-1)
    kj = pair_keys.reshape(-1)
    valid_p = jnp.repeat(valid, PV) & (rj != NP) & (kj < PK) & table.active[rj]
    rj = jnp.where(valid_p, rj, NP)
    kj = jnp.where(valid_p, kj, PK)
    acq_p = jnp.where(valid_p, jnp.repeat(acquire, PV), 0).astype(jnp.float32)

    # threshold: per-item override beats rule count (parsedHotItems)
    ov = dyn.override[kj]
    threshold = jnp.where(ov >= 0.0, ov, table.count[rj])
    max_count = threshold + table.burst[rj]
    duration = jnp.maximum(table.duration_ms[rj], 1).astype(jnp.float32)

    # --- segments: one per key row (key rows are unique per (rule, value)) ---
    order = seg.sort_by_keys(kj)
    rj_s = rj[order]
    kj_s = kj[order]
    acq_s = acq_p[order]
    valid_s = valid_p[order]
    starts = seg.segment_starts(kj_s, jnp.zeros_like(kj_s))
    leader = seg.segment_leader_index(starts)

    thr_s = threshold[order]
    maxc_s = max_count[order]
    dur_s = duration[order]
    grade_s = table.grade[rj_s]
    behavior_s = table.behavior[rj_s]

    # --- QPS default: leader refill, then greedy in-segment consumption ---
    last_fill = dyn.last_fill_ms[kj_s]
    never = last_fill == _NEVER
    pass_time = (rel_now_ms - last_fill).astype(jnp.float32)
    refill = pass_time > dur_s
    to_add = jnp.floor(pass_time * thr_s / dur_s)
    t0 = jnp.where(never, maxc_s,
                   jnp.where(refill,
                             jnp.minimum(dyn.tokens[kj_s] + to_add, maxc_s),
                             dyn.tokens[kj_s]))
    t0 = seg.segment_broadcast_first(t0, leader)
    qps_pass = seg.greedy_admit(jnp.zeros_like(acq_s), acq_s, t0, starts, leader)
    qps_pass = qps_pass & (thr_s > 0.0) & (acq_s <= maxc_s)

    # --- QPS rate limiter: per-key paced queue ---
    cost_s = jnp.round(1000.0 * acq_s * dur_s / 1000.0
                       / jnp.maximum(thr_s, 1e-9)).astype(jnp.int32)
    c_first = seg.segment_broadcast_first(cost_s, leader)
    L0 = dyn.latest_passed_ms[kj_s]
    due = (L0 == _NEVER) | ((L0 + c_first - rel_now_ms) <= 0)
    base_time = jnp.where(due, rel_now_ms - c_first, L0)
    # a rejected request consumes no pacing budget (its CAS never lands in
    # the reference) — fixed-point like greedy_admit: drop rejected costs,
    # recompute the prefix; exact after one refinement for the dominant
    # admit-prefix/deny-suffix shape, bounded over-spacing otherwise
    rl_pass = jnp.ones_like(starts)
    maxq_s = table.max_queue_ms[rj_s]
    for _ in range(3):
        # exclusive prefix over ADMITTED earlier costs + own cost always
        excl_cost, _ = seg.segment_prefix_sum(
            jnp.where(rl_pass, cost_s, 0), starts, leader)
        latest_s = base_time + excl_cost + cost_s
        wait_s = jnp.maximum(latest_s - rel_now_ms, 0)
        # strict '<' on maxQueueingTimeMs (default 0 ⇒ only zero-wait passes)
        rl_pass = ((wait_s <= 0) | (wait_s < maxq_s)) & (thr_s > 0.0)

    # --- THREAD grade: per-key concurrency, +1 each regardless of acquire ---
    ones = jnp.where(valid_s, 1.0, 0.0)
    thread_pass = seg.greedy_admit(dyn.threads[kj_s].astype(jnp.float32),
                                   ones, thr_s, starts, leader)

    is_rl = (grade_s == GRADE_QPS) & (behavior_s == BEHAVIOR_RATE_LIMITER)
    is_qps = (grade_s == GRADE_QPS) & ~is_rl
    pair_pass_s = jnp.where(is_qps, qps_pass,
                            jnp.where(is_rl, rl_pass, thread_pass))
    pair_pass_s = pair_pass_s | ~valid_s
    pair_wait_s = jnp.where(is_rl & pair_pass_s & valid_s, wait_s, 0)

    # --- back to events: every pair must pass ---
    pair_pass = seg.unsort(order, pair_pass_s.astype(jnp.int32)).astype(jnp.bool_)
    pair_wait = seg.unsort(order, pair_wait_s.astype(jnp.int32))
    allow = jnp.all(pair_pass.reshape(B, PV), axis=1)
    wait_ms = jnp.max(pair_wait.reshape(B, PV), axis=1).astype(jnp.int32)
    allow = allow | ~valid

    # --- state writeback (scatter at segment granularity) ---
    # Consumption is EVENT-level: a pair whose event is blocked by a sibling
    # pair consumes nothing (the reference's per-rule sequential check leaves
    # earlier rules' consumption in place on a later rule's failure — an
    # order-dependent artifact this build replaces with the same
    # blocked-consumes-nothing invariant the rest of the pipeline uses).
    event_ok_pair_s = jnp.repeat(allow & valid, PV)[order]
    live_qps = valid_s & is_qps
    consumed = jnp.where(live_qps & pair_pass_s & event_ok_pair_s, acq_s, 0.0)
    _, incl_consumed = seg.segment_prefix_sum(consumed, starts, leader)
    new_tokens = t0 - incl_consumed
    # last element of each key segment carries the final value. Dropped
    # writes target PK+1 (out of range → mode="drop" discards) rather
    # than the sentinel row PK, so the sentinel slot stays clean and the
    # scalar variant can be pinned bit-exact against this path.
    is_last = jnp.concatenate([starts[1:], jnp.ones((1,), jnp.bool_)])
    tok_target = jnp.where(is_last & live_qps, kj_s, PK + 1)
    tokens = dyn.tokens.at[tok_target].set(new_tokens, mode="drop")
    fill_target = jnp.where(is_last & live_qps & (never | refill), kj_s,
                            PK + 1)
    last_fill_new = dyn.last_fill_ms.at[fill_target].set(rel_now_ms, mode="drop")

    rl_latest = jnp.where(is_rl & pair_pass_s & valid_s & event_ok_pair_s,
                          latest_s, _NEVER)
    rl_target = jnp.where(is_rl & valid_s, kj_s, PK + 1)
    latest_passed = dyn.latest_passed_ms.at[rl_target].max(rl_latest, mode="drop")

    dyn = dyn._replace(tokens=tokens, last_fill_ms=last_fill_new,
                       latest_passed_ms=latest_passed)
    return dyn, allow, wait_ms


def param_check_scalar(
    table: ParamRuleTable,
    dyn: ParamDynState,
    pair_rules: jnp.ndarray,     # int32[B, PV] table slot, NP = none
    pair_keys: jnp.ndarray,      # int32[B, PV] key row, PK = none
    acquire: jnp.ndarray,        # int32[B] — HOST-VERIFIED uniform (>= 1)
    valid: jnp.ndarray,          # bool[B]
    rel_now_ms: jnp.ndarray,     # int32 scalar
) -> Tuple[ParamDynState, jnp.ndarray, jnp.ndarray]:
    """Scalar-path param check → (dyn', allow bool[B], wait_ms int32[B]).

    Bit-exact with :func:`param_check` under the uniform-acquire
    precondition the host verifies before selecting the scalar/fast flow
    variants (runtime.decide_raw): within a key segment every admission
    quantity — refilled bucket ``t0``, threshold, pacing cost — is a
    function of the KEY alone, so the greedy token consumption, the
    rate-limiter fixed point, and the THREAD-concurrency check all
    collapse to arrival-rank compares (the round-4/5 playbook —
    rules/flow.flow_check_scalar), replacing the key sort + prefix-sum
    machinery with ONE rank pass (:func:`ops.segments.ranks_by_key`) and
    elementwise math. Writebacks become scatters keyed directly by the
    key row (same final values: ``t0 - total_consumed``, refill stamp,
    pacing max — per-key constants either way).

    Reference parity: ParamFlowChecker.java:122-220 (token bucket +
    burst), rate-limiter mode (cost per element, strict '<' on
    maxQueueingTimeMs), THREAD mode per-key concurrency.
    """
    B, PV = pair_rules.shape
    NP = table.active.shape[0] - 1
    PK = dyn.tokens.shape[0] - 1

    rj = pair_rules.reshape(-1)
    kj = pair_keys.reshape(-1)
    valid_p = jnp.repeat(valid, PV) & (rj != NP) & (kj < PK) & table.active[rj]
    rj = jnp.where(valid_p, rj, NP)
    kj = jnp.where(valid_p, kj, PK)
    # the uniform acquire (device-side derivation masked by valid, same
    # as flow_check_scalar)
    a = (jnp.float32(0)
         + jnp.max(jnp.where(valid, acquire, 0)).astype(jnp.float32))

    rank = seg.ranks_by_key(kj)
    rankf = rank.astype(jnp.float32)

    ov = dyn.override[kj]
    threshold = jnp.where(ov >= 0.0, ov, table.count[rj])
    maxc = threshold + table.burst[rj]
    duration = jnp.maximum(table.duration_ms[rj], 1).astype(jnp.float32)
    grade = table.grade[rj]
    behavior = table.behavior[rj]

    # --- QPS default: per-key refill, then rank-prefix consumption ---
    last_fill = dyn.last_fill_ms[kj]
    never = last_fill == _NEVER
    pass_time = (rel_now_ms - last_fill).astype(jnp.float32)
    refill = pass_time > duration
    to_add = jnp.floor(pass_time * threshold / duration)
    t0 = jnp.where(never, maxc,
                   jnp.where(refill,
                             jnp.minimum(dyn.tokens[kj] + to_add, maxc),
                             dyn.tokens[kj]))
    # same operand association as greedy_admit's `base + excl + amounts`
    # with base = 0 (f32-exact while counts stay under 2^24)
    qps_pass = (rankf * a) + a <= t0
    qps_pass = qps_pass & (threshold > 0.0) & (a <= maxc)

    # --- QPS rate limiter: per-key closed form (bounded rank budget) ---
    cost = jnp.round(1000.0 * a * duration / 1000.0
                     / jnp.maximum(threshold, 1e-9)).astype(jnp.int32)
    L0 = dyn.latest_passed_ms[kj]
    due = (L0 == _NEVER) | ((L0 + cost - rel_now_ms) <= 0)
    base_time = jnp.where(due, rel_now_ms - cost, L0)
    maxq = table.max_queue_ms[rj]
    # pass ⇔ wait <= 0 OR wait < maxq ⇔ wait < max(maxq, 1) — strict '<'
    # (maxQueueingTimeMs 0 admits only zero-wait, like the reference)
    maxq_eff = jnp.maximum(maxq, 1)
    rl_numer = rel_now_ms + maxq_eff - base_time
    # (k+1)*cost < numer ⇔ k < (numer-1)//cost — ints, overflow-free
    max_k = jnp.maximum((rl_numer - 1) // jnp.maximum(cost, 1), 0)
    wait0_ok = jnp.maximum(base_time - rel_now_ms, 0) < maxq_eff
    max_k = jnp.where(cost > 0, max_k,
                      jnp.where(wait0_ok, jnp.int32(2 ** 30), 0))
    rl_pass = (rank < max_k) & (threshold > 0.0)
    safe_rank = jnp.minimum(rank, max_k)
    wait_pair = jnp.maximum(
        base_time + (safe_rank + 1) * cost - rel_now_ms, 0)

    # --- THREAD grade: per-key concurrency, +1 regardless of acquire ---
    thread_pass = (dyn.threads[kj].astype(jnp.float32) + rankf) + 1.0 \
        <= threshold

    is_rl = (grade == GRADE_QPS) & (behavior == BEHAVIOR_RATE_LIMITER)
    is_qps = (grade == GRADE_QPS) & ~is_rl
    pair_pass = jnp.where(is_qps, qps_pass,
                          jnp.where(is_rl, rl_pass, thread_pass))
    pair_pass = pair_pass | ~valid_p
    pair_wait = jnp.where(is_rl & pair_pass & valid_p, wait_pair, 0)

    allow = jnp.all(pair_pass.reshape(B, PV), axis=1)
    wait_ms = jnp.max(pair_wait.reshape(B, PV), axis=1).astype(jnp.int32)
    allow = allow | ~valid

    # --- state writeback (scatters keyed by key row; PK+1 = dropped) ---
    event_ok_pair = jnp.repeat(allow & valid, PV)
    live_qps = valid_p & is_qps
    drop = PK + 1
    tgt_qps = jnp.where(live_qps, kj, drop)
    # refreshed bucket value, then subtract what this batch consumed
    tokens = dyn.tokens.at[tgt_qps].set(t0, mode="drop")
    consumed = jnp.where(live_qps & pair_pass & event_ok_pair, a, 0.0)
    tokens = tokens.at[tgt_qps].add(-consumed, mode="drop")
    fill_tgt = jnp.where(live_qps & (never | refill), kj, drop)
    last_fill_new = dyn.last_fill_ms.at[fill_tgt].set(rel_now_ms,
                                                      mode="drop")
    latest_pair = jnp.where(is_rl & rl_pass & valid_p & event_ok_pair,
                            base_time + (safe_rank + 1) * cost, _NEVER)
    rl_tgt = jnp.where(is_rl & valid_p, kj, drop)
    latest_passed = dyn.latest_passed_ms.at[rl_tgt].max(latest_pair,
                                                        mode="drop")

    dyn = dyn._replace(tokens=tokens, last_fill_ms=last_fill_new,
                       latest_passed_ms=latest_passed)
    return dyn, allow, wait_ms


def param_thread_update(
    table: ParamRuleTable,
    dyn: ParamDynState,
    pair_rules: jnp.ndarray,     # int32[B, PV]
    pair_keys: jnp.ndarray,      # int32[B, PV]
    counted: jnp.ndarray,        # bool[B] — events whose pairs adjust threads
    delta: int,
) -> ParamDynState:
    """±1 per-key concurrency for THREAD-grade pairs (the reference's
    ``ParamFlowStatisticEntryCallback`` / ``ExitCallback`` thread bookkeeping,
    applied post-decision for passed entries and on exit)."""
    NP = table.active.shape[0] - 1
    PK = dyn.tokens.shape[0] - 1
    PV = pair_rules.shape[1]
    rj = pair_rules.reshape(-1)
    kj = pair_keys.reshape(-1)
    live = jnp.repeat(counted, PV) & (rj != NP) & (kj < PK)
    live = live & (table.grade[rj] == GRADE_THREAD)
    target = jnp.where(live, kj, PK)
    threads = dyn.threads.at[target].add(jnp.where(live, delta, 0), mode="drop")
    if delta < 0:
        threads = jnp.maximum(threads, 0)
    return dyn._replace(threads=threads)


def invalidate_param_keys(dyn: ParamDynState, rows: jnp.ndarray) -> ParamDynState:
    """Reset recycled key rows (registry-eviction hygiene)."""
    return ParamDynState(
        tokens=dyn.tokens.at[rows].set(0.0, mode="drop"),
        last_fill_ms=dyn.last_fill_ms.at[rows].set(_NEVER, mode="drop"),
        latest_passed_ms=dyn.latest_passed_ms.at[rows].set(_NEVER, mode="drop"),
        threads=dyn.threads.at[rows].set(0, mode="drop"),
        override=dyn.override.at[rows].set(-1.0, mode="drop"),
    )


def apply_overrides(dyn: ParamDynState, rows: jnp.ndarray,
                    values: jnp.ndarray) -> ParamDynState:
    """Flush pending per-item threshold writes (rows padded with PK)."""
    return dyn._replace(override=dyn.override.at[rows].set(values, mode="drop"))
