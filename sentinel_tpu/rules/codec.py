"""Rule ⇄ JSON codecs in the reference wire format.

Field names match the fastjson serialization of the reference's rule beans
(``FlowRule.java``, ``DegradeRule.java``, ``SystemRule.java``,
``AuthorityRule.java``, ``ParamFlowRule.java`` + ``ParamFlowItem``), i.e. the
format the Sentinel dashboard pushes via ``setRules`` and datasources store —
so rule files and dashboard payloads are interchangeable between the
reference and this framework.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Sequence

from sentinel_tpu.rules.authority import AuthorityRule
from sentinel_tpu.rules.degrade import DegradeRule
from sentinel_tpu.rules.flow import FlowRule
from sentinel_tpu.rules.param_flow import ParamFlowItem, ParamFlowRule
from sentinel_tpu.rules.system import SystemRule


def flow_rule_to_dict(r: FlowRule) -> Dict[str, Any]:
    d: Dict[str, Any] = {
        "resource": r.resource, "limitApp": r.limit_app, "grade": r.grade,
        "count": r.count, "strategy": r.strategy,
        "refResource": r.ref_resource, "controlBehavior": r.control_behavior,
        "warmUpPeriodSec": r.warm_up_period_sec,
        "maxQueueingTimeMs": r.max_queueing_time_ms,
        "clusterMode": r.cluster_mode,
    }
    if r.cluster_mode:
        d["clusterConfig"] = {
            "flowId": r.cluster_flow_id,
            "thresholdType": r.cluster_threshold_type,
            "fallbackToLocalWhenFail": r.cluster_fallback_to_local,
        }
    return d


def flow_rule_from_dict(d: Dict[str, Any]) -> FlowRule:
    cc = d.get("clusterConfig") or {}
    return FlowRule(
        resource=d["resource"],
        count=float(d.get("count", 0.0)),
        grade=int(d.get("grade", 1)),
        limit_app=d.get("limitApp") or "default",
        strategy=int(d.get("strategy", 0)),
        ref_resource=d.get("refResource") or "",
        control_behavior=int(d.get("controlBehavior", 0)),
        warm_up_period_sec=int(d.get("warmUpPeriodSec", 10)),
        max_queueing_time_ms=int(d.get("maxQueueingTimeMs", 500)),
        cluster_mode=bool(d.get("clusterMode", False)),
        cluster_flow_id=int(cc.get("flowId", 0)),
        cluster_threshold_type=int(cc.get("thresholdType", 0)),
        cluster_fallback_to_local=bool(cc.get("fallbackToLocalWhenFail", True)),
    )


def degrade_rule_to_dict(r: DegradeRule) -> Dict[str, Any]:
    return {
        "resource": r.resource, "grade": r.grade, "count": r.count,
        "timeWindow": r.time_window, "minRequestAmount": r.min_request_amount,
        "statIntervalMs": r.stat_interval_ms,
        "slowRatioThreshold": r.slow_ratio_threshold,
    }


def degrade_rule_from_dict(d: Dict[str, Any]) -> DegradeRule:
    return DegradeRule(
        resource=d["resource"], grade=int(d.get("grade", 0)),
        count=float(d.get("count", 0.0)),
        time_window=int(d.get("timeWindow", 0)),
        min_request_amount=int(d.get("minRequestAmount", 5)),
        stat_interval_ms=int(d.get("statIntervalMs", 1000)),
        slow_ratio_threshold=float(d.get("slowRatioThreshold", 1.0)),
    )


def system_rule_to_dict(r: SystemRule) -> Dict[str, Any]:
    return {
        "highestSystemLoad": r.highest_system_load,
        "highestCpuUsage": r.highest_cpu_usage,
        "qps": r.qps, "avgRt": r.avg_rt, "maxThread": r.max_thread,
    }


def system_rule_from_dict(d: Dict[str, Any]) -> SystemRule:
    return SystemRule(
        highest_system_load=float(d.get("highestSystemLoad", -1.0)),
        highest_cpu_usage=float(d.get("highestCpuUsage", -1.0)),
        qps=float(d.get("qps", -1.0)),
        avg_rt=float(d.get("avgRt", -1.0)),
        max_thread=float(d.get("maxThread", -1.0)),
    )


def authority_rule_to_dict(r: AuthorityRule) -> Dict[str, Any]:
    return {"resource": r.resource, "limitApp": r.limit_app,
            "strategy": r.strategy}


def authority_rule_from_dict(d: Dict[str, Any]) -> AuthorityRule:
    return AuthorityRule(
        resource=d["resource"], limit_app=d.get("limitApp") or "",
        strategy=int(d.get("strategy", 0)))


def param_flow_rule_to_dict(r: ParamFlowRule) -> Dict[str, Any]:
    d: Dict[str, Any] = {
        "resource": r.resource, "paramIdx": r.param_idx, "count": r.count,
        "grade": r.grade, "durationInSec": r.duration_in_sec,
        "burstCount": r.burst_count, "controlBehavior": r.control_behavior,
        "maxQueueingTimeMs": r.max_queueing_time_ms,
        "clusterMode": r.cluster_mode,
        "paramFlowItemList": [
            {"object": str(it.object), "count": it.count,
             "classType": it.class_type or type(it.object).__name__}
            for it in r.param_flow_item_list],
    }
    if r.cluster_mode:
        d["clusterConfig"] = {"flowId": r.cluster_flow_id}
    return d


_ITEM_TYPES = {"int": int, "Integer": int, "long": int, "Long": int,
               "float": float, "Float": float, "double": float,
               "Double": float, "bool": bool, "boolean": bool,
               "Boolean": bool}


def _parse_item_object(obj: Any, class_type: str) -> Any:
    if not isinstance(obj, str):
        return obj
    conv = _ITEM_TYPES.get(class_type)
    if conv is bool:
        return obj in ("true", "True")
    if conv is not None:
        try:
            return conv(obj)
        except ValueError:
            return obj
    return obj


def param_flow_rule_from_dict(d: Dict[str, Any]) -> ParamFlowRule:
    cc = d.get("clusterConfig") or {}
    items = [ParamFlowItem(
        object=_parse_item_object(it.get("object"), it.get("classType", "")),
        count=int(it.get("count", 0)),
        class_type=it.get("classType", ""))
        for it in d.get("paramFlowItemList") or []]
    return ParamFlowRule(
        resource=d["resource"], param_idx=int(d.get("paramIdx", 0)),
        count=float(d.get("count", 0.0)), grade=int(d.get("grade", 1)),
        duration_in_sec=int(d.get("durationInSec", 1)),
        burst_count=int(d.get("burstCount", 0)),
        control_behavior=int(d.get("controlBehavior", 0)),
        max_queueing_time_ms=int(d.get("maxQueueingTimeMs", 0)),
        param_flow_item_list=items,
        cluster_mode=bool(d.get("clusterMode", False)),
        cluster_flow_id=int(cc.get("flowId", 0)),
    )


_TO = {"flow": flow_rule_to_dict, "degrade": degrade_rule_to_dict,
       "system": system_rule_to_dict, "authority": authority_rule_to_dict,
       "paramFlow": param_flow_rule_to_dict}
_FROM = {"flow": flow_rule_from_dict, "degrade": degrade_rule_from_dict,
         "system": system_rule_from_dict, "authority": authority_rule_from_dict,
         "paramFlow": param_flow_rule_from_dict}

RULE_TYPES = tuple(_TO)


def rules_to_json(rule_type: str, rules: Sequence[Any]) -> str:
    return json.dumps([_TO[rule_type](r) for r in rules])


def rules_from_json(rule_type: str, text: str) -> List[Any]:
    data = json.loads(text) if text.strip() else []
    return [_FROM[rule_type](d) for d in data]
