"""System adaptive protection (SystemSlot).

Reference (``sentinel-core/.../slots/system/SystemRuleManager.java``):
``checkSystem`` gates only ``EntryType.IN`` traffic against *global* inbound
aggregates — total QPS, total thread count, average RT, system load1 (with the
BBR-style escape hatch: when load is high, still admit while
``curThread <= maxSuccessQps × minRt / 1000``), and CPU usage. Thresholds are
the minimum over all loaded rules (volatile fields rebuilt on rule update);
load/CPU come from a 1 s ``SystemStatusListener`` poll of the OS.

TPU-native shape: thresholds fold host-side into one scalar struct at rule
load; the check is a handful of scalar compares broadcast over the batch's IN
events, with greedy in-batch prefix for the QPS/thread gates. Load and CPU are
host-sampled floats fed into the step (device code never syscalls).

The global inbound aggregate is row 0 of the main tables (reference
``Constants.ENTRY_NODE``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp

from sentinel_tpu.core.registry import ENTRY_NODE_ROW
from sentinel_tpu.stats import events as ev
from sentinel_tpu.stats.window import (
    WindowSpec, WindowState, min_rt_rows, rt_totals, valid_mask,
)


@dataclasses.dataclass
class SystemRule:
    """Reference ``SystemRule.java``: any subset of gates; -1 = unset."""

    highest_system_load: float = -1.0
    highest_cpu_usage: float = -1.0
    qps: float = -1.0
    avg_rt: float = -1.0          # ms
    max_thread: float = -1.0


_UNSET = float(2 ** 31)


class SystemThresholds(NamedTuple):
    """Folded minima as a tiny device array pack (all float32 scalars)."""

    max_load: jnp.ndarray
    max_cpu: jnp.ndarray
    max_qps: jnp.ndarray
    max_rt: jnp.ndarray
    max_thread: jnp.ndarray


def compile_system_rules(rules: Sequence[SystemRule]) -> SystemThresholds:
    def fold(vals):
        vals = [v for v in vals if v >= 0.0]
        return min(vals) if vals else _UNSET

    load = fold([r.highest_system_load for r in rules])
    cpu = fold([r.highest_cpu_usage for r in rules])
    qps = fold([r.qps for r in rules])
    rt = fold([r.avg_rt for r in rules])
    thread = fold([r.max_thread for r in rules])
    return SystemThresholds(
        max_load=jnp.float32(load), max_cpu=jnp.float32(cpu),
        max_qps=jnp.float32(qps), max_rt=jnp.float32(rt),
        max_thread=jnp.float32(thread),
    )


def host_system_status() -> Tuple[float, float]:
    """(load1, cpu_usage∈[0,1]) — the ``SystemStatusListener`` analog.

    CPU usage is derived from /proc/stat deltas by the runtime's sampler;
    this fallback returns load only (cpu -1 = unknown) so the framework works
    on any POSIX host without psutil.
    """
    try:
        load1 = os.getloadavg()[0]
    except OSError:  # pragma: no cover
        load1 = -1.0
    return load1, -1.0


def system_check(
    thresholds: SystemThresholds,
    spec: WindowSpec,
    main_second: WindowState,
    main_threads: jnp.ndarray,
    is_in: jnp.ndarray,        # bool[B] — EntryType.IN events only are gated
    acquire: jnp.ndarray,      # int32[B]
    valid: jnp.ndarray,        # bool[B]
    now_idx_s: jnp.ndarray,
    load1: jnp.ndarray,        # float32 scalar (host-sampled)
    cpu_usage: jnp.ndarray,    # float32 scalar
    statistic_max_rt: int,
) -> jnp.ndarray:
    """→ allow bool[B] (False = SystemBlockException)."""
    row0 = jnp.array([ENTRY_NODE_ROW], jnp.int32)
    gated = is_in & valid

    entry = main_second.counters[ENTRY_NODE_ROW]                  # [Bk, E]
    live = valid_mask(spec, main_second.stamps[ENTRY_NODE_ROW][None, :], now_idx_s)[0]
    pass_1s = jnp.sum(jnp.where(live, entry[:, ev.PASS], 0)).astype(jnp.float32)
    succ_1s = jnp.sum(jnp.where(live, entry[:, ev.SUCCESS], 0)).astype(jnp.float32)
    rt_sum = jnp.sum(jnp.where(live, main_second.rt_sum[ENTRY_NODE_ROW], 0.0))
    avg_rt = jnp.where(succ_1s > 0, rt_sum / jnp.maximum(succ_1s, 1.0), 0.0)
    cur_thread = main_threads[ENTRY_NODE_ROW].astype(jnp.float32)
    min_rt = min_rt_rows(spec, main_second, row0, now_idx_s,
                         statistic_max_rt)[0].astype(jnp.float32)
    # maxSuccessQps (StatisticNode): max bucket success × buckets/sec
    per_sec = 1000.0 / spec.win_ms
    max_succ = jnp.max(jnp.where(live, entry[:, ev.SUCCESS], 0)).astype(jnp.float32)
    max_success_qps = max_succ * per_sec

    # greedy in-batch admission for the global QPS gate: a denied request
    # never increments ENTRY pass and so must not consume budget for batch
    # peers (reference counts pass post-decision) — fixed-point refinement,
    # exact for uniform acquire.
    acq = jnp.where(gated, acquire, 0).astype(jnp.float32)
    qps_ok = jnp.ones_like(gated)
    for _ in range(3):
        contrib = jnp.where(qps_ok, acq, 0.0)
        prefix = jnp.cumsum(contrib) - contrib
        qps_ok = pass_1s + prefix + acq <= thresholds.max_qps

    thread_ok = cur_thread <= thresholds.max_thread
    rt_ok = avg_rt <= thresholds.max_rt

    # BBR check (SystemRuleManager.checkBbr): applied when load exceeds the
    # threshold — still admit while concurrency is under the pipe capacity.
    bbr_ok = (cur_thread <= 1.0) | (cur_thread <= max_success_qps * min_rt / 1000.0)
    load_ok = (load1 <= thresholds.max_load) | bbr_ok
    cpu_ok = cpu_usage <= thresholds.max_cpu

    ok = qps_ok & thread_ok & rt_ok & load_ok & cpu_ok
    return ok | ~gated
