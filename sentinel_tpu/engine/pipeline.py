"""The decision pipeline: slot chain as one fused jitted step.

Reference architecture (``sentinel-core``, SURVEY §3.1): every entry walks
``NodeSelectorSlot → ClusterBuilderSlot → LogSlot → StatisticSlot →
AuthoritySlot → SystemSlot → [ParamFlowSlot] → FlowSlot → DegradeSlot``, where
``StatisticSlot`` fires the rule slots FIRST and records pass/block *after*
the decision returns (``StatisticSlot.java:54-131``) — statistics are
post-decision, and that ordering is preserved here.

TPU-native shape: the whole chain is two pure functions over dense state —

* :func:`decide_entries` — batch of entry events → verdicts + updated state;
* :func:`record_exits`  — batch of completions → updated state (RT/success/
  exception recording + circuit-breaker feed, ``StatisticSlot.exit`` +
  ``DegradeSlot.exit``).

Node-tree equivalents are *views* over rows (SURVEY §7 phase 1): the global
per-resource row is the ClusterNode, hashed (resource × origin) and
(resource × context) rows in the ``alt`` table are origin-/chain-DefaultNodes,
and row 0 aggregates all inbound traffic (ENTRY_NODE). Gating masks cascade
through the slots so an event blocked upstream never consumes downstream
quota (a blocked-by-authority request can't eat flow tokens or a breaker
probe), and blocked events don't record pass counts — decision-before-
statistics, like the reference.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from sentinel_tpu.core.errors import BlockReason
from sentinel_tpu.core.registry import ENTRY_NODE_ROW
from sentinel_tpu.rules import authority as auth_mod
from sentinel_tpu.rules import degrade as deg_mod
from sentinel_tpu.obs import resource_hist
from sentinel_tpu.rules import flow as flow_mod
from sentinel_tpu.rules import param_flow as pf_mod
from sentinel_tpu.rules import system as sys_mod
from sentinel_tpu.stats import events as ev
from sentinel_tpu.stats.window import (
    WindowSpec, WindowState, add_one_row, add_rows, add_rows_hist,
    add_rows_multi, add_rows_vec, extract_rows, hist_add_fits, init_window,
    invalidate_rows, refresh_all, refresh_rows, restore_rows,
)


@dataclasses.dataclass(frozen=True)
class EngineSpec:
    """Static engine geometry (hashable; closed over by the jitted steps)."""

    rows: int                 # R — main resource rows (row 0 = ENTRY_NODE)
    alt_rows: int             # RA — hashed (resource×origin/context) rows
    second: WindowSpec
    minute: Optional[WindowSpec]
    statistic_max_rt: int
    param_keys: int = 0       # PK — hot-key rows (0 = param flow disabled)
    param_pairs: int = 0      # PV — (rule, value) checks per event
    occupy_timeout_ms: int = 500   # OccupyTimeoutProperty default (0 = off)
    # HB — per-resource RT histogram buckets (obs/resource_hist.py);
    # 0 = table disabled: state.rt_hist is None and every consumer
    # compiles the feature away (round-20 bit-parity switch)
    hist_buckets: int = 0


class SentinelState(NamedTuple):
    """All mutable device state, one pytree."""

    second: WindowState           # [R]
    minute: WindowState           # [R] (rows=1 when minute disabled)
    alt_second: WindowState       # [RA]
    threads: jnp.ndarray          # int32[R]
    alt_threads: jnp.ndarray      # int32[RA]
    flow_dyn: flow_mod.FlowDynState
    breakers: deg_mod.BreakerState
    param_dyn: pf_mod.ParamDynState
    # per-registered-DeviceSlot pytree state slices (engine/slots.py),
    # positionally aligned with the custom_slots tuple the steps were
    # compiled with; () when no custom slots are registered
    custom: Tuple = ()
    # int32[R, HB] cumulative per-resource RT histogram (round 20) —
    # counts only grow (they ride tier demote/promote and geometry
    # changes) and reset on row invalidation. None ⇔ spec.hist_buckets
    # == 0, so the leaf's absence keeps old programs byte-identical.
    rt_hist: Optional[jnp.ndarray] = None


class RuleSet(NamedTuple):
    """All compiled rule tables; swapped atomically on rule reload."""

    flow_table: flow_mod.FlowRuleTable
    flow_idx: jnp.ndarray
    deg_table: deg_mod.DegradeRuleTable
    deg_idx: jnp.ndarray
    auth_table: auth_mod.AuthorityRuleTable
    auth_idx: jnp.ndarray
    sys_thresholds: sys_mod.SystemThresholds
    param_table: pf_mod.ParamRuleTable
    # concat(flow_idx, deg_idx) [R, Kf+Kd] — the scalar path gathers BOTH
    # slots' rule ids in ONE pass over the big row table (a 512k random
    # gather from a [1M]-row table costs ~6 ms on the v5 chip; two of
    # them were ~25% of the scalar step). None = gather separately.
    # ALWAYS build via with_joint() (or build_joint_np on the SAME numpy
    # arrays being shipped as flow_idx/deg_idx — the runtime's host-side
    # assembly) — the consumer splits at flow_idx.shape[1], so any other
    # hand-concatenated copy can silently desync.
    joint_idx: Optional[jnp.ndarray] = None

    def with_joint(self) -> "RuleSet":
        """→ self with ``joint_idx`` derived from the flow_idx/deg_idx
        THIS ruleset actually carries (desync-proof by construction)."""
        return self._replace(joint_idx=jnp.concatenate(
            [self.flow_idx, self.deg_idx], axis=1))

    @staticmethod
    def build_joint_np(flow_idx_np, deg_idx_np):
        """Host-side form of :meth:`with_joint` for callers that assemble
        the ruleset in numpy and device_put once (cold-start path): pass
        the EXACT arrays that become flow_idx/deg_idx."""
        import numpy as np
        return np.concatenate([flow_idx_np, deg_idx_np], axis=1)


class EntryBatch(NamedTuple):
    """Device-side entry events (padded to static size; padding: rows >= R,
    valid False)."""

    rows: jnp.ndarray           # int32[B]
    origin_ids: jnp.ndarray     # int32[B] (0 = none)
    origin_rows: jnp.ndarray    # int32[B] (>= RA = none)
    context_ids: jnp.ndarray    # int32[B]
    chain_rows: jnp.ndarray     # int32[B] (>= RA = none)
    acquire: jnp.ndarray        # int32[B]
    is_in: jnp.ndarray          # bool[B]
    prioritized: jnp.ndarray    # bool[B]
    valid: jnp.ndarray          # bool[B]
    param_rules: Optional[jnp.ndarray] = None   # int32[B, PV] (param slot off: None)
    param_keys: Optional[jnp.ndarray] = None    # int32[B, PV]
    # per-event bitmask over per-resource rule slots: bit k set = the
    # cluster-mode rule in slot k had its token request fail with
    # fallbackToLocalWhenFail, so exactly that rule checks LOCALLY
    # (per-rule FlowRuleChecker.fallbackToLocalOrPass); None = no fallback
    cluster_fallback: Optional[jnp.ndarray] = None   # int32[B]
    # False = don't count this event in the thread (concurrency) gauges:
    # host-leased admissions are never thread-counted (the lease pre-charge
    # batch and each leased exit both carry False, keeping the gauge
    # consistent). None = all True.
    count_thread: Optional[jnp.ndarray] = None       # bool[B]
    # False = a DENIAL of this event records no BLOCK stat: lease renewal
    # probes are speculative acquire=C requests — a denied probe isn't C
    # denied callers (the triggering caller re-decides per-event and
    # records its own block). None = all True.
    record_block: Optional[jnp.ndarray] = None       # bool[B]


class ExitBatch(NamedTuple):
    rows: jnp.ndarray           # int32[B]
    origin_rows: jnp.ndarray    # int32[B]
    chain_rows: jnp.ndarray     # int32[B]
    acquire: jnp.ndarray        # int32[B]
    rt_ms: jnp.ndarray          # int32[B]
    error: jnp.ndarray          # bool[B]
    is_in: jnp.ndarray          # bool[B]
    valid: jnp.ndarray          # bool[B]
    param_rules: Optional[jnp.ndarray] = None   # int32[B, PV]
    param_keys: Optional[jnp.ndarray] = None    # int32[B, PV]
    count_thread: Optional[jnp.ndarray] = None  # bool[B] (see EntryBatch)


class Verdicts(NamedTuple):
    allow: jnp.ndarray          # bool[B]
    reason: jnp.ndarray         # int8[B] (BlockReason codes)
    wait_ms: jnp.ndarray        # int32[B]
    sf_overflow: Optional[jnp.ndarray] = None   # int32 scalar — sort-free
    # claim-cascade overflow count this step (elements that took the
    # sorted fallback; feeds obs counter sortfree.bucket_overflow). None
    # when the step was built without the sortfree static.


def _init_state_traced(spec: EngineSpec, nf: int, nd: int) -> SentinelState:
    minute_rows = spec.rows if spec.minute else 1
    minute_spec = spec.minute or WindowSpec(1, 1000, track_rt=False)
    return SentinelState(
        second=init_window(spec.second, spec.rows),
        minute=init_window(minute_spec, minute_rows),
        alt_second=init_window(spec.second, spec.alt_rows),
        threads=jnp.zeros((spec.rows,), jnp.int32),
        alt_threads=jnp.zeros((spec.alt_rows,), jnp.int32),
        flow_dyn=flow_mod.init_flow_dyn(nf, spec.second.buckets, spec.rows),
        breakers=deg_mod.init_breaker_state(nd),
        param_dyn=pf_mod.init_param_dyn(spec.param_keys),
        rt_hist=(jnp.zeros((spec.rows, spec.hist_buckets), jnp.int32)
                 if spec.hist_buckets else None),
    )


@functools.lru_cache(maxsize=None)
def _init_state_jit(spec: EngineSpec, nf: int, nd: int):
    return jax.jit(functools.partial(_init_state_traced, spec, nf, nd))


def _init_state_np(spec: EngineSpec, nf: int, nd: int) -> SentinelState:
    """Numpy mirror of :func:`_init_state_traced` (bit-identical leaves —
    pinned by ``tests/test_pipeline.py::test_init_state_np_parity``)."""
    import numpy as np

    # python literals, NOT the module's device scalars (int(NEVER) would
    # be a blocking device readback — the RPC this function exists to
    # avoid); parity with the traced constants pinned by the test
    never = -(2 ** 30)
    i32max = np.iinfo(np.int32).max

    def win(wspec, rows):
        b_rt = wspec.buckets if wspec.track_rt else 0
        return WindowState(
            counters=np.zeros((rows, wspec.buckets, ev.NUM_EVENTS),
                              np.int32),
            stamps=np.full((rows, wspec.buckets), never, np.int32),
            rt_sum=np.zeros((rows, b_rt), np.float32),
            min_rt=np.full((rows, b_rt), i32max, np.int32))

    minute_rows = spec.rows if spec.minute else 1
    minute_spec = spec.minute or WindowSpec(1, 1000, track_rt=False)
    pk = spec.param_keys
    return SentinelState(
        second=win(spec.second, spec.rows),
        minute=win(minute_spec, minute_rows),
        alt_second=win(spec.second, spec.alt_rows),
        threads=np.zeros((spec.rows,), np.int32),
        alt_threads=np.zeros((spec.alt_rows,), np.int32),
        flow_dyn=flow_mod.FlowDynState(
            latest_passed_ms=np.full((nf + 1,), never, np.int32),
            stored_tokens=np.zeros((nf + 1,), np.float32),
            last_filled_sec=np.full((nf + 1,), never, np.int32),
            occupied_count=np.zeros(
                (spec.rows, spec.second.buckets + 1), np.float32),
            occupied_window=np.full(
                (spec.rows, spec.second.buckets + 1), never, np.int32)),
        breakers=deg_mod.BreakerState(
            state=np.zeros((nd + 1,), np.int32),
            next_retry_ms=np.full((nd + 1,), never, np.int32),
            win_stamp=np.full((nd + 1,), never, np.int32),
            bad=np.zeros((nd + 1,), np.int32),
            total=np.zeros((nd + 1,), np.int32)),
        param_dyn=pf_mod.ParamDynState(
            tokens=np.zeros((pk + 1,), np.float32),
            last_fill_ms=np.full((pk + 1,), never, np.int32),
            latest_passed_ms=np.full((pk + 1,), never, np.int32),
            threads=np.zeros((pk + 1,), np.int32),
            override=np.full((pk + 1,), -1.0, np.float32)),
        rt_hist=(np.zeros((spec.rows, spec.hist_buckets), np.int32)
                 if spec.hist_buckets else None),
    )


# above this, raw zero-transfers beat the fused fill program; below it,
# the one-program form wins (bench-scale 1M-row states would transfer
# ~90 MB). Measured on the tunneled v5: 25 MB state transfers in ~1.1 s
# vs ~3.1 s for the fused program's cached-executable load.
_TRANSFER_STATE_LIMIT_BYTES = 48 * 1024 * 1024


def init_state(spec: EngineSpec, nf: int, nd: int) -> SentinelState:
    """Initial device state — WITHOUT paying per-process program loads
    where possible.

    Eager construction dispatched ~17 tiny fill programs; each cached
    executable pays a program-load round-trip on a tunneled TPU (~0.12 s
    each, ~2 s of every warm start — the cold-start story in
    docs/OPERATIONS.md). Serving-sized states (≤ ~48 MB) are instead
    built host-side and device_put as ONE transfer (no XLA program at
    all, ~1.1 s for the default geometry); bigger states (the 1M-row
    bench scale) fall back to one fused fill program, jit-cached per
    geometry."""
    import math
    import os
    mode = os.environ.get("SENTINEL_INIT_MODE", "")
    # size from shapes alone — don't allocate ~90 MB of numpy zeros just
    # to discard them on the program path
    shapes = jax.eval_shape(
        functools.partial(_init_state_traced, spec, nf, nd))
    nbytes = sum(math.prod(leaf.shape) * leaf.dtype.itemsize
                 for leaf in jax.tree.leaves(shapes))
    if mode != "program" and (mode == "transfer"
                              or nbytes <= _TRANSFER_STATE_LIMIT_BYTES):
        return jax.device_put(_init_state_np(spec, nf, nd))
    return _init_state_jit(spec, nf, nd)()


def _stat_targets(spec: EngineSpec, rows, origin_rows, chain_rows, valid,
                  is_in):
    """Recording target rows shared by entry/block recorders: the event row
    + the global ENTRY row (IN only) in the main table, the origin + chain
    rows in the alt table; padding = one-past-the-end (dropped scatters)."""
    pad_r = jnp.int32(spec.rows)
    pad_a = jnp.int32(spec.alt_rows)
    main_rows = jnp.where(valid, rows, pad_r)
    entry_rows = jnp.where(valid & is_in, jnp.int32(ENTRY_NODE_ROW), pad_r)
    alt_o = jnp.where(valid, origin_rows, pad_a)
    alt_c = jnp.where(valid, chain_rows, pad_a)
    return (jnp.concatenate([main_rows, entry_rows]),
            jnp.concatenate([alt_o, alt_c]))


def decide_entries(
    spec: EngineSpec,
    rules: RuleSet,
    state: SentinelState,
    batch: EntryBatch,
    times: jnp.ndarray,          # int32[4]: idx_s, idx_m, rel_ms, in_win_ms
    sys_scalars: jnp.ndarray,    # float32[2]: load1, cpu_usage
    enable_occupy: bool = True,  # STATIC (see flow_check)
    custom_slots: Tuple = (),    # STATIC: registered DeviceSlots (slots.py)
    record_alt: bool = True,     # STATIC: False = batch carries no origin/
    # chain rows (host-verified all-padding) → the alt-table scatters and
    # the alt thread gauge compile away entirely; origin-less traffic is
    # the common case and those scatters are pure padding work there
    scalar_flow: bool = False,   # STATIC: HOST-VERIFIED preconditions
    # (no alt rows, uniform acquire >= 1, no prioritized events, no
    # cluster_fallback bits) → flow + degrade take the scalar admission
    # path: per-rule budgets, one rank sort, sort-free breaker probes
    # (see rules/flow.flow_check_scalar). Implies record_alt=False.
    # With enable_occupy=True the scalar checker folds LANDED occupy
    # bookings into the QPS base (occupy_base) — the batch still carries
    # no prioritized events, it only dispatches AROUND live bookings.
    fast_flow: bool = False,     # STATIC: HOST-VERIFIED preconditions
    # (uniform acquire >= 1, composite key fits int32) → the fast
    # GENERAL path: origins/alt rows/CHAIN/fallback bits all live,
    # admission via rank closed forms (rules/flow.flow_check_fast).
    # Mutually exclusive with scalar_flow. With enable_occupy=True the
    # occupy-capable variant runs (rules/flow.flow_check_fast_occupy):
    # prioritized events take the vectorized tryOccupyNext path.
    skip_auth: bool = False,     # STATIC: no authority rules loaded —
    # the whole slot (incl. its [B, Ka] gathers) compiles away
    skip_sys: bool = False,      # STATIC: no system thresholds set
    scalar_has_rl: bool = True,  # STATIC: ruleset contains rate-limiter
    # rules (scalar path only — gates the pacing-clock histogram scatter)
    skip_threads: bool = False,  # STATIC: nothing loaded READS the live-
    # concurrency gauges (no THREAD-grade flow rules, no system rules, no
    # THREAD-grade param rules — the only reference readers:
    # DefaultController.java:50-76 THREAD branch, SystemRuleManager
    # .checkSystem, ParamFlowChecker THREAD mode), so their maintenance
    # scatters are elided entirely. The gauges then read 0 (observability
    # trade documented in docs/OPERATIONS.md); loading a gauge-reading
    # rule flips the flag (retrace) and the gauge warms as pre-flip
    # entries exit (decrements clamp at 0).
    sortfree: bool = False,      # STATIC: every flow path groups segments
    # via the sort-free hash-bucketed scatter machinery (ops/sortfree.py)
    # instead of stable sorts — bit-exact by construction (claim overflow
    # falls back to the sorted branch under lax.cond). The verdicts then
    # carry sf_overflow (int32 scalar) for the runtime's
    # sortfree.bucket_overflow counter.
) -> Tuple[SentinelState, Verdicts]:
    """One device step: decide a batch, then record post-decision statistics.

    Time/system inputs arrive PACKED (one int32[4] + one float32[2]) so a
    step costs two host→device transfers, not six — on a tunneled TPU each
    per-call transfer is real latency on the hot path."""
    R = spec.rows
    RA = spec.alt_rows
    now_idx_s = times[0]
    now_idx_m = times[1]
    rel_now_ms = times[2]
    in_win_ms = times[3]
    load1 = sys_scalars[0]
    cpu_usage = sys_scalars[1]

    if scalar_flow:
        assert not record_alt, "scalar_flow implies record_alt=False"
    if fast_flow:
        assert not scalar_flow, "fast_flow is exclusive with scalar_flow"

    # ---- slot cascade (each gate only sees events still alive) ----
    live = batch.valid

    if skip_auth:
        auth_ok = jnp.ones_like(live)
    else:
        auth_ok = auth_mod.authority_check(
            rules.auth_table, rules.auth_idx, batch.rows, batch.origin_ids,
            live)
    live1 = live & auth_ok

    # unset thresholds fold to a huge sentinel, so the check is a no-op pass
    # when no system rules are loaded (no branch: avoids retracing); a host
    # that KNOWS no system rules exist passes skip_sys and the whole check
    # (its ENTRY-row window reads included) compiles away
    if skip_sys:
        sys_ok = jnp.ones_like(live1)
    else:
        sys_ok = sys_mod.system_check(
            rules.sys_thresholds, spec.second, state.second, state.threads,
            batch.is_in, batch.acquire, live1, now_idx_s, load1, cpu_usage,
            spec.statistic_max_rt)
    live2 = live1 & sys_ok

    # ParamFlowSlot sits between SystemSlot and FlowSlot (extension SPI slot
    # order, SURVEY §1). Static skip when the engine has no param geometry.
    param_dyn = state.param_dyn
    if spec.param_keys and batch.param_rules is not None:
        # scalar_flow/fast_flow imply host-verified uniform acquire — the
        # precondition for the rank-prefix param variant (VERDICT r4 #9)
        pcheck = (pf_mod.param_check_scalar
                  if (scalar_flow or fast_flow) else pf_mod.param_check)
        param_dyn, param_ok, param_wait = pcheck(
            rules.param_table, param_dyn, batch.param_rules, batch.param_keys,
            batch.acquire, live2, rel_now_ms)
        live2 = live2 & param_ok
    else:
        param_ok = jnp.ones_like(live2)
        param_wait = jnp.zeros(live2.shape, jnp.int32)

    flow_bk = deg_bk = None
    if (scalar_flow or fast_flow) and rules.joint_idx is not None:
        # ONE random gather over the [R, Kf+Kd] joint table feeds both
        # slots (see RuleSet.joint_idx)
        from sentinel_tpu.ops.segments import padded_table_gather
        Kf = rules.flow_idx.shape[1]
        NFs = rules.flow_table.active.shape[0] - 1
        NDs = rules.deg_table.active.shape[0] - 1
        joint = padded_table_gather(rules.joint_idx, batch.rows, 0)
        in_r = (batch.rows < R)[:, None]
        flow_bk = jnp.where(in_r, joint[:, :Kf], NFs)
        deg_bk = jnp.where(in_r, joint[:, Kf:], NDs)
    sf_ovf = jnp.int32(0)
    if scalar_flow:
        flow_dyn, flow_ok, wait_ms = flow_mod.flow_check_scalar(
            rules.flow_table, state.flow_dyn, rules.flow_idx, spec.second,
            state.second, state.threads, batch.rows, batch.acquire, live2,
            now_idx_s, rel_now_ms,
            minute_spec=spec.minute,
            main_minute=state.minute if spec.minute else None,
            now_idx_m=now_idx_m,
            has_rate_limiter=scalar_has_rl,
            rules_bk=flow_bk,
            occupy_base=enable_occupy,
            sortfree=sortfree)
        occupied = jnp.zeros_like(flow_ok)
        live3 = live2 & flow_ok
        breakers, deg_ok = deg_mod.degrade_entry_check_scalar(
            rules.deg_table, state.breakers, rules.deg_idx, batch.rows,
            live3, rel_now_ms, rules_bk=deg_bk)
    elif fast_flow:
        # fast general path: per-pair origin/row selection stays live, the
        # admission machinery collapses to rank closed forms; the degrade
        # slot is origin-independent, so the scalar variant applies as-is
        cl_fb = (batch.cluster_fallback if batch.cluster_fallback is not None
                 else jnp.zeros(batch.valid.shape, jnp.int32))
        fview = flow_mod.FlowBatchView(
            rows=batch.rows, origin_ids=batch.origin_ids,
            origin_rows=batch.origin_rows, context_ids=batch.context_ids,
            chain_rows=batch.chain_rows, acquire=batch.acquire, valid=live2,
            prioritized=batch.prioritized, cluster_fallback=cl_fb)
        if enable_occupy:
            fn_occ = (flow_mod.flow_check_fast_occupy_sortfree if sortfree
                      else flow_mod.flow_check_fast_occupy)
            out = fn_occ(
                rules.flow_table, state.flow_dyn, rules.flow_idx,
                spec.second, state.second, state.alt_second,
                state.threads, state.alt_threads, fview, now_idx_s,
                rel_now_ms,
                minute_spec=spec.minute,
                main_minute=state.minute if spec.minute else None,
                now_idx_m=now_idx_m,
                in_win_ms=in_win_ms,
                occupy_timeout_ms=spec.occupy_timeout_ms,
                has_rate_limiter=scalar_has_rl,
                has_thread_rules=not skip_threads,
                rules_bk=flow_bk)
            if sortfree:
                flow_dyn, flow_ok, wait_ms, occupied, sf_ovf = out
            else:
                flow_dyn, flow_ok, wait_ms, occupied = out
        else:
            fn_plain = (flow_mod.flow_check_fast_sortfree if sortfree
                        else flow_mod.flow_check_fast)
            out = fn_plain(
                rules.flow_table, state.flow_dyn, rules.flow_idx, spec.second,
                state.second, state.alt_second, state.threads,
                state.alt_threads, fview, now_idx_s, rel_now_ms,
                minute_spec=spec.minute,
                main_minute=state.minute if spec.minute else None,
                now_idx_m=now_idx_m,
                has_rate_limiter=scalar_has_rl,
                has_thread_rules=not skip_threads,
                rules_bk=flow_bk)
            if sortfree:
                flow_dyn, flow_ok, wait_ms, sf_ovf = out
            else:
                flow_dyn, flow_ok, wait_ms = out
            occupied = jnp.zeros_like(flow_ok)
        live3 = live2 & flow_ok
        # occupied (PriorityWait) events bypass the degrade slot — see the
        # general branch below
        breakers, deg_ok = deg_mod.degrade_entry_check_scalar(
            rules.deg_table, state.breakers, rules.deg_idx, batch.rows,
            live3 & ~occupied, rel_now_ms, rules_bk=deg_bk)
        deg_ok = deg_ok | occupied
    else:
        cl_fb = (batch.cluster_fallback if batch.cluster_fallback is not None
                 else jnp.zeros(batch.valid.shape, jnp.int32))
        fview = flow_mod.FlowBatchView(
            rows=batch.rows, origin_ids=batch.origin_ids,
            origin_rows=batch.origin_rows, context_ids=batch.context_ids,
            chain_rows=batch.chain_rows, acquire=batch.acquire, valid=live2,
            prioritized=batch.prioritized, cluster_fallback=cl_fb)
        fcheck = (flow_mod.flow_check_sortfree if sortfree
                  else flow_mod.flow_check)
        out = fcheck(
            rules.flow_table, state.flow_dyn, rules.flow_idx, spec.second,
            state.second, state.alt_second, state.threads, state.alt_threads,
            fview, now_idx_s, rel_now_ms,
            minute_spec=spec.minute,
            main_minute=state.minute if spec.minute else None,
            now_idx_m=now_idx_m,
            in_win_ms=in_win_ms,
            occupy_timeout_ms=spec.occupy_timeout_ms,
            enable_occupy=enable_occupy,
            has_thread_rules=not skip_threads)
        if sortfree:
            flow_dyn, flow_ok, wait_ms, occupied, sf_ovf = out
        else:
            flow_dyn, flow_ok, wait_ms, occupied = out
        live3 = live2 & flow_ok

        # occupied (PriorityWait) events bypass the degrade slot entirely —
        # in the reference the PriorityWaitException aborts the slot chain
        # before DegradeSlot.entry runs, and the booking is already committed
        breakers, deg_ok = deg_mod.degrade_entry_check(
            rules.deg_table, state.breakers, rules.deg_idx, batch.rows,
            live3 & ~occupied, rel_now_ms)
        deg_ok = deg_ok | occupied

    # ---- user DeviceSlots (slot-chain SPI analog; STATIC: compiles to
    # nothing when none are registered) ----
    custom_states = state.custom
    if custom_slots:
        from sentinel_tpu.engine.slots import DeviceSlotView, run_device_slots
        from sentinel_tpu.stats.window import window_sum_rows
        safe_rows = jnp.minimum(batch.rows, R - 1)
        pass_counts = window_sum_rows(
            spec.second, state.second, safe_rows, ev.PASS,
            now_idx_s).astype(jnp.float32)
        cview = DeviceSlotView(
            rows=batch.rows, origin_ids=batch.origin_ids,
            acquire=batch.acquire, is_in=batch.is_in,
            prioritized=batch.prioritized, live=live3 & deg_ok,
            now_idx_s=now_idx_s, rel_now_ms=rel_now_ms,
            pass_counts=pass_counts)
        custom_states, custom_ok, custom_reason = run_device_slots(
            custom_slots, state.custom, cview)
    else:
        custom_ok = jnp.ones_like(live)
        custom_reason = jnp.zeros(batch.rows.shape, jnp.int8)

    allow = live & auth_ok & sys_ok & param_ok & flow_ok & deg_ok & custom_ok
    reason = jnp.zeros(batch.rows.shape, jnp.int8)
    reason = jnp.where(~custom_ok, custom_reason, reason)
    reason = jnp.where(~deg_ok, jnp.int8(BlockReason.DEGRADE), reason)
    reason = jnp.where(~flow_ok, jnp.int8(BlockReason.FLOW), reason)
    reason = jnp.where(~param_ok, jnp.int8(BlockReason.PARAM_FLOW), reason)
    reason = jnp.where(~sys_ok, jnp.int8(BlockReason.SYSTEM), reason)
    reason = jnp.where(~auth_ok, jnp.int8(BlockReason.AUTHORITY), reason)
    reason = jnp.where(~batch.valid, jnp.int8(BlockReason.NONE), reason)
    wait_ms = jnp.where(allow, jnp.maximum(wait_ms, param_wait), 0)

    # ---- StatisticSlot.entry (post-decision recording) ----
    passed = allow & batch.valid
    blocked = ~allow & batch.valid
    # occupied (PriorityWait) entries don't count PASS now — their pass
    # belongs to the next window (virtual booking in flow dyn state); they
    # still hold a thread and show up as OCCUPIED_PASS in this second's
    # metrics (half-a-window earlier than the reference's landing-time
    # accounting; admission math is unaffected)
    pass_now = passed & ~occupied
    occupied = occupied & passed      # occupied implies admitted; belt-and-
    # braces so a blocked event can never record OCCUPIED_PASS
    pad_r = jnp.int32(R)
    pad_a = jnp.int32(RA)

    _, alt_targets = _stat_targets(
        spec, batch.rows, batch.origin_rows, batch.chain_rows, batch.valid,
        batch.is_in)
    blocked_rec = (blocked & batch.record_block
                   if batch.record_block is not None else blocked)
    occ1 = occupied if enable_occupy else jnp.zeros_like(pass_now)

    # Recording strategy (this block was ~70% of the step's device time as
    # per-event add_rows passes): (1) full-table lazy reset (refresh_all:
    # dynamic-slice, no index arrays); (2) each event lands in exactly ONE
    # lane (pass_now / occupied / blocked are mutually exclusive), so the
    # per-row record is one fused scatter of B indices (add_rows_multi);
    # (3) the global ENTRY row — formerly a second B-index scatter half —
    # is a reduction + one single-row update (add_one_row).
    rec1 = pass_now | occ1 | blocked_rec            # all already ∧ valid
    ev_ids1 = jnp.where(pass_now, jnp.int32(ev.PASS),
                        jnp.where(occ1, jnp.int32(ev.OCCUPIED_PASS),
                                  jnp.int32(ev.BLOCK)))
    acq = batch.acquire
    rec_amt1 = jnp.where(rec1, acq, 0)
    main_rec1 = jnp.where(rec1, batch.rows, pad_r)

    ein = batch.is_in
    n_ev = state.second.counters.shape[2]
    entry_vec = jnp.zeros((n_ev,), jnp.int32)
    entry_vec = entry_vec.at[ev.PASS].set(
        jnp.sum(jnp.where(pass_now & ein, acq, 0)))
    if enable_occupy:
        entry_vec = entry_vec.at[ev.OCCUPIED_PASS].set(
            jnp.sum(jnp.where(occ1 & ein, acq, 0)))
    entry_vec = entry_vec.at[ev.BLOCK].set(
        jnp.sum(jnp.where(blocked_rec & ein, acq, 0)))

    if spec.second.buckets >= 2:
        second = refresh_all(spec.second, state.second, now_idx_s)
    else:   # B=1: full restamp would erase untouched rows' prev window
        # ENTRY joins the refresh list only when this batch actually lands
        # something on it — an idle/all-outbound batch restamping ENTRY
        # would erase its previous-window bucket (previousPassQps for
        # warm-up rules reading the entry node). add_one_row with an
        # all-zero vector on the unrefreshed bucket is a no-op.
        entry_refresh = jnp.where(jnp.any(entry_vec != 0),
                                  jnp.int32(ENTRY_NODE_ROW), pad_r)
        second = refresh_rows(
            spec.second, state.second,
            jnp.concatenate([main_rec1, entry_refresh[None]]),
            now_idx_s)
    second = add_rows_multi(spec.second, second, main_rec1, ev_ids1,
                            rec_amt1, now_idx_s)
    second = add_one_row(spec.second, second, ENTRY_NODE_ROW, entry_vec,
                         now_idx_s)

    # alt rows (origin + chain hashes): no OCCUPIED lane on alt (as before)
    if record_alt:
        alt_mask1 = pass_now | blocked_rec
        alt_mask2 = jnp.concatenate([alt_mask1, alt_mask1])
        ev_ids2 = jnp.concatenate([ev_ids1, ev_ids1])
        alt_rec = jnp.where(alt_mask2, alt_targets, pad_a)
        if spec.second.buckets >= 2:
            alt_second = refresh_all(spec.second, state.alt_second,
                                     now_idx_s)
        else:
            alt_second = refresh_rows(spec.second, state.alt_second,
                                      alt_targets, now_idx_s)
        if fast_flow and RA <= 4096 and hist_add_fits(2 * batch.rows.shape[0]):
            # the [2B]-index scatter collides massively on the small alt
            # table; the histogram matmul is ~3x cheaper on the MXU, and
            # fast_flow's host-verified uniform acquire makes its int32
            # post-scaling bit-exact (see stats.window.add_rows_hist)
            a_uni = jnp.max(jnp.where(batch.valid, acq, 0))
            alt_second = add_rows_hist(spec.second, alt_second, alt_rec,
                                       ev_ids2, a_uni, now_idx_s)
        else:
            acq2 = jnp.concatenate([acq, acq])
            alt_amt = jnp.where(alt_mask2, acq2, 0)
            alt_second = add_rows_multi(spec.second, alt_second, alt_rec,
                                        ev_ids2, alt_amt, now_idx_s)
    else:
        alt_second = state.alt_second

    minute = state.minute
    if spec.minute:
        minute = refresh_all(spec.minute, state.minute, now_idx_m)
        minute = add_rows_multi(spec.minute, minute, main_rec1, ev_ids1,
                                rec_amt1, now_idx_m)
        minute = add_one_row(spec.minute, minute, ENTRY_NODE_ROW, entry_vec,
                             now_idx_m)

    if skip_threads:
        # nothing loaded reads the gauges: the scatters (+ the alt half)
        # compile away — ~1/3 of the scalar step's floor
        threads = state.threads
        alt_threads = state.alt_threads
    else:
        ct1 = batch.count_thread
        thr_mask1 = passed if ct1 is None else passed & ct1
        thr_amt1 = jnp.where(thr_mask1, 1, 0)
        # +1 per entry (reference curThreadNum); leased admissions opt out
        threads = state.threads.at[
            jnp.where(passed, batch.rows, pad_r)].add(thr_amt1, mode="drop")
        threads = threads.at[ENTRY_NODE_ROW].add(
            jnp.sum(jnp.where(thr_mask1 & ein, 1, 0)))
        if record_alt:
            pass2 = jnp.concatenate([passed, passed])
            thr_amt2 = jnp.concatenate([thr_amt1, thr_amt1])
            alt_threads = state.alt_threads.at[
                jnp.where(pass2, alt_targets, pad_a)].add(thr_amt2,
                                                          mode="drop")
        else:
            alt_threads = state.alt_threads

    if spec.param_keys and batch.param_rules is not None and \
            not skip_threads:
        param_dyn = pf_mod.param_thread_update(
            rules.param_table, param_dyn, batch.param_rules, batch.param_keys,
            passed, +1)

    new_state = SentinelState(
        second=second, minute=minute, alt_second=alt_second,
        threads=threads, alt_threads=alt_threads,
        flow_dyn=flow_dyn, breakers=breakers, param_dyn=param_dyn,
        custom=custom_states, rt_hist=state.rt_hist)
    return new_state, Verdicts(allow=allow, reason=reason, wait_ms=wait_ms,
                               sf_overflow=sf_ovf if sortfree else None)


def record_exits(
    spec: EngineSpec,
    rules: RuleSet,
    state: SentinelState,
    batch: ExitBatch,
    times: jnp.ndarray,          # int32[4] (same packing as decide_entries)
    record_alt: bool = True,     # STATIC (see decide_entries)
    skip_threads: bool = False,  # STATIC (see decide_entries)
) -> SentinelState:
    """Completion step: ``StatisticSlot.exit`` (rt/success/exception, thread
    decrement, for node + origin + chain + ENTRY) then ``DegradeSlot.exit``
    (breaker feed)."""
    R = spec.rows
    RA = spec.alt_rows
    now_idx_s = times[0]
    now_idx_m = times[1]
    rel_now_ms = times[2]
    pad_r = jnp.int32(R)
    pad_a = jnp.int32(RA)

    main_rows = jnp.where(batch.valid, batch.rows, pad_r)
    alt_o = jnp.where(batch.valid, batch.origin_rows, pad_a)
    alt_c = jnp.where(batch.valid, batch.chain_rows, pad_a)
    alt_targets = jnp.concatenate([alt_o, alt_c])

    acq1 = jnp.where(batch.valid, batch.acquire, 0)
    err1 = jnp.where(batch.error, acq1, 0)
    rt1 = batch.rt_ms
    ein = batch.valid & batch.is_in

    # An exit can record BOTH SUCCESS and EXCEPTION, so the fused per-row
    # form is a full event-lane payload (one scatter instead of one per
    # event type); rt rides the same pass. The ENTRY row is a reduction +
    # one single-row update, not a second scatter half (see decide).
    n_ev = state.second.counters.shape[2]
    payload = jnp.zeros((batch.rows.shape[0], n_ev), jnp.int32)
    payload = payload.at[:, ev.SUCCESS].set(acq1)
    payload = payload.at[:, ev.EXCEPTION].set(err1)
    payload2 = jnp.concatenate([payload, payload])

    entry_vec = jnp.zeros((n_ev,), jnp.int32)
    entry_vec = entry_vec.at[ev.SUCCESS].set(jnp.sum(jnp.where(ein, acq1, 0)))
    entry_vec = entry_vec.at[ev.EXCEPTION].set(
        jnp.sum(jnp.where(ein, err1, 0)))
    # float32 BEFORE the sum: the ENTRY aggregate overflows int32 within a
    # single large batch (rt_sum is float32 for exactly this reason)
    entry_rt_add = jnp.sum(jnp.where(ein, rt1, 0).astype(jnp.float32))
    entry_rt_min = jnp.min(jnp.where(ein, rt1, jnp.iinfo(jnp.int32).max))

    if spec.second.buckets >= 2:
        second = refresh_all(spec.second, state.second, now_idx_s)
    else:
        # B=1: same ENTRY gating as decide_entries — only refresh the
        # entry row when an IN event actually lands on it this batch
        entry_refresh = jnp.where(jnp.any(ein),
                                  jnp.int32(ENTRY_NODE_ROW), pad_r)
        second = refresh_rows(
            spec.second, state.second,
            jnp.concatenate([main_rows, entry_refresh[None]]),
            now_idx_s)
    second = add_rows_vec(spec.second, second, main_rows, payload,
                          now_idx_s, rt_ms=rt1, rt_valid=batch.valid)
    second = add_one_row(spec.second, second, ENTRY_NODE_ROW, entry_vec,
                         now_idx_s, rt_add=entry_rt_add,
                         rt_min=entry_rt_min)
    if record_alt:
        if spec.second.buckets >= 2:
            alt_second = refresh_all(spec.second, state.alt_second,
                                     now_idx_s)
        else:
            alt_second = refresh_rows(spec.second, state.alt_second,
                                      alt_targets, now_idx_s)
        rt2 = jnp.concatenate([rt1, rt1])
        valid2 = jnp.concatenate([batch.valid, batch.valid])
        alt_second = add_rows_vec(spec.second, alt_second, alt_targets,
                                  payload2, now_idx_s, rt_ms=rt2,
                                  rt_valid=valid2)
    else:
        alt_second = state.alt_second

    minute = state.minute
    if spec.minute:
        minute = refresh_all(spec.minute, state.minute, now_idx_m)
        minute = add_rows_vec(spec.minute, minute, main_rows, payload,
                              now_idx_m, rt_ms=rt1, rt_valid=batch.valid)
        minute = add_one_row(spec.minute, minute, ENTRY_NODE_ROW, entry_vec,
                             now_idx_m, rt_add=entry_rt_add,
                             rt_min=entry_rt_min)

    if skip_threads:
        threads = state.threads
        alt_threads = state.alt_threads
    else:
        ct1 = batch.count_thread
        dec1 = jnp.where(batch.valid if ct1 is None
                         else batch.valid & ct1, 1, 0)
        threads = state.threads.at[main_rows].add(-dec1, mode="drop")
        threads = threads.at[ENTRY_NODE_ROW].add(
            -jnp.sum(jnp.where(ein if ct1 is None else ein & ct1, 1, 0)))
        threads = jnp.maximum(threads, 0)
        if record_alt:
            dec2 = jnp.concatenate([dec1, dec1])
            alt_threads = state.alt_threads.at[alt_targets].add(-dec2,
                                                               mode="drop")
            alt_threads = jnp.maximum(alt_threads, 0)
        else:
            alt_threads = state.alt_threads

    breakers = deg_mod.degrade_exit_feed(
        rules.deg_table, state.breakers, rules.deg_idx, batch.rows,
        batch.rt_ms, batch.error, batch.valid, rel_now_ms)

    param_dyn = state.param_dyn
    if spec.param_keys and batch.param_rules is not None and \
            not skip_threads:
        param_dyn = pf_mod.param_thread_update(
            rules.param_table, param_dyn, batch.param_rules, batch.param_keys,
            batch.valid, -1)

    rt_hist = state.rt_hist
    if spec.hist_buckets:
        # round 20: cumulative per-resource RT histogram — one +1 per
        # valid exit at [row, log2 ms bucket]; invalid lanes ride the
        # pad row and drop. Not acquire-scaled: the table counts
        # completions (the tail shape), one sample per exit like the
        # entry-node rt aggregate, not acquire-weighted like rt_sum.
        bidx = resource_hist.bucket_index(rt1, spec.hist_buckets)
        rt_hist = rt_hist.at[main_rows, bidx].add(
            jnp.where(batch.valid, 1, 0), mode="drop")

    return SentinelState(
        second=second, minute=minute, alt_second=alt_second,
        threads=threads, alt_threads=alt_threads,
        flow_dyn=state.flow_dyn, breakers=breakers, param_dyn=param_dyn,
        custom=state.custom, rt_hist=rt_hist)


def decide_and_record_exits(
    spec: EngineSpec,
    rules: RuleSet,
    state: SentinelState,
    entry_batch: EntryBatch,
    exit_batch: ExitBatch,
    times: jnp.ndarray,          # int32[4]
    sys_scalars: jnp.ndarray,    # float32[2]
    enable_occupy: bool = False,
    custom_slots: Tuple = (),
    record_alt: bool = True,     # STATIC (see decide_entries)
    scalar_flow: bool = False,   # STATIC (see decide_entries)
    fast_flow: bool = False,     # STATIC (see decide_entries)
    skip_auth: bool = False,     # STATIC
    skip_sys: bool = False,      # STATIC
    scalar_has_rl: bool = True,  # STATIC
    skip_threads: bool = False,  # STATIC (see decide_entries)
    sortfree: bool = False,      # STATIC (see decide_entries)
) -> Tuple[SentinelState, Verdicts]:
    """Fused entry+exit step: one dispatch where serving loops would pay two.

    A steady-state workload completes a batch of calls per step
    (``DegradeSlot.entry`` feeding breakers on the way in,
    ``StatisticSlot.exit`` + ``DegradeSlot.exit`` on the way out —
    ``StatisticSlot.java:133-178``); the exit batch is known at dispatch time
    (it is the *previous* step's completions), so both halves fuse into one
    jitted call. Ordering matches the two-dispatch form: exits land AFTER
    this step's decisions, exactly like the separate ``record_exits``
    dispatch that immediately follows ``decide_entries`` — XLA fuses the
    window scatters of both halves into one pass over the tables, and a
    tunneled TPU pays one dispatch RTT instead of two."""
    state, verdicts = decide_entries(
        spec, rules, state, entry_batch, times, sys_scalars,
        enable_occupy=enable_occupy, custom_slots=custom_slots,
        record_alt=record_alt, scalar_flow=scalar_flow,
        fast_flow=fast_flow, skip_auth=skip_auth, skip_sys=skip_sys,
        scalar_has_rl=scalar_has_rl, skip_threads=skip_threads,
        sortfree=sortfree)
    state = record_exits(spec, rules, state, exit_batch, times,
                         record_alt=record_alt, skip_threads=skip_threads)
    return state, verdicts


def record_blocks(
    spec: EngineSpec,
    state: SentinelState,
    rows: jnp.ndarray,
    origin_rows: jnp.ndarray,
    chain_rows: jnp.ndarray,
    acquire: jnp.ndarray,
    is_in: jnp.ndarray,
    valid: jnp.ndarray,
    times: jnp.ndarray,          # int32[4]
) -> SentinelState:
    """Record BLOCK events decided OUTSIDE the local pipeline (cluster token
    denials: the reference's StatisticSlot counts a cluster BLOCKED like any
    other BlockException)."""
    now_idx_s = times[0]
    now_idx_m = times[1]
    main_targets, alt_targets = _stat_targets(
        spec, rows, origin_rows, chain_rows, valid, is_in)
    amt = jnp.where(valid, acquire, 0)
    amt2 = jnp.concatenate([amt, amt])
    if spec.second.buckets >= 2:
        second = refresh_all(spec.second, state.second, now_idx_s)
        alt_second = refresh_all(spec.second, state.alt_second, now_idx_s)
    else:
        second = refresh_rows(spec.second, state.second, main_targets,
                              now_idx_s)
        alt_second = refresh_rows(spec.second, state.alt_second, alt_targets,
                                  now_idx_s)
    second = add_rows(spec.second, second, main_targets, ev.BLOCK, amt2,
                      now_idx_s)
    alt_second = add_rows(spec.second, alt_second, alt_targets, ev.BLOCK,
                          amt2, now_idx_s)
    minute = state.minute
    if spec.minute:
        minute = refresh_all(spec.minute, state.minute, now_idx_m)
        minute = add_rows(spec.minute, minute, main_targets, ev.BLOCK, amt2,
                          now_idx_m)
    return state._replace(second=second, alt_second=alt_second, minute=minute)


def uncount_reserved(spec: EngineSpec, state: SentinelState,
                     rows: jnp.ndarray, sec_idx: jnp.ndarray,
                     min_idx: jnp.ndarray,
                     amounts: jnp.ndarray) -> SentinelState:
    """Return unused host-lease tokens to their window buckets: a lease
    pre-charge recorded PASS for the whole chunk up front (the admission
    ledger must see reserved tokens), so the remainder of an expired lease
    is subtracted back — pass metrics then count actual admissions, not
    reservations. Only live buckets are touched (see
    :func:`stats.window.uncount_rows`)."""
    from sentinel_tpu.stats.window import uncount_rows

    second = uncount_rows(spec.second, state.second, rows, sec_idx,
                          ev.PASS, amounts)
    minute = state.minute
    if spec.minute:
        minute = uncount_rows(spec.minute, state.minute, rows, min_idx,
                              ev.PASS, amounts)
    return state._replace(second=second, minute=minute)


def invalidate_resource_rows(spec: EngineSpec, state: SentinelState,
                             rows: jnp.ndarray,
                             alt_rows: jnp.ndarray) -> SentinelState:
    """Forget recycled rows' stats (registry eviction hygiene).

    ``alt_rows`` are the hashed (resource × origin/context) rows the evicted
    resources ever touched — without clearing them, a recycled main row whose
    (new resource, origin) pair hashes to the same alt slot would inherit the
    evicted resource's live origin counters. A hash-collided alt row shared
    with a live pair loses that pair's short-window stats too — bounded, the
    same merging the hash already implies.
    """
    second = invalidate_rows(spec.second, state.second, rows)
    minute = state.minute
    if spec.minute:
        minute = invalidate_rows(spec.minute, state.minute, rows)
    threads = state.threads.at[rows].set(0, mode="drop")
    alt_second = invalidate_rows(spec.second, state.alt_second, alt_rows)
    alt_threads = state.alt_threads.at[alt_rows].set(0, mode="drop")
    rt_hist = state.rt_hist
    if rt_hist is not None:
        # the ONLY reset path for the cumulative RT histogram (round 20)
        rt_hist = rt_hist.at[rows].set(0, mode="drop")
    # occupy bookings are keyed by resource ROW — a recycled row must not
    # inherit the evicted resource's pre-booked next-window budget
    flow_dyn = state.flow_dyn._replace(
        occupied_count=state.flow_dyn.occupied_count.at[rows].set(
            0.0, mode="drop"),
        occupied_window=state.flow_dyn.occupied_window.at[rows].set(
            -(2 ** 30), mode="drop"))
    return state._replace(second=second, minute=minute, threads=threads,
                          alt_second=alt_second, alt_threads=alt_threads,
                          flow_dyn=flow_dyn, rt_hist=rt_hist)


class ResourceRowSlice(NamedTuple):
    """One batch of demoted rows' complete per-row state — everything
    :func:`invalidate_resource_rows` destroys, gathered FIRST so the cold
    tier (sentinel_tpu/tiering/) can hold it host-side and a later
    promotion restores the row bit-identically. Window stamps and occupy
    target windows are absolute indices, so the payload needs no
    rebasing at restore time. ``alt_*`` leaves carry the hashed
    (resource × origin/context) slots the demoted resources touched —
    keyed by (kind, key id) host-side so promotion can re-hash them to
    the NEW row's slots."""

    second: WindowState            # [K, ...] per-row second-window slice
    minute: WindowState            # [K, ...] ([K, 0...] when disabled)
    threads: jnp.ndarray           # int32[K]
    occ_cnt: jnp.ndarray           # float32[K, B+1] occupy booking ring
    occ_win: jnp.ndarray           # int32[K, B+1]
    alt_second: WindowState        # [KA, ...] alt-window slices
    alt_threads: jnp.ndarray       # int32[KA]
    rt_hist: Optional[jnp.ndarray] = None   # int32[K, HB] (round 20;
    # None when the engine has no histogram table — see EngineSpec)


def extract_resource_rows(spec: EngineSpec, state: SentinelState,
                          rows: jnp.ndarray,
                          alt_rows: jnp.ndarray) -> ResourceRowSlice:
    """Gather the demotion payload for ``rows`` (+ their ``alt_rows``)
    out of the live state. Pure gathers into FRESH output buffers — safe
    to dispatch under the engine lock and read back asynchronously while
    later steps donate the state (the telemetry-tick discipline)."""
    r = rows.clip(0, spec.rows - 1)
    ra = alt_rows.clip(0, spec.alt_rows - 1)
    if spec.minute:
        minute = extract_rows(spec.minute, state.minute, rows)
    else:   # minute ring disabled: placeholder slice (ignored at restore)
        minute = extract_rows(spec.second, state.minute,
                              jnp.zeros_like(rows))
    return ResourceRowSlice(
        second=extract_rows(spec.second, state.second, rows),
        minute=minute,
        threads=state.threads[r],
        occ_cnt=state.flow_dyn.occupied_count[r],
        occ_win=state.flow_dyn.occupied_window[r],
        alt_second=extract_rows(spec.second, state.alt_second, alt_rows),
        alt_threads=state.alt_threads[ra],
        rt_hist=state.rt_hist[r] if state.rt_hist is not None else None)


def restore_resource_rows(spec: EngineSpec, state: SentinelState,
                          rows: jnp.ndarray, payload: ResourceRowSlice,
                          alt_rows: jnp.ndarray) -> SentinelState:
    """Scatter a promotion payload into freshly (re)allocated ``rows``.

    The inverse of :func:`extract_resource_rows` modulo two documented
    asymmetries: (a) ``alt_rows`` here are the NEW rows' hashed slots
    (host-side re-hash of the payload's (kind, key id) identities — a
    collision with a live pair overwrites that pair's short-window alt
    stats, the same bounded merging the hash table already implies); and
    (b) occupy bookings that straddled a rule reload while cold must be
    settled HOST-side first (tiering/coldtier.py replays the reload's
    ``settle_occupied`` with the reload's own ``now_idx``, so the
    restored ring is bit-identical to the ring the row would hold had it
    stayed resident). Padding rows >= R / alt >= RA drop."""
    second = restore_rows(spec.second, state.second, rows, payload.second)
    minute = state.minute
    if spec.minute:
        minute = restore_rows(spec.minute, state.minute, rows,
                              payload.minute)
    flow_dyn = state.flow_dyn._replace(
        occupied_count=state.flow_dyn.occupied_count.at[rows].set(
            payload.occ_cnt, mode="drop"),
        occupied_window=state.flow_dyn.occupied_window.at[rows].set(
            payload.occ_win, mode="drop"))
    rt_hist = state.rt_hist
    if rt_hist is not None and payload.rt_hist is not None:
        rt_hist = rt_hist.at[rows].set(payload.rt_hist, mode="drop")
    return state._replace(
        second=second, minute=minute,
        threads=state.threads.at[rows].set(payload.threads, mode="drop"),
        alt_second=restore_rows(spec.second, state.alt_second, alt_rows,
                                payload.alt_second),
        alt_threads=state.alt_threads.at[alt_rows].set(
            payload.alt_threads, mode="drop"),
        flow_dyn=flow_dyn, rt_hist=rt_hist)
