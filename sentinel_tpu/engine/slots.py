"""Pluggable processor slots — the SlotChainBuilder / ProcessorSlot SPI
re-designed for a fused, jitted pipeline.

Reference: custom slots plug into the chain via SPI
(``slotchain/SlotChainProvider.java:39``, ``spi/SpiLoader.java:73-179``,
``DefaultSlotChainBuilder.java:39``; demos ``sentinel-demo-slot-spi`` and
``sentinel-demo-slotchain-spi``). A Java slot is an object in a linked
chain; here the chain is ONE compiled function, so extensibility comes in
two tiers:

* :class:`HostGate` — a host-side pre-decide gate. Runs before the device
  dispatch on both the single-entry and batch tiers; can deny by returning
  False (or raising a :class:`~sentinel_tpu.core.errors.BlockException`).
  Denials are recorded on device like any other block (StatisticSlot
  parity) and surface as :class:`CustomSlotException` with the gate's
  name. This is the "annotate/block without editing the engine" tier — no
  jax knowledge needed.

* :class:`DeviceSlot` — a jittable gate COMPILED INTO the fused decide
  step at registration time. ``check(state, view)`` must be a pure
  jax-traceable function over a :class:`DeviceSlotView`; it returns the
  slot's next state and a per-event ok mask. The slot owns a pytree state
  slice carried inside the engine state (donated across steps like every
  other slot's). This is the full-power tier: a user gate with the same
  standing as FlowSlot, at device speed, still without editing
  ``engine/pipeline.py``.

Ordering: device slots run after the built-in cascade (authority → system
→ param → flow → degrade), in registration order, each seeing only events
still live — the same only-live-events contract the built-in slots have.
Host gates run before everything (they can veto the device dispatch).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp


class DeviceSlotView(NamedTuple):
    """Read-only per-event inputs handed to a :class:`DeviceSlot`."""

    rows: jnp.ndarray          # int32[B] main resource row (>= R padding)
    origin_ids: jnp.ndarray    # int32[B] (0 = none)
    acquire: jnp.ndarray       # int32[B]
    is_in: jnp.ndarray         # bool[B]
    prioritized: jnp.ndarray   # bool[B]
    live: jnp.ndarray          # bool[B] — still admitted by earlier slots
    now_idx_s: jnp.ndarray     # int32 scalar, second-window index
    rel_now_ms: jnp.ndarray    # int32 scalar, ms since process epoch
    pass_counts: jnp.ndarray   # float32[B] — rolling PASS of each row's
    # second window (the most common gate input, pre-gathered once)


class DeviceSlot:
    """Base class for jittable slots. Subclass and override."""

    #: shown in block logs / CustomSlotException.slot_name
    name: str = "device-slot"

    def init_state(self, spec) -> Any:
        """Initial pytree state slice (called at registration and on
        engine-state resets). ``spec`` is the EngineSpec. Return () for a
        stateless gate."""
        return ()

    def check(self, state: Any, view: DeviceSlotView):
        """Pure jax function: → ``(next_state, ok bool[B])``. Events with
        ``view.live == False`` are already denied/padded — their ok value
        is ignored."""
        raise NotImplementedError


class HostGate:
    """Base class for host-side pre-decide gates. Subclass and override
    :meth:`check` (and optionally :meth:`check_batch` for the batch tier —
    the default loops ``check``)."""

    name: str = "host-gate"

    def check(self, resource: str, origin: str, acquire: int,
              args: Sequence) -> bool:
        """→ False to deny (or raise a BlockException subclass)."""
        return True

    def check_batch(self, resources: Sequence[str],
                    origins: Optional[Sequence[str]],
                    acquire, args_list) -> Sequence[bool]:
        from sentinel_tpu.core.errors import BlockException

        out = []
        for i, r in enumerate(resources):
            org = origins[i] if origins is not None and origins[i] else ""
            args = args_list[i] if args_list is not None else ()
            try:
                ok = bool(self.check(r, org, int(acquire[i]), args))
            except BlockException:
                # the documented deny style on the entry() path denies
                # just this event on the batch tier (custom exception
                # classes collapse to the gate's reason code here)
                ok = False
            out.append(ok)
        return out


def run_device_slots(custom_slots: Tuple[DeviceSlot, ...], custom_states,
                     view: DeviceSlotView):
    """Cascade the registered device slots (called from the fused decide;
    static over ``custom_slots`` so an empty registry compiles to
    nothing). → (next_states tuple, combined ok bool[B], reason int8[B]
    where blocked: CUSTOM_BASE + slot position, else 0)."""
    from sentinel_tpu.core.errors import BlockReason

    ok_all = jnp.ones_like(view.live)
    reason = jnp.zeros(view.rows.shape, jnp.int8)
    live = view.live
    next_states = []
    for si, slot in enumerate(custom_slots):
        sview = view._replace(live=live)
        st2, ok = slot.check(custom_states[si], sview)
        ok = ok | ~live               # only live events can be denied
        next_states.append(st2)
        newly = ~ok & (reason == 0)
        reason = jnp.where(newly, jnp.int8(BlockReason.CUSTOM_BASE + si),
                           reason)
        ok_all = ok_all & ok
        live = live & ok
    return tuple(next_states), ok_all, reason
