"""Host-side fast path for the single-entry tier.

SURVEY §7 hard-part 1: the reference's local decision is ~ns in-process
(``FlowRuleChecker`` reading a ``LeapArray`` on the caller's thread); on a
device-attached engine every ``entry()`` pays a host→device round trip even
for resources with no rules. This module decides ON HOST for the two cases
that dominate real traffic, while keeping every statistic on device:

* **FREE** resources — named by NO rule of any kind: admit immediately and
  buffer the pass; buffered events flush through the normal jitted decide
  in batches (rule-free events can't block, so the flush is pure
  ``StatisticSlot`` recording — pass counts, thread gauge, ENTRY node,
  origin/chain rows all land exactly as the slow path would record them).

* **LEASED** resources — exactly one simple QPS flow rule
  (DefaultController grade, ``limitApp=default``, DIRECT strategy,
  non-cluster): the host pre-charges a token chunk by pushing ONE decide
  with ``acquire=C`` through the full device pipeline, then hands tokens
  out locally until the chunk is exhausted or the window bucket rotates.
  Because every leased admission was already counted at pre-charge,
  over-admission beyond the configured count is STRUCTURALLY impossible;
  unused chunk remainder at bucket rotation is bounded under-admission
  (the analog of the reference's tolerated check-then-act skew, in the
  conservative direction). When the chunk is denied the row is marked hot
  for the bucket and every event takes the exact device path.

Exclusions (events fall through to the device path): prioritized entries
(a PriorityWait admission must book the next window in the device's
FlowDynState ring — host leases cannot; the device side is no longer a
demotion, it runs the vectorized occupy variant,
rules/flow.flow_check_fast_occupy), entries with args on param-ruled
resources, origin/non-default-context entries on LEASED rows (their
per-origin stats need per-event recording), and everything while system
rules are loaded (SystemSlot gates inbound traffic globally;
host-admitting would bypass it).

Thread gauge: leased admissions are excluded from the concurrency gauge on
both sides (entry pre-charge and exit both carry ``count_thread=False``),
so the gauge stays consistent; FREE events are thread-counted exactly.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

FREE = 0
LEASED = 1
INELIGIBLE = 2

# lease_state verdicts
ADMIT = 0      # served from the live lease
RENEW = 1      # no live lease (or exhausted, matching) → try a pre-charge
DEVICE = 2     # take the exact device path for this event


class _Lease:
    __slots__ = ("bucket_idx", "remaining", "is_in", "created_ms")

    def __init__(self, bucket_idx: int, remaining: int, is_in: bool,
                 created_ms: int):
        self.bucket_idx = bucket_idx
        self.remaining = remaining
        self.is_in = is_in
        self.created_ms = created_ms


class HostFastPath:
    """Classification tables + stat buffers + lease book-keeping.

    Thread-safe; the runtime owns WHEN to flush (size/age triggers checked
    by :meth:`due`, plus forced flushes before introspection reads).
    """

    def __init__(self, *, flush_events: int, flush_ms: int,
                 lease_fraction: float, win_ms: int):
        self.flush_events = flush_events
        self.flush_ms = flush_ms
        self.lease_fraction = lease_fraction
        self.win_ms = max(1, win_ms)
        self.sys_active = False
        self._ineligible: Set[int] = set()
        self._lease_count: Dict[int, float] = {}
        self._leases: Dict[int, _Lease] = {}
        self._hot_bucket: Dict[int, int] = {}
        self._renewing: Set[int] = set()   # rows with a pre-charge in flight
        # expired leases' unused tokens awaiting window reversal:
        # (row, created_ms, remaining, is_in)
        self._expired: List[tuple] = []
        self._pass_buf: List[tuple] = []
        self._exit_buf: List[tuple] = []
        self._buf_bucket = -1
        self._last_flush_ms = 0
        self._lock = threading.Lock()
        # bumped on every set_tables: a pre-charge granted under an older
        # generation must not install (its budget belongs to the old rules)
        self.table_gen = 0
        # observability: how many device dispatches the fast path avoided
        self.fast_admits = 0
        self.lease_renewals = 0

    # ---------------------------------------------------------------- tables
    def set_tables(self, ineligible: Set[int], lease_counts: Dict[int, float],
                   sys_active: bool) -> None:
        """Swap in a fresh classification after a rule load. Live leases
        are dropped; their unused pre-charged tokens queue for window
        reversal at the next flush (transiently reserved on device until
        then — never over-admission)."""
        with self._lock:
            self._ineligible = ineligible
            self._lease_count = lease_counts
            self.sys_active = sys_active
            self.table_gen += 1
            self._collect_expired_locked(drop_all=True)
            self._hot_bucket.clear()

    def classify(self, row: int) -> int:
        if row in self._ineligible:
            return INELIGIBLE
        if row in self._lease_count:
            return LEASED
        return FREE

    # ---------------------------------------------------------------- leases
    def bucket_of(self, now_ms: int) -> int:
        return now_ms // self.win_ms

    def _retire_lease_locked(self, row: int, lease) -> None:
        """Queue a dead lease's unused remainder for window reversal at the
        next flush (callers hold the lock and have unlinked the lease)."""
        if lease.remaining > 0:
            self._expired.append((row, lease.created_ms,
                                  lease.remaining, lease.is_in))

    def lease_state(self, row: int, acquire: int, is_in: bool,
                    now_ms: int) -> int:
        """→ ADMIT (token taken from the live lease), RENEW (no live lease
        this bucket, or a matching one is exhausted — a pre-charge may
        help), or DEVICE (live lease with a different entry type: renewing
        would burn budget on a second chunk, so the event takes the exact
        device path). Never decides a denial."""
        b = self.bucket_of(now_ms)
        with self._lock:
            lease = self._leases.get(row)
            if lease is not None and lease.bucket_idx != b:
                # bucket rotated: unused tokens go back to their window
                self._leases.pop(row)
                self._retire_lease_locked(row, lease)
                lease = None
            if lease is not None:
                if lease.is_in != is_in:
                    return DEVICE
                if lease.remaining >= acquire:
                    lease.remaining -= acquire
                    self.fast_admits += 1
                    return ADMIT
            return RENEW

    def begin_renewal(self, row: int) -> bool:
        """Claim the single renewal slot for ``row``; False = another
        thread's pre-charge is in flight (caller takes the device path
        instead of double-charging the window)."""
        with self._lock:
            if row in self._renewing:
                return False
            self._renewing.add(row)
            return True

    def end_renewal(self, row: int) -> None:
        with self._lock:
            self._renewing.discard(row)

    def is_hot(self, row: int, now_ms: int) -> bool:
        """True while the current bucket already had a chunk denied —
        every event goes through the exact device path until rotation."""
        return self._hot_bucket.get(row) == self.bucket_of(now_ms)

    def lease_chunk(self, row: int, acquire: int) -> int:
        """Chunk size for a renewal: a fraction of the per-window budget,
        at least the triggering event's acquire."""
        count = self._lease_count.get(row, 0.0)
        per_window = count * self.win_ms / 1000.0
        return max(int(acquire), int(per_window * self.lease_fraction))

    def install_lease(self, row: int, chunk: int, used: int, is_in: bool,
                      now_ms: int, gen: Optional[int] = None) -> None:
        """Credit a granted pre-charge. MERGES into a live matching lease
        (every granted chunk was already recorded on device — dropping one
        would waste budget, never over-admit). ``gen`` (from
        :attr:`table_gen` before the device pre-charge) guards a renewal
        racing a rule reload: a chunk granted under the OLD tables must not
        serve under the new (possibly lower) limit — its unused remainder
        queues straight for window reversal instead (bounded
        under-admission, the safe direction)."""
        with self._lock:
            if gen is not None and gen != self.table_gen:
                if chunk - used > 0:
                    self._expired.append((row, now_ms, chunk - used, is_in))
                self.fast_admits += 1
                return
            b = self.bucket_of(now_ms)
            lease = self._leases.get(row)
            if (lease is not None and lease.bucket_idx == b
                    and lease.is_in == is_in):
                lease.remaining += chunk - used
            else:
                if lease is not None:
                    self._retire_lease_locked(row, lease)
                self._leases[row] = _Lease(b, chunk - used, is_in, now_ms)
            self.lease_renewals += 1
            self.fast_admits += 1

    def mark_hot(self, row: int, now_ms: int) -> None:
        with self._lock:
            self._hot_bucket[row] = self.bucket_of(now_ms)
            lease = self._leases.pop(row, None)
            if lease is not None:
                self._retire_lease_locked(row, lease)

    def _collect_expired_locked(self, drop_all: bool = False,
                                now_ms: Optional[int] = None) -> None:
        b = None if now_ms is None else self.bucket_of(now_ms)
        for row in list(self._leases):
            lease = self._leases[row]
            if drop_all or lease.bucket_idx != b:
                del self._leases[row]
                self._retire_lease_locked(row, lease)

    def expire_all(self) -> None:
        """Reconcile every live lease (snapshot save / shutdown): unused
        tokens queue for window reversal at the next flush."""
        with self._lock:
            self._collect_expired_locked(drop_all=True)

    # ---------------------------------------------------------------- buffers
    def buffer_pass(self, row: int, o_row: int, c_row: int, acquire: int,
                    is_in: bool, now_ms: int) -> None:
        with self._lock:
            if not self._pass_buf and not self._exit_buf:
                self._buf_bucket = self.bucket_of(now_ms)
            self._pass_buf.append((row, o_row, c_row, acquire, is_in, now_ms))
            self.fast_admits += 1

    def buffer_exit(self, row: int, o_row: int, c_row: int, acquire: int,
                    rt_ms: int, error: bool, is_in: bool,
                    count_thread: bool, now_ms: int) -> None:
        with self._lock:
            if not self._pass_buf and not self._exit_buf:
                self._buf_bucket = self.bucket_of(now_ms)
            self._exit_buf.append((row, o_row, c_row, acquire, rt_ms, error,
                                   is_in, count_thread, now_ms))

    def due(self, now_ms: int) -> bool:
        if self._expired:
            return True            # unused lease tokens awaiting reversal
        n = len(self._pass_buf) + len(self._exit_buf)
        if n == 0:
            return False
        if n >= self.flush_events:
            return True
        # bucket rotation: flush BEFORE buffering into a new window slice so
        # each flush group shares one time stamp (exact window attribution)
        if self.bucket_of(now_ms) != self._buf_bucket:  # graftlint: disable=LOCK002 -- stale-tolerant flush heuristic; a missed rotation is caught by the next due() call
            return True
        return now_ms - self._last_flush_ms >= self.flush_ms

    def drain(self, now_ms: int):
        """→ (passes, exits, expired_leases) and reset (caller dispatches
        them to device; expired leases' unused tokens are subtracted back
        from their window buckets)."""
        with self._lock:
            self._collect_expired_locked(now_ms=now_ms)
            p, self._pass_buf = self._pass_buf, []
            x, self._exit_buf = self._exit_buf, []
            e, self._expired = self._expired, []
            self._last_flush_ms = now_ms
            return p, x, e
