"""Global concurrency tokens: cluster-wide in-flight call limiting.

Reference (``sentinel-cluster-server-default``):

* ``ConcurrentClusterFlowChecker`` (``flow/ConcurrentClusterFlowChecker.java:26-80``):
  ``acquire`` — if ``nowCalls + acquireCount > calcGlobalThreshold(rule)``
  (count, or count × connectedCount for AVG_LOCAL) → BLOCKED; else add and
  mint a ``TokenCacheNode`` with a fresh tokenId; ``release(tokenId)`` —
  missing node → ALREADY_RELEASE, else decrement → RELEASE_OK.
* ``TokenCacheNodeManager`` (ConcurrentLinkedHashMap of tokenId → node) +
  ``RegularExpireStrategy`` (scheduled sweep deleting expired borrows and
  returning their permits) — **the only lease/expiry GC in the system**
  (SURVEY §5): it reclaims tokens from clients that died mid-call.

TPU-native placement: concurrency state is *host* state by design. Unlike the
windowed QPS counters (dense tensors, device), ``nowCalls`` is a handful of
scalars mutated by acquire/release pairs at call rate, and the lease table is
a dict with TTLs — the reference itself serializes acquires on a lock
(``synchronized (nowCalls)``). The host runtime owns it; the device engine
owns the windowed statistics. Sweeps are vectorized over numpy lease arrays.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sentinel_tpu.parallel.cluster import (
    STATUS_ALREADY_RELEASE, STATUS_BLOCKED, STATUS_FAIL, STATUS_NO_RULE_EXISTS,
    STATUS_OK, STATUS_RELEASE_OK, THRESHOLD_GLOBAL,
)

# ClusterFlowConfig.resourceTimeout default (cluster/flow/rule/ClusterFlowConfig.java)
DEFAULT_RESOURCE_TIMEOUT_MS = 2000


@dataclasses.dataclass
class ConcurrentFlowRule:
    """Concurrency-grade cluster rule (FlowRule with GRADE_THREAD + cluster
    config: flowId, thresholdType, resourceTimeout)."""

    flow_id: int
    count: float
    threshold_type: int = THRESHOLD_GLOBAL
    resource_timeout_ms: int = DEFAULT_RESOURCE_TIMEOUT_MS


@dataclasses.dataclass
class TokenLease:
    """TokenCacheNode: one outstanding borrow."""

    token_id: int
    flow_id: int
    acquire: int
    client_address: str
    expire_at_ms: int


class ConcurrentTokenManager:
    """CurrentConcurrencyManager + TokenCacheNodeManager + expire sweep."""

    def __init__(self, *, connected_count: int = 1):
        self._lock = threading.Lock()
        self._rules: Dict[int, ConcurrentFlowRule] = {}
        self._now_calls: Dict[int, int] = {}
        self._leases: Dict[int, TokenLease] = {}
        self._token_ids = itertools.count(1)
        self._connected: Dict[int, int] = {}
        self._default_connected = max(1, connected_count)

    # ------------------------------------------------------------------
    def load_rules(self, rules: Sequence[ConcurrentFlowRule]) -> None:
        """Replace the rule set; nowCalls of surviving flows are preserved
        (CurrentConcurrencyManager keeps counters across rule updates)."""
        with self._lock:
            keep = {r.flow_id for r in rules}
            self._rules = {r.flow_id: r for r in rules}
            for fid in list(self._now_calls):
                if fid not in keep:
                    del self._now_calls[fid]
            for fid in keep:
                self._now_calls.setdefault(fid, 0)

    def set_connected_count(self, flow_id: int, count: int) -> None:
        with self._lock:
            self._connected[flow_id] = max(1, count)

    def _threshold(self, rule: ConcurrentFlowRule) -> float:
        if rule.threshold_type == THRESHOLD_GLOBAL:
            return rule.count
        conn = self._connected.get(rule.flow_id, self._default_connected)
        return rule.count * conn

    # ------------------------------------------------------------------
    def acquire(self, flow_id: int, acquire: int, *, client_address: str = "",
                now_ms: int) -> Tuple[int, int]:
        """→ (status, token_id). OK mints a lease; BLOCKED/FAIL → token 0."""
        if acquire <= 0:
            return STATUS_FAIL, 0
        with self._lock:
            rule = self._rules.get(flow_id)
            if rule is None or flow_id not in self._now_calls:
                return STATUS_FAIL, 0
            if self._now_calls[flow_id] + acquire > self._threshold(rule):
                return STATUS_BLOCKED, 0
            self._now_calls[flow_id] += acquire
            tid = next(self._token_ids)
            self._leases[tid] = TokenLease(
                token_id=tid, flow_id=flow_id, acquire=acquire,
                client_address=client_address,
                expire_at_ms=now_ms + rule.resource_timeout_ms)
            return STATUS_OK, tid

    def release(self, token_id: int) -> int:
        """→ status (RELEASE_OK / ALREADY_RELEASE / NO_RULE_EXISTS)."""
        with self._lock:
            lease = self._leases.pop(token_id, None)
            if lease is None:
                return STATUS_ALREADY_RELEASE
            if lease.flow_id not in self._rules:
                return STATUS_NO_RULE_EXISTS
            self._now_calls[lease.flow_id] = max(
                0, self._now_calls.get(lease.flow_id, 0) - lease.acquire)
            return STATUS_RELEASE_OK

    # ------------------------------------------------------------------
    def sweep_expired(self, *, now_ms: int) -> int:
        """RegularExpireStrategy: reclaim permits from expired leases.

        Vectorized: one pass over lease arrays, then dict surgery on the
        expired subset. Returns the number of leases reclaimed."""
        with self._lock:
            if not self._leases:
                return 0
            tids = np.fromiter(self._leases, np.int64, count=len(self._leases))
            exp = np.fromiter((l.expire_at_ms for l in self._leases.values()),
                              np.int64, count=len(self._leases))
            dead = tids[exp <= now_ms]
            for tid in dead.tolist():
                lease = self._leases.pop(tid)
                if lease.flow_id in self._now_calls:
                    self._now_calls[lease.flow_id] = max(
                        0, self._now_calls[lease.flow_id] - lease.acquire)
            return int(dead.size)

    # ------------------------------------------------------------------
    def now_calls(self, flow_id: int) -> int:
        with self._lock:
            return self._now_calls.get(flow_id, 0)

    def lease_count(self) -> int:
        with self._lock:
            return len(self._leases)

    def leases_of(self, client_address: str) -> List[TokenLease]:
        with self._lock:
            return [l for l in self._leases.values()
                    if l.client_address == client_address]
