"""Shared row-shard math for every sharded engine tier.

One implementation of the three pieces of arithmetic that every
row-sharded surface in this repo needs, extracted so the single-process
virtual-mesh engines (:mod:`sentinel_tpu.parallel.cluster`,
:mod:`sentinel_tpu.parallel.local_shard`) and the multi-process runtime
(:mod:`sentinel_tpu.multihost`) cannot drift apart:

* **ownership** — a global row lives on shard ``row // rows_per_shard``
  at local position ``row % rows_per_shard`` (contiguous slabs, the
  layout ``NamedSharding(mesh, P(axis))`` gives a ``[S·L, ...]`` tensor);
* **geometry validation** — row dimensions must divide over the mesh
  axis, with an actionable error;
* **request routing** — grouping a flat request stream into the dense
  ``[S, Bl]`` per-shard lane layout the sharded device step consumes,
  and scattering the ``[S, Bl]`` verdicts back into request order.

The routing plan is a pure function of the request ids and the geometry,
so every host in a multi-process mesh computes the IDENTICAL plan from
the shared stream metadata while materializing payload lanes only for
the shards it owns (host-local ingestion, ``multihost/ingest.py``).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from sentinel_tpu.core.batching import pad_pow2


def owner_shard(global_rows, rows_per_shard: int):
    """Shard index owning each global row (contiguous-slab layout)."""
    return global_rows // rows_per_shard


def local_row(global_rows, rows_per_shard: int):
    """Row position within the owner shard's local slab."""
    return global_rows % rows_per_shard


def validate_divisible(name: str, dim: int, n_shards: int,
                       hint: str = "") -> None:
    """Fail fast (with a fix) when a row dimension can't shard evenly."""
    if dim % n_shards:
        raise ValueError(
            f"{name}={dim} does not divide over {n_shards} mesh devices; "
            + (hint or f"round {name} up to a multiple of {n_shards}"))


class RoutedLanes(NamedTuple):
    """Dense per-shard lane arrays, shape ``[S, Bl]`` (``Bl`` = power of
    two ≥ the busiest shard's request count). Flattened on axis 0 they
    feed a row-sharded ``TokenBatch`` directly."""

    rows: np.ndarray         # int32[S, Bl] — local row within owner shard
    acquire: np.ndarray      # int32[S, Bl]
    prioritized: np.ndarray  # bool[S, Bl]
    valid: np.ndarray        # bool[S, Bl]
    lanes: int               # Bl


class RoutingPlan(NamedTuple):
    """Everything needed to scatter ``[S, Bl]`` verdicts back into the
    original request order. ``status0`` carries the host-predecided
    status per request (bad-request / no-rule); routed requests keep the
    fail placeholder and are overwritten by the device verdict."""

    src: np.ndarray          # int64[m] — original index of routed request
    shard: np.ndarray        # int64[m] — owner shard (sorted, stable)
    lane: np.ndarray         # int64[m] — lane within the shard
    status0: np.ndarray      # int64[n] — predecided status per request


def route_requests(
        rowg: np.ndarray, acquire: np.ndarray, prioritized: np.ndarray,
        n_shards: int, rows_per_shard: int, *,
        status_fail: int, status_bad: int, status_no_rule: int,
) -> Tuple[Optional[RoutedLanes], RoutingPlan]:
    """Group a flat request stream into per-shard lanes (vectorized).

    ``rowg`` holds each request's GLOBAL row, ``< 0`` for unroutable ids.
    Returns ``(lanes, plan)``; ``lanes is None`` when nothing is
    routable (``plan.status0`` is then final). One argsort + one scatter
    — no per-request Python loop.
    """
    n = rowg.shape[0]
    acq_arr = np.asarray(acquire, np.int64)
    prio_arr = (np.asarray(prioritized, np.bool_) if prioritized is not None
                else np.zeros(n, np.bool_))
    bad = acq_arr <= 0
    norule = (rowg < 0) & ~bad
    status0 = np.where(
        bad, status_bad,
        np.where(norule, status_no_rule, status_fail)).astype(np.int64)
    ok = ~bad & ~norule
    if not ok.any():
        return None, RoutingPlan(
            src=np.zeros(0, np.int64), shard=np.zeros(0, np.int64),
            lane=np.zeros(0, np.int64), status0=status0)
    idx_ok = np.nonzero(ok)[0]
    sh = rowg[idx_ok] // rows_per_shard
    order = np.argsort(sh, kind="stable")
    sh_s = sh[order]
    counts = np.bincount(sh_s, minlength=n_shards)
    blp = pad_pow2(int(counts.max()))
    starts = np.zeros(n_shards, np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    pos = np.arange(sh_s.shape[0], dtype=np.int64) - np.repeat(starts, counts)
    src = idx_ok[order]
    rows = np.zeros((n_shards, blp), np.int32)
    acq2 = np.zeros((n_shards, blp), np.int32)
    prio2 = np.zeros((n_shards, blp), np.bool_)
    valid2 = np.zeros((n_shards, blp), np.bool_)
    rows[sh_s, pos] = (rowg[src] % rows_per_shard).astype(np.int32)
    acq2[sh_s, pos] = acq_arr[src].astype(np.int32)
    prio2[sh_s, pos] = prio_arr[src]
    valid2[sh_s, pos] = True
    return (RoutedLanes(rows=rows, acquire=acq2, prioritized=prio2,
                        valid=valid2, lanes=blp),
            RoutingPlan(src=src, shard=sh_s, lane=pos, status0=status0))


def scatter_verdicts(plan: RoutingPlan, lanes: int,
                     status: np.ndarray, wait_ms: np.ndarray,
                     remaining: np.ndarray,
                     n_shards: int) -> List[Tuple[int, int, int]]:
    """Inverse of :func:`route_requests`: fold ``[S·Bl]`` verdict arrays
    back into request order → aligned ``(status, wait_ms, remaining)``."""
    st = np.asarray(status).reshape(n_shards, lanes)
    wt = np.asarray(wait_ms).reshape(n_shards, lanes)
    rm = np.asarray(remaining).reshape(n_shards, lanes)
    n = plan.status0.shape[0]
    st_o = plan.status0.copy()
    wt_o = np.zeros(n, np.int64)
    rm_o = np.zeros(n, np.int64)
    st_o[plan.src] = st[plan.shard, plan.lane]
    wt_o[plan.src] = wt[plan.shard, plan.lane]
    rm_o[plan.src] = rm[plan.shard, plan.lane]
    return list(zip(st_o.tolist(), wt_o.tolist(), rm_o.tolist()))


def mask_to_local_lanes(lanes: RoutedLanes, plan: RoutingPlan,
                        local_shards: Sequence[int]) -> RoutedLanes:
    """Host-local ingestion: zero every lane NOT owned by this process.

    In a multi-process mesh each host's ``device_put`` only materializes
    the shards it owns, so non-local lanes of the host-side arrays are
    never read by any device — zeroing them documents (and enforces)
    that only the local slice of the payload has to exist on this host.
    """
    keep = np.zeros(lanes.rows.shape[0], np.bool_)
    keep[np.asarray(list(local_shards), np.int64)] = True
    k = keep[:, None]
    return RoutedLanes(
        rows=np.where(k, lanes.rows, 0),
        acquire=np.where(k, lanes.acquire, 0),
        prioritized=np.where(k, lanes.prioritized, False),
        valid=np.where(k, lanes.valid, False),
        lanes=lanes.lanes)
