"""Sharded cluster token engine: the TPU-native ClusterFlowChecker.

Reference semantics being reproduced (``sentinel-cluster-server-default``):

* ``ClusterFlowChecker.acquireClusterToken`` (``flow/ClusterFlowChecker.java:55-112``):
  threshold = ``calcGlobalThreshold(rule) × exceedCount`` where the global
  threshold is ``count`` (GLOBAL) or ``count × connectedCount`` (AVG_LOCAL);
  pass ⇒ add PASS/PASS_REQUEST (+OCCUPIED_PASS when prioritized); prioritized
  deficit ⇒ ``tryOccupyNext`` → SHOULD_WAIT(waitInMs) bounded by
  ``maxOccupyRatio``; else BLOCK/BLOCK_REQUEST.
* ``GlobalRequestLimiter`` (``server/connection/../GlobalRequestLimiter.java``):
  per-namespace inbound token-request QPS self-protection (default 30,000/s,
  ``ServerFlowConfig.java:26-31``) → TOO_MANY_REQUEST.
* ``ClusterMetric`` (``statistic/metric/ClusterMetric.java``): 10×100 ms
  LeapArray of ClusterFlowEvent counters — here the same
  :mod:`sentinel_tpu.stats.window` dense tensors used by the local engine.

TPU-native shape (SURVEY §2.8 north star): flow counters live in ONE window
tensor of rows = ``n_shards × flows_per_shard``, sharded over the mesh axis
``"shard"`` on the row dimension — each device owns its flows' counters, so
per-flow admission is an entirely local greedy segment scan (no collective on
the critical path). The *namespace* request-limiter counters are
shard-local tensors whose pod-global totals are combined with ``lax.psum``
over ICI inside ``shard_map`` — the reference's single-JVM global view,
rebuilt as a collective.

The host routes each token request to its flow's owner shard by batch
position (``ClusterEngine.request_tokens``); cross-shard prefix interaction in
the namespace limiter is ignored within one batch step, a bounded
over-admission of the same class the reference tolerates
(``FlowRuleChecker.java:89`` comment).
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level (kwarg: check_vma)
    from jax import shard_map as _shard_map_impl
    _SM_CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover — older jax (kwarg: check_rep)
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _SM_CHECK_KW = "check_rep"


def _shard_map(body, *, mesh, in_specs, out_specs, check_vma=True):
    """shard_map across jax versions: ``check_vma`` (≥ 0.6) and its
    predecessor ``check_rep`` are the same switch under different names."""
    return _shard_map_impl(body, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs,
                           **{_SM_CHECK_KW: check_vma})

from sentinel_tpu.core.pending import PendingResult, start_host_copy
from sentinel_tpu.ops import segments as seg
from sentinel_tpu.parallel import shard_math
from sentinel_tpu.stats import events as ev
from sentinel_tpu.stats.window import (
    WindowSpec, WindowState, init_window, valid_mask, window_sum_all,
)

# TokenResultStatus parity (CORE/cluster/TokenResultStatus.java)
STATUS_BAD_REQUEST = -4
STATUS_TOO_MANY_REQUEST = -2
STATUS_FAIL = -1
STATUS_OK = 0
STATUS_BLOCKED = 1
STATUS_SHOULD_WAIT = 2
STATUS_NO_RULE_EXISTS = 3
STATUS_NO_REF_RULE_EXISTS = 4
STATUS_NOT_AVAILABLE = 5
STATUS_RELEASE_OK = 6
STATUS_ALREADY_RELEASE = 7

# thresholdType (ClusterRuleConstant)
THRESHOLD_AVG_LOCAL = 0
THRESHOLD_GLOBAL = 1

# ClusterMetric geometry: sampleCount 10 × interval 1000 ms
CLUSTER_WINDOW = WindowSpec(buckets=10, win_ms=100, track_rt=False)


@dataclasses.dataclass(frozen=True)
class ClusterSpec:
    """Static sharded-engine geometry (hashable, closed over by jit)."""

    n_shards: int
    flows_per_shard: int          # L — flow rows owned per shard
    namespaces: int               # NS — namespace slots
    window: WindowSpec = CLUSTER_WINDOW
    param_keys_per_shard: int = 0  # PK — hot-key rows per shard (0 = off)
    max_params: int = 4            # PV — values per param request

    @property
    def total_rows(self) -> int:
        return self.n_shards * self.flows_per_shard

    @property
    def total_param_rows(self) -> int:
        return self.n_shards * max(1, self.param_keys_per_shard)


class ClusterRuleTable(NamedTuple):
    """Device rule arrays, row-sharded like the counters ([S·L])."""

    active: jnp.ndarray        # bool[S·L]
    count: jnp.ndarray         # float32 — rule threshold
    is_global: jnp.ndarray     # bool — GLOBAL vs AVG_LOCAL
    exceed: jnp.ndarray        # float32 — exceedCount factor
    max_occupy: jnp.ndarray    # float32 — maxOccupyRatio
    ns_id: jnp.ndarray         # int32 — owning namespace


class ClusterState(NamedTuple):
    flows: WindowState             # rows = S·L (sharded on rows)
    ns: WindowState                # rows = S·NS (sharded: NS local rows/shard)
    params: WindowState            # rows = S·PK — hot-key counters


class TokenBatch(NamedTuple):
    """Routed request batch, arrays [S·Bl] sharded on axis 0."""

    local_rows: jnp.ndarray    # int32 — row within the owner shard [0, L)
    acquire: jnp.ndarray       # int32
    prioritized: jnp.ndarray   # bool
    valid: jnp.ndarray         # bool
    is_param: jnp.ndarray      # bool — PARAM_FLOW request (param path)
    param_rows: jnp.ndarray    # int32[S·Bl, PV] — local key row; PK = none
    param_count: jnp.ndarray   # float32[S·Bl, PV] — raw per-value threshold


class TokenVerdicts(NamedTuple):
    status: jnp.ndarray        # int32[S·Bl] — TokenResultStatus codes
    wait_ms: jnp.ndarray       # int32[S·Bl]
    remaining: jnp.ndarray     # int32[S·Bl]


def init_cluster_state(spec: ClusterSpec) -> ClusterState:
    return ClusterState(
        flows=init_window(spec.window, spec.total_rows),
        ns=init_window(spec.window, spec.n_shards * spec.namespaces),
        params=init_window(spec.window, spec.total_param_rows),
    )


def _shard_step(
    spec: ClusterSpec,
    table: ClusterRuleTable,
    state: ClusterState,
    batch: TokenBatch,
    connected: jnp.ndarray,     # float32[NS] replicated
    ns_limit: jnp.ndarray,      # float32[NS] replicated
    now_idx: jnp.ndarray,       # int32 scalar
    in_win_ms: jnp.ndarray,     # int32 scalar — ms elapsed inside current window
) -> Tuple[ClusterState, TokenVerdicts]:
    """Per-shard body (runs under shard_map; local views)."""
    w = spec.window
    L = table.active.shape[0]       # local flow rows
    NS = spec.namespaces
    Bl = batch.local_rows.shape[0]  # local batch

    rows = jnp.where(batch.valid, batch.local_rows, 0)
    active = table.active[rows] & batch.valid
    ns_req = jnp.where(active, table.ns_id[rows], NS)  # NS = inapplicable seg

    # ---- GlobalRequestLimiter: pod-global per-namespace request QPS (psum) ----
    ns_local = window_sum_all(w, state.ns, ev.PASS, now_idx).astype(jnp.float32)
    ns_global = lax.psum(ns_local, "shard")                       # [NS]
    ns_base = jnp.concatenate([ns_global, jnp.zeros((1,), jnp.float32)])
    ns_lim = jnp.concatenate([ns_limit, jnp.full((1,), jnp.inf, jnp.float32)])

    order_ns = seg.sort_by_keys(ns_req)
    ns_s = ns_req[order_ns]
    starts_ns = seg.segment_starts(ns_s, jnp.zeros_like(ns_s))
    leader_ns = seg.segment_leader_index(starts_ns)
    ones = jnp.where(active, 1.0, 0.0)[order_ns]
    limiter_ok_s = seg.greedy_admit(ns_base[ns_s], ones, ns_lim[ns_s],
                                    starts_ns, leader_ns)
    limiter_ok = seg.unsort(order_ns, limiter_ok_s.astype(jnp.int32)).astype(jnp.bool_)
    proceed = active & limiter_ok

    # ---- per-flow admission (ClusterFlowChecker.acquireClusterToken) ----
    flow_req = proceed & ~batch.is_param
    latest = window_sum_all(w, state.flows, ev.PASS, now_idx).astype(jnp.float32)  # [L]
    conn = connected[jnp.minimum(table.ns_id, NS - 1)]
    thr_rule = table.count * jnp.where(table.is_global, 1.0, conn) * table.exceed  # [L]

    seg_rows = jnp.where(flow_req, rows, L)  # L = never-blocking sentinel segment
    order = seg.sort_by_keys(seg_rows)
    rows_s = seg_rows[order]
    starts = seg.segment_starts(rows_s, jnp.zeros_like(rows_s))
    leader = seg.segment_leader_index(starts)
    acq_s = jnp.where(flow_req, batch.acquire, 0).astype(jnp.float32)[order]
    safe_rows_s = jnp.minimum(rows_s, L - 1)
    base_s = latest[safe_rows_s]
    lim_s = jnp.where(rows_s < L, thr_rule[safe_rows_s], jnp.inf)
    admit_s = seg.greedy_admit(base_s, acq_s, lim_s, starts, leader)
    excl_s, _ = seg.segment_prefix_sum(jnp.where(admit_s, acq_s, 0.0), starts, leader)
    remaining_s = lim_s - base_s - excl_s - acq_s
    admitted = seg.unsort(order, admit_s.astype(jnp.int32)).astype(jnp.bool_) & flow_req
    remaining = jnp.where(jnp.isfinite(remaining_s), remaining_s, 0.0)
    remaining = seg.unsort(order, remaining.astype(jnp.int32))

    # ---- occupy: prioritized deficit pre-books future windows ----
    denied = flow_req & ~admitted
    waiting_sum = window_sum_all(w, state.flows, ev.WAITING, now_idx).astype(jnp.float32)
    occupy_open = waiting_sum[rows] <= table.max_occupy[rows] * thr_rule[rows]
    # expiry scan: waiting until bucket k (stamp s_k) rotates out frees its
    # PASS count at wait = (s_k - now_idx + B)·win - in_win_ms
    stamps_req = state.flows.stamps[rows]                       # [Bl, B]
    pass_req = state.flows.counters[rows, :, ev.PASS]           # [Bl, B]
    live = valid_mask(w, stamps_req, now_idx)
    delta = jnp.where(live, stamps_req - now_idx, jnp.int32(0))  # [-B+1, 0]
    # freed(k) = sum of pass in buckets expiring no later than bucket k
    freed = jnp.sum(
        jnp.where(live[:, None, :] & (delta[:, None, :] <= delta[:, :, None]),
                  pass_req[:, None, :], 0), axis=2).astype(jnp.float32)  # [Bl, B]
    total_pass = latest[rows][:, None]
    fits = (total_pass - freed + batch.acquire[:, None].astype(jnp.float32)
            <= thr_rule[rows][:, None]) & live
    wait_k = (delta + w.buckets) * w.win_ms - in_win_ms          # [Bl, B]
    wait_k = jnp.where(fits & (wait_k > 0), wait_k, jnp.int32(2 ** 30))
    best_wait = jnp.min(wait_k, axis=1)
    should_wait = (denied & batch.prioritized & occupy_open
                   & (best_wait < 2 ** 30))
    wait_ms = jnp.where(should_wait, best_wait, 0)

    blocked = denied & ~should_wait

    # ---- hot-param admission (ClusterParamFlowChecker.acquireClusterToken) ----
    # Per-value avg vs calcGlobalThreshold; a request passes iff EVERY carried
    # value fits, and only then are all its values counted (reference
    # semantics; the host resolves per-item threshold overrides into
    # ``param_count``). Values are hashed onto PK local key rows; within one
    # batch step concurrent requests on a shared key over-admit — the same
    # check-then-act class the reference tolerates across threads.
    PK = spec.param_keys_per_shard
    is_p = proceed & batch.is_param
    pstate = state.params
    if PK:
        latest_p = window_sum_all(w, pstate, ev.PASS, now_idx).astype(jnp.float32)
        prow = batch.param_rows                               # [Bl, PV]
        live = (prow >= 0) & (prow < PK) & is_p[:, None]
        thr_p = batch.param_count * jnp.where(
            table.is_global[rows], 1.0, conn[rows])[:, None]  # [Bl, PV]
        acq_f = batch.acquire.astype(jnp.float32)[:, None]

        # within-batch exact admission: greedy segment admit over flattened
        # (request × value) rows sharing a key, like the flow path. A value
        # row admitted for a request that ultimately fails on ANOTHER value
        # still reserves quota within this batch (bounded under-admission) —
        # but its count is never recorded, so nothing leaks across steps.
        flat_keys = jnp.where(live, prow, PK).reshape(-1)     # [Bl·PV]
        order_p = seg.sort_by_keys(flat_keys)
        keys_s = flat_keys[order_p]
        starts_p = seg.segment_starts(keys_s, jnp.zeros_like(keys_s))
        leader_p = seg.segment_leader_index(starts_p)
        acq_flat_s = jnp.where(live, acq_f, 0.0).reshape(-1)[order_p]
        safe_keys_s = jnp.minimum(keys_s, PK - 1)
        base_s = latest_p[safe_keys_s]
        lim_s = jnp.where(keys_s < PK, thr_p.reshape(-1)[order_p], jnp.inf)
        ok_s = seg.greedy_admit(base_s, acq_flat_s, lim_s, starts_p, leader_p)
        excl_p, _ = seg.segment_prefix_sum(
            jnp.where(ok_s, acq_flat_s, 0.0), starts_p, leader_p)
        rem_flat_s = lim_s - base_s - excl_p - acq_flat_s
        row_ok = seg.unsort(order_p, ok_s.astype(jnp.int32)).reshape(
            (Bl, -1)).astype(jnp.bool_)
        rem_flat = seg.unsort(
            order_p, jnp.where(jnp.isfinite(rem_flat_s), rem_flat_s, 0.0)
        ).reshape((Bl, -1))

        any_live = jnp.any(live, axis=1)
        all_ok = jnp.all(row_ok | ~live, axis=1)
        param_pass = is_p & (all_ok | ~any_live)
        param_block = is_p & any_live & ~all_ok
        # remaining meaningful only for single-value requests (host packs
        # values densely from column 0); multi-value → -1 like the reference
        nlive = jnp.sum(live.astype(jnp.int32), axis=1)
        rem1 = jnp.maximum(rem_flat[:, 0], 0.0)
        rem_p = jnp.where(nlive == 1, rem1, -1.0).astype(jnp.int32)

        from sentinel_tpu.stats.window import add_rows as _add, refresh_rows as _refresh
        flat = jnp.where(live & param_pass[:, None], prow, PK).reshape(-1)
        pstate = _refresh(w, pstate, flat, now_idx)
        pstate = _add(w, pstate, flat, ev.PASS,
                      jnp.where(live & param_pass[:, None],
                                batch.acquire[:, None], 0).reshape(-1), now_idx)
    else:
        param_pass = is_p          # param slot disabled: empty-values → OK
        param_block = jnp.zeros_like(is_p)
        rem_p = jnp.full((Bl,), -1, jnp.int32)

    # ---- record (post-decision, like StatisticSlot ordering) ----
    pad = jnp.int32(L)
    def tgt(mask):
        return jnp.where(mask, rows, pad)

    flows = state.flows
    from sentinel_tpu.stats.window import add_rows, refresh_rows
    flows = refresh_rows(w, flows, tgt(proceed), now_idx)
    acq = batch.acquire
    flows = add_rows(w, flows, tgt(admitted), ev.PASS, jnp.where(admitted, acq, 0), now_idx)
    flows = add_rows(w, flows, tgt(admitted), ev.PASS_REQUEST,
                     jnp.where(admitted, 1, 0), now_idx)
    flows = add_rows(w, flows, tgt(admitted & batch.prioritized), ev.OCCUPIED_PASS,
                     jnp.where(admitted & batch.prioritized, acq, 0), now_idx)
    flows = add_rows(w, flows, tgt(blocked), ev.BLOCK, jnp.where(blocked, acq, 0), now_idx)
    flows = add_rows(w, flows, tgt(blocked), ev.BLOCK_REQUEST,
                     jnp.where(blocked, 1, 0), now_idx)
    flows = add_rows(w, flows, tgt(should_wait), ev.WAITING,
                     jnp.where(should_wait, acq, 0), now_idx)

    ns_state = state.ns
    ns_state = refresh_rows(w, ns_state, ns_req, now_idx)
    ns_state = add_rows(w, ns_state, jnp.where(proceed, ns_req, jnp.int32(NS)),
                        ev.PASS, jnp.where(proceed, 1, 0), now_idx)
    ns_state = add_rows(w, ns_state, jnp.where(active & ~limiter_ok, ns_req, jnp.int32(NS)),
                        ev.BLOCK, jnp.where(active & ~limiter_ok, 1, 0), now_idx)

    status = jnp.full((Bl,), STATUS_FAIL, jnp.int32)
    status = jnp.where(batch.valid & ~table.active[rows], STATUS_NO_RULE_EXISTS, status)
    status = jnp.where(active & ~limiter_ok, STATUS_TOO_MANY_REQUEST, status)
    status = jnp.where(blocked, STATUS_BLOCKED, status)
    status = jnp.where(should_wait, STATUS_SHOULD_WAIT, status)
    status = jnp.where(admitted, STATUS_OK, status)
    status = jnp.where(param_block, STATUS_BLOCKED, status)
    status = jnp.where(param_pass, STATUS_OK, status)

    remaining = jnp.where(admitted, jnp.maximum(remaining, 0), 0)
    remaining = jnp.where(param_pass | param_block,
                          jnp.where(param_pass, rem_p, 0), remaining)
    verdicts = TokenVerdicts(
        status=status,
        wait_ms=wait_ms.astype(jnp.int32),
        remaining=remaining.astype(jnp.int32))
    return ClusterState(flows=flows, ns=ns_state, params=pstate), verdicts


@dataclasses.dataclass
class ClusterParamFlowRule:
    """Cluster hot-param rule (reference ``ParamFlowRule`` cluster fields:
    flowId, thresholdType, count, plus exclusive per-item thresholds —
    ``parsedHotItems``)."""

    flow_id: int
    count: float
    threshold_type: int = THRESHOLD_AVG_LOCAL
    items: Optional[Dict[object, float]] = None

    def value_threshold(self, value: object) -> float:
        if self.items is not None:
            override = self.items.get(value)
            if override is not None:
                return float(override)
        return float(self.count)


@dataclasses.dataclass
class ClusterFlowRule:
    """Host-facing cluster rule (reference ``FlowRule`` cluster fields +
    ``ClusterFlowConfig``: flowId, thresholdType, count; exceedCount and
    maxOccupyRatio come from ``ClusterServerConfigManager`` server-wide but are
    kept per-rule here, defaulting to the reference's 1.0/1.0)."""

    flow_id: int
    count: float
    threshold_type: int = THRESHOLD_AVG_LOCAL
    exceed_count: float = 1.0
    max_occupy_ratio: float = 1.0


class PendingTokenResults(PendingResult):
    """Handle for an in-flight token batch: the device step is already
    dispatched (and the verdict transfer started async); :meth:`result`
    materializes the aligned ``(status, wait_ms, remaining)`` list. Lets
    callers double-buffer — dispatch batch N+1 while batch N's verdicts are
    still in flight over the host link."""

    __slots__ = ()


def _start_host_copy(verdicts: "TokenVerdicts") -> None:
    start_host_copy((verdicts.status, verdicts.wait_ms, verdicts.remaining))


class ClusterEngine:
    """Host facade: flow routing, namespace management, the sharded step.

    The reference's ``ClusterFlowRuleManager`` (flowId→rule, namespace→flowIds,
    per-namespace property suppliers) + ``DefaultTokenService`` dispatch,
    collapsed onto dense sharded tensors.
    """

    def __init__(self, spec: ClusterSpec, mesh: Optional[Mesh] = None,
                 default_ns_qps: float = 30_000.0):
        self.spec = spec
        if mesh is None:
            devs = jax.devices()[:spec.n_shards]
            if len(devs) < spec.n_shards:
                raise ValueError(
                    f"need {spec.n_shards} devices, have {len(devs)}")
            mesh = Mesh(np.array(devs), ("shard",))
        self.mesh = mesh
        # Multi-process mesh (multihost/): state + batches shard across
        # processes; readbacks then go through a cross-process allgather
        # instead of np.asarray (a host can only address its own shards).
        # Rule loads / connected counts MUST be replayed identically on
        # every participating process — the mesh is SPMD, every process
        # executes every step (multihost/ingest.py drives this).
        self._multiprocess = len(
            {d.process_index for d in np.ravel(mesh.devices)}) > 1
        self._sh_rows = NamedSharding(mesh, P("shard"))
        self._sh_rep = NamedSharding(mesh, P())

        self._flow_to_row: Dict[int, int] = {}
        self._row_to_flow: Dict[int, int] = {}
        self._ns_ids: Dict[str, int] = {}
        self._flow_ns: Dict[int, str] = {}
        self._rules: Dict[int, ClusterFlowRule] = {}
        self._param_rules: Dict[int, ClusterParamFlowRule] = {}
        self._fid_lookup = None       # dense fid→row (vectorized prep)
        # host-side hot-value sightings per param flow for metricList's
        # topParams (ClusterParamMetric.getTopValues analog): fid →
        # {value: count} over the current window, previous window kept so
        # a read right after rotation isn't empty
        self._param_hits: Dict[int, Dict[object, int]] = {}
        self._param_hits_prev: Dict[int, Dict[object, int]] = {}
        self._param_hits_win = -1
        self._param_hits_cap = 64     # values tracked per flow (LRU-ish)
        self._connected = np.ones(spec.namespaces, np.float32)
        self._default_ns_qps = float(default_ns_qps)
        self._ns_limit = np.full(spec.namespaces, default_ns_qps, np.float32)
        self._next_row_per_shard = [0] * spec.n_shards
        self._free_rows: List[List[int]] = [[] for _ in range(spec.n_shards)]
        self._rr = 0  # round-robin shard cursor for row allocation
        self._lock = threading.RLock()  # guards state swap (donated buffers),
        # routing tables, and rule reloads against concurrent server threads

        self.state = jax.device_put(init_cluster_state(spec), self._sh_rows)
        self._table = self._empty_table()
        self._step = self._build_step()
        self._row_gather = None  # lazy jitted row snapshot (multiprocess)

    # ------------------------------------------------------------------
    def _empty_table(self) -> ClusterRuleTable:
        n = self.spec.total_rows
        z = np.zeros(n, np.float32)
        return jax.device_put(ClusterRuleTable(
            active=jnp.asarray(np.zeros(n, np.bool_)),
            count=jnp.asarray(z), is_global=jnp.asarray(np.zeros(n, np.bool_)),
            exceed=jnp.asarray(np.ones(n, np.float32)),
            max_occupy=jnp.asarray(np.ones(n, np.float32)),
            ns_id=jnp.asarray(np.zeros(n, np.int32))), self._sh_rows)

    def _build_step(self):
        spec = self.spec
        mesh = self.mesh
        body = functools.partial(_shard_step, spec)
        row_spec = P("shard")
        state_specs = ClusterState(
            flows=WindowState(*([row_spec] * 4)), ns=WindowState(*([row_spec] * 4)),
            params=WindowState(*([row_spec] * 4)))
        table_specs = ClusterRuleTable(*([row_spec] * 6))
        batch_specs = TokenBatch(*([row_spec] * 7))
        sm = _shard_map(
            body, mesh=mesh,
            in_specs=(table_specs, state_specs, batch_specs, P(), P(), P(), P()),
            out_specs=(state_specs, TokenVerdicts(row_spec, row_spec, row_spec)),
            check_vma=False)
        return jax.jit(sm, donate_argnums=(1,))

    # ------------------------------------------------------------------
    # Namespace / rule management
    # ------------------------------------------------------------------

    def namespace_id(self, namespace: str) -> int:
        nid = self._ns_ids.get(namespace)
        if nid is None:
            if len(self._ns_ids) >= self.spec.namespaces:
                raise ValueError("namespace capacity exceeded")
            nid = len(self._ns_ids)
            self._ns_ids[namespace] = nid
        return nid

    def set_connected_count(self, namespace: str, count: int) -> None:
        """ConnectionManager.getConnectedCount feed for AVG_LOCAL thresholds."""
        with self._lock:
            self._connected[self.namespace_id(namespace)] = max(1, count)
        # connected counts are replicated scalars; no table rebuild needed

    def set_namespace_qps_limit(self, namespace: str, limit: float) -> None:
        """ServerFlowConfig.maxAllowedQps per namespace (hot-tunable)."""
        with self._lock:
            self._ns_limit[self.namespace_id(namespace)] = limit

    def namespace_qps_limit(self, namespace: str, *,
                            create: bool = True) -> float:
        """Per-namespace maxAllowedQps. ``create=False`` is a pure read: an
        unregistered namespace returns the default limit without consuming
        one of the ``spec.namespaces`` slots (read-only command-plane
        fetches must not allocate capacity)."""
        with self._lock:
            nid = self._ns_ids.get(namespace)
            if nid is None:
                if not create:
                    return float(self._default_ns_qps)
                nid = self.namespace_id(namespace)
            return float(self._ns_limit[nid])

    def namespace_flow_ids(self, namespace: str) -> List[int]:
        """Flow ids registered under a namespace (flow + param rules)."""
        with self._lock:
            return sorted(fid for fid, ns in self._flow_ns.items()
                          if ns == namespace)

    def namespace_rules(self, namespace: str, *, param: bool = False
                        ) -> Dict[int, object]:
        """Read-only snapshot {flow_id: rule} of what this engine ENFORCES
        for a namespace — ``param=False`` → :class:`ClusterFlowRule` entries
        (excluding param-rule proxy rows), ``param=True`` →
        :class:`ClusterParamFlowRule` entries. The supported surface for
        command-plane fetch/metricList (don't reach into ``_rules``)."""
        with self._lock:
            store = self._param_rules if param else self._rules
            return {fid: store[fid]
                    for fid, ns in sorted(self._flow_ns.items())
                    if ns == namespace and fid in store
                    and (param or fid not in self._param_rules)}

    def load_rules(self, namespace: str, rules: Sequence[ClusterFlowRule]) -> None:
        """Replace the namespace's rules (ClusterFlowRuleManager property path).

        Rows of removed flows go to a free list for reuse; their window state
        is invalidated immediately so a reused row can't inherit the dead
        flow's live counters.
        """
        with self._lock:
            self.namespace_id(namespace)
            freed: List[int] = []
            for fid, ns in list(self._flow_ns.items()):
                if (ns == namespace and fid not in {r.flow_id for r in rules}
                        and fid not in self._param_rules):
                    row = self._flow_to_row.pop(fid)
                    self._row_to_flow.pop(row, None)
                    self._flow_ns.pop(fid)
                    self._rules.pop(fid, None)
                    self._free_rows[row // self.spec.flows_per_shard].append(row)
                    freed.append(row)
            for r in rules:
                if r.flow_id not in self._flow_to_row:
                    self._flow_to_row[r.flow_id] = self._alloc_row()
                    self._row_to_flow[self._flow_to_row[r.flow_id]] = r.flow_id
                self._flow_ns[r.flow_id] = namespace
                self._rules[r.flow_id] = r
            if freed:
                from sentinel_tpu.stats.window import invalidate_rows
                self.state = self.state._replace(flows=invalidate_rows(
                    self.spec.window, self.state.flows,
                    jnp.asarray(np.asarray(freed, np.int32))))
            self._rebuild_table()

    def load_param_rules(self, namespace: str,
                         rules: Sequence["ClusterParamFlowRule"]) -> None:
        """Replace the namespace's hot-param rules
        (ClusterParamFlowRuleManager property path). Requires
        ``spec.param_keys_per_shard > 0``."""
        if self.spec.param_keys_per_shard <= 0 and rules:
            raise ValueError("engine built without param key capacity")
        with self._lock:
            self.namespace_id(namespace)
            new_ids = {r.flow_id for r in rules}
            freed: List[int] = []
            for fid, ns in list(self._flow_ns.items()):
                if (ns == namespace and fid in self._param_rules
                        and fid not in new_ids):
                    row = self._flow_to_row.pop(fid)
                    self._row_to_flow.pop(row, None)
                    self._flow_ns.pop(fid)
                    self._rules.pop(fid, None)
                    self._param_rules.pop(fid, None)
                    self._free_rows[row // self.spec.flows_per_shard].append(row)
                    freed.append(row)
            if freed:
                from sentinel_tpu.stats.window import invalidate_rows
                self.state = self.state._replace(flows=invalidate_rows(
                    self.spec.window, self.state.flows,
                    jnp.asarray(np.asarray(freed, np.int32))))
            for r in rules:
                if r.flow_id not in self._flow_to_row:
                    self._flow_to_row[r.flow_id] = self._alloc_row()
                    self._row_to_flow[self._flow_to_row[r.flow_id]] = r.flow_id
                self._flow_ns[r.flow_id] = namespace
                self._param_rules[r.flow_id] = r
                # proxy row in the rule table: ns routing + GLOBAL/AVG flag
                self._rules[r.flow_id] = ClusterFlowRule(
                    flow_id=r.flow_id, count=r.count,
                    threshold_type=r.threshold_type)
            self._rebuild_table()

    def _param_key(self, flow_id: int, value: object) -> int:
        """Stable (process-independent) hash of a param value onto the owner
        shard's PK key rows. Type-tagged so ``1`` and ``"1"`` stay distinct."""
        import hashlib

        tag = f"{flow_id}|{type(value).__name__}|{value!r}".encode()
        h = hashlib.blake2s(tag, digest_size=8).digest()
        return int.from_bytes(h, "little") % self.spec.param_keys_per_shard

    def request_param_tokens(self, flow_ids: Sequence[int],
                             acquire: Sequence[int],
                             params: Sequence[Sequence[object]],
                             *, now_ms: int) -> List[Tuple[int, int, int]]:
        """Batched ``TokenService.requestParamToken`` → ``(status, wait_ms,
        remaining)`` per request. Values beyond ``spec.max_params`` per
        request are dropped (cap documented on :class:`ClusterSpec`)."""
        return self.request_param_tokens_nowait(
            flow_ids, acquire, params, now_ms=now_ms).result()

    def request_param_tokens_nowait(
            self, flow_ids: Sequence[int], acquire: Sequence[int],
            params: Sequence[Sequence[object]],
            *, now_ms: int) -> PendingTokenResults:
        """Dispatch-only variant: the sharded step is enqueued and the
        verdict readback deferred to ``.result()`` so callers can overlap
        batch N's readback with batch N+1's host prep + dispatch."""
        from sentinel_tpu.core.batching import pad_pow2

        n = len(flow_ids)
        S = self.spec.n_shards
        L = self.spec.flows_per_shard
        PV = self.spec.max_params
        PK = self.spec.param_keys_per_shard

        with self._lock:
            per_shard: List[List[int]] = [[] for _ in range(S)]
            results: List[Optional[Tuple[int, int, int]]] = [None] * n
            for i, fid in enumerate(flow_ids):
                rule = self._param_rules.get(int(fid))
                if acquire[i] <= 0:
                    results[i] = (STATUS_BAD_REQUEST, 0, 0)
                elif rule is None:
                    results[i] = (STATUS_NO_RULE_EXISTS, 0, 0)
                elif not params[i]:
                    results[i] = (STATUS_OK, 0, 0)   # empty values pass
                else:
                    per_shard[self._flow_to_row[int(fid)] // L].append(i)

            bl = max((len(p) for p in per_shard), default=0)
            if bl == 0:
                out = [r or (STATUS_FAIL, 0, 0) for r in results]
                return PendingTokenResults(lambda: out)
            blp = pad_pow2(bl)

            rows = np.zeros((S, blp), np.int32)
            acq = np.zeros((S, blp), np.int32)
            valid = np.zeros((S, blp), np.bool_)
            is_param = np.zeros((S, blp), np.bool_)
            prow = np.full((S, blp, PV), PK, np.int32)
            pcnt = np.zeros((S, blp, PV), np.float32)
            win = now_ms // (self.spec.window.win_ms
                             * self.spec.window.buckets)
            if win != self._param_hits_win:
                self._param_hits_prev = self._param_hits
                self._param_hits = {}
                self._param_hits_win = win
            for s in range(S):
                for k, i in enumerate(per_shard[s]):
                    fid = int(flow_ids[i])
                    rule = self._param_rules[fid]
                    rows[s, k] = self._flow_to_row[fid] % L
                    acq[s, k] = acquire[i]
                    valid[s, k] = True
                    is_param[s, k] = True
                    hits = self._param_hits.setdefault(fid, {})
                    for j, v in enumerate(list(params[i])[:PV]):
                        prow[s, k, j] = self._param_key(fid, v)
                        pcnt[s, k, j] = rule.value_threshold(v)
                        if v in hits or len(hits) < self._param_hits_cap:
                            hits[v] = hits.get(v, 0) + int(acquire[i])

            batch = jax.device_put(TokenBatch(
                local_rows=jnp.asarray(rows.reshape(-1)),
                acquire=jnp.asarray(acq.reshape(-1)),
                prioritized=jnp.asarray(np.zeros((S * blp,), np.bool_)),
                valid=jnp.asarray(valid.reshape(-1)),
                is_param=jnp.asarray(is_param.reshape(-1)),
                param_rows=jnp.asarray(prow.reshape(S * blp, PV)),
                param_count=jnp.asarray(pcnt.reshape(S * blp, PV))),
                self._sh_rows)

            w = self.spec.window
            now_idx = jnp.int32(w.index_of(now_ms))
            in_win = jnp.int32(now_ms % w.win_ms)
            self.state, verdicts = self._step(
                self._table, self.state, batch,
                jax.device_put(jnp.asarray(self._connected), self._sh_rep),
                jax.device_put(jnp.asarray(self._ns_limit), self._sh_rep),
                now_idx, in_win)
        self._maybe_start_host_copy(verdicts)
        return PendingTokenResults(functools.partial(
            self._gather_results, verdicts, per_shard, results, S, blp))

    def _gather_results(self, verdicts, per_shard, results, S, blp):
        """Deferred readback: materialize the verdict arrays and scatter
        them back into request order (shared by flow + param paths)."""
        st = self._to_host(verdicts.status).reshape(S, blp)
        wt = self._to_host(verdicts.wait_ms).reshape(S, blp)
        rm = self._to_host(verdicts.remaining).reshape(S, blp)
        for s in range(S):
            for k, i in enumerate(per_shard[s]):
                results[i] = (int(st[s, k]), int(wt[s, k]), int(rm[s, k]))
        return [r or (STATUS_FAIL, 0, 0) for r in results]

    def _alloc_row(self) -> int:
        L = self.spec.flows_per_shard
        for _ in range(self.spec.n_shards):
            s = self._rr
            self._rr = (self._rr + 1) % self.spec.n_shards
            if self._free_rows[s]:
                return self._free_rows[s].pop()
            if self._next_row_per_shard[s] < L:
                local = self._next_row_per_shard[s]
                self._next_row_per_shard[s] += 1
                return s * L + local
        raise ValueError("cluster flow capacity exceeded")

    def _rebuild_fid_lookup(self) -> None:
        """Dense flow-id → global-row array for the vectorized request
        prep; None when ids are sparse enough that the array would waste
        memory (the loop path then resolves through the dict)."""
        self._fid_lookup = None
        if not self._flow_to_row:
            return
        if min(self._flow_to_row) < 0:
            return        # negative fids route via the dict; array can't
        max_fid = max(self._flow_to_row)
        if max_fid < max(1 << 20, 4 * len(self._flow_to_row)):
            lut = np.full(max_fid + 1, -1, np.int64)
            for fid, row in self._flow_to_row.items():
                lut[fid] = row
            self._fid_lookup = lut

    def _rebuild_table(self) -> None:
        self._rebuild_fid_lookup()
        n = self.spec.total_rows
        active = np.zeros(n, np.bool_)
        count = np.zeros(n, np.float32)
        is_global = np.zeros(n, np.bool_)
        exceed = np.ones(n, np.float32)
        max_occ = np.ones(n, np.float32)
        ns_id = np.zeros(n, np.int32)
        for fid, row in self._flow_to_row.items():
            r = self._rules[fid]
            active[row] = True
            count[row] = r.count
            is_global[row] = r.threshold_type == THRESHOLD_GLOBAL
            exceed[row] = r.exceed_count
            max_occ[row] = r.max_occupy_ratio
            ns_id[row] = self._ns_ids[self._flow_ns[fid]]
        self._table = jax.device_put(ClusterRuleTable(
            active=jnp.asarray(active), count=jnp.asarray(count),
            is_global=jnp.asarray(is_global), exceed=jnp.asarray(exceed),
            max_occupy=jnp.asarray(max_occ), ns_id=jnp.asarray(ns_id)),
            self._sh_rows)

    # ------------------------------------------------------------------
    # Token requests
    # ------------------------------------------------------------------

    def request_tokens(self, flow_ids: Sequence[int], acquire: Sequence[int],
                       prioritized: Optional[Sequence[bool]] = None,
                       *, now_ms: int) -> List[Tuple[int, int, int]]:
        """Batched ``TokenService.requestToken`` → list of
        ``(status, wait_ms, remaining)`` aligned with the inputs."""
        return self.request_tokens_nowait(
            flow_ids, acquire, prioritized, now_ms=now_ms).result()

    def request_tokens_nowait(self, flow_ids: Sequence[int],
                              acquire: Sequence[int],
                              prioritized: Optional[Sequence[bool]] = None,
                              *, now_ms: int) -> PendingTokenResults:
        """Dispatch-only ``requestToken``: enqueue the sharded step, start
        the async device→host verdict copy, and defer materialization to
        ``.result()`` — the double-buffered front-end the serving path uses
        to hide readback latency (state updates still apply in dispatch
        order under the engine lock)."""
        from sentinel_tpu.core.batching import pad_pow2

        n = len(flow_ids)
        S = self.spec.n_shards
        L = self.spec.flows_per_shard

        with self._lock:
            vec = self._vector_prep(flow_ids, acquire, prioritized, n, S, L)
            if vec is not None:
                prep, gather = vec
                if prep is None:        # nothing routable: results are final
                    return PendingTokenResults(lambda: gather)
                rows, acq, prio, valid, blp = prep
            else:
                if prioritized is None:     # numpy arrays: no truthiness
                    prioritized = [False] * n
                per_shard: List[List[int]] = [[] for _ in range(S)]
                results: List[Optional[Tuple[int, int, int]]] = [None] * n
                for i, fid in enumerate(flow_ids):
                    row = self._flow_to_row.get(int(fid))
                    if acquire[i] <= 0:
                        # DefaultTokenService.requestToken count validation
                        results[i] = (STATUS_BAD_REQUEST, 0, 0)
                    elif row is None:
                        results[i] = (STATUS_NO_RULE_EXISTS, 0, 0)
                    else:
                        per_shard[row // L].append(i)

                bl = max((len(p) for p in per_shard), default=0)
                if bl == 0:
                    out = [r or (STATUS_FAIL, 0, 0) for r in results]
                    return PendingTokenResults(lambda: out)
                blp = pad_pow2(bl)

                rows = np.zeros((S, blp), np.int32)
                acq = np.zeros((S, blp), np.int32)
                prio = np.zeros((S, blp), np.bool_)
                valid = np.zeros((S, blp), np.bool_)
                for s in range(S):
                    for k, i in enumerate(per_shard[s]):
                        rows[s, k] = self._flow_to_row[int(flow_ids[i])] % L
                        acq[s, k] = acquire[i]
                        prio[s, k] = bool(prioritized[i])
                        valid[s, k] = True

            verdicts = self.step_routed(rows, acq, prio, valid, blp,
                                        now_ms=now_ms)
        if vec is not None:
            return PendingTokenResults(functools.partial(
                self._gather_results_vec, verdicts, gather, blp))
        return PendingTokenResults(functools.partial(
            self._gather_results, verdicts, per_shard, results, S, blp))

    def step_routed(self, rows, acq, prio, valid, blp: int, *,
                    now_ms: int) -> TokenVerdicts:
        """Run the sharded device step on pre-routed ``[S, Bl]`` lanes
        (``shard_math.route_requests`` layout) and return the raw sharded
        verdicts; scatter back with ``shard_math.scatter_verdicts``.

        This is the SPMD choke point shared by the single-process request
        paths and :mod:`sentinel_tpu.multihost.ingest`. In a multi-process
        mesh every participating process must call it with the SAME
        geometry (``blp``), ``now_ms`` and routing plan — only the lanes
        of shards this host owns need real payload data (``device_put``
        materializes local shards only); read verdicts back via
        :meth:`_gather_results_vec` / ``_to_host``.
        """
        S = self.spec.n_shards
        PV = self.spec.max_params
        PK = self.spec.param_keys_per_shard
        with self._lock:
            batch = self._put_rows(TokenBatch(
                local_rows=rows.reshape(-1).astype(np.int32),
                acquire=acq.reshape(-1).astype(np.int32),
                prioritized=prio.reshape(-1).astype(np.bool_),
                valid=valid.reshape(-1).astype(np.bool_),
                is_param=np.zeros((S * blp,), np.bool_),
                param_rows=np.full((S * blp, PV), PK, np.int32),
                param_count=np.zeros((S * blp, PV), np.float32)))

            w = self.spec.window
            if self._multiprocess:
                # scalars must be placed on every process's local devices
                # (an uncommitted single-device array is not addressable
                # by the other hosts of the global mesh)
                now_idx = jax.device_put(
                    np.int32(w.index_of(now_ms)), self._sh_rep)
                in_win = jax.device_put(
                    np.int32(now_ms % w.win_ms), self._sh_rep)
            else:
                now_idx = jnp.int32(w.index_of(now_ms))
                in_win = jnp.int32(now_ms % w.win_ms)
            self.state, verdicts = self._step(
                self._table, self.state, batch,
                jax.device_put(jnp.asarray(self._connected), self._sh_rep),
                jax.device_put(jnp.asarray(self._ns_limit), self._sh_rep),
                now_idx, in_win)
        self._maybe_start_host_copy(verdicts)
        return verdicts

    def _put_rows(self, tree):
        """Place a host pytree on the row sharding. Multi-process meshes
        need ``make_array_from_callback`` — each process materializes its
        OWN shards from its own host arrays (``device_put`` would instead
        assert the value is identical on every process, defeating
        host-local ingestion where non-local lanes hold garbage/zeros)."""
        if not self._multiprocess:
            return jax.device_put(tree, self._sh_rows)
        return jax.tree.map(
            lambda x: jax.make_array_from_callback(
                x.shape, self._sh_rows, lambda idx, x=x: x[idx]), tree)

    def _maybe_start_host_copy(self, verdicts: TokenVerdicts) -> None:
        # The async device→host prefetch only works on fully-addressable
        # arrays; multi-process readback goes through _to_host's
        # allgather instead.
        if not self._multiprocess:
            _start_host_copy(verdicts)

    def _to_host(self, x) -> np.ndarray:
        """Materialize a possibly cross-process row-sharded array."""
        if self._multiprocess:
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(
                x, tiled=True))
        return np.asarray(x)

    def rows_for_flows(self, flow_ids) -> Optional[np.ndarray]:
        """Global row per flow id (``-1`` = unregistered), vectorized via
        the dense lookup when possible. → None for sparse/non-int ids
        (callers fall back to the dict path). The row→shard math on the
        result is :mod:`~sentinel_tpu.parallel.shard_math`'s."""
        lut = self._fid_lookup
        if lut is None:
            return None
        ids = np.asarray(flow_ids)
        if ids.dtype.kind not in "iu" or ids.ndim != 1:
            return None
        in_rng = (ids >= 0) & (ids < lut.shape[0])
        return np.where(in_rng, lut[np.clip(ids, 0, lut.shape[0] - 1)], -1)

    def _vector_prep(self, flow_ids, acquire, prioritized, n: int, S: int,
                     L: int):
        """Vectorized request grouping (shard_math.route_requests): one
        argsort + scatter instead of per-event dict/append loops. → None
        to fall back to the loop path (sparse ids, non-int input), or
        ``(prep_arrays_or_None, gather_ctx_or_final_results)``."""
        if n == 0:
            return None
        rowg = self.rows_for_flows(flow_ids)
        if rowg is None:
            return None
        lanes, plan = shard_math.route_requests(
            rowg, acquire, prioritized, S, L,
            status_fail=STATUS_FAIL, status_bad=STATUS_BAD_REQUEST,
            status_no_rule=STATUS_NO_RULE_EXISTS)
        if lanes is None:
            return (None, [(int(s), 0, 0) for s in plan.status0])
        return ((lanes.rows, lanes.acquire, lanes.prioritized, lanes.valid,
                 lanes.lanes), plan)

    def _gather_results_vec(self, verdicts, plan, blp):
        """Vectorized inverse of :meth:`_vector_prep`'s grouping."""
        return shard_math.scatter_verdicts(
            plan, blp, self._to_host(verdicts.status),
            self._to_host(verdicts.wait_ms),
            self._to_host(verdicts.remaining), self.spec.n_shards)

    def top_params(self, flow_id: int, *, now_ms: int,
                   top_n: int = 10) -> Dict[object, int]:
        """Most-requested param values of a flow over the current (or,
        right after a rotation, the previous) window — feeds metricList's
        ``topParams`` (``ClusterParamMetric.getTopValues``). Counts are
        REQUESTED acquire sums, host-observed; grant/deny split stays in
        the device counters."""
        with self._lock:
            win = now_ms // (self.spec.window.win_ms
                             * self.spec.window.buckets)
            if win - self._param_hits_win > 1:
                return {}            # tracker is stale by more than a window
            hits = (self._param_hits.get(flow_id)
                    or self._param_hits_prev.get(flow_id) or {})
            return dict(sorted(hits.items(), key=lambda kv: -kv[1])[:top_n])

    def _row_snapshot(self, row: int):
        """``(counters[row], stamps[row])`` of the flow window state. In a
        multi-process mesh a host can't index shards it doesn't own, so
        the row is gathered on-device to a replicated output — which also
        means every process must call this collectively (SPMD), same as
        the step itself."""
        if not self._multiprocess:
            return (np.asarray(self.state.flows.counters[row]),
                    np.asarray(self.state.flows.stamps[row]))
        if self._row_gather is None:
            self._row_gather = jax.jit(
                lambda c, s, r: (c[r], s[r]), out_shardings=self._sh_rep)
        c, s = self._row_gather(self.state.flows.counters,
                                self.state.flows.stamps, row)
        return np.asarray(c), np.asarray(s)

    def flow_metrics(self, flow_id: int, *, now_ms: int) -> dict:
        """Per-flow current-window snapshot (ClusterMetricNodeGenerator)."""
        with self._lock:
            row = self._flow_to_row.get(flow_id)
            if row is None:
                return {}
            w = self.spec.window
            now_idx = jnp.int32(w.index_of(now_ms))
            counters, stamps = self._row_snapshot(row)  # [B, E], [B]
        delta = (int(now_idx) - stamps.astype(np.int64)).astype(np.int32)
        live = (delta >= 0) & (delta < w.buckets)
        tot = np.where(live[:, None], counters, 0).sum(axis=0)
        return {name: int(tot[i]) for i, name in enumerate(ev.NAMES)}
