"""Row-sharding of the LOCAL engine over a device mesh (product mode).

The north-star scale axis (SURVEY §7 phase 1): the local ``[R, B, E]``
window tensors — the dense rebuild of the reference's per-resource
StatisticNode forest — shard on the RESOURCE axis across the mesh, the
distributed analog of the reference's checker running against shared
state (``sentinel-cluster-server-default/.../flow/ClusterFlowChecker.java:38-118``
generalized to the whole slot chain). Rules, batches, and verdicts are
replicated; XLA's SPMD partitioner keeps the scatter-adds local to the
owning shard and inserts the gathers the decision reads need.

Usage::

    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("rows",))
    sph = Sentinel(config, mesh=mesh)      # everything else is unchanged

Design notes (why GSPMD annotations, not ``shard_map``): one local entry
event touches up to four DIFFERENT row spaces — its main row, the global
ENTRY row, and two hashed alt rows (origin/chain) — each owned by a
potentially different shard, plus replicated per-rule state (breakers,
pacing clocks). ``shard_map`` with host-side owner routing (the
:mod:`~sentinel_tpu.parallel.cluster` pattern) fits the token engine,
where a request targets exactly one flow row; for the full slot chain the
sharding is expressed as annotations on the state pytree and XLA
partitions the fused step. Parity with the single-device engine is
bit-exact (asserted in tests and the driver dry run).

Field map (state pytree → PartitionSpec), the single source of truth:

==================  ==========================  =====================
state field          shape                       sharding
==================  ==========================  =====================
second/minute        WindowState [R, B, ...]     P("rows") on axis 0
alt_second           WindowState [RA, B, ...]    P("rows") on axis 0
threads              int32[R]                    P("rows")
alt_threads          int32[RA]                   P("rows")
flow_dyn.occupied_*  [R, B+1]                    P("rows") on axis 0
flow_dyn (pacing)    [NF+1]                      replicated
breakers             [ND+1]                      replicated
param_dyn            [PK+1]                      replicated
custom               user DeviceSlot pytrees     replicated
rt_hist              int32[R, HB] (or absent)    P("rows") on axis 0
==================  ==========================  =====================
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sentinel_tpu.engine.pipeline import EngineSpec, SentinelState, Verdicts
from sentinel_tpu.parallel import shard_math

MESH_AXIS = "rows"


def local_mesh(n_devices: Optional[int] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """The one way to build a local row-sharding mesh — runtime callers,
    benches, the driver dry run, and tests all construct through here so
    the axis name and device ordering can never drift apart.

    ``n_devices`` takes the first n visible devices (all of them when
    None); pass ``devices`` to pin an explicit ordering instead."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"local_mesh(n_devices={n_devices}) but only "
                    f"{len(devices)} devices visible — on CPU, set "
                    f"XLA_FLAGS=--xla_force_host_platform_device_count="
                    f"{n_devices}")
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (MESH_AXIS,))


def validate_mesh(spec: EngineSpec, mesh: Mesh) -> None:
    """Fail fast (with a fix) when the geometry can't shard over the mesh."""
    if MESH_AXIS not in mesh.axis_names:
        raise ValueError(
            f"local-engine mesh needs a {MESH_AXIS!r} axis; got "
            f"{mesh.axis_names} — build it as Mesh(devices, ({MESH_AXIS!r},))")
    n = mesh.shape[MESH_AXIS]
    shard_math.validate_divisible(
        "max_resources", spec.rows, n,
        f"round max_resources up to a multiple of {n}")
    shard_math.validate_divisible(
        "alt_rows", spec.alt_rows, n,
        f"round max_resources up to a multiple of {n} (alt_rows follows it)")


def shard_of_rows(n_rows: int, mesh: Optional[Mesh],
                  rows: np.ndarray) -> np.ndarray:
    """Owner shard per row id under the contiguous leading-axis split
    (``validate_mesh`` guarantees even divisibility). Unmeshed engines
    are a single shard. The tiering ticker uses this to spread
    proactive demotions across shards so no device's hot set thins
    faster than its peers'."""
    rows = np.asarray(rows)
    if mesh is None:
        return np.zeros(rows.shape, np.int32)
    per = n_rows // mesh.shape[MESH_AXIS]
    return (rows // per).astype(np.int32)


def state_shardings(spec: EngineSpec, mesh: Mesh,
                    state: SentinelState) -> SentinelState:
    """A ``SentinelState``-shaped pytree of :class:`NamedSharding` per the
    field map above. ``state`` supplies the structure of the variable-shape
    parts (custom slot states, rt-tracking window leaves)."""
    row = NamedSharding(mesh, P(MESH_AXIS))
    rep = NamedSharding(mesh, P())

    def rows_first(sub):          # every leaf leads with the row axis
        return jax.tree.map(lambda _: row, sub)

    def replicated(sub):
        return jax.tree.map(lambda _: rep, sub)

    return SentinelState(
        second=rows_first(state.second),
        # minute is [R]-rowed when enabled, a 1-row stub when disabled
        minute=(rows_first(state.minute) if spec.minute
                else replicated(state.minute)),
        alt_second=rows_first(state.alt_second),
        threads=row,
        alt_threads=row,
        flow_dyn=state.flow_dyn._replace(
            latest_passed_ms=rep, stored_tokens=rep, last_filled_sec=rep,
            occupied_count=row, occupied_window=row),
        breakers=replicated(state.breakers),
        param_dyn=replicated(state.param_dyn),
        custom=replicated(state.custom),
        # round 20: [R, HB] RT histogram rows live with their resource
        rt_hist=(row if state.rt_hist is not None else None),
    )


def verdict_shardings(mesh: Mesh) -> Verdicts:
    rep = NamedSharding(mesh, P())
    return Verdicts(allow=rep, reason=rep, wait_ms=rep, sf_overflow=rep)


def pin_state(state: SentinelState,
              shardings: SentinelState) -> SentinelState:
    """Place (or re-place) every state leaf on its canonical sharding —
    used at init and whenever host code rebuilds a leaf (window geometry
    change, snapshot restore), so a freshly created unsharded array can't
    silently drop the engine back to single-device execution."""
    return jax.tree.map(jax.device_put, state, shardings)


def shardings_for(spec: EngineSpec, mesh: Optional[Mesh],
                  state: SentinelState):
    """→ (state_shardings, verdict_shardings) or (None, None) without a
    mesh; the one call sites use."""
    if mesh is None:
        return None, None
    validate_mesh(spec, mesh)
    return state_shardings(spec, mesh, state), verdict_shardings(mesh)


@functools.lru_cache(maxsize=8)
def batch_shardings(mesh: Mesh):
    """→ (batch_axis, replicated) :class:`NamedSharding` pair for event
    columns (cached per mesh — one pair serves every dispatch)."""
    return NamedSharding(mesh, P(MESH_AXIS)), NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, x) -> NamedSharding:
    """Batch-axis sharding for ONE event column: partition the leading
    (event) dimension over the mesh when it divides evenly, else
    replicate — the retrieval brief's naive-sharding utility pattern
    (SNIPPETS [1]/[2] ``get_naive_sharding``). Trailing dimensions (the
    param-pair lanes) stay unpartitioned."""
    sharded, rep = batch_shardings(mesh)
    n = mesh.shape[MESH_AXIS]
    return sharded if (x.ndim >= 1 and x.shape[0] % n == 0) else rep


def place_batch(batch, mesh: Mesh):
    """Place every present column of an ``EntryBatch`` / ``ExitBatch``
    (any NamedTuple of host arrays with optional ``None`` leaves) on its
    batch-axis sharding before dispatch. Explicit placement keeps the
    host→device transfer of the event columns partitioned like the step
    that consumes them — without it the compiled step would re-lay-out
    replicated inputs on every dispatch. Values are unchanged (placement
    is layout, not math); the parity tests pin that."""
    return jax.tree.map(
        lambda x: jax.device_put(x, batch_sharding(mesh, np.asarray(x))),
        batch)


def topk_layout(spec: EngineSpec, mesh: Optional[Mesh]):
    """→ ``(n_shards, rows_per_shard)`` for the telemetry top-K merge
    (obs/telemetry.py). THE row-ownership contract of the sharded merge:
    shard ``i`` owns the contiguous global rows
    ``[i*rows_per_shard, (i+1)*rows_per_shard)`` — exactly how GSPMD
    partitions a ``P("rows")`` axis-0 sharding — so a local top-k index
    maps to its global row as ``local + axis_index * rows_per_shard``.
    Kept here (not in the telemetry module) so the layout can never
    drift from the state sharding it must mirror."""
    if mesh is None:
        return 1, spec.rows
    n = int(mesh.shape[MESH_AXIS])
    return n, spec.rows // n


def mesh_topology(spec: EngineSpec, mesh: Optional[Mesh],
                  state_sh: Optional[SentinelState] = None) -> dict:
    """Artifact-ready description of the serving layout: device count,
    axis name, per-device row span, and — when the sharding pytree is
    supplied — how many state leaves actually shard vs replicate, so a
    BENCH artifact records the layout that produced its numbers."""
    if mesh is None:
        return {"n_devices": 1, "axis": None,
                "rows_per_device": spec.rows, "sharded": False}
    n = mesh.shape[MESH_AXIS]
    out = {"n_devices": int(n), "axis": MESH_AXIS,
           "rows_per_device": spec.rows // int(n), "sharded": True,
           "multihost": len({d.process_index
                             for d in np.ravel(np.asarray(mesh.devices))}) > 1}
    if state_sh is not None:
        leaves = jax.tree.leaves(state_sh)
        n_rows = sum(1 for s in leaves if s.spec == P(MESH_AXIS))
        out["state_leaves_sharded"] = n_rows
        out["state_leaves_replicated"] = len(leaves) - n_rows
    return out
