"""Row-sharding of the LOCAL engine over a device mesh (product mode).

The north-star scale axis (SURVEY §7 phase 1): the local ``[R, B, E]``
window tensors — the dense rebuild of the reference's per-resource
StatisticNode forest — shard on the RESOURCE axis across the mesh, the
distributed analog of the reference's checker running against shared
state (``sentinel-cluster-server-default/.../flow/ClusterFlowChecker.java:38-118``
generalized to the whole slot chain). Rules, batches, and verdicts are
replicated; XLA's SPMD partitioner keeps the scatter-adds local to the
owning shard and inserts the gathers the decision reads need.

Usage::

    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("rows",))
    sph = Sentinel(config, mesh=mesh)      # everything else is unchanged

Design notes (why GSPMD annotations, not ``shard_map``): one local entry
event touches up to four DIFFERENT row spaces — its main row, the global
ENTRY row, and two hashed alt rows (origin/chain) — each owned by a
potentially different shard, plus replicated per-rule state (breakers,
pacing clocks). ``shard_map`` with host-side owner routing (the
:mod:`~sentinel_tpu.parallel.cluster` pattern) fits the token engine,
where a request targets exactly one flow row; for the full slot chain the
sharding is expressed as annotations on the state pytree and XLA
partitions the fused step. Parity with the single-device engine is
bit-exact (asserted in tests and the driver dry run).

Field map (state pytree → PartitionSpec), the single source of truth:

==================  ==========================  =====================
state field          shape                       sharding
==================  ==========================  =====================
second/minute        WindowState [R, B, ...]     P("rows") on axis 0
alt_second           WindowState [RA, B, ...]    P("rows") on axis 0
threads              int32[R]                    P("rows")
alt_threads          int32[RA]                   P("rows")
flow_dyn.occupied_*  [R, B+1]                    P("rows") on axis 0
flow_dyn (pacing)    [NF+1]                      replicated
breakers             [ND+1]                      replicated
param_dyn            [PK+1]                      replicated
custom               user DeviceSlot pytrees     replicated
==================  ==========================  =====================
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sentinel_tpu.engine.pipeline import EngineSpec, SentinelState, Verdicts
from sentinel_tpu.parallel import shard_math

MESH_AXIS = "rows"


def validate_mesh(spec: EngineSpec, mesh: Mesh) -> None:
    """Fail fast (with a fix) when the geometry can't shard over the mesh."""
    if MESH_AXIS not in mesh.axis_names:
        raise ValueError(
            f"local-engine mesh needs a {MESH_AXIS!r} axis; got "
            f"{mesh.axis_names} — build it as Mesh(devices, ({MESH_AXIS!r},))")
    n = mesh.shape[MESH_AXIS]
    shard_math.validate_divisible(
        "max_resources", spec.rows, n,
        f"round max_resources up to a multiple of {n}")
    shard_math.validate_divisible(
        "alt_rows", spec.alt_rows, n,
        f"round max_resources up to a multiple of {n} (alt_rows follows it)")


def state_shardings(spec: EngineSpec, mesh: Mesh,
                    state: SentinelState) -> SentinelState:
    """A ``SentinelState``-shaped pytree of :class:`NamedSharding` per the
    field map above. ``state`` supplies the structure of the variable-shape
    parts (custom slot states, rt-tracking window leaves)."""
    row = NamedSharding(mesh, P(MESH_AXIS))
    rep = NamedSharding(mesh, P())

    def rows_first(sub):          # every leaf leads with the row axis
        return jax.tree.map(lambda _: row, sub)

    def replicated(sub):
        return jax.tree.map(lambda _: rep, sub)

    return SentinelState(
        second=rows_first(state.second),
        # minute is [R]-rowed when enabled, a 1-row stub when disabled
        minute=(rows_first(state.minute) if spec.minute
                else replicated(state.minute)),
        alt_second=rows_first(state.alt_second),
        threads=row,
        alt_threads=row,
        flow_dyn=state.flow_dyn._replace(
            latest_passed_ms=rep, stored_tokens=rep, last_filled_sec=rep,
            occupied_count=row, occupied_window=row),
        breakers=replicated(state.breakers),
        param_dyn=replicated(state.param_dyn),
        custom=replicated(state.custom),
    )


def verdict_shardings(mesh: Mesh) -> Verdicts:
    rep = NamedSharding(mesh, P())
    return Verdicts(allow=rep, reason=rep, wait_ms=rep)


def pin_state(state: SentinelState,
              shardings: SentinelState) -> SentinelState:
    """Place (or re-place) every state leaf on its canonical sharding —
    used at init and whenever host code rebuilds a leaf (window geometry
    change, snapshot restore), so a freshly created unsharded array can't
    silently drop the engine back to single-device execution."""
    return jax.tree.map(jax.device_put, state, shardings)


def shardings_for(spec: EngineSpec, mesh: Optional[Mesh],
                  state: SentinelState):
    """→ (state_shardings, verdict_shardings) or (None, None) without a
    mesh; the one call sites use."""
    if mesh is None:
        return None, None
    validate_mesh(spec, mesh)
    return state_shardings(spec, mesh, state), verdict_shardings(mesh)
