"""Request-attribute extraction for gateway rules.

Reference: ``GatewayParamParser.java`` — for each of a resource's gateway
rules with a param item, pull the configured attribute (client IP / Host /
header / URL param / cookie) out of the request and place it at the rule's
assigned index in the args array; values failing the rule's pattern filter
become ``$NM`` (which the converted rule's per-item override passes freely);
rules without a param item share a trailing ``$D`` slot
(``parseParameterFor:52-85``). Match strategies: exact / contains / regex
(cached, ``GatewayRegexCache``) / prefix (``parseWithMatchStrategyInternal``).
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional, Protocol

from sentinel_tpu.gateway.rules import (
    GATEWAY_DEFAULT_PARAM,
    GATEWAY_NOT_MATCH_PARAM,
    PARAM_MATCH_STRATEGY_CONTAINS,
    PARAM_MATCH_STRATEGY_EXACT,
    PARAM_MATCH_STRATEGY_PREFIX,
    PARAM_MATCH_STRATEGY_REGEX,
    PARAM_PARSE_STRATEGY_CLIENT_IP,
    PARAM_PARSE_STRATEGY_COOKIE,
    PARAM_PARSE_STRATEGY_HEADER,
    PARAM_PARSE_STRATEGY_HOST,
    PARAM_PARSE_STRATEGY_URL_PARAM,
    GatewayFlowRule,
    GatewayParamFlowItem,
    GatewayRuleManager,
)

_REGEX_CACHE: dict = {}


def _cached_regex(pattern: str) -> Optional["re.Pattern"]:
    rx = _REGEX_CACHE.get(pattern)
    if rx is None:
        try:
            rx = re.compile(pattern)
        except re.error:
            return None
        _REGEX_CACHE[pattern] = rx
    return rx


class RequestItemParser(Protocol):
    """Adapter-facing request accessor (``RequestItemParser.java``)."""

    def get_path(self, request) -> str: ...
    def get_remote_address(self, request) -> Optional[str]: ...
    def get_header(self, request, key: str) -> Optional[str]: ...
    def get_url_param(self, request, name: str) -> Optional[str]: ...
    def get_cookie_value(self, request, name: str) -> Optional[str]: ...


class DictRequestItemParser:
    """Plain-dict requests: ``{"path", "remote", "headers", "params",
    "cookies"}`` — the test/reference-free parser, also used by the WSGI
    adapter after environ normalization."""

    def get_path(self, request) -> str:
        return request.get("path", "")

    def get_remote_address(self, request) -> Optional[str]:
        return request.get("remote")

    def get_header(self, request, key: str) -> Optional[str]:
        headers = request.get("headers") or {}
        return headers.get(key) or headers.get(key.lower())

    def get_url_param(self, request, name: str) -> Optional[str]:
        return (request.get("params") or {}).get(name)

    def get_cookie_value(self, request, name: str) -> Optional[str]:
        return (request.get("cookies") or {}).get(name)


def _match_value(strategy: int, value: Optional[str],
                 pattern: str) -> Optional[str]:
    """``parseWithMatchStrategyInternal:156-174`` — non-matching → $NM."""
    if value is None:
        return None
    if strategy == PARAM_MATCH_STRATEGY_EXACT:
        return value if value == pattern else GATEWAY_NOT_MATCH_PARAM
    if strategy == PARAM_MATCH_STRATEGY_CONTAINS:
        return value if pattern in value else GATEWAY_NOT_MATCH_PARAM
    if strategy == PARAM_MATCH_STRATEGY_PREFIX:
        return value if value.startswith(pattern) else GATEWAY_NOT_MATCH_PARAM
    if strategy == PARAM_MATCH_STRATEGY_REGEX:
        rx = _cached_regex(pattern)
        if rx is None:
            return value
        return value if rx.fullmatch(value) else GATEWAY_NOT_MATCH_PARAM
    return value


class GatewayParamParser:
    """Builds the entry args for a gateway resource from a live request."""

    def __init__(self, manager: GatewayRuleManager,
                 item_parser: Optional[RequestItemParser] = None):
        self._manager = manager
        self._parser = item_parser or DictRequestItemParser()

    def _parse_item(self, item: GatewayParamFlowItem, request) -> Optional[str]:
        p = self._parser
        strategy = item.parse_strategy
        if strategy == PARAM_PARSE_STRATEGY_CLIENT_IP:
            value = p.get_remote_address(request)
        elif strategy == PARAM_PARSE_STRATEGY_HOST:
            value = p.get_header(request, "Host")
        elif strategy == PARAM_PARSE_STRATEGY_HEADER:
            value = p.get_header(request, item.field_name)
        elif strategy == PARAM_PARSE_STRATEGY_URL_PARAM:
            value = p.get_url_param(request, item.field_name)
        elif strategy == PARAM_PARSE_STRATEGY_COOKIE:
            value = p.get_cookie_value(request, item.field_name)
        else:
            return None
        if not item.pattern:
            return value
        return _match_value(item.match_strategy, value, item.pattern)

    def parse_parameters(self, resource: str, request,
                         rule_predicate: Optional[Callable[[GatewayFlowRule], bool]] = None
                         ) -> List[Optional[str]]:
        """→ args for ``Sentinel.entry(resource, args=...)``.

        ``rule_predicate`` filters which rules apply (the Spring Cloud
        adapter uses it for API-vs-route scoping); mixed verdicts → no args
        (``parseParameterFor:69-71``)."""
        if not resource or request is None:
            return []
        param_rules = []
        preds = set()
        has_non_param = False
        for rule in self._manager.rules_for_resource(resource):
            if rule.param_item is not None:
                param_rules.append(rule)
                if rule_predicate is not None:
                    preds.add(bool(rule_predicate(rule)))
            else:
                has_non_param = True
        if not param_rules and not has_non_param:
            return []
        if len(preds) > 1 or False in preds:
            return []
        size = len(param_rules) + (1 if has_non_param else 0)
        args: List[Optional[str]] = [None] * size
        for rule in param_rules:
            idx = rule.param_item.index
            if idx is not None and 0 <= idx < size:
                args[idx] = self._parse_item(rule.param_item, request)
        if has_non_param:
            args[size - 1] = GATEWAY_DEFAULT_PARAM
        return args
