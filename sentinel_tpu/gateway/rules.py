"""Gateway flow rules and their conversion onto the hot-param engine.

Reference semantics (``sentinel-api-gateway-adapter-common``):

* ``GatewayFlowRule.java:30-47`` — field parity: resource (route id or custom
  API name), resourceMode, grade (QPS default), count, intervalSec=1,
  controlBehavior, burst, maxQueueingTimeoutMs=500, optional
  ``GatewayParamFlowItem`` (parseStrategy, fieldName, pattern, matchStrategy).
* ``GatewayRuleConverter.java:29-88`` — every gateway rule becomes a
  ``ParamFlowRule``; pattern-based items get a ``$NM`` (not-match) per-item
  override with a huge threshold so non-matching traffic passes freely
  (``generateNonMatchPassParamItem``).
* ``GatewayRuleManager.applyGatewayRuleInternal:179-237`` — per-resource
  parameter indices are assigned densely to param-item rules (0..n-1); rules
  WITHOUT a param item all share the next index and throttle the single
  synthetic value ``$D`` (so a plain per-route QPS cap rides the same
  machinery, ``applyNonParamToParamRule``).
* ``GatewayRuleManager.isValidRule:117-134`` — validation parity.

TPU-native shape: the converted ``ParamFlowRule`` set is handed to the
runtime's param-flow engine (one merged param slot — the reference's separate
``GatewayFlowSlot`` checks the same converted rules against the same entry
args, so merging is semantics-preserving); gateway entries pass the parsed
request attributes as their ``args``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from sentinel_tpu.rules import param_flow as pf

RESOURCE_MODE_ROUTE_ID = 0
RESOURCE_MODE_CUSTOM_API_NAME = 1

PARAM_PARSE_STRATEGY_CLIENT_IP = 0
PARAM_PARSE_STRATEGY_HOST = 1
PARAM_PARSE_STRATEGY_HEADER = 2
PARAM_PARSE_STRATEGY_URL_PARAM = 3
PARAM_PARSE_STRATEGY_COOKIE = 4

PARAM_MATCH_STRATEGY_EXACT = 0
PARAM_MATCH_STRATEGY_PREFIX = 1
PARAM_MATCH_STRATEGY_REGEX = 2
PARAM_MATCH_STRATEGY_CONTAINS = 3

GATEWAY_NOT_MATCH_PARAM = "$NM"
GATEWAY_DEFAULT_PARAM = "$D"

_NOT_MATCH_PASS_COUNT = 10_000_000   # generateNonMatchPassParamItem threshold

GRADE_QPS = pf.GRADE_QPS
GRADE_THREAD = pf.GRADE_THREAD


@dataclasses.dataclass
class GatewayParamFlowItem:
    """What request attribute to throttle by (``GatewayParamFlowItem.java``)."""

    parse_strategy: int = PARAM_PARSE_STRATEGY_CLIENT_IP
    field_name: str = ""                 # header/url-param/cookie name
    pattern: str = ""                    # optional value filter
    match_strategy: int = PARAM_MATCH_STRATEGY_EXACT
    index: Optional[int] = None          # assigned at load time

    def is_valid(self) -> bool:
        if self.parse_strategy not in (
                PARAM_PARSE_STRATEGY_CLIENT_IP, PARAM_PARSE_STRATEGY_HOST,
                PARAM_PARSE_STRATEGY_HEADER, PARAM_PARSE_STRATEGY_URL_PARAM,
                PARAM_PARSE_STRATEGY_COOKIE):
            return False
        if self.parse_strategy in (PARAM_PARSE_STRATEGY_HEADER,
                                   PARAM_PARSE_STRATEGY_URL_PARAM,
                                   PARAM_PARSE_STRATEGY_COOKIE) \
                and not self.field_name:
            return False
        return True


@dataclasses.dataclass
class GatewayFlowRule:
    """Gateway-granularity flow rule (``GatewayFlowRule.java`` field parity)."""

    resource: str
    resource_mode: int = RESOURCE_MODE_ROUTE_ID
    grade: int = GRADE_QPS
    count: float = 0.0
    interval_sec: int = 1
    control_behavior: int = pf.BEHAVIOR_DEFAULT
    burst: int = 0
    max_queueing_timeout_ms: int = 500
    param_item: Optional[GatewayParamFlowItem] = None

    def is_valid(self) -> bool:
        if (not self.resource or self.resource_mode < 0 or self.grade < 0
                or self.count < 0 or self.burst < 0
                or self.control_behavior < 0 or self.interval_sec <= 0):
            return False
        if (self.control_behavior == pf.BEHAVIOR_RATE_LIMITER
                and self.max_queueing_timeout_ms < 0):
            return False
        if self.param_item is not None:
            return self.param_item.is_valid()
        return True


def _to_param_rule(rule: GatewayFlowRule, idx: int) -> pf.ParamFlowRule:
    """``GatewayRuleConverter.applyToParamRule`` / ``applyNonParamToParamRule``."""
    items: List[pf.ParamFlowItem] = []
    if rule.param_item is not None and rule.param_item.pattern:
        # pattern-based matching: the parser maps non-matching values to $NM,
        # which this per-item override lets through at an effectively
        # unlimited rate (generateNonMatchPassParamItem)
        items.append(pf.ParamFlowItem(object=GATEWAY_NOT_MATCH_PARAM,
                                      count=_NOT_MATCH_PASS_COUNT))
    return pf.ParamFlowRule(
        resource=rule.resource,
        param_idx=idx,
        count=rule.count,
        grade=rule.grade,
        duration_in_sec=rule.interval_sec,
        burst_count=rule.burst,
        control_behavior=rule.control_behavior,
        max_queueing_time_ms=rule.max_queueing_timeout_ms,
        param_flow_item_list=items,
    )


class GatewayRuleManager:
    """Holds gateway rules for one Sentinel instance and keeps the converted
    param-rule set installed (``GatewayRuleManager`` + ``GatewayFlowSlot``)."""

    def __init__(self, sentinel):
        import threading
        self._sentinel = sentinel
        self._load_lock = threading.Lock()   # command threads race reloads
        self._rules: Dict[str, List[GatewayFlowRule]] = {}
        # resource → number of param-item indices (the args-array length is
        # this plus one shared slot for non-param rules, filled with $D)
        self._param_idx_count: Dict[str, int] = {}
        self._has_non_param: Dict[str, bool] = {}

    def load_rules(self, rules: Sequence[GatewayFlowRule]) -> None:
        rule_map: Dict[str, List[GatewayFlowRule]] = {}
        idx_map: Dict[str, int] = {}
        has_non_param: Dict[str, bool] = {}
        converted: List[pf.ParamFlowRule] = []
        non_param: List[GatewayFlowRule] = []

        for rule in rules:
            if not rule.is_valid():
                continue
            rule_map.setdefault(rule.resource, []).append(rule)
            if rule.param_item is None:
                non_param.append(rule)
                has_non_param[rule.resource] = True
            else:
                idx = idx_map.get(rule.resource, 0)
                rule.param_item.index = idx
                idx_map[rule.resource] = idx + 1
                converted.append(_to_param_rule(rule, idx))
        # non-param rules all share the resource's LAST index; their traffic
        # is the synthetic $D value the parser appends
        for rule in non_param:
            converted.append(_to_param_rule(rule, idx_map.get(rule.resource, 0)))

        # one lock around the multi-map swap + param-rule install: two
        # concurrent command-plane reloads must not interleave (the parser's
        # args_length would disagree with the installed rules)
        with self._load_lock:
            self._rules = rule_map
            self._param_idx_count = idx_map
            self._has_non_param = has_non_param
            self._sentinel.set_gateway_param_rules(converted)

    def rules_for_resource(self, resource: str) -> List[GatewayFlowRule]:
        return list(self._rules.get(resource, ()))

    def all_rules(self) -> List[GatewayFlowRule]:
        return [r for rs in self._rules.values() for r in rs]

    def args_length(self, resource: str) -> int:
        """Length of the parsed-parameter array for a resource's entries."""
        n = self._param_idx_count.get(resource, 0)
        return n + (1 if self._has_non_param.get(resource) else 0)

    def has_non_param_rule(self, resource: str) -> bool:
        return bool(self._has_non_param.get(resource))
