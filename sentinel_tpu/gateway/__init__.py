"""API-gateway flow control (reference
``sentinel-adapter/sentinel-api-gateway-adapter-common``): route/custom-API
granularity rules with request-attribute matchers, converted onto the
hot-param engine."""

from sentinel_tpu.gateway.api import (
    URL_MATCH_STRATEGY_EXACT,
    URL_MATCH_STRATEGY_PREFIX,
    URL_MATCH_STRATEGY_REGEX,
    ApiDefinition,
    ApiPathPredicateItem,
    GatewayApiDefinitionManager,
)
from sentinel_tpu.gateway.param import (
    DictRequestItemParser,
    GatewayParamParser,
    RequestItemParser,
)
from sentinel_tpu.gateway.rules import (
    GATEWAY_DEFAULT_PARAM,
    GATEWAY_NOT_MATCH_PARAM,
    PARAM_MATCH_STRATEGY_CONTAINS,
    PARAM_MATCH_STRATEGY_EXACT,
    PARAM_MATCH_STRATEGY_PREFIX,
    PARAM_MATCH_STRATEGY_REGEX,
    PARAM_PARSE_STRATEGY_CLIENT_IP,
    PARAM_PARSE_STRATEGY_COOKIE,
    PARAM_PARSE_STRATEGY_HEADER,
    PARAM_PARSE_STRATEGY_HOST,
    PARAM_PARSE_STRATEGY_URL_PARAM,
    RESOURCE_MODE_CUSTOM_API_NAME,
    RESOURCE_MODE_ROUTE_ID,
    GatewayFlowRule,
    GatewayParamFlowItem,
    GatewayRuleManager,
)

__all__ = [
    "GatewayFlowRule", "GatewayParamFlowItem", "GatewayRuleManager",
    "ApiDefinition", "ApiPathPredicateItem", "GatewayApiDefinitionManager",
    "GatewayParamParser", "RequestItemParser", "DictRequestItemParser",
    "RESOURCE_MODE_ROUTE_ID", "RESOURCE_MODE_CUSTOM_API_NAME",
    "PARAM_PARSE_STRATEGY_CLIENT_IP", "PARAM_PARSE_STRATEGY_HOST",
    "PARAM_PARSE_STRATEGY_HEADER", "PARAM_PARSE_STRATEGY_URL_PARAM",
    "PARAM_PARSE_STRATEGY_COOKIE",
    "PARAM_MATCH_STRATEGY_EXACT", "PARAM_MATCH_STRATEGY_PREFIX",
    "PARAM_MATCH_STRATEGY_REGEX", "PARAM_MATCH_STRATEGY_CONTAINS",
    "URL_MATCH_STRATEGY_EXACT", "URL_MATCH_STRATEGY_PREFIX",
    "URL_MATCH_STRATEGY_REGEX",
    "GATEWAY_NOT_MATCH_PARAM", "GATEWAY_DEFAULT_PARAM",
]
