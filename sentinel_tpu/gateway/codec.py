"""Gateway rule / API-definition JSON codecs (reference
``sentinel-api-gateway-adapter-common``'s command payloads — field names
match ``GatewayFlowRule.java`` / ``ApiDefinition.java`` fastjson output so
the reference dashboard's gateway screens can drive these agents)."""

from __future__ import annotations

import json
from typing import Any, Dict, List, Sequence

from sentinel_tpu.gateway.api import ApiDefinition, ApiPathPredicateItem
from sentinel_tpu.gateway.rules import GatewayFlowRule, GatewayParamFlowItem


def gateway_rule_to_dict(r: GatewayFlowRule) -> Dict[str, Any]:
    d: Dict[str, Any] = {
        "resource": r.resource, "resourceMode": r.resource_mode,
        "grade": r.grade, "count": r.count, "intervalSec": r.interval_sec,
        "controlBehavior": r.control_behavior, "burst": r.burst,
        "maxQueueingTimeoutMs": r.max_queueing_timeout_ms,
    }
    if r.param_item is not None:
        p = r.param_item
        d["paramItem"] = {
            "parseStrategy": p.parse_strategy, "fieldName": p.field_name,
            "pattern": p.pattern, "matchStrategy": p.match_strategy,
        }
    return d


def gateway_rule_from_dict(d: Dict[str, Any]) -> GatewayFlowRule:
    item = None
    if d.get("paramItem"):
        p = d["paramItem"]
        item = GatewayParamFlowItem(
            parse_strategy=int(p.get("parseStrategy", 0)),
            field_name=str(p.get("fieldName", "") or ""),
            pattern=str(p.get("pattern", "") or ""),
            match_strategy=int(p.get("matchStrategy", 0)))
    return GatewayFlowRule(
        resource=str(d["resource"]),
        resource_mode=int(d.get("resourceMode", 0)),
        grade=int(d.get("grade", 1)),
        count=float(d.get("count", 0.0)),
        interval_sec=int(d.get("intervalSec", 1)),
        control_behavior=int(d.get("controlBehavior", 0)),
        burst=int(d.get("burst", 0)),
        max_queueing_timeout_ms=int(d.get("maxQueueingTimeoutMs", 500)),
        param_item=item)


def api_definition_to_dict(a: ApiDefinition) -> Dict[str, Any]:
    return {"apiName": a.api_name, "predicateItems": [
        {"pattern": p.pattern, "matchStrategy": p.match_strategy}
        for p in a.predicate_items]}


def api_definition_from_dict(d: Dict[str, Any]) -> ApiDefinition:
    items = tuple(ApiPathPredicateItem(
        pattern=str(p.get("pattern", "")),
        match_strategy=int(p.get("matchStrategy", 0)))
        for p in d.get("predicateItems", []) or [])
    return ApiDefinition(api_name=str(d["apiName"]), predicate_items=items)


def gateway_rules_to_json(rules: Sequence[GatewayFlowRule]) -> str:
    return json.dumps([gateway_rule_to_dict(r) for r in rules])


def gateway_rules_from_json(text: str) -> List[GatewayFlowRule]:
    return [gateway_rule_from_dict(d) for d in json.loads(text or "[]")]


def api_definitions_to_json(defs: Sequence[ApiDefinition]) -> str:
    return json.dumps([api_definition_to_dict(a) for a in defs])


def api_definitions_from_json(text: str) -> List[ApiDefinition]:
    return [api_definition_from_dict(d) for d in json.loads(text or "[]")]
