"""Custom API ("API group") definitions with path predicates.

Reference: ``sentinel-api-gateway-adapter-common/.../api/``
(``ApiDefinition.java``, ``ApiPathPredicateItem.java``,
``GatewayApiDefinitionManager.java``) and the concrete matcher behavior in
``sentinel-spring-cloud-gateway-adapter/.../WebExchangeApiMatcher.java:56-69``:
EXACT = equality, PREFIX = ant path (``/foo/**``), REGEX = full match.
An API matches when ANY of its predicate items matches
(``AbstractApiMatcher.test:57-64``)."""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Dict, List, Optional, Sequence

URL_MATCH_STRATEGY_EXACT = 0
URL_MATCH_STRATEGY_PREFIX = 1
URL_MATCH_STRATEGY_REGEX = 2


@dataclasses.dataclass(frozen=True)
class ApiPathPredicateItem:
    pattern: str
    match_strategy: int = URL_MATCH_STRATEGY_EXACT


@dataclasses.dataclass(frozen=True)
class ApiDefinition:
    api_name: str
    predicate_items: tuple = ()

    def is_valid(self) -> bool:
        return bool(self.api_name) and self.predicate_items is not None


def _ant_to_regex(pattern: str) -> "re.Pattern":
    """Ant-style path pattern → regex (`**` any depth, `*` one segment,
    `?` one char) — the PREFIX strategy's matcher."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                out.append(".*")
                i += 2
                continue
            out.append("[^/]*")
        elif c == "?":
            out.append("[^/]")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("".join(out) + r"\Z")


class _ApiMatcher:
    def __init__(self, definition: ApiDefinition):
        self.api_name = definition.api_name
        self.definition = definition
        self._preds = []
        for item in definition.predicate_items:
            if not item.pattern:
                continue
            if item.match_strategy == URL_MATCH_STRATEGY_REGEX:
                rx = re.compile(item.pattern)
                self._preds.append(lambda p, rx=rx: rx.fullmatch(p) is not None)
            elif item.match_strategy == URL_MATCH_STRATEGY_PREFIX:
                rx = _ant_to_regex(item.pattern)
                self._preds.append(lambda p, rx=rx: rx.match(p) is not None)
            else:
                self._preds.append(lambda p, pat=item.pattern: p == pat)

    def test(self, path: str) -> bool:
        return any(pred(path) for pred in self._preds)


class GatewayApiDefinitionManager:
    """Registry of custom API groups; resolves a request path to the API
    names whose predicates match (``GatewayApiDefinitionManager`` + the
    per-adapter matcher caches)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._defs: Dict[str, ApiDefinition] = {}
        self._matchers: List[_ApiMatcher] = []
        self._listeners = []

    def load_api_definitions(self, definitions: Sequence[ApiDefinition]) -> None:
        valid = [d for d in definitions if d.is_valid()]
        with self._lock:
            self._defs = {d.api_name: d for d in valid}
            self._matchers = [_ApiMatcher(d) for d in valid]
        for listener in list(self._listeners):
            listener(valid)

    def add_listener(self, fn) -> None:
        """``ApiDefinitionChangeObserver`` analog."""
        self._listeners.append(fn)

    def get_api_definition(self, api_name: str) -> Optional[ApiDefinition]:
        with self._lock:
            return self._defs.get(api_name)

    def get_api_definitions(self) -> List[ApiDefinition]:
        with self._lock:
            return list(self._defs.values())

    def matching_apis(self, path: str) -> List[str]:
        """All custom-API resource names whose predicates match the path."""
        with self._lock:
            matchers = list(self._matchers)
        return [m.api_name for m in matchers if m.test(path)]
