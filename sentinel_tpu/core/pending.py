"""Lazy result handles for dispatched-but-unread device work.

The double-buffering primitive shared by the serving paths
(``Sentinel.entry_batch_nowait`` / ``ClusterEngine.request_tokens_nowait``):
the device step is dispatched (engine state already advanced in order) and
the device→host transfer started async; :meth:`PendingResult.result`
materializes. Holding a handle while dispatching the next batch overlaps the
readback — the dominant per-batch cost on a remote-attached device — with
the next batch's host prep.
"""

from __future__ import annotations


class _Cell:
    """Shared settle state for a :class:`PendingResult`.

    Split out of the handle so a GC finalizer can settle a leaked handle
    without resurrecting it: the finalizer closes over the cell, and a
    handle whose cell was already settled by the finalizer still returns
    the cached result from :meth:`settle`.
    """

    __slots__ = ("fn", "done", "res")

    def __init__(self, fn):
        self.fn = fn
        self.done = False
        self.res = None

    def settle(self):
        if not self.done:
            self.res = self.fn()
            self.done = True
            self.fn = None
        return self.res


class PendingResult:
    """Memoizing one-shot handle: ``result()`` runs the deferred
    materialization exactly once and returns the cached value after."""

    __slots__ = ("_cell", "__weakref__")

    def __init__(self, fn):
        self._cell = _Cell(fn)

    def result(self):
        return self._cell.settle()


def start_host_copy(arrays) -> None:
    """Kick off async device→host copies so a later ``np.asarray`` finds
    the data already (or nearly) resident instead of paying the full RTT
    at materialization time. Backends without async D2H just sync later."""
    for a in arrays:
        try:
            a.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
