"""Lazy result handles for dispatched-but-unread device work.

The double-buffering primitive shared by the serving paths
(``Sentinel.entry_batch_nowait`` / ``ClusterEngine.request_tokens_nowait``):
the device step is dispatched (engine state already advanced in order) and
the device→host transfer started async; :meth:`PendingResult.result`
materializes. Holding a handle while dispatching the next batch overlaps the
readback — the dominant per-batch cost on a remote-attached device — with
the next batch's host prep.
"""

from __future__ import annotations


class PendingResult:
    """Memoizing one-shot handle: ``result()`` runs the deferred
    materialization exactly once and returns the cached value after."""

    __slots__ = ("_fn", "_done", "_res")

    def __init__(self, fn):
        self._fn = fn
        self._done = False
        self._res = None

    def result(self):
        if not self._done:
            self._res = self._fn()
            self._done = True
            self._fn = None
        return self._res


def start_host_copy(arrays) -> None:
    """Kick off async device→host copies so a later ``np.asarray`` finds
    the data already (or nearly) resident instead of paying the full RTT
    at materialization time. Backends without async D2H just sync later."""
    for a in arrays:
        try:
            a.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
