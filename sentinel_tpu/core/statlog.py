"""Async rolling-file stat logging — the generic engine under the block
log and the cluster server's stat lines.

Reference: the embedded EagleEye logger (``CORE/eagleeye``, SURVEY §5):
``EagleEyeRollingFileAppender.java`` (size-rolling file appender),
``EagleEyeLogDaemon.java`` (async flush daemon — hot threads never touch
the filesystem), ``StatLogger/StatRollingData/StatEntry`` (periodic
key→counter rollups onto the appender). This module provides the same
split re-designed for the engine: a bounded in-memory line queue drained
by one daemon thread per appender (rotation included), plus a generic
periodic rollup logger; :class:`sentinel_tpu.core.logs.BlockStatLogger`
and the token server's stat log ride it.

Loss is bounded and VISIBLE, never blocking: a full queue drops new lines
and the next drain appends one ``__appender_dropped__`` marker with the
count (EagleEye increments a discard counter on its ringbuffer).
"""

from __future__ import annotations

import atexit
import os
import threading
import weakref
from collections import deque
from typing import Dict, Optional, Tuple

__all__ = ["AsyncRollingAppender", "StatLogger"]

_DEFAULT_MAX_BYTES = 300 * 1024 * 1024
# weak registry: abandoned appenders stay collectable; atexit flushes
# whatever is still alive
_all_appenders: "weakref.WeakSet[AsyncRollingAppender]" = weakref.WeakSet()
_all_lock = threading.Lock()
# a drained daemon parks this many intervals with an empty queue, then
# exits (the next append revives it) — long-lived idle loggers don't pin
# a thread each for the life of the process
_IDLE_WAKEUPS_BEFORE_EXIT = 60


def _flush_all_at_exit() -> None:   # pragma: no cover — interpreter exit
    with _all_lock:
        apps = list(_all_appenders)
    for a in apps:
        try:
            a.flush()
        except Exception:
            pass


atexit.register(_flush_all_at_exit)


class AsyncRollingAppender:
    """Size-rolling file appender with an async flush daemon.

    ``append`` is wait-free for the caller: it enqueues into a bounded
    buffer (full buffer ⇒ the line is dropped and counted, never blocks)
    and the daemon thread drains every ``flush_interval_s`` — or sooner
    when the buffer passes half full. Rotation keeps ``backups`` numbered
    files (``name.1`` newest) and happens on the drain thread only, so
    the hot path never stats or opens files. ``flush()`` drains
    synchronously (shutdown hooks, tests)."""

    def __init__(self, path: str, *, max_bytes: int = _DEFAULT_MAX_BYTES,
                 backups: int = 3, flush_interval_s: float = 1.0,
                 queue_cap: int = 65536):
        self.path = path
        self._max_bytes = max_bytes
        self._backups = backups
        self._interval = flush_interval_s
        self._cap = queue_cap
        self._q: deque = deque()
        self._q_lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._dropped = 0
        self._thread: Optional[threading.Thread] = None
        with _all_lock:
            _all_appenders.add(self)

    # ------------------------------------------------------------ hot path
    def append(self, line: str) -> bool:
        """Enqueue one line (no trailing newline). False = dropped."""
        with self._q_lock:
            if len(self._q) >= self._cap:
                self._dropped += 1
                return False
            self._q.append(line)
            depth = len(self._q)
        self._ensure_daemon()
        if depth >= self._cap // 2:
            self._wake.set()
        return True

    def append_many(self, lines) -> int:
        """Enqueue many lines → number accepted."""
        n = 0
        with self._q_lock:
            room = self._cap - len(self._q)
            for line in lines:
                if n >= room:
                    self._dropped += 1
                    continue
                self._q.append(line)
                n += 1
            depth = len(self._q)
        self._ensure_daemon()
        if depth >= self._cap // 2:
            self._wake.set()
        return n

    # ------------------------------------------------------------ drain
    def flush(self) -> None:
        """Drain the queue to disk NOW, on the calling thread."""
        self._drain()

    def close(self) -> None:
        """Terminal: drain, stop the daemon, unregister. Lines appended
        after close() only reach disk via an explicit flush()."""
        self._stop.set()
        self._wake.set()
        t = self._thread  # graftlint: disable=LOCK002 -- benign: _stop is set before the read; joining a stale thread handle is harmless
        if t is not None and t.is_alive():
            t.join(timeout=5)
        self._drain()
        with _all_lock:
            _all_appenders.discard(self)

    def _ensure_daemon(self) -> None:
        if self._thread is not None and self._thread.is_alive():  # graftlint: disable=LOCK002 -- double-checked locking: this lock-free check is re-verified under _q_lock before spawning
            return
        with self._q_lock:
            if self._thread is not None and self._thread.is_alive():
                return
            if self._stop.is_set():
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"statlog-flush:{os.path.basename(self.path)}")
            self._thread.start()

    def _run(self) -> None:
        idle = 0
        while not self._stop.is_set():
            self._wake.wait(self._interval)
            self._wake.clear()
            with self._q_lock:
                empty = not self._q and not self._dropped
            if empty:
                idle += 1
                if idle >= _IDLE_WAKEUPS_BEFORE_EXIT:
                    # exit is announced under the queue lock so a racing
                    # append either lands where this check sees it, or
                    # finds _thread cleared and revives the daemon
                    with self._q_lock:
                        if not self._q and not self._dropped:
                            self._thread = None
                            return
                    idle = 0
                continue
            idle = 0
            try:
                self._drain()
            except Exception:   # pragma: no cover — daemon must survive
                pass

    def _drain(self) -> None:
        with self._q_lock:
            if not self._q and not self._dropped:
                return
            lines, self._q = self._q, deque()
            dropped, self._dropped = self._dropped, 0
        with self._io_lock:
            try:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                if (os.path.exists(self.path)
                        and os.path.getsize(self.path) > self._max_bytes):
                    for i in range(self._backups - 1, 0, -1):
                        src = f"{self.path}.{i}"
                        if os.path.exists(src):
                            os.replace(src, f"{self.path}.{i + 1}")
                    os.replace(self.path, f"{self.path}.1")
                with open(self.path, "a", encoding="utf-8") as fh:
                    for line in lines:
                        fh.write(line + "\n")
                    if dropped:
                        fh.write(f"__appender_dropped__|{dropped}\n")
            except OSError:   # pragma: no cover — never break callers on IO
                pass


class StatLogger:
    """Generic periodic key→counter rollup onto an async appender
    (reference ``StatLogger``/``StatRollingData``: entries accumulate in
    memory per period and flush as one line per key).

    Line format: ``ms|k1,k2,...|v1,v2,...`` — the same shape the block
    log and the token server's per-second stat lines use. ``max_entries``
    bounds distinct keys per period (overflow keys are dropped and
    surfaced as one ``__dropped__`` line, maxEntryCount=6000 in the
    reference)."""

    def __init__(self, name: str, clock, base_dir: Optional[str] = None,
                 *, period_ms: int = 1000, max_entries: int = 6000,
                 max_bytes: int = _DEFAULT_MAX_BYTES, backups: int = 3,
                 appender: Optional[AsyncRollingAppender] = None):
        from sentinel_tpu.core.logs import log_base_dir
        self.name = name
        self._clock = clock
        self._period = max(1, period_ms)
        self._max_entries = max_entries
        self.appender = appender or AsyncRollingAppender(
            os.path.join(base_dir or log_base_dir(), f"{name}.log"),
            max_bytes=max_bytes, backups=backups)
        self._lock = threading.Lock()
        self._bucket = 0
        self._counts: Dict[Tuple[str, ...], list] = {}
        self._overflow = 0

    def stat(self, *key: str, values=(1,)) -> None:
        """Accumulate ``values`` (ints) under ``key`` for this period."""
        bucket = self._clock.now_ms() // self._period
        pending = None
        with self._lock:
            if bucket != self._bucket and self._counts:
                pending = (self._bucket, self._counts, self._overflow)
                self._counts = {}
                self._overflow = 0
            self._bucket = bucket
            cur = self._counts.get(key)
            if cur is None:
                if len(self._counts) >= self._max_entries:
                    self._overflow += 1
                    cur = None
                else:
                    cur = self._counts[key] = [0] * len(values)
            if cur is not None:
                for i, v in enumerate(values):
                    cur[i] += v
        if pending:
            self._emit(*pending)

    def flush(self) -> None:
        with self._lock:
            pending = (self._bucket, self._counts, self._overflow)
            self._counts = {}
            self._overflow = 0
        if pending[1] or pending[2]:
            self._emit(*pending)
        self.appender.flush()

    def _emit(self, bucket: int, counts: Dict, overflow: int) -> None:
        ms = bucket * self._period
        lines = [f"{ms}|{','.join(k)}|{','.join(str(v) for v in vs)}"
                 for k, vs in counts.items()]
        if overflow:
            lines.append(f"{ms}|__dropped__|{overflow}")
        self.appender.append_many(lines)
