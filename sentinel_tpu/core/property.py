"""Observable config cells.

Reference: ``sentinel-core/.../property/DynamicSentinelProperty.java`` — every
hot-reloadable knob (rules, sample counts, cluster config) is a property cell
with listeners; rule managers subscribe and rebuild derived state on update.
Same pattern here: datasources push into a cell, the rule manager listener
recompiles the device rule tables and swaps them atomically.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")


class SentinelProperty(Generic[T]):
    def __init__(self, value: Optional[T] = None):
        self._value = value
        self._listeners: List[Callable[[T], None]] = []
        # RLock, and listeners fire WHILE HELD: guarantees each listener sees
        # a total order of values (initial fire can't race an update_value and
        # deliver stale-last). Listeners may re-enter the property.
        self._lock = threading.RLock()

    def get(self) -> Optional[T]:
        return self._value

    def add_listener(self, listener: Callable[[T], None]) -> None:
        """Registers and immediately fires with the current value if set
        (reference: PropertyListener.configLoad on register)."""
        with self._lock:
            self._listeners.append(listener)
            if self._value is not None:
                listener(self._value)

    def remove_listener(self, listener: Callable[[T], None]) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def update_value(self, new_value: T) -> bool:
        """Fires listeners only when the value actually changed
        (DynamicSentinelProperty.updateValue)."""
        with self._lock:
            if self._value == new_value:
                return False
            self._value = new_value
            for listener in list(self._listeners):
                listener(new_value)
        return True
