"""Layered startup configuration.

Reference: ``sentinel-core/.../config/SentinelConfig.java:54-70`` +
``SentinelConfigLoader`` — precedence JVM props > config file > env. Here:
explicit kwargs > ``SENTINEL_TPU_*`` env vars > properties file named by
``SENTINEL_TPU_CONFIG_FILE`` > defaults. All runtime-mutable knobs are held in
:class:`~sentinel_tpu.core.property.SentinelProperty` cells by their owners;
this module only covers boot-time constants and capacity planning (which fix
tensor shapes and therefore can't hot-swap without a state migration).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


@dataclasses.dataclass(frozen=True)
class SentinelConfig:
    # Identity (reference: app name/type keys, SentinelConfig.java:54)
    app_name: str = "sentinel-tpu-app"
    app_type: int = 0

    # Capacity planning — these size the device tensors. The reference caps at
    # 6,000 slot chains / 2,000 contexts (Constants.java:37-38) and silently
    # stops checking beyond; we pre-allocate instead and the registry can
    # evict. Row 0 is reserved for the global inbound ENTRY_NODE.
    max_resources: int = 8192
    max_origins: int = 1024
    max_flow_rules: int = 4096
    max_degrade_rules: int = 4096
    max_system_rules: int = 64
    max_authority_rules: int = 1024
    max_param_rules: int = 512
    max_rules_per_resource: int = 4  # K in the per-event rule gather
    param_table_slots: int = 65536   # hot-key rows (ParameterMetric LRU cap analog)
    param_pairs_per_event: int = 4   # PV — (rule, value) checks per entry

    # Statistics windows (reference: SampleCountProperty SAMPLE_COUNT=2,
    # IntervalProperty INTERVAL=1000; minute window 60×1000ms)
    second_sample_count: int = 2
    second_interval_ms: int = 1000
    minute_enabled: bool = True

    # Occupy / prioritized borrow (OccupyTimeoutProperty default 500ms)
    occupy_timeout_ms: int = 500

    # Statistic max RT (SentinelConfig.java:69 default 5000)
    statistic_max_rt: int = 5000

    # Metric log (SentinelConfig.java:66-67 defaults 50MB × 6)
    metric_log_dir: str = ""
    metric_log_single_size: int = 50 * 1024 * 1024
    metric_log_total_count: int = 6
    metric_flush_interval_sec: int = 1

    # Transport (TransportConfig.java: api port 8719, heartbeat 10s)
    api_port: int = 8719
    dashboard_server: str = ""
    heartbeat_interval_ms: int = 10_000

    # Cluster (ClusterConstants: port 18730, request timeout 20ms)
    cluster_port: int = 18730
    cluster_request_timeout_ms: int = 20
    cluster_max_qps_per_namespace: float = 30_000.0  # ServerFlowConfig.java:31

    # Host batching
    batch_size: int = 1024

    # Host-side fast path (SURVEY §7 hard-part 1: the local analog of
    # fallbackToLocalOrPass). Rule-free resources decide on host with
    # batched device stat recording; resources with one simple QPS rule
    # serve from a host-held token lease pre-charged through the device
    # pipeline. Disabled automatically while system rules are loaded.
    host_fast_path: bool = True
    fast_path_flush_events: int = 1024   # buffered stat events per flush
    fast_path_flush_ms: int = 20         # max staleness of buffered stats
    fast_path_lease_fraction: float = 0.5  # lease chunk = count × fraction

    # Warm-up cold factor (SentinelConfig default 3)
    cold_factor: int = 3

    # Thread-gauge elision: when nothing loaded READS live concurrency
    # (no THREAD-grade flow/param rules, no system rules), the gauge-
    # maintenance scatters are elided from the hot steps and the gauges
    # read 0 (reference readers: DefaultController THREAD branch,
    # SystemRuleManager.checkSystem, ParamFlowChecker THREAD mode).
    # Set True to always maintain the gauges — live-concurrency
    # observability (dashboard threadNum) at ~20% step-floor cost.
    thread_gauge_always: bool = False

    # Persistent XLA compilation-cache directory (cold-start story,
    # core/compile_cache.py). None/"" = the default
    # ~/.cache/sentinel_tpu/xla; SENTINEL_COMPILE_CACHE=off disables.
    compile_cache_dir: str = ""

    def __post_init__(self) -> None:
        if not 1 <= self.max_rules_per_resource <= 31:
            # the per-rule cluster-fallback mask is an int32 bitmask over
            # the per-resource rule slots — slot 31+ would overflow it
            raise ValueError("max_rules_per_resource must be in [1, 31]")

    def metric_dir(self) -> str:
        if self.metric_log_dir:
            return self.metric_log_dir
        return os.path.join(os.path.expanduser("~"), "logs", "csp")


_ENV_PREFIX = "SENTINEL_TPU_"

_FIELD_TYPES = {f.name: f.type for f in dataclasses.fields(SentinelConfig)}


def _coerce(name: str, raw: str):
    ftype = _FIELD_TYPES.get(name, "str")
    if ftype in ("int", int):
        return int(raw)
    if ftype in ("float", float):
        return float(raw)
    if ftype in ("bool", bool):
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return raw


def load_config(**overrides) -> SentinelConfig:
    """defaults < properties file < env < explicit kwargs."""
    values = {}
    cfg_file = os.environ.get(_ENV_PREFIX + "CONFIG_FILE")
    if cfg_file and os.path.isfile(cfg_file):
        with open(cfg_file) as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                k, _, v = line.partition("=")
                k = k.strip().lower()
                if k in _FIELD_TYPES:
                    values[k] = _coerce(k, v.strip())
    for name in _FIELD_TYPES:
        raw = os.environ.get(_ENV_PREFIX + name.upper())
        if raw is not None:
            values[name] = _coerce(name, raw)
    for k, v in overrides.items():
        if k not in _FIELD_TYPES:
            raise TypeError(f"unknown config field: {k}")
        values[k] = _coerce(k, v) if isinstance(v, str) else v
    cfg = SentinelConfig(**values)
    for f in dataclasses.fields(SentinelConfig):
        got = getattr(cfg, f.name)
        want = {int: int, float: (int, float), bool: bool, str: str}.get(
            f.type if isinstance(f.type, type) else {"int": int, "float": float,
                                                     "bool": bool, "str": str}.get(f.type, str))
        if want and not isinstance(got, want):
            raise TypeError(f"config field {f.name} expects {f.type}, got {type(got).__name__}")
    return cfg
