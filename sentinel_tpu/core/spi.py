"""Provider SPI loader — the Python analog of the reference's custom
service loader (``spi/SpiLoader.java:73-179``, ``spi/Spi.java``).

The reference discovers providers through ``META-INF/services`` files and
an ``@Spi(value, order, isDefault)`` annotation; the Python equivalents
are:

* direct registration — the :func:`spi` decorator (or
  :meth:`SpiLoader.register`) at import time of the providing module;
* the ``SENTINEL_TPU_PLUGINS`` environment variable — a comma-separated
  list of module paths imported once on first SPI access (the analog of
  dropping a provider jar on the classpath); importing the module runs its
  ``@spi`` decorators;
* ``importlib.metadata`` entry points in group ``sentinel_tpu.plugins``
  (for installed packages), loaded on the same first access.

Semantics preserved from the reference: providers carry an ``order``
(lower sorts first; default ``LOWEST_PRECEDENCE`` like
``InitOrder.LOWEST_PRECEDENCE``), an optional alias, and an optional
``is_default`` flag; instances are singletons per provider unless the
caller asks for fresh instances (``load_new_instance_list_sorted`` — used
for per-engine providers such as processor slots, whose state must not be
shared between Sentinel instances).

Well-known service names (the analog of the reference's SPI interfaces):

* ``init_func`` — ``fn(sentinel)`` startup hooks (``InitFunc.java``),
  executed once per process by
  :class:`~sentinel_tpu.core.initexec.InitExecutor`.
* ``processor_slot`` — :class:`~sentinel_tpu.engine.slots.HostGate` /
  :class:`~sentinel_tpu.engine.slots.DeviceSlot` subclasses, auto-
  registered into every new ``Sentinel`` (``ProcessorSlot`` SPI,
  ``DefaultSlotChainBuilder.java:39``).
* ``command_handler`` — callables with ``command_name``/``command_desc``
  attributes, auto-registered into every command center built by
  ``register_default_handlers`` (``CommandHandler`` SPI).
"""

from __future__ import annotations

import importlib
import os
import threading
from typing import Any, Dict, List, Optional

LOWEST_PRECEDENCE = 2 ** 31 - 1      # InitOrder.LOWEST_PRECEDENCE

SERVICE_INIT_FUNC = "init_func"
SERVICE_PROCESSOR_SLOT = "processor_slot"
SERVICE_COMMAND_HANDLER = "command_handler"

PLUGINS_ENV = "SENTINEL_TPU_PLUGINS"
ENTRY_POINT_GROUP = "sentinel_tpu.plugins"

_plugins_lock = threading.Lock()
_plugins_loaded = False


def load_plugins(force: bool = False) -> List[str]:
    """Import plugin modules (env var + entry points) exactly once;
    importing runs their ``@spi`` decorators. → names imported this call."""
    global _plugins_loaded
    with _plugins_lock:
        if _plugins_loaded and not force:
            return []
        _plugins_loaded = True
        imported: List[str] = []
        for mod in filter(None,
                          (m.strip() for m in
                           os.environ.get(PLUGINS_ENV, "").split(","))):
            try:
                importlib.import_module(mod)
                imported.append(mod)
            except Exception as exc:
                from sentinel_tpu.core.logs import record_log
                record_log().warning("plugin module %s failed to import: %r",
                                     mod, exc)
        try:
            from importlib.metadata import entry_points
            for ep in entry_points(group=ENTRY_POINT_GROUP):
                try:
                    ep.load()
                    imported.append(ep.name)
                except Exception as exc:
                    from sentinel_tpu.core.logs import record_log
                    record_log().warning(
                        "plugin entry point %s failed: %r", ep.name, exc)
        except Exception:
            pass                      # no importlib.metadata / old API
        return imported


class _Provider:
    __slots__ = ("obj", "alias", "order", "is_default", "seq")

    def __init__(self, obj: Any, alias: str, order: int,
                 is_default: bool, seq: int):
        self.obj = obj
        self.alias = alias
        self.order = order
        self.is_default = is_default
        self.seq = seq


class SpiLoader:
    """One loader per service name; ``SpiLoader.of(service)`` is the
    cached accessor like the reference's ``SpiLoader.of(Class)``."""

    _loaders: Dict[str, "SpiLoader"] = {}
    _global_lock = threading.Lock()

    def __init__(self, service: str):
        self.service = service
        self._lock = threading.Lock()
        self._providers: List[_Provider] = []
        self._singletons: Dict[int, Any] = {}
        self._seq = 0

    # ------------------------------------------------------------- access
    @classmethod
    def of(cls, service: str) -> "SpiLoader":
        with cls._global_lock:
            loader = cls._loaders.get(service)
            if loader is None:
                loader = cls._loaders[service] = SpiLoader(service)
            return loader

    @classmethod
    def reset_and_clear_all(cls) -> None:
        """Test hygiene (reference ``resetAndClearAll``)."""
        global _plugins_loaded
        with cls._global_lock:
            cls._loaders.clear()
        with _plugins_lock:
            _plugins_loaded = False

    # ----------------------------------------------------------- register
    def register(self, provider: Any, *, alias: Optional[str] = None,
                 order: int = LOWEST_PRECEDENCE,
                 is_default: bool = False) -> Any:
        """Register a provider: a class (instantiated lazily, singleton
        per class unless fresh instances are requested) or any
        non-class object/callable used as-is. → the provider (decorator-
        friendly)."""
        name = alias or getattr(provider, "__name__",
                                provider.__class__.__name__)
        with self._lock:
            self._providers.append(_Provider(
                provider, name, int(order), bool(is_default), self._seq))
            self._seq += 1
        return provider

    def unregister(self, provider: Any) -> None:
        with self._lock:
            self._providers = [p for p in self._providers
                               if p.obj is not provider]

    # --------------------------------------------------------------- load
    def _sorted(self) -> List[_Provider]:
        load_plugins()
        with self._lock:
            return sorted(self._providers, key=lambda p: (p.order, p.seq))

    def _instantiate(self, p: _Provider, fresh: bool) -> Any:
        if not isinstance(p.obj, type):
            return p.obj
        if fresh:
            return p.obj()
        with self._lock:
            inst = self._singletons.get(p.seq)
            if inst is None:
                inst = self._singletons[p.seq] = p.obj()
            return inst

    def load_instance_list_sorted(self) -> List[Any]:
        return [self._instantiate(p, False) for p in self._sorted()]

    def load_new_instance_list_sorted(self) -> List[Any]:
        """Fresh instances for class providers — for per-engine services
        (processor slots) whose state must not leak across Sentinels."""
        return [self._instantiate(p, True) for p in self._sorted()]

    def load_highest_priority_instance(self) -> Optional[Any]:
        ps = self._sorted()
        return self._instantiate(ps[0], False) if ps else None

    def load_default_instance(self) -> Optional[Any]:
        """The ``is_default`` provider, else the first sorted (reference
        ``loadFirstInstanceOrDefault``)."""
        ps = self._sorted()
        for p in ps:
            if p.is_default:
                return self._instantiate(p, False)
        return self._instantiate(ps[0], False) if ps else None

    def load_instance_by_alias(self, alias: str) -> Optional[Any]:
        for p in self._sorted():
            if p.alias == alias:
                return self._instantiate(p, False)
        return None

    def aliases(self) -> List[str]:
        return [p.alias for p in self._sorted()]


def spi(service: str, *, alias: Optional[str] = None,
        order: int = LOWEST_PRECEDENCE, is_default: bool = False):
    """Class/function decorator registering a provider (``@Spi`` analog)::

        @spi("processor_slot", order=100)
        class AuditGate(HostGate): ...
    """
    def wrap(provider):
        return SpiLoader.of(service).register(
            provider, alias=alias, order=order, is_default=is_default)
    return wrap
