from sentinel_tpu.core.clock import Clock, ManualClock, SystemClock, global_clock, set_global_clock
from sentinel_tpu.core.config import SentinelConfig, load_config
from sentinel_tpu.core.errors import (
    AuthorityException,
    BlockException,
    BlockReason,
    DegradeException,
    ErrorEntryFreeError,
    FlowException,
    ParamFlowException,
    SentinelError,
    SystemBlockException,
    block_exception_for,
    is_block_exception,
)
from sentinel_tpu.core.property import SentinelProperty
from sentinel_tpu.core.registry import (
    ENTRY_NODE_NAME,
    ENTRY_NODE_ROW,
    OriginRegistry,
    Registry,
    ResourceRegistry,
)

__all__ = [
    "Clock", "ManualClock", "SystemClock", "global_clock", "set_global_clock",
    "SentinelConfig", "load_config",
    "BlockException", "BlockReason", "FlowException", "DegradeException",
    "SystemBlockException", "AuthorityException", "ParamFlowException",
    "SentinelError", "ErrorEntryFreeError", "block_exception_for", "is_block_exception",
    "SentinelProperty",
    "Registry", "ResourceRegistry", "OriginRegistry", "ENTRY_NODE_ROW", "ENTRY_NODE_NAME",
]
