"""Counter-state snapshot / warm restart.

The reference deliberately has NO stats checkpointing (windows are seconds
deep; restart = cold counters — SURVEY §5), and rules persist through
writable datasources. This module keeps that stance but adds the cheap
extra the dense design makes possible: the whole counter state is a handful
of arrays, so a warm restart can resume sliding windows, breaker states,
pacing clocks, and occupy bookings across a process restart (useful when a
restart would otherwise let a burst through the cold windows).

Format: one ``.npz`` with the flattened state pytree + a JSON sidecar of
registry contents (name → row) and the wall-clock epoch, so absolute window
indices stay meaningful. Restore requires identical engine geometry."""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import Optional

import numpy as np
import jax


_META_SUFFIX = ".meta.json"

_FORMAT_VERSION = 1


def _rules_digest(sentinel) -> str:
    """Fingerprint of the loaded rule sets: flow_dyn/breaker state is
    slot-indexed, so restoring it under a different rule compilation would
    attach pacing clocks and breaker states to the wrong rules."""
    from sentinel_tpu.rules import codec
    parts = [codec.rules_to_json(t, g()) for t, g in (
        ("flow", sentinel.get_flow_rules),
        ("degrade", sentinel.get_degrade_rules),
        ("system", sentinel.get_system_rules),
        ("authority", sentinel.get_authority_rules),
        ("paramFlow", sentinel.get_param_flow_rules))]
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def _geometry(sentinel) -> dict:
    s = sentinel.spec
    return {
        "rows": s.rows, "alt_rows": s.alt_rows,
        "second": [s.second.buckets, s.second.win_ms],
        "minute": [s.minute.buckets, s.minute.win_ms] if s.minute else None,
        "param_keys": s.param_keys,
        "max_flow_rules": sentinel.cfg.max_flow_rules,
        "max_degrade_rules": sentinel.cfg.max_degrade_rules,
    }


def save_state(sentinel, path: str) -> None:
    """Snapshot the device state + registries of a Sentinel instance."""
    # land buffered fast-path stats and reconcile live lease remainders
    # first: the restored process knows nothing about host-held tokens, so
    # leaving them reserved would snapshot phantom passes
    sentinel._fast.expire_all()
    sentinel._flush_fast()
    with sentinel._lock:
        leaves, treedef = jax.tree.flatten(sentinel._state)
        arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
        meta = {
            "version": _FORMAT_VERSION,
            "geometry": _geometry(sentinel),
            "rules_digest": _rules_digest(sentinel),
            "epoch_ms": sentinel.epoch_ms,
            "saved_at_ms": sentinel.clock.now_ms(),
            "resources": sentinel.resources.items(),
            "origins": sentinel.origins.items(),
            "contexts": sentinel.contexts.items(),
        }
    # atomic: a crash mid-save must not leave a truncated snapshot that a
    # later warm restart trips over
    npz_final = path if str(path).endswith(".npz") else str(path) + ".npz"
    tmp_npz = f"{npz_final}.{os.getpid()}.tmp"
    with open(tmp_npz, "wb") as fh:
        np.savez(fh, **arrays)
    os.replace(tmp_npz, npz_final)
    tmp_meta = f"{path}{_META_SUFFIX}.{os.getpid()}.tmp"
    Path(tmp_meta).write_text(json.dumps(meta))
    os.replace(tmp_meta, str(path) + _META_SUFFIX)


def load_state(sentinel, path: str):
    """Warm-restore a snapshot into a fresh Sentinel with the same geometry.

    → ``"full"`` (everything restored), ``"partial"`` (the loaded RULES
    differ from the snapshot's: window counters + epoch restore — their
    meaning is keyed by resource rows, which matched — while the
    slot-indexed flow pacing / breaker / hot-param state stays cold, since
    restoring it under a different rule compilation would attach clocks
    and breaker states to the wrong rules), or ``False`` (geometry or
    registry mismatch → cold start, the reference's own restart behavior).
    Both truthy results restore; callers needing exactly-full check
    ``== "full"``. Rules are NOT restored (they live in datasources); load
    rules first, then restore counters.
    """
    meta_path = Path(str(path) + _META_SUFFIX)
    npz_path = Path(path if str(path).endswith(".npz") else str(path) + ".npz")
    if not meta_path.exists() or not npz_path.exists():
        return False
    try:
        meta = json.loads(meta_path.read_text())
        data = np.load(npz_path)
    except Exception:        # truncated/corrupt snapshot → cold start
        return False
    if meta.get("version") != _FORMAT_VERSION:
        return False
    if meta.get("geometry") != _geometry(sentinel):
        return False
    digest_ok = meta.get("rules_digest") == _rules_digest(sentinel)
    with sentinel._lock:
        leaves, treedef = jax.tree.flatten(sentinel._state)
        if len(leaves) != len(data.files):
            return False
        restored = []
        for i, cur in enumerate(leaves):
            arr = data[f"leaf_{i}"]
            if tuple(arr.shape) != tuple(cur.shape):
                return False
            restored.append(arr.astype(cur.dtype))
        # registries FIRST (before touching device state): re-intern in
        # row-id order so a fresh registry assigns the same ids (LRU
        # iteration order ≠ allocation order). Snapshots taken after
        # evictions have id holes and restore cold — fine, the reference
        # never restores counters at all. On mismatch the instance stays
        # cold (some names pre-interned, counters untouched).
        for reg_name, reg in (("resources", sentinel.resources),
                              ("origins", sentinel.origins),
                              ("contexts", sentinel.contexts)):
            for name, rid in sorted(meta[reg_name], key=lambda p: p[1]):
                if reg.get_or_create(name) != rid:
                    return False      # interning drifted: treat as cold
        full = jax.tree.unflatten(treedef, restored)
        if digest_ok:
            # live-concurrency counters must NOT survive: the snapshot's
            # in-flight entries never exit in this process, so restored
            # thread counts would be phantom forever (threads only
            # decrement at exit)
            new_state = full._replace(
                threads=sentinel._state.threads,
                alt_threads=sentinel._state.alt_threads)
        else:
            # degraded restore-what-matches: rules changed since the
            # snapshot → windows (row-keyed, still meaningful) carry over,
            # slot-indexed dyn state stays cold
            new_state = sentinel._state._replace(
                second=full.second, minute=full.minute,
                alt_second=full.alt_second)
        sentinel._state = new_state
        # meshed engines: restored host arrays must land on their canonical
        # shardings, not default single-device placement
        sentinel._pin_state_locked()
        # window indices are derived from absolute wall time, so they stay
        # valid across the restart; the relative-ms epoch must carry over
        # for pacing clocks/warm-up state to stay meaningful
        sentinel.epoch_ms = meta["epoch_ms"]
    return "full" if digest_ok else "partial"
