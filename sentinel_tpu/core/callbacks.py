"""Entry/exit callback hooks (reference
``StatisticSlotCallbackRegistry`` — onPass/onBlocked hooks StatisticSlot
fires around its recording — and the ``MetricExtension`` SPI
(``metric/extension/MetricExtension.java``) that external metric systems
plug into; the param-flow extension and metric exporters attach here in the
reference).

Handlers run on the calling thread after the decision; they must be fast
and must not raise (exceptions are swallowed into the record log, like SPI
callback failures in the reference).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence

from sentinel_tpu.core.logs import record_log

# handler signatures
OnPass = Callable[[str, str, int, Sequence], None]          # resource, origin, acquire, args
OnBlocked = Callable[[str, str, int, BaseException], None]  # resource, origin, acquire, exc
OnExit = Callable[[str, int, bool, int], None]              # resource, rt_ms, error, acquire


class StatisticCallbackRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._on_pass: List[OnPass] = []
        self._on_blocked: List[OnBlocked] = []
        self._on_exit: List[OnExit] = []

    # registration (addEntryCallback / addExitCallback)
    def add_pass_handler(self, fn: OnPass) -> None:
        with self._lock:
            self._on_pass = self._on_pass + [fn]

    def add_blocked_handler(self, fn: OnBlocked) -> None:
        with self._lock:
            self._on_blocked = self._on_blocked + [fn]

    def add_exit_handler(self, fn: OnExit) -> None:
        with self._lock:
            self._on_exit = self._on_exit + [fn]

    def clear(self) -> None:
        with self._lock:
            self._on_pass, self._on_blocked, self._on_exit = [], [], []

    @property
    def empty(self) -> bool:
        return not (self._on_pass or self._on_blocked or self._on_exit)  # graftlint: disable=LOCK002 -- copy-on-write lists: writers swap whole lists under the lock; lock-free reads see one coherent snapshot

    # dispatch (copy-on-write lists: iteration is lock-free)
    def fire_pass(self, resource: str, origin: str, acquire: int,
                  args: Sequence = ()) -> None:
        for fn in self._on_pass:  # graftlint: disable=LOCK002 -- copy-on-write list swap under the lock; lock-free iteration is the documented dispatch contract
            try:
                fn(resource, origin, acquire, args)
            except Exception as exc:
                record_log().warning("onPass callback failed: %r", exc)

    def fire_blocked(self, resource: str, origin: str, acquire: int,
                     exc_val: Optional[BaseException]) -> None:
        for fn in self._on_blocked:
            try:
                fn(resource, origin, acquire, exc_val)
            except Exception as exc:
                record_log().warning("onBlocked callback failed: %r", exc)

    def fire_exit(self, resource: str, rt_ms: int, error: bool,
                  acquire: int) -> None:
        for fn in self._on_exit:  # graftlint: disable=LOCK002 -- copy-on-write list swap under the lock; lock-free iteration is the documented dispatch contract
            try:
                fn(resource, rt_ms, error, acquire)
            except Exception as exc:
                record_log().warning("onExit callback failed: %r", exc)
