"""Block-exception hierarchy and verdict reason codes.

Mirrors the reference's ``BlockException`` family
(``sentinel-core/.../slots/block/*``): one subclass per rule engine, carrying
the triggering rule. The device pipeline returns an ``int8`` reason code per
event (it cannot raise), and the host runtime maps codes to these exceptions
at the API boundary, preserving ``SphU.entry`` semantics (throw on block).
"""

from __future__ import annotations

from typing import Any, Optional


class BlockReason:
    """Verdict reason codes produced by the device pipeline (int8)."""

    NONE = 0
    FLOW = 1
    DEGRADE = 2
    SYSTEM = 3
    AUTHORITY = 4
    PARAM_FLOW = 5
    # codes >= CUSTOM_BASE are user ProcessorSlots (reference: custom slots
    # inserted via SlotChainBuilder SPI throw their own BlockException
    # subclasses). Two disjoint sub-spaces of the int8 range:
    # CUSTOM_BASE + i  = registered DeviceSlot i (emitted by the pipeline)
    # CUSTOM_GATE_BASE + i = registered HostGate i (emitted host-side)
    CUSTOM_BASE = 16
    CUSTOM_GATE_BASE = 96

    NAMES = {
        NONE: "none",
        FLOW: "FlowException",
        DEGRADE: "DegradeException",
        SYSTEM: "SystemBlockException",
        AUTHORITY: "AuthorityException",
        PARAM_FLOW: "ParamFlowException",
    }


class SentinelError(Exception):
    """Base for framework errors that are NOT flow-control verdicts."""


class ErrorEntryFreeError(SentinelError):
    """Mis-paired entry/exit (reference: ErrorEntryFreeException)."""


class BlockException(Exception):
    """A guarded call was denied. Reference: ``BlockException``."""

    reason_code = BlockReason.NONE

    def __init__(self, resource: str, rule: Optional[Any] = None,
                 origin: str = "", wait_ms: int = 0):
        self.resource = resource
        self.rule = rule
        self.origin = origin
        self.wait_ms = wait_ms
        super().__init__(f"{type(self).__name__}: resource={resource!r} origin={origin!r}")


class FlowException(BlockException):
    reason_code = BlockReason.FLOW


class DegradeException(BlockException):
    reason_code = BlockReason.DEGRADE


class SystemBlockException(BlockException):
    reason_code = BlockReason.SYSTEM


class AuthorityException(BlockException):
    reason_code = BlockReason.AUTHORITY


class ParamFlowException(BlockException):
    reason_code = BlockReason.PARAM_FLOW


class CustomSlotException(BlockException):
    """A user ProcessorSlot denied the entry. ``slot_name`` names the
    registered slot (the analog of a custom BlockException subclass from a
    slot-chain-SPI slot)."""

    reason_code = BlockReason.CUSTOM_BASE

    def __init__(self, resource: str, rule: Optional[Any] = None,
                 origin: str = "", wait_ms: int = 0, slot_name: str = ""):
        self.slot_name = slot_name
        super().__init__(resource, rule=rule, origin=origin, wait_ms=wait_ms)


_BY_CODE = {
    BlockReason.FLOW: FlowException,
    BlockReason.DEGRADE: DegradeException,
    BlockReason.SYSTEM: SystemBlockException,
    BlockReason.AUTHORITY: AuthorityException,
    BlockReason.PARAM_FLOW: ParamFlowException,
}


def exception_name_for(code: int) -> str:
    """Exception class name for a BlockReason code (block-log lines)."""
    if int(code) >= BlockReason.CUSTOM_BASE:
        return CustomSlotException.__name__
    return _BY_CODE.get(int(code), BlockException).__name__


def block_exception_for(code: int, resource: str, origin: str = "",
                        wait_ms: int = 0, rule: Optional[Any] = None,
                        slot_name: str = "") -> BlockException:
    if int(code) >= BlockReason.CUSTOM_BASE:
        return CustomSlotException(resource, rule=rule, origin=origin,
                                   wait_ms=wait_ms, slot_name=slot_name)
    cls = _BY_CODE.get(int(code), BlockException)
    return cls(resource, rule=rule, origin=origin, wait_ms=wait_ms)


def is_block_exception(exc: BaseException) -> bool:
    return isinstance(exc, BlockException)
