"""Resource / origin registries: string name → dense row id.

The reference keys everything by string resource name inside copy-on-write
maps (``CtSph.lookProcessChain``, ``ClusterBuilderSlot`` resource→ClusterNode)
and hard-caps at 6,000 chains / 2,000 contexts (``Constants.java:37-38``),
silently skipping checks beyond the cap. Here the registry maps names to rows
of the dense counter tensors. Capacity is pre-allocated (tensor shapes are
static under jit); on overflow we evict the least-recently-entered unpinned
row instead of silently disabling checks — strictly better than the
reference's behavior.

Evicted row ids are queued; the runtime drains them via :meth:`drain_evicted`
and invalidates those rows' window state on the next device step (see
``stats.window.invalidate_rows``) so a recycled row never inherits the evicted
resource's live counters.

Row 0 is reserved for the global inbound aggregate (reference
``Constants.ENTRY_NODE``), used by the system-adaptive slot.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, Iterable, List, Optional, Tuple

ENTRY_NODE_ROW = 0
ENTRY_NODE_NAME = "__entry_node__"


class Registry:
    """Thread-safe name→id allocator, O(1) LRU eviction on overflow."""

    def __init__(self, capacity: int, reserved: Iterable[str] = ()):  # rows [0, capacity)
        reserved = tuple(reserved)
        if capacity < 1 + len(reserved):
            raise ValueError("capacity too small")
        self._capacity = capacity
        self._lock = threading.Lock()
        # OrderedDict in LRU order: oldest first; move_to_end on touch.
        self._name_to_id: "collections.OrderedDict[str, int]" = collections.OrderedDict()
        self._id_to_name: List[Optional[str]] = [None] * capacity
        self._next = 0
        self._free: List[int] = []
        self._pinned: set = set()
        self._evicted_pending: List[int] = []
        for name in reserved:
            rid = self._alloc_locked(name)
            self._pinned.add(rid)

    @property
    def capacity(self) -> int:
        return self._capacity

    def _alloc_locked(self, name: str) -> int:
        if self._free:
            rid = self._free.pop()
        elif self._next < self._capacity:
            rid = self._next
            self._next += 1
        else:
            rid = self._evict_locked()
        self._name_to_id[name] = rid
        self._id_to_name[rid] = name
        return rid

    def _evict_locked(self) -> int:
        for victim, rid in self._name_to_id.items():
            if rid not in self._pinned:
                del self._name_to_id[victim]
                self._id_to_name[rid] = None
                self._evicted_pending.append(rid)
                return rid
        raise RuntimeError("registry full and all rows pinned")

    def get_or_create(self, name: str) -> int:
        with self._lock:
            rid = self._name_to_id.get(name)
            if rid is None:
                rid = self._alloc_locked(name)
            else:
                self._name_to_id.move_to_end(name)
            return rid

    def lookup(self, name: str) -> Optional[int]:
        with self._lock:
            return self._name_to_id.get(name)

    def name_of(self, rid: int) -> Optional[str]:
        with self._lock:
            if 0 <= rid < self._capacity:
                return self._id_to_name[rid]
            return None

    def pin(self, name: str) -> int:
        """Allocate and protect from eviction (rule-referenced resources)."""
        with self._lock:
            rid = self._name_to_id.get(name)
            if rid is None:
                rid = self._alloc_locked(name)
            self._pinned.add(rid)
            return rid

    def unpin(self, name: str) -> None:
        with self._lock:
            rid = self._name_to_id.get(name)
            if rid is not None:
                self._pinned.discard(rid)

    def evict_name(self, name: str) -> bool:
        """Targeted eviction (the tiering ticker's proactive demotion):
        drop ``name``'s row to the free list and queue it for the next
        invalidation drain, exactly as an LRU overflow would. Refuses
        pinned or unknown names."""
        with self._lock:
            rid = self._name_to_id.get(name)
            if rid is None or rid in self._pinned:
                return False
            del self._name_to_id[name]
            self._id_to_name[rid] = None
            self._evicted_pending.append(rid)
            self._free.append(rid)
            return True

    def drain_evicted(self) -> List[int]:
        """Row ids recycled since the last drain; caller must invalidate their
        window state before the rows serve a new resource's decisions."""
        with self._lock:
            out = self._evicted_pending
            self._evicted_pending = []
            return out

    def items(self) -> List[Tuple[str, int]]:
        with self._lock:
            return list(self._name_to_id.items())

    def __len__(self) -> int:
        with self._lock:
            return len(self._name_to_id)


class ResourceRegistry(Registry):
    def __init__(self, capacity: int):
        super().__init__(capacity, reserved=(ENTRY_NODE_NAME,))


class OriginRegistry(Registry):
    """Origin "" (unknown caller) is id 0, parity with empty-origin checks."""

    DEFAULT_ORIGIN_ID = 0

    def __init__(self, capacity: int):
        super().__init__(capacity, reserved=("",))


def make_registry(capacity: int, reserved: Iterable[str] = ()):
    """Registry factory: the C++ table when buildable (g++, cached .so),
    else the pure-Python implementation — identical semantics either way.
    ``SENTINEL_TPU_NATIVE=0`` forces Python."""
    try:
        from sentinel_tpu.native import NativeRegistry, native_available
        if native_available():
            return NativeRegistry(capacity, reserved)
    except Exception:
        pass
    return Registry(capacity, reserved)


def make_resource_registry(capacity: int):
    return make_registry(capacity, reserved=(ENTRY_NODE_NAME,))


def make_origin_registry(capacity: int):
    return make_registry(capacity, reserved=("",))
