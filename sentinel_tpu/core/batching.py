"""Host-side batch staging helpers shared by the local and cluster engines.

Batches are padded to powers of two so jit compiles a small, reused set of
shapes (the analog of the reference compiling one slot chain per resource,
``CtSph.lookProcessChain`` — here one executable per batch shape).
"""

from __future__ import annotations

import numpy as np


def pad_pow2(n: int, floor: int = 8) -> int:
    """Smallest power of two >= max(n, floor)."""
    b = floor
    while b < n:
        b *= 2
    return b


def pad_to(arr, b: int, fill, dtype) -> np.ndarray:
    """Copy ``arr`` into a length-``b`` array padded with ``fill``."""
    out = np.full(b, fill, dtype)
    n = arr.shape[0] if hasattr(arr, "shape") else len(arr)
    out[:n] = arr
    return out


def pad_into(dst: np.ndarray, arr, fill) -> np.ndarray:
    """In-place :func:`pad_to` against a preallocated staging buffer:
    fill ``dst[:n]`` from ``arr`` and ``dst[n:]`` with ``fill`` → ``dst``.
    The caller owns the reuse discipline (the runtime's staging ring
    rotates buffers so a slot is not rewritten while a dispatch built
    from it could still be reading)."""
    n = arr.shape[0] if hasattr(arr, "shape") else len(arr)
    dst[:n] = arr
    if n < dst.shape[0]:
        dst[n:] = fill
    return dst
