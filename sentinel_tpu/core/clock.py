"""Injectable time source.

The reference routes *every* time read through a single cached clock
(``sentinel-core/.../util/TimeUtil.java:222``), which is what makes its whole
test suite deterministic (``AbstractTimeBasedTest`` PowerMocks it). We preserve
that property structurally: device code receives ``now_ms`` as an explicit
scalar argument, and host code reads time only through a ``Clock`` object that
tests can replace with :class:`ManualClock`.

Unlike the reference's adaptive cached-millis thread (TimeUtil RUNNING/IDLE
modes, needed because ``System.currentTimeMillis`` is a contended vDSO call at
>1M qps), the host here reads time once per *batch*, so a plain monotonic read
is already off the hot path.
"""

from __future__ import annotations

import threading
import time


class Clock:
    """Wall-clock milliseconds. Base class doubles as the system clock."""

    def now_ms(self) -> int:
        return time.time_ns() // 1_000_000

    def sleep_ms(self, ms: int) -> None:
        if ms > 0:
            time.sleep(ms / 1000.0)


SystemClock = Clock


class ManualClock(Clock):
    """Deterministic clock for tests (parity with AbstractTimeBasedTest).

    ``set_ms`` / ``advance_ms`` step virtual time; ``sleep_ms`` advances it
    instead of blocking, so throttling-wait tests run instantly.
    """

    def __init__(self, start_ms: int = 1_000_000):
        self._ms = start_ms
        self._lock = threading.Lock()

    def now_ms(self) -> int:
        with self._lock:
            return self._ms

    def set_ms(self, ms: int) -> None:
        with self._lock:
            self._ms = ms

    def advance_ms(self, delta: int) -> None:
        with self._lock:
            self._ms += delta

    def sleep_ms(self, ms: int) -> None:
        if ms > 0:
            self.advance_ms(int(ms))


_global_clock: Clock = SystemClock()


def global_clock() -> Clock:
    return _global_clock


def set_global_clock(clock: Clock) -> Clock:
    """Install a clock process-wide; returns the previous one."""
    global _global_clock
    prev = _global_clock
    _global_clock = clock
    return prev
