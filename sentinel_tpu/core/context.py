"""Per-thread call context (ContextUtil analog).

Reference: ``sentinel-core/.../context/ContextUtil.java`` — a ThreadLocal
holding the context name (entrance) and origin (caller app); adapters call
``ContextUtil.enter(contextName, origin)`` before ``SphU.entry``. The context
name keys CHAIN-strategy flow rules and the entrance-node aggregation; the
origin keys authority checks and origin-specific flow rules.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

DEFAULT_CONTEXT_NAME = "sentinel_default_context"


@dataclasses.dataclass
class Context:
    name: str = DEFAULT_CONTEXT_NAME
    origin: str = ""


_tls = threading.local()


def current_context() -> Context:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        ctx = Context()
        _tls.ctx = ctx
    return ctx


def enter_context(name: str, origin: str = "") -> Context:
    """Reference ``ContextUtil.enter`` (names beyond the registry capacity
    degrade to the shared default context at lookup time, not here)."""
    ctx = Context(name=name or DEFAULT_CONTEXT_NAME, origin=origin or "")
    _tls.ctx = ctx
    return ctx


def exit_context() -> None:
    _tls.ctx = None


class ContextScope:
    """``with ContextScope("entrance", origin="app-a"): ...``"""

    def __init__(self, name: str, origin: str = ""):
        self._name = name
        self._origin = origin
        self._prev: Optional[Context] = None

    def __enter__(self) -> Context:
        self._prev = getattr(_tls, "ctx", None)
        return enter_context(self._name, self._origin)

    def __exit__(self, *exc) -> None:
        _tls.ctx = self._prev
        return None
