"""Per-task call context (ContextUtil analog).

Reference: ``sentinel-core/.../context/ContextUtil.java`` — a ThreadLocal
holding the context name (entrance) and origin (caller app); adapters call
``ContextUtil.enter(contextName, origin)`` before ``SphU.entry``. The context
name keys CHAIN-strategy flow rules and the entrance-node aggregation; the
origin keys authority checks and origin-specific flow rules.

Storage is a ``contextvars.ContextVar``, not ``threading.local``: asyncio
interleaves many logical calls on one thread, and a thread-local context set
by task A would leak into task B at the first ``await`` — exactly the hazard
the reference solves for its async paths by snapshotting the context into
``AsyncEntry`` (``CORE/AsyncEntry.java``). ContextVar gives every asyncio
task its own value chain automatically (tasks copy the enclosing context at
creation), and plain threads still see independent values.
"""

from __future__ import annotations

import contextvars
import dataclasses
from typing import Optional

DEFAULT_CONTEXT_NAME = "sentinel_default_context"


@dataclasses.dataclass
class Context:
    name: str = DEFAULT_CONTEXT_NAME
    origin: str = ""


_ctx_var: contextvars.ContextVar[Optional[Context]] = contextvars.ContextVar(
    "sentinel_tpu_context", default=None)

_DEFAULT = Context()


def current_context() -> Context:
    ctx = _ctx_var.get()
    return ctx if ctx is not None else _DEFAULT


def enter_context(name: str, origin: str = "") -> Context:
    """Reference ``ContextUtil.enter`` (names beyond the registry capacity
    degrade to the shared default context at lookup time, not here)."""
    ctx = Context(name=name or DEFAULT_CONTEXT_NAME, origin=origin or "")
    _ctx_var.set(ctx)
    return ctx


def exit_context() -> None:
    _ctx_var.set(None)


def snapshot_context() -> Context:
    """Copy of the current context for asynchronous continuation — the
    ``AsyncEntry.java`` context snapshot. Restore with
    :func:`restore_context` from whatever task/thread completes the work."""
    cur = current_context()
    return Context(name=cur.name, origin=cur.origin)


def restore_context(ctx: Context) -> None:
    _ctx_var.set(Context(name=ctx.name, origin=ctx.origin))


class ContextScope:
    """``with ContextScope("entrance", origin="app-a"): ...``

    Token-based restore: safe under asyncio interleaving (each task's
    ContextVar chain is private, and nesting unwinds correctly)."""

    def __init__(self, name: str, origin: str = ""):
        self._name = name
        self._origin = origin
        self._token: Optional[contextvars.Token] = None

    def __enter__(self) -> Context:
        ctx = Context(name=self._name or DEFAULT_CONTEXT_NAME,
                      origin=self._origin or "")
        self._token = _ctx_var.set(ctx)
        return ctx

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _ctx_var.reset(self._token)
            self._token = None
        return None
