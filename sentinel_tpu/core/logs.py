"""Framework logging: record log + aggregated block log.

Reference: ``sentinel-core/.../log/RecordLog.java`` (``sentinel-record.log``
in ``${user.home}/logs/csp/``, overridable dir, daily-rolling) and the
EagleEye-backed block log (``slots/logger/EagleEyeLogUtil.java`` +
``eagleeye/StatLogger``): block events are NOT written per-occurrence but
rolled up per (resource, exception, limitApp, origin, ruleId) key every
second and flushed as one pipe-delimited line — that per-interval rollup is
what keeps logging off the hot path, and is reproduced here by
:class:`BlockStatLogger`. Python's stdlib logging plays the ``Logger`` SPI
role (handlers are swappable, the slf4j-binding analog)."""

from __future__ import annotations

import logging
import logging.handlers
import os
import threading
from typing import Dict, Optional, Tuple

_DEF_DIR = os.path.join(os.path.expanduser("~"), "logs", "csp")


def log_base_dir() -> str:
    return os.environ.get("SENTINEL_TPU_LOG_DIR", _DEF_DIR)


_record_logger: Optional[logging.Logger] = None
_record_lock = threading.Lock()


def record_log(to_file: bool = True) -> logging.Logger:
    """The framework's own diagnostics channel (``RecordLog``)."""
    global _record_logger
    with _record_lock:
        if _record_logger is None:
            lg = logging.getLogger("sentinel_tpu.record")
            lg.setLevel(logging.INFO)
            lg.propagate = False
            if to_file:
                try:
                    os.makedirs(log_base_dir(), exist_ok=True)
                    h = logging.handlers.TimedRotatingFileHandler(
                        os.path.join(log_base_dir(), "sentinel-record.log"),
                        when="midnight", backupCount=7, delay=True)
                    h.setFormatter(logging.Formatter(
                        "%(asctime)s %(levelname)s %(message)s"))
                    lg.addHandler(h)
                except OSError:   # unwritable home: stderr fallback
                    lg.addHandler(logging.StreamHandler())
            else:
                lg.addHandler(logging.NullHandler())
            _record_logger = lg
        return _record_logger


class BlockStatLogger:
    """Per-second rollup of block events → ``sentinel-block.log``.

    Line format mirrors the EagleEye stat line:
    ``ms|resource,exception,limitApp,origin,ruleId|count`` with at most
    ``max_entries`` distinct keys per interval (overflow keys are dropped,
    like the StatLogger's maxEntryCount=6000).

    Written LINES are additionally rate-limited by a token bucket
    (``max_lines_per_sec``, burst = one second's worth) — the EagleEye
    ``TokenBucket`` analog. The DEFAULT equals ``max_entries`` so the
    documented per-interval key contract is never silently trimmed; the
    knob exists for operators with a tighter disk budget (a sustained
    block storm over high-cardinality resources still rolls up to 6000
    lines/s otherwise). Trimmed intervals append one ``__dropped__``
    summary line so the loss is visible, not silent."""

    FILE_NAME = "sentinel-block.log"

    def __init__(self, clock, base_dir: Optional[str] = None,
                 max_entries: int = 6000, max_bytes: int = 300 * 1024 * 1024,
                 backups: int = 3, file_name: Optional[str] = None,
                 max_lines_per_sec: Optional[int] = None):
        self._clock = clock
        self._dir = base_dir or log_base_dir()
        self.file_name = file_name or self.FILE_NAME
        self._max_entries = max_entries
        self._max_bytes = max_bytes
        self._backups = backups
        self._lock = threading.Lock()
        self._bucket_sec = 0
        self._counts: Dict[Tuple[str, str, str, str, str], int] = {}
        self._line_rate = max(1, max_lines_per_sec
                              if max_lines_per_sec is not None
                              else max_entries)
        self._line_tokens = float(self._line_rate)
        self._last_refill_sec = 0

    def log(self, resource: str, exception_name: str, limit_app: str = "",
            origin: str = "", rule_id: str = "", count: int = 1) -> None:
        sec = self._clock.now_ms() // 1000
        flush = None
        with self._lock:
            if sec != self._bucket_sec and self._counts:
                flush = (self._bucket_sec, self._counts)
                self._counts = {}
            self._bucket_sec = sec
            key = (resource, exception_name, limit_app, origin, rule_id)
            if key in self._counts or len(self._counts) < self._max_entries:
                self._counts[key] = self._counts.get(key, 0) + count
        if flush:
            self._write(*flush)

    def flush(self) -> None:
        with self._lock:
            pending = (self._bucket_sec, self._counts)
            self._counts = {}
        if pending[1]:
            self._write(*pending)

    def _take_line_tokens(self, sec: int, want: int) -> int:
        """Token-bucket refill + take → number of lines allowed now."""
        with self._lock:
            elapsed = max(0, sec - self._last_refill_sec)
            self._last_refill_sec = sec
            self._line_tokens = min(float(self._line_rate),
                                    self._line_tokens
                                    + elapsed * self._line_rate)
            granted = min(want, int(self._line_tokens))
            self._line_tokens -= granted
            return granted

    def _write(self, sec: int, counts: Dict) -> None:
        path = os.path.join(self._dir, self.file_name)
        budget = self._take_line_tokens(sec, len(counts))
        dropped = len(counts) - budget
        try:
            os.makedirs(self._dir, exist_ok=True)
            if os.path.exists(path) and os.path.getsize(path) > self._max_bytes:
                for i in range(self._backups - 1, 0, -1):
                    src = f"{path}.{i}"
                    if os.path.exists(src):
                        os.replace(src, f"{path}.{i + 1}")
                os.replace(path, f"{path}.1")
            with open(path, "a", encoding="utf-8") as fh:
                for (res, exc, la, org, rid), n in list(counts.items())[:budget]:
                    fh.write(f"{sec * 1000}|{res},{exc},{la},{org},{rid}|{n}\n")
                if dropped > 0:
                    fh.write(f"{sec * 1000}|__dropped__|{dropped}\n")
        except OSError:   # pragma: no cover — never break the hot path on IO
            pass
