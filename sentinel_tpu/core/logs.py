"""Framework logging: record log + aggregated block log.

Reference: ``sentinel-core/.../log/RecordLog.java`` (``sentinel-record.log``
in ``${user.home}/logs/csp/``, overridable dir, daily-rolling) and the
EagleEye-backed block log (``slots/logger/EagleEyeLogUtil.java`` +
``eagleeye/StatLogger``): block events are NOT written per-occurrence but
rolled up per (resource, exception, limitApp, origin, ruleId) key every
second and flushed as one pipe-delimited line — that per-interval rollup is
what keeps logging off the hot path, and is reproduced here by
:class:`BlockStatLogger` (the generic rollup + async appender machinery
lives in :mod:`sentinel_tpu.core.statlog`). Python's stdlib logging plays
the ``Logger`` SPI role (handlers are swappable, the slf4j-binding
analog)."""

from __future__ import annotations

import logging
import logging.handlers
import os
import threading
from typing import Dict, Optional

from sentinel_tpu.core.statlog import AsyncRollingAppender, StatLogger

_DEF_DIR = os.path.join(os.path.expanduser("~"), "logs", "csp")


def log_base_dir() -> str:
    return os.environ.get("SENTINEL_TPU_LOG_DIR", _DEF_DIR)


_record_logger: Optional[logging.Logger] = None
_record_lock = threading.Lock()


def record_log(to_file: bool = True) -> logging.Logger:
    """The framework's own diagnostics channel (``RecordLog``)."""
    global _record_logger
    with _record_lock:
        if _record_logger is None:
            lg = logging.getLogger("sentinel_tpu.record")
            lg.setLevel(logging.INFO)
            lg.propagate = False
            if to_file:
                try:
                    os.makedirs(log_base_dir(), exist_ok=True)
                    h = logging.handlers.TimedRotatingFileHandler(
                        os.path.join(log_base_dir(), "sentinel-record.log"),
                        when="midnight", backupCount=7, delay=True)
                    h.setFormatter(logging.Formatter(
                        "%(asctime)s %(levelname)s %(message)s"))
                    lg.addHandler(h)
                except OSError:   # unwritable home: stderr fallback
                    lg.addHandler(logging.StreamHandler())
            else:
                lg.addHandler(logging.NullHandler())
            _record_logger = lg
        return _record_logger


class BlockStatLogger(StatLogger):
    """Per-second rollup of block events → ``sentinel-block.log``.

    The generic :class:`~sentinel_tpu.core.statlog.StatLogger` rollup
    (1 s period, max_entries key cap, async rolling appender) with the
    block log's fixed 5-part key and an additional per-LINE token bucket
    (``max_lines_per_sec``, burst = one second's worth) — the EagleEye
    ``TokenBucket`` analog. The DEFAULT equals ``max_entries`` so the
    documented per-interval key contract is never silently trimmed; the
    knob exists for operators with a tighter disk budget (a sustained
    block storm over high-cardinality resources still rolls up to 6000
    lines/s otherwise). Trimmed or overflowed intervals append one
    ``__dropped__`` summary line so the loss is visible, not silent.

    Line format mirrors the EagleEye stat line:
    ``ms|resource,exception,limitApp,origin,ruleId|count``."""

    FILE_NAME = "sentinel-block.log"

    def __init__(self, clock, base_dir: Optional[str] = None,
                 max_entries: int = 6000, max_bytes: int = 300 * 1024 * 1024,
                 backups: int = 3, file_name: Optional[str] = None,
                 max_lines_per_sec: Optional[int] = None):
        self._dir = base_dir or log_base_dir()
        self.file_name = file_name or self.FILE_NAME
        # size rotation + actual file IO live on the appender's flush
        # daemon — the entry/exit hot path only formats and enqueues
        # (EagleEyeRollingFileAppender + EagleEyeLogDaemon split)
        super().__init__(
            self.file_name, clock, period_ms=1000, max_entries=max_entries,
            appender=AsyncRollingAppender(
                os.path.join(self._dir, self.file_name),
                max_bytes=max_bytes, backups=backups))
        self._line_rate = max(1, max_lines_per_sec
                              if max_lines_per_sec is not None
                              else max_entries)
        self._line_tokens = float(self._line_rate)
        self._last_refill_sec = 0

    def log(self, resource: str, exception_name: str, limit_app: str = "",
            origin: str = "", rule_id: str = "", count: int = 1) -> None:
        self.stat(resource, exception_name, limit_app, origin, rule_id,
                  values=(count,))

    def close(self) -> None:
        """Flush pending rollups and retire the appender (terminal)."""
        self.flush()
        self.appender.close()

    def _take_line_tokens(self, sec: int, want: int) -> int:
        """Token-bucket refill + take → number of lines allowed now."""
        with self._lock:
            elapsed = max(0, sec - self._last_refill_sec)
            self._last_refill_sec = sec
            self._line_tokens = min(float(self._line_rate),
                                    self._line_tokens
                                    + elapsed * self._line_rate)
            granted = min(want, int(self._line_tokens))
            self._line_tokens -= granted
            return granted

    def _emit(self, bucket: int, counts: Dict, overflow: int) -> None:
        budget = self._take_line_tokens(bucket, len(counts))
        trimmed = len(counts) - budget
        ms = bucket * self._period
        lines = [f"{ms}|{','.join(k)}|{vs[0]}"
                 for k, vs in list(counts.items())[:budget]]
        if trimmed + overflow > 0:
            lines.append(f"{ms}|__dropped__|{trimmed + overflow}")
        self.appender.append_many(lines)
