"""Persistent XLA compilation cache — the cold-start story.

The reference agent is usable at the first ``SphU.entry`` (static init,
``Env.java`` — milliseconds). A JAX engine instead pays an XLA compile of
the fused decision step per (geometry, variant) per process: ~20-40 s on
the tunneled TPU, seconds on CPU. This module turns that into a
once-per-geometry cost machine-wide: every ``Sentinel`` construction
enables JAX's persistent compilation cache (content-addressed by HLO, so
identical geometry + jaxlib + flags ⇒ disk hit), making every process
after the first start in warm time. Measured numbers + ops guidance live
in ``docs/OPERATIONS.md`` ("Cold start").

Env knobs:
- ``SENTINEL_COMPILE_CACHE`` — cache directory (default
  ``~/.cache/sentinel_tpu/xla``); ``0``/``off`` disables.

Default policy: AUTO-ON for accelerator backends (TPU — where a step
compile costs tens of seconds), OPT-IN on the CPU backend (set the env
var or config field): this jax/jaxlib's CPU AOT loader logs a
machine-feature-mismatch warning for every cache entry it loads
(``cpu_aot_loader.cc`` — the compile records ``+prefer-no-scatter``-style
pseudo-features host detection lacks), ~44 stderr lines per warm start,
which is not an acceptable default for a serving process's logs.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

_lock = threading.Lock()
_enabled_dir: Optional[str] = None


def default_cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "sentinel_tpu", "xla")


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Idempotently enable JAX's persistent compilation cache → the active
    cache dir (None when disabled via env or unavailable).

    Safe to call before or after backend initialization (the cache is
    consulted per compilation, not at client creation). First caller wins
    the directory; later calls with a different explicit ``path`` are
    ignored (one cache per process — JAX has one global config).
    """
    global _enabled_dir
    env = os.environ.get("SENTINEL_COMPILE_CACHE", "")
    if env.lower() in ("0", "off", "disable", "disabled"):
        return None
    with _lock:
        if _enabled_dir is not None:
            return _enabled_dir
        if not path and not env:
            # default-on only off-CPU (see module docstring)
            try:
                import jax
                if jax.default_backend() == "cpu":
                    return None
            except Exception:  # pragma: no cover
                return None
        cache_dir = path or env or default_cache_dir()
        try:
            os.makedirs(cache_dir, exist_ok=True)
        except OSError:
            return None
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # cache everything: the engine's step compiles are the cost we
            # exist to amortize, and even "fast" (>0.1 s) entries add up
            # across the variant set
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception:  # pragma: no cover - future-flag drift
            return None
        _enabled_dir = cache_dir
        return cache_dir


def active_cache_dir() -> Optional[str]:
    return _enabled_dir
