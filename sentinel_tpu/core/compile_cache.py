"""Persistent XLA compilation cache — the cold-start story.

The reference agent is usable at the first ``SphU.entry`` (static init,
``Env.java`` — milliseconds). A JAX engine instead pays an XLA compile of
the fused decision step per (geometry, variant) per process: ~20-40 s on
the tunneled TPU, seconds on CPU. This module turns that into a
once-per-geometry cost machine-wide: every ``Sentinel`` construction
enables JAX's persistent compilation cache (content-addressed by HLO, so
identical geometry + jaxlib + flags ⇒ disk hit), making every process
after the first start in warm time. Measured numbers + ops guidance live
in ``docs/OPERATIONS.md`` ("Cold start").

Env knobs:
- ``SENTINEL_COMPILE_CACHE`` — cache directory (default
  ``~/.cache/sentinel_tpu/xla``); ``0``/``off`` disables.
- ``SENTINEL_FIRST_LOAD_TIMEOUT_S`` / ``SENTINEL_FIRST_LOAD_RETRIES`` —
  wall-clock timeout and retry budget for :func:`guarded_first_fetch`
  (first program fetches). Default: 20 s / 2 retries on accelerator
  backends, disabled on CPU; ``0`` disables everywhere.

Default policy: AUTO-ON for accelerator backends (TPU — where a step
compile costs tens of seconds), OPT-IN on the CPU backend (set the env
var or config field): this jax/jaxlib's CPU AOT loader logs a
machine-feature-mismatch warning for every cache entry it loads
(``cpu_aot_loader.cc`` — the compile records ``+prefer-no-scatter``-style
pseudo-features host detection lacks), ~44 stderr lines per warm start,
which is not an acceptable default for a serving process's logs.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Mapping, Optional, Tuple

_lock = threading.Lock()
_enabled_dir: Optional[str] = None


def default_cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "sentinel_tpu", "xla")


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Idempotently enable JAX's persistent compilation cache → the active
    cache dir (None when disabled via env or unavailable).

    Safe to call before or after backend initialization (the cache is
    consulted per compilation, not at client creation). First caller wins
    the directory; later calls with a different explicit ``path`` are
    ignored (one cache per process — JAX has one global config).
    """
    global _enabled_dir
    env = os.environ.get("SENTINEL_COMPILE_CACHE", "")
    if env.lower() in ("0", "off", "disable", "disabled"):
        return None
    with _lock:
        if _enabled_dir is not None:
            return _enabled_dir
        if not path and not env:
            # default-on only off-CPU (see module docstring)
            try:
                import jax
                if jax.default_backend() == "cpu":
                    return None
            except Exception:  # pragma: no cover
                return None
        cache_dir = path or env or default_cache_dir()
        try:
            os.makedirs(cache_dir, exist_ok=True)
        except OSError:
            return None
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # cache everything: the engine's step compiles are the cost we
            # exist to amortize, and even "fast" (>0.1 s) entries add up
            # across the variant set
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception:  # pragma: no cover - future-flag drift
            return None
        _enabled_dir = cache_dir
        return cache_dir


def active_cache_dir() -> Optional[str]:
    return _enabled_dir


def program_key(kind: str, step_id: int, geometry, statics: Mapping) -> tuple:
    """Hashable identity of one compiled program variant for first-fetch
    bookkeeping (``Sentinel._fetched_programs`` / ``compile_cache.hit`` /
    ``.miss`` counters).

    ``kind`` names the program family (``"decide"``, ``"fused"``);
    ``step_id`` is ``id()`` of the jitted callable, so rebuilt jits
    (rule reload, geometry change) key fresh; ``geometry`` is the padded
    batch-shape tuple (one entry for decide, ``(b_entry, b_exit)`` for
    the fused decide+exit program); ``statics`` the static-arg flags the
    variant was specialized on."""
    return (kind, int(step_id), tuple(geometry),
            tuple(sorted(statics.items())))


# ---------------------------------------------------------------------------
# First program fetch guard — the cold-start TAIL story.
#
# The measured warm start on the tunneled TPU is ~6-7 s, but one run in
# three measured rounds rode a ~50 s transport stall on a SINGLE program
# load (54.9 s total — OPERATIONS.md "Cold start"). The fetch itself is
# cheap and idempotent (cache load + program transfer); only the stalled
# RPC is slow. A fresh attempt opens a fresh transfer and typically
# completes at the normal 0.1-0.6 s cost, so a timeout + bounded retry
# caps the tail at ~(retries x timeout) instead of the full stall.
# ---------------------------------------------------------------------------

_log = logging.getLogger("sentinel_tpu.coldstart")


def _fire_retry(on_retry) -> None:
    if on_retry is None:
        return
    try:
        on_retry()
    except Exception:   # telemetry must never mask the fetch itself
        _log.debug("first-fetch on_retry callback failed", exc_info=True)


def first_fetch_policy() -> Tuple[float, int]:
    """→ ``(timeout_s, retries)`` for :func:`guarded_first_fetch`.

    ``SENTINEL_FIRST_LOAD_TIMEOUT_S`` overrides the timeout (``0`` turns
    the guard off); ``SENTINEL_FIRST_LOAD_RETRIES`` the retry budget.
    Default policy mirrors the cache itself: on for accelerator backends
    (where the program-load RPC can stall), off on CPU (loads are local
    file reads — a guard thread per program would be pure overhead)."""
    retries = 2
    env_r = os.environ.get("SENTINEL_FIRST_LOAD_RETRIES", "")
    if env_r:
        try:
            retries = max(0, int(env_r))
        except ValueError:
            pass
    env_t = os.environ.get("SENTINEL_FIRST_LOAD_TIMEOUT_S", "")
    if env_t:
        try:
            return max(0.0, float(env_t)), retries
        except ValueError:
            return 0.0, 0
    try:
        import jax
        if jax.default_backend() == "cpu":
            return 0.0, 0
    except Exception:  # pragma: no cover
        return 0.0, 0
    return 20.0, retries


def guarded_first_fetch(fn, what: str, timeout_s: float, retries: int,
                        on_retry=None):
    """Run ``fn`` — an IDEMPOTENT first program fetch/execution — with a
    wall-clock timeout and a bounded retry budget; → the first attempt's
    result to complete. A warning is logged every time a retry fires,
    and ``on_retry`` (when given) is invoked once per fired retry — the
    runtime hooks its ``compile_cache.first_fetch_retry`` counter here
    (obs/counters.py); callback failures never mask the fetch.

    ``fn`` MUST be safe to run concurrently with a stalled copy of
    itself (throwaway inputs, no shared mutable state): a timed-out
    attempt cannot be cancelled (the RPC is stuck inside the runtime),
    so the retry races it and the straggler's result is discarded. The
    LAST attempt waits without a timeout — once the budget is spent
    there is no cap left to enforce, and the warning trail already
    records the stalls."""
    if timeout_s <= 0:
        return fn()
    import queue
    q: "queue.Queue" = queue.Queue()

    def _run():
        try:
            q.put((None, fn()))
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            q.put((e, None))

    last_err: Optional[BaseException] = None
    for attempt in range(retries + 1):
        threading.Thread(target=_run, daemon=True,
                         name=f"sentinel-first-fetch-{attempt}").start()
        final = attempt == retries
        try:
            err, out = q.get(timeout=None if final else timeout_s)
        except queue.Empty:
            _log.warning(
                "first program fetch of %s stalled > %gs "
                "(attempt %d/%d) — retrying; a persistent-cache load or "
                "program transfer is likely riding a transport stall",
                what, timeout_s, attempt + 1, retries + 1)
            _fire_retry(on_retry)
            continue
        if err is None:
            return out
        last_err = err
        if final:
            raise err
        _log.warning(
            "first program fetch of %s failed (%s: %s) on attempt %d/%d "
            "— retrying", what, type(err).__name__, err, attempt + 1,
            retries + 1)
        _fire_retry(on_retry)
    # every attempt timed out and the final blocking get was interrupted
    # by a straggler's error — surface it rather than hanging
    if last_err is not None:  # pragma: no cover - straggler-error race
        raise last_err
    raise RuntimeError(f"first program fetch of {what} did not complete")
