"""Ordered startup hooks — the ``InitExecutor`` / ``InitFunc`` /
``@InitOrder`` analog (reference ``init/InitExecutor.java``,
``init/InitFunc.java``, ``init/InitOrder.java``).

An init func is any callable ``fn(sentinel)`` registered under the
``init_func`` SPI service (directly, via :func:`init_func`, or from a
plugin module — see :mod:`sentinel_tpu.core.spi`). ``InitExecutor``
runs them once per process in ascending order, triggered by the static
facade's instance creation (``api.init()`` — the analog of ``Env``'s
static init firing on the first ``SphU.entry``); class-based users call
:meth:`InitExecutor.do_init` themselves.

Failure semantics match the reference: the first raising func interrupts
the remaining ones (logged, not propagated — ``InitExecutor.doInit``
catches at the loop level), and initialization never re-runs.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from sentinel_tpu.core.spi import (
    LOWEST_PRECEDENCE, SERVICE_INIT_FUNC, SpiLoader,
)


def init_func(order: int = LOWEST_PRECEDENCE,
              alias: Optional[str] = None) -> Callable:
    """Decorator registering ``fn(sentinel)`` as an InitFunc::

        @init_func(order=10)
        def wire_metrics(sph): ...
    """
    def wrap(fn):
        return SpiLoader.of(SERVICE_INIT_FUNC).register(
            fn, alias=alias, order=order)
    return wrap


class InitExecutor:
    # Claim-then-Event design: the lock is held only to CLAIM the init (never
    # while hooks run — user callbacks under a held lock would be an AB/BA
    # deadlock hazard); losers wait on the completion Event, so no caller can
    # observe (and use) the instance mid-initialization. The Event also gives
    # the steady-state fast path: one lock-free is_set() per call, so hot-path
    # accessors (api.instance) can rendezvous on every call for free.
    _lock = threading.Lock()
    _done = False                        # claimed
    _complete = threading.Event()        # hooks finished
    _owner: Optional[int] = None         # claiming thread id (re-entrancy)
    # Bound on the loser rendezvous; configurable because "slow" is
    # deployment-specific (first-process XLA compiles can legitimately take
    # tens of seconds). Env override: SENTINEL_INIT_WAIT_TIMEOUT_S.
    WAIT_TIMEOUT_S = 10.0

    @classmethod
    def do_init(cls, sentinel) -> bool:
        """Run all registered init funcs in order, once per process.
        → True if this call performed the initialization. Concurrent calls
        block until the winning call's hooks have completed ("hooks run
        before first use")."""
        if cls._complete.is_set():       # steady state: lock-free
            return False
        with cls._lock:
            if cls._done:
                winner = False
            else:
                cls._done = True
                cls._owner = threading.get_ident()
                winner = True
            complete = cls._complete     # reset() swaps the Event
        if not winner:
            if cls._owner != threading.get_ident():
                # Bounded wait: an init hook that spawns a helper thread
                # which itself reaches do_init would otherwise deadlock
                # (hook waits on helper, helper waits on hook's Event).
                # After the timeout we log and proceed — weaker ordering
                # beats a silent process hang. Re-check is_set() after the
                # wait so a completion racing the timeout edge isn't
                # mis-reported as a hang.
                timeout = cls._wait_timeout_s()
                if not complete.wait(timeout=timeout) \
                        and not complete.is_set():
                    from sentinel_tpu.core.logs import record_log
                    record_log().warning(
                        "[InitExecutor] waited %.0fs for init hooks to "
                        "finish; proceeding before completion (is an init "
                        "hook blocking on a thread that uses the facade? "
                        "Slow-but-healthy hooks: raise "
                        "SENTINEL_INIT_WAIT_TIMEOUT_S)", timeout)
            return False
        from sentinel_tpu.core.logs import record_log
        try:
            for fn in SpiLoader.of(
                    SERVICE_INIT_FUNC).load_instance_list_sorted():
                record_log().info("[InitExecutor] executing %s",
                                  getattr(fn, "__name__", fn))
                fn(sentinel)
        except Exception as exc:
            # first failure interrupts the remaining funcs but never
            # propagates (InitExecutor.java:56-63)
            record_log().warning(
                "[InitExecutor] initialization failed: %r", exc)
        finally:
            cls._owner = None
            complete.set()
        return True

    @classmethod
    def _wait_timeout_s(cls) -> float:
        import math
        import os
        try:
            v = float(os.environ.get("SENTINEL_INIT_WAIT_TIMEOUT_S",
                                     cls.WAIT_TIMEOUT_S))
        except ValueError:
            return cls.WAIT_TIMEOUT_S
        # non-positive/non-finite values would silently disable the
        # rendezvous bound — fall back rather than obey them
        if not math.isfinite(v) or v <= 0:
            return cls.WAIT_TIMEOUT_S
        return v

    @classmethod
    def reset(cls) -> None:
        """Test hygiene: allow do_init to run again."""
        with cls._lock:
            cls._done = False
            cls._owner = None
            cls._complete = threading.Event()
