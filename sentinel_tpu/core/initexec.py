"""Ordered startup hooks — the ``InitExecutor`` / ``InitFunc`` /
``@InitOrder`` analog (reference ``init/InitExecutor.java``,
``init/InitFunc.java``, ``init/InitOrder.java``).

An init func is any callable ``fn(sentinel)`` registered under the
``init_func`` SPI service (directly, via :func:`init_func`, or from a
plugin module — see :mod:`sentinel_tpu.core.spi`). ``InitExecutor``
runs them once per process in ascending order, triggered by the static
facade's instance creation (``api.init()`` — the analog of ``Env``'s
static init firing on the first ``SphU.entry``); class-based users call
:meth:`InitExecutor.do_init` themselves.

Failure semantics match the reference: the first raising func interrupts
the remaining ones (logged, not propagated — ``InitExecutor.doInit``
catches at the loop level), and initialization never re-runs.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from sentinel_tpu.core.spi import (
    LOWEST_PRECEDENCE, SERVICE_INIT_FUNC, SpiLoader,
)


def init_func(order: int = LOWEST_PRECEDENCE,
              alias: Optional[str] = None) -> Callable:
    """Decorator registering ``fn(sentinel)`` as an InitFunc::

        @init_func(order=10)
        def wire_metrics(sph): ...
    """
    def wrap(fn):
        return SpiLoader.of(SERVICE_INIT_FUNC).register(
            fn, alias=alias, order=order)
    return wrap


class InitExecutor:
    _lock = threading.Lock()
    _done = False

    @classmethod
    def do_init(cls, sentinel) -> bool:
        """Run all registered init funcs in order, once per process.
        → True if this call performed the initialization."""
        with cls._lock:
            if cls._done:
                return False
            cls._done = True
        from sentinel_tpu.core.logs import record_log
        try:
            for fn in SpiLoader.of(
                    SERVICE_INIT_FUNC).load_instance_list_sorted():
                record_log().info("[InitExecutor] executing %s",
                                  getattr(fn, "__name__", fn))
                fn(sentinel)
        except Exception as exc:
            # first failure interrupts the remaining funcs but never
            # propagates (InitExecutor.java:56-63)
            record_log().warning("[InitExecutor] initialization failed: %r",
                                 exc)
        return True

    @classmethod
    def reset(cls) -> None:
        """Test hygiene: allow do_init to run again."""
        with cls._lock:
            cls._done = False
