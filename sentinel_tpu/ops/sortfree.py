"""Sort-free hash-bucketed scatter aggregation (round 10).

The general admission path's only superlinear stage is the composite-key
sort that groups (rule, stat-row) pairs into segments
(``ops/segments.py`` ``sort_by_keys`` — n·log n, ~11 ms of the 40.5 ms
general step at B=512k per BASELINE.md's round-5 ablation). Everything
downstream of the sort — prefix sums, greedy fixed point, unsorts — is
linear. This module removes the sort:

1. **Claim cascade** (``build_pair_plan`` / ``build_key_plan``): each
   distinct segment key claims a private bucket in a power-of-two table
   of ``T = 2^bits`` slots. Per round (3 rounds, independent
   multiplicative hashes) every unsettled key scatter-mins its
   coordinates into its hashed bucket; a key *settles* in the first
   round where it reads its own coordinates back (it won the claim).
   The effective bucket id ``round·T + bucket`` is therefore injective
   over distinct keys — two keys can share a bucket only across
   different rounds. Keys still unsettled after 3 rounds raise the
   plan's ``overflow`` flag: the caller falls back to the sorted
   reference via ``lax.cond`` (graceful fallback, never wrong answers)
   and the count feeds the ``sortfree.bucket_overflow`` counter.

2. **Scatter ranks** (``scatter_ranks``): arrival rank within bucket in
   ORIGINAL batch order, without sorting — a ``lax.scan`` over fixed-size
   chunks carrying a ``[num_buckets]`` running count: each chunk reads
   its pre-chunk counts (gather), adds its within-chunk triangular
   equality counts (dense [m, m] compare, VPU-friendly), and scatter-adds
   its histogram into the carry. O(n·m) dense work and O(num_buckets)
   memory replace the n·log n sort.

3. **Counting order** (``counting_order``): the stable counting-sort
   permutation ``offsets[bucket] + rank`` — buckets made contiguous,
   batch arrival order preserved inside each bucket. The general path
   feeds this permutation into its UNCHANGED segment machinery
   (prefix sums / ``greedy_admit`` / unsorts), so bit-parity with the
   sorted reference needs no second implementation of the admission
   math: within a segment the element order is identical (stability),
   and across segments the cumsum-minus-leader-base prefix form is
   exact for the integer-valued float32 amounts both paths already
   require (the documented < 2^24 envelope — see
   ``flow_check_scalar``'s parity contract), so segment ORDER cannot
   change any admitted bit.

The bucket histograms ride :func:`ops.pallas_kernels.scatter_add` (the
XLA-scatter/Pallas-tile dispatch seam), so a future TPU measurement can
move them onto the MXU tile kernel without touching callers.

Env knobs: ``SENTINEL_SORTFREE`` (runtime routing — see runtime.py),
``SENTINEL_SORTFREE_BITS`` (claim-table size override, mainly for the
collision-forcing tests), ``SENTINEL_SORTFREE_CHUNK`` (scan chunk).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from sentinel_tpu.ops.pallas_kernels import scatter_add

# Claim rounds: 3 independent hashes drive the per-key settle probability
# low enough that overflow is a counter-visible rarity at the default
# table load (~n distinct keys into 2n buckets), while the lax.cond
# fallback keeps correctness unconditional.
ROUNDS = 3

# Odd 32-bit mixing constants (Knuth / xxhash family), one (A, B) pair
# per round so a pair of keys colliding in round r is independently
# re-scattered in round r+1.
_HASH_A = (0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D)
_HASH_B = (0x27D4EB2F, 0x165667B1, 0x7FEB352D)
_HASH_MIX = 0x2C1B3C6D

_I32_MAX = 2 ** 31 - 1


def table_bits(n: int) -> int:
    """Claim-table size exponent for an n-element batch (STATIC, read at
    trace time). Default sizes the table to ~2 buckets per element
    (distinct keys <= elements), clamped to [6, 18];
    ``SENTINEL_SORTFREE_BITS`` overrides — the collision-forcing parity
    tests pin it tiny to exercise the overflow fallback."""
    raw = os.environ.get("SENTINEL_SORTFREE_BITS", "")
    if raw:
        try:
            return max(1, min(int(raw), 18))
        except ValueError:
            pass
    bits = 1
    while (1 << bits) < 2 * max(n, 2):
        bits += 1
    return max(6, min(bits, 18))


def chunk_size() -> int:
    """``lax.scan`` chunk for :func:`scatter_ranks` (STATIC). Each scan
    step does one [m, m] dense compare; ``SENTINEL_SORTFREE_CHUNK``
    overrides, clamped to [16, 4096]."""
    raw = os.environ.get("SENTINEL_SORTFREE_CHUNK", "")
    try:
        m = int(raw) if raw else 256
    except ValueError:
        return 256
    return max(16, min(m, 4096))


class BucketPlan(NamedTuple):
    """Output of the claim cascade.

    ``bucket[i]`` is element i's effective bucket in ``[0, num_buckets)``
    — injective over distinct keys when ``overflow`` is False (settled
    keys only; unsettled elements hold bucket 0, but then ``overflow``
    is True and the caller must take the sorted fallback branch).
    The LAST bucket (``num_buckets - 1``) is reserved for the caller's
    sentinel key so the padding segment never contests the hash table.
    """

    bucket: jnp.ndarray          # int32[n]
    overflow: jnp.ndarray        # bool scalar
    overflow_count: jnp.ndarray  # int32 scalar — unsettled elements
    num_buckets: int             # STATIC: ROUNDS * 2^bits + 1


def _bucket_of(mix: jnp.ndarray, bits: int) -> jnp.ndarray:
    h = (mix ^ (mix >> jnp.uint32(15))) * jnp.uint32(_HASH_MIX)
    return (h >> jnp.uint32(32 - bits)).astype(jnp.int32)


def _cascade(n: int, bits: int, sentinel_mask: jnp.ndarray,
             round_bucket, claim_and_win) -> BucketPlan:
    """Shared cascade body: per round, unsettled elements hash
    (``round_bucket``), claim (``claim_and_win`` → winner mask), and
    settled winners freeze ``r * T + bucket_r``."""
    T = 1 << bits
    settled = sentinel_mask
    bucket = jnp.where(sentinel_mask, jnp.int32(ROUNDS * T), jnp.int32(0))
    for r in range(ROUNDS):
        b_r = round_bucket(r)
        # settled elements sit out: their claim target T is out of range
        # for the [T] claim arrays (mode="drop")
        tgt = jnp.where(settled, jnp.int32(T), b_r)
        win = (~settled) & claim_and_win(tgt, b_r)
        bucket = jnp.where(win, r * T + b_r, bucket)
        settled = settled | win
    overflow_count = jnp.sum((~settled).astype(jnp.int32))
    return BucketPlan(bucket=bucket, overflow=overflow_count > 0,
                      overflow_count=overflow_count,
                      num_buckets=ROUNDS * T + 1)


def build_pair_plan(k1: jnp.ndarray, k2: jnp.ndarray,
                    sentinel_mask: jnp.ndarray, bits: int) -> BucketPlan:
    """Claim cascade over PAIR keys (k1, k2) — the general path's
    (rule, stat-row) segment key, which need not fit a single int32
    (this path is exactly the one the runtime routes to when the fast
    path's composite key does NOT fit).

    Two independent scatter-mins claim each bucket; an element wins iff
    it reads BOTH its coordinates back. Sound: the winning pair per
    bucket is (min k1, min k2) over the bucket's contenders, and only
    one distinct key can equal that pair — so at most one KEY settles
    per (round, bucket), preserving injectivity. (The combined minima
    may belong to no contender at all; then nobody wins the bucket this
    round and its contenders rehash — progress is probabilistic,
    correctness is not.)
    """
    T = 1 << bits
    u1 = k1.astype(jnp.uint32)
    u2 = k2.astype(jnp.uint32)

    def round_bucket(r: int) -> jnp.ndarray:
        return _bucket_of(u1 * jnp.uint32(_HASH_A[r])
                          + u2 * jnp.uint32(_HASH_B[r]), bits)

    def claim_and_win(tgt: jnp.ndarray, b_r: jnp.ndarray) -> jnp.ndarray:
        claim1 = jnp.full((T,), _I32_MAX, jnp.int32).at[tgt].min(
            k1, mode="drop")
        claim2 = jnp.full((T,), _I32_MAX, jnp.int32).at[tgt].min(
            k2, mode="drop")
        return (claim1[b_r] == k1) & (claim2[b_r] == k2)

    return _cascade(k1.shape[0], bits, sentinel_mask, round_bucket,
                    claim_and_win)


def build_key_plan(key: jnp.ndarray, sentinel_mask: jnp.ndarray,
                   bits: int) -> BucketPlan:
    """Claim cascade over single int32 keys (the fast path's composite
    key, host-verified < 2^31). One scatter-min per round: an element
    wins its bucket iff it reads its own key back."""
    T = 1 << bits
    u = key.astype(jnp.uint32)

    def round_bucket(r: int) -> jnp.ndarray:
        return _bucket_of(u * jnp.uint32(_HASH_A[r]) + jnp.uint32(_HASH_B[r]),
                          bits)

    def claim_and_win(tgt: jnp.ndarray, b_r: jnp.ndarray) -> jnp.ndarray:
        claim = jnp.full((T,), _I32_MAX, jnp.int32).at[tgt].min(
            key, mode="drop")
        return claim[b_r] == key

    return _cascade(key.shape[0], bits, sentinel_mask, round_bucket,
                    claim_and_win)


def bucket_histogram(bucket: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    """Per-bucket element counts → int32[num_buckets], through the
    :func:`ops.pallas_kernels.scatter_add` dispatch seam (single event
    lane)."""
    counters = jnp.zeros((num_buckets, 1), jnp.int32)
    events = jnp.zeros(bucket.shape, jnp.int32)
    ones = jnp.ones(bucket.shape, jnp.int32)
    return scatter_add(counters, bucket, events, ones)[:, 0]


def scatter_ranks(bucket: jnp.ndarray, num_buckets: int,
                  chunk: Optional[int] = None) -> jnp.ndarray:
    """Arrival rank within bucket, ORIGINAL order → int32[n].

    ``rank[i]`` = number of earlier elements (batch order) in i's bucket
    — :func:`ops.segments.ranks_by_key` without the sort, valid whenever
    the bucket assignment is injective over keys (claim cascade, or an
    identity mapping for small key spaces). A ``lax.scan`` over chunks
    of ``m`` carries the ``[num_buckets]`` running counts; each chunk's
    within-chunk ranks come from one dense [m, m] triangular equality
    compare.
    """
    n = bucket.shape[0]
    m = min(chunk if chunk is not None else chunk_size(), max(n, 1))
    c = -(-n // m)
    pad = c * m - n
    b_p = bucket
    if pad:
        # padding targets num_buckets: dropped by the carry scatter, and
        # the padded lanes' outputs are sliced away below
        b_p = jnp.concatenate(
            [bucket, jnp.full((pad,), num_buckets, jnp.int32)])
    chunks = b_p.reshape(c, m)
    tri = jnp.tril(jnp.ones((m, m), jnp.bool_), k=-1)

    def step(state, b_chunk):
        pre = state[b_chunk]            # OOB padding gathers clamp; sliced
        eq = b_chunk[:, None] == b_chunk[None, :]
        within = jnp.sum((eq & tri).astype(jnp.int32), axis=1)
        return state.at[b_chunk].add(1, mode="drop"), pre + within

    _, ranks = lax.scan(step, jnp.zeros((num_buckets,), jnp.int32), chunks)
    return ranks.reshape(-1)[:n]


def counting_order(bucket: jnp.ndarray, num_buckets: int,
                   ranks: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Stable counting-sort permutation by bucket → int32[n], drop-in for
    ``seg.sort_by_keys`` when buckets are injective over segment keys:
    groups are contiguous and batch arrival order is preserved inside
    each group, which is all the downstream segment machinery assumes
    (the cross-group order differs from the key-sorted reference, which
    cannot change any admitted bit — see the module docstring)."""
    n = bucket.shape[0]
    hist = bucket_histogram(bucket, num_buckets)
    offsets = jnp.cumsum(hist) - hist
    if ranks is None:
        ranks = scatter_ranks(bucket, num_buckets)
    pos = offsets[bucket] + ranks
    return jnp.zeros((n,), jnp.int32).at[pos].set(
        jnp.arange(n, dtype=jnp.int32))


def ranks2d_ident(key2d: jnp.ndarray, num_keys: int) -> jnp.ndarray:
    """Sort-free :func:`ops.segments.ranks_per_slot` for SMALL key spaces
    (the scalar path: key = rule id in [0, num_keys)) — identity buckets,
    so no cascade, no collisions, no overflow. → int32[B, K]."""
    return jax.vmap(
        lambda col: scatter_ranks(col, num_keys))(key2d.T).T


def ranks2d_hashed(key2d: jnp.ndarray, sentinel_value: int,
                   bits: int):
    """Sort-free :func:`ops.segments.ranks_per_slot` for LARGE key spaces
    (the fast path's composite key) → (ranks int32[B, K], overflow_count
    int32 scalar).

    Slot columns carry disjoint key groups (the ranks_per_slot contract),
    so each column runs its own claim cascade; the shared cross-slot
    sentinel key is routed to the reserved bucket per column (its ranks
    are per-slot, matching the sorted per-slot reference — callers never
    consume sentinel ranks either way). On ``overflow_count > 0`` the
    ranks are NOT valid — the caller must ``lax.cond`` to the sorted
    reference."""
    def one(col):
        plan = build_key_plan(col, col == jnp.int32(sentinel_value), bits)
        return (scatter_ranks(plan.bucket, plan.num_buckets),
                plan.overflow_count)

    ranks, ovf = jax.vmap(one)(key2d.T)
    return ranks.T, jnp.sum(ovf)
