"""Pallas TPU kernel for the streaming counter update — A/B'd and RETIRED
from the hot path (kept as the reference MXU formulation).

The engine's per-batch counter update is a high-fan-in scatter-add: N events
→ ``counters[K, E]``. The TPU-native alternative formulated here is one-hot
matmul accumulation on the MXU::

    counters[K, E] += onehot(keys)[N, K]ᵀ · (onehot(events)[N, E] · amounts)

tiled over (K, N) grid cells with VMEM one-hots and ``jnp.dot``
accumulation — no atomics, deterministic (SURVEY §2.8.1 → §7 Phase 1).

**Measured outcome (round 3, real v5 lite chip, honest-mode timing — see
BASELINE.md "Scatter A/B"): XLA's native scatter wins at every product
shape**, 1.2× at K=1k-4k and up to 55× at K=1M, because each K-tile of the
one-hot kernel must scan the whole event stream (O(K/tile · N) MACs vs
XLA's O(N)). :func:`scatter_add` therefore dispatches to XLA everywhere;
the kernel stays as a tested reference implementation and the benchmark
harness (``BENCH_SCATTER={xla,pallas}`` on ``bench.py``,
``benchmarks/scatter_ab.py`` for the sweep) re-runs the comparison on any
future hardware where the balance may shift.

On CPU (tests, virtual mesh) the kernel runs in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# lane width: last-dim tiles are 128 on TPU
_LANE = 128


def scatter_add_xla(counters: jnp.ndarray, keys: jnp.ndarray,
                    events: jnp.ndarray,
                    amounts: jnp.ndarray) -> jnp.ndarray:
    """Reference semantics: ``counters[K, E] += Σ`` over the event stream.
    Out-of-range keys (>= K, e.g. padding) are dropped."""
    return counters.at[keys, events].add(amounts, mode="drop")


def _tile_kernel(keys_ref, events_ref, amounts_ref, counters_ref, out_ref,
                 *, tile_k: int, tile_n: int, num_events: int):
    """Grid cell (tk, tn): counter rows [tk·tile_k, (tk+1)·tile_k) ×
    stream chunk [tn·tile_n, (tn+1)·tile_n).

    one_hot_k: [tile_n, tile_k]  — event i hits local key col (keys[i]-base)
    one_hot_e: [tile_n, E]       — event i's event lane, scaled by amounts
    partial = one_hot_kᵀ @ one_hot_e  → [tile_k, E] on the MXU, accumulated
    across tn steps (tn is the innermost grid dim, so out_ref persists for
    a fixed k-tile; tn==0 seeds it from the current counters).

    The stream operands arrive as [tile_n, 1] blocks: Mosaic (the TPU
    Pallas backend) has no general 1D→2D vector reshape, so the host
    wrapper feeds column vectors and everything here broadcasts [tile_n, 1]
    against [tile_n, tile_k] (lane broadcast, no reshape ops). The N axis
    is tiled because a full-stream one-hot would blow scoped VMEM.
    """
    tk = pl.program_id(0)
    tn = pl.program_id(1)
    base = tk * tile_k
    keys = keys_ref[:, :]                    # [tile_n, 1]
    events = events_ref[:, :]
    amounts = amounts_ref[:, :]

    local = keys - base
    in_tile = (local >= 0) & (local < tile_k)
    local = jnp.where(in_tile, local, 0)

    col_k = jax.lax.broadcasted_iota(jnp.int32, (tile_n, tile_k), 1)
    one_hot_k = (col_k == local) & in_tile   # [tile_n,1] broadcasts lanes

    col_e = jax.lax.broadcasted_iota(jnp.int32, (tile_n, num_events), 1)
    one_hot_e = jnp.where(col_e == events, amounts, 0)

    partial = jnp.dot(one_hot_k.astype(jnp.float32).T,
                      one_hot_e.astype(jnp.float32),
                      preferred_element_type=jnp.float32)

    @pl.when(tn == 0)
    def _seed():
        out_ref[:, :] = counters_ref[:, :]

    out_ref[:, :] += partial.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def scatter_add_pallas(counters: jnp.ndarray, keys: jnp.ndarray,
                       events: jnp.ndarray, amounts: jnp.ndarray,
                       *, interpret: bool = False) -> jnp.ndarray:
    """MXU scatter-add: ``counters[K, E] += stream``. K must be a multiple
    of the tile (pad the table, harmless); out-of-range keys are dropped
    because no tile claims them."""
    orig_k, e = counters.shape
    orig_n = keys.shape[0]
    tile_k = min(orig_k, 512)
    tile_n = min(max(orig_n, 8), 2048)
    k = ((orig_k + tile_k - 1) // tile_k) * tile_k
    if k != orig_k:
        # pad the table to a tile multiple and route any out-of-range key
        # (padding convention: key >= orig_k) past the padded rows too
        counters = jnp.pad(counters, ((0, k - orig_k), (0, 0)))
        keys = jnp.where(keys < orig_k, keys, k)
    n = ((orig_n + tile_n - 1) // tile_n) * tile_n
    if n != orig_n:
        # padded stream slots target key k (no tile owns it) with amount 0
        pad_n = n - orig_n
        keys = jnp.concatenate([keys, jnp.full((pad_n,), k, keys.dtype)])
        events = jnp.concatenate([events, jnp.zeros((pad_n,), events.dtype)])
        amounts = jnp.concatenate([amounts,
                                   jnp.zeros((pad_n,), amounts.dtype)])
    grid = (k // tile_k, n // tile_n)        # tn innermost: accumulation

    # column-vector stream operands (see _tile_kernel: Mosaic needs 2D)
    keys2 = keys.reshape(-1, 1)
    events2 = events.reshape(-1, 1)
    amounts2 = amounts.reshape(-1, 1)

    kernel = functools.partial(_tile_kernel, tile_k=tile_k, tile_n=tile_n,
                               num_events=e)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_n, 1), lambda tk, tn: (tn, 0)),
            pl.BlockSpec((tile_n, 1), lambda tk, tn: (tn, 0)),
            pl.BlockSpec((tile_n, 1), lambda tk, tn: (tn, 0)),
            pl.BlockSpec((tile_k, e), lambda tk, tn: (tk, 0)),  # my tile
        ],
        out_specs=pl.BlockSpec((tile_k, e), lambda tk, tn: (tk, 0)),
        out_shape=jax.ShapeDtypeStruct(counters.shape, counters.dtype),
        interpret=interpret,
    )(keys2, events2, amounts2, counters)
    return out[:orig_k] if k != orig_k else out



def scatter_add(counters: jnp.ndarray, keys: jnp.ndarray,
                events: jnp.ndarray, amounts: jnp.ndarray) -> jnp.ndarray:
    """Backend dispatch — currently XLA scatter on every backend: the
    round-3 A/B on real TPU hardware (BASELINE.md "Scatter A/B") measured
    XLA ahead at all product shapes, so the MXU kernel is not selected.
    Kept as the dispatch seam so a future measurement can flip it
    per-shape without touching callers."""
    return scatter_add_xla(counters, keys, events, amounts)
