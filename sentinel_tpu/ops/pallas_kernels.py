"""Pallas TPU kernels for the streaming counter update (the hot op).

The engine's per-batch counter update is a high-fan-in scatter-add: N events
→ ``counters[K, E]`` (hot-param key tables, cluster per-flow tables, and —
tiled over row blocks — the main ``[R, B, E]`` tensor). XLA lowers scatter
on TPU to a serialized loop; the TPU-native formulation is **one-hot matmul
accumulation on the MXU**::

    counters[K, E] += onehot(keys)[N, K]ᵀ · (onehot(events)[N, E] · amounts)

This kernel tiles K across the grid, builds both one-hots in VMEM per tile,
and accumulates with ``jnp.dot`` — no atomics, no serialization, deterministic
(the reference's LongAdder striping solves contention on the JVM; the MXU
formulation removes contention entirely, SURVEY §2.8.1 → §7 Phase 1).

On CPU (tests, virtual mesh) the kernel runs in interpret mode; callers can
also use :func:`scatter_add_xla` (same semantics, ``.at[].add``) — the
engine picks per backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# lane width: last-dim tiles are 128 on TPU
_LANE = 128


def scatter_add_xla(counters: jnp.ndarray, keys: jnp.ndarray,
                    events: jnp.ndarray,
                    amounts: jnp.ndarray) -> jnp.ndarray:
    """Reference semantics: ``counters[K, E] += Σ`` over the event stream.
    Out-of-range keys (>= K, e.g. padding) are dropped."""
    return counters.at[keys, events].add(amounts, mode="drop")


def _tile_kernel(keys_ref, events_ref, amounts_ref, counters_ref, out_ref,
                 *, tile_k: int, num_events: int):
    """One grid step owns rows [t*tile_k, (t+1)*tile_k) of the counter table.

    one_hot_k: [N, tile_k]  — event i hits local key column (keys[i] - base)
    one_hot_e: [N, E]       — event i's event lane, scaled by amounts[i]
    partial = one_hot_kᵀ @ one_hot_e  → [tile_k, E] on the MXU.
    """
    t = pl.program_id(0)
    base = t * tile_k
    keys = keys_ref[:]                       # [N]
    events = events_ref[:]                   # [N]
    amounts = amounts_ref[:]                 # [N]
    n = keys.shape[0]

    local = keys - base                      # [N]
    in_tile = (local >= 0) & (local < tile_k)
    local = jnp.where(in_tile, local, 0)

    col_k = jax.lax.broadcasted_iota(jnp.int32, (n, tile_k), 1)
    one_hot_k = ((col_k == local[:, None]) & in_tile[:, None])

    col_e = jax.lax.broadcasted_iota(jnp.int32, (n, num_events), 1)
    one_hot_e = jnp.where(col_e == events[:, None],
                          amounts[:, None], 0)

    partial = jnp.dot(one_hot_k.astype(jnp.float32).T,
                      one_hot_e.astype(jnp.float32),
                      preferred_element_type=jnp.float32)
    out_ref[:, :] = counters_ref[:, :] + partial.astype(counters_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def scatter_add_pallas(counters: jnp.ndarray, keys: jnp.ndarray,
                       events: jnp.ndarray, amounts: jnp.ndarray,
                       *, interpret: bool = False) -> jnp.ndarray:
    """MXU scatter-add: ``counters[K, E] += stream``. K must be a multiple
    of the tile (pad the table, harmless); out-of-range keys are dropped
    because no tile claims them."""
    orig_k, e = counters.shape
    tile_k = min(orig_k, 512)
    k = ((orig_k + tile_k - 1) // tile_k) * tile_k
    if k != orig_k:
        # pad the table to a tile multiple and route any out-of-range key
        # (padding convention: key >= orig_k) past the padded rows too
        counters = jnp.pad(counters, ((0, k - orig_k), (0, 0)))
        keys = jnp.where(keys < orig_k, keys, k)
    grid = (k // tile_k,)

    kernel = functools.partial(_tile_kernel, tile_k=tile_k, num_events=e)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(keys.shape, lambda t: (0,)),       # whole stream
            pl.BlockSpec(events.shape, lambda t: (0,)),
            pl.BlockSpec(amounts.shape, lambda t: (0,)),
            pl.BlockSpec((tile_k, e), lambda t: (t, 0)),    # my tile
        ],
        out_specs=pl.BlockSpec((tile_k, e), lambda t: (t, 0)),
        out_shape=jax.ShapeDtypeStruct(counters.shape, counters.dtype),
        interpret=interpret,
    )(keys, events, amounts, counters)
    return out[:orig_k] if k != orig_k else out



def scatter_add(counters: jnp.ndarray, keys: jnp.ndarray,
                events: jnp.ndarray, amounts: jnp.ndarray) -> jnp.ndarray:
    """Backend dispatch: the Pallas MXU kernel on TPU, XLA scatter elsewhere
    (interpret-mode Pallas is for tests, not production CPU)."""
    platform = jax.devices()[0].platform
    if platform == "tpu":
        return scatter_add_pallas(counters, keys, events, amounts)
    return scatter_add_xla(counters, keys, events, amounts)
