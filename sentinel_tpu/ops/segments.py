"""Intra-batch segment primitives.

The device pipeline admits a whole batch of events in one step. To preserve
the reference's sequential greedy semantics ("each request sees the counters
as incremented by the requests admitted before it" —
``DefaultController.canPass``), events touching the same (rule, stat-row) pair
are grouped into *segments* and given their in-batch prefix sums, so event i's
check sees ``window_count + prefix_of_earlier_batch_events``. This turns the
reference's CAS loop into one sort + one scan — fully vectorized, no
data-dependent control flow.

All helpers are jit-safe, static-shape, and O(n log n) in batch size.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def sort_by_keys(primary: jnp.ndarray,
                 secondary: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Stable order of indices sorted by (primary, secondary) — int32[n].

    Stability preserves batch arrival order inside a segment, which is what
    makes the greedy admission FIFO like the reference's lock-free race-free
    single-thread case.

    ``secondary=None`` (the common single-key case) is ONE stable argsort;
    two keys compose two stable passes. Either way the arrival-order
    tiebreak is implicit in stability — on TPU each sort pass over a
    1M-element batch costs ~10 ms, so not lexsort'ing a redundant arange
    key matters on the hot path.
    """
    n = primary.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    # lax.sort carrying the iota payload ≈ 12% faster than argsort on the
    # v5 chip; the two-key case is ONE lexicographic pass (num_keys=2)
    # instead of two stable passes + a gather
    if secondary is None:
        _, order = lax.sort((primary, idx), num_keys=1, is_stable=True)
        return order
    _, _, order = lax.sort((primary, secondary, idx), num_keys=2,
                           is_stable=True)
    return order


def segment_starts(primary_sorted: jnp.ndarray, secondary_sorted: jnp.ndarray) -> jnp.ndarray:
    """bool[n]: True where a new (primary, secondary) segment begins."""
    n = primary_sorted.shape[0]
    first = jnp.zeros((n,), jnp.bool_).at[0].set(True)
    diff = (primary_sorted[1:] != primary_sorted[:-1]) | (
        secondary_sorted[1:] != secondary_sorted[:-1])
    return first.at[1:].set(diff)


def segment_leader_index(starts: jnp.ndarray) -> jnp.ndarray:
    """For each sorted position, the index of its segment's first position."""
    n = starts.shape[0]
    idx = jnp.where(starts, jnp.arange(n, dtype=jnp.int32), jnp.int32(0))
    return lax.associative_scan(jnp.maximum, idx)


def segment_prefix_sum(values_sorted: jnp.ndarray, starts: jnp.ndarray,
                       leader: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(exclusive, inclusive) prefix sums within each segment.

    ``exclusive[i]`` = sum of values of earlier elements in i's segment.
    """
    cum = jnp.cumsum(values_sorted)
    excl_global = cum - values_sorted
    base = excl_global[leader]
    exclusive = excl_global - base
    inclusive = cum - base
    return exclusive, inclusive


def segment_broadcast_first(values_sorted: jnp.ndarray, leader: jnp.ndarray) -> jnp.ndarray:
    """Each element gets its segment leader's value."""
    return values_sorted[leader]


def unsort(order: jnp.ndarray, values_sorted: jnp.ndarray) -> jnp.ndarray:
    """Inverse of ``x[order]``: scatter back to original positions."""
    out = jnp.zeros_like(values_sorted)
    return out.at[order].set(values_sorted)


def ranks_by_key(key: jnp.ndarray) -> jnp.ndarray:
    """Per-element arrival rank within its key group → int32[n], original
    order.

    ``ranks[i]`` = number of earlier elements (batch order) with the same
    key. This is the only genuinely cross-element quantity the scalar
    admission path needs: one stable argsort + one scan + one unsort
    scatter, vs the general path's two-key sort plus per-pair gathers of
    every rule attribute. FIFO semantics come from sort stability exactly
    as in :func:`sort_by_keys`.
    """
    n = key.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    # lax.sort with the iota as a carried operand measures ~12% faster
    # than argsort on the v5 chip (and ks comes out of the same pass
    # instead of a separate gather)
    ks, order = lax.sort((key, idx), num_keys=1, is_stable=True)
    starts = jnp.zeros((n,), jnp.bool_).at[0].set(True).at[1:].set(
        ks[1:] != ks[:-1])
    leader = lax.associative_scan(
        jnp.maximum, jnp.where(starts, idx, jnp.int32(0)))
    rank_s = idx - leader
    return jnp.zeros((n,), jnp.int32).at[order].set(rank_s)


def ranks_per_slot(key2d: jnp.ndarray) -> jnp.ndarray:
    """:func:`ranks_by_key` over each SLOT column of a [B, K] pair-key
    table → int32[B, K], as ONE batched stable sort over [K, B] (a
    Python loop of K separate sorts here used to pay K dispatch+sort
    passes; ``lax.sort`` batches over leading dims natively, and the
    scan/scatter stages batch the same way).

    Valid whenever slot columns carry DISJOINT key groups — true for the
    rule-gather tables: a rule lives at exactly one (row, slot), so every
    admission segment is confined to one slot and K sorts of [B]
    reproduce the flattened [B*K] sort's ranks exactly. Caveat carried
    once here for both call sites (flow_check_scalar / flow_check_fast):
    a sentinel key shared ACROSS slots (the invalid/padding group) ranks
    differently per slot than globally — callers must never consume
    sentinel ranks (both flow paths mask them)."""
    B, K = key2d.shape
    kt = key2d.T                                             # [K, B]
    iota = jnp.arange(B, dtype=jnp.int32)
    idx = jnp.broadcast_to(iota, (K, B))
    ks, order = lax.sort((kt, idx), num_keys=1, is_stable=True)
    starts = jnp.concatenate(
        [jnp.ones((K, 1), jnp.bool_), ks[:, 1:] != ks[:, :-1]], axis=1)
    leader = lax.associative_scan(
        jnp.maximum, jnp.where(starts, iota[None, :], jnp.int32(0)), axis=1)
    rank_s = iota[None, :] - leader
    out = jnp.zeros((K, B), jnp.int32).at[
        jnp.arange(K, dtype=jnp.int32)[:, None], order].set(rank_s)
    return out.T


def padded_table_gather(idx_table: jnp.ndarray, rows: jnp.ndarray,
                        sentinel) -> jnp.ndarray:
    """Gather ``idx_table[rows]`` ([R, K] → [B, K]) where out-of-range
    rows (>= R: batch padding) yield ``sentinel``. The ONE canonical
    clamp-and-sentinel idiom shared by the pipeline's joint rule gather
    and the flow/degrade fallback gathers — keep them in lockstep."""
    R = idx_table.shape[0]
    safe_rows = jnp.minimum(rows, R - 1)
    return jnp.where((rows < R)[:, None], idx_table[safe_rows], sentinel)


def first_index_by_key(key: jnp.ndarray, num_keys: int) -> jnp.ndarray:
    """Index of each key group's FIRST element (batch order) → int32
    [num_keys], filled with ``n`` for absent keys.

    The scatter-min winner equals what a stable sort's segment-first would
    pick — the parity-critical invariant the breaker probe election
    (entry + exit feed) relies on. Keys must be in [0, num_keys).
    """
    n = key.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    return jnp.full((num_keys,), n, jnp.int32).at[key].min(idx, mode="drop")


def greedy_admit(base: jnp.ndarray, amounts: jnp.ndarray, limit: jnp.ndarray,
                 starts: jnp.ndarray, leader: jnp.ndarray,
                 iterations: int = 3) -> jnp.ndarray:
    """Sequential greedy admission within segments, vectorized → bool[n].

    Element i (in sorted order) is admitted iff
    ``base + (admitted amount of earlier elements in its segment) + amounts[i]
    <= limit[i]`` — the reference's check-then-act loop, where a *denied*
    request never increments the counter and so never consumes quota
    (``DefaultController.canPass``).

    The admitted-prefix recurrence is sequential; we solve it by fixed-point
    refinement: start from "everyone contributes", drop the denied, recompute.
    For uniform amounts (acquire=1, the dominant case) one pass is already
    exact; heterogeneous amounts converge in a few iterations, and any
    residual divergence after ``iterations`` is bounded over-admission on
    deep admit/deny alternation chains — the same class of skew the
    reference's own tolerated races produce (``FlowRuleChecker.java:89``).
    """
    admitted = jnp.ones_like(starts)
    for _ in range(iterations):
        excl, _ = segment_prefix_sum(jnp.where(admitted, amounts, 0), starts, leader)
        admitted = base + excl + amounts <= limit
    return admitted
