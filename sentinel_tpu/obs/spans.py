"""Lock-free per-thread ring-buffer span recorder (Dapper-style sampled
tracing of the batch lifecycle).

Every dispatching thread appends finished spans into its OWN fixed-size
ring — appends are plain ``list.append`` / index stores (GIL-atomic), no
lock is ever taken on the record path; the registry lock is touched once
per thread lifetime when its ring is created. ``snapshot()`` merges the
rings from any thread; a concurrently-wrapping ring can tear a snapshot
by one span, which is the documented price of lock-freedom.

Sampling is deterministic: rate ``p`` becomes a stride ``round(1/p)`` and
every stride-th ``maybe_trace()`` call opens a trace (trace id > 0); the
runtime threads that id through the batch's lifecycle so a sampled batch
records its FULL chain (entry → host gates → split decision →
compile-cache lookup → device dispatch → settle/exit) and an unsampled
batch records nothing. With the recorder disabled the runtime's
instrumentation sites reduce to one attribute check.

Timestamps are integer nanoseconds. Under a real clock they come from
``time.perf_counter_ns``; under the test suite's manual/virtual clock
(anything exposing ``set_ms`` — core/clock.ManualClock) they derive from
``clock.now_ms() * 1e6`` so span durations follow virtual time exactly
(:func:`SpanRecorder.for_clock`).

Span schema (``snapshot()`` dicts — docs/OBSERVABILITY.md):
``trace`` (sampled trace id), ``name``, ``start_ns``, ``end_ns``,
``dur_ns``, ``thread`` (ident), ``n`` (event count the span covered),
``note`` (free-form: route taken, sub-batch sizes, ...).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 2048


class _Ring:
    __slots__ = ("buf", "idx")

    def __init__(self) -> None:
        self.buf: list = []
        self.idx = 0


class SpanRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 sample: float = 1.0, time_ns=None) -> None:
        self.capacity = max(16, int(capacity))
        # rate → stride: 1.0 records every trace, 0.01 every 100th, ≤0 none
        self._stride = 0 if sample <= 0 else max(1, round(1.0 / sample))
        self.sample = 0.0 if sample <= 0 else 1.0 / self._stride
        self._time_ns = time_ns or time.perf_counter_ns
        self._dispatch_seq = itertools.count()   # sampling stride counter
        self._trace_seq = itertools.count(1)     # issued trace ids
        self._tls = threading.local()
        self._rings: List[_Ring] = []
        self._rings_lock = threading.Lock()
        self.enabled = True

    @staticmethod
    def for_clock(clock, capacity: int = DEFAULT_CAPACITY,
                  sample: float = 1.0) -> "SpanRecorder":
        """Recorder whose ns timestamps ride a manual/virtual clock when
        one is installed (tests), the monotonic clock otherwise."""
        tfn = None
        if clock is not None and hasattr(clock, "set_ms"):
            tfn = lambda: int(clock.now_ms()) * 1_000_000   # noqa: E731
        return SpanRecorder(capacity=capacity, sample=sample, time_ns=tfn)

    # ---- hot path ----------------------------------------------------

    def now_ns(self) -> int:
        return self._time_ns()

    def maybe_trace(self) -> int:
        """→ a fresh trace id when this dispatch is sampled, else 0."""
        if not self.enabled or self._stride == 0:
            return 0
        if next(self._dispatch_seq) % self._stride:
            return 0
        return next(self._trace_seq)

    def record(self, trace_id: int, name: str, start_ns: int, end_ns: int,
               n: int = 0, note: str = "") -> None:
        if not trace_id or not self.enabled:
            return
        try:
            ring = self._tls.ring
        except AttributeError:
            ring = _Ring()
            self._tls.ring = ring
            with self._rings_lock:
                self._rings.append(ring)
        entry = (trace_id, name, int(start_ns), int(end_ns),
                 threading.get_ident(), int(n), note)
        if len(ring.buf) < self.capacity:
            ring.buf.append(entry)
        else:
            ring.buf[ring.idx % self.capacity] = entry
        ring.idx += 1

    # ---- read side ---------------------------------------------------

    def snapshot(self, limit: Optional[int] = None,
                 trace_id: Optional[int] = None) -> List[Dict]:
        with self._rings_lock:
            rings = list(self._rings)
        spans = []
        for ring in rings:
            spans.extend(list(ring.buf))   # atomic-enough copy (see module)
        if trace_id is not None:
            spans = [s for s in spans if s[0] == trace_id]
        spans.sort(key=lambda s: (s[0], s[2]))
        if limit is not None and len(spans) > limit:
            spans = spans[-limit:]
        return [{"trace": s[0], "name": s[1], "start_ns": s[2],
                 "end_ns": s[3], "dur_ns": s[3] - s[2], "thread": s[4],
                 "n": s[5], "note": s[6]} for s in spans]

    def chain(self, trace_id: int) -> List[Dict]:
        """All spans of one sampled trace, start-ordered (the demo's
        "full span chain" view)."""
        return self.snapshot(trace_id=trace_id)

    def last_trace_id(self) -> int:
        """Highest trace id with at least one recorded span (0 if none)."""
        with self._rings_lock:
            rings = list(self._rings)
        best = 0
        for ring in rings:
            for s in list(ring.buf):
                if s[0] > best:
                    best = s[0]
        return best

    def clear(self) -> None:
        with self._rings_lock:
            rings = list(self._rings)
            self._rings = []
        for ring in rings:
            ring.buf = []
            ring.idx = 0
        # threads still holding a cleared ring re-register on next record
        self._tls = threading.local()

    def close(self) -> None:
        """Idempotent: disable recording and drop the rings. The recorder
        owns no thread, so close is purely a state transition (safe to
        call from Sentinel.close() repeatedly)."""
        self.enabled = False
        self.clear()
