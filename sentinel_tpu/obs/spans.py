"""Lock-free per-thread ring-buffer span recorder (Dapper-style sampled
tracing of the batch lifecycle).

Every dispatching thread appends finished spans into its OWN fixed-size
ring — appends are plain ``list.append`` / index stores (GIL-atomic), no
lock is ever taken on the record path; the registry lock is touched once
per thread lifetime when its ring is created. ``snapshot()`` merges the
rings from any thread; a concurrently-wrapping ring can tear a snapshot
by one span, which is the documented price of lock-freedom.

Sampling is deterministic: rate ``p`` becomes a stride ``round(1/p)`` and
every stride-th ``maybe_trace()`` call opens a trace (trace id > 0); the
runtime threads that id through the batch's lifecycle so a sampled batch
records its FULL chain (entry → host gates → split decision →
compile-cache lookup → device dispatch → settle/exit) and an unsampled
batch records nothing. With the recorder disabled the runtime's
instrumentation sites reduce to one attribute check.

Timestamps are integer nanoseconds. Under a real clock they come from
``time.perf_counter_ns``; under the test suite's manual/virtual clock
(anything exposing ``set_ms`` — core/clock.ManualClock) they derive from
``clock.now_ms() * 1e6`` so span durations follow virtual time exactly
(:func:`SpanRecorder.for_clock`).

Span schema (``snapshot()`` dicts — docs/OBSERVABILITY.md):
``trace`` (sampled trace id), ``name``, ``start_ns``, ``end_ns``,
``dur_ns``, ``thread`` (ident), ``n`` (event count the span covered),
``note`` (free-form: route taken, sub-batch sizes, ...).

Causal links (PR 8): traces relate across the fan-in/fan-out points of
the serving stack — many request traces coalesce into one batch trace at
an ingest flush, and the batch fans back out to per-request verdicts at
settle. :meth:`link` records one ``(src, dst, kind, ts_ns)`` edge per
relation into per-thread rings of the same lock-free shape as the span
rings; :meth:`causal` computes the trace-id closure over those edges so
``chain(request_id)`` returns the request's FULL lifecycle: its own
frontend spans, the flush batch's pipeline/device spans, and the settle
edge back. ``verdict`` edges (batch→request fan-out) are only expanded
from the closure root — walking them from an interior batch node would
pull every sibling request of the batch into every request's chain.

Ring overflow is an explicit signal (PR 8): every overwritten span/link
fires ``on_wrap`` (wired by RuntimeObs to the ``obs.span_ring_wrap``
counter) so operators can see when capacity 2048 is too small instead of
silently losing the tail.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 2048
LINK_CAPACITY = 4096

#: link kinds (the causal-edge vocabulary; docs/OBSERVABILITY.md)
LINK_FLUSH = "flush"        # request trace → the batch trace that took it
LINK_VERDICT = "verdict"    # batch trace → one request trace it settled


class _Ring:
    __slots__ = ("buf", "idx")

    def __init__(self) -> None:
        self.buf: list = []
        self.idx = 0


class SpanRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 sample: float = 1.0, time_ns=None, on_wrap=None) -> None:
        self.capacity = max(16, int(capacity))
        # rate → stride: 1.0 records every trace, 0.01 every 100th, ≤0 none
        self._stride = 0 if sample <= 0 else max(1, round(1.0 / sample))
        self.sample = 0.0 if sample <= 0 else 1.0 / self._stride
        self._time_ns = time_ns or time.perf_counter_ns
        self._dispatch_seq = itertools.count()   # sampling stride counter
        self._trace_seq = itertools.count(1)     # issued trace ids
        self._tls = threading.local()
        self._rings: List[_Ring] = []
        self._link_rings: List[_Ring] = []
        self._rings_lock = threading.Lock()
        # fired once per OVERWRITTEN span/link (ring wrapped past a live
        # entry); RuntimeObs wires it to the obs.span_ring_wrap counter
        self.on_wrap = on_wrap
        self.enabled = True

    @staticmethod
    def for_clock(clock, capacity: int = DEFAULT_CAPACITY,
                  sample: float = 1.0, on_wrap=None) -> "SpanRecorder":
        """Recorder whose ns timestamps ride a manual/virtual clock when
        one is installed (tests), the monotonic clock otherwise."""
        tfn = None
        if clock is not None and hasattr(clock, "set_ms"):
            tfn = lambda: int(clock.now_ms()) * 1_000_000   # noqa: E731
        return SpanRecorder(capacity=capacity, sample=sample, time_ns=tfn,
                            on_wrap=on_wrap)

    # ---- hot path ----------------------------------------------------

    def now_ns(self) -> int:
        return self._time_ns()

    def maybe_trace(self) -> int:
        """→ a fresh trace id when this dispatch is sampled, else 0."""
        if not self.enabled or self._stride == 0:
            return 0
        if next(self._dispatch_seq) % self._stride:
            return 0
        return next(self._trace_seq)

    def mint(self) -> int:
        """A fresh trace id UNCONDITIONALLY (no sampling stride) — the
        flight recorder's always-on tier: every request/batch gets an id
        so an SLO trigger can retroactively pin any chain, not just the
        stride-sampled ones. → 0 only when the recorder is disabled."""
        if not self.enabled:
            return 0
        return next(self._trace_seq)

    def record(self, trace_id: int, name: str, start_ns: int, end_ns: int,
               n: int = 0, note: str = "") -> None:
        if not trace_id or not self.enabled:
            return
        try:
            ring = self._tls.ring
        except AttributeError:
            ring = _Ring()
            self._tls.ring = ring
            with self._rings_lock:
                self._rings.append(ring)
        entry = (trace_id, name, int(start_ns), int(end_ns),
                 threading.get_ident(), int(n), note)
        if len(ring.buf) < self.capacity:
            ring.buf.append(entry)
        else:
            ring.buf[ring.idx % self.capacity] = entry
            if self.on_wrap is not None:
                self.on_wrap()
        ring.idx += 1

    def link(self, src: int, dst: int, kind: str) -> None:
        """One causal edge ``src trace → dst trace`` (fan-in: request →
        flush batch; fan-out: batch → request verdict). Same lock-free
        per-thread ring discipline as :meth:`record`."""
        if not src or not dst or not self.enabled:
            return
        try:
            ring = self._tls.links
        except AttributeError:
            ring = _Ring()
            self._tls.links = ring
            with self._rings_lock:
                self._link_rings.append(ring)
        entry = (int(src), int(dst), kind, self._time_ns())
        if len(ring.buf) < LINK_CAPACITY:
            ring.buf.append(entry)
        else:
            ring.buf[ring.idx % LINK_CAPACITY] = entry
            if self.on_wrap is not None:
                self.on_wrap()
        ring.idx += 1

    # ---- read side ---------------------------------------------------

    def snapshot(self, limit: Optional[int] = None,
                 trace_id: Optional[int] = None) -> List[Dict]:
        with self._rings_lock:
            rings = list(self._rings)
        spans = []
        for ring in rings:
            spans.extend(list(ring.buf))   # atomic-enough copy (see module)
        if trace_id is not None:
            spans = [s for s in spans if s[0] == trace_id]
        spans.sort(key=lambda s: (s[0], s[2]))
        if limit is not None and len(spans) > limit:
            spans = spans[-limit:]
        return [{"trace": s[0], "name": s[1], "start_ns": s[2],
                 "end_ns": s[3], "dur_ns": s[3] - s[2], "thread": s[4],
                 "n": s[5], "note": s[6]} for s in spans]

    def links_snapshot(self, limit: Optional[int] = None) -> List[Dict]:
        """All recorded causal edges, ts-ordered."""
        links = self._raw_links()
        links.sort(key=lambda e: e[3])
        if limit is not None and len(links) > limit:
            links = links[-limit:]
        return [{"src": e[0], "dst": e[1], "kind": e[2], "ts_ns": e[3]}
                for e in links]

    def _raw_links(self) -> list:
        with self._rings_lock:
            rings = list(self._link_rings)
        links = []
        for ring in rings:
            links.extend(list(ring.buf))   # atomic-enough copy (see module)
        return links

    def causal(self, trace_id: int) -> Dict:
        """The causal closure of one trace: ``{"root", "spans", "links"}``.

        Follows recorded edges forward from ``trace_id`` to a fixpoint.
        ``verdict`` (fan-out) edges expand only from the root itself:
        from a request root, the flush edge reaches the batch and the
        batch's verdict edge BACK to this request is kept (both endpoints
        are in the closure) while sibling requests stay out; from a batch
        root, the fan-out to every request it settled is the point."""
        raw = self._raw_links()
        ids = {int(trace_id)}
        changed = True
        while changed:
            changed = False
            for src, dst, kind, _ts in raw:
                if (src in ids and dst not in ids
                        and (kind != LINK_VERDICT or src == trace_id)):
                    ids.add(dst)
                    changed = True
        spans = self.snapshot()
        spans = [s for s in spans if s["trace"] in ids]
        spans.sort(key=lambda s: s["start_ns"])
        links = [{"src": e[0], "dst": e[1], "kind": e[2], "ts_ns": e[3]}
                 for e in sorted(raw, key=lambda e: e[3])
                 if e[0] in ids and e[1] in ids]
        return {"root": int(trace_id), "spans": spans, "links": links}

    def chain(self, trace_id: int) -> List[Dict]:
        """All spans reachable from one trace id, start-ordered: the
        trace's own spans plus — through recorded causal links — the
        flush batch / settle spans of its full lifecycle (the demo's
        "full span chain" view; identical to a single-trace filter when
        no links were recorded)."""
        return self.causal(trace_id)["spans"]

    def last_trace_id(self) -> int:
        """Highest trace id with at least one recorded span (0 if none)."""
        with self._rings_lock:
            rings = list(self._rings)
        best = 0
        for ring in rings:
            for s in list(ring.buf):
                if s[0] > best:
                    best = s[0]
        return best

    def clear(self) -> None:
        with self._rings_lock:
            rings = list(self._rings) + list(self._link_rings)
            self._rings = []
            self._link_rings = []
        for ring in rings:
            ring.buf = []
            ring.idx = 0
        # threads still holding a cleared ring re-register on next record
        self._tls = threading.local()

    def close(self) -> None:
        """Idempotent: disable recording and drop the rings. The recorder
        owns no thread, so close is purely a state transition (safe to
        call from Sentinel.close() repeatedly)."""
        self.enabled = False
        self.clear()
