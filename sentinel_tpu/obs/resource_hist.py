"""Device-resident per-resource RT histograms (round 20).

One fixed log-bucket cumulative histogram row per hot-tier resource
row, living INSIDE the engine state pytree (``SentinelState.rt_hist``,
``int32[rows, hb]``) so recording rides the fused single-dispatch
serving tick (round 16) for zero extra dispatches. Same geometry family
as the host-side interval histogram in :mod:`sentinel_tpu.obs.hist`,
but in milliseconds (the engine's RT unit) and sized for an int32
threshold table:

* bucket ``0`` covers ``[0, 1]`` ms,
* bucket ``i`` covers ``(2**(i-1), 2**i]`` ms,
* the top bucket is open above (quantile interpolation treats its upper
  edge as ``2**(hb-1)`` ms — no per-row max tracking device-side).

With the default ``hb = 32`` the table resolves ~1 ms → ~24 days, far
past any device RT the runtime can record; the clamp ceiling of 32
keeps every threshold (``2**(hb-2)``) inside int32.

Cumulative-forever semantics: counts only grow (they survive window
geometry changes and the demote→promote tiering round trip) and reset
only on row invalidation. That makes the vectors mergeable by plain
addition — across shards (device-side gather in obs/telemetry.py) and
across hosts (psum/allgather in multihost/obs_agg.py) — and lets the
controller recover *interval* tails from deltas between successive
snapshots (:class:`ResourceTailTracker`).

Env knobs (registered in tune/knobs.py; both trace-scope — they size
the state pytree, so changing one forces a fresh engine):

* ``SENTINEL_RESOURCE_HIST_DISABLE`` — drop the table entirely:
  ``rt_hist`` stays ``None``, every consumer compiles the feature away,
  and the jitted step programs are byte-identical to pre-r20 (the gate
  (n) bit-parity leg pins this).
* ``SENTINEL_RESOURCE_HIST_BUCKETS`` — bucket count, clamped [8, 32].
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

RESOURCE_HIST_DISABLE_ENV = "SENTINEL_RESOURCE_HIST_DISABLE"
RESOURCE_HIST_BUCKETS_ENV = "SENTINEL_RESOURCE_HIST_BUCKETS"

DEFAULT_BUCKETS = 32
MIN_BUCKETS = 8
MAX_BUCKETS = 32            # thresholds up to 2**30 — int32-safe

#: The quantiles the jitted per-tick extraction produces, in order —
#: the q_k output's last axis, the hot-entry ``rt_p{50,95,99}_ms``
#: fields, and the Prometheus ``quantile`` label values.
QUANTILES: Tuple[float, ...] = (0.50, 0.95, 0.99)

_BOOL_FALSE = ("0", "off", "false", "disable", "disabled")


def _env_int(name: str, default: int, lo: int, hi: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return min(hi, max(lo, int(raw)))
    except ValueError:
        return default


def resource_hist_disabled(default: bool = False) -> bool:
    """``SENTINEL_RESOURCE_HIST_DISABLE`` (same boolean spellings as the
    other engine switches: anything not in the false set reads on)."""
    raw = os.environ.get(RESOURCE_HIST_DISABLE_ENV, "")
    if not raw:
        return default
    return raw.lower() not in _BOOL_FALSE


def resource_hist_buckets(default: int = DEFAULT_BUCKETS) -> int:
    """``SENTINEL_RESOURCE_HIST_BUCKETS``, clamped to [8, 32]."""
    return _env_int(RESOURCE_HIST_BUCKETS_ENV, default, 8, 32)


def engine_hist_buckets() -> int:
    """The ``EngineSpec.hist_buckets`` value for a new engine: 0 when
    the feature is disabled (state leaf absent, programs unchanged),
    else the clamped bucket count."""
    return 0 if resource_hist_disabled() else resource_hist_buckets()


# ---- geometry ---------------------------------------------------------


def bucket_thresholds_ms(hb: int) -> np.ndarray:
    """int32[hb-1] upper edges ``[1, 2, 4, ..., 2**(hb-2)]`` ms; a value
    strictly above ``thresholds[i-1]`` lands at bucket >= i."""
    return (np.int32(1) << np.arange(hb - 1, dtype=np.int32))


def bucket_edges_ms(hb: int) -> np.ndarray:
    """float32[hb+1] bucket boundaries ``[0, 1, 2, 4, ..., 2**(hb-1)]``
    (the interpolation grid; the last edge caps the open top bucket)."""
    edges = np.zeros(hb + 1, dtype=np.float32)
    edges[1:] = np.ldexp(1.0, np.arange(hb)).astype(np.float32)
    return edges


def bucket_index(rt_ms, hb: int):
    """Traced bucket index per value: ``sum(v > thresholds)`` — 0 for
    v <= 1 ms, hb-1 for anything above ``2**(hb-2)`` ms. Works on any
    leading shape; negative inputs clamp to bucket 0."""
    th = jnp.asarray(bucket_thresholds_ms(hb))
    v = jnp.asarray(rt_ms)
    return jnp.sum((v[..., None] > th).astype(jnp.int32), axis=-1)


def np_bucket_index(rt_ms, hb: int) -> np.ndarray:
    """NumPy mirror of :func:`bucket_index` (bit-exact test reference)."""
    th = bucket_thresholds_ms(hb)
    v = np.asarray(rt_ms)
    return np.sum((v[..., None] > th).astype(np.int32), axis=-1)


# ---- quantile extraction ---------------------------------------------


def quantiles_from_counts(counts, quantiles: Sequence[float] = QUANTILES):
    """Traced ``int32[..., hb] → float32[..., len(quantiles)]`` ms.

    Mirrors ``obs.hist.LogHistogram.percentile``: 1-based rank
    ``max(1, p·total)``, landing bucket = first with ``cum >= rank``,
    linear interpolation between the bucket's edges. Empty rows
    (total == 0) yield 0.0 — "no signal", distinct from any recorded
    latency only together with the count, which callers carry.
    """
    c = jnp.asarray(counts).astype(jnp.float32)
    hb = c.shape[-1]
    total = jnp.sum(c, axis=-1)                              # [...]
    cum = jnp.cumsum(c, axis=-1)                             # [..., hb]
    edges = bucket_edges_ms(hb)
    lo = jnp.asarray(edges[:-1])
    hi = jnp.asarray(edges[1:])
    outs = []
    for p in quantiles:
        rank = jnp.maximum(1.0, np.float32(p) * total)       # [...]
        idx = jnp.sum((cum < rank[..., None]).astype(jnp.int32), axis=-1)
        idx = jnp.minimum(idx, hb - 1)
        cb = jnp.take_along_axis(cum, idx[..., None], axis=-1)[..., 0]
        ci = jnp.take_along_axis(c, idx[..., None], axis=-1)[..., 0]
        frac = (rank - (cb - ci)) / jnp.maximum(ci, 1.0)
        v = lo[idx] + (hi[idx] - lo[idx]) * frac
        outs.append(jnp.where(total > 0, v, 0.0))
    return jnp.stack(outs, axis=-1).astype(jnp.float32)


def np_quantiles(counts, quantiles: Sequence[float] = QUANTILES
                 ) -> np.ndarray:
    """NumPy mirror of :func:`quantiles_from_counts`, same float32
    arithmetic order — the bit-exact reference for the merge/extract
    tests and the host-side fallback (multihost aggregation, the
    controller's interval deltas)."""
    c = np.asarray(counts).astype(np.float32)
    hb = c.shape[-1]
    total = np.sum(c, axis=-1)
    cum = np.cumsum(c, axis=-1)
    edges = bucket_edges_ms(hb)
    lo, hi = edges[:-1], edges[1:]
    outs = []
    for p in quantiles:
        rank = np.maximum(np.float32(1.0), np.float32(p) * total)
        idx = np.sum((cum < rank[..., None]).astype(np.int32), axis=-1)
        idx = np.minimum(idx, hb - 1)
        cb = np.take_along_axis(cum, idx[..., None], axis=-1)[..., 0]
        ci = np.take_along_axis(c, idx[..., None], axis=-1)[..., 0]
        frac = (rank - (cb - ci)) / np.maximum(ci, np.float32(1.0))
        v = lo[idx] + (hi[idx] - lo[idx]) * frac
        outs.append(np.where(total > 0, v, np.float32(0.0)))
    return np.stack(outs, axis=-1).astype(np.float32)


# ---- controller interval tails ---------------------------------------


class ResourceTailTracker:
    """Interval p99 per resource from cumulative-vector deltas.

    The device table is cumulative-forever; the controller wants the
    tail of the LAST interval. This keeps the previous snapshot per
    resource name and differences successive vectors — the histogram
    analog of ``control.policy.HistDeltaP99``, but per resource and in
    the ms geometry. A shrinking count (row invalidated and re-enrolled
    between ticks) resets the baseline: the full vector is treated as
    the interval. The name map is bounded: names absent from an update
    are evicted once the map exceeds ``cap`` (hot sets are small — K
    entries — so in practice eviction only fires across hot-set churn).
    """

    def __init__(self, cap: int = 256) -> None:
        self._prev: Dict[str, np.ndarray] = {}
        self._cap = int(cap)

    def update(self, entries) -> Tuple[Tuple[str, float], ...]:
        """``[(name, cumulative counts)]`` → ``((name, interval_p99_ms),
        ...)`` for every resource with interval samples."""
        out: List[Tuple[str, float]] = []
        seen = set()
        for name, counts in entries:
            c = np.asarray(counts, dtype=np.int64)
            if c.ndim != 1 or c.shape[0] < MIN_BUCKETS:
                continue
            seen.add(name)
            prev = self._prev.get(name)
            if prev is None or prev.shape != c.shape or np.any(c < prev):
                delta = c
            else:
                delta = c - prev
            self._prev[name] = c
            if int(delta.sum()) > 0:
                p99 = float(np_quantiles(delta[None, :])[0, -1])
                out.append((name, p99))
        if len(self._prev) > self._cap:
            for stale in [n for n in self._prev if n not in seen]:
                del self._prev[stale]
                if len(self._prev) <= self._cap:
                    break
        return tuple(out)
