"""SLO-triggered flight recorder: tail-based trace capture (Canopy,
Kaldor et al. SOSP 2017 — record everything cheaply, persist only what a
trigger retroactively pins).

The span recorder's stride sampling is head-based: whether a dispatch is
traced is decided BEFORE anyone knows it will be slow, so the tail events
the serving SLO bands police are exactly the ones a low sample rate
misses. The flight recorder inverts that: while it is active the serving
front end mints a trace id for EVERY request and batch
(``SpanRecorder.mint`` — the always-on reduced-detail tier riding the
same lock-free per-thread rings), and nothing is persisted until an SLO
trigger fires:

* ``deadline_miss`` — a request settled past its absolute deadline
  (frontend/batcher.py settle loop);
* ``shed`` — an :class:`~sentinel_tpu.frontend.batcher.IngestOverload`
  backpressure rejection;
* ``p99`` — the rolling ``hist_request`` p99 breached the
  ``SENTINEL_FLIGHT_P99_MS`` budget (checked every
  :data:`P99_CHECK_EVERY` requests);
* ``block_burst`` — more than ``SENTINEL_FLIGHT_BLOCK_BURST`` denials
  landed within one second (runtime ``_obs_block``).

A trigger pins the offending chain(s): the causal closure
(``SpanRecorder.causal`` — spans + fan-in/fan-out links) of the
triggering trace, or of the most recent traces inside the retro window
(``SENTINEL_FLIGHT_WINDOW_MS``) when the trigger has no specific root.
Pinned records buffer in memory (:meth:`snapshot` — the transport /
dashboard view) and persist through the same
:class:`~sentinel_tpu.metrics.writer.MetricWriter` rotation machinery as
the block-event log, under the app name ``<app>-trace``: one fat line
per pinned chain whose ``resource`` field is the compact-JSON chain
(``json.loads``-able straight off :class:`MetricSearcher` read-back —
:func:`load_pinned`), ``block_qps`` the span count, ``classification``
the trigger code (:data:`TRIGGER_CODES`), ``rt`` the overrun/worst ms.

Triggers are rate-limited per kind to one pin per window so a trigger
storm (every request of a flash crowd missing its deadline) costs one
snapshot, not thousands. Env knobs (construction time; kwargs override):
``SENTINEL_FLIGHT_DISABLE`` — off entirely;
``SENTINEL_FLIGHT_WINDOW_MS`` — retro window AND per-kind re-trigger
gap, default 2000; ``SENTINEL_FLIGHT_P99_MS`` — p99 budget, default 0 =
trigger disabled; ``SENTINEL_FLIGHT_BLOCK_BURST`` — denials/second
threshold, default 512.
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import Dict, List, Optional

from sentinel_tpu.obs import counters as obs_keys

FLIGHT_DISABLE_ENV = "SENTINEL_FLIGHT_DISABLE"
FLIGHT_WINDOW_ENV = "SENTINEL_FLIGHT_WINDOW_MS"
FLIGHT_P99_ENV = "SENTINEL_FLIGHT_P99_MS"
FLIGHT_BURST_ENV = "SENTINEL_FLIGHT_BLOCK_BURST"

#: trigger kind → MetricNode.classification code in the <app>-trace log
TRIGGER_CODES = {"deadline_miss": 1, "shed": 2, "p99": 3, "block_burst": 4,
                 "controller_action": 5}

RECENT_CAP = 64          # in-memory pinned-record tail (command surface)
PENDING_CAP = 256        # un-flushed disk buffer bound (oldest dropped)
MAX_CHAIN_SPANS = 192    # per pinned chain, keeps one fat line bounded
MAX_WINDOW_ROOTS = 4     # rootless triggers pin at most this many chains
P99_CHECK_EVERY = 256    # requests between rolling-p99 evaluations


def flight_disabled() -> bool:
    return os.environ.get(FLIGHT_DISABLE_ENV, "").lower() in (
        "1", "true", "on", "yes")


def _env_ms(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return max(0.0, float(raw))
    except ValueError:
        return default


class FlightRecorder:
    """One per :class:`~sentinel_tpu.obs.RuntimeObs`; host-side only, no
    threads — :meth:`flush` rides the metric timer tick / close exactly
    like the block-event log."""

    def __init__(self, obs, *, enabled: Optional[bool] = None,
                 window_ms: Optional[float] = None,
                 p99_budget_ms: Optional[float] = None,
                 block_burst: Optional[int] = None) -> None:
        self._obs = obs
        self.active = (not flight_disabled()) if enabled is None else enabled
        self.window_ms = (_env_ms(FLIGHT_WINDOW_ENV, 2000.0)
                          if window_ms is None else max(1.0, float(window_ms)))
        self.p99_budget_ms = (_env_ms(FLIGHT_P99_ENV, 0.0)
                              if p99_budget_ms is None
                              else max(0.0, float(p99_budget_ms)))
        self.block_burst = (int(_env_ms(FLIGHT_BURST_ENV, 512))
                            if block_burst is None else int(block_burst))
        self._lock = threading.Lock()
        self._last_pin_ns: Dict[str, int] = {}   # per-kind rate limiter
        self._recent: "collections.deque" = collections.deque(
            maxlen=RECENT_CAP)
        self._pending: List[dict] = []
        self._req_count = 0
        self._block_sec = -1
        self._block_n = 0
        self.writer = None
        self.base_name: Optional[str] = None
        # hot-set pinning (obs/telemetry.py sets this to its flight_hot):
        # a trigger snapshots the top hot resources AT TRIGGER TIME into
        # the record, so a pinned SLO-miss chain names what was hot when
        # it happened. Must be cheap and lock-light (host list copy).
        self.hot_provider = None
        self._closed = False

    # ---- persistence wiring (bootstrap / tests) ----------------------

    def configure(self, base_dir: str, app_name: str, *,
                  single_file_size: int = 50 * 1024 * 1024,
                  total_file_count: int = 6) -> str:
        """Attach the rolling ``<app>-trace`` writer (idempotent per
        instance); → the on-disk base file name the searcher should use."""
        from sentinel_tpu.metrics.writer import MetricWriter, \
            form_metric_file_name
        if self.writer is None:
            self.writer = MetricWriter(
                base_dir, app_name + "-trace",
                single_file_size=single_file_size,
                total_file_count=total_file_count)
            self.base_name = form_metric_file_name(app_name + "-trace")
        return self.base_name

    # ---- trigger surface (hot-adjacent; every call is guarded) -------

    def trigger(self, kind: str, root: int = 0, note: str = "",
                worst_ms: float = 0.0, force: bool = False) -> bool:
        """Fire one SLO trigger; → True when a chain was actually pinned
        (False: inactive, rate-limited, or nothing recorded to pin).
        ``force`` skips the per-kind rate limiter: controller actions are
        already cooldown-limited upstream and every one must leave a pin."""
        if not self.active or self._closed:
            return False
        spans = self._obs.spans
        now_ns = spans.now_ns()
        gap_ns = int(self.window_ms * 1e6)
        with self._lock:
            if not force:
                last = self._last_pin_ns.get(kind)
                if last is not None and now_ns - last < gap_ns:
                    return False
            self._last_pin_ns[kind] = now_ns
        roots = [int(root)] if root else self._window_roots(now_ns)
        if not roots:
            return False
        counters = self._obs.counters
        counters.add(obs_keys.FLIGHT_TRIGGER_PREFIX + kind)
        now_ms = int(self._obs_now_ms())
        hot: List[Dict] = []
        if self.hot_provider is not None:
            try:
                hot = list(self.hot_provider())
            except Exception:   # telemetry must not break a pin
                hot = []
        pinned = 0
        for r in roots:
            causal = spans.causal(r)
            if not causal["spans"]:
                continue
            rec = {
                "ts_ms": now_ms, "kind": kind, "root": r, "note": note,
                "worst_ms": round(float(worst_ms), 3),
                "spans": causal["spans"][:MAX_CHAIN_SPANS],
                "links": causal["links"],
                "truncated": len(causal["spans"]) > MAX_CHAIN_SPANS,
                "hot": hot,
            }
            with self._lock:
                self._recent.append(rec)
                self._pending.append(rec)
                if len(self._pending) > PENDING_CAP:
                    del self._pending[:len(self._pending) - PENDING_CAP]
            pinned += 1
        if pinned:
            counters.add(obs_keys.FLIGHT_PINNED, pinned)
        return pinned > 0

    def note_requests(self, n: int) -> None:
        """Per settled batch: roll the request count and evaluate the
        hist-detected p99 trigger every :data:`P99_CHECK_EVERY`."""
        if not self.active or self.p99_budget_ms <= 0:
            return
        self._req_count += n
        if self._req_count < P99_CHECK_EVERY:
            return
        self._req_count = 0
        p99 = self._obs.hist_request.percentile_ms(0.99)
        if p99 is not None and p99 > self.p99_budget_ms:
            self.trigger("p99", note=f"p99_ms={p99:.1f}", worst_ms=p99)

    def note_blocks(self, count: int, now_ms: int) -> None:
        """Per grouped denial record: the block-reason burst trigger
        (more than ``block_burst`` denials inside one second)."""
        if not self.active or self.block_burst <= 0:
            return
        sec = int(now_ms) // 1000
        if sec != self._block_sec:
            self._block_sec = sec
            self._block_n = 0
        self._block_n += int(count)
        if self._block_n >= self.block_burst:
            self._block_n = -(1 << 30)   # once per second; rate limiter too
            self.trigger("block_burst",
                         note=f"blocks_1s>={self.block_burst}")

    def _window_roots(self, now_ns: int) -> List[int]:
        """Most recent trace ids with a span starting inside the retro
        window (rootless triggers: p99 breach, block burst)."""
        cutoff = now_ns - int(self.window_ms * 1e6)
        ids = {s["trace"] for s in self._obs.spans.snapshot()
               if s["start_ns"] >= cutoff}
        return sorted(ids, reverse=True)[:MAX_WINDOW_ROOTS]

    def _obs_now_ms(self) -> float:
        clock = getattr(self._obs, "clock", None)
        if clock is not None:
            return clock.now_ms()
        import time
        return time.time() * 1000.0

    # ---- read / persist side -----------------------------------------

    def snapshot(self, limit: int = 16, full: bool = False) -> List[Dict]:
        """Most recent pinned records; metadata-only unless ``full``."""
        with self._lock:
            tail = list(self._recent)[-limit:]
        if full:
            return tail
        return [{k: r[k] for k in
                 ("ts_ms", "kind", "root", "note", "worst_ms", "truncated")}
                | {"spans": len(r["spans"]), "links": len(r["links"])}
                for r in tail]

    def pinned(self, root: int) -> Optional[Dict]:
        """The most recent pinned record for one root trace id."""
        with self._lock:
            for rec in reversed(self._recent):
                if rec["root"] == root:
                    return rec
        return None

    def flush(self) -> int:
        """Write pending pinned chains; → lines written. One fat line per
        chain: ``resource`` = the compact-JSON record (no ``|`` ever —
        the writer would mangle one into ``_``), grouped ascending by
        second for the writer's high-water mark."""
        if self.writer is None:
            return 0
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return 0
        from sentinel_tpu.metrics.node import MetricNode
        by_sec: Dict[int, List[MetricNode]] = {}
        for rec in pending:
            blob = json.dumps(rec, separators=(",", ":"))
            by_sec.setdefault(rec["ts_ms"] // 1000, []).append(MetricNode(
                timestamp=rec["ts_ms"], resource=blob,
                block_qps=len(rec["spans"]),
                rt=int(rec.get("worst_ms") or 0),
                classification=TRIGGER_CODES.get(rec["kind"], 0)))
        written = 0
        for sec in sorted(by_sec):
            nodes = by_sec[sec]
            self.writer.write(sec * 1000, nodes)
            written += len(nodes)
        return written

    def close(self) -> None:
        """Idempotent: flush what a writer can take, then stop pinning."""
        if self._closed:
            return
        self._closed = True
        self.active = False
        try:
            self.flush()
        finally:
            if self.writer is not None:
                self.writer.close()


def load_pinned(base_dir: str, app_name: str, begin_ms: int = 0,
                end_ms: Optional[int] = None) -> List[Dict]:
    """Read pinned chains back off the ``<app>-trace`` rotation (the
    ci_gate mechanism probe / post-mortem path): every line whose
    ``resource`` parses as a chain record."""
    from sentinel_tpu.metrics.searcher import MetricSearcher
    from sentinel_tpu.metrics.writer import form_metric_file_name
    searcher = MetricSearcher(base_dir,
                              form_metric_file_name(app_name + "-trace"))
    out: List[Dict] = []
    for node in searcher.find(begin_ms, end_ms):
        try:
            rec = json.loads(node.resource)
        except ValueError:
            continue
        if isinstance(rec, dict) and "spans" in rec:
            out.append(rec)
    return out
